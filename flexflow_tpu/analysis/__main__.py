"""fflint CLI.

Strategy/graph passes (need a model + strategy file):

    python -m flexflow_tpu.analysis MODEL STRATEGY_FILE \
        [--mesh data=4,model=2] [--strict] [--format json] \
        [--passes legality,perf,schema] [--model-arg k=v ...]

ffsan source passes (no model, no strategy file — pure AST over
flexflow_tpu/runtime by default):

    python -m flexflow_tpu.analysis --passes concurrency,tracestability \
        [--path DIR_OR_FILE ...] [--format json] [--tiered-exit]

MODEL: a builtin graph name (mlp | transformer | dlrm | pipeline), a
`package.module:callable` spec, or `none` for a schema-only check of the
file. The two pass families compose: naming both runs both and merges
the reports.

Exit codes (default, pinned since PR 1): 0 = clean (info notes
allowed), 1 = violations found (errors; warnings too under --strict),
2 = usage / model-build failure. With --tiered-exit (what the CI lint
tier consumes): 0 = clean, 1 = warnings only, 2 = errors,
64 = usage / model-build failure.

Pure static analysis: no jax.sharding.Mesh is built and nothing traces —
a bad strategy (or a lock-order inversion) is named in milliseconds, not
after a 40 s collective rendezvous timeout.
"""

from __future__ import annotations

import argparse
import sys

from flexflow_tpu.analysis import ALL_PASSES, analyze
from flexflow_tpu.analysis.models import BUILTIN, build_model
from flexflow_tpu.analysis.sanitize import SOURCE_PASSES, analyze_sources

EX_USAGE = 64       # --tiered-exit usage/build failure (sysexits.h)


def parse_mesh(spec: str):
    mesh = {}
    for part in spec.split(","):
        ax, eq, size = part.partition("=")
        if not eq or not ax.strip() or not size.strip().isdigit() \
                or int(size) < 1:
            raise ValueError(
                f"bad --mesh entry {part!r}; expected 'axis=size[,...]', "
                f"e.g. 'data=4,model=2'")
        mesh[ax.strip()] = int(size)
    return mesh


def _parse_model_args(pairs):
    out = {}
    for p in pairs or ():
        k, eq, v = p.partition("=")
        if not eq:
            raise ValueError(f"bad --model-arg {p!r}; expected k=v")
        try:
            out[k] = int(v)
        except ValueError:
            out[k] = v
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m flexflow_tpu.analysis",
        description="fflint: static strategy/sharding + concurrency/"
                    "trace-stability analyzer")
    ap.add_argument("model", nargs="?", default=None,
                    help=f"builtin graph ({', '.join(sorted(BUILTIN))}), "
                         f"'module:callable', or 'none' for schema-only "
                         f"(optional when only source passes run)")
    ap.add_argument("strategy_file", nargs="?", default=None,
                    help="strategy file to analyze (optional when only "
                         "source passes run)")
    ap.add_argument("--mesh", default="data=8",
                    help="mesh shape, e.g. data=4,model=2 (default data=8)")
    ap.add_argument("--passes", default=",".join(ALL_PASSES),
                    help="comma-separated subset of: "
                         + ",".join(ALL_PASSES + SOURCE_PASSES)
                         + f" (default: {','.join(ALL_PASSES)})")
    ap.add_argument("--path", action="append", default=[],
                    metavar="DIR_OR_FILE",
                    help="source-pass target (repeatable; default: "
                         "flexflow_tpu/runtime)")
    ap.add_argument("--model-arg", action="append", default=[],
                    metavar="K=V", help="builder kwarg (repeatable), "
                    "e.g. --model-arg layers=4")
    ap.add_argument("--strict", action="store_true",
                    help="warnings also fail (exit 1)")
    ap.add_argument("--format", choices=("text", "json"), default=None,
                    dest="fmt",
                    help="report format on stdout (default text)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="alias for --format json")
    ap.add_argument("--tiered-exit", action="store_true",
                    help="severity-tiered exit codes: 0 clean, "
                         "1 warnings only, 2 errors, 64 usage (the CI "
                         "contract; default keeps the pinned 0/1/2)")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress info notes in text output")
    args = ap.parse_args(argv)
    as_json = args.as_json or args.fmt == "json"

    def usage(msg: str) -> int:
        if args.tiered_exit:
            print(f"fflint: {msg}", file=sys.stderr)
            return EX_USAGE
        ap.error(msg)   # exits 2

    passes = tuple(p.strip() for p in args.passes.split(",") if p.strip())
    unknown = [p for p in passes
               if p not in ALL_PASSES + SOURCE_PASSES]
    if unknown:
        return usage(f"unknown pass(es) {unknown}; valid: "
                     f"{ALL_PASSES + SOURCE_PASSES}")
    model_passes = tuple(p for p in passes if p in ALL_PASSES)
    source_passes = tuple(p for p in passes if p in SOURCE_PASSES)
    if model_passes and (args.model is None or args.strategy_file is None):
        return usage(
            f"passes {model_passes} analyze a model + strategy file — "
            f"give both positionals, or select only source passes "
            f"({', '.join(SOURCE_PASSES)})")
    try:
        mesh = parse_mesh(args.mesh)
        model_args = _parse_model_args(args.model_arg)
    except ValueError as e:
        return usage(str(e))

    report = None
    if model_passes:
        model = None
        if args.model != "none":
            try:
                model = build_model(args.model, mesh, model_args)
            except Exception as e:
                print(f"fflint: cannot build model {args.model!r}: "
                      f"{type(e).__name__}: {e}", file=sys.stderr)
                return EX_USAGE if args.tiered_exit else 2
        report = analyze(model, mesh_shape=mesh, passes=model_passes,
                         strategy_file=args.strategy_file)
    if source_passes:
        src_report = analyze_sources(
            paths=args.path or None, passes=source_passes)
        if report is None:
            report = src_report
        else:
            report.extend(src_report.violations)

    if as_json:
        print(report.to_json())
    else:
        print(report.format_text(include_notes=not args.quiet))
    if args.tiered_exit:
        if report.errors():
            return 2
        if report.warnings():
            return 1
        return 0
    failed = bool(report.errors()) or (args.strict
                                       and bool(report.warnings()))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
