"""fflint CLI.

    python -m flexflow_tpu.analysis MODEL STRATEGY_FILE \
        [--mesh data=4,model=2] [--strict] [--json] \
        [--passes legality,perf,schema] [--model-arg k=v ...]

MODEL: a builtin graph name (mlp | transformer | dlrm | pipeline), a
`package.module:callable` spec, or `none` for a schema-only check of the
file. Exit codes: 0 = clean (info notes allowed), 1 = violations found
(errors; warnings too under --strict), 2 = usage / model-build failure.

Pure static analysis: no jax.sharding.Mesh is built and nothing traces —
a bad strategy is named in milliseconds, not after a 40 s collective
rendezvous timeout.
"""

from __future__ import annotations

import argparse
import sys

from flexflow_tpu.analysis import ALL_PASSES, analyze
from flexflow_tpu.analysis.models import BUILTIN, build_model


def parse_mesh(spec: str):
    mesh = {}
    for part in spec.split(","):
        ax, eq, size = part.partition("=")
        if not eq or not ax.strip() or not size.strip().isdigit() \
                or int(size) < 1:
            raise ValueError(
                f"bad --mesh entry {part!r}; expected 'axis=size[,...]', "
                f"e.g. 'data=4,model=2'")
        mesh[ax.strip()] = int(size)
    return mesh


def _parse_model_args(pairs):
    out = {}
    for p in pairs or ():
        k, eq, v = p.partition("=")
        if not eq:
            raise ValueError(f"bad --model-arg {p!r}; expected k=v")
        try:
            out[k] = int(v)
        except ValueError:
            out[k] = v
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m flexflow_tpu.analysis",
        description="fflint: static strategy & sharding analyzer")
    ap.add_argument("model",
                    help=f"builtin graph ({', '.join(sorted(BUILTIN))}), "
                         f"'module:callable', or 'none' for schema-only")
    ap.add_argument("strategy_file", help="strategy file to analyze")
    ap.add_argument("--mesh", default="data=8",
                    help="mesh shape, e.g. data=4,model=2 (default data=8)")
    ap.add_argument("--passes", default=",".join(ALL_PASSES),
                    help="comma-separated subset of: "
                         + ",".join(ALL_PASSES))
    ap.add_argument("--model-arg", action="append", default=[],
                    metavar="K=V", help="builder kwarg (repeatable), "
                    "e.g. --model-arg layers=4")
    ap.add_argument("--strict", action="store_true",
                    help="warnings also fail (exit 1)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress info notes in text output")
    args = ap.parse_args(argv)

    passes = tuple(p.strip() for p in args.passes.split(",") if p.strip())
    unknown = [p for p in passes if p not in ALL_PASSES]
    if unknown:
        ap.error(f"unknown pass(es) {unknown}; valid: {ALL_PASSES}")
    try:
        mesh = parse_mesh(args.mesh)
        model_args = _parse_model_args(args.model_arg)
    except ValueError as e:
        ap.error(str(e))

    model = None
    if args.model != "none":
        try:
            model = build_model(args.model, mesh, model_args)
        except Exception as e:
            print(f"fflint: cannot build model {args.model!r}: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            return 2

    report = analyze(model, mesh_shape=mesh, passes=passes,
                     strategy_file=args.strategy_file)
    if args.as_json:
        print(report.to_json())
    else:
        print(report.format_text(include_notes=not args.quiet))
    failed = bool(report.errors()) or (args.strict
                                       and bool(report.warnings()))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
