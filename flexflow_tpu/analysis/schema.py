"""fflint schema pass: the strategy file's text format itself.

`parallel/strategy.py`'s loader is deliberately tolerant (token stream,
reference parity with src/runtime/strategy.cc:95-189) — a truncated or
corrupt file can half-parse into a plausible-looking table. This pass is
the strict twin: it re-walks the token stream checking counts and value
domains, then proves the parsed table round-trips EXACTLY through
save_strategies_to_file -> load_strategies_from_file (the `@axismap`
extension records must survive, or a search-discovered CONTRACT/STAGE
strategy silently degrades to the greedy degree heuristic on its next
load).
"""

from __future__ import annotations

import os
import tempfile
from typing import Dict, List, Optional, Tuple

from flexflow_tpu.analysis.report import Violation
from flexflow_tpu.config import MAX_TENSOR_DIM
from flexflow_tpu.parallel.pconfig import (CONTRACT, EXPERT, STAGE,
                                           ParallelConfig)

_SENTINELS = (-1, CONTRACT, STAGE, EXPERT)


def _v(code: str, message: str, op_name: Optional[str] = None,
       severity: str = "error") -> Violation:
    return Violation(code=code, pass_name="schema", severity=severity,
                     op_name=op_name, message=message)


class _Cursor:
    def __init__(self, tokens: List[str]):
        self.tokens = tokens
        self.pos = 0

    def done(self) -> bool:
        return self.pos >= len(self.tokens)

    def take(self) -> Optional[str]:
        if self.done():
            return None
        t = self.tokens[self.pos]
        self.pos += 1
        return t

    def take_int(self) -> Tuple[Optional[int], Optional[str]]:
        t = self.take()
        if t is None:
            return None, None
        try:
            return int(t), t
        except ValueError:
            return None, t


def check_file(path: str, roundtrip: bool = True
               ) -> Tuple[Optional[Dict[str, ParallelConfig]],
                          List[Violation]]:
    """Strictly parse `path`. Returns (strategies-or-None, violations);
    strategies is None when the file is structurally unreadable.
    roundtrip=False skips the save/load round-trip (a tempfile write) —
    for callers that only need the parse + the structural checks."""
    out: List[Violation] = []
    try:
        with open(path) as f:
            tokens = f.read().split()
    except OSError as e:
        return None, [_v("schema-unreadable", f"cannot read {path!r}: {e}")]
    cur = _Cursor(tokens)
    num_ops, raw = cur.take_int()
    if num_ops is None:
        return None, [_v("schema-bad-token",
                         f"expected the op count as the first token, got "
                         f"{raw!r}")]
    seen: Dict[str, int] = {}
    for op_i in range(num_ops):
        name = cur.take()
        if name is None:
            out.append(_v("schema-truncated",
                          f"file ends after {op_i} of {num_ops} declared "
                          f"op records"))
            break
        if name in seen:
            out.append(_v("schema-duplicate-op",
                          f"op record #{op_i} repeats name {name!r} "
                          f"(first at record #{seen[name]}) — the loader "
                          f"keeps only the LAST entry", op_name=name))
        seen[name] = op_i
        if not _parse_record(cur, name, out):
            break
    if not cur.done():
        out.append(_v("schema-trailing",
                      f"{len(tokens) - cur.pos} token(s) after the last "
                      f"declared op record (starting {tokens[cur.pos]!r}) — "
                      f"the op count header disagrees with the body",
                      severity="warning"))
    if any(x.severity == "error" for x in out):
        return None, out

    from flexflow_tpu.parallel.strategy import load_strategies_from_file

    strategies = load_strategies_from_file(path)
    if roundtrip:
        out.extend(check_roundtrip(strategies))
    return strategies, out


def _parse_record(cur: _Cursor, name: str, out: List[Violation]) -> bool:
    """One op record after its name. False = unrecoverable truncation."""
    devtype, raw = cur.take_int()
    if devtype is None:
        out.append(_v("schema-truncated" if raw is None else
                      "schema-bad-token",
                      f"expected the device-type int after the op name, "
                      f"got {raw!r}", op_name=name))
        return False
    if devtype not in (0, 1):
        out.append(_v("schema-device-type",
                      f"device type {devtype} is neither 0 (accelerator "
                      f"pool: reference GPU / our TPU) nor 1 (host CPU) — "
                      f"the loader will default it to TPU", op_name=name,
                      severity="warning"))
    ndims, raw = cur.take_int()
    if ndims is None:
        out.append(_v("schema-truncated" if raw is None else
                      "schema-bad-token",
                      f"expected nDims, got {raw!r}", op_name=name))
        return False
    # +1: a trailing CONTRACT (replica) degree rides beyond the tensor rank
    if not (1 <= ndims <= MAX_TENSOR_DIM + 1):
        out.append(_v("schema-ndims",
                      f"nDims {ndims} outside [1, {MAX_TENSOR_DIM + 1}]",
                      op_name=name))
        return False
    degs = []
    for _ in range(ndims):
        d, raw = cur.take_int()
        if d is None:
            out.append(_v("schema-truncated" if raw is None else
                          "schema-bad-token",
                          f"expected {ndims} partition degrees, got {raw!r} "
                          f"after {len(degs)}", op_name=name))
            return False
        if d < 1:
            out.append(_v("schema-degree",
                          f"partition degree {d} must be >= 1",
                          op_name=name))
        degs.append(d)
    nids, raw = cur.take_int()
    if nids is None:
        out.append(_v("schema-truncated" if raw is None else
                      "schema-bad-token",
                      f"expected the device-id count, got {raw!r}",
                      op_name=name))
        return False
    prod = 1
    for d in degs:
        prod *= d
    for i in range(nids):
        d, raw = cur.take_int()
        if d is None:
            out.append(_v("schema-truncated" if raw is None else
                          "schema-bad-token",
                          f"expected {nids} device ids, got {raw!r} after "
                          f"{i}", op_name=name))
            return False
    # optional @axismap extension record
    has_stage = False
    if not cur.done() and cur.tokens[cur.pos] == "@axismap":
        cur.take()
        k, raw = cur.take_int()
        if k is None or k < 0:
            out.append(_v("schema-axismap-truncated",
                          f"@axismap record: expected the entry count, got "
                          f"{raw!r}", op_name=name))
            return False
        for i in range(k):
            ax = cur.take()
            d, raw = cur.take_int()
            if ax is None or d is None:
                out.append(_v("schema-axismap-truncated",
                              f"@axismap record declares {k} entries but "
                              f"ends after {i} (axis {ax!r}, dim {raw!r})",
                              op_name=name))
                return False
            if d == STAGE:
                has_stage = True
            if d < 0 and d not in _SENTINELS:
                out.append(_v("schema-axismap-dim",
                              f"@axismap maps axis {ax!r} to {d}; negative "
                              f"values must be -1 (replicated), "
                              f"{CONTRACT} (CONTRACT), {STAGE} (STAGE) or "
                              f"{EXPERT} (EXPERT)",
                              op_name=name))
    # STAGE strategies occupy stage_size x num_parts devices while the
    # degree list (reference schema) excludes the stage axis, so a
    # stage-multiple id count is the canonical form there
    if nids != prod and not (has_stage and nids % max(prod, 1) == 0):
        out.append(_v("schema-ids-count",
                      f"{nids} device ids declared for {prod} partitions "
                      f"(degrees {degs}) — the mapper pairs shard i with "
                      f"device_ids[i]", op_name=name, severity="warning"))
    return True


def check_roundtrip(strategies: Dict[str, ParallelConfig]) -> List[Violation]:
    """Prove the in-memory table survives save -> load exactly.

    Compared fields: dims, device_type (normalized — reference GPU and our
    TPU both serialize to the accelerator int 0, so 'GPU' legitimately
    reloads as 'TPU'), axis_map including CONTRACT/STAGE sentinels, and
    device_ids whenever the list is consistent (len == num_parts; an
    inconsistent list is save's documented rewrite, flagged separately by
    the legality pass as device-count-mismatch)."""
    from flexflow_tpu.parallel.strategy import (load_strategies_from_file,
                                                save_strategies_to_file)

    out: List[Violation] = []
    fd, tmp = tempfile.mkstemp(suffix=".ff", prefix="fflint_rt_")
    os.close(fd)
    try:
        save_strategies_to_file(tmp, strategies)
        loaded = load_strategies_from_file(tmp)
    except Exception as e:
        return [_v("schema-roundtrip",
                   f"save/load round trip raised {type(e).__name__}: {e}")]
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass
    for name, pc in strategies.items():
        got = loaded.get(name)
        if got is None:
            out.append(_v("schema-roundtrip",
                          "op record missing after save/load round trip",
                          op_name=name))
            continue
        diffs = _diff_pc(pc, got)
        if diffs:
            out.append(_v("schema-roundtrip",
                          "strategy does not round-trip through "
                          "parallel/strategy.py: " + "; ".join(diffs),
                          op_name=name))
    for name in loaded:
        if name not in strategies:
            out.append(_v("schema-roundtrip",
                          "op record appeared from nowhere after round trip",
                          op_name=name))
    return out


def _norm_devtype(dt: str) -> str:
    # int 0 in the file = "the accelerator pool": reference-written GPU
    # strategies execute on our TPU backend by design
    return "TPU" if dt in ("TPU", "GPU") else dt


def _diff_pc(a: ParallelConfig, b: ParallelConfig) -> List[str]:
    diffs = []
    if tuple(a.dims) != tuple(b.dims):
        diffs.append(f"dims {tuple(a.dims)} -> {tuple(b.dims)}")
    if _norm_devtype(a.device_type) != _norm_devtype(b.device_type):
        diffs.append(f"device_type {a.device_type} -> {b.device_type}")
    am_a = {k: v for k, v in (a.axis_map or {}).items()}
    am_b = {k: v for k, v in (b.axis_map or {}).items()}
    if (a.axis_map is None) != (b.axis_map is None) or am_a != am_b:
        diffs.append(f"axis_map {a.axis_map} -> {b.axis_map}")
    n = max(a.num_parts(), 1)
    stage_ok = bool(a.axis_map) and any(d == STAGE
                                        for d in a.axis_map.values()) \
        and len(a.device_ids) % n == 0
    if a.device_ids and (len(a.device_ids) == a.num_parts() or stage_ok) \
            and tuple(a.device_ids) != tuple(b.device_ids):
        diffs.append(f"device_ids {a.device_ids[:4]}... -> "
                     f"{b.device_ids[:4]}...")
    return diffs
