"""fflint — static strategy & sharding analysis.

Validates an FFModel op graph plus a strategy table WITHOUT building a
`jax.sharding.Mesh` or tracing a program: strategy legality is a graph
property ("Beyond Data and Model Parallelism"), so a bad strategy file is
rejected in milliseconds with a named op + pass + rule instead of a
40-second collective-rendezvous hang or an XLA compile error with no
line back to the offending axis.

Three strategy passes (each a module here):
  legality  — can this strategy execute on this mesh at all?
  perf      — legal but pathological: ranked reshard collectives,
              replicated big weights, HBM footprint, pipeline bubbles.
  schema    — the strategy text file itself + exact save/load round-trip.

Two ffsan SOURCE passes (sanitize/ package, ISSUE 16) applying the
same millisecond-static-rejection philosophy to the threaded runtime
itself — no model or strategy file needed:
  concurrency     — lock-order inversions against the declared
                    hierarchy (runtime/locks.py), locks held across
                    blocking calls, raw-lock registry bypasses.
  tracestability  — retrace hazards: uncommitted device_put,
                    shape-dependent device-array slicing in serving
                    hot paths, jnp.* dispatch under a lock.

Entry points:
  analyze(model, ...)        -> Report            (library)
  sanitize.analyze_sources() -> Report            (library, ffsan)
  python -m flexflow_tpu.analysis MODEL FILE      (CLI, see __main__)
  python -m flexflow_tpu.analysis --passes concurrency,tracestability
  FFModel.compile()                               (FFConfig.strategy_lint:
                                                   "off" | "warn" | "strict")
"""

from __future__ import annotations

from typing import Dict, Optional

from flexflow_tpu.analysis.report import (Report, StrategyLintError,
                                          Violation)

__all__ = ["analyze", "Report", "Violation", "StrategyLintError",
           "ALL_PASSES", "SOURCE_PASSES"]

ALL_PASSES = ("legality", "perf", "schema")
# the ffsan source passes (analysis/sanitize): selected by name in
# the CLI alongside the strategy passes, run via analyze_sources()
SOURCE_PASSES = ("concurrency", "tracestability")


def analyze(model, strategies: Optional[Dict] = None,
            mesh_shape: Optional[Dict[str, int]] = None,
            machine=None, passes=ALL_PASSES,
            strategy_file: Optional[str] = None) -> Report:
    """Run the requested fflint passes. Pure static analysis: no mesh, no
    tracing, no device access beyond what importing jax already did.

    strategies defaults to model.config.strategies; mesh_shape to
    model.config.mesh_shape. strategy_file, when given, is schema-checked
    and (if strategies wasn't passed) becomes the analyzed table. The
    analyzer itself never raises on bad strategies — everything is a
    Violation in the returned Report; an internal analyzer fault degrades
    to an `internal-error` warning naming the pass.
    """
    from flexflow_tpu.analysis.context import AnalysisContext
    from flexflow_tpu.analysis.legality import check_legality
    from flexflow_tpu.analysis.perf import check_perf
    from flexflow_tpu.analysis.schema import check_file, check_roundtrip

    report = Report()
    if strategy_file is not None:
        # the file is parsed whichever passes run — a legality-only
        # invocation must still analyze the NAMED file, not silently fall
        # back to model.config.strategies; only the schema pass's
        # violations are gated on pass selection
        from_file, viol = check_file(strategy_file,
                                     roundtrip="schema" in passes)
        if "schema" in passes:
            report.extend(viol)
        elif from_file is None:
            # structurally unreadable: surface the blocking errors even
            # with the schema pass deselected, or the run would report
            # clean while having checked nothing
            report.extend([v for v in viol if v.severity == "error"])
        if strategies is None:
            strategies = from_file
            if strategies is None:
                return report  # unreadable: nothing to resolve
    if strategies is None:
        strategies = getattr(model.config, "strategies", {}) if model else {}
    if model is None:
        return report  # schema-only run (CLI MODEL == "none")
    if mesh_shape is None:
        mesh_shape = getattr(model.config, "mesh_shape", None) or {}

    try:
        ctx = AnalysisContext(model, strategies, mesh_shape)
    except Exception as e:  # never let the analyzer take compile down
        report.add(Violation(
            code="internal-error", pass_name="legality", severity="warning",
            message=f"strategy resolution crashed: {type(e).__name__}: {e}"))
        return report

    if "legality" in passes:
        report.extend(ctx.violations)
        _run_pass(report, "legality", lambda: check_legality(ctx))
    else:
        # resolution-time errors (axis-unknown, degree-unresolvable, ...)
        # mean downstream passes analyzed a STRIPPED axis_map — surface
        # them even with the legality pass deselected, or a perf-only run
        # reports clean on a strategy that cannot execute
        report.extend([v for v in ctx.violations if v.severity == "error"])
    if "schema" in passes and strategy_file is None:
        _run_pass(report, "schema", lambda: check_roundtrip(strategies))
    if "perf" in passes:
        _run_pass(report, "perf", lambda: check_perf(ctx, machine=machine))
    return report


def _run_pass(report: Report, name: str, fn) -> None:
    try:
        report.extend(fn())
    except Exception as e:
        report.add(Violation(
            code="internal-error", pass_name=name, severity="warning",
            message=f"{name} pass crashed: {type(e).__name__}: {e}"))
