"""fflint diagnostics: the Violation/Report data model.

Every pass in `flexflow_tpu/analysis` returns plain data — a list of
Violations — never raises on bad strategies. This is what makes the
analyzer usable from three callsites with different failure policies:
the CLI (exit code), `FFModel.compile` (warn logs vs strict raise), and
tests (assert on codes). The reference's only diagnostics at this layer
were asserts deep inside the mapper (src/mapper/mapper.cc:346-424) and
Legion runtime errors; here a bad strategy names the op, the pass, and
the rule it broke.

Severity model:
  error   — the strategy cannot execute correctly (unknown mesh axis,
            degree/axis-map disagreement, device block too small, ...).
            `strict` mode fails on these.
  warning — executes but is suspicious or silently degraded (XLA pad on
            a non-divisible shard, device-id list rewritten on save, a
            replicated multi-GiB weight with FSDP off, ...).
  info    — performance notes with no threshold crossed (the ranked
            reshard-collective listing). Never fails any mode.
"""

from __future__ import annotations

import dataclasses
import json
from typing import List, Optional

SEVERITIES = ("error", "warning", "info")


@dataclasses.dataclass
class Violation:
    code: str            # stable kebab-case rule id, e.g. "axis-unknown"
    pass_name: str       # "legality" | "perf" | "schema" | the ffsan
    #                      source passes "concurrency" | "tracestability"
    severity: str        # "error" | "warning" | "info"
    message: str
    op_name: Optional[str] = None   # offending op (None for whole-file
    #                                 issues); ffsan passes put the
    #                                 function/method qualname here
    # perf ranking key: estimated bytes moved by the flagged collective
    est_bytes: Optional[float] = None
    est_seconds: Optional[float] = None
    # source location (ffsan passes — None for strategy/graph passes,
    # which have no file:line to point at)
    file: Optional[str] = None
    line: Optional[int] = None

    def __post_init__(self):
        assert self.severity in SEVERITIES, self.severity

    def __str__(self) -> str:
        where = f" op {self.op_name!r}" if self.op_name else ""
        if self.file:
            where += f" {self.file}:{self.line}"
        return (f"{self.severity}[{self.pass_name}/{self.code}]{where}: "
                f"{self.message}")


class Report:
    """Ordered collection of violations from one analyze() run."""

    def __init__(self, violations: Optional[List[Violation]] = None):
        self.violations: List[Violation] = list(violations or [])

    def add(self, v: Violation) -> None:
        self.violations.append(v)

    def extend(self, vs) -> None:
        self.violations.extend(vs)

    def errors(self) -> List[Violation]:
        return [v for v in self.violations if v.severity == "error"]

    def warnings(self) -> List[Violation]:
        return [v for v in self.violations if v.severity == "warning"]

    def notes(self) -> List[Violation]:
        return [v for v in self.violations if v.severity == "info"]

    @property
    def ok(self) -> bool:
        """No errors and no warnings (info notes don't count)."""
        return not self.errors() and not self.warnings()

    def codes(self) -> List[str]:
        return [v.code for v in self.violations]

    def by_code(self, code: str) -> List[Violation]:
        return [v for v in self.violations if v.code == code]

    def summary(self) -> str:
        e, w, n = len(self.errors()), len(self.warnings()), len(self.notes())
        return f"fflint: {e} error(s), {w} warning(s), {n} note(s)"

    def format_text(self, include_notes: bool = True) -> str:
        order = {"error": 0, "warning": 1, "info": 2}
        lines = [str(v) for v in sorted(
            self.violations, key=lambda v: (order[v.severity],
                                            -(v.est_bytes or 0.0)))
            if include_notes or v.severity != "info"]
        lines.append(self.summary())
        return "\n".join(lines)

    def to_json(self) -> str:
        return json.dumps({
            "violations": [dataclasses.asdict(v) for v in self.violations],
            "num_errors": len(self.errors()),
            "num_warnings": len(self.warnings()),
            "num_notes": len(self.notes()),
        }, indent=2)

    def log(self, logger) -> None:
        """Emit through a stdlib-style logger (compile's warn mode)."""
        for v in self.violations:
            if v.severity == "error":
                logger.error("%s", v)
            elif v.severity == "warning":
                logger.warning("%s", v)


class StrategyLintError(ValueError):
    """Raised by strict-mode compile when fflint finds errors."""

    def __init__(self, report: Report):
        self.report = report
        super().__init__(
            report.summary() + "\n" + report.format_text(include_notes=False))
