"""Measured-cost block-size autotuner for the Pallas kernel tier.

The original FlexFlow thesis (PAPERS.md, "Beyond Data and Model
Parallelism") drives every placement decision from MEASURED on-device
costs; the same discipline applies one level down, to kernel tile sizes.
Round 5 showed why: the flash kernels' static 512-block default lost to
XLA's fused einsum at hidden 4096 — a hardcoded heuristic cannot know
where a given chip generation's MXU/VMEM balance tips. This module makes
block choice a measurement:

  * ``tune_flash_attention`` sweeps ``(block_q, block_k)`` candidates for
    one (seq, head_dim, dtype) shape through the dispatch-floor timing
    harness ``search/measure.py`` already uses for op costs (per-call
    min, null-dispatch floor subtracted, scalar-fetch forcing);
  * winners persist to an on-disk JSON table keyed by **(kernel,
    shape-sig incl. dtype, device kind, jax version)** — a bf16-measured
    entry can never be served for an fp32 query, and a jax/libtpu
    version bump invalidates every old row by key mismatch instead of
    silently serving stale tiles;
  * ``ops/pallas_kernels._resolve_blocks`` consults ``lookup_blocks`` at
    trace time, falling back to the static ``_pick_block`` heuristic on
    a miss (cold behavior is byte-identical to the pre-tuner code).

Re-run the tuner after a hardware/jax change::

    python -m flexflow_tpu.search.kernel_tune --seq 4096 --head-dim 128 \
        --dtype bfloat16

Table location: ``FF_KERNEL_TUNE_TABLE`` if set, else
``~/.cache/flexflow_tpu/kernel_tune.json``. ``hits``/``misses`` counters
(``stats()``) ride ServingEngine.stats() for observability.
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Dict, Optional, Sequence, Tuple

# (block_q, block_k) sweep grid; illegal candidates (not dividing the
# sequence) are skipped per shape
DEFAULT_CANDIDATES: Tuple[Tuple[int, int], ...] = (
    (128, 128), (128, 256), (256, 128), (256, 256),
    (256, 512), (512, 256), (512, 512))

# in-memory table cache: {path: (file_stat_sig, {key: entry})} — keyed by
# the file's (mtime_ns, size) so an out-of-process re-tune (the documented
# `python -m flexflow_tpu.search.kernel_tune` flow) is picked up by the
# NEXT trace in a long-lived consumer without a restart. Lookups happen at
# trace time only, so the stat() is off every warm path. The machinery
# lives in search/table_store.py (shared with the op-cost DB, ISSUE 19);
# `_TABLES` aliases its cache so existing fixtures keep working.
from flexflow_tpu.search import table_store as _store

_TABLES: Dict[str, Tuple] = _store._CACHE
_stat_sig = _store.stat_sig
_STATS = {"hits": 0, "misses": 0, "illegal": 0}
_WARNED_ILLEGAL = set()


def default_table_path() -> str:
    env = os.environ.get("FF_KERNEL_TUNE_TABLE", "")
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "flexflow_tpu",
                        "kernel_tune.json")


def device_key() -> str:
    """Device-identity half of the table key: backend, chip kind, jax
    version — measure._env_signature, the ONE environment probe every
    persisted cost key shares (table_store.env_key, shared with the
    op-cost DB). A version bump (jax or the libtpu it pins) changes
    Mosaic codegen, so old timings stop matching new executables —
    they must miss, not mislead."""
    return _store.env_key()


def shape_sig(*, seq_q: int, seq_k: int, head_dim: int, dtype,
              batch: int, heads: int, causal: bool) -> str:
    """Shape half of the key. EVERYTHING the sweep's timing depends on
    is in the signature — dtype (bf16/f32 tiles have different VMEM
    footprints and MXU throughput), batch*heads (the grid's parallel
    extent), and causality (dead-tile clamps change the work per
    block): a winner for one configuration is noise for another, so a
    mismatch must MISS to the static heuristic, never approximate."""
    import numpy as np

    return (f"sq{int(seq_q)}|sk{int(seq_k)}|d{int(head_dim)}"
            f"|b{int(batch)}|h{int(heads)}"
            f"|{'causal' if causal else 'full'}|{np.dtype(dtype).name}")


def _entry_key(kernel: str, sig: str, dev: Optional[str] = None) -> str:
    return f"{kernel}|{dev or device_key()}|{sig}"


def load_table(path: Optional[str] = None, reload: bool = False) -> Dict:
    """Entries dict for `path` (default table), cached in-process and
    invalidated by the file's (mtime, size) — a table written after the
    process's first lookup (another process's re-tune, a test fixture)
    is served on the next call, never silently shadowed by a cached
    empty read. ``reload=True`` forces the re-read regardless."""
    path = path or default_table_path()
    return _store.load(path, reload=reload)


def reload(path: Optional[str] = None) -> Dict:
    return load_table(path, reload=True)


def lookup_blocks(kernel: str, *, seq_q: int, seq_k: int, head_dim: int,
                  dtype, batch: int, heads: int, causal: bool,
                  path: Optional[str] = None) \
        -> Optional[Tuple[int, int]]:
    """Tuned (block_q, block_k) for this exact (kernel, shape, dtype,
    batch, heads, causal) on THIS device/jax version, or None (cold
    fallback — the caller's static heuristic applies). Legality is
    checked HERE: an entry whose blocks no longer divide the sequence
    (corrupt/hand-edited row) counts as a MISS + illegal, never a hit —
    the hit counter means 'a tuned pick actually governed this trace'."""
    entries = load_table(path)
    e = entries.get(_entry_key(
        kernel, shape_sig(seq_q=seq_q, seq_k=seq_k, head_dim=head_dim,
                          dtype=dtype, batch=batch, heads=heads,
                          causal=causal)))
    if e and isinstance(e.get("blocks"), (list, tuple)) \
            and len(e["blocks"]) == 2:
        bq, bk = int(e["blocks"][0]), int(e["blocks"][1])
        if 0 < bq <= seq_q and seq_q % bq == 0 \
                and 0 < bk <= seq_k and seq_k % bk == 0:
            _STATS["hits"] += 1
            return bq, bk
        note_illegal(kernel, (bq, bk), (seq_q, seq_k))
    _STATS["misses"] += 1
    return None


def note_illegal(kernel: str, blocks, shape):
    """A persisted entry that no longer divides the query shape (e.g. a
    table tuned at seq 4096 consulted at 4097 would never key-match, but
    a corrupt/hand-edited row can): log once, count, fall back."""
    _STATS["illegal"] += 1
    tag = (kernel, tuple(blocks), tuple(shape))
    if tag in _WARNED_ILLEGAL:
        return
    _WARNED_ILLEGAL.add(tag)
    from flexflow_tpu.logger import fflogger

    fflogger.warning(
        "kernel_tune: table entry %s blocks=%s does not divide shape %s "
        "— using the static heuristic", kernel, blocks, shape)


def stats() -> Dict[str, int]:
    return dict(_STATS)


def reset_stats():
    for k in _STATS:
        _STATS[k] = 0


def record(kernel: str, sig: str, blocks: Optional[Tuple[int, int]],
           seconds: float, candidates: Optional[Dict] = None,
           path: Optional[str] = None, impl: Optional[str] = None,
           extra: Optional[Dict] = None) -> str:
    """Persist one winner (atomic tmp+rename write, the checkpoint.py
    discipline) and refresh the in-memory cache. Returns the key.
    ``blocks`` entries serve the block tuner (lookup_blocks);
    ``impl`` entries serve the paged-attention impl choice
    (lookup_paged_impl) — an entry can carry either or both."""
    path = path or default_table_path()
    entries = load_table(path, reload=True)
    key = _entry_key(kernel, sig)
    entries[key] = {
        "seconds": float(seconds),
        "when": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if blocks is not None:
        entries[key]["blocks"] = [int(blocks[0]), int(blocks[1])]
    if impl is not None:
        entries[key]["impl"] = str(impl)
    if extra:
        entries[key].update(extra)
    if candidates:
        entries[key]["candidates"] = {
            f"{bq}x{bk}": float(s) for (bq, bk), s in candidates.items()}
    _store.publish(path, entries)
    return key


def lookup_paged_impl(*, page_size: int, pages_per_slot: int,
                      head_dim: int, dtype, batch: int, heads: int,
                      s: int = 1, path: Optional[str] = None) \
        -> Optional[str]:
    """Measured paged-attention impl ('pallas' | 'einsum') for one
    serving shape on THIS device/jax version, or None (the caller's
    backend heuristic applies). ``dtype`` is the POOL STORAGE dtype —
    int8/fp8/f32/bf16 — so a winner measured on quantized pages (whose
    bandwidth/compute balance differs: the kernel streams half the
    bytes but adds a dequant multiply per tile) can never be served
    for a full-width pool. Consulted by ServingEngine under
    paged_attention_impl='auto' at construction time only, with the
    DECODE slab shape ``s=1`` — decode dominates a serving engine's
    dispatches, and the engine picks ONE impl for its life; entries
    tuned at verify shapes (``--slab`` > 1) are comparison data, not
    steering input."""
    entries = load_table(path)
    sig = shape_sig(seq_q=s, seq_k=pages_per_slot * page_size,
                    head_dim=head_dim, dtype=dtype, batch=batch,
                    heads=heads, causal=True)
    e = entries.get(_entry_key("paged_fwd", sig))
    if e and e.get("impl") in ("pallas", "einsum"):
        _STATS["hits"] += 1
        return e["impl"]
    _STATS["misses"] += 1
    return None


def tune_paged_attention(*, page_size: int = 16, pages_per_slot: int = 8,
                         head_dim: int = 64, kv_heads: int = 2,
                         heads: int = 4, slots: int = 4, s: int = 1,
                         dtype="float32", kv_dtype: Optional[str] = None,
                         warmup: int = 1, iters: int = 3,
                         path: Optional[str] = None,
                         verbose: bool = False) -> Dict:
    """Measure the Pallas paged-attention kernel against the einsum
    page-gather at ONE serving shape — optionally on a QUANTIZED pool
    (``kv_dtype`` = 'int8' | 'fp8' | 'bf16': the kernel variant that
    dequantizes in VMEM vs the gather that dequantizes in HBM) — and
    persist the winning impl to the same table the block tuner uses.
    The pool's storage dtype is the signature's dtype, so int8 and
    full-width entries can never shadow each other. ServingEngine
    consults the entry under paged_attention_impl='auto'
    (lookup_paged_impl). Off-TPU the kernel runs in interpret mode:
    the sweep exercises the full tune->persist->consume path (the CI
    smoke + bench demonstration), it just measures the interpreter —
    einsum wins there by construction, which is itself the right
    'auto' answer for a CPU backend."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from flexflow_tpu.ops.attention import (kv_storage_dtype,
                                            page_dequantize, page_quantize,
                                            page_scale)
    from flexflow_tpu.ops.pallas_kernels import paged_attention_fwd_pallas
    from flexflow_tpu.search import measure

    sdtype, qmax = kv_storage_dtype(kv_dtype)
    store = sdtype if sdtype is not None else jnp.dtype(dtype)
    rs = np.random.RandomState(0)
    pool_pages = 1 + slots * pages_per_slot
    max_len = pages_per_slot * page_size

    def mk(d):
        x = jnp.asarray(rs.randn(pool_pages, page_size, kv_heads, d),
                        jnp.float32)
        if qmax is None:
            return x.astype(store), None
        sc = page_scale(x, qmax)
        return page_quantize(x, sc, qmax, store), sc

    kq, ks = mk(head_dim)
    vq, vs = mk(head_dim)
    q = jnp.asarray(rs.randn(slots, s, heads, head_dim), dtype)
    table = jnp.asarray(
        1 + np.arange(slots * pages_per_slot).reshape(slots,
                                                      pages_per_slot),
        jnp.int32)
    wp = jnp.minimum(
        jnp.full((slots,), max_len - s, jnp.int32)[:, None]
        + jnp.arange(s, dtype=jnp.int32)[None, :], max_len - 1)
    row_len = jnp.full((slots,), page_size, jnp.int32)
    prompt_pad = jnp.full((slots,), 2 * page_size, jnp.int32)
    scale = 1.0 / math.sqrt(head_dim)
    grp = heads // kv_heads

    def pallas_step(q_, k_, v_):
        out = paged_attention_fwd_pallas(q_, k_, v_, table, wp, row_len,
                                         prompt_pad, scale, k_scales=ks,
                                         v_scales=vs)
        return jnp.sum(out.astype(jnp.float32))

    def einsum_step(q_, k_, v_):
        # standalone mirror of MultiHeadAttention._paged_attention_ctx's
        # einsum branch (the tuner is model-free, so it cannot call the
        # op method); drift between the two bodies is caught by the
        # kernel-vs-oracle parity tests (test_pallas_paged /
        # test_quantized_serving), which pin the SAME pair of
        # computations against each other
        gk, gv = k_[table], v_[table]
        if qmax is not None:
            gk = page_dequantize(gk, ks[table])
            gv = page_dequantize(gv, vs[table])
        gk = gk.reshape(slots, max_len, kv_heads, head_dim)
        gv = gv.reshape(slots, max_len, kv_heads, head_dim)
        idx = jnp.arange(max_len)
        live = (idx[None, None, :] < row_len[:, None, None]) \
            | ((idx[None, None, :] >= prompt_pad[:, None, None])
               & (idx[None, None, :] <= wp[:, :, None]))
        qg = q_.reshape(slots, s, kv_heads, grp, head_dim)
        logits = jnp.einsum("bqkgd,bskd->bkgqs", qg,
                            gk.astype(q_.dtype),
                            preferred_element_type=jnp.float32) * scale
        logits = jnp.where(live[:, None, None, :, :], logits,
                           jnp.finfo(jnp.float32).min)
        probs = jax.nn.softmax(logits, axis=-1).astype(q_.dtype)
        out = jnp.einsum("bkgqs,bskd->bqkgd", probs, gv.astype(q_.dtype))
        return jnp.sum(out.astype(jnp.float32))

    timed = {}
    for impl, step in (("einsum", einsum_step), ("pallas", pallas_step)):
        timed[impl] = measure.time_scalar_program(
            jax.jit(step), q, kq, vq, warmup=warmup, iters=iters)
        if verbose:
            print(f"[kernel_tune] paged_fwd ps{page_size} "
                  f"pps{pages_per_slot} d{head_dim} "
                  f"{np.dtype(store).name} {impl}: "
                  f"{timed[impl] * 1e3:.3f} ms")
    best = min(timed, key=timed.get)
    sig = shape_sig(seq_q=s, seq_k=max_len, head_dim=head_dim,
                    dtype=store, batch=slots, heads=heads, causal=True)
    record("paged_fwd", sig, None, timed[best],
           candidates=None, path=path, impl=best,
           extra={f"{k}_seconds": float(v) for k, v in timed.items()})
    rec = {
        "kernel": "paged_fwd", "sig": sig, "device": device_key(),
        "impl": best, "kv_dtype": np.dtype(store).name,
        "seconds": timed[best],
        "candidates": {k: float(v) for k, v in timed.items()},
    }
    if verbose:
        print(f"[kernel_tune] paged winner {best} -> "
              f"{path or default_table_path()}")
    return rec


def lookup_paged_prefill_impl(*, page_size: int, pages_per_slot: int,
                              head_dim: int, dtype, batch: int,
                              heads: int, path: Optional[str] = None) \
        -> Optional[str]:
    """Measured paged prefill/append WRITE impl ('pallas' | 'einsum')
    for one serving geometry on THIS device/jax version, or None (the
    caller's backend heuristic applies). Mirrors lookup_paged_impl but
    keys the 'paged_prefill' kernel: ``dtype`` is the POOL STORAGE
    dtype (a quantized write adds an in-kernel quantize but streams
    half the bytes, so winners can't be shared across widths), and the
    signature's seq_k is the slot capacity — the slab length the write
    path scatters at its long-context worst case. Consulted by
    ServingEngine under paged_attention_impl='auto' at construction
    time only (ISSUE 18)."""
    entries = load_table(path)
    sig = shape_sig(seq_q=page_size, seq_k=pages_per_slot * page_size,
                    head_dim=head_dim, dtype=dtype, batch=batch,
                    heads=heads, causal=False)
    e = entries.get(_entry_key("paged_prefill", sig))
    if e and e.get("impl") in ("pallas", "einsum"):
        _STATS["hits"] += 1
        return e["impl"]
    _STATS["misses"] += 1
    return None


def tune_paged_prefill(*, page_size: int = 16, pages_per_slot: int = 8,
                       head_dim: int = 64, kv_heads: int = 2,
                       heads: int = 4, slots: int = 4,
                       dtype="float32", kv_dtype: Optional[str] = None,
                       warmup: int = 1, iters: int = 3,
                       path: Optional[str] = None,
                       verbose: bool = False) -> Dict:
    """Measure the Pallas page-at-a-time prefill/append write kernel
    against the einsum big-scatter oracle at ONE serving geometry —
    the slab is the slot's FULL capacity (pages_per_slot * page_size),
    the long-context worst case ISSUE 18 targets — optionally on a
    QUANTIZED pool, and persist the winning impl under the
    'paged_prefill' kernel key. ServingEngine consults the entry under
    paged_attention_impl='auto' (lookup_paged_prefill_impl). Off-TPU
    the kernel runs in interpret mode: the sweep exercises the full
    tune->persist->consume path, it just measures the interpreter —
    einsum wins there by construction, the right 'auto' answer for a
    CPU backend."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from flexflow_tpu.ops.attention import (kv_storage_dtype,
                                            page_quantize, page_scale)
    from flexflow_tpu.ops.pallas_kernels import paged_prefill_write_pallas
    from flexflow_tpu.search import measure

    sdtype, qmax = kv_storage_dtype(kv_dtype)
    store = sdtype if sdtype is not None else jnp.dtype(dtype)
    rs = np.random.RandomState(0)
    n_pages = pages_per_slot
    pool_pages = 1 + slots * pages_per_slot
    slab_len = n_pages * page_size

    kh = jnp.asarray(rs.randn(1, slab_len, kv_heads, head_dim), dtype)
    vh = jnp.asarray(rs.randn(1, slab_len, kv_heads, head_dim), dtype)
    pool_k = jnp.zeros((pool_pages, page_size, kv_heads, head_dim), store)
    pool_v = jnp.zeros_like(pool_k)
    cache = {"k": pool_k, "v": pool_v}
    if qmax is not None:
        cache["k_scale"] = jnp.zeros((pool_pages, kv_heads), jnp.float32)
        cache["v_scale"] = jnp.zeros((pool_pages, kv_heads), jnp.float32)
    pages = jnp.asarray(1 + np.arange(n_pages), jnp.int32)

    def pallas_step(kh_, vh_, pk, pv):
        out = paged_prefill_write_pallas(
            dict(cache, k=pk, v=pv), kh_, vh_, pages)
        return jnp.sum(out["k"].astype(jnp.float32)) \
            + jnp.sum(out["v"].astype(jnp.float32))

    def einsum_step(kh_, vh_, pk, pv):
        # standalone mirror of MultiHeadAttention.paged_prefill_write's
        # einsum branch (the tuner is model-free); drift is caught by
        # the kernel-vs-oracle parity tests (test_pallas_paged)
        total = jnp.float32(0.0)
        for x, pool in ((kh_, pk), (vh_, pv)):
            pf = x[0].reshape(n_pages, page_size, kv_heads, head_dim)
            if qmax is None:
                out = pool.at[pages].set(pf.astype(pool.dtype))
            else:
                pf = pf.astype(jnp.float32)
                sc = page_scale(pf, qmax)
                out = pool.at[pages].set(
                    page_quantize(pf, sc, qmax, pool.dtype))
            total = total + jnp.sum(out.astype(jnp.float32))
        return total

    timed = {}
    for impl, step in (("einsum", einsum_step), ("pallas", pallas_step)):
        timed[impl] = measure.time_scalar_program(
            jax.jit(step), kh, vh, pool_k, pool_v,
            warmup=warmup, iters=iters)
        if verbose:
            print(f"[kernel_tune] paged_prefill ps{page_size} "
                  f"pps{pages_per_slot} d{head_dim} "
                  f"{np.dtype(store).name} {impl}: "
                  f"{timed[impl] * 1e3:.3f} ms")
    best = min(timed, key=timed.get)
    sig = shape_sig(seq_q=page_size, seq_k=slab_len, head_dim=head_dim,
                    dtype=store, batch=slots, heads=heads, causal=False)
    record("paged_prefill", sig, None, timed[best],
           candidates=None, path=path, impl=best,
           extra={f"{k}_seconds": float(v) for k, v in timed.items()})
    rec = {
        "kernel": "paged_prefill", "sig": sig, "device": device_key(),
        "impl": best, "kv_dtype": np.dtype(store).name,
        "seconds": timed[best],
        "candidates": {k: float(v) for k, v in timed.items()},
    }
    if verbose:
        print(f"[kernel_tune] paged_prefill winner {best} -> "
              f"{path or default_table_path()}")
    return rec


def static_blocks(seq_q: int, seq_k: int) -> Tuple[int, int]:
    """What the cold fallback would pick — recorded next to tuned picks
    so benches/tests can state whether tuning CHANGED the decision."""
    from flexflow_tpu.ops.pallas_kernels import _pick_block

    return _pick_block(seq_q, 512), _pick_block(seq_k, 512)


def tune_flash_attention(seq_q: int, seq_k: Optional[int] = None, *,
                         head_dim: int = 64, dtype="float32",
                         batch: int = 1, heads: int = 4,
                         causal: bool = True,
                         candidates: Optional[Sequence] = None,
                         warmup: int = 1, iters: int = 3,
                         path: Optional[str] = None,
                         verbose: bool = False) -> Dict:
    """Sweep (block_q, block_k) for the flash FORWARD kernel at one
    shape, persist the winner, return the decision record::

        {"kernel", "sig", "blocks", "static", "changed", "seconds",
         "candidates": {(bq, bk): seconds}}

    Timing goes through measure.time_scalar_program — the same
    dispatch-floor harness the strategy search trusts for op costs (the
    kernel call is wrapped in a scalar-reducing jit so each timed call
    fetches 4 bytes). Off-TPU the kernels run in interpret mode: the
    sweep still exercises the full tune->persist->consume path (the CI
    smoke), it just measures the interpreter."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from flexflow_tpu.ops.pallas_kernels import flash_attention_fwd_pallas
    from flexflow_tpu.search import measure

    seq_k = seq_k or seq_q
    scale = 1.0 / math.sqrt(head_dim)
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(batch, seq_q, heads, head_dim), dtype)
    k = jnp.asarray(rs.randn(batch, seq_k, heads, head_dim), dtype)
    v = jnp.asarray(rs.randn(batch, seq_k, heads, head_dim), dtype)

    cand = [tuple(c) for c in (candidates or DEFAULT_CANDIDATES)]
    legal = [(bq, bk) for bq, bk in cand
             if bq <= seq_q and seq_q % bq == 0
             and bk <= seq_k and seq_k % bk == 0]
    if not legal:
        raise ValueError(
            f"no legal (block_q, block_k) candidate for seq_q={seq_q}, "
            f"seq_k={seq_k} in {cand}")

    timed: Dict[Tuple[int, int], float] = {}
    for bq, bk in legal:
        def step(q_, k_, v_, bq=bq, bk=bk):
            out, _ = flash_attention_fwd_pallas(
                q_, k_, v_, causal, scale, block_q=bq, block_k=bk,
                need_lse=False)
            return jnp.sum(out.astype(jnp.float32))

        dt = measure.time_scalar_program(jax.jit(step), q, k, v,
                                         warmup=warmup, iters=iters)
        timed[(bq, bk)] = dt
        if verbose:
            print(f"[kernel_tune] flash_fwd sq{seq_q} sk{seq_k} "
                  f"d{head_dim} {jnp.dtype(dtype).name} "
                  f"block ({bq}, {bk}): {dt * 1e3:.3f} ms")
    best = min(timed, key=timed.get)
    sig = shape_sig(seq_q=seq_q, seq_k=seq_k, head_dim=head_dim,
                    dtype=dtype, batch=batch, heads=heads, causal=causal)
    record("flash_fwd", sig, best, timed[best], candidates=timed,
           path=path)
    static = static_blocks(seq_q, seq_k)
    rec = {
        "kernel": "flash_fwd", "sig": sig, "device": device_key(),
        "blocks": list(best), "static": list(static),
        "changed": tuple(best) != tuple(static),
        "seconds": timed[best],
        "candidates": {f"{bq}x{bk}": s for (bq, bk), s in timed.items()},
    }
    if verbose:
        print(f"[kernel_tune] winner {best} (static {static}, "
              f"changed={rec['changed']}) -> {path or default_table_path()}")
    return rec


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(
        description="Tune flash-attention block sizes (default) or the "
                    "paged-attention impl choice (--paged, optionally on "
                    "a quantized pool via --kv-dtype) on this device and "
                    "persist the winners (consulted automatically at "
                    "trace / engine-construction time).")
    p.add_argument("--paged", action="store_true",
                   help="tune the paged-attention kernel-vs-einsum "
                        "choice instead of flash blocks")
    p.add_argument("--paged-prefill", action="store_true",
                   help="tune the paged prefill/append WRITE "
                        "kernel-vs-einsum choice (ISSUE 18) instead of "
                        "flash blocks")
    p.add_argument("--page-size", type=int, default=16)
    p.add_argument("--pages-per-slot", type=int, default=8)
    p.add_argument("--kv-heads", type=int, default=2)
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--slab", type=int, default=1,
                   help="query slab length (1 = decode — the entry "
                        "engines steer by; K+1 = verify, recorded for "
                        "comparison only)")
    p.add_argument("--kv-dtype", type=str, default="native",
                   choices=("native", "bf16", "int8", "fp8"),
                   help="pool storage dtype for --paged (part of the "
                        "table key)")
    p.add_argument("--seq", "--seq-q", dest="seq_q", type=int,
                   default=None)
    p.add_argument("--seq-k", type=int, default=None)
    p.add_argument("--head-dim", type=int, default=64)
    p.add_argument("--dtype", type=str, default="float32",
                   choices=("float32", "bfloat16"))
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--no-causal", action="store_true")
    p.add_argument("--iters", type=int, default=3)
    p.add_argument("--candidates", type=str, default="",
                   help="e.g. '128x128,256x256' (default: built-in grid)")
    p.add_argument("--table", type=str, default="",
                   help="table path (default FF_KERNEL_TUNE_TABLE or "
                        "~/.cache/flexflow_tpu/kernel_tune.json)")
    args = p.parse_args(argv)
    if args.paged_prefill:
        rec = tune_paged_prefill(
            page_size=args.page_size, pages_per_slot=args.pages_per_slot,
            head_dim=args.head_dim, kv_heads=args.kv_heads,
            heads=args.heads, slots=args.slots, dtype=args.dtype,
            kv_dtype=(None if args.kv_dtype == "native"
                      else args.kv_dtype),
            iters=args.iters, path=args.table or None, verbose=True)
        print(json.dumps(rec))
        return 0
    if args.paged:
        rec = tune_paged_attention(
            page_size=args.page_size, pages_per_slot=args.pages_per_slot,
            head_dim=args.head_dim, kv_heads=args.kv_heads,
            heads=args.heads, slots=args.slots, s=args.slab,
            dtype=args.dtype,
            kv_dtype=(None if args.kv_dtype == "native"
                      else args.kv_dtype),
            iters=args.iters, path=args.table or None, verbose=True)
        print(json.dumps(rec))
        return 0
    if args.seq_q is None:
        p.error("--seq is required (or pass --paged)")
    cand = None
    if args.candidates:
        cand = []
        for part in args.candidates.split(","):
            bq, _, bk = part.partition("x")
            cand.append((int(bq), int(bk)))
    rec = tune_flash_attention(
        args.seq_q, args.seq_k, head_dim=args.head_dim, dtype=args.dtype,
        batch=args.batch, heads=args.heads, causal=not args.no_causal,
        candidates=cand, iters=args.iters, path=args.table or None,
        verbose=True)
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
