"""Persistent op-cost database: the cross-session half of the search.

The paper's core loop anneals over MEASURED op costs; until ISSUE 19
every session re-measured (or re-compiled, for the "analyze" tier) each
op signature from scratch. This module promotes `measure.py`'s
in-process `_SIGNATURE_CACHE` to an on-disk store using the SAME atomic
publish + (mtime,size) invalidation machinery as the kernel-tune table
(`search/table_store.py` — one persistence implementation, not two).

Keys. Every entry is keyed by ``kind | env | signature``:

  * ``kind`` — ``measure`` (real fwd+bwd timing, seconds), ``analyze``
    (compile-only XLA cost_analysis: flops + bytes) or ``calib``
    (telemetry-observed whole-step entries). Measured and analyzed rows
    for one op signature can therefore never collide or shadow each
    other — the historical ``("analyze",) + sig`` tuple-prefix trick is
    replaced by structurally distinct key kinds and value schemas.
  * ``env`` — ``table_store.env_key()``: backend | device kind |
    jax version. A jax bump or backend change invalidates by mismatch.
  * ``signature`` — ``repr`` of ``measure._op_signature`` minus its
    trailing env tuple (op class, attrs, per-shard in/weight shapes,
    input dtypes): a bf16 entry can never serve an fp32 query.

Values. ``measure`` entries: ``{"seconds", "source", "when"}`` where
``source`` is ``microbench`` (the measurement harness) or ``telemetry``
(production-observed, folded back by ``export_calibration``).
``analyze`` entries: ``{"flops", "bytes", "source", "when"}``.

Activation. The DB engages only when a path is configured —
``FFConfig.cost_db_path`` / ``--cost-db`` / the ``FF_COST_DB`` env var —
so tests and one-off scripts keep hermetic in-process caches unless they
opt in. ``hits``/``misses``/``stores``/``illegal`` counters (``stats()``)
make the warm-start contract pinnable: a warm-started search re-measures
ZERO already-keyed ops (misses == 0).

Calibration. ``export_calibration(model)`` closes the loop: it reads the
PR-13 ``ff_train_step_seconds`` histogram (observed p50) and the PR-15
HBM ledger, compares them against the search's predicted step time and
fflint's footprint estimate, publishes ``ff_csim_error_ratio`` /
``ff_csim_predicted_step_seconds`` / ``ff_csim_observed_step_seconds``
(+ ``ff_csim_hbm_error_ratio``) gauges so simulator drift is
continuously observable, and — when the DB is active — persists the
observation as a ``calib`` entry tagged ``source: telemetry``.
"""

from __future__ import annotations

import os
import time
from typing import Dict, Optional, Tuple

from flexflow_tpu.search import table_store

_STATS = {"hits": 0, "misses": 0, "stores": 0, "illegal": 0}
_WARNED_ILLEGAL = set()


def default_db_path() -> str:
    env = os.environ.get("FF_COST_DB", "")
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "flexflow_tpu",
                        "cost_db.json")


def resolve_path(path: Optional[str] = None) -> Optional[str]:
    """The DB path this call should use, or None when the DB is OFF.
    Explicit path wins; else the FF_COST_DB env var; else inactive —
    persistence is strictly opt-in so unconfigured runs stay hermetic."""
    if path:
        return path
    env = os.environ.get("FF_COST_DB", "")
    return env or None


def _key(kind: str, sig: Tuple) -> str:
    from flexflow_tpu.search.measure import _env_signature

    body = sig
    if isinstance(sig, tuple) and sig and sig[-1] == _env_signature():
        body = sig[:-1]  # env identity lives in the readable key prefix
    return f"{kind}|{table_store.env_key()}|{body!r}"


def _get(kind: str, sig: Tuple, path: str) -> Optional[Dict]:
    entries = table_store.load(path)
    e = entries.get(_key(kind, sig))
    if isinstance(e, dict):
        return e
    return None


def _record(kind: str, sig: Tuple, value: Dict, path: str) -> str:
    entries = table_store.load(path, reload=True)
    key = _key(kind, sig)
    entries[key] = dict(value,
                        when=time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                           time.gmtime()))
    table_store.publish(path, entries)
    _STATS["stores"] += 1
    return key


def _note_illegal(kind: str, sig: Tuple, entry: Dict):
    _STATS["illegal"] += 1
    tag = _key(kind, sig)
    if tag in _WARNED_ILLEGAL:
        return
    _WARNED_ILLEGAL.add(tag)
    from flexflow_tpu.logger import fflogger

    fflogger.warning("cost_db: entry %s is malformed (%r) — treating as "
                     "a miss", tag, entry)


def get_measured(sig: Tuple, path: Optional[str] = None) -> Optional[float]:
    """Persisted fwd+bwd seconds for one op signature, or None (miss /
    DB off). A malformed entry counts as illegal + miss, never a hit."""
    path = resolve_path(path)
    if path is None:
        return None
    e = _get("measure", sig, path)
    if e is not None:
        s = e.get("seconds")
        if isinstance(s, (int, float)) and s > 0:
            _STATS["hits"] += 1
            return float(s)
        _note_illegal("measure", sig, e)
    _STATS["misses"] += 1
    return None


def record_measured(sig: Tuple, seconds: float, source: str = "microbench",
                    path: Optional[str] = None) -> Optional[str]:
    path = resolve_path(path)
    if path is None:
        return None
    return _record("measure", sig, {"seconds": float(seconds),
                                    "source": str(source)}, path)


def get_analyzed(sig: Tuple, path: Optional[str] = None
                 ) -> Optional[Tuple[float, float]]:
    """Persisted (flops, bytes_accessed) for one op signature, or None."""
    path = resolve_path(path)
    if path is None:
        return None
    e = _get("analyze", sig, path)
    if e is not None:
        f, b = e.get("flops"), e.get("bytes")
        if isinstance(f, (int, float)) and isinstance(b, (int, float)):
            _STATS["hits"] += 1
            return float(f), float(b)
        _note_illegal("analyze", sig, e)
    _STATS["misses"] += 1
    return None


def record_analyzed(sig: Tuple, flops: float, nbytes: float,
                    source: str = "microbench",
                    path: Optional[str] = None) -> Optional[str]:
    path = resolve_path(path)
    if path is None:
        return None
    return _record("analyze", sig, {"flops": float(flops),
                                    "bytes": float(nbytes),
                                    "source": str(source)}, path)


def entry_count(path: Optional[str] = None) -> int:
    path = resolve_path(path)
    if path is None:
        return 0
    return len(table_store.load(path, reload=True))


def stats() -> Dict[str, int]:
    return dict(_STATS)


def reset_stats():
    for k in _STATS:
        _STATS[k] = 0


# ---- telemetry feedback -----------------------------------------------------

def _observed_step_p50() -> Optional[float]:
    """p50 of the PR-13 per-step wall-time histogram, merged across label
    children, or None when fit() has not observed any steps."""
    from flexflow_tpu.runtime.telemetry import bucket_quantile, registry

    fam = registry().family("ff_train_step_seconds")
    if fam is None:
        return None
    bounds = None
    counts = None
    for child in fam.children():
        cb = getattr(child, "bounds", None)
        cc = getattr(child, "counts", None)
        if cb is None or cc is None:
            continue
        if counts is None:
            bounds, counts = cb, list(cc)
        elif cb == bounds:
            counts = [a + b for a, b in zip(counts, cc)]
    if not counts or sum(counts) <= 0:
        return None
    p50 = bucket_quantile(bounds, counts, 0.5)
    return p50 if p50 > 0 else None


def export_calibration(model, path: Optional[str] = None) -> Optional[Dict]:
    """Predicted-vs-observed calibration: compare the search's predicted
    step time (``model._predicted_step_time``, stashed by
    ``optimize_strategies_multi`` / compile) with the telemetry-observed
    p50, publish the ``ff_csim_*`` gauges, fold the observation into the
    DB as a ``calib`` entry tagged ``source: telemetry``, and return the
    record (None when either side is missing)."""
    from flexflow_tpu.runtime.telemetry import registry

    predicted = getattr(model, "_predicted_step_time", None)
    observed = _observed_step_p50()
    if not predicted or not observed:
        return None
    ratio = float(predicted) / float(observed)
    reg = registry()
    reg.gauge("ff_csim_predicted_step_seconds",
              "search cost-model predicted step time").set(float(predicted))
    reg.gauge("ff_csim_observed_step_seconds",
              "telemetry-observed per-step wall time (p50)").set(
        float(observed))
    reg.gauge("ff_csim_error_ratio",
              "predicted / observed step time — simulator drift "
              "(1.0 = perfectly calibrated)").set(ratio)
    rec = {"predicted_s": float(predicted), "observed_s": float(observed),
           "ratio": ratio, "source": "telemetry"}
    # HBM side: fflint's footprint estimate vs the PR-15 tracked ledger
    try:
        from flexflow_tpu.runtime import flightrec

        snap = flightrec.hbm_ledger().snapshot()
        est = snap.get("lint_estimated_bytes")
        tracked = snap.get("total_tracked_bytes", 0)
        if est and tracked:
            hbm_ratio = float(est) / max(float(tracked), 1.0)
            reg.gauge("ff_csim_hbm_error_ratio",
                      "lint-estimated / telemetry-tracked per-chip HBM "
                      "bytes").set(hbm_ratio)
            rec["hbm_estimated_bytes"] = float(est)
            rec["hbm_tracked_bytes"] = float(tracked)
            rec["hbm_ratio"] = hbm_ratio
    except Exception:
        pass  # ledger optional: calibration must not fail a fit teardown
    dbp = resolve_path(path)
    if dbp is not None:
        sig = ("step_time", getattr(model, "name", None)
               or type(model).__name__)
        _record("calib", sig, rec, dbp)
    return rec
