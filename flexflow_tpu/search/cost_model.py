"""Analytic strategy cost model.

The Python-side cost oracle: given the op graph and a candidate strategy
(op name -> axis_map over the mesh, plus an optional device-block placement
per op), estimate one training-iteration time. Plays the role of the
reference's Simulator::simulate_runtime (simulator.cc:325-621): per-op
roofline compute cost, resharding cost where producer/consumer shardings
disagree (the reference's region-intersection comm tasks,
simulator.cc:252-285), gradient all-reduce per weight (the reference's
post-hoc NCCL cost, simulator.cc:548-594), an HBM over-capacity penalty
(simulator.cc:595-620), and per-device timelines so op placement is rankable
(simulator.cc:325-621 per-device busy lists).

`iteration_time` is an exact Python mirror of the C++ scheduler in
csrc/sim.cc — the native annealer and this objective must agree (tested in
tests/test_csim.py), so neither can drift silently.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from flexflow_tpu.ops.base import InputOp, Op
from flexflow_tpu.search.machine import MachineModel

AxisMap = Dict[str, Optional[int]]

MEM_PENALTY_PER_BYTE = 1e-3 / 1e6  # 1 ms per MB over HBM (simulator.cc:612-617)

# Per-op memory-relief modes the multi-objective search chooses among
# (ISSUE 19): each trades step time for per-chip HBM, priced by
# op_mem_bytes (bytes side) + mem_mode_time (time side). "zero1" and
# "zero3" map onto REAL execution modes (FFConfig.overlap_grad_sync's
# ZeRO-1 sharded optimizer / fsdp_axis ZeRO-3); "remat" re-runs the
# forward in backward instead of stashing activations; "offload" parks
# grads + optimizer state host-side at host_bw streaming cost.
MEM_MODES = ("none", "remat", "zero1", "zero3", "offload")


def _parts(axis_map: AxisMap, mesh_shape: Dict[str, int]) -> int:
    n = 1
    for ax, d in (axis_map or {}).items():
        if d is not None:
            n *= mesh_shape[ax]
    return n


def _shard_degree_on_dim(axis_map: AxisMap, mesh_shape: Dict[str, int],
                         dim: int) -> int:
    n = 1
    for ax, d in (axis_map or {}).items():
        if d == dim:
            n *= mesh_shape[ax]
    return n


def _parts_out(axis_map: AxisMap, mesh_shape: Dict[str, int]) -> int:
    """Partition count of the op's OUTPUT: CONTRACT and STAGE axes shard
    inputs/weights but deliver a replicated output, so they are excluded."""
    n = 1
    for ax, d in (axis_map or {}).items():
        if d is not None and d >= 0:
            n *= mesh_shape[ax]
    return n


def align_place(place: int, ndev: int, num_devices: int) -> int:
    """Mirror of sim.cc align_place: device blocks are GSPMD-expressible
    sub-meshes — ndev must divide the device count and the start must be a
    multiple of ndev, else the block collapses to 0."""
    if ndev <= 0 or ndev >= num_devices or num_devices % ndev != 0:
        return 0
    place = max(0, min(place, num_devices - ndev))
    return place - place % ndev


class CostModel:
    def __init__(self, model, mesh_shape: Dict[str, int],
                 machine: Optional[MachineModel] = None,
                 measured: Optional[Dict] = None,
                 dtype_bytes: int = 4,
                 fsdp_axis: str = ""):
        self.model = model
        self.mesh_shape = dict(mesh_shape)
        if machine is None:
            # two-tier topology by default: when the model's config names
            # DCN-spanning axes (FFConfig.dcn_mesh_shape), EVERY cost
            # consumer — the search, csim's tables, the fflint perf pass —
            # prices collectives over those axes at the DCN tier without
            # each caller having to remember to build the machine itself
            dcn = getattr(getattr(model, "config", None),
                          "dcn_mesh_shape", None)
            machine = MachineModel(dcn_axes=dict(dcn)) if dcn \
                else MachineModel()
        self.machine = machine
        self.measured = measured or {}  # (op_name, parts) -> seconds (fwd+bwd)
        self.dtype_bytes = dtype_bytes
        # FSDP (FFConfig.fsdp_axis): weights + opt state further shard over
        # this axis, paying a per-use all-gather — the simulator must see
        # both sides or it will veto memory-feasible FSDP configs (and
        # overrate infeasible non-FSDP ones). Defaulted from the model's
        # config when not given explicitly.
        if fsdp_axis:
            if fsdp_axis not in self.mesh_shape:
                raise ValueError(
                    f"fsdp_axis={fsdp_axis!r} is not a mesh axis "
                    f"(mesh {self.mesh_shape})")
            self.fsdp_axis = fsdp_axis
        else:
            # defaulted from the model config: the config axis may
            # legitimately be absent from a caller-supplied mesh — drop
            cfg_axis = getattr(getattr(model, "config", None),
                               "fsdp_axis", "") or ""
            self.fsdp_axis = cfg_axis if cfg_axis in self.mesh_shape else ""

    @property
    def num_devices(self) -> int:
        n = 1
        for v in self.mesh_shape.values():
            n *= v
        return n

    # ---- per-op --------------------------------------------------------------

    def op_compute_time(self, op: Op, axis_map: AxisMap) -> float:
        from flexflow_tpu.parallel.pconfig import CONTRACT, EXPERT, STAGE

        parts = _parts(axis_map, self.mesh_shape)
        contract_axes = [ax for ax, d in (axis_map or {}).items()
                         if d == CONTRACT]
        stage_axes = [ax for ax, d in (axis_map or {}).items()
                      if d == STAGE]
        expert_axes = [ax for ax, d in (axis_map or {}).items()
                       if d == EXPERT]
        t = None
        if self.measured:
            # real-device measurement keyed by choice_key — per-shard output
            # shape PLUS the contract degree, which the output shape alone
            # cannot encode (search/measure.py; reference cache
            # simulator.cc:298-303); legacy fallback key: partition count
            from flexflow_tpu.search.measure import choice_key

            key = choice_key(op.name, op.outputs[0].dims, axis_map,
                             self.mesh_shape)
            if key in self.measured:
                t = self.measured[key]
            elif (not contract_axes and not stage_axes
                    and (op.name, parts) in self.measured):
                # the legacy parts-keyed fallback cannot distinguish
                # weight-sharding markers from output sharding — a STAGE
                # choice must not read a data-parallel shard's timing
                t = self.measured[(op.name, parts)]
        if t is None:
            flops = op.flops() / max(parts, 1)
            # inputs/weights are sharded over all axes incl. CONTRACT; the
            # output is psum-replicated over CONTRACT axes, so its bytes
            # divide only by the output partition count
            io_bytes = (sum(t_.volume() for t_ in op.inputs)
                        * self.dtype_bytes / max(parts, 1)
                        + sum(t_.volume() for t_ in op.outputs)
                        * self.dtype_bytes
                        / max(_parts_out(axis_map, self.mesh_shape), 1))
            fwd = self.machine.compute_time(flops, io_bytes, self.dtype_bytes)
            t = 3.0 * fwd  # fwd + ~2x bwd (reference measures both)
        # CONTRACT (row-parallel) axes psum the output activations: once in
        # forward, once for the mirror collective in backward. Added on top
        # of EITHER cost tier (the measured shard time excludes comm) and
        # folded into the op's serial cost — it gates consumers exactly
        # like compute.
        if contract_axes:
            out_bytes = (sum(t_.volume() for t_ in op.outputs)
                         * self.dtype_bytes
                         / max(_parts_out(axis_map, self.mesh_shape), 1))
            for ax in contract_axes:
                t += 2.0 * self.machine.all_reduce_time(
                    out_bytes, self.mesh_shape[ax], ax)
        # STAGE (pipeline-parallel) axes: the op's layers shard n ways (the
        # 1/n compute is already in `parts`), but the schedule pays (a) the
        # pipeline bubble — (m + n - 1)/m with m microbatches — and (b) the
        # boundary-activation p2p: one ppermute of a microbatch activation
        # per tick, forward and backward (2x; the 1F1B recompute re-reads
        # stashed inputs locally, no extra hop). Priced on top of either
        # cost tier, like CONTRACT's psum.
        if stage_axes:
            n = 1
            for ax in stage_axes:
                n *= self.mesh_shape[ax]
            # the runtime honors num_microbatches verbatim (pipeline()
            # defaults to n when unset) — so must the bubble price: a
            # clamp would underprice m < n configurations
            m = int(getattr(op, "num_microbatches", 0) or 0) or n
            ticks = m + n - 1
            t *= ticks / m  # bubble stretches the compute timeline
            out_bytes = (sum(t_.volume() for t_ in op.outputs)
                         * self.dtype_bytes
                         / max(_parts_out(axis_map, self.mesh_shape), 1))
            mb_bytes = out_bytes / m
            t += 2.0 * ticks * (mb_bytes / self.machine.ici_bw
                                + self.machine.ici_latency)
        # EXPERT (expert-parallel) axes: experts shard over the axis (the
        # 1/n compute is in `parts`, the weight shards via
        # weight_partition) and tokens move to their experts and back —
        # a dispatch + combine all-to-all in forward, mirrored in
        # backward (4 all-to-alls of the activation volume per axis).
        if expert_axes:
            out_bytes = (sum(t_.volume() for t_ in op.outputs)
                         * self.dtype_bytes
                         / max(_parts_out(axis_map, self.mesh_shape), 1))
            for ax in expert_axes:
                t += 4.0 * self.machine.all_to_all_time(
                    out_bytes, self.mesh_shape[ax], ax)
        return t

    def op_grad_sync_time(self, op: Op, axis_map: AxisMap) -> float:
        """All-reduce of weight grads over mesh axes that parallelize the op
        but do not shard the weight itself (pure replication axes). Priced
        per axis so DCN-crossing axes get the two-tier cost."""
        specs = op.weight_specs()
        if not specs:
            return 0.0
        try:
            wp = op.weight_partition(axis_map or {})
        except Exception:
            wp = {}
        total = 0.0
        for spec in specs:
            wbytes = int(np.prod(spec.shape)) * self.dtype_bytes
            pspec = wp.get(spec.name)
            sharded_axes = set()
            if pspec is not None:
                for entry in pspec:
                    if entry is None:
                        continue
                    for ax in (entry if isinstance(entry, tuple) else (entry,)):
                        sharded_axes.add(ax)
            shard_deg = 1
            for ax in sharded_axes:
                shard_deg *= self.mesh_shape.get(ax, 1)
            # FSDP applies to THIS weight only if the executor would
            # actually shard it: same rule as runtime._with_fsdp, which
            # degrades indivisible weights to unsharded (they then pay
            # the plain all-reduce, not reduce-scatter + gathers)
            fsdp = False
            if (self.fsdp_axis and self.fsdp_axis not in sharded_axes
                    and self.mesh_shape[self.fsdp_axis] > 1):
                from flexflow_tpu.runtime.executor import _with_fsdp

                base = pspec or ()
                fsdp = _with_fsdp(base, spec.shape, self.fsdp_axis,
                                  self.mesh_shape[self.fsdp_axis]) is not base
            for ax, d in (axis_map or {}).items():
                if d is not None and ax not in sharded_axes:
                    if fsdp and ax == self.fsdp_axis:
                        # FSDP: the gradient over this axis reduce-scatters
                        # instead of all-reducing
                        total += self.machine.reduce_scatter_time(
                            wbytes / shard_deg, self.mesh_shape[ax], ax)
                    else:
                        total += self.machine.all_reduce_time(
                            wbytes / shard_deg, self.mesh_shape[ax], ax)
            if fsdp:
                # per-step weight re-materialization: all-gather the
                # fsdp-sharded weight at use in forward and again for
                # backward (2x); per-chip resident bytes are
                # wbytes / (shard_deg * fsdp_size)
                n = self.mesh_shape[self.fsdp_axis]
                total += 2.0 * self.machine.all_gather_time(
                    wbytes / shard_deg / n, n, self.fsdp_axis)
        return total

    def _relief_degree(self, axis_map: AxisMap) -> int:
        """Product of mesh-axis sizes the op does NOT parallelize over —
        the replication degree ZeRO-style relief modes shard weights /
        optimizer state across (the real executor shards over the data
        or fsdp axis; replicated axes are exactly where those live)."""
        used = {ax for ax, d in (axis_map or {}).items() if d is not None}
        n = 1
        for ax, size in self.mesh_shape.items():
            if ax not in used:
                n *= size
        return max(n, 1)

    def op_mem_bytes(self, op: Op, axis_map: AxisMap,
                     mem_mode: str = "none") -> float:
        """Per-device HBM bytes under this choice: weights + grads + opt
        state (x3) plus activations, divided over the partition. CONTRACT
        axes shard the weight but leave the output replicated.

        ``mem_mode`` (one of MEM_MODES) applies the search-chosen relief:
          remat    — stash ~1/4 of activations, recompute the rest in bwd;
          zero1    — optimizer state (2/3 of the x3) shards over the op's
                     replication axes (overlap_grad_sync's ZeRO-1 update);
          zero3    — weights + grads + opt state all shard over the
                     replication axes (fsdp_axis / ZeRO-3);
          offload  — grads + optimizer state live host-side (2/3 of the
                     weight term leaves HBM), streamed per step.

        Approximation note: dividing the weight term by the FULL partition
        count credits per-shard weight slices even on pure replication
        (DP) axes — per-shard task accounting in the reference's style
        (simulator.cc:595-620). A consequence: plain fsdp_axis adds no
        further division here (it would double-count) and shows up in the
        TIME model instead; the explicit zero1/zero3 mem modes DO divide
        further — they are the search's optimistic relief pricing, paid
        for on the time side by mem_mode_time."""
        parts = _parts(axis_map, self.mesh_shape)
        w = op.weight_bytes()
        weight_term = w * 3 / max(parts, 1)
        act_term = (op.output_bytes()
                    / max(_parts_out(axis_map, self.mesh_shape), 1))
        if mem_mode == "remat":
            act_term *= 0.25
        elif mem_mode == "zero1":
            r = self._relief_degree(axis_map)
            weight_term = w * (1.0 + 2.0 / r) / max(parts, 1)
        elif mem_mode == "zero3":
            r = self._relief_degree(axis_map)
            weight_term = w * 3 / max(parts, 1) / r
        elif mem_mode == "offload":
            weight_term = w / max(parts, 1)
        return weight_term + act_term

    def mem_mode_time(self, op: Op, axis_map: AxisMap,
                      mem_mode: str = "none") -> float:
        """Step-time overhead the relief mode costs — what the
        multi-objective search trades HBM bytes against.
          remat    — one extra forward: ~1/3 of the fwd+bwd compute time;
          zero1    — params all-gather once per step over the relief axes;
          zero3    — weight all-gather at fwd use + again for bwd, plus
                     the grad reduce-scatter (3 collectives);
          offload  — grads out + updated params back over host_bw."""
        if mem_mode in ("none", "") or mem_mode is None:
            return 0.0
        parts = max(_parts(axis_map, self.mesh_shape), 1)
        w = op.weight_bytes() / parts
        if mem_mode == "remat":
            return self.op_compute_time(op, axis_map) / 3.0
        r = self._relief_degree(axis_map)
        if mem_mode == "zero1":
            return self.machine.all_gather_time(w / r, r) if r > 1 else 0.0
        if mem_mode == "zero3":
            if r <= 1:
                return 0.0
            return (2.0 * self.machine.all_gather_time(w / r, r)
                    + self.machine.reduce_scatter_time(w, r))
        if mem_mode == "offload":
            return 2.0 * w / self.machine.host_bw
        return 0.0

    def resharding_time(self, producer_map: AxisMap, consumer_map: AxisMap,
                        tensor) -> float:
        """Cost to move a tensor from its producer's sharding to what the
        consumer constrains. Zero when maps agree per axis. Collectives over
        DCN-crossing axes are priced at the DCN tier."""
        p = {ax: producer_map.get(ax) for ax in self.mesh_shape}
        c = {ax: consumer_map.get(ax) for ax in self.mesh_shape}
        if p == c:
            return 0.0
        tbytes = tensor.volume() * self.dtype_bytes
        per_chip = tbytes / max(_parts(producer_map, self.mesh_shape), 1)
        cost = 0.0
        for ax in self.mesh_shape:
            if p.get(ax) == c.get(ax):
                continue
            size = self.mesh_shape[ax]
            if size <= 1:
                continue
            if p.get(ax) is not None and c.get(ax) is not None:
                cost += self.machine.all_to_all_time(per_chip, size, ax)
            elif p.get(ax) is not None:  # consumer wants it replicated
                cost += self.machine.all_gather_time(per_chip, size, ax)
            else:  # dynamic-slice, nearly free
                cost += self.machine.ici_latency
        return cost

    # ---- whole strategy ------------------------------------------------------

    def iteration_time(self, strategy: Dict[str, AxisMap],
                       places: Optional[Dict[str, int]] = None) -> float:
        """Estimated seconds per training iteration under `strategy` (+
        optional per-op device-block placement). Exact Python mirror of the
        C++ per-device list schedule (csrc/sim.cc schedule())."""
        D = self.num_devices
        dev_compute = [0.0] * D
        dev_comm = [0.0] * D
        # grad all-reduce rides its own per-device stream: XLA's latency
        # hiding overlaps grad sync with backward compute, and the reference
        # prices NCCL post-hoc (simulator.cc:548-594) — never interleaved
        # with forward resharding traffic
        dev_sync = [0.0] * D
        dev_mem = [0.0] * D
        finish: Dict[str, float] = {}
        blocks: Dict[str, tuple] = {}

        def block_of(op, am):
            ndev = max(1, min(_parts(am, self.mesh_shape), D))
            place = align_place((places or {}).get(op.name, 0), ndev, D)
            return place, ndev

        for op in self.model.ops:
            if isinstance(op, InputOp):
                continue
            am = strategy.get(op.name, {})
            pi, ni = block_of(op, am)
            blocks[op.name] = (pi, ni)
            ready = 0.0
            for input_idx, t in enumerate(op.inputs):
                if t.owner_op is None or isinstance(t.owner_op, InputOp):
                    continue
                src = t.owner_op.name
                # consumers see the producer's OUTPUT sharding: CONTRACT
                # axes deliver psum-replicated outputs
                pam = t.owner_op.output_axis_map(strategy.get(src, {}))
                try:
                    want = op.input_axis_map(am, input_idx)
                except Exception:
                    want = am
                c = self.resharding_time(pam, want, t)
                ps, ns = blocks.get(src, (0, D))
                if ps != pi:
                    c += (t.volume() * self.dtype_bytes / max(ns, 1)
                          / self.machine.ici_bw) + self.machine.ici_latency
                if c > 0.0:
                    start = finish.get(src, 0.0)
                    for d in range(ps, ps + ns):
                        start = max(start, dev_comm[d])
                    for d in range(pi, pi + ni):
                        start = max(start, dev_comm[d])
                    end = start + c
                    for d in range(ps, ps + ns):
                        dev_comm[d] = end
                    for d in range(pi, pi + ni):
                        dev_comm[d] = end
                    ready = max(ready, end)
                else:
                    ready = max(ready, finish.get(src, 0.0))
            start = ready
            for d in range(pi, pi + ni):
                start = max(start, dev_compute[d])
            end = start + self.op_compute_time(op, am)
            for d in range(pi, pi + ni):
                dev_compute[d] = end
            finish[op.name] = end
            sync = self.op_grad_sync_time(op, am)
            if sync > 0.0:
                cstart = end
                for d in range(pi, pi + ni):
                    cstart = max(cstart, dev_sync[d])
                for d in range(pi, pi + ni):
                    dev_sync[d] = cstart + sync
            m = self.op_mem_bytes(op, am)
            for d in range(pi, pi + ni):
                dev_mem[d] += m

        total = max(max(dev_compute), max(dev_comm), max(dev_sync)) \
            if D else 0.0
        for d in range(D):
            over = dev_mem[d] - self.machine.hbm_bytes
            if over > 0.0:
                total += over * MEM_PENALTY_PER_BYTE
        return total
