"""Analytic strategy cost model.

The Python-side cost oracle: given the op graph and a candidate strategy
(op name -> axis_map over the mesh), estimate one training-iteration time.
Plays the role of the reference's Simulator::simulate_runtime
(simulator.cc:325-621) at strategy-ranking fidelity: per-op roofline compute
cost, resharding cost where producer/consumer shardings disagree (the
reference's region-intersection comm tasks, simulator.cc:252-285), gradient
all-reduce per weight (the reference's post-hoc NCCL cost,
simulator.cc:548-594), and an HBM over-capacity penalty
(simulator.cc:595-620).

The C++ simulator (csrc/) refines this with event-driven per-device
timelines; this module also feeds it per-op costs.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from flexflow_tpu.ops.base import InputOp, Op
from flexflow_tpu.search.machine import MachineModel

AxisMap = Dict[str, Optional[int]]


def _parts(axis_map: AxisMap, mesh_shape: Dict[str, int]) -> int:
    n = 1
    for ax, d in (axis_map or {}).items():
        if d is not None:
            n *= mesh_shape[ax]
    return n


def _shard_degree_on_dim(axis_map: AxisMap, mesh_shape: Dict[str, int],
                         dim: int) -> int:
    n = 1
    for ax, d in (axis_map or {}).items():
        if d == dim:
            n *= mesh_shape[ax]
    return n


class CostModel:
    def __init__(self, model, mesh_shape: Dict[str, int],
                 machine: Optional[MachineModel] = None,
                 measured: Optional[Dict] = None,
                 dtype_bytes: int = 4):
        self.model = model
        self.mesh_shape = dict(mesh_shape)
        self.machine = machine or MachineModel()
        self.measured = measured or {}  # (op_name, parts) -> seconds (fwd+bwd)
        self.dtype_bytes = dtype_bytes

    # ---- per-op --------------------------------------------------------------

    def op_compute_time(self, op: Op, axis_map: AxisMap) -> float:
        parts = _parts(axis_map, self.mesh_shape)
        if self.measured:
            # real-device measurement keyed by per-shard output shape
            # (search/measure.py; reference cache simulator.cc:298-303),
            # legacy fallback key: partition count
            from flexflow_tpu.search.measure import shard_shape

            key = (op.name, shard_shape(op.outputs[0].dims, axis_map,
                                        self.mesh_shape))
            if key in self.measured:
                return self.measured[key]
            if (op.name, parts) in self.measured:
                return self.measured[(op.name, parts)]
        flops = op.flops() / max(parts, 1)
        io_bytes = (sum(t.volume() for t in op.inputs)
                    + sum(t.volume() for t in op.outputs)) \
            * self.dtype_bytes / max(parts, 1)
        fwd = self.machine.compute_time(flops, io_bytes, self.dtype_bytes)
        return 3.0 * fwd  # fwd + ~2x bwd (reference measures both separately)

    def op_grad_sync_time(self, op: Op, axis_map: AxisMap) -> float:
        """All-reduce of weight grads over mesh axes that parallelize the op
        but do not shard the weight itself (pure replication axes)."""
        specs = op.weight_specs()
        if not specs:
            return 0.0
        try:
            wp = op.weight_partition(axis_map or {})
        except Exception:
            wp = {}
        total = 0.0
        for spec in specs:
            wbytes = int(np.prod(spec.shape)) * self.dtype_bytes
            pspec = wp.get(spec.name)
            sharded_axes = set()
            if pspec is not None:
                for entry in pspec:
                    if entry is None:
                        continue
                    for ax in (entry if isinstance(entry, tuple) else (entry,)):
                        sharded_axes.add(ax)
            shard_deg = 1
            for ax in sharded_axes:
                shard_deg *= self.mesh_shape.get(ax, 1)
            replicate_deg = 1
            for ax, d in (axis_map or {}).items():
                if d is not None and ax not in sharded_axes:
                    replicate_deg *= self.mesh_shape[ax]
            total += self.machine.all_reduce_time(wbytes / shard_deg,
                                                  replicate_deg)
        return total

    def resharding_time(self, producer_map: AxisMap, consumer_map: AxisMap,
                        tensor) -> float:
        """Cost to move a tensor from its producer's sharding to what the
        consumer constrains. Zero when maps agree per axis."""
        p = {ax: producer_map.get(ax) for ax in self.mesh_shape}
        c = {ax: consumer_map.get(ax) for ax in self.mesh_shape}
        if p == c:
            return 0.0
        tbytes = tensor.volume() * self.dtype_bytes
        per_chip = tbytes / max(_parts(producer_map, self.mesh_shape), 1)
        cost = 0.0
        for ax in self.mesh_shape:
            if p.get(ax) == c.get(ax):
                continue
            size = self.mesh_shape[ax]
            if size <= 1:
                continue
            if p.get(ax) is not None and c.get(ax) is not None:
                cost += self.machine.all_to_all_time(per_chip, size)
            elif p.get(ax) is not None:  # consumer wants it replicated
                cost += self.machine.all_gather_time(per_chip, size)
            else:  # dynamic-slice, nearly free
                cost += self.machine.ici_latency
        return cost

    # ---- whole strategy ------------------------------------------------------

    def iteration_time(self, strategy: Dict[str, AxisMap]) -> float:
        """Estimated seconds per training iteration under `strategy`.
        Serial sum over ops (ranking fidelity; the C++ simulator adds
        event-driven overlap)."""
        total = 0.0
        mem_per_chip = 0.0
        for op in self.model.ops:
            if isinstance(op, InputOp):
                continue
            am = strategy.get(op.name, {})
            total += self.op_compute_time(op, am)
            total += self.op_grad_sync_time(op, am)
            for t in op.inputs:
                if t.owner_op is None or isinstance(t.owner_op, InputOp):
                    continue
                pam = strategy.get(t.owner_op.name, {})
                # what the consumer wants for this input
                try:
                    idx = op.inputs.index(t)
                    want = op.input_axis_map(am, idx)
                except Exception:
                    want = am
                total += self.resharding_time(pam, want, t)
            parts = _parts(am, self.mesh_shape)
            mem_per_chip += (op.weight_bytes() * 3  # w + grad + opt state
                             + op.output_bytes()) / max(parts, 1)
        if mem_per_chip > self.machine.hbm_bytes:
            # 1 ms per MB over capacity (reference simulator.cc:612-617)
            total += (mem_per_chip - self.machine.hbm_bytes) / 1e6 * 1e-3
        return total
