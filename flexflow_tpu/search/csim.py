"""ctypes bridge to the native search core (csrc/sim.cc).

Builds the cost tables the C++ simulator consumes: per-op choice lists
(legal axis maps) with compute + grad-sync + per-device-memory costs and the
device count each choice spans, plus per-edge resharding cost matrices and
tensor sizes (for placement transfers). Compiles libffsim.so on first use
(g++, no pybind11 in this environment — plain C ABI + ctypes).

Strategies evaluated here are (choice, place) pairs per op: the axis map
plus the contiguous aligned device block the op runs on (reference
ParallelConfig.device_ids, config.h:47-69).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Dict, List, Optional, Tuple

import numpy as np

from flexflow_tpu.ops.base import InputOp
from flexflow_tpu.parallel.pconfig import ParallelConfig

_CSRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "csrc")
_LIB_PATH = os.path.join(_CSRC, "libffsim.so")
_lib = None


def _load_lib():
    global _lib
    if _lib is not None:
        return _lib
    src = os.path.join(_CSRC, "sim.cc")
    if (not os.path.exists(_LIB_PATH)
            or os.path.getmtime(_LIB_PATH) < os.path.getmtime(src)):
        subprocess.run(["g++", "-O3", "-std=c++17", "-fPIC", "-Wall",
                        "-shared", "-o", _LIB_PATH, src],
                       check=True, capture_output=True)
    lib = ctypes.CDLL(_LIB_PATH)
    d, i32, i64 = (np.ctypeslib.ndpointer(dtype=np.float64, flags="C"),
                   np.ctypeslib.ndpointer(dtype=np.int32, flags="C"),
                   np.ctypeslib.ndpointer(dtype=np.int64, flags="C"))
    cd = ctypes.c_double
    tables = [ctypes.c_int, ctypes.c_int, ctypes.c_int,  # ops, edges, devices
              i64, d, d, d, i32,                         # op tables
              i32, i32, i64, d, d]                       # edge tables
    lib.ff_simulate.restype = cd
    lib.ff_simulate.argtypes = tables + [i32, i32, cd, cd, cd, cd]
    lib.ff_simulate_timeline.restype = cd
    lib.ff_simulate_timeline.argtypes = tables + [i32, i32, cd, cd, cd, cd,
                                                  d, d, d, d, d, d]
    lib.ff_mcmc.restype = cd
    lib.ff_mcmc.argtypes = tables + [i32, i32, cd, cd, cd, cd,
                                     ctypes.c_int,  # allow_place
                                     ctypes.c_int, cd, ctypes.c_uint64,
                                     i32, i32]
    _lib = lib
    return lib


class CompiledSearchProblem:
    """The graph + strategy space factorized into flat cost tables."""

    def __init__(self, model, cost, mesh_shape: Dict[str, int],
                 epp: bool = True, eap: bool = True):
        from flexflow_tpu.search.driver import legal_axis_maps

        self.ops = [op for op in model.ops if not isinstance(op, InputOp)]
        self.op_index = {op.name: i for i, op in enumerate(self.ops)}
        self.mesh_shape = mesh_shape
        self.cost = cost
        self.num_devices = 1
        for v in mesh_shape.values():
            self.num_devices *= v
        self.op_maps: List[List[dict]] = [
            legal_axis_maps(op, mesh_shape, epp, eap) for op in self.ops]

        # per-op cost tables
        offsets = [0]
        compute, sync, mem, ndev = [], [], [], []
        for op, maps in zip(self.ops, self.op_maps):
            for am in maps:
                compute.append(cost.op_compute_time(op, am))
                sync.append(cost.op_grad_sync_time(op, am))
                mem.append(cost.op_mem_bytes(op, am))
                parts = 1
                for ax, dd in am.items():
                    if dd is not None:
                        parts *= mesh_shape[ax]
                ndev.append(max(1, min(parts, self.num_devices)))
            offsets.append(len(compute))
        self.op_cost_offsets = np.asarray(offsets, np.int64)
        self.op_compute_costs = np.asarray(compute, np.float64)
        self.op_sync_costs = np.asarray(sync, np.float64)
        self.op_mem_bytes = np.asarray(mem, np.float64)
        self.op_ndev = np.asarray(ndev, np.int32)

        # edges (sorted by consumer index — required by the C scheduler)
        edges = []  # (src_idx, dst_idx, input_idx, tensor)
        for dst_idx, op in enumerate(self.ops):
            for input_idx, t in enumerate(op.inputs):
                if t.owner_op is None or isinstance(t.owner_op, InputOp):
                    continue
                src_idx = self.op_index[t.owner_op.name]
                edges.append((src_idx, dst_idx, input_idx, t))
        edges.sort(key=lambda x: x[1])
        self.edge_src = np.asarray([e[0] for e in edges], np.int32)
        self.edge_dst = np.asarray([e[1] for e in edges], np.int32)
        self.edge_bytes = np.asarray(
            [e[3].volume() * cost.dtype_bytes for e in edges], np.float64)
        eoffsets = [0]
        ecosts: List[float] = []
        for src_idx, dst_idx, input_idx, t in edges:
            src_maps = self.op_maps[src_idx]
            dst_maps = self.op_maps[dst_idx]
            src_op = self.ops[src_idx]
            dst_op = self.ops[dst_idx]
            for pm in src_maps:
                # consumers see the producer's OUTPUT sharding (CONTRACT
                # axes deliver psum-replicated outputs)
                pm_out = src_op.output_axis_map(pm)
                for cm in dst_maps:
                    want = dst_op.input_axis_map(cm, input_idx)
                    ecosts.append(cost.resharding_time(pm_out, want, t))
            eoffsets.append(len(ecosts))
        self.edge_cost_offsets = np.asarray(eoffsets, np.int64)
        self.edge_costs = np.asarray(ecosts, np.float64)
        self.num_edges = len(edges)

    def _table_args(self):
        return (len(self.ops), self.num_edges, self.num_devices,
                self.op_cost_offsets, self.op_compute_costs,
                self.op_sync_costs, self.op_mem_bytes, self.op_ndev,
                self.edge_src, self.edge_dst, self.edge_cost_offsets,
                self.edge_costs, self.edge_bytes)

    def _machine_args(self):
        from flexflow_tpu.search.cost_model import MEM_PENALTY_PER_BYTE

        m = self.cost.machine
        return (float(m.hbm_bytes), float(m.ici_bw), float(m.ici_latency),
                float(MEM_PENALTY_PER_BYTE))

    def _places_arr(self, places) -> np.ndarray:
        if places is None:
            return np.zeros(len(self.ops), np.int32)
        if isinstance(places, dict):
            return np.asarray([int(places.get(op.name, 0))
                               for op in self.ops], np.int32)
        return np.ascontiguousarray(places, np.int32)

    def choices_for(self, strategy: Dict[str, dict]) -> np.ndarray:
        out = np.zeros(len(self.ops), np.int32)
        for i, (op, maps) in enumerate(zip(self.ops, self.op_maps)):
            am = strategy.get(op.name, {})
            norm = {ax: d for ax, d in am.items() if d is not None}
            for j, m in enumerate(maps):
                if {ax: d for ax, d in m.items() if d is not None} == norm:
                    out[i] = j
                    break
            else:
                raise ValueError(
                    f"strategy for op {op.name!r} ({norm}) is not in its "
                    f"legal axis-map list — check divisibility against mesh "
                    f"{self.mesh_shape} and the enable-*-parallel flags")
        return out

    def simulate(self, choices: np.ndarray, places=None) -> float:
        lib = _load_lib()
        return lib.ff_simulate(
            *self._table_args(),
            np.ascontiguousarray(choices, np.int32),
            self._places_arr(places), *self._machine_args())

    def simulate_timeline(self, choices: np.ndarray, places=None):
        """Per-task schedule under `choices` (reference: simulator DOT export
        with start/end times, --taskgraph). Returns (total_seconds, rows)
        where rows = [{kind, name, start, finish, src, dst}]."""
        lib = _load_lib()
        n, ne = len(self.ops), self.num_edges
        cs, cf = np.zeros(n), np.zeros(n)
        ss, sf = np.zeros(n), np.zeros(n)
        ms, mf = np.zeros(max(ne, 1)), np.zeros(max(ne, 1))
        total = lib.ff_simulate_timeline(
            *self._table_args(),
            np.ascontiguousarray(choices, np.int32),
            self._places_arr(places), *self._machine_args(),
            cs, cf, ms, mf, ss, sf)
        rows = []
        for i, op in enumerate(self.ops):
            rows.append({"kind": "compute", "name": op.name,
                         "start": cs[i], "finish": cf[i]})
            if sf[i] > ss[i]:
                rows.append({"kind": "grad_sync", "name": op.name,
                             "start": ss[i], "finish": sf[i]})
        for e in range(ne):
            if mf[e] > ms[e]:
                rows.append({"kind": "comm",
                             "name": f"{self.ops[self.edge_src[e]].name}->"
                                     f"{self.ops[self.edge_dst[e]].name}",
                             "start": ms[e], "finish": mf[e],
                             "src": self.ops[self.edge_src[e]].name,
                             "dst": self.ops[self.edge_dst[e]].name})
        return total, rows

    def mcmc(self, init_choices: np.ndarray, budget: int, alpha: float,
             seed: int, init_places=None, restarts: int = 1,
             allow_place: bool = True
             ) -> Tuple[np.ndarray, np.ndarray, float]:
        """Run `restarts` independent annealing chains and keep the best.
        The reference runs one chain with periodic reset-to-best
        (model.cc:1673-1677); independent restarts cut the across-seed
        variance that grows with the choice space. Chains run concurrently
        (the C call releases the GIL), so K restarts cost ~1 chain of
        wall-clock; chain seeds are spaced by a large stride so different
        base seeds never share chains."""
        from concurrent.futures import ThreadPoolExecutor

        lib = _load_lib()
        init = np.ascontiguousarray(init_choices, np.int32)
        places = self._places_arr(init_places)
        K = max(1, restarts)

        def chain(k):
            c = np.zeros(len(self.ops), np.int32)
            p = np.zeros(len(self.ops), np.int32)
            cost = lib.ff_mcmc(
                *self._table_args(), init, places, *self._machine_args(),
                int(allow_place), budget, alpha, seed * 0x9E3779B1 + k, c, p)
            return c, p, cost

        if K == 1:
            return chain(0)
        with ThreadPoolExecutor(max_workers=min(K, 8)) as ex:
            results = list(ex.map(chain, range(K)))
        return min(results, key=lambda r: r[2])


_UNCACHEABLE = object()


def _machine_cache_key(machine):
    """Value identity for the machine in the search-table cache key. The
    machine parameters feed every table entry, so two cost models over
    different machines (e.g. the infinite-HBM no-penalty comparison)
    must not share cached tables. Never id()-based: addresses get
    reused. A dataclass repr carries class + every field by value; any
    machine whose repr (or an attribute's) is the default address form
    is _UNCACHEABLE — the caller bypasses the cache entirely (no stale
    tables on a recycled address, no unbounded never-matching inserts)."""
    if machine is None:
        return None
    r = repr(machine)
    if "object at 0x" not in r:
        return (type(machine).__qualname__, r)
    attrs = getattr(machine, "__dict__", None)
    if attrs is not None:
        items = tuple(sorted((k, repr(v)) for k, v in attrs.items()))
        if not any("object at 0x" in v for _, v in items):
            return (type(machine).__qualname__, items)
    return _UNCACHEABLE


def get_search_problem(model, cost, mesh_shape: Dict[str, int],
                       epp: bool = True, eap: bool = True
                       ) -> CompiledSearchProblem:
    """Cache CompiledSearchProblem per (graph, mesh, flags, measured?) on the
    model — the search pass and the --taskgraph export at compile share one
    cost-table build instead of enumerating the O(edges x choices^2) tables
    twice."""
    measured = getattr(cost, "measured", None)
    machine = getattr(cost, "machine", None)
    mkey = _machine_cache_key(machine)
    if mkey is _UNCACHEABLE:
        return CompiledSearchProblem(model, cost, mesh_shape, epp, eap)
    key = (tuple(op.name for op in model.ops),
           tuple(sorted(mesh_shape.items())), epp, eap,
           mkey,
           getattr(cost, "fsdp_axis", None),
           getattr(cost, "dtype_bytes", None),
           # content hash of the measured table: a refreshed or in-place
           # updated table must invalidate the cached cost tables (id() can
           # be reused by a new dict at the same address)
           hash(frozenset(measured.items())) if measured else None)
    cache = model.__dict__.setdefault("_csim_problem_cache", {})
    if key not in cache:
        cache[key] = CompiledSearchProblem(model, cost, mesh_shape, epp, eap)
    return cache[key]


def native_optimize(model, cost, mesh_shape: Dict[str, int], budget: int,
                    alpha: float, seed: int,
                    verbose: bool = False,
                    restarts: int = 4,
                    warm_start=None) -> Dict[str, ParallelConfig]:
    from flexflow_tpu.search.driver import (data_parallel_strategy,
                                            hierarchical_strategy)

    cfg = getattr(model, "config", None)
    epp = getattr(cfg, "enable_parameter_parallel", True)
    eap = getattr(cfg, "enable_attribute_parallel", True)
    prob = get_search_problem(model, cost, mesh_shape, epp, eap)
    init = prob.choices_for(data_parallel_strategy(model, mesh_shape))
    dp_cost = prob.simulate(init)
    init_cost = dp_cost
    # two-tier machine: the hierarchical ICI/DCN candidate (data/STAGE on
    # the DCN axes, CONTRACT/TP inside ICI) is a first-class move — it
    # seeds the chains when it beats flat DP, and it competes with the
    # annealed winner below either way (the C tables already price its
    # grad syncs at the DCN tier through op_grad_sync_time)
    hier_c = hier_cost = None
    if getattr(cost.machine, "dcn_axes", None):
        hier_c = prob.choices_for(hierarchical_strategy(
            model, mesh_shape, cost.machine.dcn_axes, epp, eap))
        hier_cost = prob.simulate(hier_c)
        if hier_cost < init_cost:
            init, init_cost = hier_c, hier_cost
    # warm start (ISSUE 19d): a previous search's strategy — already
    # normalized by driver.warm_start_seed to this mesh's legal maps —
    # seeds the chains when cheaper and competes with the winner below,
    # so an N-chip result can only help, never hurt, the M-chip search
    warm_c = warm_cost = None
    if warm_start is not None:
        try:
            warm_c = prob.choices_for(warm_start)
            warm_cost = prob.simulate(warm_c)
            if warm_cost < init_cost:
                init, init_cost = warm_c, warm_cost
        except ValueError:
            warm_c = warm_cost = None  # stale strategy: ignore, not fatal
    # FSDP shards every weight over the full fsdp mesh axis; a sub-mesh
    # placement cannot hold such a weight, so the annealer must not
    # propose device-block moves (compile would reject its own winner)
    allow_place = not getattr(cost, "fsdp_axis", "")
    best_c, best_p, best_cost = prob.mcmc(init, budget, alpha, seed,
                                          restarts=restarts,
                                          allow_place=allow_place)
    if hier_cost is not None and hier_cost < best_cost:
        best_c, best_p, best_cost = (hier_c,
                                     np.zeros(len(prob.ops), np.int32),
                                     hier_cost)
    if warm_cost is not None and warm_cost < best_cost:
        best_c, best_p, best_cost = (warm_c,
                                     np.zeros(len(prob.ops), np.int32),
                                     warm_cost)
    if verbose:
        print(f"[search/native] best {best_cost * 1e3:.3f} ms vs DP "
              f"{dp_cost * 1e3:.3f} ms "
              f"({dp_cost / max(best_cost, 1e-12):.2f}x), "
              f"{len(prob.ops)} ops, {prob.num_edges} edges, "
              f"{prob.num_devices} devices")
    out = {}
    for i, op in enumerate(prob.ops):
        am = prob.op_maps[i][int(best_c[i])]
        pc = ParallelConfig.from_axis_map(
            op.outputs[0].num_dims, mesh_shape, am)
        ndev = int(prob.op_ndev[prob.op_cost_offsets[i] + int(best_c[i])])
        start = int(best_p[i])
        pc.device_ids = tuple(range(start, start + ndev))
        out[op.name] = pc
    _snap_tied_blocks(model, out, prob.num_devices)
    return out


def _snap_tied_blocks(model, out: Dict[str, ParallelConfig],
                      num_devices: int):
    """tie_weights PREFERENCE the annealer doesn't model: every op in a
    tie-connected component should share ONE device block. Since r5 the
    PlacementExecutor executes cross-block ties (per-step source-weight
    broadcast + gradient route-home), but the snapped strategy avoids
    that per-step transfer entirely, so the search still proposes only
    same-block tie components. Components (a source with several dests, a
    dest tied to several sources) are resolved together — a pairwise
    single pass is not a fixpoint: snapping pair 2 can re-break pair 1.
    Per component, pick the largest member block whose size every member's
    sharding degree divides; if none fits, the full mesh (block 0) —
    always valid. The simulated cost of the snapped strategy can differ
    from the annealer's estimate; correct-and-executable beats
    optimal-and-rejected."""
    tied = getattr(model, "_tied", None) or {}
    if not tied:
        return
    # union-find over tie edges
    parent: Dict[str, str] = {}

    def find(a):
        parent.setdefault(a, a)
        while parent[a] != a:
            parent[a] = parent[parent[a]]
            a = parent[a]
        return a

    for (dst_op, _), (src_op, _, _) in tied.items():
        if dst_op in out and src_op in out:
            parent[find(dst_op)] = find(src_op)
    comps: Dict[str, list] = {}
    for name in parent:
        comps.setdefault(find(name), []).append(name)

    def blk(pc):
        return ((min(pc.device_ids), len(pc.device_ids))
                if pc.device_ids else (0, num_devices))

    for members in comps.values():
        blocks = {blk(out[m]) for m in members}
        if len(blocks) <= 1:
            continue
        chosen = (0, num_devices)
        for cand in sorted(blocks, key=lambda b: -b[1]):
            if all(cand[1] % max(out[m].num_parts(), 1) == 0
                   for m in members):
                chosen = cand
                break
        ids = tuple(range(chosen[0], chosen[0] + chosen[1]))
        for m in members:
            out[m].device_ids = ids
