"""ctypes bridge to the native search core (csrc/sim.cc).

Builds the cost tables the C++ simulator consumes: per-op choice lists
(legal axis maps) with compute + grad-sync costs from the Python CostModel,
and per-edge resharding cost matrices. Compiles libffsim.so on first use
(g++, no pybind11 in this environment — plain C ABI + ctypes).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Dict, List, Optional

import numpy as np

from flexflow_tpu.ops.base import InputOp
from flexflow_tpu.parallel.pconfig import ParallelConfig

_CSRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "csrc")
_LIB_PATH = os.path.join(_CSRC, "libffsim.so")
_lib = None


def _load_lib():
    global _lib
    if _lib is not None:
        return _lib
    src = os.path.join(_CSRC, "sim.cc")
    if (not os.path.exists(_LIB_PATH)
            or os.path.getmtime(_LIB_PATH) < os.path.getmtime(src)):
        subprocess.run(["g++", "-O3", "-std=c++17", "-fPIC", "-Wall",
                        "-shared", "-o", _LIB_PATH, src],
                       check=True, capture_output=True)
    lib = ctypes.CDLL(_LIB_PATH)
    d, i32, i64 = (np.ctypeslib.ndpointer(dtype=np.float64, flags="C"),
                   np.ctypeslib.ndpointer(dtype=np.int32, flags="C"),
                   np.ctypeslib.ndpointer(dtype=np.int64, flags="C"))
    lib.ff_simulate.restype = ctypes.c_double
    lib.ff_simulate.argtypes = [ctypes.c_int, ctypes.c_int, i64, d, d,
                                i32, i32, i64, d, i32]
    lib.ff_mcmc.restype = ctypes.c_double
    lib.ff_mcmc.argtypes = [ctypes.c_int, ctypes.c_int, i64, d, d,
                            i32, i32, i64, d, i32,
                            ctypes.c_int, ctypes.c_double, ctypes.c_uint64, i32]
    lib.ff_simulate_timeline.restype = ctypes.c_double
    lib.ff_simulate_timeline.argtypes = [ctypes.c_int, ctypes.c_int, i64, d, d,
                                         i32, i32, i64, d, i32,
                                         d, d, d, d, d, d]
    _lib = lib
    return lib


class CompiledSearchProblem:
    """The graph + strategy space factorized into flat cost tables."""

    def __init__(self, model, cost, mesh_shape: Dict[str, int],
                 epp: bool = True, eap: bool = True):
        from flexflow_tpu.search.driver import legal_axis_maps

        self.ops = [op for op in model.ops if not isinstance(op, InputOp)]
        self.op_index = {op.name: i for i, op in enumerate(self.ops)}
        self.mesh_shape = mesh_shape
        self.op_maps: List[List[dict]] = [
            legal_axis_maps(op, mesh_shape, epp, eap) for op in self.ops]

        # per-op cost tables
        offsets = [0]
        compute, sync = [], []
        for op, maps in zip(self.ops, self.op_maps):
            for am in maps:
                compute.append(cost.op_compute_time(op, am))
                sync.append(cost.op_grad_sync_time(op, am))
            offsets.append(len(compute))
        self.op_cost_offsets = np.asarray(offsets, np.int64)
        self.op_compute_costs = np.asarray(compute, np.float64)
        self.op_sync_costs = np.asarray(sync, np.float64)

        # edges (sorted by consumer index — required by the C scheduler)
        edges = []  # (src_idx, dst_idx, input_idx, tensor)
        for dst_idx, op in enumerate(self.ops):
            for input_idx, t in enumerate(op.inputs):
                if t.owner_op is None or isinstance(t.owner_op, InputOp):
                    continue
                src_idx = self.op_index[t.owner_op.name]
                edges.append((src_idx, dst_idx, input_idx, t))
        edges.sort(key=lambda x: x[1])
        self.edge_src = np.asarray([e[0] for e in edges], np.int32)
        self.edge_dst = np.asarray([e[1] for e in edges], np.int32)
        eoffsets = [0]
        ecosts: List[float] = []
        for src_idx, dst_idx, input_idx, t in edges:
            src_maps = self.op_maps[src_idx]
            dst_maps = self.op_maps[dst_idx]
            dst_op = self.ops[dst_idx]
            for pm in src_maps:
                for cm in dst_maps:
                    want = dst_op.input_axis_map(cm, input_idx)
                    ecosts.append(cost.resharding_time(pm, want, t))
            eoffsets.append(len(ecosts))
        self.edge_cost_offsets = np.asarray(eoffsets, np.int64)
        self.edge_costs = np.asarray(ecosts, np.float64)
        self.num_edges = len(edges)

    def choices_for(self, strategy: Dict[str, dict]) -> np.ndarray:
        out = np.zeros(len(self.ops), np.int32)
        for i, (op, maps) in enumerate(zip(self.ops, self.op_maps)):
            am = strategy.get(op.name, {})
            norm = {ax: d for ax, d in am.items() if d is not None}
            for j, m in enumerate(maps):
                if {ax: d for ax, d in m.items() if d is not None} == norm:
                    out[i] = j
                    break
            else:
                raise ValueError(
                    f"strategy for op {op.name!r} ({norm}) is not in its "
                    f"legal axis-map list — check divisibility against mesh "
                    f"{self.mesh_shape} and the enable-*-parallel flags")
        return out

    def simulate(self, choices: np.ndarray) -> float:
        lib = _load_lib()
        return lib.ff_simulate(
            len(self.ops), self.num_edges, self.op_cost_offsets,
            self.op_compute_costs, self.op_sync_costs, self.edge_src,
            self.edge_dst, self.edge_cost_offsets, self.edge_costs,
            np.ascontiguousarray(choices, np.int32))

    def simulate_timeline(self, choices: np.ndarray):
        """Per-task schedule under `choices` (reference: simulator DOT export
        with start/end times, --taskgraph). Returns (total_seconds, rows)
        where rows = [{kind, name, start, finish, src, dst}]."""
        lib = _load_lib()
        n, ne = len(self.ops), self.num_edges
        cs, cf = np.zeros(n), np.zeros(n)
        ss, sf = np.zeros(n), np.zeros(n)
        ms, mf = np.zeros(max(ne, 1)), np.zeros(max(ne, 1))
        total = lib.ff_simulate_timeline(
            n, ne, self.op_cost_offsets, self.op_compute_costs,
            self.op_sync_costs, self.edge_src, self.edge_dst,
            self.edge_cost_offsets, self.edge_costs,
            np.ascontiguousarray(choices, np.int32), cs, cf, ms, mf, ss, sf)
        rows = []
        for i, op in enumerate(self.ops):
            rows.append({"kind": "compute", "name": op.name,
                         "start": cs[i], "finish": cf[i]})
            if sf[i] > ss[i]:
                rows.append({"kind": "grad_sync", "name": op.name,
                             "start": ss[i], "finish": sf[i]})
        for e in range(ne):
            if mf[e] > ms[e]:
                rows.append({"kind": "comm",
                             "name": f"{self.ops[self.edge_src[e]].name}->"
                                     f"{self.ops[self.edge_dst[e]].name}",
                             "start": ms[e], "finish": mf[e],
                             "src": self.ops[self.edge_src[e]].name,
                             "dst": self.ops[self.edge_dst[e]].name})
        return total, rows

    def mcmc(self, init_choices: np.ndarray, budget: int, alpha: float,
             seed: int):
        lib = _load_lib()
        best = np.zeros(len(self.ops), np.int32)
        best_cost = lib.ff_mcmc(
            len(self.ops), self.num_edges, self.op_cost_offsets,
            self.op_compute_costs, self.op_sync_costs, self.edge_src,
            self.edge_dst, self.edge_cost_offsets, self.edge_costs,
            np.ascontiguousarray(init_choices, np.int32),
            budget, alpha, seed, best)
        return best, best_cost


def get_search_problem(model, cost, mesh_shape: Dict[str, int],
                       epp: bool = True, eap: bool = True
                       ) -> CompiledSearchProblem:
    """Cache CompiledSearchProblem per (graph, mesh, flags, measured?) on the
    model — the search pass and the --taskgraph export at compile share one
    cost-table build instead of enumerating the O(edges x choices^2) tables
    twice."""
    measured = getattr(cost, "measured", None)
    key = (tuple(op.name for op in model.ops),
           tuple(sorted(mesh_shape.items())), epp, eap,
           # content hash of the measured table: a refreshed or in-place
           # updated table must invalidate the cached cost tables (id() can
           # be reused by a new dict at the same address)
           hash(frozenset(measured.items())) if measured else None)
    cache = model.__dict__.setdefault("_csim_problem_cache", {})
    if key not in cache:
        cache[key] = CompiledSearchProblem(model, cost, mesh_shape, epp, eap)
    return cache[key]


def native_optimize(model, cost, mesh_shape: Dict[str, int], budget: int,
                    alpha: float, seed: int,
                    verbose: bool = False) -> Dict[str, ParallelConfig]:
    from flexflow_tpu.search.driver import data_parallel_strategy

    cfg = getattr(model, "config", None)
    epp = getattr(cfg, "enable_parameter_parallel", True)
    eap = getattr(cfg, "enable_attribute_parallel", True)
    prob = get_search_problem(model, cost, mesh_shape, epp, eap)
    init = prob.choices_for(data_parallel_strategy(model, mesh_shape))
    dp_cost = prob.simulate(init)
    best, best_cost = prob.mcmc(init, budget, alpha, seed)
    if verbose:
        print(f"[search/native] best {best_cost * 1e3:.3f} ms vs DP "
              f"{dp_cost * 1e3:.3f} ms "
              f"({dp_cost / max(best_cost, 1e-12):.2f}x), "
              f"{len(prob.ops)} ops, {prob.num_edges} edges")
    out = {}
    for i, op in enumerate(prob.ops):
        am = prob.op_maps[i][int(best[i])]
        out[op.name] = ParallelConfig.from_axis_map(
            op.outputs[0].num_dims, mesh_shape, am)
    return out
