"""Auto-parallelization search engine.

Reference: the MCMC simulated-annealing search over per-op ParallelConfigs
(FFModel::optimize model.cc:1663-1725, strategy_search_task simulator.cu:106)
driven by an event-driven task-graph simulator (simulator.cc:325-621) whose
op costs are measured on hardware and whose comm costs come from an analytic
machine model.

TPU rebuild: the proposal space is mesh-expressible axis maps (GSPMD
constraint); the machine model is ICI/HBM/MXU; op costs come from analytic
FLOPs/bytes with optional real-device measurement
(jit(...).lower().compile() + timed run, cached). The hot simulate+anneal
loop lives in C++ (flexflow_tpu/search/csrc, loaded via ctypes) with a pure-
Python fallback.
"""

from flexflow_tpu.search.driver import optimize_strategies  # noqa: F401
