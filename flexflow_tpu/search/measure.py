"""Real-device per-op cost measurement feeding the strategy search.

Reference: Simulator::measure_operator_cost (simulator.cc:296-316) + the
cudaEvent harness Op::inner_measure_operator_cost (model.cu:20-62): each op's
real kernels are run ~15x per (op, ParallelConfig) sub-shape on GPU 0 and
cached. Here each candidate sharding's per-shard sub-shapes are timed on one
chip with a jitted fwd+bwd of the single op.

XLA compiles are seconds, not kernel launches (SURVEY §7 hard part 1), so:
  * measurements are keyed by (op signature, shard shapes) and shared across
    identical ops — a 12-layer transformer measures each distinct layer shape
    once, not 12x;
  * only shard shapes reachable from `legal_axis_maps` are measured;
  * results persist in-process in `_SIGNATURE_CACHE` across searches.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

import numpy as np

from flexflow_tpu.ffconst import DataType, dtype_to_np
from flexflow_tpu.ops.base import InputOp, Op

# (signature) -> seconds for fwd+bwd of one shard
_SIGNATURE_CACHE: Dict[Tuple, float] = {}


def shard_shape(dims, axis_map, mesh_shape) -> Tuple[int, ...]:
    """Per-shard shape of a tensor partitioned by axis_map."""
    out = list(dims)
    for ax, d in (axis_map or {}).items():
        # negative sentinels (CONTRACT) do not shard the output shape
        if d is not None and 0 <= d < len(out):
            deg = mesh_shape.get(ax, 1)
            out[d] = max(out[d] // deg, 1)
    return tuple(out)


def choice_key(op_name: str, out_dims, axis_map,
               mesh_shape: Dict[str, int]) -> Tuple:
    """Cache key for one (op, sharding choice). The per-shard OUTPUT shape
    alone cannot distinguish CONTRACT (row-parallel) from plain data
    parallelism — contract axes shard the inputs and weights, not the
    output — so the contract degree is appended when present."""
    from flexflow_tpu.parallel.pconfig import CONTRACT, STAGE

    cdeg = 1
    sdeg = 1
    for ax, d in (axis_map or {}).items():
        if d == CONTRACT:
            cdeg *= mesh_shape.get(ax, 1)
        elif d == STAGE:
            # STAGE shards the layer dim of the WEIGHTS (measured as one
            # stage's slice over the full batch); the output shape alone
            # would collide with the replicated choice
            sdeg *= mesh_shape.get(ax, 1)
    key = (op_name, shard_shape(out_dims, axis_map, mesh_shape))
    if cdeg > 1:
        key = key + (("contract", cdeg),)
    if sdeg > 1:
        key = key + (("stage", sdeg),)
    return key


def _op_signature(op: Op, in_shapes, w_shapes) -> Tuple:
    return (type(op).__name__, tuple(sorted(
        (k, repr(v)) for k, v in op.attrs.items())),
        tuple(in_shapes), tuple(w_shapes))


def _rand_for(shape, dtype: DataType, rs):
    np_dt = dtype_to_np(dtype)
    if np.issubdtype(np_dt, np.integer):
        return rs.randint(0, 2, shape).astype(np_dt)
    return rs.randn(*shape).astype(np_dt)


def _single_device_ctx():
    """A 1-device mesh shard_ctx so wants_shard_ctx ops run their local
    (dense) lowering inside the measurement harness — the per-shard compute
    cost is what the simulator wants; comm is priced separately by the
    machine model."""
    import jax
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(jax.devices()[:1]), ("_measure",))
    return {"mesh": mesh, "axis_map": {}, "sp_mode": "ring"}


def _build_fwd_bwd(op: Op, params, xs, rng):
    """fwd+bwd closure differentiating w.r.t. params and FLOAT inputs only
    (integer inputs — embedding ids — are closed over; value_and_grad on them
    would raise and previously made such ops silently unmeasurable)."""
    import jax
    import jax.numpy as jnp

    float_idx = tuple(i for i, x in enumerate(xs)
                      if jnp.issubdtype(x.dtype, jnp.floating))
    int_xs = {i: x for i, x in enumerate(xs) if i not in float_idx}
    kwargs = {}
    if getattr(op, "wants_shard_ctx", False):
        kwargs["shard_ctx"] = _single_device_ctx()
    # per-shard state: channel-sharded BatchNorm's running stats must match
    # the shard's channel count or the stat update fails to trace and the
    # choice silently falls back to analytic cost
    state0 = {k: jnp.asarray(v) for k, v in
              op.init_state_for_shapes([x.shape for x in xs]).items()} \
        if op.stateful else None

    def fwd_bwd(p, fxs):
        def loss(p_, fxs_):
            xs_ = [int_xs[i] if i in int_xs else fxs_[float_idx.index(i)]
                   for i in range(len(xs))]
            if op.stateful:
                outs, _ = op.forward_stateful(
                    p_, state0, xs_, training=True,
                    rng=rng if op.needs_rng else None)
            else:
                outs = op.forward(p_, xs_, training=True,
                                  rng=rng if op.needs_rng else None, **kwargs)
            return sum(jnp.sum(jnp.square(o.astype(jnp.float32)))
                       for o in outs)

        return jax.value_and_grad(loss, argnums=(0, 1))(p, fxs)

    float_vals = tuple(xs[i] for i in float_idx)
    return fwd_bwd, float_vals


def measure_one(op: Op, in_shapes, w_shapes, *, warmup=1, iters=5,
                timeout_compile=None) -> Optional[float]:
    """Time one jitted fwd+bwd of `op` at the given per-shard shapes on the
    default device (reference: every op implements measure_operator_cost,
    model.cu:20-62 — including attention/BN/LSTM, so we must too).
    Returns seconds, or None if the op genuinely can't run standalone."""
    import jax
    import jax.numpy as jnp

    sig = _op_signature(op, in_shapes, w_shapes)
    if sig in _SIGNATURE_CACHE:
        return _SIGNATURE_CACHE[sig]
    rs = np.random.RandomState(0)
    try:
        xs = [jnp.asarray(_rand_for(s, t.dtype, rs))
              for s, t in zip(in_shapes, op.inputs)]
        params = {spec.name: jnp.asarray(rs.randn(*s).astype(np.float32))
                  for spec, s in zip(op.weight_specs(), w_shapes)}
        rng = jax.random.PRNGKey(0)
        fwd_bwd, fxs = _build_fwd_bwd(op, params, xs, rng)
        step = jax.jit(fwd_bwd)
        out = step(params, fxs)  # compile + warmup
        jax.block_until_ready(out)
        for _ in range(warmup):
            jax.block_until_ready(step(params, fxs))
        t0 = time.perf_counter()
        for _ in range(iters):
            out = step(params, fxs)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / iters
    except Exception as e:
        _log_skip(op, e)
        return None
    _SIGNATURE_CACHE[sig] = dt
    return dt


_SKIP_LOGGED = set()


def _log_skip(op: Op, err: Exception):
    """Surface unmeasurable ops once per op name — a silent None here means
    the search runs on analytic FLOPs for that op (fidelity gap)."""
    if op.name in _SKIP_LOGGED:
        return
    _SKIP_LOGGED.add(op.name)
    from flexflow_tpu.logger import fflogger

    fflogger.warning("cost measurement skipped for %s (%s: %s) — falling "
                     "back to analytic estimate", op.name,
                     type(err).__name__, err)


def measure_op_costs(model, mesh_shape: Dict[str, int],
                     enable_parameter_parallel: bool = True,
                     enable_attribute_parallel: bool = True,
                     iters: int = 5, verbose: bool = False) -> Dict:
    """Build the `measured` table for CostModel: {(op_name, shard_out_shape):
    seconds}. Measures every distinct per-shard signature reachable by the
    search's proposal space (reference: cache keyed by op+config hash,
    simulator.cc:298-303)."""
    from flexflow_tpu.search.driver import legal_axis_maps

    measured: Dict = {}
    n_timed = 0
    for op in model.ops:
        if isinstance(op, InputOp):
            continue
        seen_keys = set()
        for am in legal_axis_maps(op, mesh_shape, enable_parameter_parallel,
                                  enable_attribute_parallel):
            key = choice_key(op.name, op.outputs[0].dims, am, mesh_shape)
            if key in seen_keys:
                continue
            seen_keys.add(key)
            in_shapes = []
            for i, t in enumerate(op.inputs):
                iam = op.input_axis_map(am, i)
                in_shapes.append(shard_shape(t.dims, iam, mesh_shape))
            try:
                wp = op.weight_partition(am)
            except Exception:
                wp = {}
            w_shapes = []
            for spec in op.weight_specs():
                ws = list(spec.shape)
                pspec = wp.get(spec.name)
                if pspec is not None:
                    for d, entry in enumerate(pspec):
                        if entry is None:
                            continue
                        axes = entry if isinstance(entry, tuple) else (entry,)
                        deg = 1
                        for ax in axes:
                            deg *= mesh_shape.get(ax, 1)
                        if d < len(ws):
                            ws[d] = max(ws[d] // deg, 1)
                w_shapes.append(tuple(ws))
            dt = measure_one(op, in_shapes, w_shapes, iters=iters)
            if dt is not None:
                measured[key] = dt
                n_timed += 1
                if verbose:
                    print(f"[measure] {op.name} {key[1:]}: "
                          f"{dt * 1e3:.3f} ms")
    if verbose:
        print(f"[measure] {n_timed} entries, "
              f"{len(_SIGNATURE_CACHE)} unique signatures timed")
    return measured


def analyze_one(op: Op, in_shapes, w_shapes) -> Optional[Tuple[float, float]]:
    """Compile (don't run) one op's fwd+bwd and read XLA's cost analysis.
    Returns (flops, bytes_accessed) or None. The compile-only middle tier
    between the analytic roofline and real timing (SURVEY §7: cost model
    fidelity without cheap per-config microbenchmarks)."""
    import jax
    import jax.numpy as jnp

    sig = ("analyze",) + _op_signature(op, in_shapes, w_shapes)
    if sig in _SIGNATURE_CACHE:
        return _SIGNATURE_CACHE[sig]
    rs = np.random.RandomState(0)
    try:
        xs = [jnp.asarray(_rand_for(s, t.dtype, rs))
              for s, t in zip(in_shapes, op.inputs)]
        params = {spec.name: jnp.asarray(rs.randn(*s).astype(np.float32))
                  for spec, s in zip(op.weight_specs(), w_shapes)}
        rng = jax.random.PRNGKey(0)
        fwd_bwd, fxs = _build_fwd_bwd(op, params, xs, rng)
        compiled = jax.jit(fwd_bwd).lower(params, fxs).compile()
        ca = compiled.cost_analysis() or {}
        if isinstance(ca, (list, tuple)):  # some backends return a list
            ca = ca[0] if ca else {}
        out = (float(ca.get("flops", 0.0)),
               float(ca.get("bytes accessed", 0.0)))
    except Exception as e:
        _log_skip(op, e)
        return None
    _SIGNATURE_CACHE[sig] = out
    return out


def analyze_op_costs(model, mesh_shape: Dict[str, int],
                     machine=None,
                     enable_parameter_parallel: bool = True,
                     enable_attribute_parallel: bool = True,
                     verbose: bool = False) -> Dict:
    """Compile-only cost table for CostModel.measured: XLA-reported
    flops/bytes per shard signature, converted to seconds by the machine
    model's roofline. ~10x cheaper than measure_op_costs (no execution,
    no warmup) and far closer to reality than per-op analytic FLOPs
    (captures XLA fusion inside the op's fwd+bwd)."""
    from flexflow_tpu.search.driver import legal_axis_maps
    from flexflow_tpu.search.machine import MachineModel

    machine = machine or MachineModel()
    table: Dict = {}
    for op in model.ops:
        if isinstance(op, InputOp):
            continue
        seen_keys = set()
        for am in legal_axis_maps(op, mesh_shape, enable_parameter_parallel,
                                  enable_attribute_parallel):
            key = choice_key(op.name, op.outputs[0].dims, am, mesh_shape)
            if key in seen_keys:
                continue
            seen_keys.add(key)
            in_shapes = []
            for i, t in enumerate(op.inputs):
                iam = op.input_axis_map(am, i)
                in_shapes.append(shard_shape(t.dims, iam, mesh_shape))
            try:
                wp = op.weight_partition(am)
            except Exception:
                wp = {}
            w_shapes = []
            for spec in op.weight_specs():
                ws = list(spec.shape)
                pspec = wp.get(spec.name)
                if pspec is not None:
                    for d, entry in enumerate(pspec):
                        if entry is None:
                            continue
                        axes = entry if isinstance(entry, tuple) else (entry,)
                        deg = 1
                        for ax in axes:
                            deg *= mesh_shape.get(ax, 1)
                        if d < len(ws):
                            ws[d] = max(ws[d] // deg, 1)
                w_shapes.append(tuple(ws))
            fb = analyze_one(op, in_shapes, w_shapes)
            if fb is not None:
                flops, nbytes = fb
                table[key] = machine.compute_time(flops, nbytes, 4)
                if verbose:
                    print(f"[analyze] {op.name} {key[1:]}: "
                          f"{flops / 1e6:.2f} MF {nbytes / 1e6:.2f} MB "
                          f"-> {table[key] * 1e6:.1f} us")
    return table
