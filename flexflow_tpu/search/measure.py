"""Real-device per-op cost measurement feeding the strategy search.

Reference: Simulator::measure_operator_cost (simulator.cc:296-316) + the
cudaEvent harness Op::inner_measure_operator_cost (model.cu:20-62): each op's
real kernels are run ~15x per (op, ParallelConfig) sub-shape on GPU 0 and
cached. Here each candidate sharding's per-shard sub-shapes are timed on one
chip with a jitted fwd+bwd of the single op.

XLA compiles are seconds, not kernel launches (SURVEY §7 hard part 1), so:
  * measurements are keyed by (op signature, shard shapes) and shared across
    identical ops — a 12-layer transformer measures each distinct layer shape
    once, not 12x;
  * only shard shapes reachable from `legal_axis_maps` are measured;
  * results persist in-process in `_SIGNATURE_CACHE` across searches, and
    — when a cost-DB path is configured (FFConfig.cost_db_path /
    FF_COST_DB, search/cost_db.py) — across PROCESSES: a warm-started
    search re-measures zero already-keyed ops.
"""

from __future__ import annotations

import os
import time
from typing import Dict, Optional, Tuple

import numpy as np

from flexflow_tpu.ffconst import DataType, dtype_to_np
from flexflow_tpu.ops.base import InputOp, Op

# ("measure", signature) -> seconds for fwd+bwd of one shard;
# ("analyze", signature) -> (flops, bytes_accessed).
# The kind prefix is a 2-tuple NESTING (not the historical flat
# ("analyze",) + sig concatenation): measured and analyzed rows carry
# structurally distinct keys AND value types, so neither can collide
# with or shadow the other here or in the persisted DB (ISSUE 19
# satellite; pinned by tests/test_cost_db.py round-trips).
_SIGNATURE_CACHE: Dict[Tuple, object] = {}


class MeasuredTable(dict):
    """The cost table measure_op_costs returns: a plain {key: seconds}
    dict (drop-in for every CostModel consumer) that also records how
    many DISTINCT signatures back its keys — twins share one timing, so
    len(table) >= signatures_timed. scripts/northstar_search.py reports
    both for cost-table provenance."""

    signatures_timed: int = 0


def shard_shape(dims, axis_map, mesh_shape) -> Tuple[int, ...]:
    """Per-shard shape of a tensor partitioned by axis_map."""
    out = list(dims)
    for ax, d in (axis_map or {}).items():
        # negative sentinels (CONTRACT) do not shard the output shape
        if d is not None and 0 <= d < len(out):
            deg = mesh_shape.get(ax, 1)
            out[d] = max(out[d] // deg, 1)
    return tuple(out)


def choice_key(op_name: str, out_dims, axis_map,
               mesh_shape: Dict[str, int]) -> Tuple:
    """Cache key for one (op, sharding choice). The per-shard OUTPUT shape
    alone cannot distinguish CONTRACT (row-parallel) from plain data
    parallelism — contract axes shard the inputs and weights, not the
    output — so the contract degree is appended when present."""
    from flexflow_tpu.parallel.pconfig import CONTRACT, EXPERT, STAGE

    cdeg = 1
    sdeg = 1
    edeg = 1
    for ax, d in (axis_map or {}).items():
        if d == CONTRACT:
            cdeg *= mesh_shape.get(ax, 1)
        elif d == STAGE:
            # STAGE shards the layer dim of the WEIGHTS (measured as one
            # stage's slice over the full batch); the output shape alone
            # would collide with the replicated choice
            sdeg *= mesh_shape.get(ax, 1)
        elif d == EXPERT:
            # EXPERT shards the expert dim of the weights — same
            # output-shape collision as STAGE
            edeg *= mesh_shape.get(ax, 1)
    key = (op_name, shard_shape(out_dims, axis_map, mesh_shape))
    if cdeg > 1:
        key = key + (("contract", cdeg),)
    if sdeg > 1:
        key = key + (("stage", sdeg),)
    if edeg > 1:
        key = key + (("expert", edeg),)
    return key


_ENV_SIG: Optional[Tuple] = None


def _env_signature() -> Tuple:
    """(backend, device kind, jax version) stamped into every cost
    signature. Within one process it is constant — but these signatures
    are the keys the persistent cost tables (kernel_tune today, the
    ROADMAP-3 cross-session cost DB next) are built from, and a timing
    taken on one backend/jax build must never be served on another."""
    global _ENV_SIG
    if _ENV_SIG is None:
        import jax

        try:
            kind = getattr(jax.devices()[0], "device_kind", "?")
        except Exception:
            kind = "?"
        _ENV_SIG = (jax.default_backend(), kind, jax.__version__)
    return _ENV_SIG


def _op_signature(op: Op, in_shapes, w_shapes) -> Tuple:
    # BUGFIX (ISSUE 7 satellite): shapes alone under-keyed the cache —
    # the same (op, shard shape) measured in bf16 was served for an fp32
    # query (2x the HBM bytes), and nothing invalidated entries across a
    # jax/libtpu bump. Input dtypes + the environment signature are now
    # part of every key.
    in_dtypes = tuple(t.dtype.name if hasattr(t.dtype, "name")
                      else repr(t.dtype) for t in op.inputs)
    return (type(op).__name__, tuple(sorted(
        (k, repr(v)) for k, v in op.attrs.items())),
        tuple(in_shapes), tuple(w_shapes), in_dtypes, _env_signature())


def _rand_for(shape, dtype: DataType, rs):
    np_dt = dtype_to_np(dtype)
    if np.issubdtype(np_dt, np.integer):
        return rs.randint(0, 2, shape).astype(np_dt)
    return rs.randn(*shape).astype(np_dt)


def _single_device_ctx():
    """A 1-device mesh shard_ctx so wants_shard_ctx ops run their local
    (dense) lowering inside the measurement harness — the per-shard compute
    cost is what the simulator wants; comm is priced separately by the
    machine model."""
    import jax
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(jax.devices()[:1]), ("_measure",))
    return {"mesh": mesh, "axis_map": {}, "sp_mode": "ring"}


def _build_fwd_bwd(op: Op, params, xs, rng):
    """fwd+bwd closure differentiating w.r.t. params and FLOAT inputs only
    (integer inputs — embedding ids — are closed over; value_and_grad on them
    would raise and previously made such ops silently unmeasurable)."""
    import jax
    import jax.numpy as jnp

    float_idx = tuple(i for i, x in enumerate(xs)
                      if jnp.issubdtype(x.dtype, jnp.floating))
    int_xs = {i: x for i, x in enumerate(xs) if i not in float_idx}
    kwargs = {}
    if getattr(op, "wants_shard_ctx", False):
        kwargs["shard_ctx"] = _single_device_ctx()
    # per-shard state: channel-sharded BatchNorm's running stats must match
    # the shard's channel count or the stat update fails to trace and the
    # choice silently falls back to analytic cost
    state0 = {k: jnp.asarray(v) for k, v in
              op.init_state_for_shapes([x.shape for x in xs]).items()} \
        if op.stateful else None

    def fwd_bwd(p, fxs):
        def loss(p_, fxs_):
            xs_ = [int_xs[i] if i in int_xs else fxs_[float_idx.index(i)]
                   for i in range(len(xs))]
            if op.stateful:
                outs, _ = op.forward_stateful(
                    p_, state0, xs_, training=True,
                    rng=rng if op.needs_rng else None)
            else:
                outs = op.forward(p_, xs_, training=True,
                                  rng=rng if op.needs_rng else None, **kwargs)
            return sum(jnp.sum(jnp.square(o.astype(jnp.float32)))
                       for o in outs)

        return jax.value_and_grad(loss, argnums=(0, 1))(p, fxs)

    float_vals = tuple(xs[i] for i in float_idx)
    return fwd_bwd, float_vals


_LOOP_COUNT: Optional[int] = None


def _loop_count() -> int:
    """In-program repetitions per timed call (defense 3 in measure_one).
    Tunneled TPU: per-call jitter is ~ms while realistic per-op costs are
    ~0.1 ms, so amortize 16x inside the program. Local backends: per-call
    overhead is ~us and CPU op costs reach ~0.5 s, where a 16x loop would
    make table builds unusably slow — 1 is both accurate and fast.
    FF_MEASURE_LOOP overrides."""
    global _LOOP_COUNT
    if _LOOP_COUNT is None:
        env = os.environ.get("FF_MEASURE_LOOP")
        if env:
            try:
                _LOOP_COUNT = max(int(env), 1)
            except ValueError as e:
                # fail the whole build loudly and immediately: a typo'd
                # knob silently defaulting would taint every table row
                raise ValueError(
                    f"FF_MEASURE_LOOP={env!r}: must be an integer") from e
        else:
            import jax

            _LOOP_COUNT = 16 if jax.default_backend() == "tpu" else 1
    return _LOOP_COUNT


_FLOOR_FN = None


def _dispatch_floor(calls: int = 3) -> float:
    """Host->device->host round trip of a trivial jitted program, min over
    `calls`, measured FRESH at each use. On the tunneled device this floor
    is ms-scale and must be subtracted from every op measurement — and it
    DRIFTS by >30x over a run (round-5: ~2 ms at session start, ~65 ms an
    hour later; a process-cached floor turned a 45-min ResNet table build
    into 142 ops of phantom `(new_latency - old_floor)/loop` cost). Within
    the ~2 s window of one signature's timed calls the drift is negligible,
    so callers sample it immediately before timing. On local CPU/TPU the
    floor is ~us and subtracting it is harmless."""
    global _FLOOR_FN
    import jax
    import jax.numpy as jnp

    if _FLOOR_FN is None:
        _FLOOR_FN = jax.jit(lambda x: x + 1)
        float(_FLOOR_FN(jnp.float32(0)))  # compile once per process
    best = float("inf")
    for _ in range(calls):
        t0 = time.perf_counter()
        float(_FLOOR_FN(jnp.float32(0)))  # scalar fetch: forces completion
        # even where block_until_ready is advisory (tunnel), matching the
        # per-iter force in measure_one
        best = min(best, time.perf_counter() - t0)
    return best


def time_scalar_program(step, *args, warmup: int = 1, iters: int = 5,
                        loop: int = 1) -> float:
    """THE timing primitive (exposed for the kernel autotuner,
    search/kernel_tune.py, and any future microbench): time a jitted
    callable that returns ONE scalar, with every tunnel defense
    measure_one documents — compile excluded, each call forced by a
    4-byte float() fetch, the null-dispatch floor sampled inside the
    same drift window and subtracted, best-of-iters so one transport
    stall cannot inflate the result. ``loop`` divides the result when
    the program repeats its body in-graph (lax.scan amortization).
    Returns seconds, clamped positive."""
    import time as _time

    float(step(*args))  # compile + first warmup
    for _ in range(warmup):
        float(step(*args))
    floor = _dispatch_floor()
    best = float("inf")
    for _ in range(iters):
        t0 = _time.perf_counter()
        float(step(*args))
        best = min(best, _time.perf_counter() - t0)
    return max((best - floor) / max(loop, 1), 1e-9)


def measure_one(op: Op, in_shapes, w_shapes, *, warmup=1, iters=5,
                timeout_compile=None,
                db_path: Optional[str] = None) -> Optional[float]:
    """Time one jitted fwd+bwd of `op` at the given per-shard shapes on the
    default device (reference: every op implements measure_operator_cost,
    model.cu:20-62 — including attention/BN/LSTM, so we must too).
    Returns seconds, or None if the op genuinely can't run standalone.

    Tunnel-robust timing (round-5 calibration findings — a 4-config
    ladder was off 10-600x in both directions until all of these were
    in; the reference's cudaEvent harness at model.cu:20-62 times on
    the device and has none of these failure modes, so a wall-clock
    harness over a tunneled device must rebuild each defense):
      1. the jitted program reduces loss AND every gradient leaf to ONE
         f32 scalar — returning grad pytrees made each call fetch
         multi-MB outputs through the tunnel, measuring transport
         bandwidth instead of compute;
      2. each call is forced by float(out) — a 4-byte fetch — because
         block_until_ready is advisory through the tunnel (same defense
         as bench.py's timed loop);
      3. the fwd+bwd body runs `loop` times inside ONE program via
         lax.scan, with each iteration's params perturbed by the
         previous gradients (a true sequential chain XLA cannot
         collapse), so per-call dispatch noise is divided by `loop` —
         ops at realistic shard sizes cost ~0.1 ms, BELOW the tunnel's
         per-call jitter, and were measuring as the clamp floor;
      4. per-call MIN with the null-dispatch floor subtracted, so one
         transport stall cannot inflate an op 100x."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    sig = _op_signature(op, in_shapes, w_shapes)
    ck = ("measure", sig)
    if ck in _SIGNATURE_CACHE:
        return _SIGNATURE_CACHE[ck]
    # cross-session tier: the persistent cost DB (when configured) serves
    # already-keyed signatures with zero compiles/timings
    from flexflow_tpu.search import cost_db

    if cost_db.resolve_path(db_path) is not None:
        dt = cost_db.get_measured(sig, path=db_path)
        if dt is not None:
            _SIGNATURE_CACHE[ck] = dt
            return dt
    loop = _loop_count()
    rs = np.random.RandomState(0)
    try:
        xs = [jnp.asarray(_rand_for(s, t.dtype, rs))
              for s, t in zip(in_shapes, op.inputs)]
        params = {spec.name: jnp.asarray(rs.randn(*s).astype(np.float32))
                  for spec, s in zip(op.weight_specs(), w_shapes)}
        rng = jax.random.PRNGKey(0)
        fwd_bwd, fxs = _build_fwd_bwd(op, params, xs, rng)

        def scalar_loop(p, fxs0):
            # Harness overhead budget per iteration, deliberately minimal
            # (it IS timed along with the op): one jnp.sum read pass per
            # gradient leaf — the cheapest consumption XLA cannot DCE or
            # slice through — plus an O(1) single-element update per
            # param/input leaf that folds the consumed scalar back in, so
            # iteration i+1 depends on iteration i's gradients (no
            # CSE/loop-invariant hoisting of identical iterations). A full
            # `p + 1e-30*g` tree_map here would bias bandwidth-bound ops:
            # 3 extra passes over an embedding table per iteration dwarfs
            # the gather/scatter being measured.
            def chain(a, s):
                flat = a.reshape(-1)
                return flat.at[0].add((1e-30 * s).astype(a.dtype)) \
                    .reshape(a.shape)

            def body(carry, _):
                p_, fxs_, acc = carry
                v, (gp, gfx) = fwd_bwd(p_, fxs_)
                consumed = v.astype(jnp.float32)
                for g in (jax.tree_util.tree_leaves(gp)
                          + jax.tree_util.tree_leaves(gfx)):
                    consumed = consumed + jnp.sum(g).astype(jnp.float32)
                p2 = jax.tree_util.tree_map(
                    lambda a: chain(a, consumed), p_)
                fxs2 = jax.tree_util.tree_map(
                    lambda a: chain(a, consumed), fxs_)
                return (p2, fxs2, acc + consumed), None
            (pN, fxsN, acc), _ = lax.scan(
                body, (p, fxs0, jnp.float32(0)), None, length=loop)
            # fold the final carries in so their whole chain is live; the
            # host fetch stays 4 bytes
            return acc + sum(jnp.sum(l.astype(jnp.float32))
                             for l in (jax.tree_util.tree_leaves(pN)
                                       + jax.tree_util.tree_leaves(fxsN)))

        step = jax.jit(scalar_loop)
        # shared primitive: compile+warmup, floor sampled inside the
        # same drift window, per-call min, scan-loop amortization
        dt = max(time_scalar_program(step, params, fxs, warmup=warmup,
                                     iters=iters, loop=loop), 1e-7)
    except Exception as e:
        _log_skip(op, e)
        return None
    _SIGNATURE_CACHE[ck] = dt
    cost_db.record_measured(sig, dt, path=db_path)  # no-op when DB off
    return dt


_SKIP_LOGGED = set()


def _log_skip(op: Op, err: Exception):
    """Surface unmeasurable ops once per op name — a silent None here means
    the search runs on analytic FLOPs for that op (fidelity gap)."""
    if op.name in _SKIP_LOGGED:
        return
    _SKIP_LOGGED.add(op.name)
    from flexflow_tpu.logger import fflogger

    fflogger.warning("cost measurement skipped for %s (%s: %s) — falling "
                     "back to analytic estimate", op.name,
                     type(err).__name__, err)


def measure_op_costs(model, mesh_shape: Dict[str, int],
                     enable_parameter_parallel: bool = True,
                     enable_attribute_parallel: bool = True,
                     iters: int = 5, verbose: bool = False,
                     time_budget_s: Optional[float] = None,
                     db_path: Optional[str] = None) -> Dict:
    """Build the `measured` table for CostModel: {(op_name, shard_out_shape):
    seconds}. Measures every distinct per-shard signature reachable by the
    search's proposal space (reference: cache keyed by op+config hash,
    simulator.cc:298-303).

    time_budget_s bounds wall-clock: signatures are measured in DESCENDING
    analytic-impact order (per-shard FLOP estimate), so an exhausted budget
    leaves only the cheapest tail to the analytic fallback — on the
    tunneled chip each fresh signature costs a scan-loop compile
    (~tens of seconds), and an unbounded branchy graph (InceptionV3:
    hundreds of signatures) cannot finish a bounded session otherwise.
    The drop is logged, never silent."""
    from flexflow_tpu.parallel.pconfig import CONTRACT, EXPERT, STAGE
    from flexflow_tpu.search.driver import legal_axis_maps

    work = []  # (est_flops, op, key, in_shapes, w_shapes)
    seen_keys = set()
    for op in model.ops:
        if isinstance(op, InputOp):
            continue
        for am in legal_axis_maps(op, mesh_shape, enable_parameter_parallel,
                                  enable_attribute_parallel):
            key = choice_key(op.name, op.outputs[0].dims, am, mesh_shape)
            if key in seen_keys:
                continue
            seen_keys.add(key)
            in_shapes = []
            for i, t in enumerate(op.inputs):
                iam = op.input_axis_map(am, i)
                in_shapes.append(shard_shape(t.dims, iam, mesh_shape))
            try:
                wp = op.weight_partition(am)
            except Exception:
                wp = {}
            w_shapes = []
            for spec in op.weight_specs():
                ws = list(spec.shape)
                pspec = wp.get(spec.name)
                if pspec is not None:
                    for d, entry in enumerate(pspec):
                        if entry is None:
                            continue
                        axes = entry if isinstance(entry, tuple) else (entry,)
                        deg = 1
                        for ax in axes:
                            deg *= mesh_shape.get(ax, 1)
                        if d < len(ws):
                            ws[d] = max(ws[d] // deg, 1)
                w_shapes.append(tuple(ws))
            full_vol = max(float(np.prod(op.outputs[0].dims)), 1.0)
            shard_vol = max(float(np.prod(
                shard_shape(op.outputs[0].dims, am, mesh_shape))), 1.0)
            # CONTRACT/STAGE axes shard the weights/inputs, not the output
            # (choice_key appends their degrees for exactly this reason) —
            # the output-volume ratio alone would price a row-parallel or
            # staged shard at the FULL op's FLOPs, overestimating
            # contracted ops in the impact ordering and in the
            # "% FLOP mass measured" budget log
            wdeg = 1
            for ax, d in (am or {}).items():
                if d in (CONTRACT, STAGE, EXPERT):
                    wdeg *= mesh_shape.get(ax, 1)
            try:
                est = float(op.flops()) * (shard_vol / full_vol) / wdeg
            except Exception:
                est = shard_vol / wdeg
            work.append((est, op, key, in_shapes, w_shapes))
    # big shards first; same-signature keys dedup through _SIGNATURE_CACHE,
    # so later duplicates are free regardless of order
    work.sort(key=lambda t: -t[0])
    measured: Dict = MeasuredTable()
    sigs = set()  # distinct signatures behind this table's keys
    n_timed = 0
    stopped_at = None
    t0 = time.perf_counter()
    for i, (est, op, key, in_shapes, w_shapes) in enumerate(work):
        if (time_budget_s is not None
                and time.perf_counter() - t0 > time_budget_s):
            stopped_at = i
            break
        dt = measure_one(op, in_shapes, w_shapes, iters=iters,
                         db_path=db_path)
        if dt is not None:
            measured[key] = dt
            sigs.add(_op_signature(op, in_shapes, w_shapes))
            n_timed += 1
            if verbose:
                print(f"[measure] {op.name} {key[1:]}: "
                      f"{dt * 1e3:.3f} ms")
    if stopped_at is not None:
        from flexflow_tpu.logger import fflogger

        # zero-cost sweep of the tail: a key whose signature twin was
        # already timed (repeated residual/branch blocks) must carry the
        # same measured cost, not an analytic one — identical computations
        # priced inconsistently in one table would skew the MCMC ranking
        n_swept = 0
        for est, op, key, in_shapes, w_shapes in work[stopped_at:]:
            sig = _op_signature(op, in_shapes, w_shapes)
            hit = _SIGNATURE_CACHE.get(("measure", sig))
            if isinstance(hit, float):
                measured[key] = hit
                sigs.add(sig)
                n_swept += 1
        est_total = sum(w[0] for w in work) or 1.0
        est_done = sum(w[0] for w in work[:stopped_at])
        fflogger.warning(
            "measure budget %.0fs exhausted after %d/%d signatures "
            "(impact-ordered: %.1f%% of estimated FLOP mass measured; "
            "%d tail keys filled from the signature cache); %d signatures "
            "fall back to analytic costs",
            time_budget_s, stopped_at, len(work),
            100.0 * est_done / est_total, n_swept,
            len(work) - stopped_at - n_swept)
    measured.signatures_timed = len(sigs)
    if verbose:
        print(f"[measure] {n_timed} entries, "
              f"{measured.signatures_timed} distinct signatures")
    return measured


def analyze_one(op: Op, in_shapes, w_shapes,
                db_path: Optional[str] = None
                ) -> Optional[Tuple[float, float]]:
    """Compile (don't run) one op's fwd+bwd and read XLA's cost analysis.
    Returns (flops, bytes_accessed) or None. The compile-only middle tier
    between the analytic roofline and real timing (SURVEY §7: cost model
    fidelity without cheap per-config microbenchmarks)."""
    import jax
    import jax.numpy as jnp

    sig = _op_signature(op, in_shapes, w_shapes)
    ck = ("analyze", sig)
    if ck in _SIGNATURE_CACHE:
        return _SIGNATURE_CACHE[ck]
    from flexflow_tpu.search import cost_db

    if cost_db.resolve_path(db_path) is not None:
        hit = cost_db.get_analyzed(sig, path=db_path)
        if hit is not None:
            _SIGNATURE_CACHE[ck] = hit
            return hit
    rs = np.random.RandomState(0)
    try:
        xs = [jnp.asarray(_rand_for(s, t.dtype, rs))
              for s, t in zip(in_shapes, op.inputs)]
        params = {spec.name: jnp.asarray(rs.randn(*s).astype(np.float32))
                  for spec, s in zip(op.weight_specs(), w_shapes)}
        rng = jax.random.PRNGKey(0)
        fwd_bwd, fxs = _build_fwd_bwd(op, params, xs, rng)
        compiled = jax.jit(fwd_bwd).lower(params, fxs).compile()
        ca = compiled.cost_analysis() or {}
        if isinstance(ca, (list, tuple)):  # some backends return a list
            ca = ca[0] if ca else {}
        out = (float(ca.get("flops", 0.0)),
               float(ca.get("bytes accessed", 0.0)))
    except Exception as e:
        _log_skip(op, e)
        return None
    _SIGNATURE_CACHE[ck] = out
    cost_db.record_analyzed(sig, out[0], out[1], path=db_path)
    return out


def analyze_op_costs(model, mesh_shape: Dict[str, int],
                     machine=None,
                     enable_parameter_parallel: bool = True,
                     enable_attribute_parallel: bool = True,
                     verbose: bool = False,
                     db_path: Optional[str] = None) -> Dict:
    """Compile-only cost table for CostModel.measured: XLA-reported
    flops/bytes per shard signature, converted to seconds by the machine
    model's roofline. ~10x cheaper than measure_op_costs (no execution,
    no warmup) and far closer to reality than per-op analytic FLOPs
    (captures XLA fusion inside the op's fwd+bwd)."""
    from flexflow_tpu.search.driver import legal_axis_maps
    from flexflow_tpu.search.machine import MachineModel

    machine = machine or MachineModel()
    table: Dict = {}
    for op in model.ops:
        if isinstance(op, InputOp):
            continue
        seen_keys = set()
        for am in legal_axis_maps(op, mesh_shape, enable_parameter_parallel,
                                  enable_attribute_parallel):
            key = choice_key(op.name, op.outputs[0].dims, am, mesh_shape)
            if key in seen_keys:
                continue
            seen_keys.add(key)
            in_shapes = []
            for i, t in enumerate(op.inputs):
                iam = op.input_axis_map(am, i)
                in_shapes.append(shard_shape(t.dims, iam, mesh_shape))
            try:
                wp = op.weight_partition(am)
            except Exception:
                wp = {}
            w_shapes = []
            for spec in op.weight_specs():
                ws = list(spec.shape)
                pspec = wp.get(spec.name)
                if pspec is not None:
                    for d, entry in enumerate(pspec):
                        if entry is None:
                            continue
                        axes = entry if isinstance(entry, tuple) else (entry,)
                        deg = 1
                        for ax in axes:
                            deg *= mesh_shape.get(ax, 1)
                        if d < len(ws):
                            ws[d] = max(ws[d] // deg, 1)
                w_shapes.append(tuple(ws))
            fb = analyze_one(op, in_shapes, w_shapes, db_path=db_path)
            if fb is not None:
                flops, nbytes = fb
                table[key] = machine.compute_time(flops, nbytes, 4)
                if verbose:
                    print(f"[analyze] {op.name} {key[1:]}: "
                          f"{flops / 1e6:.2f} MF {nbytes / 1e6:.2f} MB "
                          f"-> {table[key] * 1e6:.1f} us")
    return table
