"""MCMC strategy search driver.

Reference: FFModel::optimize (model.cc:1663-1725) — simulated annealing over
per-op ParallelConfigs: start from data-parallel (or imported), propose =
re-randomize one op's config (rewrite, model.cc:1652-1661), accept if better
else with prob exp(-alpha * diff), periodic reset-to-best every budget/100
iterations (capped 1000).

TPU version: proposals are mesh-expressible axis maps (each mesh axis is
assigned to one of the op's partitionable output dims or left replicated,
subject to divisibility) — the GSPMD-constrained SOAP space. The objective is
CostModel.iteration_time; when the C++ simulator library is built it replaces
the Python loop wholesale (flexflow_tpu/search/csim.py).
"""

from __future__ import annotations

import math
import random
from typing import Dict, Optional

from flexflow_tpu.ops.base import InputOp
from flexflow_tpu.parallel.pconfig import ParallelConfig
from flexflow_tpu.search.cost_model import AxisMap, CostModel
from flexflow_tpu.search.machine import MachineModel


def legal_axis_maps(op, mesh_shape: Dict[str, int],
                    enable_parameter_parallel: bool = True,
                    enable_attribute_parallel: bool = True):
    """All axis maps for one op: each mesh axis -> None or a partitionable
    output dim whose size divides evenly.

    The two enable flags gate the proposal distribution the way the reference
    gates it (--enable-parameter-parallel, model.cc:2023 and linear.cu:1082;
    --enable-attribute-parallel for conv spatial dims, model.cc:2027 — minus
    the upstream bug where the latter sets the former)."""
    from flexflow_tpu.ffconst import OperatorType
    from flexflow_tpu.parallel.pconfig import CONTRACT, EXPERT, STAGE

    dims = list(op.partitionable_output_dims())
    out_shape = op.outputs[0].dims
    nd = len(out_shape)
    if not enable_parameter_parallel:
        weighted = {OperatorType.OP_LINEAR, OperatorType.OP_EMBEDDING,
                    OperatorType.OP_CONV2D, OperatorType.OP_MULTIHEAD_ATTENTION,
                    OperatorType.OP_BATCHNORM}  # channel dim shards scale/bias
        if op.op_type in weighted:
            param_dim = 1 if op.op_type in (
                OperatorType.OP_CONV2D, OperatorType.OP_BATCHNORM) else nd - 1
            dims = [d for d in dims if d != param_dim]
    if not enable_attribute_parallel and op.op_type in (
            OperatorType.OP_CONV2D, OperatorType.OP_POOL2D):
        dims = [d for d in dims if d not in (2, 3)]
    # CONTRACT (row-parallel) proposals, gated like parameter parallelism
    csize = op.contract_size() if enable_parameter_parallel else None
    # EXPERT (MoE expert-parallel) proposals, same gate: sharded weights
    esize = op.expert_parallel_size() if enable_parameter_parallel else None
    axes = [a for a in mesh_shape if mesh_shape[a] > 1]
    single_axis = set(op.single_axis_dims())
    maps = [{}]
    for ax in axes:
        new_maps = []
        size = mesh_shape[ax]
        for m in maps:
            new_maps.append({**m, ax: None})
            for d in dims:
                if d in single_axis and any(d2 == d for d2 in m.values()):
                    continue  # executor takes one mesh axis max for this dim
                deg = size
                for a2, d2 in m.items():
                    if d2 == d:
                        deg *= mesh_shape[a2]
                if d < len(out_shape) and out_shape[d] % deg == 0:
                    new_maps.append({**m, ax: d})
            if csize is not None:
                deg = size
                for a2, d2 in m.items():
                    if d2 == CONTRACT:
                        deg *= mesh_shape[a2]
                if csize % deg == 0:
                    new_maps.append({**m, ax: CONTRACT})
            if esize is not None:
                deg = size
                for a2, d2 in m.items():
                    if d2 == EXPERT:
                        deg *= mesh_shape[a2]
                if esize % deg == 0:
                    new_maps.append({**m, ax: EXPERT})
            # STAGE (pipeline-parallel) proposals: one mesh axis becomes the
            # ppermute ring the op's stacked layers pipeline over. Single
            # axis only — the GPipe/1F1B loop rotates around ONE named axis
            stages = op.pipeline_stages()
            if (stages and stages % size == 0 and size > 1
                    and not any(d2 == STAGE for d2 in m.values())):
                new_maps.append({**m, ax: STAGE})
        maps = new_maps
    return maps




def hierarchical_strategy(model, mesh_shape: Dict[str, int],
                          dcn_axes: Dict[str, int],
                          enable_parameter_parallel: bool = True,
                          enable_attribute_parallel: bool = True
                          ) -> Dict[str, AxisMap]:
    """First-class ICI/DCN candidate (ROADMAP item 4): place the
    once-per-step parallelism (data, STAGE) on the DCN-spanning axes and
    keep the per-layer-collective parallelism (CONTRACT/TP) inside ICI —
    the hierarchy the two-tier machine model prices but a flat proposal
    distribution only finds by luck. Per op the candidate is chosen from
    the op's LEGAL axis maps by a placement score, so the result always
    simulates, lints, and compiles. ``optimize_strategies`` seeds the
    anneal with it (and keeps it as a competing ``best``) whenever the
    machine model declares DCN axes."""
    from flexflow_tpu.parallel.pconfig import CONTRACT, STAGE

    dcn = {ax for ax, hosts in (dcn_axes or {}).items()
           if int(hosts) > 1 and mesh_shape.get(ax, 1) > 1}
    out: Dict[str, AxisMap] = {}
    for op in model.ops:
        if isinstance(op, InputOp):
            continue
        best, best_score = {}, float("-inf")
        for am in legal_axis_maps(op, mesh_shape,
                                  enable_parameter_parallel,
                                  enable_attribute_parallel):
            score = 0.0
            for ax, d in am.items():
                if d is None:
                    continue
                if ax in dcn:
                    # batch/stage across hosts: one grad sync / boundary
                    # hop per step. Anything else (CONTRACT psum, a
                    # sharded non-batch dim's halo/reshard) pays a
                    # per-layer collective at DCN bandwidth — the
                    # anti-pattern this candidate exists to avoid.
                    score += 2.0 if d in (0, STAGE) else -4.0
                else:
                    # spend ICI on the model dimensions first
                    score += (1.5 if d == CONTRACT
                              else 1.0 if d != 0 else 0.5)
            if score > best_score:
                best, best_score = am, score
        out[op.name] = {ax: d for ax, d in best.items() if d is not None}
    return out


def data_parallel_strategy(model, mesh_shape: Dict[str, int]) -> Dict[str, AxisMap]:
    out = {}
    for op in model.ops:
        if isinstance(op, InputOp):
            continue
        am: AxisMap = {}
        if mesh_shape.get("data", 1) > 1 and op.outputs[0].num_dims > 0 \
                and op.outputs[0].dims[0] % mesh_shape["data"] == 0:
            am["data"] = 0
        out[op.name] = am
    return out


def warm_start_seed(model, mesh_shape: Dict[str, int],
                    warm_start, enable_parameter_parallel: bool = True,
                    enable_attribute_parallel: bool = True
                    ) -> Optional[Dict[str, AxisMap]]:
    """Normalize a saved strategy dict ({op_name: ParallelConfig}, e.g.
    searched at a DIFFERENT chip count) into a per-op axis-map seed legal
    on THIS mesh. Each saved map is restricted to the new mesh's axes and
    kept only when it matches one of the op's legal maps; illegal or
    missing maps fall back to data parallel. Returns None when nothing
    carries over — the elastic N->M transfer path (ISSUE 19d)."""
    if not warm_start:
        return None
    dp = data_parallel_strategy(model, mesh_shape)
    out: Dict[str, AxisMap] = {}
    carried = 0
    for op in model.ops:
        if isinstance(op, InputOp):
            continue
        pc = warm_start.get(op.name)
        am = None
        if pc is not None:
            saved = pc.axis_map if hasattr(pc, "axis_map") else pc
            if saved:
                cand = {ax: d for ax, d in saved.items()
                        if ax in mesh_shape and d is not None}
                # an empty restriction (the saved map used only axes this
                # mesh lacks) carries nothing — DP fallback, not replicated
                if cand:
                    legal = legal_axis_maps(op, mesh_shape,
                                            enable_parameter_parallel,
                                            enable_attribute_parallel)
                    norm = [{a: d for a, d in m.items() if d is not None}
                            for m in legal]
                    if cand in norm:
                        am = cand
                        carried += 1
        out[op.name] = am if am is not None else dp.get(op.name, {})
    return out if carried else None


def rank_mesh_candidates(model, candidates, strategies=None, measured=None):
    """Elastic-recovery helper (runtime/elastic.py): score candidate mesh
    shapes — factorizations of the SURVIVING device count over the saved
    axis names — by the cost model's iteration time under a re-partition
    of the saved strategy (each op keeps its saved axis map, restricted to
    the candidate's axes; ops without a usable saved map fall back to data
    parallel). Returns [(seconds, mesh_shape), ...] cheapest first; an
    infeasible candidate scores inf rather than raising, so the caller
    always gets a usable ranking. This is the "fast csim-ranked
    re-partition" path — a full re-search at the new count is
    ``research_strategies``. `measured` (a MeasuredTable, possibly
    cost-DB warm-started) prices every candidate from the same measured
    entries the original search used."""
    ops = [op for op in model.ops if not isinstance(op, InputOp)]
    scored = []
    for idx, mesh_shape in enumerate(candidates):
        try:
            cost = CostModel(model, mesh_shape, measured=measured)
            amaps: Dict[str, AxisMap] = {}
            dp = data_parallel_strategy(model, mesh_shape)
            for op in ops:
                pc = (strategies or {}).get(op.name)
                am = None
                if pc is not None and getattr(pc, "axis_map", None):
                    am = {ax: d for ax, d in pc.axis_map.items()
                          if ax in mesh_shape}
                amaps[op.name] = am if am else dp.get(op.name, {})
            scored.append((cost.iteration_time(amaps), idx, mesh_shape))
        except Exception:
            scored.append((float("inf"), idx, mesh_shape))
    scored.sort(key=lambda s: (s[0], s[1]))
    return [(t, shape) for t, _i, shape in scored]


def research_strategies(model, mesh_shape: Dict[str, int],
                        budget: int = 0,
                        warm_start=None) -> Dict[str, ParallelConfig]:
    """Re-run the strategy search at an explicit mesh — the elastic
    ``on_topology_change="research"`` entry point: the checkpointed
    strategy was searched for the OLD device count, and the paper's whole
    point is that the strategy is a searchable artifact of the machine,
    so a changed machine gets a fresh search. Budget defaults to the
    model's configured search_budget, else a small fixed sweep (the
    resumed job should start training again in seconds, not re-pay the
    original search). ``warm_start`` — the saved {op: ParallelConfig}
    from the N-chip job — seeds the M-chip anneal (ISSUE 19d), and the
    cost DB (when configured) supplies the measured entries, so the
    transfer re-measures zero already-keyed ops."""
    if budget <= 0:
        budget = getattr(model.config, "search_budget", 0) or 100
    return optimize_strategies(model, budget=budget,
                               alpha=getattr(model.config, "search_alpha",
                                             0.05),
                               mesh_shape=mesh_shape,
                               warm_start=warm_start)


def optimize_strategies(model, budget: int = 1000, alpha: float = 0.05,
                        mesh_shape: Optional[Dict[str, int]] = None,
                        machine: Optional[MachineModel] = None,
                        measured: Optional[Dict] = None,
                        seed: int = 0, verbose: bool = False,
                        use_native: bool = True,
                        warm_start=None) -> Dict[str, ParallelConfig]:
    """Run the search; returns {op_name: ParallelConfig} for the best found.
    ``warm_start`` ({op: ParallelConfig} from a previous search, possibly
    at a different chip count) becomes a competing seed after
    normalization against this mesh's legal maps."""
    mesh_shape = mesh_shape or model.config.mesh_shape
    cost = CostModel(model, mesh_shape, machine=machine, measured=measured)
    cfgflags = getattr(model, "config", None)
    epp = getattr(cfgflags, "enable_parameter_parallel", True)
    eap = getattr(cfgflags, "enable_attribute_parallel", True)
    warm = warm_start_seed(model, mesh_shape, warm_start, epp, eap)

    if use_native:
        try:
            from flexflow_tpu.search.csim import native_optimize

            return native_optimize(model, cost, mesh_shape, budget, alpha, seed,
                                   verbose=verbose, warm_start=warm)
        except (ImportError, OSError):
            pass  # fall through to the Python annealer

    rng = random.Random(seed)
    ops = [op for op in model.ops if not isinstance(op, InputOp)]
    # proposal distributions, precomputed once per op
    op_maps = {op.name: legal_axis_maps(op, mesh_shape, epp, eap) for op in ops}

    # seed candidates: flat data-parallel always; on a two-tier machine
    # also the hierarchical ICI/DCN candidate; plus the warm-start seed
    # when a previous strategy carries over. The anneal starts from the
    # CHEAPER seed, and `best` starts at that seed's cost — best-of-chain
    # can only improve on it, so the hierarchical structure survives even
    # a short or unlucky chain (the losing seed costs strictly more and
    # can never win)
    seeds = [data_parallel_strategy(model, mesh_shape)]
    if cost.machine.dcn_axes:
        seeds.append(hierarchical_strategy(model, mesh_shape,
                                           cost.machine.dcn_axes, epp, eap))
    if warm is not None:
        seeds.append(warm)
    scored = sorted(((cost.iteration_time(s), i, s)
                     for i, s in enumerate(seeds)), key=lambda t: t[:2])
    current, current_cost = dict(scored[0][2]), scored[0][0]
    best, best_cost = dict(current), current_cost
    reset_span = min(max(budget // 100, 1), 1000)  # reference model.cc:1673-1677

    for it in range(budget):
        if it % reset_span == 0 and it > 0:
            current, current_cost = dict(best), best_cost
        op = rng.choice(ops)
        proposal = dict(current)
        proposal[op.name] = rng.choice(op_maps[op.name])
        new_cost = cost.iteration_time(proposal)
        diff = new_cost - current_cost
        if diff < 0 or rng.random() < math.exp(-alpha * diff * 1e3):
            current, current_cost = proposal, new_cost
            if new_cost < best_cost:
                best, best_cost = dict(proposal), new_cost
        if verbose and it % max(budget // 10, 1) == 0:
            print(f"[search] iter {it}: current {current_cost * 1e3:.3f} ms, "
                  f"best {best_cost * 1e3:.3f} ms")

    if verbose:
        dp_cost = cost.iteration_time(data_parallel_strategy(model, mesh_shape))
        print(f"[search] done: best {best_cost * 1e3:.3f} ms vs DP "
              f"{dp_cost * 1e3:.3f} ms ({dp_cost / max(best_cost, 1e-12):.2f}x)")

    out = {}
    for op in ops:
        am = best.get(op.name, {})
        out[op.name] = ParallelConfig.from_axis_map(
            op.outputs[0].num_dims, mesh_shape, am)
    return out


def optimize_strategies_multi(model, budget: int = 1000, alpha: float = 0.05,
                              mesh_shape: Optional[Dict[str, int]] = None,
                              machine: Optional[MachineModel] = None,
                              measured: Optional[Dict] = None,
                              seed: int = 0,
                              hbm_cap_bytes: Optional[float] = None,
                              warm_start=None, verbose: bool = False,
                              use_native: bool = True
                              ) -> Dict[str, ParallelConfig]:
    """Multi-objective search (ISSUE 19c): minimize step time SUBJECT TO a
    per-chip HBM cap. Runs the time-objective anneal, then — only if the
    winning strategy's footprint exceeds ``hbm_cap_bytes`` (default: the
    machine model's per-chip capacity) — greedily buys memory relief per
    op from ``cost_model.MEM_MODES`` (gradient remat, ZeRO-1/ZeRO-3
    optimizer/weight sharding, host offload), each priced by
    ``CostModel.mem_mode_time``, picking the (op, mode) upgrade with the
    best bytes-saved-per-second-added until under cap or out of relief.
    The chosen mode lands on each ``ParallelConfig.mem_mode`` so the
    executor (PR 9's real remat/ZeRO/offload modes) runs what the search
    priced, and fflint's footprint pass audits the same accounting.

    Stashes ``model._predicted_step_time`` (base + relief overhead) and
    ``model._search_summary`` for telemetry calibration
    (``cost_db.export_calibration``) and the bench tier."""
    from flexflow_tpu.search.cost_model import MEM_MODES

    mesh_shape = mesh_shape or model.config.mesh_shape
    cost = CostModel(model, mesh_shape, machine=machine, measured=measured)
    cap = (float(hbm_cap_bytes) if hbm_cap_bytes is not None
           else float(cost.machine.hbm_bytes))

    out = optimize_strategies(model, budget=budget, alpha=alpha,
                              mesh_shape=mesh_shape, machine=machine,
                              measured=measured, seed=seed, verbose=verbose,
                              use_native=use_native, warm_start=warm_start)
    ops = {op.name: op for op in model.ops if not isinstance(op, InputOp)}
    amaps = {n: (pc.axis_map or {}) for n, pc in out.items() if n in ops}
    base_time = cost.iteration_time(amaps)

    modes: Dict[str, str] = {n: "none" for n in amaps}

    def peak_bytes() -> float:
        return sum(cost.op_mem_bytes(ops[n], amaps[n], mem_mode=modes[n])
                   for n in amaps)

    while peak_bytes() > cap:
        # the upgrade with the best bytes-saved per second-added
        pick = None  # (ratio, name, mode)
        for n in amaps:
            cur_b = cost.op_mem_bytes(ops[n], amaps[n], mem_mode=modes[n])
            cur_t = cost.mem_mode_time(ops[n], amaps[n], modes[n])
            for mode in MEM_MODES:
                if mode in ("none", modes[n]):
                    continue
                saved = cur_b - cost.op_mem_bytes(ops[n], amaps[n],
                                                  mem_mode=mode)
                if saved <= 0:
                    continue
                dt = cost.mem_mode_time(ops[n], amaps[n], mode) - cur_t
                ratio = saved / max(dt, 1e-12)
                if pick is None or ratio > pick[0]:
                    pick = (ratio, n, mode)
        if pick is None:
            break  # no relief left: return over-cap, fflint will flag it
        _, n, mode = pick
        modes[n] = mode
        if verbose:
            print(f"[search] relief: {n} -> {mode} "
                  f"(peak {peak_bytes() / 1e9:.2f} GB, cap {cap / 1e9:.2f} GB)")

    for n, mode in modes.items():
        out[n].mem_mode = mode
    overhead = sum(cost.mem_mode_time(ops[n], amaps[n], modes[n])
                   for n in amaps)
    peak = peak_bytes()
    predicted = base_time + overhead
    model._predicted_step_time = predicted
    model._search_summary = {
        "predicted_step_s": predicted,
        "base_step_s": base_time,
        "mem_overhead_s": overhead,
        "peak_hbm_bytes": peak,
        "hbm_cap_bytes": cap,
        "mem_modes": {n: m for n, m in modes.items() if m != "none"},
        "over_cap": peak > cap,
    }
    return out
