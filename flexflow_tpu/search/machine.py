"""TPU machine model for the simulator.

Replaces the reference's hardcoded GPU constants (simulator.cu:43-45:
inter-GPU 20 MB/ms, inter-node 12/numNodes, GPU<->DRAM 16) with TPU-class
numbers. Defaults are v5e-ish; override per target. Collective costs use ring
formulas over the mesh axis being reduced (scaling-book recipe) instead of
the reference's flat volume/bw (simulator.cc:548-594).

Two-tier topology (reference: intra-node 1-hop vs inter-node 3-hop transfers,
simulator.cc:252-285): `dcn_axes` maps a mesh axis name to the number of
hosts it spans. A collective over such an axis decomposes hierarchically —
ring over ICI within the host, then ring over DCN across hosts — so a
{data: 8} axis spanning 2 hosts is priced ICI(4) + DCN(2), not ICI(8).
The axis->tier mapping comes from FFConfig.dcn_mesh_shape.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional


@dataclasses.dataclass
class MachineModel:
    # per-chip compute
    peak_flops: float = 197e12  # bf16 MXU FLOP/s (v5e ~197 TFLOPs)
    peak_flops_f32: float = 49e12
    hbm_bw: float = 819e9  # bytes/s
    hbm_bytes: float = 16e9  # capacity per chip
    # interconnect
    ici_bw: float = 4.5e10  # bytes/s per link per direction (v5e ~45 GB/s)
    dcn_bw: float = 6.25e9  # bytes/s per host
    ici_latency: float = 1e-6  # seconds per hop
    dcn_latency: float = 1e-5  # seconds per hop (host NIC + switch)
    # host<->device (PCIe-class) bandwidth: prices the search's per-op
    # host-offload memory mode (cost_model.mem_mode_time, ISSUE 19)
    host_bw: float = 1.6e10  # bytes/s
    mxu_efficiency: float = 0.5  # achievable fraction of peak on real shapes
    # mesh axis name -> number of hosts the axis spans (1 = pure ICI)
    dcn_axes: Dict[str, int] = dataclasses.field(default_factory=dict)

    def compute_time(self, flops: float, bytes_moved: float,
                     dtype_bytes: int = 4) -> float:
        """Roofline: max(FLOP time, HBM time)."""
        f = self.peak_flops if dtype_bytes <= 2 else self.peak_flops_f32
        return max(flops / (f * self.mxu_efficiency),
                   bytes_moved / self.hbm_bw)

    # ---- tier decomposition -------------------------------------------------

    def _tiers(self, axis_size: int, axis_name: Optional[str]):
        """(intra_host_degree, cross_host_degree) for one mesh axis."""
        hosts = self.dcn_axes.get(axis_name, 1) if axis_name else 1
        hosts = max(1, min(hosts, axis_size))
        while hosts > 1 and axis_size % hosts != 0:
            hosts -= 1  # degenerate config: clamp to a divisor
        return axis_size // hosts, hosts

    @staticmethod
    def _ring(bytes_per_chip: float, size: int, bw: float, lat: float) -> float:
        """Bidirectional ring all-reduce over one tier."""
        if size <= 1:
            return 0.0
        return (2.0 * (size - 1) / size * bytes_per_chip / (2 * bw)
                + size * lat)

    # ---- collectives --------------------------------------------------------

    def all_reduce_time(self, bytes_per_chip: float, axis_size: int,
                        axis_name: Optional[str] = None) -> float:
        """Hierarchical ring all-reduce: ICI within the host, DCN across."""
        if axis_size <= 1:
            return 0.0
        intra, hosts = self._tiers(axis_size, axis_name)
        t = self._ring(bytes_per_chip, intra, self.ici_bw, self.ici_latency)
        t += self._ring(bytes_per_chip, hosts, self.dcn_bw, self.dcn_latency)
        return t

    def all_gather_time(self, bytes_per_chip: float, axis_size: int,
                        axis_name: Optional[str] = None) -> float:
        if axis_size <= 1:
            return 0.0
        intra, hosts = self._tiers(axis_size, axis_name)
        t = 0.0
        if intra > 1:
            t += ((intra - 1) / intra * bytes_per_chip * intra
                  / (2 * self.ici_bw) + intra * self.ici_latency)
        if hosts > 1:
            # each host gathers the other hosts' (already intra-gathered) parts
            t += ((hosts - 1) / hosts * bytes_per_chip * axis_size / hosts
                  / self.dcn_bw + hosts * self.dcn_latency)
        return t

    def reduce_scatter_time(self, bytes_per_chip: float, axis_size: int,
                            axis_name: Optional[str] = None) -> float:
        """Hierarchical ring reduce-scatter — the bucketed grad-sync
        primitive (FFConfig.overlap_grad_sync) and FSDP's gradient
        collective: the ring's reduce phase without the all-gather return
        trip, so each tier costs half an all-reduce's wire time plus the
        full per-hop latency."""
        if axis_size <= 1:
            return 0.0
        intra, hosts = self._tiers(axis_size, axis_name)
        t = 0.0
        if intra > 1:
            t += ((intra - 1) / intra * bytes_per_chip / (2 * self.ici_bw)
                  + intra * self.ici_latency)
        if hosts > 1:
            t += ((hosts - 1) / hosts * bytes_per_chip / (2 * self.dcn_bw)
                  + hosts * self.dcn_latency)
        return t

    def all_to_all_time(self, bytes_per_chip: float, axis_size: int,
                        axis_name: Optional[str] = None) -> float:
        if axis_size <= 1:
            return 0.0
        intra, hosts = self._tiers(axis_size, axis_name)
        t = 0.0
        if intra > 1:
            # each chip sends (size-1)/size of its shard, both ring dirs
            t += (bytes_per_chip * (intra - 1) / intra / (2 * self.ici_bw)
                  + intra * self.ici_latency)
        if hosts > 1:
            t += (bytes_per_chip * (hosts - 1) / hosts / self.dcn_bw
                  + hosts * self.dcn_latency)
        return t

    def p2p_time(self, nbytes: float, cross_host: bool = False) -> float:
        if cross_host:
            return nbytes / self.dcn_bw + self.dcn_latency
        return nbytes / self.ici_bw + self.ici_latency
