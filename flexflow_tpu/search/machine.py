"""TPU machine model for the simulator.

Replaces the reference's hardcoded GPU constants (simulator.cu:43-45:
inter-GPU 20 MB/ms, inter-node 12/numNodes, GPU<->DRAM 16) with TPU-class
numbers. Defaults are v5e-ish; override per target. Collective costs use ring
formulas over the mesh axis being reduced (scaling-book recipe) instead of
the reference's flat volume/bw (simulator.cc:548-594).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class MachineModel:
    # per-chip compute
    peak_flops: float = 197e12  # bf16 MXU FLOP/s (v5e ~197 TFLOPs)
    peak_flops_f32: float = 49e12
    hbm_bw: float = 819e9  # bytes/s
    hbm_bytes: float = 16e9  # capacity per chip
    # interconnect
    ici_bw: float = 4.5e10  # bytes/s per link per direction (v5e ~45 GB/s)
    dcn_bw: float = 6.25e9  # bytes/s per host
    ici_latency: float = 1e-6  # seconds per hop
    mxu_efficiency: float = 0.5  # achievable fraction of peak on real shapes

    def compute_time(self, flops: float, bytes_moved: float,
                     dtype_bytes: int = 4) -> float:
        """Roofline: max(FLOP time, HBM time)."""
        f = self.peak_flops if dtype_bytes <= 2 else self.peak_flops_f32
        return max(flops / (f * self.mxu_efficiency),
                   bytes_moved / self.hbm_bw)

    def all_reduce_time(self, bytes_per_chip: float, axis_size: int) -> float:
        """Bidirectional ring all-reduce over one mesh axis."""
        if axis_size <= 1:
            return 0.0
        ring = 2.0 * (axis_size - 1) / axis_size
        return ring * bytes_per_chip / (2 * self.ici_bw) \
            + axis_size * self.ici_latency

    def all_gather_time(self, bytes_per_chip: float, axis_size: int) -> float:
        if axis_size <= 1:
            return 0.0
        return (axis_size - 1) / axis_size * bytes_per_chip * axis_size \
            / (2 * self.ici_bw) + axis_size * self.ici_latency

    def all_to_all_time(self, bytes_per_chip: float, axis_size: int) -> float:
        if axis_size <= 1:
            return 0.0
        # each chip sends (size-1)/size of its shard, split across both ring dirs
        return bytes_per_chip * (axis_size - 1) / axis_size / (2 * self.ici_bw) \
            + axis_size * self.ici_latency

    def p2p_time(self, nbytes: float) -> float:
        return nbytes / self.ici_bw + self.ici_latency
