// Native search core: event-driven task-graph simulator + MCMC annealer.
//
// The TPU re-design of the reference's C++ search engine
// (src/runtime/simulator.cc:93-621 TaskManager/SimTask event simulation and
// src/runtime/model.cc:1652-1725 FFModel::optimize MCMC loop).
//
// Division of labor: Python (flexflow_tpu/search/cost_model.py) knows the
// machine model and computes COST TABLES —
//   * per op, per legal axis-map choice: compute seconds + gradient-sync
//     comm seconds,
//   * per graph edge, per (producer choice, consumer choice) pair:
//     resharding comm seconds.
// This library evaluates a strategy's iteration time with a two-resource
// (compute stream, ICI stream) list schedule — capturing compute/comm
// overlap the way the reference's per-device timelines did — and runs the
// Metropolis annealer over choice vectors (reference accept rule:
// exp(-alpha*diff), reset-to-best every budget/100 iters).
//
// Exposed via a C ABI for ctypes (no pybind11 in this environment).

#include <cmath>
#include <cstdint>
#include <cstring>
#include <random>
#include <vector>

extern "C" {

// Graph + cost-table layout (all arrays owned by caller):
//   num_ops, num_edges
//   op_cost_offsets[num_ops+1]        : prefix offsets into op cost tables
//   op_compute_costs[...]             : compute seconds per (op, choice)
//   op_sync_costs[...]                : grad-sync comm seconds per (op, choice)
//   edge_src[num_edges], edge_dst[num_edges] : op indices (topological: src<dst)
//   edge_cost_offsets[num_edges+1]    : prefix offsets into edge_costs
//   edge_costs[...]                   : row-major [src_choice][dst_choice]
//   choices[num_ops]                  : the strategy being evaluated
// One list-schedule implementation serves both entry points: timeline
// pointers may be null (the hot MCMC path), or caller buffers for task-graph
// export (reference: the simulator's DotFile dump with per-task times,
// simulator.h:78-131 + --taskgraph). comm times are per edge; sync times per
// op (0-width when no sync).
static double schedule(int num_ops, int num_edges,
                       const int64_t* op_cost_offsets,
                       const double* op_compute_costs,
                       const double* op_sync_costs,
                       const int32_t* edge_src, const int32_t* edge_dst,
                       const int64_t* edge_cost_offsets,
                       const double* edge_costs,
                       const int32_t* choices,
                       double* compute_start, double* compute_finish,
                       double* comm_start, double* comm_finish,
                       double* sync_start, double* sync_finish) {
  // finish time of each op's compute; streams advance monotonically
  std::vector<double> finish(num_ops, 0.0);
  std::vector<double> ready(num_ops, 0.0);
  double compute_free = 0.0, comm_free = 0.0;
  int e = 0;
  for (int i = 0; i < num_ops; ++i) {
    // schedule all incoming comm (edges are sorted by dst, topological)
    while (e < num_edges && edge_dst[e] == i) {
      int s = edge_src[e];
      int64_t off = edge_cost_offsets[e];
      int n_dst = (int)((edge_cost_offsets[e + 1] - off) /
                        (op_cost_offsets[s + 1] - op_cost_offsets[s]));
      double c = edge_costs[off + (int64_t)choices[s] * n_dst + choices[i]];
      if (c > 0.0) {
        double start = std::max(finish[s], comm_free);
        if (comm_start) { comm_start[e] = start; }
        comm_free = start + c;
        if (comm_finish) { comm_finish[e] = comm_free; }
        ready[i] = std::max(ready[i], comm_free);
      } else {
        if (comm_start) { comm_start[e] = comm_finish[e] = finish[s]; }
        ready[i] = std::max(ready[i], finish[s]);
      }
      ++e;
    }
    int64_t off = op_cost_offsets[i];
    double comp = op_compute_costs[off + choices[i]];
    double start = std::max(ready[i], compute_free);
    if (compute_start) { compute_start[i] = start; }
    finish[i] = start + comp;
    if (compute_finish) { compute_finish[i] = finish[i]; }
    compute_free = finish[i];
    // gradient sync rides the comm stream after this op's compute
    double sync = op_sync_costs[off + choices[i]];
    if (sync > 0.0) {
      double cstart = std::max(finish[i], comm_free);
      if (sync_start) { sync_start[i] = cstart; }
      comm_free = cstart + sync;
      if (sync_finish) { sync_finish[i] = comm_free; }
    } else if (sync_start) {
      sync_start[i] = sync_finish[i] = finish[i];
    }
  }
  return std::max(compute_free, comm_free);
}

double ff_simulate(int num_ops, int num_edges,
                   const int64_t* op_cost_offsets,
                   const double* op_compute_costs,
                   const double* op_sync_costs,
                   const int32_t* edge_src, const int32_t* edge_dst,
                   const int64_t* edge_cost_offsets,
                   const double* edge_costs,
                   const int32_t* choices) {
  return schedule(num_ops, num_edges, op_cost_offsets, op_compute_costs,
                  op_sync_costs, edge_src, edge_dst, edge_cost_offsets,
                  edge_costs, choices, nullptr, nullptr, nullptr, nullptr,
                  nullptr, nullptr);
}

double ff_simulate_timeline(int num_ops, int num_edges,
                            const int64_t* op_cost_offsets,
                            const double* op_compute_costs,
                            const double* op_sync_costs,
                            const int32_t* edge_src, const int32_t* edge_dst,
                            const int64_t* edge_cost_offsets,
                            const double* edge_costs,
                            const int32_t* choices,
                            double* compute_start, double* compute_finish,
                            double* comm_start, double* comm_finish,
                            double* sync_start, double* sync_finish) {
  return schedule(num_ops, num_edges, op_cost_offsets, op_compute_costs,
                  op_sync_costs, edge_src, edge_dst, edge_cost_offsets,
                  edge_costs, choices, compute_start, compute_finish,
                  comm_start, comm_finish, sync_start, sync_finish);
}

// MCMC simulated annealing (reference: model.cc:1663-1725).
// Returns the best cost; best_choices filled with the best strategy.
double ff_mcmc(int num_ops, int num_edges,
               const int64_t* op_cost_offsets,
               const double* op_compute_costs,
               const double* op_sync_costs,
               const int32_t* edge_src, const int32_t* edge_dst,
               const int64_t* edge_cost_offsets,
               const double* edge_costs,
               const int32_t* init_choices,
               int budget, double alpha, uint64_t seed,
               int32_t* best_choices) {
  std::mt19937_64 rng(seed);
  std::uniform_real_distribution<double> unif(0.0, 1.0);

  std::vector<int32_t> current(init_choices, init_choices + num_ops);
  auto eval = [&](const std::vector<int32_t>& c) {
    return ff_simulate(num_ops, num_edges, op_cost_offsets, op_compute_costs,
                       op_sync_costs, edge_src, edge_dst, edge_cost_offsets,
                       edge_costs, c.data());
  };
  double cur_cost = eval(current);
  std::vector<int32_t> best = current;
  double best_cost = cur_cost;

  int reset_span = budget / 100;
  if (reset_span < 1) reset_span = 1;
  if (reset_span > 1000) reset_span = 1000;  // reference model.cc:1673-1677

  for (int it = 0; it < budget; ++it) {
    if (it > 0 && it % reset_span == 0) {
      current = best;
      cur_cost = best_cost;
    }
    int op = (int)(rng() % (uint64_t)num_ops);
    int n_choices = (int)(op_cost_offsets[op + 1] - op_cost_offsets[op]);
    if (n_choices <= 1) continue;
    int old_choice = current[op];
    int new_choice = (int)(rng() % (uint64_t)n_choices);
    if (new_choice == old_choice) continue;
    current[op] = new_choice;
    double new_cost = eval(current);
    double diff = new_cost - cur_cost;
    // reference accepts with prob exp(-alpha*diff) on simulated ms; our
    // costs are seconds, so scale to ms for comparable alpha semantics
    if (diff < 0.0 || unif(rng) < std::exp(-alpha * diff * 1e3)) {
      cur_cost = new_cost;
      if (new_cost < best_cost) {
        best_cost = new_cost;
        best = current;
      }
    } else {
      current[op] = old_choice;
    }
  }
  std::memcpy(best_choices, best.data(), sizeof(int32_t) * num_ops);
  return best_cost;
}

}  // extern "C"
