// Native search core: event-driven task-graph simulator + MCMC annealer.
//
// The TPU re-design of the reference's C++ search engine
// (src/runtime/simulator.cc:93-621 TaskManager/SimTask per-device event
// simulation and src/runtime/model.cc:1652-1725 FFModel::optimize MCMC loop).
//
// Division of labor: Python (flexflow_tpu/search/cost_model.py) knows the
// machine model and computes COST TABLES —
//   * per op, per legal axis-map choice: compute seconds, gradient-sync comm
//     seconds, per-device memory bytes, and the number of devices spanned,
//   * per graph edge, per (producer choice, consumer choice) pair:
//     resharding comm seconds (GSPMD collectives within a device block).
// This library evaluates a strategy — a (choice, placement) pair per op —
// with PER-DEVICE compute and comm timelines (reference
// simulator.cc:325-621): ops placed on disjoint device blocks overlap, ops
// sharing devices serialize, per-device HBM footprints accumulate and
// over-capacity is penalized at 1 ms/MB (reference simulator.cc:595-620),
// and a block-start mismatch between producer and consumer adds a p2p
// placement transfer (reference's inter-device task edges,
// simulator.cc:252-285). The MCMC proposes both axis-map choices and
// contiguous aligned device blocks (reference model.cc:496-525 random
// contiguous device ranges).
//
// Exposed via a C ABI for ctypes (no pybind11 in this environment).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <random>
#include <vector>

namespace {

struct Tables {
  int num_ops, num_edges, num_devices;
  const int64_t* op_cost_offsets;   // [num_ops+1]
  const double* op_compute_costs;   // per (op, choice)
  const double* op_sync_costs;      // per (op, choice)
  const double* op_mem_bytes;       // per (op, choice): per-device HBM bytes
  const int32_t* op_ndev;           // per (op, choice): devices spanned
  const int32_t* edge_src;          // [num_edges], sorted by dst, src < dst
  const int32_t* edge_dst;
  const int64_t* edge_cost_offsets; // [num_edges+1]
  const double* edge_costs;         // row-major [src_choice][dst_choice]
  const double* edge_bytes;         // [num_edges]: full tensor bytes
  double hbm_bytes, ici_bw, ici_latency, mem_penalty_per_byte;
};

struct Timeline {
  double *compute_start, *compute_finish;   // [num_ops] or null
  double *comm_start, *comm_finish;         // [num_edges] or null
  double *sync_start, *sync_finish;         // [num_ops] or null
};

// Clamp a desired block start so [place, place+ndev) fits and is aligned to
// the block grid (the GSPMD-expressible sub-meshes: ndev | num_devices and
// place a multiple of ndev; otherwise everything collapses to block 0).
int align_place(int place, int ndev, int num_devices) {
  if (ndev <= 0 || ndev >= num_devices || num_devices % ndev != 0) return 0;
  if (place < 0) place = 0;
  if (place > num_devices - ndev) place = num_devices - ndev;
  return place - place % ndev;
}

double schedule(const Tables& T, const int32_t* choices,
                const int32_t* places, const Timeline* tl) {
  const int D = T.num_devices;
  std::vector<double> finish(T.num_ops, 0.0);
  std::vector<double> dev_compute(D, 0.0);  // per-device compute stream
  std::vector<double> dev_comm(D, 0.0);     // per-device comm (ICI) stream
  // Gradient all-reduces ride a SEPARATE per-device stream: on TPU the
  // XLA latency-hiding scheduler overlaps grad sync with backward compute,
  // and the reference likewise prices NCCL cost post-hoc rather than
  // interleaving it with forward transfers (simulator.cc:548-594).
  // Interleaving syncs into dev_comm would stall every forward resharding
  // edge behind queued grad traffic and poison the search landscape.
  std::vector<double> dev_sync(D, 0.0);     // per-device grad-sync stream
  std::vector<double> dev_mem(D, 0.0);      // per-device HBM footprint

  auto block = [&](int op) {
    int64_t off = T.op_cost_offsets[op];
    int n = T.op_ndev ? T.op_ndev[off + choices[op]] : D;
    if (n <= 0) n = 1;
    if (n > D) n = D;
    int p = places ? align_place(places[op], n, D) : 0;
    return std::pair<int, int>(p, n);
  };

  int e = 0;
  for (int i = 0; i < T.num_ops; ++i) {
    auto [pi, ni] = block(i);
    double ready = 0.0;
    // incoming comm (edges sorted by dst, topological)
    while (e < T.num_edges && T.edge_dst[e] == i) {
      int s = T.edge_src[e];
      auto [ps, ns] = block(s);
      int64_t off = T.edge_cost_offsets[e];
      int n_dst = (int)((T.edge_cost_offsets[e + 1] - off) /
                        (T.op_cost_offsets[s + 1] - T.op_cost_offsets[s]));
      double c = T.edge_costs[off + (int64_t)choices[s] * n_dst + choices[i]];
      if (T.edge_bytes && ps != pi) {
        // producer and consumer live on different device blocks: per-shard
        // p2p push over ICI (reference inter-device transfer tasks)
        c += T.edge_bytes[e] / std::max(ns, 1) / T.ici_bw + T.ici_latency;
      }
      if (c > 0.0) {
        // the transfer occupies the comm streams of both blocks
        double start = finish[s];
        for (int d = ps; d < ps + ns; ++d) start = std::max(start, dev_comm[d]);
        for (int d = pi; d < pi + ni; ++d) start = std::max(start, dev_comm[d]);
        double end = start + c;
        for (int d = ps; d < ps + ns; ++d) dev_comm[d] = end;
        for (int d = pi; d < pi + ni; ++d) dev_comm[d] = end;
        if (tl && tl->comm_start) { tl->comm_start[e] = start; tl->comm_finish[e] = end; }
        ready = std::max(ready, end);
      } else {
        if (tl && tl->comm_start) { tl->comm_start[e] = tl->comm_finish[e] = finish[s]; }
        ready = std::max(ready, finish[s]);
      }
      ++e;
    }
    int64_t off = T.op_cost_offsets[i];
    double comp = T.op_compute_costs[off + choices[i]];
    double start = ready;
    for (int d = pi; d < pi + ni; ++d) start = std::max(start, dev_compute[d]);
    double end = start + comp;
    for (int d = pi; d < pi + ni; ++d) dev_compute[d] = end;
    finish[i] = end;
    if (tl && tl->compute_start) { tl->compute_start[i] = start; tl->compute_finish[i] = end; }
    // gradient sync rides this block's sync streams after the compute
    double sync = T.op_sync_costs[off + choices[i]];
    if (sync > 0.0) {
      double cstart = end;
      for (int d = pi; d < pi + ni; ++d) cstart = std::max(cstart, dev_sync[d]);
      double cend = cstart + sync;
      for (int d = pi; d < pi + ni; ++d) dev_sync[d] = cend;
      if (tl && tl->sync_start) { tl->sync_start[i] = cstart; tl->sync_finish[i] = cend; }
    } else if (tl && tl->sync_start) {
      tl->sync_start[i] = tl->sync_finish[i] = end;
    }
    if (T.op_mem_bytes) {
      double m = T.op_mem_bytes[off + choices[i]];
      for (int d = pi; d < pi + ni; ++d) dev_mem[d] += m;
    }
  }
  double total = 0.0;
  for (int d = 0; d < D; ++d)
    total = std::max(total, std::max(dev_sync[d],
                     std::max(dev_compute[d], dev_comm[d])));
  // per-device over-HBM penalty (reference simulator.cc:595-620: 1 ms/MB)
  if (T.op_mem_bytes && T.hbm_bytes > 0.0) {
    for (int d = 0; d < D; ++d) {
      double over = dev_mem[d] - T.hbm_bytes;
      if (over > 0.0) total += over * T.mem_penalty_per_byte;
    }
  }
  return total;
}

Tables make_tables(int num_ops, int num_edges, int num_devices,
                   const int64_t* op_cost_offsets,
                   const double* op_compute_costs,
                   const double* op_sync_costs,
                   const double* op_mem_bytes,
                   const int32_t* op_ndev,
                   const int32_t* edge_src, const int32_t* edge_dst,
                   const int64_t* edge_cost_offsets,
                   const double* edge_costs,
                   const double* edge_bytes,
                   double hbm_bytes, double ici_bw, double ici_latency,
                   double mem_penalty_per_byte) {
  Tables T;
  T.num_ops = num_ops; T.num_edges = num_edges;
  T.num_devices = num_devices > 0 ? num_devices : 1;
  T.op_cost_offsets = op_cost_offsets;
  T.op_compute_costs = op_compute_costs;
  T.op_sync_costs = op_sync_costs;
  T.op_mem_bytes = op_mem_bytes;
  T.op_ndev = op_ndev;
  T.edge_src = edge_src; T.edge_dst = edge_dst;
  T.edge_cost_offsets = edge_cost_offsets;
  T.edge_costs = edge_costs;
  T.edge_bytes = edge_bytes;
  T.hbm_bytes = hbm_bytes;
  T.ici_bw = ici_bw > 0 ? ici_bw : 4.5e10;
  T.ici_latency = ici_latency;
  // 1 ms per MB over capacity when the caller passes 0 (reference
  // simulator.cc:612-617); cost_model.MEM_PENALTY_PER_BYTE feeds the real
  // value so the Python objective and this scheduler cannot drift
  T.mem_penalty_per_byte = mem_penalty_per_byte > 0.0 ? mem_penalty_per_byte
                                                      : 1e-3 / 1e6;
  return T;
}

}  // namespace

extern "C" {

double ff_simulate(int num_ops, int num_edges, int num_devices,
                   const int64_t* op_cost_offsets,
                   const double* op_compute_costs,
                   const double* op_sync_costs,
                   const double* op_mem_bytes,
                   const int32_t* op_ndev,
                   const int32_t* edge_src, const int32_t* edge_dst,
                   const int64_t* edge_cost_offsets,
                   const double* edge_costs,
                   const double* edge_bytes,
                   const int32_t* choices, const int32_t* places,
                   double hbm_bytes, double ici_bw, double ici_latency,
                   double mem_penalty_per_byte) {
  Tables T = make_tables(num_ops, num_edges, num_devices, op_cost_offsets,
                         op_compute_costs, op_sync_costs, op_mem_bytes,
                         op_ndev, edge_src, edge_dst, edge_cost_offsets,
                         edge_costs, edge_bytes, hbm_bytes, ici_bw,
                         ici_latency, mem_penalty_per_byte);
  return schedule(T, choices, places, nullptr);
}

double ff_simulate_timeline(int num_ops, int num_edges, int num_devices,
                            const int64_t* op_cost_offsets,
                            const double* op_compute_costs,
                            const double* op_sync_costs,
                            const double* op_mem_bytes,
                            const int32_t* op_ndev,
                            const int32_t* edge_src, const int32_t* edge_dst,
                            const int64_t* edge_cost_offsets,
                            const double* edge_costs,
                            const double* edge_bytes,
                            const int32_t* choices, const int32_t* places,
                            double hbm_bytes, double ici_bw,
                            double ici_latency, double mem_penalty_per_byte,
                            double* compute_start, double* compute_finish,
                            double* comm_start, double* comm_finish,
                            double* sync_start, double* sync_finish) {
  Tables T = make_tables(num_ops, num_edges, num_devices, op_cost_offsets,
                         op_compute_costs, op_sync_costs, op_mem_bytes,
                         op_ndev, edge_src, edge_dst, edge_cost_offsets,
                         edge_costs, edge_bytes, hbm_bytes, ici_bw,
                         ici_latency, mem_penalty_per_byte);
  Timeline tl{compute_start, compute_finish, comm_start, comm_finish,
              sync_start, sync_finish};
  return schedule(T, choices, places, &tl);
}

// MCMC simulated annealing (reference: model.cc:1663-1725). Proposals
// re-randomize one op's axis-map choice or its device block (reference
// rewrite model.cc:1652-1661 + random contiguous ranges model.cc:496-525).
// Returns the best cost; best_choices/best_places filled with the best
// strategy.
double ff_mcmc(int num_ops, int num_edges, int num_devices,
               const int64_t* op_cost_offsets,
               const double* op_compute_costs,
               const double* op_sync_costs,
               const double* op_mem_bytes,
               const int32_t* op_ndev,
               const int32_t* edge_src, const int32_t* edge_dst,
               const int64_t* edge_cost_offsets,
               const double* edge_costs,
               const double* edge_bytes,
               const int32_t* init_choices, const int32_t* init_places,
               double hbm_bytes, double ici_bw, double ici_latency,
               double mem_penalty_per_byte,
               int allow_place,  // 0: never propose device-block moves
                                 // (FSDP shards weights over the FULL
                                 // mesh, incompatible with sub-meshes)
               int budget, double alpha, uint64_t seed,
               int32_t* best_choices, int32_t* best_places) {
  Tables T = make_tables(num_ops, num_edges, num_devices, op_cost_offsets,
                         op_compute_costs, op_sync_costs, op_mem_bytes,
                         op_ndev, edge_src, edge_dst, edge_cost_offsets,
                         edge_costs, edge_bytes, hbm_bytes, ici_bw,
                         ici_latency, mem_penalty_per_byte);
  const int D = T.num_devices;
  std::mt19937_64 rng(seed);
  std::uniform_real_distribution<double> unif(0.0, 1.0);

  std::vector<int32_t> cur_c(init_choices, init_choices + num_ops);
  std::vector<int32_t> cur_p(num_ops, 0);
  if (init_places) cur_p.assign(init_places, init_places + num_ops);

  auto ndev_of = [&](int op, int choice) {
    int n = op_ndev ? op_ndev[op_cost_offsets[op] + choice] : D;
    return std::max(1, std::min(n, D));
  };
  auto eval = [&]() { return schedule(T, cur_c.data(), cur_p.data(), nullptr); };

  double cur_cost = eval();
  std::vector<int32_t> best_c = cur_c, best_p = cur_p;
  double best_cost = cur_cost;

  int reset_span = budget / 100;
  if (reset_span < 1) reset_span = 1;
  if (reset_span > 1000) reset_span = 1000;  // reference model.cc:1673-1677

  for (int it = 0; it < budget; ++it) {
    if (it > 0 && it % reset_span == 0) {
      cur_c = best_c; cur_p = best_p;
      cur_cost = best_cost;
    }
    int op = (int)(rng() % (uint64_t)num_ops);
    int n_choices = (int)(op_cost_offsets[op + 1] - op_cost_offsets[op]);
    int old_c = cur_c[op], old_p = cur_p[op];
    // half the proposals move the device block, half the axis map
    // (reference re-randomizes both at once; splitting mixes faster)
    bool move_place = allow_place && (rng() & 1) != 0;
    int ndev = ndev_of(op, old_c);
    int nblocks = (ndev < D && D % ndev == 0) ? D / ndev : 1;
    if (move_place && nblocks > 1) {
      cur_p[op] = (int)(rng() % (uint64_t)nblocks) * ndev;
      if (cur_p[op] == old_p) continue;
    } else {
      if (n_choices <= 1) continue;
      int new_c = (int)(rng() % (uint64_t)n_choices);
      if (new_c == old_c) continue;
      cur_c[op] = new_c;
      cur_p[op] = align_place(old_p, ndev_of(op, new_c), D);
    }
    double new_cost = eval();
    double diff = new_cost - cur_cost;
    // reference accepts with prob exp(-alpha*diff) on simulated ms; our
    // costs are seconds, so scale to ms for comparable alpha semantics
    if (diff < 0.0 || unif(rng) < std::exp(-alpha * diff * 1e3)) {
      cur_cost = new_cost;
      if (new_cost < best_cost) {
        best_cost = new_cost;
        best_c = cur_c; best_p = cur_p;
      }
    } else {
      cur_c[op] = old_c; cur_p[op] = old_p;
    }
  }
  std::memcpy(best_choices, best_c.data(), sizeof(int32_t) * num_ops);
  if (best_places) std::memcpy(best_places, best_p.data(),
                               sizeof(int32_t) * num_ops);
  return best_cost;
}

}  // extern "C"
