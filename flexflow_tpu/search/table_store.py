"""Shared atomic-JSON table persistence for measured-cost stores.

One implementation of the on-disk discipline that `search/kernel_tune.py`
proved out and the op-cost database (`search/cost_db.py`) now shares —
the ISSUE 19 satellite that forbids a second divergent persistence stack:

  * atomic publish: write ``<path>.tmp`` then ``os.replace`` so a reader
    (or a crash mid-write) can never observe a torn table;
  * in-process cache keyed by the file's ``(mtime_ns, size)`` so an
    out-of-process update (another worker's re-tune / re-measure) is
    picked up by the NEXT lookup without a restart, while warm lookups
    never stat() twice for the same generation;
  * one environment key — ``measure._env_signature()``'s
    (backend, device kind, jax version) — stamped into every persisted
    key, so a timing taken on one backend/jax build can never be served
    on another: it must MISS, not mislead.

File format (shared by every consumer)::

    {"version": 1, "entries": {"<key>": {...}, ...}}
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional, Tuple

# {path: (file_stat_sig, entries)} — shared by every table on disk; keys
# are file paths so distinct tables (kernel_tune.json, cost_db.json)
# never collide. kernel_tune aliases this as its legacy `_TABLES` name.
_CACHE: Dict[str, Tuple] = {}


def stat_sig(path: str):
    """(mtime_ns, size) of the file, or None when absent — the cache
    invalidation token: any out-of-process rewrite changes it."""
    try:
        st = os.stat(path)
        return (st.st_mtime_ns, st.st_size)
    except OSError:
        return None


def env_key() -> str:
    """Device-identity half of every persisted key: backend, chip kind,
    jax version — measure._env_signature, the ONE environment probe all
    persisted cost keys share. A version bump (jax or the libtpu it
    pins) changes codegen, so old entries stop matching new programs by
    key mismatch instead of silently serving stale numbers."""
    from flexflow_tpu.search.measure import _env_signature

    backend, kind, version = _env_signature()
    return f"{backend}|{kind}|jax-{version}"


def load(path: str, reload: bool = False) -> Dict:
    """Entries dict for `path`, cached in-process and invalidated by the
    file's (mtime, size) — a table written after this process's first
    lookup is served on the next call, never shadowed by a cached empty
    read. ``reload=True`` forces the re-read regardless."""
    sig = stat_sig(path)
    if not reload and path in _CACHE and _CACHE[path][0] == sig:
        return _CACHE[path][1]
    entries: Dict = {}
    try:
        with open(path) as f:
            data = json.load(f)
        if isinstance(data, dict):
            entries = data.get("entries", {})
    except (OSError, ValueError):
        entries = {}
    _CACHE[path] = (sig, entries)
    return entries


def publish(path: str, entries: Dict) -> None:
    """Atomic tmp+rename write (the checkpoint.py discipline) and cache
    refresh: after this returns, every reader — this process or another
    — sees either the old complete table or the new complete table."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"version": 1, "entries": entries}, f, indent=1,
                  sort_keys=True)
    os.replace(tmp, path)
    _CACHE[path] = (stat_sig(path), entries)


def clear_cache() -> None:
    """Drop every in-process cached table (test fixtures simulating a
    fresh process). On-disk state is untouched."""
    _CACHE.clear()
