"""Multi-host launch driver.

Reference: python/flexflow.py — wraps `mpirun -npernode 1 flexflow_python
-ll:py 1 -ll:gpu N -ll:fsize ...` (flexflow.py:24-99). TPU analog: multi-
controller JAX. On a TPU pod each host runs the SAME script;
`jax.distributed.initialize()` wires the hosts; GSPMD handles cross-host
(DCN) collectives. This driver:

  * single host: exec the script with the requested device env
  * multi host (--coordinator given or TPU pod env detected): call
    jax.distributed.initialize(...) then exec

Usage: python -m flexflow_tpu.launcher script.py [--num-processes N]
       [--process-id I] [--coordinator host:port] [-- script args...]
"""

from __future__ import annotations

import argparse
import os
import runpy
import sys


def _retried_initialize(jax):
    """jax.distributed.initialize under retry/backoff: on a preempted pool
    the coordinator host often comes back seconds after the workers, and
    the raw call fails once and kills the whole relaunch. Attempts/delay
    tunable for restart loops via FF_INIT_ATTEMPTS / FF_INIT_DELAY_S."""
    from flexflow_tpu.runtime.resilience import retry

    return retry(attempts=int(os.environ.get("FF_INIT_ATTEMPTS", "3")),
                 base_delay=float(os.environ.get("FF_INIT_DELAY_S", "2")),
                 max_delay=30.0, retryable=(RuntimeError, OSError),
                 name="jax.distributed.initialize")(
        jax.distributed.initialize)


def main(argv=None):
    p = argparse.ArgumentParser(prog="flexflow_tpu.launcher")
    p.add_argument("script")
    p.add_argument("--num-processes", type=int, default=None,
                   help="total controller processes (hosts)")
    p.add_argument("--process-id", type=int, default=None)
    p.add_argument("--coordinator", type=str, default=None,
                   help="host:port of process 0")
    p.add_argument("--cpu-devices", type=int, default=None,
                   help="emulate N CPU devices (testing)")
    args, rest = p.parse_known_args(argv)
    if rest and rest[0] == "--":
        rest = rest[1:]

    if args.cpu_devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.cpu_devices}")
        import jax

        from flexflow_tpu._env import force_cpu_devices

        # _env handles the jax-version skew: jax_num_cpu_devices where the
        # build has it, the XLA_FLAGS device-count fallback otherwise
        # (0.4.37) — an unguarded config.update here killed every worker
        # at startup on the older builds
        force_cpu_devices(args.cpu_devices)
        if args.num_processes and args.num_processes > 1:
            # CPU cross-process collectives need an explicit backend
            jax.config.update("jax_cpu_collectives_implementation", "gloo")

    # multi-host pod detection: require an actual multi-worker signal (a
    # single-chip dev box can still carry TPU env vars)
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    pod_env = (not args.cpu_devices) and (
        "," in hostnames or bool(os.environ.get("MEGASCALE_COORDINATOR_ADDRESS")))
    if args.coordinator or (args.num_processes and args.num_processes > 1):
        if args.coordinator and (args.num_processes is None
                                 or args.process_id is None) and not pod_env:
            p.error("--coordinator requires --num-processes and --process-id "
                    "(they cannot be auto-detected outside TPU/SLURM "
                    "environments)")
        import jax

        _retried_initialize(jax)(
            coordinator_address=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id)
    elif pod_env:
        # TPU pod: every host runs this same script; initialize with full
        # auto-detection (docstring's 'TPU pod env detected' path)
        import jax

        _retried_initialize(jax)()

    cache_dir = os.environ.get("FF_COMPILATION_CACHE_DIR", "")
    if cache_dir:
        # persistent compilation cache for the launched script: enabled
        # HERE, before the script's first trace, so even programs built
        # ahead of FFModel.compile() (warmup probes, custom jits) hit it
        from flexflow_tpu._env import enable_compilation_cache

        enable_compilation_cache(cache_dir)

    sys.argv = [args.script] + rest
    runpy.run_path(args.script, run_name="__main__")


if __name__ == "__main__":
    main()
