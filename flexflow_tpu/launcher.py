"""Multi-host launch driver.

Reference: python/flexflow.py — wraps `mpirun -npernode 1 flexflow_python
-ll:py 1 -ll:gpu N -ll:fsize ...` (flexflow.py:24-99). TPU analog: multi-
controller JAX. On a TPU pod each host runs the SAME script;
`jax.distributed.initialize()` wires the hosts; GSPMD handles cross-host
(DCN) collectives. This driver:

  * single host: exec the script with the requested device env
  * multi host (--coordinator given or TPU pod env detected): call
    jax.distributed.initialize(...) then exec

Elastic relaunch (runtime/elastic.py): ``--elastic`` (or FF_ELASTIC=1)
turns a failed ``jax.distributed.initialize`` — the surviving host of a
shrunk pool waiting on peers that are never coming back — into a logged
single-process continuation instead of a crash: the script then sees the
actual (smaller) topology and auto-resume re-shards per
``FFConfig.on_topology_change``. Workers TCP-probe the coordinator before
handing control to jax (a failed rendezvous hard-terminates, not raises,
on this class of build); the coordinator itself has nothing to probe, so
it binds the rendezvous port and waits for a peer to KNOCK — silence
means the pool shrank around it. The world size the job *expected* is
detected up front (``--num-processes`` / the pod env) and compared against
what initialize actually produced, so a changed topology is diagnosed at
startup rather than as an opaque rendezvous timeout. The
``shrink(<k>)@resume:<n>`` fault (FF_FAULT) is consumed HERE, before the
backend exists, so a relaunch drill genuinely starts with k devices.

Usage: python -m flexflow_tpu.launcher script.py [--num-processes N]
       [--process-id I] [--coordinator host:port] [--elastic]
       [-- script args...]
"""

from __future__ import annotations

import argparse
import os
import runpy
import sys


def _retried_initialize(jax):
    """jax.distributed.initialize under retry/backoff: on a preempted pool
    the coordinator host often comes back seconds after the workers, and
    the raw call fails once and kills the whole relaunch. Attempts/delay
    tunable for restart loops via FF_INIT_ATTEMPTS / FF_INIT_DELAY_S;
    FF_INIT_TIMEOUT_S bounds each rendezvous attempt (the elastic relaunch
    path needs 'peers are gone' diagnosed in seconds, not jax's default
    300 s)."""
    import functools

    from flexflow_tpu.runtime.resilience import retry

    init = jax.distributed.initialize
    timeout = os.environ.get("FF_INIT_TIMEOUT_S", "")
    if timeout:
        import inspect

        try:  # only pass the kwarg where this jax build accepts it
            if "initialization_timeout" in \
                    inspect.signature(init).parameters:
                init = functools.partial(
                    init, initialization_timeout=int(float(timeout)))
        except (TypeError, ValueError):
            pass
    return retry(attempts=int(os.environ.get("FF_INIT_ATTEMPTS", "3")),
                 base_delay=float(os.environ.get("FF_INIT_DELAY_S", "2")),
                 max_delay=30.0, retryable=(RuntimeError, OSError),
                 name="jax.distributed.initialize")(init)


def _coordinator_reachable(addr: str, timeout_s: float) -> bool:
    """TCP probe of the rendezvous address. On this class of jax build a
    failed rendezvous TERMINATES the process from inside the distributed
    client (absl fatal, no Python exception to catch) — so the elastic
    relaunch must find out the coordinator is gone BEFORE handing control
    to jax, not after. timeout_s is a DEADLINE, not a per-connect timeout:
    a refused connect returns instantly, and on a preempted pool the
    coordinator host often binds its port seconds after the workers start,
    so the probe keeps retrying until the window closes."""
    import socket
    import time

    host, _, port = addr.rpartition(":")
    deadline = time.monotonic() + timeout_s
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return False
        try:
            with socket.create_connection((host or "127.0.0.1", int(port)),
                                          timeout=remaining):
                return True
        except ValueError:
            return False
        except OSError:
            if time.monotonic() + 0.5 >= deadline:
                return False
            time.sleep(0.5)


def _await_peer_knock(addr: str, timeout_s: float) -> bool:
    """Coordinator-side (process 0) flavor of the dead-peer diagnosis:
    process 0 cannot probe anything (it IS the rendezvous address), so it
    binds the port itself and waits for any peer to knock — a relaunched
    worker's elastic probe and a plain worker's initialize both TCP-connect
    here. No knock within the window means the pool shrank around the
    coordinator; falling back BEFORE jax starts the coordination service
    matters because a failed rendezvous hard-terminates the process (see
    _coordinator_reachable). If the port cannot be bound (something else
    holds it), assume infrastructure exists and let initialize decide.
    One knock is enough — this socket closes right before jax re-binds
    the port, and a worker whose probe lands in that gap just retries
    (the probe loops until its own deadline) and hits jax's service; the
    wide backlog keeps simultaneous probes from being refused outright."""
    import socket

    host, _, port = addr.rpartition(":")
    try:
        with socket.socket() as s:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind((host or "127.0.0.1", int(port)))
            s.listen(16)
            s.settimeout(timeout_s)
            try:
                conn, _peer = s.accept()
                conn.close()
                return True
            except socket.timeout:
                return False
    except (OSError, ValueError):
        return True


def _reset_cpu_collectives(jax):
    """Undo the gloo CPU-collectives selection after an elastic fallback
    to single-process: without a distributed client the gloo backend
    refuses to initialize, so the fallback must restore the default."""
    try:
        jax.config.update("jax_cpu_collectives_implementation", "none")
    except Exception:
        pass


def main(argv=None):
    p = argparse.ArgumentParser(prog="flexflow_tpu.launcher")
    p.add_argument("script")
    p.add_argument("--num-processes", type=int, default=None,
                   help="total controller processes (hosts)")
    p.add_argument("--process-id", type=int, default=None)
    p.add_argument("--coordinator", type=str, default=None,
                   help="host:port of process 0")
    p.add_argument("--cpu-devices", type=int, default=None,
                   help="emulate N CPU devices (testing)")
    p.add_argument("--elastic", action="store_true",
                   help="continue single-process (and let auto-resume "
                        "reshard) when multi-host initialize fails — the "
                        "surviving-host relaunch path; also FF_ELASTIC=1")
    args, rest = p.parse_known_args(argv)
    if rest and rest[0] == "--":
        rest = rest[1:]
    elastic = args.elastic or os.environ.get("FF_ELASTIC", "") not in ("",
                                                                       "0")

    # deterministic topology-change drill: FF_FAULT shrink(<k>)@resume:<n>
    # presents only k visible devices to this (fresh) process — consumed
    # before any backend exists so force_cpu_devices genuinely applies
    from flexflow_tpu.runtime import faultinject

    plan = faultinject.active_plan()
    if plan.fire("shrink", "resume") and plan.last_value:
        print(f"[launcher] FF_FAULT shrink@resume: presenting "
              f"{plan.last_value} visible devices", file=sys.stderr)
        args.cpu_devices = int(plan.last_value)

    if args.cpu_devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.cpu_devices}")
        import jax

        from flexflow_tpu._env import force_cpu_devices

        # _env handles the jax-version skew: jax_num_cpu_devices where the
        # build has it, the XLA_FLAGS device-count fallback otherwise
        # (0.4.37) — an unguarded config.update here killed every worker
        # at startup on the older builds
        force_cpu_devices(args.cpu_devices)
        if args.num_processes and args.num_processes > 1:
            # CPU cross-process collectives need an explicit backend
            jax.config.update("jax_cpu_collectives_implementation", "gloo")

    # multi-host pod detection: require an actual multi-worker signal (a
    # single-chip dev box can still carry TPU env vars)
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    pod_env = (not args.cpu_devices) and (
        "," in hostnames or bool(os.environ.get("MEGASCALE_COORDINATOR_ADDRESS")))
    if args.coordinator or (args.num_processes and args.num_processes > 1):
        if args.coordinator and (args.num_processes is None
                                 or args.process_id is None) and not pod_env:
            p.error("--coordinator requires --num-processes and --process-id "
                    "(they cannot be auto-detected outside TPU/SLURM "
                    "environments)")
        import jax

        skip_init = False
        if elastic and args.coordinator:
            probe_s = float(os.environ.get("FF_INIT_TIMEOUT_S", "10") or 10)
            if args.process_id in (None, 0):
                # coordinator-side relaunch: nothing to probe (we ARE the
                # rendezvous address) — listen on the port and wait for a
                # peer to knock instead; silence means the pool shrank
                # around the coordinator
                if (args.num_processes or 0) > 1 and \
                        not _await_peer_knock(args.coordinator, probe_s):
                    skip_init = True
                    _reset_cpu_collectives(jax)
                    print(f"[launcher] elastic: no peer knocked on "
                          f"{args.coordinator} within {probe_s:.0f}s — "
                          f"expected world size {args.num_processes}, "
                          f"continuing SINGLE-process; auto-resume will "
                          f"reshard per on_topology_change",
                          file=sys.stderr)
            elif not _coordinator_reachable(args.coordinator, probe_s):
                # non-coordinator relaunch: probe the rendezvous address
                # first. An unreachable coordinator means the pool shrank
                # around us — initialize would hard-terminate the process
                # (see _coordinator_reachable), so fall back HERE, cleanly
                skip_init = True
                _reset_cpu_collectives(jax)
                print(f"[launcher] elastic: coordinator "
                      f"{args.coordinator} unreachable — expected world "
                      f"size {args.num_processes}, continuing "
                      f"SINGLE-process; auto-resume will reshard per "
                      f"on_topology_change", file=sys.stderr)
        try:
            if not skip_init:
                _retried_initialize(jax)(
                    coordinator_address=args.coordinator,
                    num_processes=args.num_processes,
                    process_id=args.process_id)
        except Exception as e:
            if not elastic:
                raise
            # the surviving-host relaunch: peers (or the coordinator) are
            # gone for good, so retrying the rendezvous forever IS the
            # outage. Continue single-process — the script sees the actual
            # topology and FFConfig.on_topology_change decides what resume
            # does with it (runtime/elastic.py)
            _reset_cpu_collectives(jax)
            print(f"[launcher] elastic: multi-host initialize failed "
                  f"({type(e).__name__}: {e}) — expected world size "
                  f"{args.num_processes}, continuing SINGLE-process; "
                  f"auto-resume will reshard per on_topology_change",
                  file=sys.stderr)
        else:
            # world-size sanity at startup (not deep inside a collective):
            # initialize succeeded, but a pod env can legitimately come up
            # smaller than the job expected — diagnose it here
            actual = jax.process_count()
            if args.num_processes and actual != args.num_processes:
                print(f"[launcher] topology change detected at startup: "
                      f"expected {args.num_processes} processes, "
                      f"initialize produced {actual}", file=sys.stderr)
    elif pod_env:
        # TPU pod: every host runs this same script; initialize with full
        # auto-detection (docstring's 'TPU pod env detected' path)
        import jax

        try:
            _retried_initialize(jax)()
        except Exception as e:
            if not elastic:
                raise
            _reset_cpu_collectives(jax)
            print(f"[launcher] elastic: pod initialize failed "
                  f"({type(e).__name__}: {e}) — continuing SINGLE-process",
                  file=sys.stderr)

    cache_dir = os.environ.get("FF_COMPILATION_CACHE_DIR", "")
    if cache_dir:
        # persistent compilation cache for the launched script: enabled
        # HERE, before the script's first trace, so even programs built
        # ahead of FFModel.compile() (warmup probes, custom jits) hit it
        from flexflow_tpu._env import enable_compilation_cache

        enable_compilation_cache(cache_dir)

    sys.argv = [args.script] + rest
    runpy.run_path(args.script, run_name="__main__")


if __name__ == "__main__":
    main()
