"""keras_exp frontend: genuine tf.keras models -> ONNX bytes -> FFModel.

Reference: python/flexflow/keras_exp/ (tf.keras + keras2onnx + ONNXModelKeras).
"""
from flexflow_tpu.keras_exp.models import Model, Sequential  # noqa: F401
