from flexflow_tpu.keras_exp.models.model import (BaseModel, Model,  # noqa: F401
                                                 Sequential)
