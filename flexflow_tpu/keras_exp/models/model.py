"""keras_exp: drive FFModel training from a GENUINE tf.keras model.

Reference: python/flexflow/keras_exp/models/model.py — BaseModel wraps a
tf.keras Model, converts it with keras2onnx, replays the ONNX graph
through ONNXModelKeras, maps the tf.keras optimizer onto the FF one, and
fit()s with FF dataloaders. This is the same flow with the in-repo
exporter (exporter.py) in keras2onnx's seat: the live Keras layers and
their real weights are serialized to ONNX protobuf BYTES and those exact
bytes are parsed back (minionnx) to build + initialize the FFModel —
nothing is read from the Keras object after export.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

from flexflow_tpu.config import FFConfig
from flexflow_tpu.keras.optimizers import get_optimizer
from flexflow_tpu.model import FFModel
from flexflow_tpu.onnx import minionnx
from flexflow_tpu.onnx.model import ONNXModelKeras
from flexflow_tpu.runtime.loss import LossType, loss_type_from_name
from flexflow_tpu.runtime.metrics import metrics_from_names


def _map_keras_optimizer(opt):
    """tf.keras optimizer instance -> FF optimizer (reference maps the
    tf.keras optimizer config onto flexflow optimizers the same way)."""
    kind = type(opt).__name__.lower()
    from flexflow_tpu.runtime.optimizer import AdamOptimizer, SGDOptimizer

    if kind not in ("sgd", "adam", "adamw"):
        return get_optimizer(opt)  # FF-side optimizers / strings
    try:
        lr = float(np.asarray(opt.learning_rate))
    except Exception as e:
        raise NotImplementedError(
            f"keras_exp: cannot map {type(opt).__name__}.learning_rate "
            f"({opt.learning_rate!r}) to a constant — keras LR schedule "
            f"objects are unsupported; use FFConfig/FF optimizers with a "
            f"runtime/schedule.py schedule instead") from e
    if kind == "adamw":
        return AdamOptimizer(alpha=lr, beta1=float(opt.beta_1),
                             beta2=float(opt.beta_2),
                             weight_decay=float(opt.weight_decay))
    if kind == "adam":
        return AdamOptimizer(alpha=lr, beta1=float(opt.beta_1),
                             beta2=float(opt.beta_2))
    return SGDOptimizer(lr=lr, momentum=float(np.asarray(opt.momentum)))


class BaseModel:
    def __init__(self, keras_model, ffconfig: Optional[FFConfig] = None):
        from flexflow_tpu.keras_exp.exporter import keras_to_onnx

        self.ffconfig = ffconfig or FFConfig.parse_args()
        # the exported BYTES are the interface: serialize the live keras
        # model, then parse those bytes back for the importer
        self.onnx_bytes = keras_to_onnx(keras_model, self.ffconfig.batch_size)
        self.onnx_model = minionnx.parse(self.onnx_bytes)
        self.ffmodel: Optional[FFModel] = None
        self._keras_name = keras_model.name

    # ---- reference BaseModel.compile (model.py:80-160) ---------------------
    def compile(self, optimizer, loss=None, metrics=None, **kwargs):
        self._optimizer = _map_keras_optimizer(optimizer)
        self._loss = loss_type_from_name(loss)
        self._metrics = metrics_from_names(metrics or [])
        ff = FFModel(self.ffconfig)
        importer = ONNXModelKeras(self.onnx_model)
        input_dict = {}
        self._input_fftensors = []
        for vi in self.onnx_model.graph.input:
            t = ff.create_tensor(list(vi.type.shape_dims), name=vi.name)
            input_dict[vi.name] = t
            self._input_fftensors.append(t)
        out = importer.apply(ff, input_dict)
        if isinstance(out, (list, tuple)):
            out = out[-1]
        ff.compile(self._optimizer, self._loss, self._metrics,
                   final_tensor=out)
        self._load_weights_from_onnx(ff, importer)
        self.ffmodel = ff
        return self

    def _load_weights_from_onnx(self, ff, importer):
        """Initialize FF params from the graph INITIALIZERS — the weights
        ride the exported bytes, proving the protobuf carries the real
        keras state (Gemm B is stored (out, in) keras2onnx-style, FF dense
        kernels are (in, out); Conv is OIHW on both sides)."""
        ops = {op.name: op for op in ff.ops}
        for node in self.onnx_model.graph.node:
            if node.op_type not in ("Gemm", "Dense", "Conv", "MatMul"):
                continue
            if node.name not in ops or len(node.input) < 2:
                continue
            w = importer.initializer.get(node.input[1])
            if w is None:
                continue
            kernel = minionnx.to_array(w)
            if node.op_type in ("Gemm", "Dense"):
                kernel = np.ascontiguousarray(kernel.T)
            ff.set_weights(node.name, "kernel", kernel)
            if len(node.input) > 2:
                b = importer.initializer.get(node.input[2])
                if b is not None:
                    ff.set_weights(node.name, "bias", minionnx.to_array(b))

    # ---- reference BaseModel.fit (model.py:160-220) ------------------------
    def fit(self, x, y, batch_size: Optional[int] = None, epochs: int = 1,
            callbacks: Sequence = (), verbose: bool = True):
        from flexflow_tpu.runtime.dataloader import attach_training_data

        assert self.ffmodel is not None, "compile() first"
        xs = x if isinstance(x, (list, tuple)) else [x]
        attach_training_data(self.ffmodel, self._input_fftensors,
                             [np.asarray(a, np.float32) for a in xs],
                             y, self._loss)
        return self.ffmodel.fit(epochs=epochs, batch_size=batch_size,
                                callbacks=callbacks, verbose=verbose)

    def predict(self, x) -> np.ndarray:
        assert self.ffmodel is not None, "compile() first"
        xs = x if isinstance(x, (list, tuple)) else [x]
        batch = {t.owner_op.name: np.asarray(a, np.float32)
                 for t, a in zip(self._input_fftensors, xs)}
        return np.asarray(self.ffmodel.predict(batch))

    def summary(self) -> str:
        g = self.onnx_model.graph
        lines = [f"keras_exp model {self._keras_name!r}: "
                 f"{len(g.node)} onnx nodes, {len(g.initializer)} weights"]
        for n in g.node:
            lines.append(f"  {n.op_type:>12} {n.name or '-'} "
                         f"{list(n.input)} -> {list(n.output)}")
        return "\n".join(lines)


class Model(BaseModel):
    """Functional keras_exp entry (reference model.py:252-268): accepts
    live tf.keras Input/output tensors, builds the tf.keras Model, then
    the shared BaseModel export/replay flow."""

    def __init__(self, inputs, outputs, name: Optional[str] = None,
                 ffconfig: Optional[FFConfig] = None):
        import keras

        if isinstance(inputs, dict):
            inputs = list(inputs.values())
        if isinstance(inputs, (list, tuple)) and len(inputs) == 1:
            inputs = inputs[0]
        km = keras.Model(inputs=inputs, outputs=outputs,
                         name=name or "keras_exp_model")
        super().__init__(km, ffconfig=ffconfig)


class Sequential(BaseModel):
    """Sequential keras_exp entry (reference model.py:270-290)."""

    def __init__(self, layers=None, name: Optional[str] = None,
                 ffconfig: Optional[FFConfig] = None):
        import keras

        km = keras.Sequential(layers or [], name=name or "keras_exp_seq")
        if not km.built:
            raise ValueError(
                "Sequential keras_exp models need an Input layer first "
                "(keras.Input(shape=...)) so shapes are known at export")
        super().__init__(km, ffconfig=ffconfig)
