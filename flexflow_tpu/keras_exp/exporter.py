"""tf.keras -> ONNX exporter: the keras2onnx analog for the keras_exp path.

Reference: python/flexflow/keras_exp/models/model.py:16-60 converts a live
tf.keras model with `keras2onnx.convert_keras` and replays the resulting
ONNX graph through ONNXModelKeras. keras2onnx cannot run here (it predates
TF2.16/Keras 3 and is not in the image), so this module IS the exporter:
it walks a genuine tf.keras (Keras 3) model — real layer objects, real
trained weights read through the Keras API — and emits the same
keras2onnx-style ONNX graph (Gemm with transposed B + bias, activation
nodes split out, node names = layer names), serialized through the in-repo
protobuf codec (minionnx, whose wire format is validated against real
`torch.onnx.export` bytes in tests/test_minionnx.py).

Supported layers mirror the reference keras_exp examples
(examples/python/keras_exp/*.py): InputLayer, Dense, Activation, Flatten,
Conv2D, MaxPooling2D, AveragePooling2D, Dropout, Concatenate, Add.
Conv models must use channels_first data format, exactly as the reference
examples demand (`backend.set_image_data_format('channels_first')`).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from flexflow_tpu.onnx import minionnx as mo

_ACT_NODES = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
              "softmax": "Softmax", "elu": "Elu"}


def _act_name(layer) -> str:
    act = getattr(layer, "activation", None)
    if act is None:
        return "linear"
    return getattr(act, "__name__", str(act))


def _pads(layer, kh: int, kw: int, in_hw) -> List[int]:
    """TF 'same' pads: total = max((ceil(in/s)-1)*s + k - in, 0), with the
    EXTRA pixel at the END when total is odd. FF conv/pool take symmetric
    padding, so an asymmetric-'same' combination (even kernels, or
    stride>1 on mismatched sizes) is refused rather than silently shifted
    by one pixel."""
    if getattr(layer, "padding", "valid") != "same":
        return [0, 0, 0, 0]
    strides = [int(s) for s in layer.strides]
    out = []
    for size, k, s in zip(in_hw, (kh, kw), strides):
        total = max((-(-size // s) - 1) * s + k - size, 0)
        if total % 2:
            raise NotImplementedError(
                f"keras_exp: padding='same' on layer {layer.name!r} needs "
                f"asymmetric pads (input {size}, kernel {k}, stride {s}) "
                f"which the symmetric FF conv cannot express; use "
                f"padding='valid' or shapes where 'same' is symmetric")
        out.append(total // 2)
    ph, pw = out
    return [ph, pw, ph, pw]


class _Export:
    def __init__(self, batch_size: int):
        self.batch = batch_size
        self.nodes: List[mo.NodeProto] = []
        self.inits: List[mo.TensorProto] = []
        self.inputs: List[mo.ValueInfoProto] = []
        self.names: Dict[str, str] = {}  # keras tensor name -> onnx symbol
        self.prefix = ""  # nested sub-model scope, "outer/inner/"
        self.used: set = set()  # emitted scoped names (layer-reuse guard)

    def _n(self, layer) -> str:
        return f"{self.prefix}{layer.name}"

    def _emit_activation(self, layer, act: str, sym: str) -> str:
        node = _ACT_NODES.get(act)
        if node is None:
            raise NotImplementedError(
                f"keras_exp exporter: activation {act!r} of layer "
                f"{layer.name!r} has no ONNX mapping")
        out = f"{self._n(layer)}/{act}:0"
        self.nodes.append(mo.make_node(node, [sym], [out],
                                       name=f"{self._n(layer)}/{act}"))
        return out

    def _inline_model(self, sub, node) -> None:
        """A keras Model called as a layer (reference
        func_cifar10_cnn_nested.py): inline its graph under a name scope —
        sub-model inputs alias the caller's symbols, InputLayers emit
        nothing, every inner name is prefixed so two sub-models may reuse
        layer names."""
        ins = [self.names[t.name] for t in node.input_tensors]
        for sub_in, sym in zip(sub.inputs, ins):
            self.names[sub_in.name] = sym
        saved = self.prefix
        scope = f"{saved}{sub.name}/"
        if scope in self.used:
            raise NotImplementedError(
                f"keras_exp exporter: sub-model {sub.name!r} is called more "
                f"than once (weight sharing); instantiate a separate "
                f"sub-model per call or use the native frontend")
        self.used.add(scope)
        self.prefix = scope
        for depth in sorted(sub._nodes_by_depth.keys(), reverse=True):
            for n in sub._nodes_by_depth[depth]:
                if type(n.operation).__name__ == "InputLayer":
                    continue  # aliased above
                self.add_layer(n)
        self.prefix = saved
        # the caller's output tensor aliases the sub-graph's output
        for out_t, sub_out in zip(node.output_tensors, sub.outputs):
            self.names[out_t.name] = self.names[sub_out.name]

    def add_layer(self, node) -> None:
        """Emit ONNX node(s) for one Keras graph node (layer call)."""
        layer = node.operation
        kind = type(layer).__name__
        if kind in ("Functional", "Sequential") or (
                hasattr(layer, "_nodes_by_depth") and kind != "InputLayer"):
            self._inline_model(layer, node)
            return
        scoped = self._n(layer)
        if scoped in self.used:
            raise NotImplementedError(
                f"keras_exp exporter: layer {scoped!r} is called more than "
                f"once (weight sharing); give each call its own layer or "
                f"use the native frontend's tie_weights")
        self.used.add(scoped)
        ins = [self.names[t.name] for t in node.input_tensors]
        out_t = node.output_tensors[0]
        out = f"{scoped}:0"

        if kind == "InputLayer":
            shape = [self.batch] + [int(d) for d in out_t.shape[1:]]
            self.inputs.append(
                mo.make_tensor_value_info(layer.name, mo.DT_FLOAT, shape))
            self.names[out_t.name] = layer.name
            return

        if kind == "Dense":
            # keras2onnx layout: Gemm with B = kernel^T (out, in), transB
            # semantics — ONNXModelKeras reads out_dim from B.dims[0]
            k, *rest = layer.get_weights()
            wname = f"{self._n(layer)}/kernel:0"
            self.inits.append(mo.from_array(
                np.ascontiguousarray(k.T.astype(np.float32)), wname))
            gemm_in = [ins[0], wname]
            if layer.use_bias:
                bname = f"{self._n(layer)}/bias:0"
                self.inits.append(mo.from_array(
                    rest[0].astype(np.float32), bname))
                gemm_in.append(bname)
            self.nodes.append(mo.make_node(
                "Gemm", gemm_in, [out], name=self._n(layer), alpha=1.0,
                beta=1.0, transB=1))
            act = _act_name(layer)
            if act != "linear":
                out = self._emit_activation(layer, act, out)
        elif kind == "Conv2D":
            if layer.data_format != "channels_first":
                raise NotImplementedError(
                    "keras_exp conv models must use channels_first "
                    "(reference keras_exp examples set "
                    "backend.set_image_data_format('channels_first'))")
            k, *rest = layer.get_weights()  # HWIO
            kh, kw = int(k.shape[0]), int(k.shape[1])
            wname = f"{self._n(layer)}/kernel:0"
            self.inits.append(mo.from_array(
                np.ascontiguousarray(
                    k.transpose(3, 2, 0, 1).astype(np.float32)), wname))
            conv_in = [ins[0], wname]
            if layer.use_bias:
                bname = f"{self._n(layer)}/bias:0"
                self.inits.append(mo.from_array(
                    rest[0].astype(np.float32), bname))
                conv_in.append(bname)
            self.nodes.append(mo.make_node(
                "Conv", conv_in, [out], name=self._n(layer),
                kernel_shape=[kh, kw],
                strides=[int(s) for s in layer.strides],
                pads=_pads(layer, kh, kw,
                           node.input_tensors[0].shape[2:4]),
                group=int(getattr(layer, "groups", 1))))
            act = _act_name(layer)
            if act != "linear":
                out = self._emit_activation(layer, act, out)
        elif kind in ("MaxPooling2D", "AveragePooling2D"):
            ph, pw = (int(p) for p in layer.pool_size)
            self.nodes.append(mo.make_node(
                "MaxPool" if kind == "MaxPooling2D" else "AveragePool",
                ins, [out], name=self._n(layer), kernel_shape=[ph, pw],
                strides=[int(s) for s in layer.strides],
                pads=_pads(layer, ph, pw,
                           node.input_tensors[0].shape[2:4])))
        elif kind == "Flatten":
            sym = ins[0]
            in_rank = len(node.input_tensors[0].shape)
            if getattr(layer, "data_format", None) == "channels_first" \
                    and in_rank > 2:
                # keras Flatten(channels_first) switches to channels-last
                # BEFORE reshaping (keras2onnx emitted the same Transpose)
                perm = [0] + list(range(2, in_rank)) + [1]
                tsym = f"{self._n(layer)}/transpose:0"
                self.nodes.append(mo.make_node(
                    "Transpose", [sym], [tsym],
                    name=f"{self._n(layer)}/transpose", perm=perm))
                sym = tsym
            self.nodes.append(mo.make_node("Flatten", [sym], [out],
                                           name=self._n(layer)))
        elif kind == "Activation":
            out = self._emit_activation(layer, _act_name(layer), ins[0])
        elif kind == "Dropout":
            self.nodes.append(mo.make_node("Dropout", ins, [out],
                                           name=self._n(layer),
                                           ratio=float(layer.rate)))
        elif kind == "Concatenate":
            self.nodes.append(mo.make_node("Concat", ins, [out],
                                           name=self._n(layer),
                                           axis=int(layer.axis)))
        elif kind == "Add":
            self.nodes.append(mo.make_node("Add", ins, [out],
                                           name=self._n(layer)))
        else:
            raise NotImplementedError(
                f"keras_exp exporter: unsupported layer type {kind} "
                f"({layer.name!r})")
        self.names[out_t.name] = out


def keras_to_onnx(model, batch_size: int) -> bytes:
    """Convert a live tf.keras model to serialized ONNX bytes (the
    keras2onnx.convert_keras analog). Returns the protobuf wire bytes —
    callers parse them back with minionnx.parse, so the exact exported
    bytes are what reaches the graph importer."""
    ex = _Export(batch_size)
    # Keras 3 functional graphs: _nodes_by_depth walks producers before
    # consumers at descending depth
    for depth in sorted(model._nodes_by_depth.keys(), reverse=True):
        for node in model._nodes_by_depth[depth]:
            ex.add_layer(node)
    out_syms = [ex.names[t.name] for t in model.outputs]
    # graph inputs follow the model.inputs order, so a multi-input fit's
    # array list lines up positionally (reference passes {key: Input} dicts)
    order = [ex.names[t.name] for t in model.inputs]
    ex.inputs.sort(key=lambda vi: order.index(vi.name))
    graph = mo.make_graph(
        ex.nodes, model.name or "keras_model", ex.inputs,
        [mo.make_tensor_value_info(s, mo.DT_FLOAT, [])
         for s in out_syms],
        initializer=ex.inits)
    proto = mo.make_model(graph)
    proto.producer_name = "flexflow_tpu.keras_exp"
    return mo.serialize(proto)
