"""Gradient accumulation (FFConfig.grad_accum_steps): microbatch-scanned
fwd+bwd with one optimizer update must be NUMERICALLY the full-batch step
— all losses are batch means, so mean-of-microbatch-means is exact."""

import numpy as np
import pytest

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer)


def _build(accum):
    cfg = FFConfig(batch_size=16, mesh_shape={"data": 2},
                   grad_accum_steps=accum)
    ff = FFModel(cfg)
    x = ff.create_tensor([16, 32], name="input")
    t = ff.dense(x, 64, name="d1")
    t = ff.relu(t, name="r1")
    t = ff.dense(t, 8, name="head")
    ff.compile(SGDOptimizer(lr=0.1),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=t)
    return ff


def test_accum_matches_full_batch_step():
    rs = np.random.RandomState(0)
    batch = {"input": rs.randn(16, 32).astype(np.float32),
             "label": rs.randint(0, 8, (16, 1)).astype(np.int32)}
    ff1, ff4 = _build(1), _build(4)
    for op, ws in ff1.params.items():
        for w, v in ws.items():
            ff4.set_weights(op, w, np.asarray(v))

    l1 = m1 = l4 = m4 = None
    for _ in range(3):
        l1, m1 = ff1._run_train_step(batch)
        l4, m4 = ff4._run_train_step(batch)
    np.testing.assert_allclose(float(l1), float(l4), rtol=1e-5)
    assert int(m1["accuracy_count"]) == int(m4["accuracy_count"])
    for op, ws in ff1.params.items():
        for w, v in ws.items():
            np.testing.assert_allclose(
                np.asarray(v), np.asarray(ff4.params[op][w]),
                atol=1e-5, rtol=1e-5, err_msg=f"{op}/{w}")


def test_accum_composes_with_scanned_trainer():
    """grad_accum nests inside the multi-step scan: scanned training with
    accum=2 matches per-step training with accum=1 on the same data."""
    from flexflow_tpu import SingleDataLoader
    from tests.test_training import build_mlp, make_blobs

    def fresh(accum):
        cfg = FFConfig(batch_size=64, grad_accum_steps=accum)
        ff, xt = build_mlp(cfg)
        ff.compile(SGDOptimizer(lr=0.1),
                   LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                   [MetricsType.METRICS_ACCURACY])
        x, y = make_blobs()
        SingleDataLoader(ff, xt, x)
        SingleDataLoader(ff, ff.label_tensor, y)
        return ff

    ff_ref, ff_scan = fresh(1), fresh(2)
    for _ in range(4):
        ff_ref._run_train_step(ff_ref._stage_batch())
    ff_scan.train_scanned(4)
    for op, ws in ff_ref.params.items():
        for w, v in ws.items():
            np.testing.assert_allclose(
                np.asarray(v), np.asarray(ff_scan.params[op][w]),
                atol=2e-5, rtol=2e-5, err_msg=f"{op}/{w}")


def test_accum_validation():
    with pytest.raises(ValueError):
        FFConfig(batch_size=16, grad_accum_steps=5)
    with pytest.raises(ValueError):
        FFConfig(batch_size=16, grad_accum_steps=0)
