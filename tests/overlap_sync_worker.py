"""Worker for the collective-overlap CI drill
(scripts/collective_overlap_smoke.py, ci/run_ci.sh `overlap` tier),
launched through flexflow_tpu.launcher on one OR two controller
processes.

Trains with FFConfig.overlap_grad_sync on (bucketed in-scan grad
reduce-scatter + ZeRO-1 sharded optimizer update) and
async_checkpointing on — single-process that publishes checkpoints from
the background thread; on two controllers the collective multihost save
falls back to synchronous with a warning (the documented contract) —
under a TrainSupervisor. FF_FAULT=sigterm@step:<k> preempts phase 1; a
relaunch resumes and must continue BITWISE (the smoke compares the
resumed loss tail against an uninterrupted reference run).

Prints one machine-checkable line per process:
  OVERLAPSYNC pid=<i> status=<s> resumed=<r> step=<n> procs=<p>
              zero1=<0|1> losses=<l1,l2,...>   (losses at %.9f)
"""

import sys

import numpy as np

import jax


def main():
    ckpt = sys.argv[1]
    total = int(sys.argv[2]) if len(sys.argv) > 2 else 8

    from flexflow_tpu import (ActiMode, FFConfig, FFModel, LossType,
                              MetricsType, SGDOptimizer, SingleDataLoader,
                              TrainSupervisor)
    from flexflow_tpu.runtime.optimizer import Zero1Update

    cfg = FFConfig(batch_size=32, epochs=1, seed=7, grad_accum_steps=2,
                   overlap_grad_sync=True, async_checkpointing=True,
                   checkpoint_dir=ckpt, checkpoint_every=2)
    ff = FFModel(cfg)
    x = ff.create_tensor([32, 16], name="x")
    t = ff.dense(x, 64, ActiMode.AC_MODE_RELU, name="fc1")
    ff.dense(t, 4, name="out")
    ff.compile(SGDOptimizer(lr=0.1),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY])

    # identical data on every controller (SPMD: same program, same inputs)
    rs = np.random.RandomState(0)
    SingleDataLoader(ff, x, rs.randn(128, 16).astype(np.float32))
    SingleDataLoader(ff, ff.label_tensor,
                     rs.randint(0, 4, (128, 1)).astype(np.int32))

    sup = TrainSupervisor(ff, ckpt)
    status = sup.run(total)
    losses = ",".join(f"{l:.9f}" for l in sup.losses)
    print(f"OVERLAPSYNC pid={jax.process_index()} status={status} "
          f"resumed={sup._resumed} step={ff._step_count} "
          f"procs={jax.process_count()} "
          f"zero1={int(isinstance(ff.optimizer, Zero1Update))} "
          f"losses={losses}", flush=True)


if __name__ == "__main__":
    main()
