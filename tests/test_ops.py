"""Op-level golden tests vs numpy/torch-cpu.

Mirrors the reference's op unit-test tier (tests/ops/test_harness.py: dump
inputs/outputs, compare with np.testing.assert_allclose at 1e-5), but runs
in-process: build a one-op graph, execute, compare against a numpy or torch
reference implementation.
"""

import numpy as np
import jax
import pytest

from flexflow_tpu import (ActiMode, AggrMode, DataType, FFConfig, FFModel,
                          PoolType)


def run_single_op(build, feeds):
    """build(ff) -> output tensor; feeds: {input_name: np.ndarray}."""
    cfg = FFConfig(num_devices=1, mesh_shape={"data": 1})
    ff = FFModel(cfg)
    out = build(ff)
    ff.compile(optimizer=None, final_tensor=out)
    fwd = ff.executor.make_forward([out])
    res = fwd(ff.params, ff.bn_state, feeds)
    return np.asarray(res[0]), ff


def test_dense_matches_numpy():
    x = np.random.RandomState(0).randn(4, 16).astype(np.float32)

    def build(ff):
        t = ff.create_tensor([4, 16], name="x")
        return ff.dense(t, 8, ActiMode.AC_MODE_RELU, name="fc")

    y, ff = run_single_op(build, {"x": x})
    k = ff.get_weights("fc", "kernel")
    b = ff.get_weights("fc", "bias")
    ref = np.maximum(x @ k + b, 0)
    np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-5)


def test_conv2d_matches_torch():
    torch = pytest.importorskip("torch")
    x = np.random.RandomState(1).randn(2, 3, 8, 8).astype(np.float32)

    def build(ff):
        t = ff.create_tensor([2, 3, 8, 8], name="x")
        return ff.conv2d(t, 4, 3, 3, 1, 1, 1, 1, name="conv")

    y, ff = run_single_op(build, {"x": x})
    k = ff.get_weights("conv", "kernel")
    b = ff.get_weights("conv", "bias")
    with torch.no_grad():
        ref = torch.nn.functional.conv2d(
            torch.from_numpy(x), torch.from_numpy(k), torch.from_numpy(b),
            stride=1, padding=1).numpy()
    np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-4)


def test_pool2d_max_matches_torch():
    torch = pytest.importorskip("torch")
    x = np.random.RandomState(2).randn(2, 3, 8, 8).astype(np.float32)

    def build(ff):
        t = ff.create_tensor([2, 3, 8, 8], name="x")
        return ff.pool2d(t, 2, 2, 2, 2, 0, 0, PoolType.POOL_MAX)

    y, _ = run_single_op(build, {"x": x})
    with torch.no_grad():
        ref = torch.nn.functional.max_pool2d(torch.from_numpy(x), 2).numpy()
    np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-5)


def test_embedding_bag_sum():
    idx = np.random.RandomState(3).randint(0, 50, size=(4, 6)).astype(np.int32)

    def build(ff):
        t = ff.create_tensor([4, 6], dtype=DataType.DT_INT32, name="x")
        return ff.embedding(t, 50, 8, AggrMode.AGGR_MODE_SUM, name="emb")

    y, ff = run_single_op(build, {"x": idx})
    table = ff.get_weights("emb", "kernel")
    ref = table[idx].sum(axis=1)
    np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-5)


def test_multihead_attention_matches_torch():
    torch = pytest.importorskip("torch")
    rs = np.random.RandomState(4)
    B, S, D, H = 2, 5, 16, 4
    x = rs.randn(B, S, D).astype(np.float32)

    def build(ff):
        q = ff.create_tensor([B, S, D], name="q")
        return ff.multihead_attention(q, q, q, D, H, bias=False, name="mha")

    y, ff = run_single_op(build, {"q": x})
    wq = ff.get_weights("mha", "wq").reshape(D, D)  # (D, H, Hd) -> (D, D)
    wk = ff.get_weights("mha", "wk").reshape(D, D)
    wv = ff.get_weights("mha", "wv").reshape(D, D)
    wo = ff.get_weights("mha", "wo").reshape(D, D)  # (H, Hd, D) -> (D, D)
    mha = torch.nn.MultiheadAttention(D, H, bias=False, batch_first=True)
    with torch.no_grad():
        mha.in_proj_weight.copy_(torch.from_numpy(
            np.concatenate([wq.T, wk.T, wv.T], axis=0)))
        mha.out_proj.weight.copy_(torch.from_numpy(wo.T))
        ref, _ = mha(torch.from_numpy(x), torch.from_numpy(x),
                     torch.from_numpy(x))
    np.testing.assert_allclose(y, ref.numpy(), rtol=1e-4, atol=1e-4)


def test_layernorm_matches_torch():
    torch = pytest.importorskip("torch")
    x = np.random.RandomState(5).randn(3, 7, 12).astype(np.float32)

    def build(ff):
        t = ff.create_tensor([3, 7, 12], name="x")
        return ff.layer_norm(t)

    y, _ = run_single_op(build, {"x": x})
    with torch.no_grad():
        ref = torch.nn.functional.layer_norm(torch.from_numpy(x), (12,)).numpy()
    np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-4)


def test_softmax_concat_split_transpose_reverse():
    x = np.random.RandomState(6).randn(4, 6).astype(np.float32)

    def build(ff):
        t = ff.create_tensor([4, 6], name="x")
        a, b = ff.split(t, 2, axis=1)
        c = ff.concat([a, b], axis=1)
        r = ff.reverse(c, axis=1)
        tr = ff.transpose(r, [1, 0])
        tr2 = ff.transpose(tr, [1, 0])
        return ff.softmax(tr2)

    y, _ = run_single_op(build, {"x": x})
    ref = np.exp(x[:, ::-1]) / np.exp(x[:, ::-1]).sum(-1, keepdims=True)
    np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-5)


def test_batch_matmul():
    rs = np.random.RandomState(7)
    a = rs.randn(3, 4, 5).astype(np.float32)
    b = rs.randn(3, 5, 6).astype(np.float32)

    def build(ff):
        ta = ff.create_tensor([3, 4, 5], name="a")
        tb = ff.create_tensor([3, 5, 6], name="b")
        return ff.batch_matmul(ta, tb)

    cfg = FFConfig(num_devices=1, mesh_shape={"data": 1})
    ff = FFModel(cfg)
    out = build(ff)
    ff.compile(optimizer=None, final_tensor=out)
    fwd = ff.executor.make_forward([out])
    y = np.asarray(fwd(ff.params, ff.bn_state, {"a": a, "b": b})[0])
    np.testing.assert_allclose(y, a @ b, rtol=1e-5, atol=1e-5)


def test_topk():
    x = np.random.RandomState(8).randn(4, 10).astype(np.float32)

    def build(ff):
        t = ff.create_tensor([4, 10], name="x")
        vals, idxs = ff.topk(t, 3)
        return vals

    y, _ = run_single_op(build, {"x": x})
    ref = -np.sort(-x, axis=1)[:, :3]
    np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-5)


def test_elementwise_chain():
    x = np.random.RandomState(9).rand(4, 5).astype(np.float32) + 0.5

    def build(ff):
        t = ff.create_tensor([4, 5], name="x")
        a = ff.exp(t)
        b = ff.scalar_multiply(t, 2.0)
        c = ff.add(a, b)
        d = ff.multiply(c, t)
        return ff.sigmoid(d)

    y, _ = run_single_op(build, {"x": x})
    ref = 1 / (1 + np.exp(-((np.exp(x) + 2 * x) * x)))
    np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-5)


def test_gradients_match_torch_mlp():
    """Backward golden test (the reference harness also diffs grads and SGD
    steps, tests/ops/test_harness.py): param grads of a 2-layer MLP with
    cross-entropy must match torch autograd at 1e-4."""
    import torch
    import torch.nn.functional as F

    from flexflow_tpu import (ActiMode, LossType, MetricsType, SGDOptimizer)

    B, D, H, C = 8, 16, 32, 5
    rs = np.random.RandomState(0)
    xd = rs.randn(B, D).astype(np.float32)
    y = rs.randint(0, C, (B, 1)).astype(np.int32)

    cfg = FFConfig(batch_size=B, mesh_shape={"data": 1}, seed=0)
    ff = FFModel(cfg)
    x = ff.create_tensor([B, D], name="x")
    t = ff.dense(x, H, ActiMode.AC_MODE_RELU, name="fc1")
    out = ff.dense(t, C, name="fc2")
    ff.compile(SGDOptimizer(lr=0.0),  # lr 0: step leaves params unchanged
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=out)

    w1 = np.asarray(ff.get_weights("fc1", "kernel"))
    b1 = np.asarray(ff.get_weights("fc1", "bias"))
    w2 = np.asarray(ff.get_weights("fc2", "kernel"))
    b2 = np.asarray(ff.get_weights("fc2", "bias"))

    # our grads via a manual value_and_grad on the same loss
    import jax as _jax

    def loss_fn(params):
        from flexflow_tpu.runtime.loss import compute_loss

        fwd = ff.executor.make_forward([out], training=True)
        logits = fwd(params, ff.bn_state, {"x": xd})[0]
        return compute_loss(ff.loss_type, logits, y)

    grads = _jax.grad(loss_fn)(ff.params)

    # torch reference
    tw1 = torch.tensor(w1, requires_grad=True)
    tb1 = torch.tensor(b1, requires_grad=True)
    tw2 = torch.tensor(w2, requires_grad=True)
    tb2 = torch.tensor(b2, requires_grad=True)
    h = F.relu(torch.tensor(xd) @ tw1 + tb1)
    logits = h @ tw2 + tb2
    loss = F.cross_entropy(logits, torch.tensor(y.ravel(), dtype=torch.long))
    loss.backward()

    np.testing.assert_allclose(np.asarray(grads["fc1"]["kernel"]),
                               tw1.grad.numpy(), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(grads["fc2"]["kernel"]),
                               tw2.grad.numpy(), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(grads["fc1"]["bias"]),
                               tb1.grad.numpy(), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(grads["fc2"]["bias"]),
                               tb2.grad.numpy(), rtol=1e-4, atol=1e-5)


@pytest.mark.slow  # 17 s torch-parity one-off
def test_sgd_momentum_step_matches_torch():
    """One SGD+momentum+weight-decay step matches torch.optim.SGD (reference
    harness compares manual SGD update sequences)."""
    import torch

    from flexflow_tpu import (LossType, MetricsType, SGDOptimizer)

    B, D, C = 8, 12, 4
    rs = np.random.RandomState(1)
    xd = rs.randn(B, D).astype(np.float32)
    y = rs.randint(0, C, (B, 1)).astype(np.int32)
    lr, mom, wd = 0.1, 0.9, 0.01

    cfg = FFConfig(batch_size=B, mesh_shape={"data": 1}, seed=2)
    ff = FFModel(cfg)
    x = ff.create_tensor([B, D], name="x")
    out = ff.dense(x, C, name="fc")
    ff.compile(SGDOptimizer(lr=lr, momentum=mom, weight_decay=wd),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=out)
    w0 = np.asarray(ff.get_weights("fc", "kernel")).copy()
    b0 = np.asarray(ff.get_weights("fc", "bias")).copy()

    tw = torch.tensor(w0, requires_grad=True)
    tb = torch.tensor(b0, requires_grad=True)
    opt = torch.optim.SGD([tw, tb], lr=lr, momentum=mom, weight_decay=wd)

    for _ in range(3):  # multi-step: exercises the momentum buffer
        ff._run_train_step({"x": xd, "label": y})
        opt.zero_grad()
        logits = torch.tensor(xd) @ tw + tb
        torch.nn.functional.cross_entropy(
            logits, torch.tensor(y.ravel(), dtype=torch.long)).backward()
        opt.step()

    np.testing.assert_allclose(np.asarray(ff.get_weights("fc", "kernel")),
                               tw.detach().numpy(), rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(ff.get_weights("fc", "bias")),
                               tb.detach().numpy(), rtol=2e-4, atol=2e-5)


def test_conv2d_gradients_match_torch():
    """Conv backward golden test (reference tests/ops cover conv grads via
    the same harness): kernel/bias grads of conv+MSE match torch autograd."""
    import torch

    from flexflow_tpu import LossType, MetricsType, SGDOptimizer

    B, C, HW, O = 4, 3, 8, 6
    rs = np.random.RandomState(0)
    xd = rs.randn(B, C, HW, HW).astype(np.float32)
    yd = rs.randn(B, O, HW, HW).astype(np.float32)

    cfg = FFConfig(batch_size=B, mesh_shape={"data": 1}, seed=0)
    ff = FFModel(cfg)
    x = ff.create_tensor([B, C, HW, HW], name="x")
    out = ff.conv2d(x, O, 3, 3, 1, 1, 1, 1, name="conv")
    ff.compile(SGDOptimizer(lr=0.0),
               LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
               [MetricsType.METRICS_MEAN_SQUARED_ERROR], final_tensor=out)

    k = np.asarray(ff.get_weights("conv", "kernel"))
    b = np.asarray(ff.get_weights("conv", "bias"))

    import jax as _jax

    def loss_fn(params):
        from flexflow_tpu.runtime.loss import compute_loss

        fwd = ff.executor.make_forward([out], training=True)
        logits = fwd(params, ff.bn_state, {"x": xd})[0]
        return compute_loss(ff.loss_type, logits, yd)

    grads = _jax.grad(loss_fn)(ff.params)

    tk = torch.tensor(k, requires_grad=True)
    tb = torch.tensor(b, requires_grad=True)
    ty = torch.nn.functional.conv2d(torch.tensor(xd), tk, tb, padding=1)
    loss = torch.nn.functional.mse_loss(ty, torch.tensor(yd))
    loss.backward()

    np.testing.assert_allclose(np.asarray(grads["conv"]["kernel"]),
                               tk.grad.numpy(), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(grads["conv"]["bias"]),
                               tb.grad.numpy(), rtol=1e-4, atol=1e-5)
