"""LR schedules (runtime/schedule.py): shape of each curve, and that the
scheduled lr actually reaches the jitted update (net-new vs the
reference's fixed-lr optimizer kernels, optimizer.cc:93-358)."""

import numpy as np
import pytest

from flexflow_tpu import (AdamOptimizer, ConstantSchedule, ExponentialDecay,
                          FFConfig, FFModel, LossType,
                          SGDOptimizer, StepDecay, WarmupCosine, WarmupLinear)


def test_curve_shapes():
    wc = WarmupCosine(warmup_steps=10, total_steps=100)
    assert float(wc(0)) == 0.0
    np.testing.assert_allclose(float(wc(5)), 0.5)
    np.testing.assert_allclose(float(wc(10)), 1.0)
    np.testing.assert_allclose(float(wc(55)), 0.5, atol=1e-6)
    np.testing.assert_allclose(float(wc(100)), 0.0, atol=1e-6)
    np.testing.assert_allclose(float(wc(500)), 0.0, atol=1e-6)  # held

    wl = WarmupLinear(warmup_steps=0, total_steps=10, final_scale=0.5)
    np.testing.assert_allclose(float(wl(5)), 0.75)
    sd = StepDecay(step_size=3, gamma=0.1)
    np.testing.assert_allclose(float(sd(2)), 1.0)
    np.testing.assert_allclose(float(sd(3)), 0.1)
    np.testing.assert_allclose(float(sd(7)), 0.01, rtol=1e-5)
    ed = ExponentialDecay(0.9)
    np.testing.assert_allclose(float(ed(2)), 0.81, rtol=1e-6)
    assert float(ConstantSchedule()(123)) == 1.0

    with pytest.raises(AssertionError):
        WarmupCosine(warmup_steps=10, total_steps=10)
    with pytest.raises(TypeError):
        SGDOptimizer(lr=0.1, schedule="cosine")
    with pytest.raises(TypeError):
        SGDOptimizer(lr=0.1, schedule=WarmupCosine)  # forgotten parens


def _one_param_model(optimizer):
    cfg = FFConfig(batch_size=4, mesh_shape={"data": 2})
    ff = FFModel(cfg)
    x = ff.create_tensor([4, 3], name="input")
    t = ff.dense(x, 1, use_bias=False, name="w")
    ff.compile(optimizer, LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
               [], final_tensor=t)
    return ff


def test_scheduled_lr_reaches_the_update():
    """StepDecay(1, 0.5): each SGD step's effective lr halves. With a
    constant gradient (identity loss vs fixed data), per-step deltas
    must halve too."""
    batch = {"input": np.ones((4, 3), np.float32),
             "label": np.zeros((4, 1), np.float32)}
    ff = _one_param_model(SGDOptimizer(lr=0.1, schedule=StepDecay(1, 0.5)))
    w0 = ff.get_weights("w").copy()
    deltas = []
    for _ in range(3):
        before = ff.get_weights("w").copy()
        ff._run_train_step(batch)
        after = ff.get_weights("w")
        deltas.append(np.abs(after - before).sum())
    # gradient changes as w moves, so compare against an unscheduled twin
    ff_c = _one_param_model(SGDOptimizer(lr=0.1))
    ff_c.set_weights("w", "kernel", w0)
    base = []
    for _ in range(3):
        before = ff_c.get_weights("w").copy()
        ff_c._run_train_step(batch)
        after = ff_c.get_weights("w")
        base.append(np.abs(after - before).sum())
    # step 0 scales match (scale 1.0); later steps shrink vs the twin
    np.testing.assert_allclose(deltas[0], base[0], rtol=1e-5)
    assert deltas[1] < base[1] * 0.75
    assert deltas[2] < base[2] * 0.5


def test_keras_optimizer_schedule_passthrough():
    from flexflow_tpu.keras.optimizers import SGD, Adam, get_optimizer
    from flexflow_tpu.runtime.schedule import ConstantSchedule

    s = StepDecay(5, 0.5)
    assert get_optimizer(SGD(learning_rate=0.1, schedule=s)).schedule is s
    assert get_optimizer(Adam(schedule=s)).schedule is s
    assert isinstance(get_optimizer(SGD()).schedule, ConstantSchedule)


def test_adam_schedule_smoke():
    batch = {"input": np.ones((4, 3), np.float32),
             "label": np.zeros((4, 1), np.float32)}
    ff = _one_param_model(AdamOptimizer(
        alpha=0.01, schedule=WarmupCosine(warmup_steps=2, total_steps=10)))
    w0 = ff.get_weights("w").copy()
    ff._run_train_step(batch)   # t=0 -> scale 0: no movement
    np.testing.assert_allclose(ff.get_weights("w"), w0, atol=1e-7)
    ff._run_train_step(batch)   # t=1 -> scale 0.5: moves
    assert np.abs(ff.get_weights("w") - w0).sum() > 0
