"""FusedOp / apply_fusion tests (reference: FFModel::apply_fusion
model.cc:1404-1475 + FusedOp fused.cu — fusion must not change semantics)."""

import numpy as np

from flexflow_tpu import (ActiMode, FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer)
from flexflow_tpu.ops.fused import FusedOp


def _build(fusion: bool):
    cfg = FFConfig(batch_size=8, mesh_shape={"data": 2}, seed=7,
                   perform_fusion=fusion)
    ff = FFModel(cfg)
    x = ff.create_tensor([8, 32], name="x")
    t = ff.dense(x, 64, name="fc1")          # no built-in activation
    t = ff.relu(t, name="act1")              # fusable follower
    t = ff.scalar_multiply(t, 0.5, name="scale1")  # second follower
    t = ff.dense(t, 32, name="fc2")
    t = ff.gelu(t, name="act2")
    out = ff.dense(t, 10, name="fc3")
    return ff, out


def test_fusion_shrinks_graph_and_preserves_forward():
    ff_plain, out_plain = _build(fusion=False)
    ff_fused, out_fused = _build(fusion=True)
    ff_plain.compile(optimizer=None, final_tensor=out_plain)
    ff_fused.compile(optimizer=None, final_tensor=out_fused)

    n_plain = len(ff_plain.ops)
    n_fused = len(ff_fused.ops)
    assert n_fused == n_plain - 3  # act1+scale1 onto fc1, act2 onto fc2
    fused_ops = [op for op in ff_fused.ops if isinstance(op, FusedOp)]
    assert len(fused_ops) == 2
    assert {op.name for op in fused_ops} == {"fc1", "fc2"}

    # identical param keys (leader names) => identical init => identical math
    xb = {"x": np.random.RandomState(0).randn(8, 32).astype(np.float32)}
    y_plain = np.asarray(ff_plain.predict(xb))
    y_fused = np.asarray(ff_fused.predict(xb))
    np.testing.assert_allclose(y_plain, y_fused, rtol=1e-6, atol=1e-6)


def test_fusion_trains():
    ff, out = _build(fusion=True)
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=out)
    rs = np.random.RandomState(0)
    loss, _ = ff._run_train_step(
        {"x": rs.randn(8, 32).astype(np.float32),
         "label": rs.randint(0, 10, (8, 1)).astype(np.int32)})
    assert np.isfinite(float(loss))


def test_fusion_respects_multi_consumer():
    """A tensor with two consumers must not become a fused intermediate."""
    cfg = FFConfig(batch_size=4, mesh_shape={"data": 1}, perform_fusion=True)
    ff = FFModel(cfg)
    x = ff.create_tensor([4, 16], name="x")
    t = ff.dense(x, 16, name="fc1")
    a = ff.relu(t, name="act")      # consumer 1 of fc1:out
    b = ff.add(t, a, name="resid")  # consumer 2 of fc1:out
    ff.compile(optimizer=None, final_tensor=b)
    assert not any(isinstance(op, FusedOp) and op.name == "fc1"
                   for op in ff.ops)
    y = ff.predict({"x": np.zeros((4, 16), np.float32)})
    assert np.asarray(y).shape == (4, 16)


def test_fusion_blocked_by_conflicting_strategy():
    from flexflow_tpu.parallel.pconfig import ParallelConfig

    cfg = FFConfig(batch_size=8, mesh_shape={"data": 2}, perform_fusion=True)
    # explicit conflicting entry on the follower blocks fusion
    cfg.strategies["act1"] = ParallelConfig.from_axis_map(
        2, {"data": 2}, {"data": None})
    cfg.strategies["fc1"] = ParallelConfig.from_axis_map(
        2, {"data": 2}, {"data": 0})
    ff = FFModel(cfg)
    x = ff.create_tensor([8, 32], name="x")
    t = ff.dense(x, 64, name="fc1")
    t = ff.relu(t, name="act1")
    out = ff.dense(t, 10, name="fc2")
    ff.compile(optimizer=None, final_tensor=out)
    assert not any(isinstance(op, FusedOp) for op in ff.ops)
