"""Pipeline parallelism and MoE tests on the emulated mesh."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from flexflow_tpu.parallel.mesh import make_mesh
from flexflow_tpu.parallel.pipeline import pipeline


def test_gpipe_matches_serial():
    """4-stage pipeline of dense blocks == serial application."""
    n_stage, b, d = 4, 16, 8
    rs = np.random.RandomState(0)
    ws = rs.randn(n_stage, d, d).astype(np.float32) * 0.3
    bs = rs.randn(n_stage, d).astype(np.float32) * 0.1
    x = rs.randn(b, d).astype(np.float32)

    def stage_fn(params, t):
        w, bias = params
        return jnp.tanh(t @ w + bias)

    mesh = make_mesh({"pipe": n_stage})
    got = np.asarray(pipeline(stage_fn, (jnp.asarray(ws), jnp.asarray(bs)),
                              jnp.asarray(x), mesh, num_microbatches=4))
    want = x
    for i in range(n_stage):
        want = np.tanh(want @ ws[i] + bs[i])
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_gpipe_grads_flow():
    n_stage, b, d = 4, 8, 4
    rs = np.random.RandomState(1)
    ws = jnp.asarray(rs.randn(n_stage, d, d).astype(np.float32) * 0.3)
    x = jnp.asarray(rs.randn(b, d).astype(np.float32))
    mesh = make_mesh({"pipe": n_stage})

    def stage_fn(w, t):
        return jnp.tanh(t @ w)

    def loss(w):
        return jnp.sum(pipeline(stage_fn, w, x, mesh) ** 2)

    g = np.asarray(jax.jit(jax.grad(loss))(ws))
    assert np.isfinite(g).all()
    # every stage's weights must receive gradient
    for i in range(n_stage):
        assert np.abs(g[i]).max() > 0, f"stage {i} got zero grad"


def test_moe_op_forward_and_training():
    from flexflow_tpu import (ActiMode, FFConfig, FFModel, LossType,
                              MetricsType, SGDOptimizer, SingleDataLoader)

    cfg = FFConfig(batch_size=32, epochs=4, mesh_shape={"data": 2, "expert": 4})
    ff = FFModel(cfg)
    x = ff.create_tensor([32, 16], name="x")
    t = ff.dense(x, 32, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.moe(t, num_experts=4, hidden_dim=64, k=2, name="moe")
    t = ff.dense(t, 4, name="out")
    ff.compile(SGDOptimizer(lr=0.1),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY])

    # expert weights sharded over the expert axis
    assert "expert" in str(ff.params["moe"]["w_in"].sharding.spec)

    rs = np.random.RandomState(0)
    centers = rs.randn(4, 16) * 3
    y = rs.randint(0, 4, 512)
    xd = (centers[y] + rs.randn(512, 16)).astype(np.float32)
    SingleDataLoader(ff, x, xd)
    SingleDataLoader(ff, ff.label_tensor, y.astype(np.int32).reshape(-1, 1))
    perf = ff.fit(verbose=False)
    assert perf.accuracy > 0.85, perf.accuracy


def test_moe_routes_to_multiple_experts():
    from flexflow_tpu import FFConfig, FFModel

    cfg = FFConfig(batch_size=64, mesh_shape={"data": 1})
    ff = FFModel(cfg)
    x = ff.create_tensor([64, 8], name="x")
    out = ff.moe(x, num_experts=4, hidden_dim=16, k=1, name="moe")
    ff.compile(optimizer=None, final_tensor=out)
    xv = np.random.RandomState(3).randn(64, 8).astype(np.float32)
    y = np.asarray(ff.predict({"x": xv}))
    assert y.shape == (64, 8)
    assert np.isfinite(y).all()
    # with random routing, output should be nonzero for most tokens
    assert (np.abs(y).sum(axis=1) > 0).mean() > 0.5


def test_transformer_pipeline_stack_matches_serial():
    """Graph-level PP: the stacked-layer transformer op under a 'pipe' mesh
    must match the single-device lax.scan path bit-for-bit (same weights)."""
    from flexflow_tpu import FFConfig, FFModel

    B, S, D, H, L = 4, 8, 16, 2, 4
    rs = np.random.RandomState(3)
    x = rs.randn(B, S, D).astype(np.float32)

    def build(mesh_shape):
        cfg = FFConfig(batch_size=B, mesh_shape=mesh_shape, seed=11)
        ff = FFModel(cfg)
        xt = ff.create_tensor([B, S, D], name="x")
        out = ff.transformer_pipeline_stack(xt, L, H, causal=True,
                                            name="stack")
        ff.compile(optimizer=None, final_tensor=out)
        return ff

    ff1 = build({"data": 1})
    y_serial = np.asarray(ff1.predict({"x": x}))
    assert y_serial.shape == (B, S, D)

    ff2 = build({"pipe": 4, "data": 1})
    for spec in ff2.ops[-1].weight_specs():
        ff2.set_weights("stack", spec.name, ff1.get_weights("stack", spec.name))
    y_pipe = np.asarray(ff2.predict({"x": x}))
    np.testing.assert_allclose(y_pipe, y_serial, rtol=2e-4, atol=2e-5)

    # stage weights actually live sharded over 'pipe'
    sh = ff2.params["stack"]["wq"].sharding.spec
    assert sh[0] == "pipe", sh


def test_transformer_pipeline_stack_trains_dp_x_pp():
    """dp x pp composition: train step over {'pipe': 2, 'data': 2}."""
    from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                              SGDOptimizer)

    B, S, D, H, L = 8, 8, 16, 2, 4
    cfg = FFConfig(batch_size=B, mesh_shape={"pipe": 2, "data": 2}, seed=0)
    ff = FFModel(cfg)
    xt = ff.create_tensor([B, S, D], name="x")
    t = ff.transformer_pipeline_stack(xt, L, H, name="stack")
    out = ff.dense(t, 8, name="head")
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=out)
    rs = np.random.RandomState(0)
    w0 = np.asarray(ff.get_weights("stack", "wq")).copy()
    loss, _ = ff._run_train_step({
        "x": rs.randn(B, S, D).astype(np.float32),
        "label": rs.randint(0, 8, (B, S, 1)).astype(np.int32)})
    assert np.isfinite(float(loss))
    w1 = np.asarray(ff.get_weights("stack", "wq"))
    assert np.abs(w1 - w0).max() > 0  # grads flowed through the ring


def test_pipeline_block_flash_matches_einsum(monkeypatch):
    """The pipeline stack's in-block attention must produce the same
    numerics whether the Pallas flash path or the einsum path runs."""
    import jax
    import jax.numpy as jnp

    from flexflow_tpu.ops.pipelined import _block

    B, S, D, H = 2, 128, 32, 4
    rs = np.random.RandomState(11)
    h = jnp.asarray(rs.randn(B, S, D).astype(np.float32))
    p = {}
    for n, shape in (("wq", (D, D)), ("wk", (D, D)), ("wv", (D, D)),
                     ("wo", (D, D)), ("w1", (D, 4 * D)), ("w2", (4 * D, D))):
        p[n] = jnp.asarray(rs.randn(*shape).astype(np.float32) * 0.05)
    for n, shape in (("bq", (D,)), ("bk", (D,)), ("bv", (D,)), ("bo", (D,)),
                     ("b1", (4 * D,)), ("b2", (D,))):
        p[n] = jnp.zeros(shape, jnp.float32)
    p["ln1_scale"] = p["ln2_scale"] = jnp.ones((D,), jnp.float32)
    p["ln1_bias"] = p["ln2_bias"] = jnp.zeros((D,), jnp.float32)

    # use_flash=False forces the einsum baseline on ANY backend (the
    # config opt-out path), so this comparison is meaningful on real TPU too
    y_einsum = np.asarray(_block(p, h, H, causal=True, use_flash=False))
    monkeypatch.setenv("FF_FORCE_FLASH_ATTENTION", "1")
    y_flash = np.asarray(_block(p, h, H, causal=True, use_flash=True))
    np.testing.assert_allclose(y_flash, y_einsum, rtol=2e-4, atol=2e-5)
    # and gradients through the block agree between the two paths
    def loss(fn_flash):
        return lambda hh: jnp.sum(
            _block(p, hh, H, causal=True, use_flash=fn_flash) ** 2)

    gf = jax.grad(loss(True))(h)
    ge = jax.grad(loss(False))(h)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(ge), rtol=2e-4,
                               atol=2e-5)
