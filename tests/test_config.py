"""FFConfig validation + parse_args coverage (config.py).

Every `__post_init__` guard exists because a typo'd knob used to surface
as an opaque failure deep inside compile/XLA (or worse, silently ran the
wrong configuration); each one gets a pinned test so a refactor cannot
drop the guard. All host-side, sub-second."""

import pytest

from flexflow_tpu.config import FFConfig


def _ok(**kw):
    return FFConfig(mesh_shape={"data": 1}, **kw)


# ------------------------------------------------------------- validation


def test_defaults_valid():
    cfg = _ok()
    assert cfg.batch_size == 64 and cfg.num_devices == 1


def test_grad_accum_validation():
    with pytest.raises(ValueError, match="grad_accum_steps"):
        _ok(grad_accum_steps=0)
    with pytest.raises(ValueError, match="not divisible"):
        _ok(batch_size=10, grad_accum_steps=3)
    assert _ok(batch_size=12, grad_accum_steps=3).grad_accum_steps == 3


def test_strategy_lint_validation():
    with pytest.raises(ValueError, match="strategy_lint"):
        _ok(strategy_lint="aggressive")
    for mode in ("off", "warn", "strict"):
        assert _ok(strategy_lint=mode).strategy_lint == mode


def test_on_nonfinite_validation():
    with pytest.raises(ValueError, match="on_nonfinite"):
        _ok(on_nonfinite="retry")
    for mode in ("none", "skip", "backoff"):
        assert _ok(on_nonfinite=mode).on_nonfinite == mode


def test_negative_resilience_knobs_rejected():
    with pytest.raises(ValueError):
        _ok(nonfinite_rewind_after=-1)
    with pytest.raises(ValueError):
        _ok(checkpoint_every=-1)


def test_overlap_knob_validation():
    with pytest.raises(ValueError, match="prefetch_depth"):
        _ok(prefetch_depth=-1)
    with pytest.raises(ValueError, match="dispatch_ahead"):
        _ok(dispatch_ahead=-2)
    cfg = _ok(prefetch_depth=0, dispatch_ahead=0)  # both legal: sync mode
    assert cfg.prefetch_depth == 0 and cfg.dispatch_ahead == 0


def test_loss_scale_validation():
    with pytest.raises(ValueError, match="loss_scale"):
        _ok(loss_scale=0.0)
    with pytest.raises(ValueError, match="loss_scale"):
        _ok(loss_scale=-2.0)
    with pytest.raises(ValueError, match="growth_interval"):
        _ok(loss_scale_growth_interval=0)


def test_serving_knob_validation():
    with pytest.raises(ValueError):
        _ok(serve_slots=0)
    with pytest.raises(ValueError):
        _ok(kv_page_size=0)
    with pytest.raises(ValueError):
        _ok(kv_pages=-1)
    assert _ok(kv_pages=0).kv_pages == 0  # 0 = derive


def test_decode_buckets_validation():
    for bad in ([], [0, 8], [8, 8], [16, 8]):
        with pytest.raises(ValueError, match="decode_buckets"):
            _ok(decode_buckets=bad)
    assert _ok(decode_buckets=[8, 16, 64]).decode_buckets == [8, 16, 64]


def test_dtype_validation():
    with pytest.raises(ValueError, match="compute_dtype"):
        _ok(compute_dtype="fp16")
    with pytest.raises(ValueError, match="master_dtype"):
        _ok(master_dtype="bf16")  # exact spelling required
    cfg = _ok(compute_dtype="bfloat16", master_dtype="bfloat16")
    assert cfg.compute_dtype == cfg.master_dtype == "bfloat16"


def test_num_devices_derived_from_mesh_without_backend():
    cfg = FFConfig(mesh_shape={"data": 4, "model": 2})
    assert cfg.num_devices == 8
    assert cfg.workers_per_node == 8 and cfg.num_nodes == 1


def test_default_mesh_from_num_devices():
    cfg = FFConfig(num_devices=4)
    assert cfg.mesh_shape == {"data": 4}


# ------------------------------------------------------------- parse_args


def test_parse_args_defaults():
    cfg = FFConfig.parse_args([])
    assert cfg.batch_size == 64 and cfg.epochs == 1
    assert cfg.search_budget == 0 and cfg.fsdp_axis == ""


def test_parse_args_training_flags():
    cfg = FFConfig.parse_args(["-e", "3", "-b", "32", "--lr", "0.5",
                               "--wd", "0.01"])
    assert (cfg.epochs, cfg.batch_size) == (3, 32)
    assert cfg.learning_rate == 0.5 and cfg.weight_decay == 0.01


def test_parse_args_mesh():
    cfg = FFConfig.parse_args(["--mesh", "data=4,model=2"])
    assert cfg.mesh_shape == {"data": 4, "model": 2}
    assert cfg.num_devices == 8


def test_parse_args_bad_mesh_errors():
    for bad in ("data", "data=", "data=0", "data=x", "=4"):
        with pytest.raises(SystemExit):
            FFConfig.parse_args(["--mesh", bad])


def test_parse_args_fsdp_const():
    assert FFConfig.parse_args(["--fsdp"]).fsdp_axis == "data"
    assert FFConfig.parse_args(["--fsdp", "model"]).fsdp_axis == "model"
    assert FFConfig.parse_args([]).fsdp_axis == ""


def test_parse_args_search_and_cost_modes():
    cfg = FFConfig.parse_args(["--budget", "10", "--alpha", "0.1"])
    assert cfg.search_budget == 10 and cfg.search_alpha == 0.1
    assert FFConfig.parse_args(["--measure-costs"]).measure_search_costs \
        == "measure"
    assert FFConfig.parse_args(["--analyze-costs"]).measure_search_costs \
        == "analyze"
    assert FFConfig.parse_args([]).measure_search_costs is False


def test_parse_args_checkpoint_flags():
    cfg = FFConfig.parse_args(["--checkpoint-dir", "/tmp/ck",
                               "--checkpoint-every", "5"])
    assert cfg.checkpoint_dir == "/tmp/ck" and cfg.checkpoint_every == 5


def test_parse_args_ignores_unknown():
    cfg = FFConfig.parse_args(["--totally-unknown-flag", "x", "-e", "2"])
    assert cfg.epochs == 2
