"""Weight tying (FFModel.tie_weights).

Reference parity: the NMT subsystem's SharedVariable (nmt/rnn.h:37-51) —
one logical weight behind many ops, gradients two-level-reduced into it.
Here the destination op's weight resolves from the source's storage at
trace time, so autodiff accumulates both ops' gradients into one array.
Modern use pinned below: tied embedding / lm_head decoder.
"""

import numpy as np
import pytest

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer)
from flexflow_tpu.ffconst import DataType

VOCAB, HIDDEN = 61, 32


def _tied_lm(mesh=None, tie=True):
    cfg = FFConfig(batch_size=4, mesh_shape=mesh or {"data": 2})
    ff = FFModel(cfg)
    toks = ff.create_tensor([4, 6], dtype=DataType.DT_INT32, name="input")
    t = ff.embedding(toks, VOCAB, HIDDEN, name="embed")
    t = ff.multihead_attention(t, t, t, HIDDEN, 4, causal=True, bias=False,
                               rope=True, name="attn")
    t = ff.rms_norm(t, name="ln")
    logits = ff.dense(t, VOCAB, use_bias=False, name="lm_head")
    if tie:
        ff.tie_weights("lm_head", "kernel", "embed", "kernel", "transpose")
    ff.compile(SGDOptimizer(lr=0.1),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=logits)
    return ff


def test_tied_storage_and_grad_accumulation():
    ff = _tied_lm()
    assert "kernel" not in ff.params.get("lm_head", {})
    # get_weights resolves through the tie
    np.testing.assert_array_equal(ff.get_weights("lm_head", "kernel"),
                                  ff.get_weights("embed", "kernel").T)

    rs = np.random.RandomState(0)
    batch = {"input": rs.randint(0, VOCAB, (4, 6)).astype(np.int32),
             "label": rs.randint(0, VOCAB, (4, 6, 1)).astype(np.int32)}
    w0 = ff.get_weights("embed", "kernel").copy()
    loss0, _ = ff._run_train_step(batch)
    w1 = ff.get_weights("embed", "kernel")
    # the lm_head gradient reaches rows the embedding gather never touched
    # (only 24 distinct tokens were gathered; CE over VOCAB classes
    # back-propagates into EVERY row through the tied projection)
    changed_rows = (np.abs(w1 - w0).sum(axis=1) > 0).sum()
    assert changed_rows == VOCAB, f"only {changed_rows}/{VOCAB} rows updated"
    # and training still optimizes
    losses = [float(loss0)]
    for _ in range(10):
        l, _ = ff._run_train_step(batch)
        losses.append(float(l))
    assert losses[-1] < losses[0]


def test_tied_model_generates():
    ff = _tied_lm()
    prompt = np.arange(8, dtype=np.int32).reshape(2, 4) % VOCAB
    out = ff.generate(prompt, max_new_tokens=4)
    assert out.shape == (2, 8)
    # decode matches the naive full-forward rescoring loop (tie resolved
    # identically on both paths)
    seq = prompt.copy()
    for _ in range(4):
        nxt = np.asarray(ff.predict({"input": seq}))[:, -1].argmax(-1)
        seq = np.concatenate([seq, nxt[:, None].astype(np.int32)], axis=1)
    np.testing.assert_array_equal(out, seq)


def test_untied_differs():
    """Sanity: tying actually changes the model (same seed, different
    first-step loss trajectory because lm_head == embed.T)."""
    a, b = _tied_lm(tie=True), _tied_lm(tie=False)
    rs = np.random.RandomState(1)
    batch = {"input": rs.randint(0, VOCAB, (4, 6)).astype(np.int32),
             "label": rs.randint(0, VOCAB, (4, 6, 1)).astype(np.int32)}
    la, _ = a._run_train_step(batch)
    lb, _ = b._run_train_step(batch)
    assert abs(float(la) - float(lb)) > 1e-6


def test_llama_tie_embeddings_flag():
    from flexflow_tpu.models.llama import llama_lm

    cfg = FFConfig(batch_size=2, mesh_shape={"data": 2})
    ff = FFModel(cfg)
    _, logits = llama_lm(ff, 2, seq_len=8, hidden=32, layers=1, heads=2,
                         vocab_size=VOCAB, tie_embeddings=True)
    ff.compile(SGDOptimizer(lr=0.05),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=logits)
    assert "kernel" not in ff.params.get("lm_head", {})
    rs = np.random.RandomState(2)
    batch = {"input": rs.randint(0, VOCAB, (2, 8)).astype(np.int32),
             "label": rs.randint(0, VOCAB, (2, 8, 1)).astype(np.int32)}
    l, _ = ff._run_train_step(batch)
    assert np.isfinite(float(l))


def test_tie_validation():
    cfg = FFConfig(batch_size=4, mesh_shape={"data": 2})
    ff = FFModel(cfg)
    toks = ff.create_tensor([4, 6], dtype=DataType.DT_INT32, name="input")
    t = ff.embedding(toks, VOCAB, HIDDEN, name="embed")
    logits = ff.dense(t, VOCAB, use_bias=False, name="head")
    with pytest.raises(ValueError, match="no op named"):
        ff.tie_weights("nope", "kernel", "embed", "kernel")
    with pytest.raises(ValueError, match="no weight"):
        ff.tie_weights("head", "bias", "embed", "kernel")
    with pytest.raises(ValueError, match="shape mismatch"):
        ff.tie_weights("head", "kernel", "embed", "kernel", "same")
    ff.tie_weights("head", "kernel", "embed", "kernel", "transpose")
    with pytest.raises(ValueError, match="already tied"):
        ff.tie_weights("head", "kernel", "embed", "kernel", "transpose")
    with pytest.raises(ValueError, match="SOURCE of an existing tie"):
        # embed.kernel is the source of head's tie; demoting it to a
        # destination would orphan both storages
        ff.dense(t, VOCAB, use_bias=False, name="head2")
        ff.tie_weights("embed", "kernel", "head2", "kernel", "transpose")
    ff.compile(final_tensor=logits)
    with pytest.raises(ValueError, match="tied"):
        ff.set_weights("head", "kernel", np.zeros((HIDDEN, VOCAB), np.float32))
    with pytest.raises(ValueError, match="before compile"):
        ff.tie_weights("head2", "kernel", "embed", "kernel", "transpose")


def test_profile_step_resolves_ties():
    from flexflow_tpu.runtime.profiler import profile_step

    ff = _tied_lm()
    rs = np.random.RandomState(3)
    rows = profile_step(ff, {"input": rs.randint(0, VOCAB, (4, 6))
                             .astype(np.int32)})
    assert any(r["op"] == "lm_head" for r in rows)
