"""KV-cache autoregressive generation (runtime/generation.py).

Correctness anchor: the decode path must produce EXACTLY the same logits
as the training-graph forward re-run on the growing prefix (the
reference's only inference mode, CompMode::COMP_MODE_INFERENCE) — teacher
forcing compares them position by position, covering RoPE position
offsets, GQA cache grouping, and the causal cache mask.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.models.llama import llama_lm
from flexflow_tpu.parallel.pconfig import ParallelConfig
from flexflow_tpu.runtime.generation import Generator

VOCAB = 89


def build_llama(mesh, strategies=None, kv_heads=2):
    cfg = FFConfig(batch_size=2, mesh_shape=dict(mesh))
    if strategies:
        cfg.strategies = dict(strategies)
    ff = FFModel(cfg)
    tokens, logits = llama_lm(ff, 2, seq_len=16, hidden=64, layers=2,
                              heads=4, kv_heads=kv_heads, vocab_size=VOCAB)
    ff.compile(final_tensor=logits)
    return ff


def full_logits(ff, toks):
    return np.asarray(ff.predict({"input": toks.astype(np.int32)}))


def test_teacher_forcing_logit_parity():
    """Prefill + single-token decode steps reproduce the full-forward
    logits at every position (GQA 4->2 heads + RoPE)."""
    ff = build_llama({"data": 2})
    rs = np.random.RandomState(0)
    toks = rs.randint(0, VOCAB, (2, 10)).astype(np.int32)
    ref = full_logits(ff, toks)  # (B, 10, V)

    gen = Generator(ff)
    s0 = 4
    caches = {op.name: op.init_cache(2, 10, jnp.float32)
              for op in gen.attn_ops}
    logits, caches = jax.jit(
        lambda p, s, t, c: gen._walk(p, s, t, c, None))(
            ff.params, ff.bn_state, jnp.asarray(toks[:, :s0]), caches)
    np.testing.assert_allclose(np.asarray(logits), ref[:, :s0], atol=2e-4,
                               rtol=2e-4)
    # the production prefill narrows the tail to the last position —
    # logits must equal the full-walk logits at that position
    caches_lo = {op.name: op.init_cache(2, 10, jnp.float32)
                 for op in gen.attn_ops}
    lo, _ = jax.jit(lambda p, s, t, c: gen._walk(p, s, t, c, None,
                                                 last_only=True))(
        ff.params, ff.bn_state, jnp.asarray(toks[:, :s0]), caches_lo)
    assert lo.shape[1] == 1
    np.testing.assert_allclose(np.asarray(lo)[:, 0], ref[:, s0 - 1],
                               atol=2e-4, rtol=2e-4)

    dec = jax.jit(lambda p, s, t, c, pos: gen._walk(p, s, t, c, pos))
    for pos in range(s0, 10):
        logits, caches = dec(ff.params, ff.bn_state,
                             jnp.asarray(toks[:, pos:pos + 1]), caches,
                             pos)
        np.testing.assert_allclose(np.asarray(logits)[:, 0], ref[:, pos],
                                   atol=2e-4, rtol=2e-4,
                                   err_msg=f"decode position {pos}")


def test_greedy_generate_matches_naive_rescoring():
    """model.generate (one jitted prefill+scan program) equals the naive
    loop that re-runs the full forward on the growing prefix and argmaxes
    the last position."""
    ff = build_llama({"data": 2})
    rs = np.random.RandomState(1)
    prompt = rs.randint(0, VOCAB, (2, 5)).astype(np.int32)

    out = ff.generate(prompt, max_new_tokens=6)
    assert out.shape == (2, 11)
    assert (out[:, :5] == prompt).all()

    seq = prompt.copy()
    for _ in range(6):
        nxt = full_logits(ff, seq)[:, -1].argmax(-1).astype(np.int32)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, seq)


def test_generate_under_head_sharded_tp():
    """Head-split TP strategy (attention dim-2 on 'model', GQA kv_heads=2
    over degree 2): decode numerics must match the data-parallel run."""
    prompt = np.arange(10, dtype=np.int32).reshape(2, 5) % VOCAB
    ff_dp = build_llama({"data": 2})
    out_dp = ff_dp.generate(prompt, max_new_tokens=5)

    mesh = {"data": 2, "model": 2}
    strategies = {}
    for i in range(2):
        strategies[f"attn_{i}"] = ParallelConfig.from_axis_map(
            3, mesh, {"data": 0, "model": 2})
        strategies[f"ffn_gate_{i}"] = ParallelConfig.from_axis_map(
            3, mesh, {"data": 0, "model": 2})
    ff_tp = build_llama(mesh, strategies)
    # same params: copy from the DP model so outputs are comparable
    for op_name, ws in ff_dp.params.items():
        for w_name, w in ws.items():
            ff_tp.set_weights(op_name, w_name, np.asarray(w))
    out_tp = ff_tp.generate(prompt, max_new_tokens=5)
    np.testing.assert_array_equal(out_dp, out_tp)


def test_mha_bias_no_rope_decoder():
    """Plain MHA (bias, no RoPE, no GQA) graphs decode too: attention is
    position-blind apart from the causal mask, so cache decode must match
    full forward."""
    cfg = FFConfig(batch_size=2, mesh_shape={"data": 2})
    ff = FFModel(cfg)
    from flexflow_tpu.ffconst import DataType

    toks = ff.create_tensor([2, 12], dtype=DataType.DT_INT32, name="input")
    t = ff.embedding(toks, VOCAB, 32, name="embed")
    a = ff.layer_norm(t, name="ln1")
    a = ff.multihead_attention(a, a, a, 32, 4, causal=True, bias=True,
                               name="attn")
    t = ff.add(t, a, name="res")
    logits = ff.dense(t, VOCAB, name="head")
    ff.compile(final_tensor=logits)

    rs = np.random.RandomState(2)
    prompt = rs.randint(0, VOCAB, (2, 4)).astype(np.int32)
    out = ff.generate(prompt, max_new_tokens=4)
    seq = prompt.copy()
    for _ in range(4):
        nxt = np.asarray(ff.predict({"input": seq}))[:, -1].argmax(-1)
        seq = np.concatenate([seq, nxt[:, None].astype(np.int32)], axis=1)
    np.testing.assert_array_equal(out, seq)


@pytest.mark.slow  # 8 s; eos draws sampled by the sweep
def test_eos_padding_and_sampling_shapes():
    ff = build_llama({"data": 2})
    rs = np.random.RandomState(3)
    prompt = rs.randint(0, VOCAB, (2, 4)).astype(np.int32)
    # discover what greedy emits first, then declare it the eos token:
    # every later token in that row must be pad
    first = ff.generate(prompt, max_new_tokens=5)
    eos = int(first[0, 4])
    out = ff.generate(prompt, max_new_tokens=5, eos_token_id=eos,
                      pad_token_id=0)
    row = out[0, 4:]
    hits = np.where(row == eos)[0]
    assert hits.size, "eos token must appear where greedy emitted it"
    assert (row[hits[0] + 1:] == 0).all()

    # temperature sampling: valid token range, deterministic under a seed
    s1 = ff.generate(prompt, max_new_tokens=5, temperature=0.8, top_k=10,
                     seed=7)
    s2 = ff.generate(prompt, max_new_tokens=5, temperature=0.8, top_k=10,
                     seed=7)
    np.testing.assert_array_equal(s1, s2)
    assert ((s1 >= 0) & (s1 < VOCAB)).all()


def test_beam_search_finds_higher_likelihood_than_greedy():
    """Beam K=4 must return sequences whose total logp (rescored by the
    full forward) is >= the greedy sequence's — beam search with
    length_penalty=0 explores a superset of the greedy path. Also: K=1
    beam == greedy exactly."""
    ff = build_llama({"data": 2})
    rs = np.random.RandomState(5)
    prompt = rs.randint(0, VOCAB, (2, 4)).astype(np.int32)

    greedy = ff.generate(prompt, max_new_tokens=5)
    beam1 = ff.generate(prompt, max_new_tokens=5, num_beams=1)
    np.testing.assert_array_equal(greedy, beam1)

    beam4 = ff.generate(prompt, max_new_tokens=5, num_beams=4)
    assert beam4.shape == greedy.shape

    def total_logp(seq):
        lg = full_logits(ff, seq)  # (B, S, V)
        logp = np.log(np.exp(lg - lg.max(-1, keepdims=True)).sum(-1))
        logp = lg - lg.max(-1, keepdims=True) - logp[..., None]
        s0 = 4
        tot = np.zeros(seq.shape[0])
        for pos in range(s0, seq.shape[1]):
            tot += logp[np.arange(seq.shape[0]), pos - 1, seq[:, pos]]
        return tot

    lp_beam, lp_greedy = total_logp(beam4), total_logp(greedy)
    assert (lp_beam >= lp_greedy - 1e-4).all(), (lp_beam, lp_greedy)


@pytest.mark.slow  # 7 s; ragged draws sampled by the sweep
def test_ragged_prompts_match_per_row_runs():
    """Ragged right-padded prompts: each row's generation must equal the
    run of that row alone at its true (unpadded) length — pad k/v slots
    masked out, RoPE continuing from the row's own length."""
    ff = build_llama({"data": 2})
    rs = np.random.RandomState(7)
    full = rs.randint(1, VOCAB, (2, 9)).astype(np.int32)
    lengths = np.array([5, 9], np.int32)
    padded = full.copy()
    padded[0, 5:] = 0  # right-pad row 0

    out = ff.generate(padded, max_new_tokens=5, prompt_lengths=lengths)
    assert out.shape == (2, 14)

    for b in range(2):
        solo = ff.generate(full[b:b + 1, :lengths[b]], max_new_tokens=5)
        np.testing.assert_array_equal(
            out[b, 9:], solo[0, lengths[b]:],
            err_msg=f"row {b} (len {lengths[b]}) diverged from solo run")

    # validation
    import pytest as _pytest

    with _pytest.raises(ValueError, match="prompt_lengths"):
        ff.generate(padded, 3, prompt_lengths=np.array([5], np.int32))
    with _pytest.raises(ValueError, match="prompt_lengths"):
        ff.generate(padded, 3, prompt_lengths=np.array([0, 9], np.int32))
    # beam validates lengths the same way (supported since r5)
    with _pytest.raises(ValueError, match="prompt_lengths"):
        ff.generate(padded, 3, num_beams=2,
                    prompt_lengths=np.array([5], np.int32))


@pytest.mark.slow  # 16 s; beam+ragged sampled by the sweep
def test_ragged_beam_matches_per_row_uniform_beam():
    """VERDICT r4 #4: beam search over ragged prompts. Each ragged row's
    beam decode must equal running that row ALONE with its true (unpadded)
    prompt — pins per-row prefill scoring position, RoPE offsets, and
    pad-slot cache masking through the beam lattice."""
    ff = build_llama({"data": 1})
    rs = np.random.RandomState(11)
    full = rs.randint(0, VOCAB, (3, 9)).astype(np.int32)
    lengths = np.array([4, 9, 6], np.int32)
    padded = full.copy()
    for b in range(3):
        padded[b, lengths[b]:] = 0

    for lp in (0.0, 1.0):
        out, score = ff.generate(padded, 5, num_beams=3, length_penalty=lp,
                                 prompt_lengths=lengths, return_scores=True)
        assert out.shape == (3, 14)
        for b in range(3):
            solo, s_solo = ff.generate(full[b:b + 1, :lengths[b]], 5,
                                       num_beams=3, length_penalty=lp,
                                       return_scores=True)
            np.testing.assert_array_equal(
                solo[0, lengths[b]:], out[b, 9:],
                err_msg=f"row {b} (len {lengths[b]}, lp {lp}) diverged")
            np.testing.assert_allclose(
                s_solo[0], score[b], rtol=1e-4, atol=1e-5,
                err_msg=f"row {b} beam score diverged (lp {lp})")


def _moe_decoder(batch, cap):
    from flexflow_tpu.ffconst import DataType

    cfg = FFConfig(batch_size=batch, mesh_shape={"data": 1})
    ff = FFModel(cfg)
    toks = ff.create_tensor([batch, 12], dtype=DataType.DT_INT32,
                            name="input")
    t = ff.embedding(toks, VOCAB, 32, name="embed")
    for i in range(2):
        a = ff.rms_norm(t, name=f"ln1_{i}")
        a = ff.multihead_attention(a, a, a, 32, 4, causal=True, bias=False,
                                   rope=True, name=f"attn_{i}")
        t = ff.add(t, a, name=f"res1_{i}")
        m = ff.moe(ff.rms_norm(t, name=f"ln2_{i}"), num_experts=4,
                   hidden_dim=64, k=2, capacity_factor=cap,
                   name=f"moe_{i}")
        t = ff.add(t, m, name=f"res2_{i}")
    logits = ff.dense(t, VOCAB, use_bias=False, name="lm_head")
    ff.compile(final_tensor=logits)
    return ff


@pytest.mark.slow  # 11 s; the sweep's gpt+MoE draws decode every run
def test_moe_decoder_generates():
    """Mixtral-style decoder (attention + MoE FFN blocks) decodes: with
    capacity high enough that the full forward drops nothing, teacher-
    forced decode logits equal the training-graph forward exactly."""
    ff = _moe_decoder(2, cap=8.0)
    rs = np.random.RandomState(11)
    prompt = rs.randint(0, VOCAB, (2, 5)).astype(np.int32)
    out = ff.generate(prompt, max_new_tokens=5)
    seq = prompt.copy()
    for _ in range(5):
        nxt = np.asarray(ff.predict({"input": seq}))[:, -1].argmax(-1)
        seq = np.concatenate([seq, nxt[:, None].astype(np.int32)], axis=1)
    np.testing.assert_array_equal(out, seq)


def test_moe_decode_rows_independent_under_tight_capacity():
    """Even with a TIGHT training capacity (drops in training), inference
    overrides capacity to the token count, so a batched generate equals
    each row's solo generate — capacity competition can never couple
    rows at inference. Weights are copied so batch-4 and batch-1 models
    share parameters."""
    ff4 = _moe_decoder(4, cap=0.5)
    ff1 = _moe_decoder(1, cap=0.5)
    for op, ws in ff4.params.items():
        for w, v in ws.items():
            ff1.set_weights(op, w, np.asarray(v))
    rs = np.random.RandomState(12)
    prompt = rs.randint(0, VOCAB, (4, 6)).astype(np.int32)
    out = ff4.generate(prompt, max_new_tokens=4)
    for b in range(4):
        solo = ff1.generate(prompt[b:b + 1], max_new_tokens=4)
        np.testing.assert_array_equal(out[b], solo[0],
                                      err_msg=f"row {b} coupled")


@pytest.mark.slow  # 19 s; int8 sampled by the sweep
def test_int8_weight_only_decode():
    """quantize='int8': decodes with int8 weights + per-channel scales.
    Lossy by design — assert the quantized greedy path produces valid
    tokens and mostly agrees with full precision on a short horizon, and
    that tied weights resolve through quantization."""
    ff = build_llama({"data": 2})
    rs = np.random.RandomState(13)
    prompt = rs.randint(0, VOCAB, (2, 5)).astype(np.int32)
    full = ff.generate(prompt, max_new_tokens=6)
    q = ff.generate(prompt, max_new_tokens=6, quantize="int8")
    assert q.shape == full.shape
    assert ((q >= 0) & (q < VOCAB)).all()
    agree = (q[:, 5:] == full[:, 5:]).mean()
    assert agree >= 0.5, f"int8 vs f32 token agreement only {agree}"

    # tied embeddings + int8 (dequant through the tie)
    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.models.llama import llama_lm

    cfg = FFConfig(batch_size=2, mesh_shape={"data": 2})
    ff2 = FFModel(cfg)
    _, logits = llama_lm(ff2, 2, seq_len=8, hidden=32, layers=1, heads=2,
                         vocab_size=VOCAB, tie_embeddings=True)
    ff2.compile(final_tensor=logits)
    out = ff2.generate(prompt, max_new_tokens=4, quantize="int8")
    assert out.shape == (2, 9)

    with pytest.raises(ValueError, match="quantize"):
        from flexflow_tpu.runtime.generation import Generator
        Generator(ff, quantize="int4")

    # the int8 cache must track weight updates: zero a layer's ffn and
    # the quantized generation must change
    ff.set_weights("ffn_down_0", "kernel",
                   np.zeros_like(ff.get_weights("ffn_down_0", "kernel")))
    q2 = ff.generate(prompt, max_new_tokens=6, quantize="int8")
    assert not np.array_equal(q, q2), \
        "stale int8 cache: weight update did not reach quantized decode"

    # cache hit on unchanged params; raw in-place mutation (not via
    # set_weights) also invalidates, caught by the leaf-identity check
    gen = next(g for k, g in ff._generators.items() if g.quantize == "int8")
    qp = gen._quantized_params()
    assert gen._quantized_params() is qp
    import jax.numpy as _jnp

    ff.params["lm_head"]["kernel"] = _jnp.asarray(
        ff.params["lm_head"]["kernel"]) * 1.0
    assert gen._quantized_params() is not qp, \
        "in-place params mutation did not invalidate the int8 cache"


@pytest.mark.slow  # 12 s; per-token scores are oracle-rescored by every sweep config
def test_return_scores():
    """return_scores: greedy scores are the model's own logp of each
    chosen token — rescoring with the full forward must reproduce them;
    beam returns the chosen beam's normalized total logp."""
    ff = build_llama({"data": 2})
    rs = np.random.RandomState(19)
    prompt = rs.randint(0, VOCAB, (2, 5)).astype(np.int32)
    out, scores = ff.generate(prompt, max_new_tokens=4, return_scores=True)
    assert scores.shape == (2, 4)
    assert (scores <= 0).all()  # logprobs
    lg = full_logits(ff, out[:, :-1])  # logits predicting positions 1..
    lp = lg - np.log(np.exp(lg - lg.max(-1, keepdims=True)).sum(-1))[..., None] \
        - lg.max(-1, keepdims=True)
    for b in range(2):
        for i in range(4):
            want = lp[b, 4 + i, out[b, 5 + i]]
            np.testing.assert_allclose(scores[b, i], want, atol=2e-4,
                                       err_msg=f"row {b} step {i}")

    bout, bscore = ff.generate(prompt, max_new_tokens=4, num_beams=3,
                               return_scores=True)
    assert bscore.shape == (2,)
    assert (bscore <= 0).all()
    # the real invariant: bscore (length_penalty 0 -> total logp of the
    # chosen beam) equals the full-forward rescoring of bout
    blg = full_logits(ff, bout[:, :-1])
    blp = blg - np.log(np.exp(blg - blg.max(-1, keepdims=True))
                       .sum(-1))[..., None] - blg.max(-1, keepdims=True)
    for b in range(2):
        tot = sum(blp[b, 4 + i, bout[b, 5 + i]] for i in range(4))
        np.testing.assert_allclose(bscore[b], tot, atol=5e-4,
                                   err_msg=f"beam row {b}")


@pytest.mark.slow  # 7 s; the sweep alternates modes against shared cached models
def test_beam_with_temperature_does_not_poison_greedy_cache():
    """A beam call keys temperature/top_k out of the Generator cache; the
    cached Generator must therefore BE greedy, or a later num_beams=1
    call with default temperature would silently sample."""
    ff = build_llama({"data": 2})
    rs = np.random.RandomState(23)
    prompt = rs.randint(0, VOCAB, (2, 5)).astype(np.int32)
    ff.generate(prompt, 3, num_beams=2, temperature=0.9, top_k=5)
    g1 = ff.generate(prompt, 3, seed=1)
    g2 = ff.generate(prompt, 3, seed=2)  # greedy: seed must not matter
    np.testing.assert_array_equal(g1, g2)


@pytest.mark.slow  # 16 s; chunk sampled by the sweep, ragged_chunked kept
def test_chunked_prefill_matches_whole_prompt():
    """prefill_chunk: chunk-by-chunk prefill (incl. an uneven tail chunk)
    must produce EXACTLY the whole-prompt generation — same causal mask,
    same RoPE positions — for greedy and beam. (Exact equality holds on
    the einsum path this CPU test runs; a flash-prefill backend differs
    only by accumulation order.)"""
    ff = build_llama({"data": 2})
    rs = np.random.RandomState(17)
    prompt = rs.randint(0, VOCAB, (2, 10)).astype(np.int32)
    whole = ff.generate(prompt, max_new_tokens=5)
    for chunk in (3, 4, 10, 64):
        out = ff.generate(prompt, max_new_tokens=5, prefill_chunk=chunk)
        np.testing.assert_array_equal(out, whole, err_msg=f"chunk={chunk}")
    beam_whole = ff.generate(prompt, max_new_tokens=5, num_beams=3)
    beam_chunk = ff.generate(prompt, max_new_tokens=5, num_beams=3,
                             prefill_chunk=4)
    np.testing.assert_array_equal(beam_whole, beam_chunk)
    # ragged + chunk is legal since r5 (full-length rows == uniform)
    ragged_chunk = ff.generate(prompt, 5,
                               prompt_lengths=np.full(2, 10, np.int32),
                               prefill_chunk=4)
    np.testing.assert_array_equal(ragged_chunk, whole)
    with pytest.raises(ValueError, match="prefill_chunk"):
        ff.generate(prompt, 3, prefill_chunk=-1)


def test_generate_under_bf16_compute():
    """All generate modes run under the production bf16 compute/master
    dtypes (casts at the graph boundary; f32 rope/softmax inside)."""
    cfg = FFConfig(batch_size=2, mesh_shape={"data": 2},
                   compute_dtype="bfloat16", master_dtype="bfloat16")
    ff = FFModel(cfg)
    from flexflow_tpu.models.llama import llama_lm as _llama

    _, logits = _llama(ff, 2, seq_len=8, hidden=64, layers=2, heads=4,
                       kv_heads=2, vocab_size=VOCAB, tie_embeddings=True)
    ff.compile(final_tensor=logits)
    rs = np.random.RandomState(21)
    p = rs.randint(0, VOCAB, (2, 6)).astype(np.int32)
    for out in (ff.generate(p, 4),
                ff.generate(p, 4, num_beams=2),
                ff.generate(p, 4, quantize="int8"),
                ff.generate(p, 4,
                            prompt_lengths=np.array([4, 6], np.int32))):
        assert out.shape == (2, 10)
        assert ((out >= 0) & (out < VOCAB)).all()


def test_checkpoint_restore_then_generate(tmp_path):
    """The full serving flow: train a few steps, checkpoint, restore into
    a FRESH model (different init), generate — outputs must equal the
    original model's, including on a different mesh shape (checkpoints
    are topology-free)."""
    from flexflow_tpu import LossType, MetricsType, SGDOptimizer
    from flexflow_tpu.runtime.checkpoint import (restore_checkpoint,
                                                 save_checkpoint)

    def build(mesh):
        cfg = FFConfig(batch_size=4, mesh_shape=mesh)
        ff = FFModel(cfg)
        toks, logits = llama_lm(ff, 4, seq_len=8, hidden=64, layers=2,
                                heads=4, kv_heads=2, vocab_size=VOCAB)
        ff.compile(SGDOptimizer(lr=0.1),
                   LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                   [MetricsType.METRICS_ACCURACY], final_tensor=logits)
        return ff

    rs = np.random.RandomState(29)
    ff = build({"data": 2})
    batch = {"input": rs.randint(0, VOCAB, (4, 8)).astype(np.int32),
             "label": rs.randint(0, VOCAB, (4, 8, 1)).astype(np.int32)}
    for _ in range(3):
        ff._run_train_step(batch)
    save_checkpoint(ff, str(tmp_path), step=3)

    prompt = rs.randint(0, VOCAB, (2, 5)).astype(np.int32)
    want = ff.generate(prompt, max_new_tokens=5)

    ff2 = build({"data": 1, "model": 2})  # different mesh, fresh init
    restore_checkpoint(ff2, str(tmp_path), step=3)
    got = ff2.generate(prompt, max_new_tokens=5)
    np.testing.assert_array_equal(want, got)


def test_generate_rejects_placement_models():
    """Params under an operator-placement strategy live on disjoint
    sub-meshes; one decode program cannot span them."""
    from tests.test_placement import (MESH as PMESH, build_branchy,
                                      placement_strategies)

    cfg = FFConfig(batch_size=8, mesh_shape=dict(PMESH))
    cfg.strategies = placement_strategies()
    ff, _ = build_branchy(cfg)
    ff.compile()
    with pytest.raises(NotImplementedError, match="placement"):
        Generator(ff)


def test_generate_rejects_non_decodable_graphs():
    cfg = FFConfig(batch_size=2, mesh_shape={"data": 2})
    ff = FFModel(cfg)
    x = ff.create_tensor([2, 3, 8, 8], name="input")
    t = ff.conv2d(x, 4, 3, 3, 1, 1, 1, 1, name="conv")
    ff.compile(final_tensor=t)
    with pytest.raises(ValueError):
        Generator(ff)


def test_ragged_chunked_prefill_matches_unchunked():
    """Round 5: ragged + chunked prefill (previously refused). All chunks
    run cache-only, then a read-only gather pass queries each row's own
    last prompt token against the filled cache — results must equal the
    whole-prompt ragged prefill exactly (einsum path), for greedy with
    scores AND beam search, including a row whose last position falls in
    an EARLIER chunk."""
    ff = build_llama({"data": 1})
    rs = np.random.RandomState(21)
    full = rs.randint(1, VOCAB, (3, 9)).astype(np.int32)
    # lengths 2 and 5: last positions in chunk 0 and chunk 1 (chunk=4);
    # length 9: in the final chunk
    lengths = np.array([2, 9, 5], np.int32)
    padded = full.copy()
    for b in range(3):
        padded[b, lengths[b]:] = 0

    out0, sc0 = ff.generate(padded, 5, prompt_lengths=lengths,
                            return_scores=True)
    out1, sc1 = ff.generate(padded, 5, prompt_lengths=lengths,
                            prefill_chunk=4, return_scores=True)
    np.testing.assert_array_equal(out0, out1)
    np.testing.assert_allclose(sc0, sc1, rtol=1e-5, atol=1e-6)

    b0, s0 = ff.generate(padded, 4, num_beams=2, prompt_lengths=lengths,
                         return_scores=True)
    b1, s1 = ff.generate(padded, 4, num_beams=2, prompt_lengths=lengths,
                         prefill_chunk=4, return_scores=True)
    np.testing.assert_array_equal(b0, b1)
    np.testing.assert_allclose(s0, s1, rtol=1e-5, atol=1e-6)


def test_beam_search_eos_freezes_and_normalizes_by_emitted_length():
    """Beam + eos: a beam that emits eos freezes (only pad continues, at
    logp 0, so its score stops changing) and the final pick normalizes by
    the TRUE emitted length, not max_new_tokens. The returned winner's
    score must equal full-forward rescoring of its emitted tokens up to
    and including eos, divided by emitted_len**penalty; tokens after eos
    must be pad."""
    ff = build_llama({"data": 1})
    rs = np.random.RandomState(17)
    prompt = rs.randint(1, VOCAB, (2, 4)).astype(np.int32)

    # choose the token beam-2 emits FIRST as eos so freezing triggers at
    # step 1 for at least one row
    probe, _ = ff.generate(prompt, 6, num_beams=2, return_scores=True)
    eos = int(probe[0, 4])

    for lp in (0.0, 1.0):
        out, score = ff.generate(prompt, 6, num_beams=2, length_penalty=lp,
                                 eos_token_id=eos, pad_token_id=0,
                                 return_scores=True)
        for r in range(2):
            new = out[r, 4:]
            hits = np.where(new == eos)[0]
            emitted = int(hits[0]) + 1 if hits.size else len(new)
            if hits.size:
                assert (new[hits[0] + 1:] == 0).all(), new
            # rescore the emitted tokens (incl. eos) by teacher forcing
            seq = np.concatenate([prompt[r], new[:emitted]])[None]
            lg = full_logits(ff, seq)[0]
            logp = 0.0
            for j in range(emitted):
                v = lg[4 - 1 + j].astype(np.float64)
                v = v - (v.max() + np.log(np.exp(v - v.max()).sum()))
                logp += v[new[j]]
            want = logp / (max(emitted, 1) ** lp)
            np.testing.assert_allclose(score[r], want, rtol=1e-3,
                                       atol=5e-3,
                                       err_msg=f"row {r} lp {lp}")


# ---- encoder-decoder (seq2seq) generation — round 5 -------------------------


def _seq2seq_model(mesh={"data": 2}, vocab=61):
    from flexflow_tpu.models.transformer import seq2seq_lm

    cfg = FFConfig(batch_size=2, mesh_shape=dict(mesh))
    ff = FFModel(cfg)
    src, tgt, logits = seq2seq_lm(ff, 2, src_len=7, tgt_len=6, hidden=32,
                                  layers=2, heads=4, vocab_size=vocab)
    ff.compile(final_tensor=logits)
    return ff


def test_seq2seq_generate_matches_naive_rescoring():
    """Encoder-decoder decode (one encode + static cross k/v + cached
    decoder scan) equals the naive loop that re-runs the FULL training
    graph on (src, growing tgt) and argmaxes the last position — pins
    the encoder boundary, cross-attention kv caching, decoder RoPE
    offsets, and the self-attention cache."""
    vocab = 61
    ff = _seq2seq_model(vocab=vocab)
    rs = np.random.RandomState(23)
    src = rs.randint(0, vocab, (2, 7)).astype(np.int32)

    out = ff.generate_seq2seq(src, max_new_tokens=5, bos_token_id=1)
    assert out.shape == (2, 6)
    assert (out[:, 0] == 1).all()

    tgt = np.full((2, 1), 1, np.int32)
    for _ in range(5):
        lg = np.asarray(ff.predict({"src": src, "tgt": tgt}))
        nxt = lg[:, -1].argmax(-1).astype(np.int32)
        tgt = np.concatenate([tgt, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, tgt)


@pytest.mark.slow  # 15 s; seq2seq rescoring + trains_then_decodes stay tier-1
def test_seq2seq_generate_eos_and_sampling():
    vocab = 61
    ff = _seq2seq_model(vocab=vocab)
    rs = np.random.RandomState(29)
    src = rs.randint(0, vocab, (2, 7)).astype(np.int32)

    first = ff.generate_seq2seq(src, max_new_tokens=5)
    eos = int(first[0, 1])
    out = ff.generate_seq2seq(src, max_new_tokens=5, eos_token_id=eos,
                              pad_token_id=0)
    row = out[0, 1:]
    hits = np.where(row == eos)[0]
    assert hits.size and (row[hits[0] + 1:] == 0).all()

    s1 = ff.generate_seq2seq(src, max_new_tokens=5, temperature=0.8,
                             top_k=7, seed=3)
    s2 = ff.generate_seq2seq(src, max_new_tokens=5, temperature=0.8,
                             top_k=7, seed=3)
    np.testing.assert_array_equal(s1, s2)
    assert ((s1 >= 0) & (s1 < vocab)).all()


def test_seq2seq_trains_then_decodes():
    """The same compiled model trains (teacher forcing) and then decodes
    — the serving path the reference's NMT never had."""
    from flexflow_tpu import (LossType, MetricsType, SGDOptimizer,
                              SingleDataLoader)
    from flexflow_tpu.models.transformer import seq2seq_lm

    vocab = 37
    cfg = FFConfig(batch_size=4, mesh_shape={"data": 2}, seed=11)
    ff = FFModel(cfg)
    src, tgt, logits = seq2seq_lm(ff, 4, src_len=6, tgt_len=5, hidden=32,
                                  layers=1, heads=2, vocab_size=vocab)
    ff.compile(SGDOptimizer(lr=0.1),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=logits)
    rs = np.random.RandomState(0)
    src_d = rs.randint(0, vocab, (16, 6)).astype(np.int32)
    tgt_d = np.roll(src_d[:, :5], 1, axis=1).astype(np.int32)  # copy task
    lab = tgt_d.copy()
    SingleDataLoader(ff, ff.ops[0].outputs[0] if ff.ops[0].name == "src"
                     else next(op.outputs[0] for op in ff.ops
                               if op.name == "src"), src_d)
    SingleDataLoader(ff, next(op.outputs[0] for op in ff.ops
                              if op.name == "tgt"), tgt_d)
    SingleDataLoader(ff, ff.label_tensor, lab)
    losses = [float(ff._run_train_step(ff._stage_batch())[0])
              for _ in range(8)]
    assert losses[4] < losses[0]  # 16/4 = 4 batches: same batch revisited
    out = ff.generate_seq2seq(src_d[:4], max_new_tokens=4)
    assert out.shape == (4, 5)
