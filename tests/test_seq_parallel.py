"""Sequence-parallel prefill (ISSUE 18 tentpole, layer b).

A monster prompt's page-aligned prefix splits into contiguous sequence
shards across the fleet's prefill replicas: shard i imports its
predecessors' slabs (so its KV attends the true full prefix), prefills
its contiguous span through the NORMAL bucket programs, and exports a
PARTIAL-PREFIX slab (``export_prefix_slab(start_page=)``). The decode
replica merges the shards by importing them in order through the
partial-prefix ``import_prefix_slab`` — which must compose mid-prefix
while refusing gapped merges.

Pinned here, at engine level (the merge algebra) and router level (the
fleet path):

  * 2- and 3-shard merges land the decode pool BITWISE identical to a
    single-replica prefill — full-width pools and int8 pools (scale
    planes included, the PR 11 published-state contract);
  * a shard slab arriving before its predecessors is refused (0 pages,
    nothing published) — never a gapped prefix;
  * the router's sharded handoff is greedy-token-identical to a plain
    single-engine run, and the new fleet counters account it.
"""

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.models.llama import llama_lm

VOCAB = 61
PS = 4


@pytest.fixture(scope="module")
def ff():
    cfg = FFConfig(batch_size=2, mesh_shape={"data": 1})
    model = FFModel(cfg)
    _, logits = llama_lm(model, 2, seq_len=16, hidden=32, layers=2,
                         heads=2, kv_heads=2, vocab_size=VOCAB)
    model.compile(final_tensor=logits)
    return model


def _prompt(seed, length):
    rs = np.random.RandomState(seed)
    return rs.randint(1, VOCAB, (length,)).astype(np.int32)


def _engine(ff, **kw):
    kw.setdefault("serve_slots", 2)
    kw.setdefault("kv_page_size", PS)
    kw.setdefault("max_seq_len", 32)
    return ff.make_serving_engine(**kw)


def _prefix_pages(eng, prompt, n_pages):
    """Every pool plane (k/v and, when quantized, the scale planes) of
    the prompt's first ``n_pages`` cached pages, as host arrays keyed
    (op, plane) — the published state two engines must agree on
    bitwise."""
    path = eng.prefix_cache.match(prompt, n_pages)
    assert len(path) == n_pages, "prefix not fully cached"
    out = {}
    for op in eng.gen.attn_ops:
        pool = eng.pool[op.name]
        for plane in pool:
            out[(op.name, plane)] = np.stack(
                [np.asarray(pool[plane][nd.page]) for nd in path])
    return out


def _shard_bounds(last, shards):
    """Contiguous page spans, remainder to the front — the router's
    split (ServingRouter._seq_parallel_prefill)."""
    base, rem = divmod(last, shards)
    bounds, s = [], 0
    for i in range(shards):
        e = s + base + (1 if i < rem else 0)
        bounds.append((s, e))
        s = e
    return bounds


def _merge_sharded(ff, prompt, shards, **engine_kw):
    """Run the sequence-parallel protocol by hand: one engine per
    shard, cumulative predecessor imports, partial exports, then merge
    everything into a fresh decode engine. Returns (decode_engine,
    n_pages)."""
    last = prompt.size // PS
    slabs = []
    for s_pg, e_pg in _shard_bounds(last, shards):
        eng = _engine(ff, **engine_kw)
        for slab in slabs:          # predecessors first: KV attends
            assert eng.import_prefix_slab(slab) > 0   # the true prefix
        assert eng.prefill_into_cache(prompt[:e_pg * PS]) == e_pg
        slab = eng.export_prefix_slab(prompt[:e_pg * PS], start_page=s_pg)
        assert slab is not None and slab["start_page"] == s_pg
        assert len(slab["payload"]) == e_pg - s_pg
        slabs.append(slab)
    dec = _engine(ff, **engine_kw)
    for slab in slabs:
        assert dec.import_prefix_slab(slab) > 0
    return dec, last


def test_shard_bounds_cover_contiguously():
    """The router's page split: contiguous, exhaustive, remainder to
    the front so no shard is more than one page bigger than another."""
    for last in (2, 5, 6, 7, 64):
        for shards in (2, 3, 4):
            if shards > last:
                continue
            bounds = _shard_bounds(last, shards)
            assert bounds[0][0] == 0 and bounds[-1][1] == last
            sizes = [e - s for s, e in bounds]
            assert all(b[0] == a[1] for a, b in zip(bounds, bounds[1:]))
            assert max(sizes) - min(sizes) <= 1
            assert sorted(sizes, reverse=True) == sizes


@pytest.mark.slow  # model fixture; longctx CI tier runs the full file
@pytest.mark.parametrize("shards", [2, 3])
def test_sharded_merge_bitwise_full_width(ff, shards):
    prompt = _prompt(41, 24)        # 6 full pages: bounds 3+3 / 2+2+2
    ref = _engine(ff)
    assert ref.prefill_into_cache(prompt) == 6
    want = _prefix_pages(ref, prompt, 6)
    dec, last = _merge_sharded(ff, prompt, shards)
    got = _prefix_pages(dec, prompt, last)
    assert got.keys() == want.keys()
    for key in want:
        assert (got[key] == want[key]).all(), \
            f"{shards}-shard merge diverged from single-replica at {key}"
    assert dec.stats()["partial_slab_imports"] == shards - 1


@pytest.mark.slow  # model fixture; longctx CI tier runs the full file
@pytest.mark.parametrize("shards", [2, 3])
def test_sharded_merge_bitwise_int8(ff, shards):
    """The quantized published-state contract (PR 11) must survive the
    merge: int8 pages AND their per-page scale rows land bitwise what a
    single replica publishes. The reference is a single replica
    EXTENDING the same prefix boundaries (not one cold full-prompt
    pass): under quantized KV the tail past a cached boundary attends
    the dequantized prefix, so the boundary placement is part of the
    published state — sharding must be invisible given the same
    boundaries, which is exactly what the decode replica observes."""
    kw = dict(kv_cache_dtype="int8")
    prompt = _prompt(43, 24)
    ref = _engine(ff, **kw)
    for _, e_pg in _shard_bounds(6, shards):
        assert ref.prefill_into_cache(prompt[:e_pg * PS]) == e_pg
    want = _prefix_pages(ref, prompt, 6)
    assert any(plane == "k_scale" for _, plane in want), \
        "int8 pool must expose scale planes"
    dec, last = _merge_sharded(ff, prompt, shards, **kw)
    got = _prefix_pages(dec, prompt, last)
    for key in want:
        assert (got[key] == want[key]).all(), \
            f"int8 {shards}-shard merge diverged at {key}"


@pytest.mark.slow  # model fixture; longctx CI tier runs the full file
def test_gapped_shard_slab_refused(ff):
    """Shard 1's slab arriving before shard 0 has merged must be
    refused outright: publishing pages past a gap would cache a prefix
    whose middle was never written."""
    prompt = _prompt(47, 24)
    (s0, e0), (s1, e1) = _shard_bounds(6, 2)
    a = _engine(ff)
    assert a.prefill_into_cache(prompt[:e0 * PS]) == e0
    slab0 = a.export_prefix_slab(prompt[:e0 * PS], start_page=s0)
    b = _engine(ff)
    assert b.import_prefix_slab(slab0) == e0
    assert b.prefill_into_cache(prompt) == 6
    slab1 = b.export_prefix_slab(prompt, start_page=s1)
    dec = _engine(ff)
    assert dec.import_prefix_slab(slab1) == 0      # gap: refused
    assert dec.stats()["partial_slab_imports"] == 0
    assert dec.prefix_cache.match(prompt, 6) == []
    # in order, the same slabs merge cleanly
    assert dec.import_prefix_slab(slab0) == e0
    assert dec.import_prefix_slab(slab1) == e1 - s1
    assert dec.stats()["partial_slab_imports"] == 1


@pytest.mark.slow  # model fixture; longctx CI tier runs the full file
def test_partial_export_bounds_validated(ff):
    prompt = _prompt(53, 24)
    eng = _engine(ff)
    assert eng.prefill_into_cache(prompt) == 6
    with pytest.raises(ValueError, match="start_page"):
        eng.export_prefix_slab(prompt, start_page=6)
    with pytest.raises(ValueError, match="start_page"):
        eng.export_prefix_slab(prompt, start_page=-1)
    # start_page=0 stays the whole-prefix slab of the disagg handoff
    whole = eng.export_prefix_slab(prompt)
    assert whole["start_page"] == 0 and len(whole["payload"]) == 6


@pytest.mark.slow  # ~35 s; longctx CI tier runs the full file
def test_router_seq_parallel_token_identity(ff):
    """Fleet leg: a disaggregated router with seq_parallel_shards=2
    must emit exactly the single-engine greedy streams for prompts long
    enough to shard, count them in the fleet rollup, and leave short
    prompts on the plain single-replica handoff."""
    prompts = [_prompt(59, 24), _prompt(61, 26), _prompt(67, 7)]
    eng = _engine(ff, serve_slots=2, max_seq_len=64)
    want = [list(r.tokens) for r in eng.run(prompts, max_new_tokens=5)]
    router = ff.make_serving_router(
        replicas=3, roles="prefill,prefill,decode",
        seq_parallel_shards=2, handoff_min_pages=2,
        serve_slots=2, kv_page_size=PS, max_seq_len=64)
    try:
        reqs = router.run(prompts, max_new_tokens=5)
        assert [r.state for r in reqs] == ["done"] * 3
        got = [list(r.tokens) for r in reqs]
        assert got == want, "sharded fleet changed a greedy stream"
        fleet = router.stats()["fleet"]
        # 24 and 26 tokens = 6 full pages >= 2 shards * 2 min pages;
        # the 7-token prompt (1 page) stays on the plain handoff
        assert fleet["seq_parallel_prefills"] == 2
        assert fleet["partial_slab_imports"] >= 2
        assert fleet["prefill_chunks_interleaved"] == 0
    finally:
        router.close()
