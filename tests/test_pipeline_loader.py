"""PipelineLoader unit tests (runtime/pipeline_loader.py) — pure host
logic, no jax programs: ordering, bounded depth, cursor accounting,
quiesce/epoch-break semantics, and the worker-thread fault contract.

These pin the invariants the integration tests (tests/test_overlap.py)
rely on, at interpreter speed: the worker delivers batches strictly
FIFO, never buffers past `depth`, never advances the consumed cursor
past a handed-out batch, and every failure parks on the worker and
re-raises on the training thread instead of deadlocking."""

import threading
import time

import numpy as np
import pytest

from flexflow_tpu.runtime import faultinject, resilience
from flexflow_tpu.runtime.pipeline_loader import PipelineLoader


@pytest.fixture(autouse=True)
def _fresh_fault_state(monkeypatch):
    monkeypatch.delenv("FF_FAULT", raising=False)
    faultinject.reset()
    resilience.reset_counters()
    yield
    faultinject.reset()


class Source:
    """Deterministic pull source with a seekable cursor (the
    SingleDataLoader contract distilled)."""

    def __init__(self, n=1000, eos_at=None):
        self.cursor = 0
        self.eos_at = eos_at
        self.n = n

    def pull(self):
        if self.eos_at is not None and self.cursor >= self.eos_at:
            return None
        v = self.cursor
        self.cursor += 1
        return {"x": v}

    def cursors(self):
        return {"x": self.cursor}

    def restore(self, snap):
        self.cursor = snap["x"]


def make(src, depth=3, shard=None):
    return PipelineLoader(src.pull, shard or (lambda b: dict(b)),
                          depth=depth, cursors=src.cursors,
                          restore=src.restore)


def test_fifo_order_many_items():
    pipe = make(Source(), depth=3).start()
    try:
        assert [pipe.get(timeout=10)["x"] for _ in range(50)] \
            == list(range(50))
    finally:
        pipe.stop()


def test_depth_bound_never_exceeded():
    seen = []

    def shard(b):
        seen.append(b["x"])
        return b

    src = Source()
    pipe = PipelineLoader(src.pull, shard, depth=2, cursors=src.cursors,
                          restore=src.restore).start()
    try:
        time.sleep(0.3)  # worker fills the buffer and must park
        assert len(seen) <= 3  # depth 2 buffered + at most 1 in flight
        pipe.get(timeout=10)
        time.sleep(0.2)
        assert len(seen) <= 4  # one refill per consume
    finally:
        pipe.stop()


def test_consumed_cursor_tracks_handed_out_batches_only():
    src = Source()
    pipe = make(src, depth=3).start()
    try:
        assert pipe.consumed_cursors() == {"x": 0}
        for i in range(4):
            pipe.get(timeout=10)
            assert pipe.consumed_cursors() == {"x": i + 1}
        # the source cursor has been pulled AHEAD of what was consumed
        time.sleep(0.2)
        assert src.cursor > 4
    finally:
        pipe.stop()
    # stop() rewound the source to the consumed position
    assert src.cursor == 4


def test_epoch_break_discards_rewinds_and_resumes():
    src = Source()
    pipe = make(src, depth=3).start()
    try:
        for _ in range(3):
            pipe.get(timeout=10)
        time.sleep(0.2)  # let the worker prefetch past the epoch point
        resets = []
        pipe.epoch_break(lambda: (src.restore({"x": 0}), resets.append(1)))
        assert resets == [1]
        # post-reset: the next batch is batch 0 again, not a stale one
        assert pipe.get(timeout=10)["x"] == 0
    finally:
        pipe.stop()


def test_stop_is_idempotent():
    src = Source()
    pipe = make(src).start()
    pipe.get(timeout=10)
    pipe.stop()
    pipe.stop()
    assert src.cursor == 1


def test_worker_error_surfaces_in_get_not_deadlock():
    def bad_shard(b):
        raise ValueError("boom")

    src = Source()
    pipe = PipelineLoader(src.pull, bad_shard, depth=2,
                          cursors=src.cursors, restore=src.restore).start()
    try:
        with pytest.raises(RuntimeError, match="prefetch worker died"):
            pipe.get(timeout=10)
    finally:
        pipe.stop()


def test_injected_loader_io_fail_retries_same_batch(monkeypatch):
    monkeypatch.setenv("FF_FAULT", "io_fail@loader:2")
    faultinject.reset()
    src = Source()
    pipe = make(src, depth=2).start()
    try:
        assert [pipe.get(timeout=10)["x"] for _ in range(6)] \
            == list(range(6)), "retry must re-pull the SAME batch"
        assert resilience.COUNTERS["retries"] >= 1
    finally:
        pipe.stop()


def test_exhausted_retries_raise_on_training_thread(monkeypatch):
    monkeypatch.setenv("FF_FAULT", "io_fail@loader:1-3")
    faultinject.reset()
    pipe = make(Source(), depth=2).start()
    try:
        with pytest.raises(RuntimeError, match="prefetch worker died"):
            pipe.get(timeout=10)
    finally:
        pipe.stop()


def test_eos_with_empty_buffer_raises_loudly():
    src = Source(eos_at=2)
    pipe = make(src, depth=2).start()
    try:
        assert pipe.get(timeout=10)["x"] == 0
        assert pipe.get(timeout=10)["x"] == 1
        with pytest.raises(RuntimeError, match="exhausted"):
            pipe.get(timeout=10)
    finally:
        pipe.stop()


def test_epoch_break_clears_eos():
    src = Source(eos_at=2)
    pipe = make(src, depth=2).start()
    try:
        pipe.get(timeout=10), pipe.get(timeout=10)
        time.sleep(0.1)  # worker hits eos and parks

        def reset():
            src.cursor = 0
            src.eos_at = None

        pipe.epoch_break(reset)
        assert pipe.get(timeout=10)["x"] == 0
    finally:
        pipe.stop()


def test_get_timeout_raises():
    blocker = threading.Event()

    def slow_pull():
        blocker.wait(5.0)
        return {"x": 0}

    pipe = PipelineLoader(slow_pull, lambda b: b, depth=1)
    pipe.start()
    try:
        with pytest.raises(TimeoutError):
            pipe.get(timeout=0.2)
    finally:
        blocker.set()
        pipe.stop()


def test_stats_count_delivered_batches():
    pipe = make(Source(), depth=2).start()
    try:
        for _ in range(5):
            pipe.get(timeout=10)
        assert pipe.stats["batches"] >= 5
        assert pipe.stats["h2d_s"] >= 0.0
    finally:
        pipe.stop()


def test_unseekable_source_has_no_cursor_contract():
    src = Source()
    pipe = PipelineLoader(src.pull, lambda b: b, depth=2).start()
    try:
        pipe.get(timeout=10)
        assert pipe.consumed_cursors() is None
    finally:
        pipe.stop()


def test_numpy_batches_pass_through_shard():
    src_arrays = [np.full((4,), i, np.float32) for i in range(8)]
    it = iter(src_arrays)
    pipe = PipelineLoader(lambda: {"x": next(it)}, lambda b: dict(b),
                          depth=2).start()
    try:
        for i in range(8):
            np.testing.assert_array_equal(pipe.get(timeout=10)["x"],
                                          src_arrays[i])
    finally:
        pipe.stop()
