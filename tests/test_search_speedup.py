"""North-star acceptance (BASELINE.md rebuild targets): the MCMC-discovered
strategy must beat pure data parallelism by >=1.5x on the reference workload
configs, simulated on a v5e-32 (4 hosts x 8 chips, two-tier ICI/DCN).

The reference's own acceptance is the same experiment on its simulator: the
search objective is simulated per-iteration runtime (model.cc:1687-1690),
and the SysML'19 headline is the discovered-strategy speedup over DP. These
tests run the full pipeline — graph build, cost tables, native C++ annealer,
per-device timelines — at the reference's default configs (batch 64,
model.cc:1917-1938; DLRM per run_summit.sh).
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scripts.northstar_search import run_one  # noqa: E402

BUDGET = 60_000


@pytest.mark.parametrize("workload,min_speedup", [
    ("transformer", 1.5),
    ("bert_fx", 1.5),  # BASELINE names "BERT-base via FX import" explicitly
    ("resnet50", 1.5),
    ("inception", 1.5),
    ("dlrm", 10.0),  # embedding-partitioned hybrid crushes DP (OOM + sync)
])
def test_search_beats_dp_on_reference_config(workload, min_speedup):
    r = run_one(workload, BUDGET, seed=0, verbose=False)
    assert r["speedup_vs_dp"] >= min_speedup, r
    # the win must come from real strategy structure, not noise
    assert r["ops_with_model_parallel_dims"] > 0 or \
        r["ops_placed_off_block0"] > 0, r


def test_large_batch_regime_is_honest():
    """At 16 samples/chip the transformer is activation-dominated and DP is
    near-optimal — the search must still never be WORSE than DP, and the
    simulator should honestly show the win shrinking."""
    r = run_one("transformer", 20_000, seed=0, verbose=False, batch=16 * 32)
    assert 1.0 <= r["speedup_vs_dp"] < 1.5, r


@pytest.mark.slow  # 13 s 64-chip scale variant; smaller search tests stay tier-1
def test_llama8b_64chip_search_combines_parallelism_axes():
    """VERDICT r4 #7, the scale-shaped joint search: the REAL Llama-8B
    shape (hidden 4096, 32 layers, GQA 32/8, ffn 14336, vocab 128k) over
    a simulated 64-chip two-tier pod (8 hosts x 8 chips). Pure DP cannot
    hold replicated 8B weights per chip (reported infeasible) and cannot
    shard batch 16 across 64 devices; the MCMC winner must COMBINE at
    least two distinct parallelism axes — TP over the ICI 'model' axis
    with DP+FSDP over the DCN 'data' axis — and beat even a
    penalty-free DP on simulated time."""
    r = run_one("llama8b", 20_000, seed=0, verbose=False)
    assert r["machine"].startswith("simulated 64-chip pod"), r
    # DP is memory-infeasible at this scale and the row says so
    assert not r["dp_fits_hbm"], r
    assert r["dp_mem_gb_per_chip"] > r["hbm_gb_per_chip"], r
    # the winner fits
    assert r["best_mem_gb_per_chip"] <= r["hbm_gb_per_chip"], r
    # >= 2 distinct mesh axes carry parallelism, with model-parallel
    # structure on the ICI axis and data/fsdp structure on the DCN axis
    used = r["axes_used"]
    assert len(used) >= 2, r
    assert "tp" in used.get("model", []) or \
        "contract" in used.get("model", []), r
    # search-CHOSEN sample sharding on the DCN axis ('fsdp' alone would be
    # config-imposed pricing, not a discovered combination)
    assert "dp" in used.get("data", []), r
    assert r["ops_with_model_parallel_dims"] > 100, r
    # and the time win is real even granting DP infinite memory
    assert r["speedup_vs_dp_nopenalty"] >= 1.5, r
