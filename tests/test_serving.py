"""Continuous-batching serving runtime (runtime/serving.py).

Correctness anchors:
  * greedy continuous batching is TOKEN-IDENTICAL to sequential
    per-request Generator.generate — the slot scheduler, shape buckets and
    paged cache are pure performance mechanics, never semantics;
  * the page-table gather is BITWISE the dense-cache attention;
  * decode early-exit returns exactly the full-length scan's tokens;
  * warm buckets never recompile (the counter proves it);
  * a poisoned request (FF_FAULT nan_loss@serve) retires as failed
    without stalling the rest of the batch;
  * the radix prefix cache is invisible to tokens: shared-prefix
    admissions emit exactly the cold-cache stream, copy-on-write keeps
    divergent continuations from ever touching each other's pages, and
    drain() leaves zero live refcounts;
  * speculative decoding is invisible to tokens: every emitted token is
    the TARGET's greedy argmax, at any K — the draft only changes how
    many dispatches that stream costs.
"""

import time

import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.models.llama import llama_lm
from flexflow_tpu.runtime import faultinject

VOCAB = 89


@pytest.fixture(scope="module")
def ff():
    cfg = FFConfig(batch_size=2, mesh_shape={"data": 1})
    model = FFModel(cfg)
    _, logits = llama_lm(model, 2, seq_len=16, hidden=64, layers=2,
                         heads=4, kv_heads=2, vocab_size=VOCAB)
    model.compile(final_tensor=logits)
    return model


def _prompts(seed, lengths):
    rs = np.random.RandomState(seed)
    return [rs.randint(1, VOCAB, (L,)).astype(np.int32) for L in lengths]


@pytest.mark.slow  # 17 s; the serving CI tier + serve_smoke drive continuous batching
def test_continuous_batching_token_identical_to_sequential(ff):
    """More requests than slots, mixed lengths spanning several buckets:
    every request's emitted tokens equal its SOLO (one-request-at-a-time)
    generate run — admission order, bucket padding, page allocation and
    slot reuse never leak into the tokens."""
    prompts = _prompts(0, [5, 9, 3, 12, 7, 6, 17, 2, 11])
    eng = ff.make_serving_engine(serve_slots=3, kv_page_size=4,
                                 max_seq_len=64)
    reqs = eng.run(prompts, max_new_tokens=6)
    assert [r.state for r in reqs] == ["done"] * len(prompts)
    for r in reqs:
        solo = ff.generate(r.prompt[None, :], max_new_tokens=6)
        np.testing.assert_array_equal(
            np.asarray(r.tokens, np.int32), solo[0, r.prompt.size:],
            err_msg=f"request {r.rid} (len {r.prompt.size}) diverged "
                    f"from its solo run")
    st = eng.stats()
    assert st["completed"] == len(prompts)
    # every page is either free or cached (warm prefix KV, refcount 0);
    # flushing the cache returns the remainder — no page leaks
    assert st["free_pages"] + st["kv_pages_cached"] == st["kv_pages"] - 1
    assert st["prefix_refs_live"] == 0
    eng.flush_prefix_cache()
    assert eng.stats()["free_pages"] == st["kv_pages"] - 1
    assert 0.0 < st["occupancy"] <= 1.0


@pytest.mark.slow  # 8 s; serving CI tier runs the full file
def test_serve_api_and_eos_retirement(ff):
    """FFModel.serve: eos retires a slot early (freeing it for the queue)
    and outputs match per-request generate with the same eos."""
    prompts = _prompts(1, [4, 6, 5, 8])
    probe = ff.generate(prompts[0][None, :], max_new_tokens=8)
    eos = int(probe[0, prompts[0].size])  # first emitted token of req 0
    outs, st = ff.serve(prompts, max_new_tokens=8, serve_slots=2,
                        kv_page_size=4, max_seq_len=64, eos_id=eos)
    assert st["completed"] == 4 and st["failed"] == 0
    for p, out in zip(prompts, outs):
        solo = ff.generate(p[None, :], max_new_tokens=8, eos_token_id=eos)
        new = solo[0, p.size:]
        hits = np.where(new == eos)[0]
        want = new[:hits[0] + 1] if hits.size else new
        np.testing.assert_array_equal(out[p.size:], want)


def test_paged_gather_matches_dense_cache_bitwise(ff):
    """paged_decode_forward through a SCRAMBLED page table must equal
    decode_forward on the equivalent contiguous cache bitwise: the gather
    reassembles the identical (B, L, KVH, Dh) operand, and the attention
    math after it is the same einsum program."""
    op = ff.make_serving_engine(max_seq_len=32).gen.attn_ops[0]
    params = {k: jnp.asarray(v) for k, v in ff.params[op.name].items()}
    rs = np.random.RandomState(3)
    b, page, n_pages = 2, 4, 4
    max_len = page * n_pages
    kvh, dqk, dv = op.num_kv_heads, op.qk_head_dim, op.v_head_dim
    dense = {
        "k": jnp.asarray(rs.randn(b, max_len, kvh, dqk), jnp.float32),
        "v": jnp.asarray(rs.randn(b, max_len, kvh, dv), jnp.float32),
    }
    x = jnp.asarray(rs.randn(b, 1, op.q_in), jnp.float32)
    pos, prompt_pad = 9, 8
    rope_pos = jnp.asarray([4, 7], jnp.int32)   # logical, not slot, pos
    row_len = jnp.asarray([3, 7], jnp.int32)

    # pool with a deliberately non-identity slot->page mapping
    table = np.array([[5, 2, 7, 1], [3, 6, 4, 8]], np.int32)
    pool = {
        "k": jnp.zeros((10, page, kvh, dqk), jnp.float32),
        "v": jnp.zeros((10, page, kvh, dv), jnp.float32),
    }
    for row in range(b):
        for p in range(n_pages):
            for name in ("k", "v"):
                pool[name] = pool[name].at[table[row, p]].set(
                    dense[name][row, p * page:(p + 1) * page])

    out_d, cache_d = op.decode_forward(
        params, [x, x, x], dense, pos, rope_pos=rope_pos,
        row_lengths=row_len, prompt_len=prompt_pad)
    out_p, cache_p = op.paged_decode_forward(
        params, [x, x, x], pool, jnp.asarray(table),
        jnp.full((b,), pos, jnp.int32), rope_pos, row_len,
        jnp.full((b,), prompt_pad, jnp.int32))
    np.testing.assert_array_equal(np.asarray(out_d), np.asarray(out_p))
    # and the scatter wrote the SAME k/v the contiguous cache holds
    for name in ("k", "v"):
        gathered = np.asarray(cache_p[name])[table].reshape(
            b, max_len, kvh, -1)
        np.testing.assert_array_equal(np.asarray(cache_d[name]), gathered)


@pytest.mark.slow  # 11 s; serving CI tier runs the full file
def test_early_exit_identical_to_full_scan(ff):
    """The while_loop early-exit path: identical tokens (and scores) to
    the full-length scan, with and without eos; without eos_id it simply
    runs the full length."""
    rs = np.random.RandomState(5)
    prompt = rs.randint(1, VOCAB, (2, 5)).astype(np.int32)
    probe = ff.generate(prompt, max_new_tokens=8)
    eos = int(probe[0, 5])
    full = ff.generate(prompt, max_new_tokens=8, eos_token_id=eos)
    fast = ff.generate(prompt, max_new_tokens=8, eos_token_id=eos,
                       early_exit=True)
    np.testing.assert_array_equal(full, fast)

    a, sa = ff.generate(prompt, max_new_tokens=6, eos_token_id=eos,
                        return_scores=True)
    b, sb = ff.generate(prompt, max_new_tokens=6, eos_token_id=eos,
                        return_scores=True, early_exit=True)
    np.testing.assert_array_equal(a, b)
    np.testing.assert_allclose(sa, sb, rtol=0, atol=0)

    no_eos = ff.generate(prompt, max_new_tokens=5, early_exit=True)
    np.testing.assert_array_equal(no_eos,
                                  ff.generate(prompt, max_new_tokens=5))

    # ragged prompts ride the same step body
    lengths = np.array([3, 5], np.int32)
    r_full = ff.generate(prompt, 6, eos_token_id=eos,
                         prompt_lengths=lengths)
    r_fast = ff.generate(prompt, 6, eos_token_id=eos,
                         prompt_lengths=lengths, early_exit=True)
    np.testing.assert_array_equal(r_full, r_fast)


def test_recompile_counter_flat_within_buckets(ff):
    """Power-of-two buckets: after one request has warmed a bucket, any
    mix of prompt lengths inside it (and any max_new_tokens) reuses the
    warm programs — the recompile counter must not move."""
    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                 max_seq_len=64)
    eng.run(_prompts(7, [5, 12]), max_new_tokens=4)   # warm buckets 8, 16
    warm = eng.recompile_count
    assert warm == 3  # prefill(8) + prefill(16) + the one decode program
    eng.run(_prompts(8, [6, 8, 3, 9, 16, 11, 2, 13]), max_new_tokens=7)
    assert eng.recompile_count == warm, \
        "mixed lengths within warm buckets must not recompile"
    # a NEW bucket is exactly one more prefill program
    eng.run(_prompts(9, [20]), max_new_tokens=4)
    assert eng.recompile_count == warm + 1


def test_poisoned_request_retired_without_stalling(ff, monkeypatch):
    """FF_FAULT=nan_loss@serve:3 poisons the 3rd admitted request's
    logits in-graph; the engine must retire exactly that request as
    failed (non-finite logits) while every other request completes with
    its solo-run tokens."""
    monkeypatch.setenv("FF_FAULT", "nan_loss@serve:3")
    faultinject.reset()
    try:
        prompts = _prompts(11, [5, 9, 3, 12, 7, 6])
        eng = ff.make_serving_engine(serve_slots=3, kv_page_size=4,
                                     max_seq_len=64)
        reqs = eng.run(prompts, max_new_tokens=5)
    finally:
        monkeypatch.delenv("FF_FAULT")
        faultinject.reset()
    states = [r.state for r in reqs]
    assert states[2] == "failed" and reqs[2].error == "non-finite logits"
    for i, r in enumerate(reqs):
        if i == 2:
            continue
        assert r.state == "done"
        solo = ff.generate(r.prompt[None, :], max_new_tokens=5)
        np.testing.assert_array_equal(np.asarray(r.tokens, np.int32),
                                      solo[0, r.prompt.size:])
    # the poisoned slot's pages were freed for reuse (its prefill is
    # never published to the prefix cache); the healthy requests' full
    # pages stay cached at refcount 0 until flushed
    st = eng.stats()
    assert st["failed"] == 1
    assert st["free_pages"] + st["kv_pages_cached"] == st["kv_pages"] - 1
    assert st["prefix_refs_live"] == 0
    eng.flush_prefix_cache()
    assert eng.stats()["free_pages"] == st["kv_pages"] - 1


@pytest.mark.slow  # 7 s; serving CI tier runs the full file
def test_page_pool_pressure_blocks_admission_not_progress(ff):
    """A pool too small for all slots at once: admission waits for
    retirements instead of deadlocking, and every request still finishes
    with its solo tokens."""
    # 2 slots x ceil(64/4)=16 pages would want 33; grant 21 — enough for
    # one max-size request (16+1) plus a small one, never two max-size
    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                 max_seq_len=64, kv_pages=21)
    prompts = _prompts(13, [30, 25, 6, 28])
    reqs = eng.run(prompts, max_new_tokens=4)
    assert [r.state for r in reqs] == ["done"] * 4
    for r in reqs:
        solo = ff.generate(r.prompt[None, :], max_new_tokens=4)
        np.testing.assert_array_equal(np.asarray(r.tokens, np.int32),
                                      solo[0, r.prompt.size:])


def test_serving_validation(ff):
    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                 max_seq_len=32)
    with pytest.raises(ValueError, match="max_seq_len"):
        eng.submit(np.arange(1, 30, dtype=np.int32), max_new_tokens=16)
    with pytest.raises(ValueError, match="empty"):
        eng.submit(np.zeros((0,), np.int32), max_new_tokens=4)
    with pytest.raises(ValueError, match="max_new_tokens"):
        eng.submit(np.arange(1, 5, dtype=np.int32), max_new_tokens=0)
    with pytest.raises(ValueError, match="kv_pages"):
        ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                               max_seq_len=32, kv_pages=4)
    with pytest.raises(ValueError, match="bucket"):
        eng2 = ff.make_serving_engine(decode_buckets=[8, 16],
                                      kv_page_size=4, max_seq_len=64)
        eng2.submit(np.arange(1, 20, dtype=np.int32), max_new_tokens=4)
    with pytest.raises(ValueError):
        FFConfig(batch_size=2, mesh_shape={"data": 1}, serve_slots=0)
    with pytest.raises(ValueError):
        FFConfig(batch_size=2, mesh_shape={"data": 1},
                 decode_buckets=[16, 8])


@pytest.mark.slow  # 17 s; serving CI tier runs the full file
def test_decode_chunk_invariance(ff):
    """decode_chunk trades dispatch overhead for retirement granularity
    ONLY: any chunk size produces identical tokens — including requests
    whose eos lands mid-chunk (the in-graph over-decode is truncated by
    the host) and whose max_new_tokens is not a chunk multiple."""
    prompts = _prompts(19, [5, 9, 3, 12])
    probe = ff.generate(prompts[0][None, :], max_new_tokens=10)
    eos = int(probe[0, prompts[0].size + 2])  # eos somewhere mid-stream
    outs = {}
    for chunk in (1, 3, 16):
        eng = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                     max_seq_len=64, decode_chunk=chunk,
                                     eos_id=eos)
        reqs = eng.run(prompts, max_new_tokens=10)
        assert [r.state for r in reqs] == ["done"] * 4
        outs[chunk] = [np.asarray(r.tokens, np.int32) for r in reqs]
    for chunk in (3, 16):
        for a, b in zip(outs[1], outs[chunk]):
            np.testing.assert_array_equal(
                a, b, err_msg=f"decode_chunk={chunk} changed tokens")
    # and chunk=1 equals the solo batch path under the same eos
    for p, got in zip(prompts, outs[1]):
        solo = ff.generate(p[None, :], max_new_tokens=10, eos_token_id=eos)
        new = solo[0, p.size:]
        hits = np.where(new == eos)[0]
        want = new[:hits[0] + 1] if hits.size else new
        np.testing.assert_array_equal(got, want)


# ---- radix prefix cache: pure-host trie semantics (sub-second) ----------


def _trie(ps=4):
    from flexflow_tpu.runtime.serving import RadixPrefixCache

    return RadixPrefixCache(ps)


def test_radix_trie_match_insert_roundtrip():
    """A published prefix is found page-aligned: full pages only, longest
    path wins, the partial last page never enters the trie."""
    pc = _trie(4)
    prompt = np.arange(1, 14, dtype=np.int32)         # 13 tokens: 3 full
    created = pc.insert(prompt, [], 0, [7, 8, 9])
    assert [n.page for n in created] == [7, 8, 9] and pc.pages == 3
    # identical prompt: all 3 pages match (cap at the last FULL page)
    assert [n.page for n in pc.match(prompt, 3)] == [7, 8, 9]
    # shares only the first 8 tokens: 2 pages
    other = prompt.copy()
    other[9] = 77
    assert [n.page for n in pc.match(other, 3)] == [7, 8]
    # a max_pages cap truncates the walk
    assert [n.page for n in pc.match(prompt, 1)] == [7]
    # nothing in common: no match
    assert pc.match(np.full((8,), 60, np.int32), 2) == []


def test_radix_trie_insert_stops_at_existing_chunk():
    """Publishing under a capped match stops at the first chunk that
    already exists — the duplicate page stays the caller's."""
    pc = _trie(4)
    prompt = np.arange(1, 13, dtype=np.int32)
    pc.insert(prompt, [], 0, [5, 6])
    # same prompt published again with different pages: nothing created
    assert pc.insert(prompt, [], 0, [11, 12]) == []
    assert pc.pages == 2
    # extend past the existing path
    m = pc.match(prompt, 3)
    created = pc.insert(prompt, m, 2, [13])
    assert [n.page for n in created] == [13] and pc.pages == 3


def test_radix_trie_refcounts_and_eviction():
    """Refcounted pages never evict; refcount-0 leaves evict LRU-first
    and cascade to exposed parents; a protected path survives."""
    pc = _trie(4)
    a = np.arange(1, 9, dtype=np.int32)
    b = np.full((4,), 50, np.int32)
    na = pc.insert(a, [], 0, [1, 2])      # chain 1 -> 2
    nb = pc.insert(b, [], 0, [3])         # leaf 3
    pc.release(na)
    pc.release(nb)
    assert pc.live_refs() == 0 and pc.pages == 3
    pc.match(a, 2)                        # touch chain a (newer last_use)
    assert pc.evict(1) == [3]             # LRU leaf goes first
    # cascade: evicting leaf 2 exposes 1
    assert sorted(pc.evict(2)) == [1, 2] and pc.pages == 0
    # refcount protection: a mounted path never evicts
    nc = pc.insert(a, [], 0, [4, 5])
    assert pc.evict(5) == [] and pc.pages == 2
    pc.release(nc)
    # protect= excludes a just-matched path about to be acquired
    assert pc.evict(5, protect=nc) == [] and pc.pages == 2
    assert sorted(pc.evict(5)) == [4, 5]
    with pytest.raises(AssertionError, match="underflow"):
        pc.release(nc)


# ---- radix prefix cache: engine semantics --------------------------------


@pytest.mark.slow  # 20 s; serving CI tier runs the full file
def test_prefix_cache_token_identical_to_cold(ff):
    """Skewed shared-prefix traffic: requests sharing a system prompt hit
    the cache (prefill only the tail) yet emit exactly the tokens a
    cold-cache engine — and a solo generate run — produces. The cache is
    a perf mechanism, never semantics."""
    rs = np.random.RandomState(23)
    system = rs.randint(1, VOCAB, (12,)).astype(np.int32)  # 3 full pages
    tails = [rs.randint(1, VOCAB, (L,)).astype(np.int32)
             for L in (3, 7, 1, 5, 9)]
    prompts = [np.concatenate([system, t]) for t in tails]

    warm = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                  max_seq_len=64)
    cold = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                  max_seq_len=64, prefix_cache=False)
    w_reqs = warm.run(prompts, max_new_tokens=6)
    c_reqs = cold.run(prompts, max_new_tokens=6)
    assert [r.state for r in w_reqs] == ["done"] * len(prompts)
    for w, c in zip(w_reqs, c_reqs):
        np.testing.assert_array_equal(
            np.asarray(w.tokens, np.int32), np.asarray(c.tokens, np.int32),
            err_msg=f"prefix cache changed request {w.rid}'s tokens")
        solo = ff.generate(w.prompt[None, :], max_new_tokens=6)
        np.testing.assert_array_equal(np.asarray(w.tokens, np.int32),
                                      solo[0, w.prompt.size:])
    ws, cs = warm.stats(), cold.stats()
    # every request after the first matched the shared 12-token prefix
    assert ws["prefix_hits"] == len(prompts) - 1
    assert ws["prefill_tokens_saved"] == (len(prompts) - 1) * 12
    assert cs["prefix_lookups"] == 0 and not cs["prefix_cache"]
    # the cold engine holds nothing back; the warm one caches pages
    assert cs["free_pages"] == cs["kv_pages"] - 1
    assert ws["free_pages"] + ws["kv_pages_cached"] == ws["kv_pages"] - 1


@pytest.mark.slow  # 15 s; serving CI tier runs the full file
def test_prefix_cow_isolation(ff):
    """Copy-on-write: concurrent requests mounting the same cached prefix
    write their divergent tails and decode tokens into their OWN pages —
    the donor's published pages are bitwise untouched, and every stream
    matches its solo run."""
    rs = np.random.RandomState(29)
    system = rs.randint(1, VOCAB, (8,)).astype(np.int32)   # 2 full pages
    prompts = [np.concatenate([system,
                               rs.randint(1, VOCAB, (L,)).astype(np.int32)])
               for L in (2, 6, 4, 3)]
    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                 max_seq_len=64)
    eng.run([prompts[0]], max_new_tokens=4)      # publish the prefix
    pc = eng.prefix_cache
    shared = []
    node = pc.root
    while node.children:
        node = next(iter(node.children.values()))
        shared.append(node.page)
    assert len(shared) >= 2                      # the 2 system pages
    shared = np.asarray(shared, np.int32)
    before = {op.name: {n: np.asarray(eng.pool[op.name][n][shared])
                        for n in ("k", "v")}
              for op in eng.gen.attn_ops}

    reqs = eng.run(prompts[1:], max_new_tokens=4)
    for r in reqs:
        assert r.prefix_tokens >= 8              # mounted the shared pages
        solo = ff.generate(r.prompt[None, :], max_new_tokens=4)
        np.testing.assert_array_equal(np.asarray(r.tokens, np.int32),
                                      solo[0, r.prompt.size:])
    after = {op.name: {n: np.asarray(eng.pool[op.name][n][shared])
                       for n in ("k", "v")}
             for op in eng.gen.attn_ops}
    for name, kv in before.items():
        for n in ("k", "v"):
            np.testing.assert_array_equal(
                kv[n], after[name][n],
                err_msg=f"shared page of {name}/{n} was written in place "
                        f"(copy-on-write violated)")


@pytest.mark.slow  # 12 s; serving CI tier runs the full file
def test_prefix_evict_under_pressure(ff):
    """A pool sized for exactly one max request: cached pages from
    retired traffic are reclaimed (LRU) when admission needs them, and
    everything still completes with solo-identical tokens."""
    eng = ff.make_serving_engine(serve_slots=1, kv_page_size=4,
                                 max_seq_len=32, kv_pages=9)
    rs = np.random.RandomState(31)
    prompts = [rs.randint(1, VOCAB, (14,)).astype(np.int32)
               for _ in range(4)]
    reqs = eng.run(prompts, max_new_tokens=4)
    assert [r.state for r in reqs] == ["done"] * 4
    for r in reqs:
        solo = ff.generate(r.prompt[None, :], max_new_tokens=4)
        np.testing.assert_array_equal(np.asarray(r.tokens, np.int32),
                                      solo[0, r.prompt.size:])
    st = eng.stats()
    assert st["prefix_evictions"] > 0, \
        "distinct 14-token prompts must force cache eviction in 9 pages"
    assert st["free_pages"] + st["kv_pages_cached"] == st["kv_pages"] - 1
    assert st["prefix_refs_live"] == 0


@pytest.mark.slow  # 10 s; serving CI tier runs the full file
def test_prefix_refcounts_clean_after_drain(ff):
    """drain() with slots mid-flight: every trie refcount drops to zero,
    pages are either free or cached, and flush_prefix_cache() returns the
    pool to exactly kv_pages - 1 free (the leak check)."""
    rs = np.random.RandomState(37)
    system = rs.randint(1, VOCAB, (8,)).astype(np.int32)
    prompts = [np.concatenate([system,
                               rs.randint(1, VOCAB, (3,)).astype(np.int32)])
               for _ in range(5)]
    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                 max_seq_len=64, decode_chunk=2)
    for p in prompts:
        eng.submit(p, max_new_tokens=12)
    eng.step()                    # slots mid-flight, queue non-empty
    snap = eng.drain()
    assert snap["drained"] and snap["prefix_refs_live"] == 0
    assert snap["queued"] == len(prompts) - eng.slots
    st = eng.stats()
    assert st["free_pages"] + st["kv_pages_cached"] == st["kv_pages"] - 1
    freed = eng.flush_prefix_cache()
    assert freed == st["kv_pages_cached"]
    assert eng.stats()["free_pages"] == st["kv_pages"] - 1


@pytest.mark.slow  # 14 s; serving CI tier runs the full file
def test_pool_exhaustion_flood_tiny_pool(ff):
    """Regression (satellite): flooding a tiny pool must never fail a
    request — admission leaves what doesn't fit in the queue and run()
    keeps making progress via retirements until the flood drains."""
    eng = ff.make_serving_engine(serve_slots=4, kv_page_size=4,
                                 max_seq_len=32, kv_pages=9)
    rs = np.random.RandomState(41)
    prompts = [rs.randint(1, VOCAB, (rs.randint(2, 15),)).astype(np.int32)
               for _ in range(12)]
    reqs = eng.run(prompts, max_new_tokens=3)
    assert [r.state for r in reqs] == ["done"] * len(prompts)
    st = eng.stats()
    assert st["failed"] == 0 and st["completed"] == len(prompts)
    assert st["free_pages"] + st["kv_pages_cached"] == st["kv_pages"] - 1


# ---- speculative decoding ------------------------------------------------


@pytest.fixture(scope="module")
def draft(ff):
    """A smaller draft LM over the SAME vocabulary (random weights — its
    proposals rarely match, which exercises the reject path hard)."""
    cfg = FFConfig(batch_size=2, mesh_shape={"data": 1})
    model = FFModel(cfg)
    _, logits = llama_lm(model, 2, seq_len=16, hidden=32, layers=1,
                         heads=2, kv_heads=2, vocab_size=VOCAB)
    model.compile(final_tensor=logits)
    return model


@pytest.mark.slow  # 35 s; serving CI tier runs the full file
def test_speculative_greedy_token_identity(ff, draft):
    """Speculative decoding at several K — including K larger than
    max_new_tokens — emits exactly the non-speculative greedy stream.
    Two drafts: a random small model (near-0 accept rate, the all-reject
    path) and the target itself (near-1 accept rate, the long-accept
    path); the tokens must not depend on either."""
    prompts = _prompts(43, [5, 9, 3, 12])
    base = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                  max_seq_len=64)
    want = [np.asarray(r.tokens, np.int32)
            for r in base.run(prompts, max_new_tokens=5)]
    for dm in (draft, ff):
        for k in (1, 3, 8):      # 8 > max_new_tokens=5
            eng = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                         max_seq_len=64, draft_model=dm,
                                         speculate_k=k)
            reqs = eng.run(prompts, max_new_tokens=5)
            assert [r.state for r in reqs] == ["done"] * len(prompts)
            for w, r in zip(want, reqs):
                np.testing.assert_array_equal(
                    w, np.asarray(r.tokens, np.int32),
                    err_msg=f"speculate_k={k} draft={'self' if dm is ff else 'small'} "
                            f"changed request {r.rid}'s tokens")
            st = eng.stats()
            assert st["spec_proposed"] > 0
            if dm is ff:
                # self-draft: proposals are the target's own argmax —
                # the accept path must actually run
                assert st["spec_accepted"] > 0
            assert st["free_pages"] + st["kv_pages_cached"] \
                == st["kv_pages"] - 1


@pytest.mark.slow  # 12 s; serving CI tier runs the full file
def test_speculative_with_eos_and_prefix_cache(ff, draft):
    """eos retirement mid-verify-window truncates cleanly, and the prefix
    cache + speculation compose: identical tokens to the plain engine
    under the same eos."""
    rs = np.random.RandomState(47)
    system = rs.randint(1, VOCAB, (8,)).astype(np.int32)
    prompts = [np.concatenate([system,
                               rs.randint(1, VOCAB, (L,)).astype(np.int32)])
               for L in (2, 5, 3)]
    probe = ff.generate(prompts[0][None, :], max_new_tokens=8)
    eos = int(probe[0, prompts[0].size + 2])
    base = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                  max_seq_len=64, eos_id=eos)
    want = [np.asarray(r.tokens, np.int32)
            for r in base.run(prompts, max_new_tokens=8)]
    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                 max_seq_len=64, eos_id=eos,
                                 draft_model=draft, speculate_k=2)
    reqs = eng.run(prompts, max_new_tokens=8)
    for w, r in zip(want, reqs):
        np.testing.assert_array_equal(w, np.asarray(r.tokens, np.int32))
    assert eng.stats()["prefix_hits"] >= len(prompts) - 1


@pytest.mark.slow  # 25 s; serving CI tier runs the full file
def test_recompile_flat_with_prefix_and_speculation(ff, draft):
    """Warm-window flatness with BOTH features on: after one pass has
    warmed the buckets (cold + hit prefills, draft mirrors, draft decode
    and verify), further same-bucket traffic compiles nothing."""
    rs = np.random.RandomState(53)
    system = rs.randint(1, VOCAB, (8,)).astype(np.int32)

    def mk(n, lo, hi):
        return [np.concatenate([system, rs.randint(
            1, VOCAB, (rs.randint(lo, hi),)).astype(np.int32)])
            for _ in range(n)]

    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                 max_seq_len=64, draft_model=draft,
                                 speculate_k=2)
    eng.run(mk(6, 1, 8), max_new_tokens=4)      # warm bucket 16 paths
    warm = eng.recompile_count
    eng.run(mk(10, 1, 8), max_new_tokens=6)
    assert eng.recompile_count == warm, \
        "warm shared-prefix + speculative traffic must not recompile"
    st = eng.stats()
    assert st["prefix_hits"] > 0 and st["spec_proposed"] > 0


def test_speculative_validation(ff, draft):
    """The accept rule's preconditions are enforced at construction.
    (temperature > 0 + speculation is no longer an error: ISSUE 14's
    rejection-sampled speculation serves sampled requests — the sampling
    params themselves are validated instead.)"""
    with pytest.raises(ValueError, match="draft model"):
        ff.make_serving_engine(speculate_k=2)
    with pytest.raises(ValueError, match="must be >= 0"):
        ff.make_serving_engine(speculate_k=-1, draft_model=draft)
    # sampled speculation constructs fine; bad sampling params do not
    eng = ff.make_serving_engine(speculate_k=2, draft_model=draft,
                                 temperature=0.7, kv_page_size=4,
                                 max_seq_len=64)
    assert eng.speculate_k == 2 and eng.default_temperature == 0.7
    with pytest.raises(ValueError, match="temperature"):
        ff.make_serving_engine(temperature=-0.5)
    with pytest.raises(ValueError, match="top_p"):
        ff.make_serving_engine(top_p=0.0)
    with pytest.raises(ValueError, match="top_k"):
        ff.make_serving_engine(top_k=-3)


@pytest.mark.slow  # 8 s; one extra model compile
def test_speculative_vocab_mismatch_rejected(ff):
    cfg = FFConfig(batch_size=2, mesh_shape={"data": 1})
    model = FFModel(cfg)
    _, logits = llama_lm(model, 2, seq_len=16, hidden=32, layers=1,
                         heads=2, kv_heads=2, vocab_size=VOCAB + 7)
    model.compile(final_tensor=logits)
    with pytest.raises(ValueError, match="vocab mismatch"):
        ff.make_serving_engine(speculate_k=2, draft_model=model)


def test_serving_config_knob_validation():
    """FFConfig __post_init__ guards + parse_args flags (satellite)."""
    with pytest.raises(ValueError, match="power of two"):
        FFConfig(batch_size=2, mesh_shape={"data": 1}, kv_page_size=12)
    with pytest.raises(ValueError, match="serve_speculate_k"):
        FFConfig(batch_size=2, mesh_shape={"data": 1},
                 serve_speculate_k=-2)
    cfg = FFConfig.parse_args([
        "--batch-size", "2", "--serve-slots", "6", "--kv-page-size", "64",
        "--kv-pages", "40", "--no-prefix-cache",
        "--serve-speculate-k", "3"])
    assert cfg.serve_slots == 6 and cfg.kv_page_size == 64
    assert cfg.kv_pages == 40 and cfg.serve_prefix_cache is False
    assert cfg.serve_speculate_k == 3
    dflt = FFConfig.parse_args(["--batch-size", "2"])
    assert dflt.serve_prefix_cache is True and dflt.serve_speculate_k == 0


def test_stats_and_health_expose_pool_observability(ff):
    """The router-facing observability keys (satellite): pool occupancy,
    prefix-cache and speculation signals present in stats() AND mirrored
    in health() without compiling anything."""
    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                 max_seq_len=32)
    st = eng.stats()
    for key in ("pages_in_use", "free_pages", "kv_pages_cached",
                "kv_pages_shared", "prefix_hit_rate", "prefix_hits",
                "prefill_tokens_saved", "prefix_evictions",
                "prefix_refs_live", "spec_accept_rate", "spec_proposed",
                "spec_accepted", "speculate_k",
                # decode-attention hot path (ISSUE 7): impl routing,
                # pages the last dispatch's attention read, autotune
                # table consultations
                "paged_attention_impl", "pages_touched",
                "last_pages_touched", "kernel_tune_hits",
                "kernel_tune_misses"):
        assert key in st, f"stats() missing {key}"
    assert st["pages_in_use"] == 0 and st["prefix_hit_rate"] == 0.0
    assert st["paged_attention_impl"] in ("pallas", "einsum")
    assert st["pages_touched"] == 0 and st["last_pages_touched"] == 0
    before = eng.recompile_count
    h = eng.health()
    assert eng.recompile_count == before     # health never compiles
    for key in ("pages_in_use", "kv_pages_shared", "prefix_hit_rate",
                "spec_accept_rate"):
        assert key in h, f"health() missing {key}"
    assert h["status"] == "idle"


def test_engine_deadline_expires_in_queue_without_dispatch(ff):
    """submit(deadline=): a request that expires while queued retires as
    "timeout" at the next tick — no prefill, no pages, no compile (the
    engine half of the router's per-request-deadline contract). An
    unexpired sibling is untouched."""
    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                 max_seq_len=32)
    now = time.perf_counter()
    dead = eng.submit(np.arange(1, 6, dtype=np.int32), 4, deadline=now)
    live = eng.submit(np.arange(1, 7, dtype=np.int32), 4,
                      deadline=now + 3600.0)
    free0 = len(eng._free_pages)
    eng._expire_queued()   # what _admit runs first, without the prefill
    assert dead.state == "timeout" and "deadline" in dead.error
    assert dead.tokens == [] and dead.t_done > 0
    assert live.state == "queued"
    st = eng.stats()
    assert st["timeouts"] == 1 and st["requests"] == 2
    assert eng.recompile_count == 0, "expired work must never compile"
    assert len(eng._free_pages) == free0, "expired work must hold no pages"
    assert "timeouts" in eng.health()
    # load() is the router's lock-free dispatch signal
    assert eng.load() == {"active_slots": 0, "queued": 1}


@pytest.mark.slow  # 18 s; the router drives each replica from its own
# thread — this pins the one-engine-lock contract under real contention
def test_engine_thread_safe_under_concurrent_submit(ff):
    """Concurrent-submit stress: four threads submit while the main
    thread drives step() — every request completes exactly once, the
    counters add up, and the page accounting survives (the invariants a
    torn queue/slot mutation would break)."""
    import threading

    eng = ff.make_serving_engine(serve_slots=3, kv_page_size=4,
                                 max_seq_len=64)
    per_thread, n_threads = 6, 4
    all_reqs, errs = [], []
    lock = threading.Lock()
    done_submitting = threading.Event()
    barrier = threading.Barrier(n_threads + 1)

    def submitter(seed):
        rs = np.random.RandomState(seed)
        barrier.wait()
        try:
            for _ in range(per_thread):
                p = rs.randint(1, VOCAB,
                               (int(rs.randint(2, 14)),)).astype(np.int32)
                r = eng.submit(p, int(rs.randint(2, 6)))
                with lock:
                    all_reqs.append(r)
                time.sleep(0.001 * rs.randint(0, 4))
        except Exception as e:  # noqa: BLE001 — surfaced to the assert
            with lock:
                errs.append(e)

    threads = [threading.Thread(target=submitter, args=(60 + i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    barrier.wait()

    def stepper():
        while not done_submitting.is_set() or eng.pending():
            if not eng.step():
                time.sleep(0.001)

    step_thread = threading.Thread(target=stepper)
    step_thread.start()
    for t in threads:
        t.join()
    done_submitting.set()
    step_thread.join()

    assert not errs, errs
    total = per_thread * n_threads
    assert len(all_reqs) == total
    assert [r.state for r in all_reqs] == ["done"] * total
    st = eng.stats()
    assert st["requests"] == total and st["completed"] == total
    assert st["failed"] == 0 and st["timeouts"] == 0
    assert st["free_pages"] + st["kv_pages_cached"] == st["kv_pages"] - 1
    assert st["prefix_refs_live"] == 0
    # spot-check token identity through the contention
    for r in all_reqs[::7]:
        solo = ff.generate(r.prompt[None, :],
                           max_new_tokens=r.max_new_tokens)
        np.testing.assert_array_equal(np.asarray(r.tokens, np.int32),
                                      solo[0, r.prompt.size:])


@pytest.mark.slow  # 7 s; serving CI tier runs the full file
def test_explicit_buckets_and_per_request_max_new(ff):
    """Pinned decode_buckets honor their boundaries; per-request
    max_new_tokens mixes freely in one batch."""
    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                 max_seq_len=64, decode_buckets=[8, 24])
    rs = np.random.RandomState(17)
    reqs = [eng.submit(rs.randint(1, VOCAB, (L,)).astype(np.int32), m)
            for L, m in [(5, 3), (20, 6), (8, 2), (11, 5)]]
    assert [r.bucket for r in reqs] == [8, 24, 8, 24]
    while eng.step():
        pass
    for r in reqs:
        assert r.state == "done" and len(r.tokens) == r.max_new_tokens
        solo = ff.generate(r.prompt[None, :],
                           max_new_tokens=r.max_new_tokens)
        np.testing.assert_array_equal(np.asarray(r.tokens, np.int32),
                                      solo[0, r.prompt.size:])
