"""Continuous-batching serving runtime (runtime/serving.py).

Correctness anchors:
  * greedy continuous batching is TOKEN-IDENTICAL to sequential
    per-request Generator.generate — the slot scheduler, shape buckets and
    paged cache are pure performance mechanics, never semantics;
  * the page-table gather is BITWISE the dense-cache attention;
  * decode early-exit returns exactly the full-length scan's tokens;
  * warm buckets never recompile (the counter proves it);
  * a poisoned request (FF_FAULT nan_loss@serve) retires as failed
    without stalling the rest of the batch.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.models.llama import llama_lm
from flexflow_tpu.runtime import faultinject

VOCAB = 89


@pytest.fixture(scope="module")
def ff():
    cfg = FFConfig(batch_size=2, mesh_shape={"data": 1})
    model = FFModel(cfg)
    _, logits = llama_lm(model, 2, seq_len=16, hidden=64, layers=2,
                         heads=4, kv_heads=2, vocab_size=VOCAB)
    model.compile(final_tensor=logits)
    return model


def _prompts(seed, lengths):
    rs = np.random.RandomState(seed)
    return [rs.randint(1, VOCAB, (L,)).astype(np.int32) for L in lengths]


@pytest.mark.slow  # 17 s; the serving CI tier + serve_smoke drive continuous batching
def test_continuous_batching_token_identical_to_sequential(ff):
    """More requests than slots, mixed lengths spanning several buckets:
    every request's emitted tokens equal its SOLO (one-request-at-a-time)
    generate run — admission order, bucket padding, page allocation and
    slot reuse never leak into the tokens."""
    prompts = _prompts(0, [5, 9, 3, 12, 7, 6, 17, 2, 11])
    eng = ff.make_serving_engine(serve_slots=3, kv_page_size=4,
                                 max_seq_len=64)
    reqs = eng.run(prompts, max_new_tokens=6)
    assert [r.state for r in reqs] == ["done"] * len(prompts)
    for r in reqs:
        solo = ff.generate(r.prompt[None, :], max_new_tokens=6)
        np.testing.assert_array_equal(
            np.asarray(r.tokens, np.int32), solo[0, r.prompt.size:],
            err_msg=f"request {r.rid} (len {r.prompt.size}) diverged "
                    f"from its solo run")
    st = eng.stats()
    assert st["completed"] == len(prompts)
    assert st["free_pages"] == st["kv_pages"] - 1  # all pages returned
    assert 0.0 < st["occupancy"] <= 1.0


@pytest.mark.slow  # 8 s; serving CI tier runs the full file
def test_serve_api_and_eos_retirement(ff):
    """FFModel.serve: eos retires a slot early (freeing it for the queue)
    and outputs match per-request generate with the same eos."""
    prompts = _prompts(1, [4, 6, 5, 8])
    probe = ff.generate(prompts[0][None, :], max_new_tokens=8)
    eos = int(probe[0, prompts[0].size])  # first emitted token of req 0
    outs, st = ff.serve(prompts, max_new_tokens=8, serve_slots=2,
                        kv_page_size=4, max_seq_len=64, eos_id=eos)
    assert st["completed"] == 4 and st["failed"] == 0
    for p, out in zip(prompts, outs):
        solo = ff.generate(p[None, :], max_new_tokens=8, eos_token_id=eos)
        new = solo[0, p.size:]
        hits = np.where(new == eos)[0]
        want = new[:hits[0] + 1] if hits.size else new
        np.testing.assert_array_equal(out[p.size:], want)


def test_paged_gather_matches_dense_cache_bitwise(ff):
    """paged_decode_forward through a SCRAMBLED page table must equal
    decode_forward on the equivalent contiguous cache bitwise: the gather
    reassembles the identical (B, L, KVH, Dh) operand, and the attention
    math after it is the same einsum program."""
    op = ff.make_serving_engine(max_seq_len=32).gen.attn_ops[0]
    params = {k: jnp.asarray(v) for k, v in ff.params[op.name].items()}
    rs = np.random.RandomState(3)
    b, page, n_pages = 2, 4, 4
    max_len = page * n_pages
    kvh, dqk, dv = op.num_kv_heads, op.qk_head_dim, op.v_head_dim
    dense = {
        "k": jnp.asarray(rs.randn(b, max_len, kvh, dqk), jnp.float32),
        "v": jnp.asarray(rs.randn(b, max_len, kvh, dv), jnp.float32),
    }
    x = jnp.asarray(rs.randn(b, 1, op.q_in), jnp.float32)
    pos, prompt_pad = 9, 8
    rope_pos = jnp.asarray([4, 7], jnp.int32)   # logical, not slot, pos
    row_len = jnp.asarray([3, 7], jnp.int32)

    # pool with a deliberately non-identity slot->page mapping
    table = np.array([[5, 2, 7, 1], [3, 6, 4, 8]], np.int32)
    pool = {
        "k": jnp.zeros((10, page, kvh, dqk), jnp.float32),
        "v": jnp.zeros((10, page, kvh, dv), jnp.float32),
    }
    for row in range(b):
        for p in range(n_pages):
            for name in ("k", "v"):
                pool[name] = pool[name].at[table[row, p]].set(
                    dense[name][row, p * page:(p + 1) * page])

    out_d, cache_d = op.decode_forward(
        params, [x, x, x], dense, pos, rope_pos=rope_pos,
        row_lengths=row_len, prompt_len=prompt_pad)
    out_p, cache_p = op.paged_decode_forward(
        params, [x, x, x], pool, jnp.asarray(table),
        jnp.full((b,), pos, jnp.int32), rope_pos, row_len,
        jnp.full((b,), prompt_pad, jnp.int32))
    np.testing.assert_array_equal(np.asarray(out_d), np.asarray(out_p))
    # and the scatter wrote the SAME k/v the contiguous cache holds
    for name in ("k", "v"):
        gathered = np.asarray(cache_p[name])[table].reshape(
            b, max_len, kvh, -1)
        np.testing.assert_array_equal(np.asarray(cache_d[name]), gathered)


@pytest.mark.slow  # 11 s; serving CI tier runs the full file
def test_early_exit_identical_to_full_scan(ff):
    """The while_loop early-exit path: identical tokens (and scores) to
    the full-length scan, with and without eos; without eos_id it simply
    runs the full length."""
    rs = np.random.RandomState(5)
    prompt = rs.randint(1, VOCAB, (2, 5)).astype(np.int32)
    probe = ff.generate(prompt, max_new_tokens=8)
    eos = int(probe[0, 5])
    full = ff.generate(prompt, max_new_tokens=8, eos_token_id=eos)
    fast = ff.generate(prompt, max_new_tokens=8, eos_token_id=eos,
                       early_exit=True)
    np.testing.assert_array_equal(full, fast)

    a, sa = ff.generate(prompt, max_new_tokens=6, eos_token_id=eos,
                        return_scores=True)
    b, sb = ff.generate(prompt, max_new_tokens=6, eos_token_id=eos,
                        return_scores=True, early_exit=True)
    np.testing.assert_array_equal(a, b)
    np.testing.assert_allclose(sa, sb, rtol=0, atol=0)

    no_eos = ff.generate(prompt, max_new_tokens=5, early_exit=True)
    np.testing.assert_array_equal(no_eos,
                                  ff.generate(prompt, max_new_tokens=5))

    # ragged prompts ride the same step body
    lengths = np.array([3, 5], np.int32)
    r_full = ff.generate(prompt, 6, eos_token_id=eos,
                         prompt_lengths=lengths)
    r_fast = ff.generate(prompt, 6, eos_token_id=eos,
                         prompt_lengths=lengths, early_exit=True)
    np.testing.assert_array_equal(r_full, r_fast)


def test_recompile_counter_flat_within_buckets(ff):
    """Power-of-two buckets: after one request has warmed a bucket, any
    mix of prompt lengths inside it (and any max_new_tokens) reuses the
    warm programs — the recompile counter must not move."""
    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                 max_seq_len=64)
    eng.run(_prompts(7, [5, 12]), max_new_tokens=4)   # warm buckets 8, 16
    warm = eng.recompile_count
    assert warm == 3  # prefill(8) + prefill(16) + the one decode program
    eng.run(_prompts(8, [6, 8, 3, 9, 16, 11, 2, 13]), max_new_tokens=7)
    assert eng.recompile_count == warm, \
        "mixed lengths within warm buckets must not recompile"
    # a NEW bucket is exactly one more prefill program
    eng.run(_prompts(9, [20]), max_new_tokens=4)
    assert eng.recompile_count == warm + 1


def test_poisoned_request_retired_without_stalling(ff, monkeypatch):
    """FF_FAULT=nan_loss@serve:3 poisons the 3rd admitted request's
    logits in-graph; the engine must retire exactly that request as
    failed (non-finite logits) while every other request completes with
    its solo-run tokens."""
    monkeypatch.setenv("FF_FAULT", "nan_loss@serve:3")
    faultinject.reset()
    try:
        prompts = _prompts(11, [5, 9, 3, 12, 7, 6])
        eng = ff.make_serving_engine(serve_slots=3, kv_page_size=4,
                                     max_seq_len=64)
        reqs = eng.run(prompts, max_new_tokens=5)
    finally:
        monkeypatch.delenv("FF_FAULT")
        faultinject.reset()
    states = [r.state for r in reqs]
    assert states[2] == "failed" and reqs[2].error == "non-finite logits"
    for i, r in enumerate(reqs):
        if i == 2:
            continue
        assert r.state == "done"
        solo = ff.generate(r.prompt[None, :], max_new_tokens=5)
        np.testing.assert_array_equal(np.asarray(r.tokens, np.int32),
                                      solo[0, r.prompt.size:])
    # the poisoned slot's pages were freed for reuse
    st = eng.stats()
    assert st["failed"] == 1 and st["free_pages"] == st["kv_pages"] - 1


@pytest.mark.slow  # 7 s; serving CI tier runs the full file
def test_page_pool_pressure_blocks_admission_not_progress(ff):
    """A pool too small for all slots at once: admission waits for
    retirements instead of deadlocking, and every request still finishes
    with its solo tokens."""
    # 2 slots x ceil(64/4)=16 pages would want 33; grant 21 — enough for
    # one max-size request (16+1) plus a small one, never two max-size
    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                 max_seq_len=64, kv_pages=21)
    prompts = _prompts(13, [30, 25, 6, 28])
    reqs = eng.run(prompts, max_new_tokens=4)
    assert [r.state for r in reqs] == ["done"] * 4
    for r in reqs:
        solo = ff.generate(r.prompt[None, :], max_new_tokens=4)
        np.testing.assert_array_equal(np.asarray(r.tokens, np.int32),
                                      solo[0, r.prompt.size:])


def test_serving_validation(ff):
    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                 max_seq_len=32)
    with pytest.raises(ValueError, match="max_seq_len"):
        eng.submit(np.arange(1, 30, dtype=np.int32), max_new_tokens=16)
    with pytest.raises(ValueError, match="empty"):
        eng.submit(np.zeros((0,), np.int32), max_new_tokens=4)
    with pytest.raises(ValueError, match="max_new_tokens"):
        eng.submit(np.arange(1, 5, dtype=np.int32), max_new_tokens=0)
    with pytest.raises(ValueError, match="kv_pages"):
        ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                               max_seq_len=32, kv_pages=4)
    with pytest.raises(ValueError, match="bucket"):
        eng2 = ff.make_serving_engine(decode_buckets=[8, 16],
                                      kv_page_size=4, max_seq_len=64)
        eng2.submit(np.arange(1, 20, dtype=np.int32), max_new_tokens=4)
    with pytest.raises(ValueError):
        FFConfig(batch_size=2, mesh_shape={"data": 1}, serve_slots=0)
    with pytest.raises(ValueError):
        FFConfig(batch_size=2, mesh_shape={"data": 1},
                 decode_buckets=[16, 8])


@pytest.mark.slow  # 17 s; serving CI tier runs the full file
def test_decode_chunk_invariance(ff):
    """decode_chunk trades dispatch overhead for retirement granularity
    ONLY: any chunk size produces identical tokens — including requests
    whose eos lands mid-chunk (the in-graph over-decode is truncated by
    the host) and whose max_new_tokens is not a chunk multiple."""
    prompts = _prompts(19, [5, 9, 3, 12])
    probe = ff.generate(prompts[0][None, :], max_new_tokens=10)
    eos = int(probe[0, prompts[0].size + 2])  # eos somewhere mid-stream
    outs = {}
    for chunk in (1, 3, 16):
        eng = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                     max_seq_len=64, decode_chunk=chunk,
                                     eos_id=eos)
        reqs = eng.run(prompts, max_new_tokens=10)
        assert [r.state for r in reqs] == ["done"] * 4
        outs[chunk] = [np.asarray(r.tokens, np.int32) for r in reqs]
    for chunk in (3, 16):
        for a, b in zip(outs[1], outs[chunk]):
            np.testing.assert_array_equal(
                a, b, err_msg=f"decode_chunk={chunk} changed tokens")
    # and chunk=1 equals the solo batch path under the same eos
    for p, got in zip(prompts, outs[1]):
        solo = ff.generate(p[None, :], max_new_tokens=10, eos_token_id=eos)
        new = solo[0, p.size:]
        hits = np.where(new == eos)[0]
        want = new[:hits[0] + 1] if hits.size else new
        np.testing.assert_array_equal(got, want)


@pytest.mark.slow  # 7 s; serving CI tier runs the full file
def test_explicit_buckets_and_per_request_max_new(ff):
    """Pinned decode_buckets honor their boundaries; per-request
    max_new_tokens mixes freely in one batch."""
    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                 max_seq_len=64, decode_buckets=[8, 24])
    rs = np.random.RandomState(17)
    reqs = [eng.submit(rs.randint(1, VOCAB, (L,)).astype(np.int32), m)
            for L, m in [(5, 3), (20, 6), (8, 2), (11, 5)]]
    assert [r.bucket for r in reqs] == [8, 24, 8, 24]
    while eng.step():
        pass
    for r in reqs:
        assert r.state == "done" and len(r.tokens) == r.max_new_tokens
        solo = ff.generate(r.prompt[None, :],
                           max_new_tokens=r.max_new_tokens)
        np.testing.assert_array_equal(np.asarray(r.tokens, np.int32),
                                      solo[0, r.prompt.size:])
