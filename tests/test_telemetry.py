"""Unified telemetry plane (runtime/telemetry.py, ISSUE 13).

Correctness anchors:
  * the registry is exact — labeled series are independent, counters
    survive a concurrent-increment stress bit-for-bit, histogram bucket
    math is pinned against hand-computed buckets and the Prometheus
    exposition format against a golden string;
  * the trace ring is bounded (fixed memory whatever the traffic) and a
    request's span tree stays CONNECTED across threads, replicas,
    failover resubmission and the prefill->decode handoff (one trace id
    rides the request everywhere);
  * zero behavior change: ``stats()``/``health()`` on engine and router
    still carry every pre-telemetry key (pinned superset lists) — the
    registry is an export plane over those dicts, not a replacement of
    their contract;
  * FF_FAULT injections annotate the trace at their fire site
    (``telemetry.fault_events()``) — a drill's trace shows where the
    fault landed;
  * ``FFConfig.telemetry="off"`` / ``set_enabled(False)`` short-circuit
    every emit (the bench's overhead control arm).
"""

import json
import threading
import time

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.models.llama import llama_lm
from flexflow_tpu.runtime import faultinject, telemetry
from flexflow_tpu.runtime.telemetry import Registry, Tracer, log_bounds

VOCAB = 61


@pytest.fixture(scope="module")
def ff():
    cfg = FFConfig(batch_size=2, mesh_shape={"data": 1})
    model = FFModel(cfg)
    _, logits = llama_lm(model, 2, seq_len=16, hidden=32, layers=1,
                         heads=2, kv_heads=2, vocab_size=VOCAB)
    model.compile(final_tensor=logits)
    return model


def _prompts(seed, lengths):
    rs = np.random.RandomState(seed)
    return [rs.randint(1, VOCAB, (n,)).astype(np.int32) for n in lengths]


# ---------------------------------------------------------------- registry


def test_counter_labeled_series_independent():
    reg = Registry()
    c = reg.counter("req_total", "requests", labels=("replica", "role"))
    c.labels("0", "mixed").inc()
    c.labels("0", "mixed").inc(2)
    c.labels("1", "decode").inc(5)
    assert c.labels("0", "mixed").get() == 3
    assert c.labels("1", "decode").get() == 5
    assert c.labels(replica="1", role="decode").get() == 5  # kw spelling
    assert len(c.children()) == 2


def test_gauge_set_and_label_free():
    reg = Registry()
    g = reg.gauge("depth", "queue depth")
    g.set(7)
    g.set(3)
    assert g.labels().get() == 3
    # idempotent re-registration returns the same family
    assert reg.gauge("depth", "queue depth") is g
    with pytest.raises(ValueError):
        reg.counter("depth")        # kind mismatch must raise


def test_label_arity_checked():
    reg = Registry()
    c = reg.counter("x_total", labels=("a",))
    with pytest.raises(ValueError):
        c.labels("1", "2")


def test_log_bounds():
    b = log_bounds(0.001, 0.01)
    assert b == (0.001, 0.002, 0.004, 0.008, 0.016)
    with pytest.raises(ValueError):
        log_bounds(0, 1)
    with pytest.raises(ValueError):
        log_bounds(1, 2, growth=1.0)


def test_histogram_bucket_math():
    reg = Registry()
    h = reg.histogram("lat", "latency", labels=("r",),
                      bounds=(0.001, 0.01, 0.1, 1.0))
    ch = h.labels("0")
    for v in (0.0005, 0.001, 0.005, 0.05, 0.5, 5.0, 50.0):
        ch.observe(v)
    # le-semantics: a value equal to a bound lands IN that bucket
    assert ch.counts == [2, 1, 1, 1, 2]    # last = +Inf bucket
    assert ch.count == 7
    assert ch.sum == pytest.approx(55.5565)
    # cumulative counts in the exposition
    text = reg.to_prometheus()
    assert 'lat_bucket{r="0",le="0.001"} 2' in text
    assert 'lat_bucket{r="0",le="1"} 5' in text
    assert 'lat_bucket{r="0",le="+Inf"} 7' in text
    assert 'lat_count{r="0"} 7' in text


def test_histogram_quantiles():
    reg = Registry()
    h = reg.histogram("q", bounds=(1.0, 2.0, 4.0, 8.0))
    ch = h.labels()
    assert ch.quantile(0.5) == 0.0          # empty
    for _ in range(100):
        ch.observe(1.5)                      # all in the (1, 2] bucket
    q50 = ch.quantile(0.50)
    assert 1.0 <= q50 <= 2.0                 # exact to the bucket
    ch.observe(100.0)                        # +Inf bucket clamps
    assert ch.quantile(1.0) == 8.0


def test_concurrent_increment_stress():
    reg = Registry()
    c = reg.counter("stress_total", labels=("t",))
    h = reg.histogram("stress_lat", bounds=(0.5, 1.0))
    n_threads, per = 8, 5000

    def work(i):
        ch = c.labels(str(i % 2))
        for _ in range(per):
            ch.inc()
            h.observe(0.75)

    threads = [threading.Thread(target=work, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = sum(ch.get() for ch in c.children())
    assert total == n_threads * per          # no lost increments
    assert h.labels().count == n_threads * per
    assert h.labels().counts[1] == n_threads * per


def test_prometheus_golden():
    """Exposition format pinned: HELP/TYPE lines, label quoting,
    histogram cumulative buckets + sum + count, integer rendering."""
    reg = Registry()
    c = reg.counter("ff_req_total", "requests served", labels=("replica",))
    c.labels("0").inc(4)
    g = reg.gauge("ff_up", "liveness")
    g.set(1)
    h = reg.histogram("ff_lat_seconds", "latency", bounds=(0.5, 1.0))
    h.observe(0.25)
    h.observe(0.75)
    expected = (
        "# HELP ff_req_total requests served\n"
        "# TYPE ff_req_total counter\n"
        'ff_req_total{replica="0"} 4\n'
        "# HELP ff_up liveness\n"
        "# TYPE ff_up gauge\n"
        "ff_up 1\n"
        "# HELP ff_lat_seconds latency\n"
        "# TYPE ff_lat_seconds histogram\n"
        'ff_lat_seconds_bucket{le="0.5"} 1\n'
        'ff_lat_seconds_bucket{le="1"} 2\n'
        'ff_lat_seconds_bucket{le="+Inf"} 2\n'
        "ff_lat_seconds_sum 1\n"
        "ff_lat_seconds_count 2\n")
    assert reg.to_prometheus() == expected


def test_json_snapshot_shape():
    reg = Registry()
    reg.counter("a_total", "x", labels=("k",)).labels("v").inc(2)
    reg.histogram("b", bounds=(1.0, 2.0)).observe(1.5)
    snap = reg.snapshot()
    assert snap["a_total"]["type"] == "counter"
    assert snap["a_total"]["series"] == [
        {"labels": {"k": "v"}, "value": 2}]
    row = snap["b"]["series"][0]
    assert row["count"] == 1 and row["buckets"] == {"1": 0, "2": 1}
    json.dumps(snap)    # must be JSON-serializable as-is


def test_collector_weakref_does_not_leak():
    reg = Registry()

    class Obj:
        def collect(self, r):
            r.gauge("from_obj").set(1)

    o = Obj()
    reg.add_collector(o.collect)
    reg.to_prometheus()
    assert reg._families["from_obj"].labels().get() == 1
    del o
    import gc

    gc.collect()
    reg.to_prometheus()                     # dead collector pruned, no crash
    assert reg._collectors == []


# ---------------------------------------------------------------- tracing


def test_trace_ring_bounded():
    tr = Tracer(cap=64)
    for i in range(500):
        tr.instant("e", trace_id=f"t{i}")
    assert len(tr) == 64
    evs = tr.events()
    assert evs[0]["args"]["trace_id"] == "t436"    # oldest fell off


def test_span_nesting_thread_local_and_tree():
    tr = Tracer()
    with tr.span("root", trace_id="tX", track="a"):
        with tr.span("child", trace_id="tX", track="b"):
            time.sleep(0.001)
        tr.instant("mark", trace_id="tX", track="a")
    tree = tr.trace_tree("tX")
    assert tree["root"]["name"] == "root"
    assert tree["complete"], tree
    assert set(tree["names"]) == {"root", "child"}
    assert [e["name"] for e in tree["annotations"]] == ["mark"]
    assert tree["tracks"] == ["a", "b"]


def test_current_trace_id_follows_span_stack():
    with telemetry.tracer().span("outer", trace_id="ctx1"):
        assert telemetry.current_trace_id() == "ctx1"
        with telemetry.tracer().span("inner", trace_id="ctx2"):
            assert telemetry.current_trace_id() == "ctx2"
        assert telemetry.current_trace_id() == "ctx1"
    assert telemetry.current_trace_id() is None


def test_cross_thread_begin_end():
    tr = Tracer()
    h = tr.begin("work", trace_id="tc", track="r0")

    def closer():
        tr.end(h, state="done")

    t = threading.Thread(target=closer)
    t.start()
    t.join()
    evs = tr.events(trace_id="tc")
    assert len(evs) == 1 and evs[0]["args"]["state"] == "done"
    tr.end(h)           # double-end is a no-op
    tr.end(0)           # zero handle (telemetry off) is a no-op
    assert len(tr.events(trace_id="tc")) == 1


def test_set_enabled_short_circuits():
    reg = Registry()
    c = reg.counter("off_total")
    h = reg.histogram("off_lat", bounds=(1.0,))
    tr = Tracer()
    prev = telemetry.set_enabled(False)
    try:
        sp = tr.span("x", trace_id="off")
        assert sp is telemetry.NULL_SPAN
        with sp:
            pass
        assert tr.begin("y") == 0
        tr.instant("z", trace_id="off")
        c.inc()
        h.observe(0.5)
        assert len(tr) == 0
        assert c.labels().get() == 0 and h.labels().count == 0
    finally:
        telemetry.set_enabled(prev)
    c.inc()
    assert c.labels().get() == 1


def test_chrome_trace_export(tmp_path):
    tr = telemetry.tracer()
    with tr.span("exported", trace_id="exp1", track="t"):
        pass
    path = str(tmp_path / "trace.json")
    n = telemetry.export_chrome_trace(path)
    assert n >= 1
    doc = json.load(open(path))
    assert isinstance(doc["traceEvents"], list)
    for ev in doc["traceEvents"]:
        assert {"name", "ph", "ts", "pid", "tid"} <= set(ev)
        if ev["ph"] == "X":
            assert "dur" in ev


# ------------------------------------------------------- fault annotations


def test_fault_injection_annotates_trace(monkeypatch):
    monkeypatch.setenv("FF_FAULT", "io_fail@save:1")
    faultinject.reset()
    before = len(telemetry.fault_events())
    with pytest.raises(faultinject.InjectedFault):
        faultinject.maybe_fail("io_fail", "save")
    evs = telemetry.fault_events()
    assert len(evs) == before + 1
    assert evs[-1]["args"]["kind"] == "io_fail"
    assert evs[-1]["args"]["site"] == "save"
    # the counter series fired too
    text = telemetry.registry().to_prometheus()
    assert 'ff_fault_fired_total{kind="io_fail",site="save"}' in text
    monkeypatch.delenv("FF_FAULT")
    faultinject.reset()


# ----------------------------------------------------------------- logger


def test_logger_env_alias_precedence(monkeypatch):
    from flexflow_tpu import logger as fflog

    monkeypatch.delenv("FLEXFLOW_LOG_LEVEL", raising=False)
    monkeypatch.setenv("FF_LOGGING_LEVEL", "debug")
    assert fflog._env("FLEXFLOW_LOG_LEVEL", "FF_LOGGING_LEVEL") == "debug"
    monkeypatch.setenv("FLEXFLOW_LOG_LEVEL", "error")
    assert fflog._env("FLEXFLOW_LOG_LEVEL", "FF_LOGGING_LEVEL") == "error"


def test_logger_json_format_carries_trace_id():
    import logging

    from flexflow_tpu.logger import _JsonFormatter

    fmt = _JsonFormatter()
    rec = logging.LogRecord("flexflow_tpu", logging.INFO, "f.py", 1,
                            "hello %s", ("world",), None)
    with telemetry.tracer().span("logline", trace_id="log-7"):
        row = json.loads(fmt.format(rec))
    assert row["msg"] == "hello world"
    assert row["level"] == "info"
    assert row["trace_id"] == "log-7"
    row2 = json.loads(fmt.format(rec))
    assert "trace_id" not in row2           # no active span -> no id


# ----------------------------------------------- zero-behavior-change pins

# stats()/health() keys as of the PR BEFORE telemetry (ISSUE 12 state):
# the telemetry plane may ADD keys, never remove or rename these.
ENGINE_STATS_KEYS = {
    "requests", "completed", "failed", "timeouts", "tokens_generated",
    "decode_steps", "recompiles", "occupancy", "occupied_slot_steps",
    "ttft_p50_ms", "ttft_p99_ms", "free_pages", "kv_pages",
    "kv_page_size", "serve_slots", "kv_cache_dtype", "weight_dtype",
    "kv_pool_bytes", "kv_bytes_per_token", "tokens_per_pool_gb",
    "kv_capacity_vs_bf16", "kv_effective_page_capacity", "pages_in_use",
    "kv_pages_cached", "kv_pages_shared", "host_kv_pages",
    "kv_pages_hbm", "kv_pages_host", "tier_demotions", "tier_promotions",
    "tier_demote_failures", "tier_promote_failures",
    "tier_host_evictions", "tier_pending_migrations",
    "prefill_only_requests", "prefix_slab_exports", "prefix_slab_imports",
    "prefix_pages_imported", "prefix_cache", "prefix_lookups",
    "prefix_hits", "prefix_hit_rate", "prefill_tokens_saved",
    "prefix_evictions", "prefix_refs_live", "speculate_k",
    "spec_proposed", "spec_accepted", "spec_accept_rate",
    "paged_attention_impl", "pages_touched", "last_pages_touched",
    "kernel_tune_hits", "kernel_tune_misses",
}
ENGINE_HEALTH_KEYS = {
    "status", "admitting", "active_slots", "queued", "serve_slots",
    "free_pages", "completed", "failed", "timeouts", "occupancy",
    "recompiles", "pages_in_use", "kv_pages_shared", "prefix_hit_rate",
    "spec_accept_rate", "kv_cache_dtype", "weight_dtype",
    "kv_bytes_per_token", "tokens_per_pool_gb",
}
ROUTER_STATS_KEYS = {
    "replicas", "alive", "roles", "submitted", "dispatched", "completed",
    "failed", "timeouts", "rejected", "fenced", "resubmitted",
    "handoffs", "handoff_fallbacks", "queued", "max_queue",
    "ttft_p50_ms", "ttft_p99_ms", "affinity_keys", "affinity_host_keys",
    "per_replica", "fleet",
}
ROUTER_HEALTH_KEYS = {
    "status", "admitting", "alive", "replicas", "queued", "outstanding",
    "fenced", "max_queue",
}


def test_engine_stats_health_keys_superset(ff):
    eng = ff.make_serving_engine(max_seq_len=32, kv_page_size=8)
    st = eng.stats()
    missing = ENGINE_STATS_KEYS - set(st)
    assert not missing, f"stats() lost pre-telemetry keys: {missing}"
    hl = eng.health()
    missing = ENGINE_HEALTH_KEYS - set(hl)
    assert not missing, f"health() lost pre-telemetry keys: {missing}"


def test_router_stats_health_keys_superset(ff):
    router = ff.make_serving_router(replicas=2, max_seq_len=32,
                                    kv_page_size=8, start=False)
    try:
        st = router.stats()
        missing = ROUTER_STATS_KEYS - set(st)
        assert not missing, f"stats() lost pre-telemetry keys: {missing}"
        hl = router.health()
        missing = ROUTER_HEALTH_KEYS - set(hl)
        assert not missing, f"health() lost pre-telemetry keys: {missing}"
    finally:
        router.close()


# ------------------------------------------------- engine/router telemetry


def test_engine_emits_histograms_and_spans(ff):
    eng = ff.make_serving_engine(max_seq_len=32, kv_page_size=8)
    eng.set_telemetry_identity("t0", "solo-test")
    reqs = eng.run(_prompts(3, [5, 9, 12]), max_new_tokens=4)
    assert all(r.state == "done" for r in reqs)
    reg = telemetry.registry()
    hist = reg.histogram("ff_serving_ttft_seconds", labels=("replica",
                                                            "role"))
    assert hist.labels("t0", "solo-test").count == 3
    itl = reg.histogram("ff_serving_intertoken_seconds",
                        labels=("replica", "role"))
    assert itl.labels("t0", "solo-test").count == 3 * 3  # 4 tokens -> 3 gaps
    qw = reg.histogram("ff_serving_queue_wait_seconds",
                       labels=("replica", "role"))
    assert qw.labels("t0", "solo-test").count == 3
    # every request has a connected span tree: queue_wait + prefill
    # (cold) + decode, and the decode span closed at retirement
    for r in reqs:
        tree = telemetry.trace_tree(r.trace_id)
        assert {"queue_wait", "prefill", "decode"} <= set(tree["names"])
        decode = [e for e in tree["spans"] if e["name"] == "decode"][0]
        assert decode["args"]["state"] == "done"
        assert decode["args"]["tokens"] == 4
        prefill = [e for e in tree["spans"] if e["name"] == "prefill"][0]
        assert prefill["args"]["kind"] == "cold"
    # the scrape exports the stats() dict as labeled gauges
    text = reg.to_prometheus()
    assert ('ff_serving_completed{replica="t0",role="solo-test"}'
            in text)
    assert 'ff_serving_ttft_seconds_bucket{replica="t0"' in text


def test_engine_prefix_hit_span_kind(ff):
    eng = ff.make_serving_engine(max_seq_len=48, kv_page_size=8)
    rs = np.random.RandomState(5)
    system = rs.randint(1, VOCAB, (16,)).astype(np.int32)
    p1 = np.concatenate([system, rs.randint(1, VOCAB, (3,)).astype(np.int32)])
    p2 = np.concatenate([system, rs.randint(1, VOCAB, (4,)).astype(np.int32)])
    r1 = eng.run([p1], max_new_tokens=2)[0]
    r2 = eng.run([p2], max_new_tokens=2)[0]
    k1 = [e for e in telemetry.trace_tree(r1.trace_id)["spans"]
          if e["name"] == "prefill"][0]["args"]
    k2 = [e for e in telemetry.trace_tree(r2.trace_id)["spans"]
          if e["name"] == "prefill"][0]["args"]
    assert k1["kind"] == "cold" and k1["matched_pages"] == 0
    assert k2["kind"] == "hit" and k2["matched_pages"] == 2


def test_engine_telemetry_off_is_silent(ff, tmp_path):
    from flexflow_tpu.runtime import flightrec

    cfg_prev = ff.config.telemetry
    fr_prev = ff.config.flight_recorder_dir
    ff.config.telemetry = "off"
    # the flight recorder + SLO evaluator (ISSUE 15) must short-circuit
    # at the SAME single predicate: even with a bundle directory and an
    # SLO spec configured, "off" silences them alongside every emit
    ff.config.flight_recorder_dir = str(tmp_path)
    ff.config.slo_ttft_p99_s = 0.001
    try:
        eng = ff.make_serving_engine(max_seq_len=32, kv_page_size=8)
        eng.set_telemetry_identity("off0", "off-test")
        ring_before = len(telemetry.tracer())
        log_before = len(flightrec.log_ring())
        reqs = eng.run(_prompts(7, [5, 9]), max_new_tokens=3)
        assert all(r.state == "done" for r in reqs)
        hist = telemetry.registry().histogram(
            "ff_serving_ttft_seconds", labels=("replica", "role"))
        assert hist.labels("off0", "off-test").count == 0
        assert not telemetry.tracer().events(trace_id=reqs[0].trace_id)
        assert len(telemetry.tracer()) == ring_before
        # engine construction itself configured the recorder with
        # telemetry="off" (the call is unconditional for exactly this):
        # even with a directory and SLO specs set, every piece is silent
        flightrec.trip("engine_exception", replica="off0")
        assert flightrec.recorder().wait_pending(2.0)
        assert flightrec.list_bundles(str(tmp_path)) == []
        assert flightrec.slo_monitor().maybe_evaluate() == []
        assert flightrec.slo_monitor().evaluate() == []
        assert len(flightrec.log_ring()) == log_before
    finally:
        ff.config.telemetry = cfg_prev
        ff.config.flight_recorder_dir = fr_prev
        ff.config.slo_ttft_p99_s = 0.0
        flightrec.reset()


def test_router_trace_tree_complete(ff):
    router = ff.make_serving_router(replicas=1, max_seq_len=32,
                                    kv_page_size=8, start=False)
    try:
        reqs = router.run(_prompts(11, [5, 9, 14]), max_new_tokens=4,
                          timeout=600)
        assert all(r.state == "done" for r in reqs)
        for r in reqs:
            tree = telemetry.trace_tree(r.trace_id)
            assert tree["complete"], tree
            assert tree["root"]["name"] == "request"
            assert tree["root"]["args"]["state"] == "done"
            assert {"queue_wait", "prefill", "decode"} <= set(tree["names"])
            assert any(e["name"] == "dispatch"
                       for e in tree["annotations"])
        recent = router.recent_traces()
        assert {t["trace_id"] for t in recent} >= \
            {r.trace_id for r in reqs}
    finally:
        router.close()


def test_failover_span_continuity(ff, monkeypatch):
    """A crash-failover request keeps ONE trace: spans on both replicas
    under the same root, a resubmit annotation in between, and the
    fault annotation marks where the drill landed."""
    # crash at the 2nd busy tick: tick 1 genuinely ADMITTED work on
    # replica 0 (prefills ran), so failed-over traces carry spans from
    # both replicas; enough requests that work is still queued/in-flight
    # when the crash lands
    monkeypatch.setenv("FF_FAULT", "crash(2)@replica:0")
    faultinject.reset()
    try:
        # decode_chunk=2: a request takes 4+ ticks, so tick-2 work is
        # genuinely mid-decode when the replica dies
        router = ff.make_serving_router(replicas=2, max_seq_len=32,
                                        kv_page_size=8,
                                        health_timeout_s=60,
                                        decode_chunk=2, start=False)
        reqs = router.run(_prompts(13, [6, 10, 15, 7, 11, 9,
                                        8, 12, 5, 14, 10, 7]),
                          max_new_tokens=8, timeout=600)
        st = router.stats()
        assert st["fenced"] == 1 and st["resubmitted"] >= 1
        resub = [r for r in reqs if r.attempts == 2]
        assert resub, "the crash was supposed to catch work in flight"
        for r in resub:
            assert r.state == "done"
            tree = telemetry.trace_tree(r.trace_id)
            assert tree["complete"], tree
            assert tree["root"]["args"]["state"] == "done"
            marks = [e["name"] for e in tree["annotations"]]
            assert "resubmit" in marks
        # at least one failed-over request was ADMITTED on the dead
        # replica first: its one trace carries prefill spans from both
        # replicas (the span-continuity acceptance)
        assert any(
            len({e["pid"] for e in
                 telemetry.trace_tree(r.trace_id)["spans"]
                 if e["name"] == "prefill"}) == 2
            for r in resub), "no trace crossed both replicas"
        # the drill's fault annotation is present
        faults = telemetry.fault_events()
        assert any(e["args"]["kind"] == "crash"
                   and e["args"]["site"] == "replica" for e in faults)
        router.close()
    finally:
        monkeypatch.delenv("FF_FAULT", raising=False)
        faultinject.reset()


@pytest.mark.slow
def test_handoff_span_continuity(ff):
    """A prefill->decode handoff request keeps ONE trace: handoff_export
    on the prefill replica, handoff_import + hit prefill + decode on the
    decode replica, all inside the router's root span."""
    router = ff.make_serving_router(
        replicas=2, roles=["prefill", "decode"], max_seq_len=48,
        kv_page_size=8, start=False)
    try:
        rs = np.random.RandomState(17)
        system = rs.randint(1, VOCAB, (16,)).astype(np.int32)
        prompts = [np.concatenate(
            [system, rs.randint(1, VOCAB, (3,)).astype(np.int32)])
            for _ in range(4)]
        reqs = router.run(prompts, max_new_tokens=4, timeout=600)
        assert all(r.state == "done" for r in reqs)
        handed = [r for r in reqs if r.handoff]
        assert handed, "no request ever handed off"
        for r in handed:
            tree = telemetry.trace_tree(r.trace_id)
            assert tree["complete"], tree
            names = set(tree["names"])
            assert {"handoff_export", "handoff_import", "prefill",
                    "decode"} <= names, names
            # export on replica0 (prefill), decode on replica1
            by = {e["name"]: e["pid"] for e in tree["spans"]}
            assert by["handoff_export"] == "replica0"
            assert by["decode"] == "replica1"
    finally:
        router.close()


# --------------------------------------------------------- training spans


def test_fit_emits_step_spans_and_histogram():
    from flexflow_tpu import (ActiMode, LossType, MetricsType,
                              SGDOptimizer, SingleDataLoader)

    # host-resident data + no prefetch: the per-step (t_b..t_d) loop the
    # span emitter instruments
    cfg = FFConfig(batch_size=16, epochs=1, seed=3,
                   device_resident_data=False, native_dataloader=False,
                   prefetch_depth=0)
    model = FFModel(cfg)
    x = model.create_tensor([16, 8], name="x")
    t = model.dense(x, 16, ActiMode.AC_MODE_RELU, name="fc1")
    model.dense(t, 4, name="out")
    model.compile(SGDOptimizer(lr=0.1),
                  LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                  [MetricsType.METRICS_ACCURACY])
    rs = np.random.RandomState(7)
    SingleDataLoader(model, x, rs.randn(64, 8).astype(np.float32))
    SingleDataLoader(model, model.label_tensor,
                     rs.randint(0, 4, (64, 1)).astype(np.int32))
    before = telemetry.registry().histogram(
        "ff_train_step_seconds").labels().count
    model.fit(verbose=False)
    after = telemetry.registry().histogram(
        "ff_train_step_seconds").labels().count
    assert after > before
    steps = telemetry.tracer().events(name="train_step")
    assert steps, "fit() emitted no train_step spans"
    sid = steps[-1]["args"]["trace_id"]
    names = set(telemetry.trace_tree(sid)["names"])
    assert "host_wait" in names and "dispatch" in names
