"""Elastic fleet (ISSUE 20): SLO-driven autoscaling + preemption-tolerant
serving with exactly-once state evacuation.

Correctness anchors:
  * the AutoscalePolicy is a windowed hysteresis controller: a breach
    must PERSIST across ``breach_windows`` consecutive windows before
    the fleet grows, idleness must persist across ``idle_windows``
    before it shrinks, every action starts a cooldown, and min/max
    bounds always win — a breach storm thrashes counters, never
    replicas;
  * live membership preserves every existing contract: a scaled-out
    replica serves token-identical greedy streams, a scale-in requeues
    queued-never-admitted work automatically (the PR-5 drain contract
    left it parked on the retiring engine — the regression pinned
    here), and no request is stranded when remove_replica() races
    fresh submissions;
  * survivors inherit the retiree's state: hot prefix pages land
    bitwise-identical (per-namespace) on a survivor and serve warm
    hits, and registered LoRA adapters keep serving with no caller
    re-register;
  * preemption is exactly-once: every queued/in-flight request on the
    preempted replica completes exactly once on a survivor with its
    solo-identical stream (losses NOT counted — a later real failover
    still fits the cap), and a deadline-starved evacuation degrades to
    a clean fence, never a stall, duplicate, or lost request.

Drills are deterministic via FF_FAULT (preempt(<deadline_ms>)@replica:<r>,
slow_evac(<ms>)@evacuate:<n> — runtime/faultinject.py).
"""

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.models.llama import llama_lm
from flexflow_tpu.runtime import faultinject
from flexflow_tpu.runtime.autoscale import AutoscalePolicy, PlacementAdvisor

VOCAB = 89


@pytest.fixture(scope="module")
def ff():
    cfg = FFConfig(batch_size=2, mesh_shape={"data": 1})
    model = FFModel(cfg)
    _, logits = llama_lm(model, 2, seq_len=16, hidden=64, layers=2,
                         heads=4, kv_heads=2, vocab_size=VOCAB)
    model.compile(final_tensor=logits)
    return model


def _prompts(seed, lengths):
    rs = np.random.RandomState(seed)
    return [rs.randint(1, VOCAB, (L,)).astype(np.int32) for L in lengths]


def _solo_check(ff, reqs, max_new):
    for r in reqs:
        solo = ff.generate(r.prompt[None, :], max_new_tokens=max_new)
        np.testing.assert_array_equal(
            np.asarray(r.tokens, np.int32), solo[0, r.prompt.size:],
            err_msg=f"request {r.rid} (attempts {r.attempts}, replica "
                    f"{r.replica}) diverged from its solo run")


def _assert_slab_bitwise(got, ref):
    """Two page slabs carry the SAME prefix: tokens, salted namespace,
    and every pool array of every page, bitwise."""
    np.testing.assert_array_equal(got["tokens"], ref["tokens"])
    assert got["ns"] == ref["ns"], "namespace changed in evacuation"
    assert len(got["payload"]) == len(ref["payload"])
    for gp, rp in zip(got["payload"], ref["payload"]):
        assert gp.keys() == rp.keys()
        for key in gp:
            assert gp[key].keys() == rp[key].keys()
            for name in gp[key]:
                np.testing.assert_array_equal(
                    gp[key][name], rp[key][name],
                    err_msg=f"page array {key}/{name} not bitwise")


def _arm_fault(monkeypatch, spec):
    monkeypatch.setenv("FF_FAULT", spec)
    faultinject.reset()


def _disarm_fault(monkeypatch):
    monkeypatch.delenv("FF_FAULT", raising=False)
    faultinject.reset()


# ---- policy state machine (fake fleet, no model: tier-1 fast) ------------


class _FakeCfg:
    telemetry = "off"           # keep the fake off the global registries
    slo_window_s = 10.0
    dcn_mesh_shape = {"data": 2}
    autoscale_min_replicas = 1
    autoscale_max_replicas = 3
    autoscale_breach_windows = 2
    autoscale_idle_windows = 3
    autoscale_cooldown_s = 30.0


class _FakeModel:
    config = _FakeCfg()


class _FakeRouter:
    """Just enough fleet for the policy: health(), stats(), and the two
    actuators, with a scriptable load signal."""

    def __init__(self, replicas=2):
        self.model = _FakeModel()
        self.alive = replicas
        self.queued = 0
        self.outstanding = 0
        self.added = []
        self.removed = []

    def health(self):
        return {"alive": self.alive, "queued": self.queued,
                "outstanding": self.outstanding}

    def stats(self):
        rows = [{"replica": r, "fenced": False, "retired": False,
                 "suspended": False, "outstanding": r, "queued": 0}
                for r in range(self.alive)]
        return {"alive": self.alive, "per_replica": rows,
                "fleet": {"pages_by_tier": {"hbm": 8, "host": 0}},
                "evacuated_pages": 0, "evacuation_bytes": 0}

    def add_replica(self):
        self.alive += 1
        self.added.append(self.alive - 1)
        return self.alive - 1

    def remove_replica(self, r, **kw):
        self.alive -= 1
        self.removed.append(r)
        return {"replica": r, "requeued": 0, "fenced": False}


class _FakeSLO:
    def __init__(self):
        self.rows = []

    def maybe_evaluate(self, now=None):
        return []

    def breaches(self):
        return self.rows

    def __getattr__(self, name):
        # the monkeypatched accessor is global: engines/routers under
        # test call rebaseline()/add_source()/... on membership changes
        # too — absorb everything that is not the scripted read surface
        return lambda *a, **kw: None


def _policy(monkeypatch, router, **kw):
    slo = _FakeSLO()
    from flexflow_tpu.runtime import autoscale as A
    monkeypatch.setattr(A.flightrec, "slo_monitor", lambda: slo)
    return AutoscalePolicy(router, **kw), slo


def test_autoscale_breach_streak_hysteresis_and_cooldown(monkeypatch):
    """One bad window never scales; a persistent queue_wait breach does;
    the action zeroes the streak and starts a cooldown that suppresses
    (and counts) the next trigger; an unrelated SLO never triggers."""
    rt = _FakeRouter(replicas=2)
    pol, slo = _policy(monkeypatch, rt, max_replicas=5)
    breach = [{"slo": "queue_wait_p99", "replica": -1, "value": 2.0,
               "bound": 0.5, "ok_streak": 0, "windows": 3}]
    slo.rows = breach
    assert pol.tick() is None, "breach window 1 of 2 must not act"
    assert pol.tick() == "scale_out" and rt.added == [2]
    st = pol.state()
    assert st["breach_streak"] == 0 and st["scale_outs"] == 1
    # cooldown: the streak re-arms but the action is suppressed
    assert pol.tick() is None and pol.tick() is None
    assert pol.state()["cooldown_blocks"] >= 1 and rt.alive == 3
    # a quality SLO (hit rate) is NOT a capacity signal
    slo.rows = [{"slo": "prefix_hit_rate", "replica": -1, "value": 0.1,
                 "bound": 0.5, "ok_streak": 0, "windows": 3}]
    pol2, _ = _policy(monkeypatch, _FakeRouter(replicas=1))
    for _ in range(5):
        assert pol2.tick() is None
    assert pol2.state()["breach_streak"] == 0


def test_autoscale_max_bound_blocks_scale_out(monkeypatch):
    rt = _FakeRouter(replicas=3)         # already at max_replicas
    pol, slo = _policy(monkeypatch, rt)
    slo.rows = [{"slo": "ttft_p99", "replica": 0, "value": 9.0,
                 "bound": 1.0, "ok_streak": 0, "windows": 2}]
    for _ in range(4):
        assert pol.tick() is None
    assert rt.added == [] and pol.state()["bound_blocks"] >= 1


def test_autoscale_idle_streak_scale_in_and_min_bound(monkeypatch):
    """Sustained idleness retires the least-loaded replica; busy-but-ok
    windows reset the idle streak; min_replicas always wins."""
    rt = _FakeRouter(replicas=2)
    pol, _ = _policy(monkeypatch, rt, cooldown_s=0.0)
    assert pol.tick() is None and pol.tick() is None
    # a busy window resets the calm
    rt.queued = 3
    assert pol.tick() is None and pol.state()["idle_streak"] == 0
    rt.queued = 0
    for _ in range(2):
        assert pol.tick() is None
    assert pol.tick() == "scale_in"
    assert rt.removed == [0], "least-outstanding replica retires first"
    # now at min_replicas: idleness can never empty the fleet
    for _ in range(6):
        pol.tick()
    assert rt.alive == 1 and pol.state()["bound_blocks"] >= 1


def test_autoscale_knob_validation_and_state_keys():
    rt = _FakeRouter()
    with pytest.raises(ValueError, match="min_replicas"):
        AutoscalePolicy(rt, min_replicas=0)
    with pytest.raises(ValueError, match="max_replicas"):
        AutoscalePolicy(rt, min_replicas=4, max_replicas=2)
    st = AutoscalePolicy(rt).state()
    for k in ("breach_streak", "idle_streak", "cooldown_remaining_s",
              "scale_outs", "scale_ins", "cooldown_blocks",
              "bound_blocks", "last_action", "events"):
        assert k in st


def test_placement_advisor_prices_ici_vs_dcn():
    """The advisor reuses the search's interconnect constants: ICI while
    the modeled transfer fits the budget, DCN (with the penalty ratio
    recorded) once it does not — the decision is priced, not guessed."""
    adv = PlacementAdvisor(budget_s=1.0)
    small = adv.place(1 << 20)
    assert small["tier"] == "ici" and small["dcn_s"] > small["ici_s"]
    assert small["dcn_penalty_x"] > 1.0
    huge = adv.place(10 ** 12)          # ~22 s on ICI: over any warmup budget
    assert huge["tier"] == "dcn"
    assert huge["ici_s"] > 1.0


def test_config_elastic_knob_validation():
    base = dict(batch_size=2, mesh_shape={"data": 1})
    with pytest.raises(ValueError, match="autoscale_min_replicas"):
        FFConfig(autoscale_min_replicas=0, **base)
    with pytest.raises(ValueError, match="autoscale_max_replicas"):
        FFConfig(autoscale_min_replicas=3, autoscale_max_replicas=2, **base)
    with pytest.raises(ValueError, match="autoscale_breach_windows"):
        FFConfig(autoscale_breach_windows=0, **base)
    with pytest.raises(ValueError, match="autoscale_cooldown_s"):
        FFConfig(autoscale_cooldown_s=-1.0, **base)
    with pytest.raises(ValueError, match="preempt_deadline_s"):
        FFConfig(preempt_deadline_s=0.0, **base)
    cfg = FFConfig.parse_args([
        "--autoscale-min-replicas", "2",
        "--autoscale-max-replicas", "5",
        "--autoscale-breach-windows", "3",
        "--autoscale-idle-windows", "9",
        "--autoscale-cooldown-s", "7.5",
        "--preempt-deadline-s", "2.0"])
    assert (cfg.autoscale_min_replicas, cfg.autoscale_max_replicas) \
        == (2, 5)
    assert (cfg.autoscale_breach_windows, cfg.autoscale_idle_windows) \
        == (3, 9)
    assert cfg.autoscale_cooldown_s == 7.5
    assert cfg.preempt_deadline_s == 2.0


def test_engine_reclaim_queued_drains_parked_queue(ff):
    """The PR-5 drain contract left queued-never-admitted requests
    parked on a draining engine; reclaim_queued() hands them back so a
    scale-in can requeue them (the ISSUE-20 bugfix)."""
    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                 max_seq_len=64)
    ps = _prompts(21, [5, 7, 3])
    reqs = [eng.submit(p, max_new_tokens=4) for p in ps]
    got = eng.reclaim_queued()
    assert [id(r) for r in got] == [id(r) for r in reqs]
    assert eng.load()["queued"] == 0
    assert eng.reclaim_queued() == []


# ---- live membership + preemption drills (model fixture: slow) -----------


@pytest.mark.slow  # 20 s; elastic_serve CI tier runs the full file
def test_scale_out_serves_token_identical(ff):
    """add_replica() on a live, mid-flood fleet: the newcomer is warmed
    before admission, takes real work, and every stream stays
    solo-identical; the ledger and /healthz see the grown fleet."""
    router = ff.make_serving_router(replicas=1, serve_slots=2,
                                    kv_page_size=4, max_seq_len=64,
                                    start=False)
    try:
        router.warmup(_prompts(6, [5, 9]), max_new_tokens=2)
        prompts = _prompts(31, [5, 9, 3, 12, 7, 6, 11, 4])
        reqs = [router.submit(p, max_new_tokens=5) for p in prompts]
        router.start()
        r_new = router.add_replica()
        assert r_new == 1
        router.wait(reqs, timeout=300)
        assert [r.state for r in reqs] == ["done"] * len(prompts)
        _solo_check(ff, reqs, 5)
        st = router.stats()
        assert st["scale_outs"] == 1 and st["alive"] == 2
        assert router.health()["replicas"] == 2
        # the newcomer genuinely served (warmup on the new engine plus
        # dispatched flood work)
        more = router.run(_prompts(32, [6, 8, 5, 9]), max_new_tokens=4,
                          timeout=300)
        assert any(r.replica == r_new for r in reqs + more), \
            "scaled-out replica never took work"
        _solo_check(ff, more, 4)
    finally:
        router.close()


@pytest.mark.slow  # 30 s; elastic_serve CI tier runs the full file
def test_scale_in_requeues_and_survivor_inherits(ff):
    """remove_replica() racing fresh submissions strands nothing: parked
    never-admitted work is requeued automatically and completes
    solo-identical on survivors. The retiree's hot prefix pages land
    BITWISE on a survivor (namespace preserved) and serve warm hits."""
    rs = np.random.RandomState(13)
    system = rs.randint(1, VOCAB, (8,)).astype(np.int32)  # 2 full pages
    shared = [np.concatenate([system,
                              rs.randint(1, VOCAB, (L,)).astype(np.int32)])
              for L in (2, 5, 3)]
    router = ff.make_serving_router(replicas=2, serve_slots=2,
                                    kv_page_size=4, max_seq_len=64)
    try:
        first = router.run([shared[0]], max_new_tokens=4, timeout=300)[0]
        home = first.replica
        survivor = 1 - home
        ref_slab = router.engines[home].export_prefix_slab(system)
        assert ref_slab is not None and ref_slab["tokens"].size == 8
        # race the retirement against a fresh flood
        prompts = _prompts(41, [5, 9, 3, 12, 7, 6])
        reqs = [router.submit(p, max_new_tokens=5) for p in prompts]
        snap = router.remove_replica(home)
        router.wait(reqs, timeout=300)
        assert not snap["fenced"] and snap["pages"] >= 2
        assert [r.state for r in reqs] == ["done"] * len(prompts), \
            "scale-in stranded submitted work"
        _solo_check(ff, reqs, 5)
        assert all(r.replica == survivor for r in reqs)
        st = router.stats()
        assert st["scale_ins"] == 1 and st["alive"] == 1
        assert st["fenced"] == 0, "clean scale-in must not count a loss"
        assert st["replicas"] == 1 and st["retired"] == 1
        assert router.health()["status"] in ("idle", "busy")
        # inherited pages are bitwise the retiree's, namespace intact
        got = router.engines[survivor].export_prefix_slab(system)
        assert got is not None
        _assert_slab_bitwise(got, ref_slab)
        # and they serve warm hits: the shared prefix re-runs hot
        h0 = router.engines[survivor].stats()["prefix_hits"]
        more = router.run(shared[1:], max_new_tokens=4, timeout=300)
        assert all(r.state == "done" for r in more)
        _solo_check(ff, more, 4)
        assert router.engines[survivor].stats()["prefix_hits"] > h0, \
            "evacuated prefix pages never served a warm hit"
    finally:
        router.close()


@pytest.mark.slow  # 15 s; elastic_serve CI tier runs the full file
def test_scale_in_inherits_adapters_no_reregister(ff):
    """After the adapter-holding replica retires, the tenant keeps
    serving from survivors with NO caller re-register; a later
    add_replica() replays the registry onto the newcomer too."""
    from tests.test_tenancy import RANK, _adapter_weights
    router = ff.make_serving_router(replicas=2, serve_slots=2,
                                    kv_page_size=4, max_seq_len=64,
                                    adapter_pool_pages=2, lora_rank=RANK)
    try:
        geo = router.engines[0].lora.geometry
        router.register_adapter("t", _adapter_weights(geo, 3))
        p = _prompts(51, [7])[0]
        want = router.run([p], max_new_tokens=4, adapter="t",
                          timeout=300)[0]
        assert want.state == "done"
        router.remove_replica(0)
        got = router.run([p], max_new_tokens=4, adapter="t",
                         timeout=300)[0]
        assert got.state == "done" and got.replica == 1
        assert got.tokens == want.tokens, \
            "adapter stream changed across scale-in"
        r_new = router.add_replica()
        assert "t" in router.engines[r_new].lora.registry, \
            "newcomer missed the adapter registry replay"
    finally:
        router.close()


@pytest.mark.slow  # 30 s; elastic_serve CI tier runs the full file
def test_preempt_exactly_once_and_prefix_evacuation(ff, monkeypatch):
    """FF_FAULT preempt(800)@replica:0 mid-flood: the replica evacuates
    queued + in-flight work and its hot prefix pages inside the
    deadline, retires WITHOUT a fence (no loss counted — the router
    ledger equals the per-engine completion sum), every request
    completes exactly once solo-identical, and the evacuated prefix
    serves warm on the survivor."""
    rs = np.random.RandomState(17)
    system = rs.randint(1, VOCAB, (8,)).astype(np.int32)
    shared = [np.concatenate([system,
                              rs.randint(1, VOCAB, (L,)).astype(np.int32)])
              for L in (2, 5, 3, 4)]
    router = ff.make_serving_router(replicas=2, serve_slots=2,
                                    kv_page_size=4, max_seq_len=64,
                                    decode_chunk=2, start=False)
    try:
        router.warmup(_prompts(6, [5, 9]), max_new_tokens=2)
        base = [e.stats()["completed"] for e in router.engines]
        _arm_fault(monkeypatch, "preempt(800)@replica:0")
        prompts = shared + _prompts(61, [5, 9, 12, 7, 6])
        reqs = router.run(prompts, max_new_tokens=8, timeout=300)
        assert [r.state for r in reqs] == ["done"] * len(prompts)
        _solo_check(ff, reqs, 8)
        st = router.stats()
        assert st["preempts"] == 1
        assert st["fenced"] == 0, \
            "a clean preemption must not count as a replica loss"
        assert st["evac_deadline_misses"] == 0
        assert st["completed"] == len(prompts)
        # exactly-once: router ledger == sum of per-engine completions
        done = [e.stats()["completed"] - b
                for e, b in zip(router.engines, base)]
        assert sum(done) == len(prompts), \
            f"duplicated or lost across preemption: {done}"
        assert st["per_replica"][0]["retired"]
        assert router.health()["replicas"] == 1
        # a replica that evacuated everything moved its state over
        if st["evacuated_slabs"]:
            assert st["evacuated_pages"] > 0 and st["evacuation_bytes"] > 0
        # round 2: the shared prefix serves warm from the survivor
        h0 = router.engines[1].stats()["prefix_hits"]
        more = router.run([shared[0]], max_new_tokens=4, timeout=300)
        assert more[0].state == "done" and more[0].replica == 1
        assert router.engines[1].stats()["prefix_hits"] > h0
        # exactly-once survives a LATER real failover: evacuation did
        # not burn a loss, so the losses cap still has headroom
        assert all(r.losses == 0 for r in reqs)
    finally:
        _disarm_fault(monkeypatch)
        router.close()


@pytest.mark.slow  # 25 s; elastic_serve CI tier runs the full file
def test_preempt_deadline_starved_degrades_to_clean_fence(ff, monkeypatch):
    """slow_evac stalls the first slab export past a tiny preemption
    deadline: evacuation aborts, the replica is FENCED (this one IS a
    loss) and its work resubmits cold through the existing exactly-once
    machinery — never a stall, duplicate, or lost request."""
    router = ff.make_serving_router(replicas=2, serve_slots=2,
                                    kv_page_size=4, max_seq_len=64,
                                    decode_chunk=2, start=False)
    try:
        router.warmup(_prompts(6, [5, 9]), max_new_tokens=2)
        _arm_fault(monkeypatch,
                   "preempt(150)@replica:0,slow_evac(400)@evacuate:1")
        prompts = _prompts(71, [5, 9, 3, 12, 7, 6])
        reqs = router.run(prompts, max_new_tokens=8, timeout=300)
        assert [r.state for r in reqs] == ["done"] * len(prompts)
        _solo_check(ff, reqs, 8)
        st = router.stats()
        assert st["preempts"] == 1
        assert st["evac_deadline_misses"] == 1
        assert st["fenced"] == 1, \
            "a starved evacuation must degrade to a fence"
        assert st["completed"] == len(prompts), "lost or duplicated"
        assert all(1 <= r.attempts <= 2 for r in reqs)
        assert st["per_replica"][0]["retired"]
    finally:
        _disarm_fault(monkeypatch)
        router.close()


@pytest.mark.slow  # 15 s; elastic_serve CI tier runs the full file
def test_autoscaler_drives_real_router(ff, monkeypatch):
    """The policy wired to a REAL fleet: a scripted breach grows it via
    add_replica (newcomer serves token-identical), scripted idleness
    shrinks it back — actuators run outside the policy lock, so a tick
    can run concurrently with serving."""
    from flexflow_tpu.runtime import autoscale as A
    router = ff.make_serving_router(replicas=1, serve_slots=2,
                                    kv_page_size=4, max_seq_len=64,
                                    start=False)
    slo = _FakeSLO()
    monkeypatch.setattr(A.flightrec, "slo_monitor", lambda: slo)
    pol = AutoscalePolicy(router, min_replicas=1, max_replicas=2,
                          breach_windows=2, idle_windows=2,
                          cooldown_s=0.0)
    try:
        router.warmup(_prompts(6, [5, 9]), max_new_tokens=2)
        router.start()
        slo.rows = [{"slo": "queue_wait_p99", "replica": -1, "value": 2.0,
                     "bound": 0.5, "ok_streak": 0, "windows": 2}]
        assert pol.tick() is None
        assert pol.tick() == "scale_out"
        assert router.stats()["alive"] == 2
        reqs = router.run(_prompts(81, [5, 9, 3, 7]), max_new_tokens=4,
                          timeout=300)
        assert all(r.state == "done" for r in reqs)
        _solo_check(ff, reqs, 4)
        slo.rows = []
        assert pol.tick() is None
        assert pol.tick() == "scale_in"
        assert router.stats()["alive"] == 1
        assert pol.state()["events"][-1]["placement"]["tier"] in (
            "ici", "dcn")
    finally:
        pol.close()
        router.close()
