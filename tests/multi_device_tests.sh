#!/bin/bash
# End-to-end multi-device example sweep (analog of the reference's
# tests/multi_gpu_tests.sh: run every example at -ll:gpu $GPUS; here every
# example runs on an N-device virtual CPU mesh via FLEXFLOW_FORCE_CPU_DEVICES).
#
# Usage: tests/multi_device_tests.sh [N_DEVICES] [BATCH]
set -e
set -x

NDEV="${1:-8}"
BATCH="${2:-$((16 * NDEV))}"
ROOT="$(cd "$(dirname "$0")/.." && pwd)"
export FLEXFLOW_FORCE_CPU_DEVICES="$NDEV"
export EPOCHS=1
# smoke sweep: cap dataset size so every example is a handful of batches
export FLEXFLOW_DATASET_LIMIT="${FLEXFLOW_DATASET_LIMIT:-256}"
cd "$ROOT"

# native API examples
python examples/native/mnist_mlp.py -e 1 -b "$BATCH"
python examples/native/alexnet.py -e 1 -b "$BATCH"
python examples/native/multi_head_attention.py -e 1 -b "$BATCH"
python examples/native/candle_uno.py -e 1 -b "$BATCH"
python examples/native/resnet50.py -b "$NDEV" --iters 2 --image-size 64 --num-classes 10
python examples/native/bert_proxy.py -b "$NDEV" --iters 2 --layers 2 --hidden 64 --seq-len 32
python examples/native/transformer.py -e 1 -b "$((2 * NDEV))" \
  --num-layers 2 --hidden-size 64 --sequence-length 32 --num-heads 4
python examples/native/dlrm.py -e 1 -b "$BATCH" \
  --arch-embedding-size 1000 --num-tables 4
# strategy-file flow: generate a hetero strategy, train under it
python examples/native/dlrm_strategy.py --out /tmp/ff_dlrm_strategy.txt \
  --data 2 --model 2
python examples/native/dlrm.py -e 1 -b "$BATCH" \
  --arch-embedding-size 1000 --num-tables 8 \
  --import /tmp/ff_dlrm_strategy.txt --mesh data=2,model=2

# native API examples (round 2 additions)
python examples/native/mnist_cnn.py -e 1 -b "$BATCH"
python examples/native/cifar10_cnn.py -e 1 -b "$BATCH"
python examples/native/cifar10_cnn_concat.py -e 1 -b "$BATCH"
python examples/native/mnist_mlp_attach.py -e 1 -b "$BATCH"
python examples/native/split.py -e 1 -b "$BATCH"
python examples/native/print_layers.py -b "$BATCH"
python examples/native/nmt.py -b "$NDEV" --iters 2 --hidden 64 --vocab 500 --seq 10
python examples/native/print_input.py
# round 3: Llama-family decoder (RMSNorm + RoPE + GQA + SwiGLU)
python examples/native/llama.py -e 1 -b "$BATCH" --hidden 64 --num-layers 2 \
  --num-heads 4 --num-kv-heads 2 --sequence-length 32 --vocab 256
python examples/native/llama_generate.py -b "$NDEV" --hidden 64 --num-layers 2 \
  --prompt-length 8 --max-new-tokens 8
python examples/native/vit.py -e 1 -b "$BATCH" --image-size 32 --patch 8 \
  --hidden 64 --num-layers 2
python examples/native/charlm_generate.py -e 1 -b "$NDEV" --hidden 64 \
  --num-layers 1 --seq 32 --sample-chars 16
python examples/native/tensor_attach.py -e 1 -b "$BATCH"
python examples/native/cifar10_cnn_attach.py -e 1 -b "$BATCH"

# keras frontend examples
python examples/keras/mnist_mlp.py
python examples/keras/mnist_cnn.py
python examples/keras/candle_uno.py
python examples/keras/cifar10_cnn.py
python examples/keras/func_mnist_mlp.py
python examples/keras/func_mnist_mlp_concat.py
python examples/keras/func_mnist_cnn.py
python examples/keras/func_cifar10_cnn_concat.py
python examples/keras/func_cifar10_alexnet.py
python examples/keras/seq_reuters_mlp.py
python examples/keras/reshape.py
python examples/keras/unary.py

# keras frontend examples (net2net / nested / concat / seq variants)
python examples/keras/seq_mnist_mlp.py
python examples/keras/seq_cifar10_cnn.py
python examples/keras/func_cifar10_cnn.py
python examples/keras/func_mnist_cnn_concat.py
python examples/keras/func_mnist_mlp_concat2.py
python examples/keras/func_mnist_mlp_net2net.py
python examples/keras/seq_mnist_mlp_net2net.py
python examples/keras/func_cifar10_cnn_net2net.py
python examples/keras/seq_mnist_cnn_nested.py
python examples/keras/func_cifar10_cnn_nested.py
python examples/keras/func_cifar10_cnn_concat_model.py
python examples/keras/func_cifar10_cnn_concat_seq_model.py
python examples/keras/callback.py

# importer frontends
python examples/pytorch/mnist_mlp_fx.py -e 1 -b "$BATCH"
python examples/pytorch/cnn_fx.py -e 1 -b "$BATCH"
python examples/pytorch/resnet_fx.py -e 1 -b "$BATCH"
python examples/pytorch/mlp_torch_compare.py
python examples/pytorch/mnist_mlp_torch.py
python examples/pytorch/cifar10_cnn_fx.py -e 1 -b "$BATCH"
python examples/pytorch/torch_vision.py -e 1 -b "$BATCH"
python examples/pytorch/mnist_mlp_torch2.py -e 1 -b "$BATCH"
python examples/pytorch/bert_fx.py -b "$NDEV" --iters 2
python examples/pytorch/regnet_fx.py -b "$NDEV" --iters 2
python examples/pytorch/resnet152_training.py -b "$NDEV" --depth 50 --iters 1 --image-size 32
python examples/onnx/mnist_mlp_onnx.py -e 1 -b "$BATCH"
python examples/onnx/mnist_mlp.py -e 1 -b "$BATCH"
python examples/onnx/cifar10_cnn.py -e 1 -b "$BATCH"
python examples/onnx/alexnet.py -e 1 -b 16
python examples/onnx/resnet.py -e 1 -b "$BATCH"
python examples/onnx/mnist_mlp_keras.py -e 1 -b "$BATCH"
python examples/onnx/mnist_mlp_pt.py -e 1 -b "$BATCH"
python examples/onnx/cifar10_cnn_pt.py -e 1 -b "$BATCH"
python examples/onnx/alexnet_pt.py -e 1 -b 16
python examples/onnx/resnet_pt.py -e 1 -b "$BATCH"
python examples/onnx/cifar10_cnn_keras.py -e 1 -b "$BATCH"

# bootcamp demo
python bootcamp_demo/native_alexnet.py -e 1 -b "$BATCH"
python bootcamp_demo/torch_alexnet_import.py -e 1 -b "$BATCH"
python bootcamp_demo/keras_alexnet_cifar10.py

echo "multi_device_tests: ALL PASSED"
