"""Test configuration: run everything on an 8-device virtual CPU mesh so
multi-chip sharding is exercised without TPU hardware (SURVEY.md §4:
"JAX offers CPU simulation of meshes, so distributed tests can run
single-host").

Note: this environment preloads jax._src at interpreter startup (sitecustomize
for the TPU tunnel), so JAX_PLATFORMS env vars set here are too late; we must
go through jax.config before any backend is initialized.
"""

import os
import sys

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax (e.g. 0.4.37) has no jax_num_cpu_devices option; the
    # XLA_FLAGS --xla_force_host_platform_device_count above already
    # forces the 8-device virtual CPU mesh there
    pass

# NOTE (round 6): enabling jax's persistent compilation cache here looked
# like a free suite-wide speedup (identical tiny models recompile across
# files constantly), but on this jax (0.4.37) a warm cache returned a
# WRONG executable for test_grad_accum (loss mismatch — stale/colliding
# entry class of bug), so the suite must NOT use it. Serving/bench keep
# their opt-in caches (multi-second compiles, distinct program shapes).

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
