"""Strategy text-schema satellites: the device-type int mapping, the save
path's device-id diagnostic (no more silent rewrite), and exact @axismap
round-trips including the explicitly-replicated and STAGE forms.
"""

import logging
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from flexflow_tpu.parallel.pconfig import CONTRACT, STAGE, ParallelConfig
from flexflow_tpu.parallel.strategy import (load_strategies_from_file,
                                            save_strategies_to_file)

MESH = {"data": 4, "model": 2}


def _roundtrip(tmp_path, strategies):
    p = str(tmp_path / "s.ff")
    save_strategies_to_file(p, strategies)
    return load_strategies_from_file(p)


# ------------------------------------------------------------ device types

def test_device_type_roundtrip_cpu_tpu_and_reference_gpu(tmp_path):
    """Int 0 in the file means 'the accelerator pool': our TPU strategies
    and reference-written GPU strategies both serialize there, and BOTH
    load as TPU (the pool this rebuild executes on). CPU (int 1, the
    reference's hetero-DLRM host embeddings) survives exactly."""
    strategies = {
        "tpu_op": ParallelConfig.from_axis_map(2, MESH, {"data": 0}),
        "cpu_op": ParallelConfig.host(2),
        "gpu_op": ParallelConfig(device_type="GPU", dims=(4, 1),
                                 device_ids=tuple(range(4)),
                                 axis_map={"data": 0}),
    }
    loaded = _roundtrip(tmp_path, strategies)
    assert loaded["tpu_op"].device_type == "TPU"
    assert loaded["cpu_op"].device_type == "CPU"
    # reference-written GPU deliberately loads as the accelerator pool
    assert loaded["gpu_op"].device_type == "TPU"
    # ... with everything else about the record intact
    assert loaded["gpu_op"].dims == (4, 1)
    assert loaded["gpu_op"].axis_map == {"data": 0}


def test_reference_written_file_loads_unchanged(tmp_path):
    """A file with no @axismap records (what the reference writes, int 0
    device types) parses to degree-only configs."""
    p = tmp_path / "ref.ff"
    p.write_text("1\ndense1\n0\n2\n2\t4\n8\n0\t1\t2\t3\t4\t5\t6\t7\n")
    loaded = load_strategies_from_file(str(p))
    pc = loaded["dense1"]
    assert pc.device_type == "TPU" and pc.axis_map is None
    assert pc.dims == (4, 2)  # file order is reversed (sample last)


# ------------------------------------------------------------ save path

def test_save_inconsistent_ids_warns_and_rewrites(tmp_path, caplog):
    from flexflow_tpu.logger import fflogger

    pc = ParallelConfig(dims=(4, 1), device_ids=(0, 1, 2),
                        axis_map={"data": 0})
    # fflogger doesn't propagate to root; capture via caplog's handler
    fflogger.addHandler(caplog.handler)
    try:
        with caplog.at_level(logging.WARNING, logger="flexflow_tpu"):
            loaded = _roundtrip(tmp_path, {"op": pc})
    finally:
        fflogger.removeHandler(caplog.handler)
    assert any("3 device_ids for 4 partitions" in r.message
               for r in caplog.records), caplog.records
    assert loaded["op"].device_ids == (0, 1, 2, 3)  # documented rewrite


def test_save_inconsistent_ids_strict_raises(tmp_path):
    pc = ParallelConfig(dims=(4, 1), device_ids=(0, 1, 2),
                        axis_map={"data": 0})
    with pytest.raises(ValueError, match="device_ids"):
        save_strategies_to_file(str(tmp_path / "s.ff"), {"op": pc},
                                strict=True)


def test_save_strict_never_leaves_a_truncated_file(tmp_path):
    """strict validates the whole table BEFORE writing: a raise must not
    strand a half-written file whose header disagrees with its body."""
    p = tmp_path / "s.ff"
    strategies = {
        "aa_fine": ParallelConfig.from_axis_map(2, MESH, {"data": 0}),
        "mm_bad": ParallelConfig(dims=(4, 1), device_ids=(0, 1, 2),
                                 axis_map={"data": 0}),
    }
    with pytest.raises(ValueError, match="mm_bad"):
        save_strategies_to_file(str(p), strategies, strict=True)
    assert not p.exists(), "strict save wrote a truncated file"


def test_save_consistent_ids_no_warning(tmp_path, caplog):
    from flexflow_tpu.logger import fflogger

    pc = ParallelConfig.from_axis_map(2, MESH, {"data": 0, "model": 1})
    fflogger.addHandler(caplog.handler)
    try:
        with caplog.at_level(logging.WARNING, logger="flexflow_tpu"):
            loaded = _roundtrip(tmp_path, {"op": pc})
    finally:
        fflogger.removeHandler(caplog.handler)
    assert not caplog.records
    assert loaded["op"].device_ids == tuple(range(8))


# ------------------------------------------------------------ round trips

def test_axismap_sentinels_roundtrip_exactly(tmp_path):
    strategies = {
        "col": ParallelConfig.from_axis_map(2, MESH,
                                            {"data": 0, "model": 1}),
        "row": ParallelConfig.from_axis_map(2, MESH,
                                            {"data": 0, "model": CONTRACT}),
        "rep": ParallelConfig.replicated(3),  # explicit empty axis_map
        "unused": ParallelConfig(axis_map={"data": 0, "model": None},
                                 dims=(4, 1), device_ids=tuple(range(4))),
    }
    loaded = _roundtrip(tmp_path, strategies)
    for name, pc in strategies.items():
        assert loaded[name].axis_map == pc.axis_map, name
        assert loaded[name].dims == pc.dims, name
    # the explicitly-replicated {} must NOT degrade to None (None means
    # "derive from degrees via the greedy heuristic")
    assert loaded["rep"].axis_map == {}


def test_stage_strategy_roundtrips_with_stage_devices(tmp_path):
    """A PP strategy occupies stage_size x num_parts devices; the id list
    (canonical from_axis_map/csim form) must survive save/load even though
    the schema's degree product excludes the stage axis."""
    mesh = {"data": 2, "pipe": 2}
    pc = ParallelConfig.from_axis_map(3, mesh, {"data": 0, "pipe": STAGE})
    assert pc.num_parts() == 2 and len(pc.device_ids) == 4
    loaded = _roundtrip(tmp_path, {"stack": pc})
    assert loaded["stack"].axis_map == {"data": 0, "pipe": STAGE}
    assert loaded["stack"].device_ids == (0, 1, 2, 3)
    assert loaded["stack"].dims == pc.dims


def test_schema_pass_agrees_with_loader(tmp_path):
    """fflint's strict parser accepts everything the tolerant loader
    accepts on well-formed files (no false positives)."""
    from flexflow_tpu.analysis.schema import check_file

    strategies = {
        "a": ParallelConfig.from_axis_map(2, MESH, {"data": 0}),
        "b": ParallelConfig.host(2),
        "c": ParallelConfig.from_axis_map(2, MESH,
                                          {"data": 0, "model": CONTRACT}),
    }
    p = str(tmp_path / "s.ff")
    save_strategies_to_file(p, strategies)
    parsed, violations = check_file(p)
    assert parsed is not None and set(parsed) == set(strategies)
    assert [str(v) for v in violations] == []
