"""Hierarchical ICI/DCN pricing in search/machine.py (ISSUE 10 satellite):
a collective over a DCN-spanning axis must decompose EXACTLY into the
intra-host ICI leg plus the cross-host DCN leg — and the axis->tier map
must round-trip from FFConfig.dcn_mesh_shape into every cost consumer
(CostModel default machine, csim tables, the fflint perf pass) without
each caller rebuilding it.

Composition laws tested (b = bytes/chip, axis 8 = 4 chips/host x 2 hosts):
  all-reduce:      AR(b, 8, dcn) == AR(b, 4, ici) + AR(b, 2, pure-dcn)
  reduce-scatter:  RS(b, 8, dcn) == RS(b, 4, ici) + RS(b, 2, pure-dcn)
  all-gather:      AG(b, 8, dcn) == AG(b, 4, ici) + AG(4b, 2, pure-dcn)
                   (each host forwards its intra-GATHERED 4b part)
"""

import pytest

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.search.cost_model import CostModel
from flexflow_tpu.search.machine import MachineModel

B = 64 * 1024 * 1024  # bytes per chip


def _machines():
    """(hierarchical over 'data', pure-ICI, pure-DCN-only helper)."""
    hier = MachineModel(dcn_axes={"data": 2})
    ici = MachineModel()
    dcn = MachineModel(dcn_axes={"x": 2})  # axis 'x' size 2 => hosts-only
    return hier, ici, dcn


def test_all_reduce_composes_ici_plus_dcn():
    hier, ici, dcn = _machines()
    composed = hier.all_reduce_time(B, 8, "data")
    assert composed == pytest.approx(
        ici.all_reduce_time(B, 4, None) + dcn.all_reduce_time(B, 2, "x"),
        rel=1e-12)


def test_reduce_scatter_composes_ici_plus_dcn():
    hier, ici, dcn = _machines()
    composed = hier.reduce_scatter_time(B, 8, "data")
    assert composed == pytest.approx(
        ici.reduce_scatter_time(B, 4, None)
        + dcn.reduce_scatter_time(B, 2, "x"), rel=1e-12)
    # the reduce-scatter is the ring's reduce phase: strictly cheaper than
    # the full all-reduce over the same axis, and more than a third of it
    ar = hier.all_reduce_time(B, 8, "data")
    assert ar / 3 < composed < ar


def test_all_gather_composes_ici_plus_dcn():
    hier, ici, dcn = _machines()
    composed = hier.all_gather_time(B, 8, "data")
    # the DCN leg moves the intra-gathered 4b parts between hosts
    assert composed == pytest.approx(
        ici.all_gather_time(B, 4, None) + dcn.all_gather_time(4 * B, 2, "x"),
        rel=1e-12)


def test_dcn_axis_only_applies_to_named_axis():
    hier, ici, _ = _machines()
    for fn in ("all_reduce_time", "all_gather_time", "reduce_scatter_time",
               "all_to_all_time"):
        assert getattr(hier, fn)(B, 8, "model") == pytest.approx(
            getattr(ici, fn)(B, 8, "model")), fn


def test_degenerate_host_count_clamps_to_divisor():
    """dcn_axes hosts that don't divide the axis clamp to the nearest
    divisor instead of mis-pricing (the _tiers contract)."""
    m = MachineModel(dcn_axes={"data": 3})
    assert m._tiers(8, "data") == (4, 2)
    m2 = MachineModel(dcn_axes={"data": 16})
    assert m2._tiers(8, "data") == (1, 8)  # clamped to the axis size


def test_size_one_axis_costs_zero():
    m = MachineModel(dcn_axes={"data": 2})
    assert m.reduce_scatter_time(B, 1, "data") == 0.0
    assert m.all_reduce_time(B, 1, "data") == 0.0


def _tiny_model(**cfg_kw):
    cfg = FFConfig(batch_size=32, **cfg_kw)
    ff = FFModel(cfg)
    x = ff.create_tensor([32, 64], name="x")
    t = ff.dense(x, 256, name="fc1")
    t = ff.relu(t, name="r")
    ff.dense(t, 8, name="head")
    return ff


def test_dcn_mesh_shape_roundtrips_into_cost_model():
    """FFConfig.dcn_mesh_shape -> CostModel's DEFAULT machine: every cost
    consumer that builds a CostModel without an explicit machine (the
    search, csim's tables, fflint's perf pass) prices the axis at the DCN
    tier."""
    ff = _tiny_model(mesh_shape={"data": 8}, dcn_mesh_shape={"data": 2})
    cost = CostModel(ff, ff.config.mesh_shape)
    assert cost.machine.dcn_axes == {"data": 2}
    flat = CostModel(ff, ff.config.mesh_shape, machine=MachineModel())
    op = ff.get_op_by_name("fc1")
    dp = {"data": 0}
    assert cost.op_grad_sync_time(op, dp) > flat.op_grad_sync_time(op, dp)
    # an explicit machine always wins over the config default
    assert flat.machine.dcn_axes == {}


def test_dcn_mesh_shape_roundtrips_into_search_tables():
    """csim's CompiledSearchProblem reads the grad-sync costs from the
    same CostModel — a DCN-priced table row must exceed the flat one."""
    from flexflow_tpu.search.csim import CompiledSearchProblem

    ff = _tiny_model(mesh_shape={"data": 8}, dcn_mesh_shape={"data": 2})
    mesh = ff.config.mesh_shape
    hier = CompiledSearchProblem(ff, CostModel(ff, mesh), mesh)
    flat = CompiledSearchProblem(
        ff, CostModel(ff, mesh, machine=MachineModel()), mesh)
    assert hier.op_sync_costs.max() > flat.op_sync_costs.max()


def test_hierarchical_strategy_shape():
    """driver.hierarchical_strategy: data parallelism lands on the DCN
    axis, contract/TP stays inside ICI, and every per-op map is drawn
    from the op's legal set (so it simulates and compiles)."""
    from flexflow_tpu.parallel.pconfig import CONTRACT
    from flexflow_tpu.search.driver import (hierarchical_strategy,
                                            legal_axis_maps)

    ff = _tiny_model(mesh_shape={"data": 4, "model": 2},
                     dcn_mesh_shape={"data": 2})
    mesh = ff.config.mesh_shape
    hier = hierarchical_strategy(ff, mesh, {"data": 2})
    for name, am in hier.items():
        assert am.get("data") in (0, None), (name, am)
        assert am.get("model") != 0 or am.get("data") is None, (name, am)
    # the weighted ops spend ICI on the model dimension
    assert hier["fc1"].get("model") in (CONTRACT, 1)
    # membership in the legal set
    for op in ff.ops:
        if op.name in hier:
            legal = [{ax: d for ax, d in m.items() if d is not None}
                     for m in legal_axis_maps(op, mesh)]
            assert hier[op.name] in legal, op.name


def test_search_runs_with_dcn_machine():
    """optimize_strategies on a two-tier machine returns a legal strategy
    table whose simulated cost is no worse than flat data-parallel."""
    from flexflow_tpu.search.driver import (data_parallel_strategy,
                                            optimize_strategies)

    ff = _tiny_model(mesh_shape={"data": 4, "model": 2},
                     dcn_mesh_shape={"data": 2})
    mesh = ff.config.mesh_shape
    machine = MachineModel(dcn_axes={"data": 2})
    best = optimize_strategies(ff, budget=150, mesh_shape=mesh,
                               machine=machine, seed=0, use_native=False)
    cost = CostModel(ff, mesh, machine=machine)
    best_am = {k: v.axis_map or {} for k, v in best.items()}
    assert cost.iteration_time(best_am) <= cost.iteration_time(
        data_parallel_strategy(ff, mesh)) * (1 + 1e-9)
