"""Worker script for the 2-process multi-controller test (launched through
flexflow_tpu.launcher, which calls jax.distributed.initialize). Each process
owns 4 virtual CPU devices; the model trains over the 8-device global mesh
with dp x tp sharding — the TPU-pod control-replication analog of the
reference's GASNet multi-node path (mapper.cc:267-282).

Prints `MULTIHOST pid=<i> loss=<loss>` for the parent test to compare.
"""

import sys

import numpy as np

import jax


def main():
    assert jax.process_count() == 2, jax.process_count()
    pid = jax.process_index()

    from flexflow_tpu import (ActiMode, FFConfig, FFModel, LossType,
                              MetricsType, SGDOptimizer, SingleDataLoader)
    from flexflow_tpu.parallel.pconfig import ParallelConfig

    mesh_shape = {"data": 4, "model": 2}
    cfg = FFConfig(batch_size=32, epochs=1, mesh_shape=mesh_shape, seed=11)
    cfg.strategies["fc1"] = ParallelConfig.from_axis_map(
        2, mesh_shape, {"data": 0, "model": 1})
    ff = FFModel(cfg)
    x = ff.create_tensor([32, 16], name="x")
    t = ff.dense(x, 64, ActiMode.AC_MODE_RELU, name="fc1")
    ff.dense(t, 4, name="out")
    ff.compile(SGDOptimizer(lr=0.1),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY])

    # identical data on every controller (SPMD: same program, same inputs)
    rs = np.random.RandomState(0)
    xdat = rs.randn(64, 16).astype(np.float32)
    y = rs.randint(0, 4, (64, 1)).astype(np.int32)
    SingleDataLoader(ff, x, xdat)
    SingleDataLoader(ff, ff.label_tensor, y)

    losses = []
    for _ in range(3):
        batch = ff._stage_batch()
        loss, _ = ff._run_train_step(batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses

    # multi-host sharded checkpoint: save, train further (params drift),
    # restore, and check the local shards came back exactly
    if len(sys.argv) > 1:
        from flexflow_tpu.runtime.checkpoint import (restore_checkpoint,
                                                     save_checkpoint)

        ckpt_dir = sys.argv[1]
        save_checkpoint(ff, ckpt_dir)
        saved = np.asarray(
            ff.params["fc1"]["kernel"].addressable_shards[0].data)
        loss2, _ = ff._run_train_step(ff._stage_batch())
        drifted = np.asarray(
            ff.params["fc1"]["kernel"].addressable_shards[0].data)
        assert np.abs(drifted - saved).max() > 0, "training did not move params"
        restore_checkpoint(ff, ckpt_dir)
        back = np.asarray(
            ff.params["fc1"]["kernel"].addressable_shards[0].data)
        np.testing.assert_allclose(back, saved, rtol=1e-6)
        print(f"MULTIHOST pid={pid} ckpt=ok", flush=True)

    print(f"MULTIHOST pid={pid} loss={losses[-1]:.6f}", flush=True)


if __name__ == "__main__":
    main()
