"""LSTM/GRU golden tests vs torch + pallas flash attention (interpret mode)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from flexflow_tpu import FFConfig, FFModel


def test_lstm_matches_torch():
    torch = pytest.importorskip("torch")
    B, S, D, H = 2, 6, 8, 12
    rs = np.random.RandomState(0)
    x = rs.randn(B, S, D).astype(np.float32)

    cfg = FFConfig(batch_size=B, mesh_shape={"data": 1})
    ff = FFModel(cfg)
    xt = ff.create_tensor([B, S, D], name="x")
    out = ff.lstm(xt, H, name="lstm")
    ff.compile(optimizer=None, final_tensor=out)

    ref = torch.nn.LSTM(D, H, batch_first=True)
    # torch gate order: i, f, g, o — same as ours
    wx = ref.weight_ih_l0.detach().numpy().T  # (D, 4H)
    wh = ref.weight_hh_l0.detach().numpy().T
    bias = (ref.bias_ih_l0 + ref.bias_hh_l0).detach().numpy()
    ff.set_weights("lstm", "wx", wx)
    ff.set_weights("lstm", "wh", wh)
    ff.set_weights("lstm", "bias", bias)

    got = np.asarray(ff.predict({"x": x}))
    with torch.no_grad():
        want, _ = ref(torch.from_numpy(x))
    np.testing.assert_allclose(got, want.numpy(), rtol=1e-4, atol=1e-5)


def test_gru_matches_torch():
    torch = pytest.importorskip("torch")
    B, S, D, H = 2, 5, 8, 10
    rs = np.random.RandomState(1)
    x = rs.randn(B, S, D).astype(np.float32)

    cfg = FFConfig(batch_size=B, mesh_shape={"data": 1})
    ff = FFModel(cfg)
    xt = ff.create_tensor([B, S, D], name="x")
    out = ff.gru(xt, H, name="gru")
    ff.compile(optimizer=None, final_tensor=out)

    ref = torch.nn.GRU(D, H, batch_first=True)
    ff.set_weights("gru", "wx", ref.weight_ih_l0.detach().numpy().T)
    ff.set_weights("gru", "wh", ref.weight_hh_l0.detach().numpy().T)
    # torch keeps separate ih/hh biases; our cell folds ih bias into xg and
    # applies hh bias inside the recurrence only via wh @ h (hn term differs) —
    # set hh bias to zero in the reference for an exact comparison
    with torch.no_grad():
        ref.bias_hh_l0.zero_()
    ff.set_weights("gru", "bias", ref.bias_ih_l0.detach().numpy())

    got = np.asarray(ff.predict({"x": x}))
    with torch.no_grad():
        want, _ = ref(torch.from_numpy(x))
    np.testing.assert_allclose(got, want.numpy(), rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_dense(causal):
    from flexflow_tpu.ops.pallas_kernels import flash_attention

    B, S, H, D = 2, 128, 4, 16
    rs = np.random.RandomState(2)
    q = rs.randn(B, S, H, D).astype(np.float32)
    k = rs.randn(B, S, H, D).astype(np.float32)
    v = rs.randn(B, S, H, D).astype(np.float32)

    s = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
    if causal:
        mask = np.tril(np.ones((S, S), bool))
        s = np.where(mask, s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    want = np.einsum("bhqk,bkhd->bqhd", p, v)

    got = np.asarray(flash_attention(jnp.asarray(q), jnp.asarray(k),
                                     jnp.asarray(v), causal))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal,shape", [
    (False, (2, 128, 2, 16)),
    (True, (2, 128, 2, 16)),
    (True, (1, 256, 4, 64)),
])
def test_flash_attention_grads_match_dense(causal, shape):
    """The hand-written dq/dk/dv Pallas kernels must match autodiff through
    a dense reference — finite-and-nonzero alone would not catch a sign,
    scale, or masking regression."""
    from flexflow_tpu.ops.pallas_kernels import flash_attention

    B, S, H, D = shape
    rs = np.random.RandomState(3)
    q, k, v, g = (jnp.asarray(rs.randn(B, S, H, D).astype(np.float32))
                  for _ in range(4))
    scale = 1.0 / np.sqrt(D)

    def dense(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            mask = jnp.tril(jnp.ones((S, S), bool))
            s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqs,bshd->bqhd", p, v)

    gf = jax.grad(lambda *a: jnp.vdot(flash_attention(*a, causal, scale), g),
                  (0, 1, 2))(q, k, v)
    gd = jax.grad(lambda *a: jnp.vdot(dense(*a), g), (0, 1, 2))(q, k, v)
    for name, a, b in zip("dq dk dv".split(), gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=2e-5, err_msg=name)


@pytest.mark.parametrize("causal,sq,sk", [
    (False, 64, 192),   # plain cross-attention
    (True, 64, 192),    # causal cross: diagonal offset sk-sq=128
    (True, 128, 384),
    (True, 96, 136),    # offset 40 not a block multiple (blocks degrade to 8)
    (True, 1, 128),     # single-query decode shape
])
def test_flash_cross_attention_matches_dense(causal, sq, sk):
    """sq != sk on the flash path (VERDICT r3 #6): the causal mask carries
    the bottom-right diagonal offset k_pos <= q_pos + (sk - sq), matching
    the einsum path's tril(k=sk-sq) — fwd AND all three grads (the
    dead-tile index-map clamps shift with the offset too; a clamp bug
    shows up as a wrong, not crashing, gradient)."""
    from flexflow_tpu.ops.pallas_kernels import flash_attention

    B, H, D = 2, 2, 16
    rs = np.random.RandomState(11)
    q = jnp.asarray(rs.randn(B, sq, H, D).astype(np.float32))
    k = jnp.asarray(rs.randn(B, sk, H, D).astype(np.float32))
    v = jnp.asarray(rs.randn(B, sk, H, D).astype(np.float32))
    g = jnp.asarray(rs.randn(B, sq, H, D).astype(np.float32))
    scale = 1.0 / np.sqrt(D)

    def dense(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
            s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqs,bshd->bqhd", p, v)

    got = np.asarray(flash_attention(q, k, v, causal, scale))
    np.testing.assert_allclose(got, np.asarray(dense(q, k, v)), rtol=2e-4,
                               atol=2e-5)

    gf = jax.grad(lambda *a: jnp.vdot(flash_attention(*a, causal, scale), g),
                  (0, 1, 2))(q, k, v)
    gd = jax.grad(lambda *a: jnp.vdot(dense(*a), g), (0, 1, 2))(q, k, v)
    for name, a, b in zip("dq dk dv".split(), gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=2e-5, err_msg=name)


def test_flash_causal_rejects_more_queries_than_keys():
    from flexflow_tpu.ops.pallas_kernels import flash_attention

    q = jnp.zeros((1, 128, 2, 16), jnp.float32)
    kv = jnp.zeros((1, 64, 2, 16), jnp.float32)
    with pytest.raises(AssertionError, match="sq <= sk"):
        flash_attention(q, kv, kv, True, 0.25)


def test_mha_causal_cross_attention_flash_matches_einsum(monkeypatch):
    """Model-level: a decoder-style MHA (causal, kv longer than q — the
    reference Transformer app shape, attention.cu:533-570) runs the flash
    path and matches the einsum mask convention. SK must satisfy
    _flash_ok's 128-divisibility gate or the comparison silently becomes
    einsum-vs-einsum — asserted below."""
    B, SQ, SK, D, H = 2, 64, 256, 32, 4
    rs = np.random.RandomState(13)
    xq = rs.randn(B, SQ, D).astype(np.float32)
    xkv = rs.randn(B, SK, D).astype(np.float32)

    def run():
        cfg = FFConfig(batch_size=B, mesh_shape={"data": 1}, seed=11)
        ff = FFModel(cfg)
        qt = ff.create_tensor([B, SQ, D], name="q")
        kvt = ff.create_tensor([B, SK, D], name="kv")
        out = ff.multihead_attention(qt, kvt, kvt, D, H, causal=True,
                                     name="xmha")
        ff.compile(optimizer=None, final_tensor=out)
        op = next(o for o in ff.ops if o.name == "xmha")
        return np.asarray(ff.predict({"q": xq, "kv": xkv})), op

    monkeypatch.delenv("FF_FORCE_FLASH_ATTENTION", raising=False)
    y_einsum, _ = run()
    monkeypatch.setenv("FF_FORCE_FLASH_ATTENTION", "1")
    y_flash, op = run()
    assert op._flash_ok(jnp.zeros((B, SQ, H, D // H)),
                        jnp.zeros((B, SK, H, D // H))), \
        "shape no longer takes the flash path — comparison is vacuous"
    np.testing.assert_allclose(y_flash, y_einsum, rtol=2e-4, atol=2e-5)


def test_mha_flash_path_matches_einsum(monkeypatch):
    """Model-level equivalence: MultiHeadAttention with the Pallas flash
    kernel forced on (interpret mode on CPU) vs the einsum softmax path."""
    B, S, D, H = 2, 128, 32, 4
    rs = np.random.RandomState(7)
    x = rs.randn(B, S, D).astype(np.float32)

    def run():
        cfg = FFConfig(batch_size=B, mesh_shape={"data": 1}, seed=11)
        ff = FFModel(cfg)
        xt = ff.create_tensor([B, S, D], name="x")
        out = ff.multihead_attention(xt, xt, xt, D, H, causal=True,
                                     name="mha")
        ff.compile(optimizer=None, final_tensor=out)
        return np.asarray(ff.predict({"x": x}))

    monkeypatch.delenv("FF_FORCE_FLASH_ATTENTION", raising=False)
    y_einsum = run()
    monkeypatch.setenv("FF_FORCE_FLASH_ATTENTION", "1")
    y_flash = run()
    np.testing.assert_allclose(y_flash, y_einsum, rtol=2e-4, atol=2e-5)


def test_flash_bwd_dlse_term():
    """The dlse slot of flash_attention_bwd_pallas (lse cotangent folded
    into delta) must match autodiff of the dense logsumexp: grad of
    sum(w * lse(q,k)) via the kernel equals the dense reference."""
    from flexflow_tpu.ops.pallas_kernels import (flash_attention_bwd_pallas,
                                                 flash_attention_fwd_pallas)

    B, S, H, D = 1, 64, 2, 16
    rs = np.random.RandomState(9)
    q, k, v = (jnp.asarray(rs.randn(B, S, H, D).astype(np.float32))
               for _ in range(3))
    w = jnp.asarray(rs.randn(B, H, S).astype(np.float32))
    scale = 1.0 / np.sqrt(D)

    out8, lse8 = flash_attention_fwd_pallas(q, k, v, False, scale)
    o = out8.reshape(B, H, S, D).transpose(0, 2, 1, 3)
    # cotangents: do = 0, dlse = w  ->  dq/dk from the lse path only
    dq, dk, dv = flash_attention_bwd_pallas(
        q, k, v, o, lse8, jnp.zeros_like(q), False, scale, dlse=w)

    def dense_lse(q, k):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                       preferred_element_type=jnp.float32) * scale
        return jnp.sum(w * jax.scipy.special.logsumexp(s, axis=-1))

    gd_q, gd_k = jax.grad(dense_lse, (0, 1))(q, k)
    np.testing.assert_allclose(np.asarray(dq), np.asarray(gd_q), rtol=2e-4,
                               atol=2e-5)
    np.testing.assert_allclose(np.asarray(dk), np.asarray(gd_k), rtol=2e-4,
                               atol=2e-5)
    assert np.abs(np.asarray(dv)).max() == 0  # lse has no v dependence
