"""Fault-injection tests for the resilience layer (runtime/resilience.py,
runtime/faultinject.py, atomic checkpoints in runtime/checkpoint.py).

Every failure path runs deterministically on CPU in tier-1 via FF_FAULT
(`kind@site:index` grammar): kill-and-resume must reproduce the
uninterrupted loss trajectory bitwise, injected NaN must skip the step
in-graph (params untouched) and rewind after N consecutive bad steps,
injected orbax IO failure must exercise retry/backoff, and SIGTERM must
checkpoint-then-stop. No test sleeps longer than 1s.
"""

import os
import signal
import time

import numpy as np
import pytest

from flexflow_tpu import (ActiMode, FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader, TrainSupervisor)
from flexflow_tpu.runtime import faultinject, resilience
from flexflow_tpu.runtime.checkpoint import (latest_step, load_meta,
                                             restore_checkpoint)
from flexflow_tpu.runtime.faultinject import FaultPlan


@pytest.fixture(autouse=True)
def _fresh_fault_state(monkeypatch):
    monkeypatch.delenv("FF_FAULT", raising=False)
    faultinject.reset()
    resilience.reset_counters()
    yield
    faultinject.reset()


def _build(ckpt_dir="", *, on_nonfinite="skip", rewind_after=0,
           checkpoint_every=0, keep=3, seed=3, n=64, native=False):
    cfg = FFConfig(batch_size=16, epochs=1, seed=seed,
                   checkpoint_dir=str(ckpt_dir),
                   checkpoint_every=checkpoint_every,
                   keep_checkpoints=keep,
                   on_nonfinite=on_nonfinite,
                   nonfinite_rewind_after=rewind_after,
                   native_dataloader=native)
    ff = FFModel(cfg)
    x = ff.create_tensor([16, 8], name="x")
    t = ff.dense(x, 16, ActiMode.AC_MODE_RELU, name="fc1")
    ff.dense(t, 4, name="out")
    ff.compile(SGDOptimizer(lr=0.1),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY])
    rs = np.random.RandomState(7)
    SingleDataLoader(ff, x, rs.randn(n, 8).astype(np.float32))
    SingleDataLoader(ff, ff.label_tensor,
                     rs.randint(0, 4, (n, 1)).astype(np.int32))
    return ff


# --------------------------------------------------------- FF_FAULT grammar


def test_fault_plan_grammar():
    p = FaultPlan.parse("nan_loss@step:7,sigterm@step:12,io_fail@save:1")
    assert p.at_step("nan_loss", 7)
    assert not p.at_step("nan_loss", 7), "step events are one-shot"
    assert not p.at_step("nan_loss", 8)
    assert p.fire("io_fail", "save")          # 1st save fails
    assert not p.fire("io_fail", "save")      # 2nd succeeds
    # ranges expand per-step
    r = FaultPlan.parse("nan_loss@step:3-5")
    assert [r.at_step("nan_loss", s) for s in (3, 4, 5, 6)] == \
        [True, True, True, False]
    # unrelated (kind, site) never counts occurrences
    assert not p.fire("io_fail", "load")
    # range match for chunked step counters (fit's scanned program):
    # an event inside the chunk fires at the next boundary, once
    r2 = FaultPlan.parse("sigterm@step:7")
    assert not r2.in_step_range("sigterm", 0, 6)
    assert r2.in_step_range("sigterm", 4, 8)
    assert not r2.in_step_range("sigterm", 4, 8), "consumed"
    for bad in ("nan_loss", "nan@step", "x@y:z", "x@y:5-2"):
        with pytest.raises(ValueError):
            FaultPlan.parse(bad)


def test_env_plan_reparses_on_change(monkeypatch):
    monkeypatch.setenv("FF_FAULT", "io_fail@save:1")
    assert faultinject.active_plan().events == [("io_fail", "save", 1)]
    monkeypatch.setenv("FF_FAULT", "sigterm@step:2")
    assert faultinject.active_plan().events == [("sigterm", "step", 2)]


# ------------------------------------------------------- atomic checkpoints


def test_atomic_checkpoint_layout_retention_and_meta(tmp_path):
    ff = _build(tmp_path)
    sup = TrainSupervisor(ff, str(tmp_path), keep=2)
    for k in range(1, 5):
        sup.step()
        sup.save(reason="test")
        if k == 1:
            # per-step meta records the supervisor extras
            meta = load_meta(str(tmp_path), 1)
            assert meta["step"] == 1
            assert np.asarray(meta["rng_key"]).shape \
                == np.asarray(ff._rng).shape
            assert meta["dataloaders"]["x"] == 16  # one batch consumed
            assert meta["dataloaders"]["label"] == 16
    # retention: only the newest 2 step dirs survive
    dirs = sorted(d for d in os.listdir(tmp_path) if d.startswith("step_"))
    assert dirs == ["step_3", "step_4"]
    assert latest_step(str(tmp_path)) == 4
    # checkpoints are self-contained (meta + strategy inside the step dir)
    assert os.path.exists(tmp_path / "step_4" / "ff_meta.json")
    assert os.path.exists(tmp_path / "step_4" / "strategy.txt")
    # a leftover tmp dir from a killed save is ignored, not a checkpoint
    (tmp_path / ".tmp-step_99").mkdir()
    assert latest_step(str(tmp_path)) == 4
    # and restore of the survivor works
    ff2 = _build(tmp_path)
    assert restore_checkpoint(ff2, str(tmp_path)) == 4
    np.testing.assert_array_equal(ff2.get_weights("fc1"),
                                  ff.get_weights("fc1"))


# ------------------------------------------------- retry / injected IO fail


def test_retry_on_injected_save_failure(tmp_path, monkeypatch):
    ff = _build(tmp_path)
    sup = TrainSupervisor(ff, str(tmp_path))
    sup.step()
    # every attempt fails -> retry exhausts and the error propagates
    monkeypatch.setenv("FF_FAULT", "io_fail@save:1-3")
    faultinject.reset()
    with pytest.raises(OSError):
        sup.save(reason="test")
    assert latest_step(str(tmp_path)) is None
    # only the 1st attempt fails -> backoff retry recovers transparently
    monkeypatch.setenv("FF_FAULT", "io_fail@save:1")
    faultinject.reset()
    resilience.reset_counters()
    sup.save(reason="test")
    assert resilience.COUNTERS["retries"] >= 1
    assert latest_step(str(tmp_path)) == 1


def test_retry_decorator_backoff_and_predicates():
    sleeps = []
    calls = []

    @resilience.retry(attempts=3, base_delay=0.01, retryable=(ValueError,),
                      sleep=sleeps.append)
    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ValueError("transient")
        return "ok"

    assert flaky() == "ok"
    assert len(calls) == 3 and sleeps == [0.01, 0.02]

    @resilience.retry(attempts=3, base_delay=0.01, retryable=(ValueError,),
                      sleep=sleeps.append)
    def wrong_kind():
        raise KeyError("not retryable")

    with pytest.raises(KeyError):
        wrong_kind()


# --------------------------------------------- divergence guard: skip-step


def test_nan_injection_skips_step_params_untouched(tmp_path):
    ff = _build(tmp_path)
    sup = TrainSupervisor(ff, str(tmp_path),
                          faults=FaultPlan.parse("nan_loss@step:3"))
    sup.step(), sup.after_step()
    sup.step(), sup.after_step()
    w = np.array(ff.get_weights("fc1"))
    mom = {k: np.array(v) for k, v in
           ff.opt_state.get("fc1", {}).items()} if ff.opt_state else {}
    sup.step()  # step 3: injected NaN
    assert np.isnan(sup.losses[-1])
    np.testing.assert_array_equal(ff.get_weights("fc1"), w)
    for k, v in mom.items():
        np.testing.assert_array_equal(np.asarray(ff.opt_state["fc1"][k]), v)
    assert int(np.asarray(ff._guard_state["skipped"])) == 1
    assert int(np.asarray(ff._guard_state["bad_streak"])) == 1
    sup.after_step()
    assert resilience.COUNTERS["steps_skipped"] == 1
    sup.step()  # step 4: finite again, training proceeds
    sup.after_step()
    assert np.isfinite(sup.losses[-1])
    assert int(np.asarray(ff._guard_state["bad_streak"])) == 0
    assert not np.array_equal(ff.get_weights("fc1"), w)


def test_guarded_step_matches_unguarded_bitwise():
    losses = {}
    for mode in ("none", "skip"):
        ff = _build("", on_nonfinite=mode)
        ls = []
        for _ in range(5):
            loss, _ = ff._run_train_step(ff._stage_batch())
            ls.append(float(loss))
        losses[mode] = ls
    assert losses["none"] == losses["skip"], \
        "guard must be a bitwise no-op on finite steps"


def test_guard_is_in_graph_no_host_sync():
    """The whole guarded step (finite check, skip/keep selection, streak
    update) must trace abstractly — any host-side branch/fetch on device
    values would raise a ConcretizationTypeError here."""
    import jax

    ff = _build("")
    batch = ff.executor.shard_batch(ff._stage_batch())
    out = jax.eval_shape(ff._guarded_step, ff.params, ff.opt_state,
                         ff.bn_state, batch, ff._rng, ff._guard_state,
                         np.bool_(False))
    assert len(out) == 6  # params, opt, bn, loss, mets, guard_state


def test_backoff_mode_halves_loss_scale(tmp_path):
    ff = _build(tmp_path, on_nonfinite="backoff")
    sup = TrainSupervisor(ff, str(tmp_path),
                          faults=FaultPlan.parse("nan_loss@step:2"))
    sup.step()
    assert float(np.asarray(ff._guard_state["loss_scale"])) == 1.0
    sup.step()  # injected NaN: scale halves
    assert float(np.asarray(ff._guard_state["loss_scale"])) == 0.5
    sup.step()  # finite: scale holds until the growth interval
    assert float(np.asarray(ff._guard_state["loss_scale"])) == 0.5
    assert np.isfinite(sup.losses[-1])


# ------------------------------------------------------------------ rewind


def test_rewind_after_consecutive_nans(tmp_path):
    ff = _build(tmp_path, rewind_after=2, checkpoint_every=2)
    sup = TrainSupervisor(
        ff, str(tmp_path),
        faults=FaultPlan.parse("nan_loss@step:3,nan_loss@step:4"))
    assert sup.run(6) == "completed"
    assert resilience.COUNTERS["rewinds"] == 1
    assert ff._step_count == 6
    assert len(sup.losses) == 6 and np.isfinite(sup.losses).all(), \
        "rewound steps re-execute cleanly"
    # after the rewind to the step-2 checkpoint, the trajectory must be
    # exactly the clean run's (params, RNG, and cursors all restored)
    clean = _build(tmp_path / "clean", rewind_after=2, checkpoint_every=2)
    csup = TrainSupervisor(clean, str(tmp_path / "clean"))
    assert csup.run(6) == "completed"
    assert sup.losses == csup.losses

    # regression: a rewind AFTER a resume must truncate `losses` relative
    # to the resume offset (absolute step indexing left stale NaN entries).
    # Fresh supervisors on the step-6 models: resume() restores the step-6
    # checkpoint, so losses index from base 6
    # checkpoint_every=0 pins the rewind target to the step-6 checkpoint
    # (periodic saves would otherwise land one mid-streak at step 8)
    sup2 = TrainSupervisor(
        ff, str(tmp_path), rewind_after=2, checkpoint_every=0,
        faults=FaultPlan.parse("nan_loss@step:8,nan_loss@step:9"))
    assert sup2.run(10) == "completed"  # resumes at 6, rewinds once
    assert resilience.COUNTERS["rewinds"] == 2
    assert len(sup2.losses) == 4 and np.isfinite(sup2.losses).all()
    csup2 = TrainSupervisor(clean, str(tmp_path / "clean"), rewind_after=2,
                            checkpoint_every=0)
    assert csup2.run(10) == "completed"
    assert sup2.losses == csup2.losses

    # livelock cap: a rewind replays identical state, so rewinding to the
    # SAME checkpoint repeatedly (deterministic NaN) must abort loudly
    sup3 = TrainSupervisor(ff, str(tmp_path), max_rewinds=2)
    sup3.rewind()
    sup3.rewind()
    with pytest.raises(RuntimeError, match="livelock"):
        sup3.rewind()


def test_fit_rewind_step_accounting(tmp_path, monkeypatch):
    # reviewer repro: 1 epoch x 4 batches, checkpoint at 1, NaN at step 3
    # with rewind_after=1 — the rewound steps replay and the epoch must
    # end at exactly 4 counted batch indices (off-by-one here trained a
    # duplicate extra step per rewind)
    monkeypatch.setenv("FF_FAULT", "nan_loss@step:3")
    faultinject.reset()
    ff = _build(tmp_path, rewind_after=1, checkpoint_every=2)
    ff.fit(verbose=False)
    assert resilience.COUNTERS["rewinds"] == 1
    # steps 1, 2, 3(NaN) -> rewind to step-1 ckpt (k=2) -> replay 2', 3',
    # then 4: counter ends at 4, one extra EXECUTED step per rewound one
    assert ff._step_count == 4


def test_rewind_without_checkpoint_raises(tmp_path):
    ff = _build(tmp_path)
    sup = TrainSupervisor(ff, str(tmp_path / "empty"))
    with pytest.raises(RuntimeError, match="no checkpoint"):
        sup.rewind()


# ------------------------------------------- preemption: SIGTERM + resume


def test_kill_and_resume_bitwise_identical(tmp_path, monkeypatch):
    # uninterrupted reference run: 15 supervised steps (the 64-sample /
    # 4-batch dataset wraps ~4x, so cursor restore is exercised too)
    ff_a = _build(tmp_path / "a")
    sup_a = TrainSupervisor(ff_a, str(tmp_path / "a"))
    assert sup_a.run(15) == "completed"
    assert len(sup_a.losses) == 15

    # interrupted run: injected SIGTERM right after step 9 — the handler
    # flags, the supervisor checkpoints at the step boundary and stops
    monkeypatch.setenv("FF_FAULT", "sigterm@step:9")
    faultinject.reset()
    prev = signal.getsignal(signal.SIGTERM)
    ff_b = _build(tmp_path / "b")
    sup_b = TrainSupervisor(ff_b, str(tmp_path / "b"))
    assert sup_b.run(15) == "preempted"
    assert ff_b._step_count == 9
    assert latest_step(str(tmp_path / "b")) == 9
    assert load_meta(str(tmp_path / "b"), 9)["reason"] == "preempt"
    assert signal.getsignal(signal.SIGTERM) == prev, \
        "run() must restore the previous SIGTERM disposition"
    assert resilience.COUNTERS["preempt_stops"] == 1
    # through step 9 the interrupted run tracked the reference bitwise
    assert sup_b.losses == sup_a.losses[:9]

    # "restart the job": a fresh model resumes from the auto-checkpoint
    monkeypatch.delenv("FF_FAULT")
    faultinject.reset()
    ff_c = _build(tmp_path / "b")
    sup_c = TrainSupervisor(ff_c, str(tmp_path / "b"))
    assert sup_c.run(15) == "completed"
    assert resilience.COUNTERS["resumes"] == 1
    # steps 10..15 bitwise identical to the uninterrupted run
    assert sup_c.losses == sup_a.losses[9:]
    np.testing.assert_array_equal(ff_c.get_weights("fc1"),
                                  ff_a.get_weights("fc1"))
    np.testing.assert_array_equal(np.asarray(ff_c._rng),
                                  np.asarray(ff_a._rng))


def test_fit_auto_resume_and_preemption(tmp_path, monkeypatch):
    # 2 epochs x 4 batches = 8 steps; preempt after step 5 (mid-epoch 2)
    monkeypatch.setenv("FF_FAULT", "sigterm@step:5")
    faultinject.reset()
    ff = _build(tmp_path, checkpoint_every=4)
    ff.config.epochs = 2
    ff.fit(verbose=False)
    assert ff._step_count == 5
    assert latest_step(str(tmp_path)) == 5

    # restart: fit() resumes from step 5 and finishes the remaining steps
    monkeypatch.delenv("FF_FAULT")
    faultinject.reset()
    ff2 = _build(tmp_path, checkpoint_every=4)
    ff2.config.epochs = 2
    ff2.fit(verbose=False)
    assert ff2._step_count == 8
    # the resumed trajectory matches an uninterrupted 2-epoch run (no
    # supervisor at all — plain fit on an empty checkpoint_dir config)
    ref = _build("")
    ref.config.epochs = 2
    ref.fit(verbose=False)
    np.testing.assert_array_equal(ff2.get_weights("fc1"),
                                  ref.get_weights("fc1"))


# ---------------------------------------------------------------- watchdog


def test_watchdog_dumps_and_calls_on_timeout(tmp_path):
    dump = tmp_path / "dump.txt"
    fired = []
    wd = resilience.Watchdog(0.1, on_timeout=fired.append,
                             dump_path=str(dump))
    with wd.arm("slow step"):
        time.sleep(0.35)
    assert fired == ["slow step"] and wd.fired
    text = dump.read_text()
    assert "watchdog" in text and "Current thread" in text
    assert resilience.COUNTERS["watchdog_fires"] == 1


def test_watchdog_default_aborts_main_thread():
    wd = resilience.Watchdog(0.1)
    with pytest.raises(KeyboardInterrupt):
        with wd.arm("hung collective"):
            time.sleep(0.5)


def test_watchdog_disarmed_and_fast_path():
    wd = resilience.Watchdog(0.0)
    with wd.arm("x"):
        pass  # disarmed: no timer
    wd = resilience.Watchdog(5.0)
    with wd.arm("y"):
        pass  # fast step: timer cancelled, nothing fires
    assert not wd.fired
    assert resilience.COUNTERS["watchdog_fires"] == 0


def test_hang_injection_trips_supervisor_watchdog(tmp_path):
    ff = _build(tmp_path)
    sup = TrainSupervisor(ff, str(tmp_path), step_timeout_s=0.15,
                          faults=FaultPlan.parse("hang@step:2"))
    with pytest.raises(KeyboardInterrupt):
        sup.run(3)
    assert resilience.COUNTERS["watchdog_fires"] == 1
