"""CONTRACT (row-parallel / Megatron) sharding: numerics, persistence,
and cost-model semantics.

The reference expresses row parallelism as Linear's NDIM+1 replica dim +
backward2 reduction (linear.cu:171-192,774-835); the TPU re-design shards the
kernel's input-feature dim over a mesh axis (axis_map value CONTRACT) and
lets GSPMD insert the activation psum."""

import numpy as np
import pytest

from flexflow_tpu import (ActiMode, FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.parallel.pconfig import CONTRACT, ParallelConfig
from flexflow_tpu.parallel.strategy import (load_strategies_from_file,
                                            save_strategies_to_file)
from flexflow_tpu.search.cost_model import CostModel
from flexflow_tpu.search.driver import legal_axis_maps

MESH = {"data": 2, "model": 4}


def build(strategies):
    cfg = FFConfig(batch_size=16, mesh_shape=dict(MESH))
    cfg.strategies = dict(strategies)
    ff = FFModel(cfg)
    x = ff.create_tensor([16, 64], name="x")
    t = ff.dense(x, 128, ActiMode.AC_MODE_RELU, name="col")
    t = ff.dense(t, 64, name="row")
    ff.dense(t, 8, name="head")
    ff.compile(SGDOptimizer(lr=0.1),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY])
    return ff


def megatron_strategy():
    return {
        "col": ParallelConfig.from_axis_map(2, MESH, {"data": 0, "model": 1}),
        "row": ParallelConfig.from_axis_map(
            2, MESH, {"data": 0, "model": CONTRACT}),
        "head": ParallelConfig.from_axis_map(2, MESH, {"data": 0}),
    }


def _train_losses(strategies, steps=3):
    ff = build(strategies)
    rs = np.random.RandomState(0)
    xd = rs.randn(16, 64).astype(np.float32)
    yd = rs.randint(0, 8, (16, 1)).astype(np.int32)
    SingleDataLoader(ff, ff.ops[0].outputs[0], xd)
    SingleDataLoader(ff, ff.label_tensor, yd)
    out = []
    for _ in range(steps):
        loss, _ = ff._run_train_step(ff._stage_batch())
        out.append(float(loss))
    return out


def test_megatron_pair_matches_dp_numerics():
    """col(column-parallel) -> row(CONTRACT) training must be numerically
    identical to pure DP: GSPMD's psum replaces the reference's backward2
    replica reduction."""
    dp = _train_losses({})
    meg = _train_losses(megatron_strategy())
    np.testing.assert_allclose(dp, meg, rtol=1e-4, atol=1e-5)


def test_contract_weight_sharding_applied():
    ff = build(megatron_strategy())
    sh = ff.executor.param_shardings()
    # row's kernel is sharded on its INPUT dim over 'model'
    assert sh["row"]["kernel"].spec[0] == "model"
    assert sh["row"]["kernel"].spec[1] is None
    # col's kernel is sharded on its OUTPUT dim
    assert sh["col"]["kernel"].spec[1] == "model"


def test_contract_round_trips_through_strategy_file(tmp_path):
    """The text schema carries the contract degree as a trailing dim entry
    (the reference's replica-dim convention); a degrees-only reload must
    resolve back to a CONTRACT axis map."""
    from flexflow_tpu.runtime.executor import resolve_axis_map

    path = str(tmp_path / "s.txt")
    strat = megatron_strategy()
    save_strategies_to_file(path, strat)
    loaded = load_strategies_from_file(path)
    pc = loaded["row"]
    assert pc.dims == (2, 1, 4)  # batch 2-way, out unsharded, contract 4-way
    am = resolve_axis_map(pc, MESH, ndims=2)
    assert am.get("model") == CONTRACT and am.get("data") == 0
    # and training under the reloaded strategy still matches DP
    reloaded_losses = _train_losses(loaded)
    np.testing.assert_allclose(_train_losses({}), reloaded_losses,
                               rtol=1e-4, atol=1e-5)


def test_cost_model_megatron_pair_has_free_edge():
    """The col->row edge needs NO resharding: col's output is sharded on its
    last dim over 'model', exactly what the CONTRACT consumer wants."""
    ff = build({})
    cost = CostModel(ff, MESH)
    col = ff.get_op_by_name("col")
    row = ff.get_op_by_name("row")
    pm = col.output_axis_map({"data": 0, "model": 1})
    want = row.input_axis_map({"data": 0, "model": CONTRACT}, 0)
    assert cost.resharding_time(pm, want, col.outputs[0]) == 0.0
    # whereas feeding a CONTRACT consumer from a replicated producer is not free
    pm_dp = col.output_axis_map({"data": 0})
    assert cost.resharding_time(pm_dp, want, col.outputs[0]) > 0.0


def test_contract_in_legal_axis_maps_and_sync_free():
    ff = build({})
    row = ff.get_op_by_name("row")
    maps = legal_axis_maps(row, MESH)
    assert {"data": 0, "model": CONTRACT} in maps
    # contract shards the kernel -> no grad all-reduce over 'model'
    cost = CostModel(ff, MESH)
    sync_contract = cost.op_grad_sync_time(row, {"data": 0, "model": CONTRACT})
    sync_dp = cost.op_grad_sync_time(row, {"data": 0, "model": 0})
    assert sync_contract < sync_dp
    # but the contract choice pays the activation psum in compute
    t_contract = cost.op_compute_time(row, {"data": 0, "model": CONTRACT})
    t_dp = cost.op_compute_time(row, {"data": 0, "model": 0})
    assert t_contract > 0 and t_dp > 0


def test_measured_table_distinguishes_contract_from_dp():
    """The measured-cost cache key must separate CONTRACT from plain DP:
    both have the same per-shard OUTPUT shape, but contract shards the
    inputs/weights. A collision would price row-parallel as the DP
    measurement and silently drop the psum term."""
    from flexflow_tpu.search.measure import choice_key

    ff = build({})
    row = ff.get_op_by_name("row")
    dp_key = choice_key("row", row.outputs[0].dims,
                        {"data": 0, "model": 0}, MESH)
    c_key = choice_key("row", row.outputs[0].dims,
                       {"data": 0, "model": CONTRACT}, MESH)
    assert dp_key != c_key
    # with a measured entry for the DP key only, the contract choice must
    # NOT reuse it (falls back to analytic + psum)
    cost = CostModel(ff, MESH, measured={dp_key: 1e-6})
    t_dp = cost.op_compute_time(row, {"data": 0, "model": 0})
    t_c = cost.op_compute_time(row, {"data": 0, "model": CONTRACT})
    assert t_dp == 1e-6
    assert t_c != t_dp
    # and a measured entry for the contract key is used but still pays psum
    base = 1e-6
    cost2 = CostModel(ff, MESH, measured={c_key: base})
    assert cost2.op_compute_time(row, {"data": 0, "model": CONTRACT}) > base


def test_conv_contract_matches_dp_numerics():
    """Conv2D row-parallel pair (c1 out-channel-sharded -> c2 CONTRACT on
    input channels) trains identically to DP."""
    def build(strategies):
        cfg = FFConfig(batch_size=8, mesh_shape=dict(MESH))
        cfg.strategies = dict(strategies)
        ff = FFModel(cfg)
        from flexflow_tpu.ffconst import ActiMode as AM
        x = ff.create_tensor([8, 8, 16, 16], name="x")
        t = ff.conv2d(x, 16, 3, 3, 1, 1, 1, 1, AM.AC_MODE_RELU, name="c1")
        t = ff.conv2d(t, 8, 3, 3, 1, 1, 1, 1, name="c2")
        t = ff.flat(t)
        ff.dense(t, 4, name="head")
        ff.compile(SGDOptimizer(lr=0.05),
                   LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                   [MetricsType.METRICS_ACCURACY])
        return ff

    meg = {
        "c1": ParallelConfig.from_axis_map(4, MESH, {"data": 0, "model": 1}),
        "c2": ParallelConfig.from_axis_map(
            4, MESH, {"data": 0, "model": CONTRACT}),
    }
    rs = np.random.RandomState(0)
    xd = rs.randn(16, 8, 16, 16).astype(np.float32)
    yd = rs.randint(0, 4, (16, 1)).astype(np.int32)
    out = {}
    for name, s in (("dp", {}), ("meg", meg)):
        ff = build(s)
        SingleDataLoader(ff, ff.ops[0].outputs[0], xd)
        SingleDataLoader(ff, ff.label_tensor, yd)
        ls = []
        for _ in range(3):
            loss, _ = ff._run_train_step(ff._stage_batch())
            ls.append(float(loss))
        out[name] = ls
    np.testing.assert_allclose(out["dp"], out["meg"], rtol=1e-4, atol=1e-5)
    # kernel sharded on its input-channel dim
    assert ff.params["c2"]["kernel"].sharding.spec[1] == "model"


def test_channel_sharded_batchnorm_matches_dp():
    """BN statistics reduce over N,H,W only, so sharding the channel dim
    (with scale/bias sharded alongside) must train identically to DP — this
    is what lets a channel-sharded conv feed BN without an all-gather."""
    def build(strategies):
        cfg = FFConfig(batch_size=8, mesh_shape=dict(MESH))
        cfg.strategies = dict(strategies)
        ff = FFModel(cfg)
        x = ff.create_tensor([8, 8, 16, 16], name="x")
        t = ff.conv2d(x, 16, 3, 3, 1, 1, 1, 1, name="c1")
        t = ff.batch_norm(t, relu=True, name="bn1")
        t = ff.conv2d(t, 8, 3, 3, 1, 1, 1, 1, name="c2")
        t = ff.flat(t)
        ff.dense(t, 4, name="head")
        ff.compile(SGDOptimizer(lr=0.05),
                   LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                   [MetricsType.METRICS_ACCURACY])
        return ff

    ch = {
        "c1": ParallelConfig.from_axis_map(4, MESH, {"data": 0, "model": 1}),
        "bn1": ParallelConfig.from_axis_map(4, MESH, {"data": 0, "model": 1}),
    }
    rs = np.random.RandomState(0)
    xd = rs.randn(16, 8, 16, 16).astype(np.float32)
    yd = rs.randint(0, 4, (16, 1)).astype(np.int32)
    out, states = {}, {}
    for name, s in (("dp", {}), ("chan", ch)):
        ff = build(s)
        SingleDataLoader(ff, ff.ops[0].outputs[0], xd)
        SingleDataLoader(ff, ff.label_tensor, yd)
        ls = []
        for _ in range(3):
            loss, _ = ff._run_train_step(ff._stage_batch())
            ls.append(float(loss))
        out[name] = ls
        states[name] = {k: np.asarray(v)
                        for k, v in ff.bn_state["bn1"].items()}
    np.testing.assert_allclose(out["dp"], out["chan"], rtol=1e-4, atol=1e-5)
    assert ff.params["bn1"]["scale"].sharding.spec[0] == "model"
    # running statistics (the eval-path state) must also match DP
    np.testing.assert_allclose(
        np.asarray(states["dp"]["mean"]), np.asarray(states["chan"]["mean"]),
        rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(states["dp"]["var"]), np.asarray(states["chan"]["var"]),
        rtol=1e-5, atol=1e-6)


def test_contract_output_not_sharded():
    """CONTRACT axes never appear in the output PartitionSpec, and the
    per-shard output shape ignores them."""
    from flexflow_tpu.search.measure import shard_shape

    pc = ParallelConfig.from_axis_map(2, MESH, {"data": 0, "model": CONTRACT})
    spec = pc.to_partition_spec(2, ["data", "model"])
    assert spec[0] == "data" and spec[1] is None
    assert shard_shape((16, 64), {"data": 0, "model": CONTRACT}, MESH) \
        == (8, 64)
