"""FSDP / ZeRO-3 analog (FFConfig.fsdp_axis): weights + optimizer state
sharded over the data axis on top of any strategy sharding; GSPMD
all-gathers at use and reduce-scatters gradients. Numerics must be
IDENTICAL to the unsharded run — FSDP is a memory layout, not a model
change."""

import numpy as np
import pytest

from flexflow_tpu import (AdamOptimizer, FFConfig, FFModel, LossType,
                          MetricsType)
from flexflow_tpu.parallel.pconfig import ParallelConfig

MESH = {"data": 4, "model": 2}


def _build(fsdp):
    cfg = FFConfig(batch_size=16, mesh_shape=dict(MESH),
                   fsdp_axis="data" if fsdp else "")
    # TP on the first dense: its kernel already shards out-dim on
    # 'model'; FSDP adds 'data' on the in-dim -> 2D-sharded weight
    cfg.strategies = {"d1": ParallelConfig.from_axis_map(
        2, MESH, {"data": 0, "model": 1})}
    ff = FFModel(cfg)
    x = ff.create_tensor([16, 64], name="input")
    t = ff.dense(x, 128, name="d1")
    t = ff.relu(t, name="r1")
    t = ff.dense(t, 64, name="d2")
    t = ff.dense(t, 8, name="head")
    ff.compile(AdamOptimizer(alpha=0.01),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=t)
    return ff


def test_fsdp_shards_params_and_opt_state():
    ff = _build(True)
    k1 = ff.params["d1"]["kernel"]          # (64, 128), TP'd on 'model'
    assert "data" in str(k1.sharding.spec) and "model" in str(k1.sharding.spec)
    # 2D sharded: each device holds 1/8 of the array
    shard = k1.addressable_shards[0].data
    assert shard.size * 8 == k1.size, (shard.shape, k1.shape)
    k2 = ff.params["d2"]["kernel"]          # (128, 64), no strategy
    assert "data" in str(k2.sharding.spec)
    assert k2.addressable_shards[0].data.size * 4 == k2.size
    # optimizer state follows the param sharding
    m = ff.opt_state["m"]["d2"]["kernel"]
    assert m.addressable_shards[0].data.size * 4 == m.size


def test_fsdp_numerics_match_unsharded():
    rs = np.random.RandomState(0)
    batch = {"input": rs.randn(16, 64).astype(np.float32),
             "label": rs.randint(0, 8, (16, 1)).astype(np.int32)}
    ff_f, ff_r = _build(True), _build(False)
    for _ in range(3):
        lf, _ = ff_f._run_train_step(batch)
        lr, _ = ff_r._run_train_step(batch)
    np.testing.assert_allclose(float(lf), float(lr), rtol=1e-5)
    for op, ws in ff_r.params.items():
        for w, v in ws.items():
            np.testing.assert_allclose(
                np.asarray(ff_f.params[op][w]), np.asarray(v),
                atol=1e-5, rtol=1e-5, err_msg=f"{op}/{w}")
    # sharding survives the donated train step (stays FSDP across steps)
    assert "data" in str(ff_f.params["d2"]["kernel"].sharding.spec)


def test_fsdp_validation_and_indivisible_fallback():
    with pytest.raises(ValueError, match="not a mesh axis"):
        cfg = FFConfig(batch_size=8, mesh_shape={"data": 2},
                       fsdp_axis="zero")
        ff = FFModel(cfg)
        x = ff.create_tensor([8, 16], name="input")
        ff.dense(x, 4, name="d")
        ff.compile()
    # a weight with no divisible dim stays unsharded instead of failing
    cfg = FFConfig(batch_size=8, mesh_shape={"data": 8}, fsdp_axis="data")
    ff = FFModel(cfg)
    x = ff.create_tensor([8, 6], name="input")
    ff.dense(x, 6, name="tiny")  # 6x6: nothing divides 8
    ff.compile()
    assert "data" not in str(ff.params["tiny"]["kernel"].sharding.spec)
