"""FSDP / ZeRO-3 analog (FFConfig.fsdp_axis): weights + optimizer state
sharded over the data axis on top of any strategy sharding; GSPMD
all-gathers at use and reduce-scatters gradients. Numerics must be
IDENTICAL to the unsharded run — FSDP is a memory layout, not a model
change."""

import numpy as np
import pytest

from flexflow_tpu import (AdamOptimizer, FFConfig, FFModel, LossType,
                          MetricsType)
from flexflow_tpu.parallel.pconfig import ParallelConfig

MESH = {"data": 4, "model": 2}


def _build(fsdp):
    cfg = FFConfig(batch_size=16, mesh_shape=dict(MESH),
                   fsdp_axis="data" if fsdp else "")
    # TP on the first dense: its kernel already shards out-dim on
    # 'model'; FSDP adds 'data' on the in-dim -> 2D-sharded weight
    cfg.strategies = {"d1": ParallelConfig.from_axis_map(
        2, MESH, {"data": 0, "model": 1})}
    ff = FFModel(cfg)
    x = ff.create_tensor([16, 64], name="input")
    t = ff.dense(x, 128, name="d1")
    t = ff.relu(t, name="r1")
    t = ff.dense(t, 64, name="d2")
    t = ff.dense(t, 8, name="head")
    ff.compile(AdamOptimizer(alpha=0.01),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=t)
    return ff


def test_fsdp_shards_params_and_opt_state():
    ff = _build(True)
    k1 = ff.params["d1"]["kernel"]          # (64, 128), TP'd on 'model'
    assert "data" in str(k1.sharding.spec) and "model" in str(k1.sharding.spec)
    # 2D sharded: each device holds 1/8 of the array
    shard = k1.addressable_shards[0].data
    assert shard.size * 8 == k1.size, (shard.shape, k1.shape)
    k2 = ff.params["d2"]["kernel"]          # (128, 64), no strategy
    assert "data" in str(k2.sharding.spec)
    assert k2.addressable_shards[0].data.size * 4 == k2.size
    # optimizer state follows the param sharding
    m = ff.opt_state["m"]["d2"]["kernel"]
    assert m.addressable_shards[0].data.size * 4 == m.size


def test_fsdp_numerics_match_unsharded():
    rs = np.random.RandomState(0)
    batch = {"input": rs.randn(16, 64).astype(np.float32),
             "label": rs.randint(0, 8, (16, 1)).astype(np.int32)}
    ff_f, ff_r = _build(True), _build(False)
    for _ in range(3):
        lf, _ = ff_f._run_train_step(batch)
        lr, _ = ff_r._run_train_step(batch)
    np.testing.assert_allclose(float(lf), float(lr), rtol=1e-5)
    for op, ws in ff_r.params.items():
        for w, v in ws.items():
            np.testing.assert_allclose(
                np.asarray(ff_f.params[op][w]), np.asarray(v),
                atol=1e-5, rtol=1e-5, err_msg=f"{op}/{w}")
    # sharding survives the donated train step (stays FSDP across steps)
    assert "data" in str(ff_f.params["d2"]["kernel"].sharding.spec)


def test_cost_model_prices_fsdp():
    """Search-side FSDP awareness (time model): grad sync over the fsdp
    axis becomes a reduce-scatter (~half an all-reduce) plus 2 per-step
    weight all-gathers; memory is already per-shard-credited (see
    op_mem_bytes approximation note), so it is unchanged."""
    from flexflow_tpu.search.cost_model import CostModel

    cfg = FFConfig(batch_size=16, mesh_shape=dict(MESH))
    ff = FFModel(cfg)
    x = ff.create_tensor([16, 256], name="input")
    t = ff.dense(x, 1024, name="big")
    ff.dense(t, 8, name="head")
    dp = {"data": 0}
    plain = CostModel(ff, MESH)
    fsdp = CostModel(ff, MESH, fsdp_axis="data")
    op = ff.get_op_by_name("big")

    assert fsdp.op_mem_bytes(op, dp) == plain.op_mem_bytes(op, dp)

    s_plain, s_fsdp = (c.op_grad_sync_time(op, dp) for c in (plain, fsdp))
    assert s_fsdp != s_plain
    # reduce-scatter (0.5x all-reduce) + 2 gathers of the 1/4-resident
    # weight: strictly between half and double the plain all-reduce
    assert 0.5 * s_plain < s_fsdp < 2.0 * s_plain

    # a weight whose partition already uses the fsdp axis (TP on 'model'
    # with fsdp_axis='model') gets no FSDP terms at all
    tp = {"data": 0, "model": 1}
    both = CostModel(ff, MESH, fsdp_axis="model")
    np.testing.assert_allclose(both.op_grad_sync_time(op, tp),
                               plain.op_grad_sync_time(op, tp))

    # CostModel defaults fsdp_axis from the model's config
    cfg2 = FFConfig(batch_size=16, mesh_shape=dict(MESH), fsdp_axis="data")
    ff2 = FFModel(cfg2)
    x2 = ff2.create_tensor([16, 256], name="input")
    ff2.dense(x2, 1024, name="big")
    auto = CostModel(ff2, MESH)
    assert auto.fsdp_axis == "data"

    # explicit typo'd axis raises (config-derived absence is dropped)
    with pytest.raises(ValueError, match="not a mesh axis"):
        CostModel(ff, MESH, fsdp_axis="dat")

    # a weight with NO dim divisible by the fsdp axis is priced plain
    # (matches executor._with_fsdp's degrade-to-unsharded rule)
    cfg3 = FFConfig(batch_size=16, mesh_shape=dict(MESH))
    ff3 = FFModel(cfg3)
    x3 = ff3.create_tensor([16, 255], name="input")
    ff3.dense(x3, 1023, use_bias=False, name="odd")  # 255x1023: 4 | none
    odd = ff3.get_op_by_name("odd")
    np.testing.assert_allclose(
        CostModel(ff3, MESH, fsdp_axis="data").op_grad_sync_time(odd, dp),
        CostModel(ff3, MESH).op_grad_sync_time(odd, dp))


def test_fsdp_validation_and_indivisible_fallback():
    with pytest.raises(ValueError, match="not a mesh axis"):
        cfg = FFConfig(batch_size=8, mesh_shape={"data": 2},
                       fsdp_axis="zero")
        ff = FFModel(cfg)
        x = ff.create_tensor([8, 16], name="input")
        ff.dense(x, 4, name="d")
        ff.compile()
    # a weight with no divisible dim stays unsharded instead of failing
    cfg = FFConfig(batch_size=8, mesh_shape={"data": 8}, fsdp_axis="data")
    ff = FFModel(cfg)
    x = ff.create_tensor([8, 6], name="input")
    ff.dense(x, 6, name="tiny")  # 6x6: nothing divides 8
    ff.compile()
    assert "data" not in str(ff.params["tiny"]["kernel"].sharding.spec)
