"""Search v2 tests: persistent op-cost DB, warm-started search, and the
multi-objective (time x HBM) objective (ISSUE 19).

Covers the contracts the PR pins:
  * table_store round-trip + atomic publish (no .tmp debris, valid JSON);
  * measured vs analyzed entries for ONE op signature can never collide
    or shadow (the ("analyze",) tuple-prefix bug, satellite 2);
  * a jax-version/backend bump invalidates by key mismatch;
  * a warm-started search re-measures ZERO already-keyed ops
    (cost_db.stats()["misses"] == 0);
  * a tight per-chip HBM cap makes the multi-objective search choose
    remat/ZeRO/offload relief, and the chosen strategy lints UNDER cap
    where the time-only objective lints over (and fflint escalates);
  * sequence-parallel and expert-parallel axes appear in the SOAP
    candidate space (legal_axis_maps);
  * an N-chip strategy warm-starts the M-chip search
    (warm_start_seed / rank_mesh_candidates / research path).
"""

import json
import os

import pytest

from flexflow_tpu import ActiMode, FFConfig, FFModel
from flexflow_tpu.parallel.pconfig import EXPERT, ParallelConfig
from flexflow_tpu.search import cost_db, measure, table_store
from flexflow_tpu.search.cost_model import MEM_MODES, CostModel
from flexflow_tpu.search.driver import (legal_axis_maps, optimize_strategies,
                                        optimize_strategies_multi,
                                        rank_mesh_candidates, warm_start_seed)
from flexflow_tpu.search.machine import MachineModel

MESH = {"data": 2, "model": 2}


@pytest.fixture(autouse=True)
def _fresh_caches():
    """Every test starts as a fresh process would: no in-memory signature
    cache, no table cache, zeroed counters."""
    measure._SIGNATURE_CACHE.clear()
    table_store.clear_cache()
    cost_db.reset_stats()
    yield
    measure._SIGNATURE_CACHE.clear()
    table_store.clear_cache()
    cost_db.reset_stats()


def build_mlp(mesh_shape=MESH, batch=16):
    cfg = FFConfig(batch_size=batch, mesh_shape=mesh_shape)
    ff = FFModel(cfg)
    x = ff.create_tensor([batch, 32], name="x")
    t = ff.dense(x, 64, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.dense(t, 64, ActiMode.AC_MODE_RELU, name="fc2")
    ff.dense(t, 8, name="out")
    return ff


def build_moe(mesh_shape=MESH, batch=8):
    cfg = FFConfig(batch_size=batch, mesh_shape=mesh_shape)
    ff = FFModel(cfg)
    x = ff.create_tensor([batch, 16, 32], name="x")
    ff.moe(x, num_experts=4, hidden_dim=64, name="moe")
    return ff


# ---- table_store ------------------------------------------------------------

def test_table_store_roundtrip_and_atomicity(tmp_path):
    path = str(tmp_path / "sub" / "t.json")
    table_store.publish(path, {"a": {"v": 1}, "b": {"v": 2}})
    # atomic publish: final file only, no tmp debris
    names = os.listdir(os.path.dirname(path))
    assert names == ["t.json"]
    with open(path) as f:
        data = json.load(f)
    assert data["version"] == 1
    assert data["entries"]["a"] == {"v": 1}
    # cached load serves without re-reading; reload matches
    assert table_store.load(path) == {"a": {"v": 1}, "b": {"v": 2}}
    assert table_store.load(path, reload=True) == table_store.load(path)
    # a rewrite behind the cache's back is picked up via (mtime,size)
    table_store.publish(path, {"c": {"v": 3}})
    assert table_store.load(path) == {"c": {"v": 3}}


def test_table_store_missing_and_corrupt(tmp_path):
    assert table_store.load(str(tmp_path / "nope.json")) == {}
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert table_store.load(str(bad)) == {}


# ---- keying -----------------------------------------------------------------

def test_measure_analyze_entries_never_collide(tmp_path):
    """Satellite 2: one op signature, both a measured and an analyzed
    entry — each round-trips to its own value, neither shadows the other
    (the old in-memory table prefixed analyze rows with ("analyze",),
    which a flat persisted keyspace could collide with)."""
    db = str(tmp_path / "db.json")
    sig = ("Dense", (("units", 64),), ((16, 32),), ((32, 64),),
           ("float32",), measure._env_signature())
    cost_db.record_measured(sig, 0.125, path=db)
    cost_db.record_analyzed(sig, 1e9, 2e6, path=db)
    assert cost_db.get_measured(sig, path=db) == 0.125
    assert cost_db.get_analyzed(sig, path=db) == (1e9, 2e6)
    # distinct keys on disk, env identity in the readable prefix
    entries = table_store.load(db, reload=True)
    keys = sorted(entries)
    assert len(keys) == 2
    assert keys[0].startswith("analyze|") and keys[1].startswith("measure|")
    assert all(table_store.env_key() in k for k in keys)


def test_signature_cache_kinds_distinct():
    """The in-memory cache keys are structurally distinct nested tuples —
    ("measure", sig) vs ("analyze", sig) — not flat concatenations that
    an adversarial signature could alias."""
    sig = ("Dense", (("units", 8),))
    measure._SIGNATURE_CACHE[("measure", sig)] = 0.5
    measure._SIGNATURE_CACHE[("analyze", sig)] = (1.0, 2.0)
    assert measure._SIGNATURE_CACHE[("measure", sig)] == 0.5
    assert measure._SIGNATURE_CACHE[("analyze", sig)] == (1.0, 2.0)


def test_env_bump_invalidates(tmp_path, monkeypatch):
    db = str(tmp_path / "db.json")
    sig = ("Dense", (("units", 64),), measure._env_signature())
    cost_db.record_measured(sig, 0.25, path=db)
    assert cost_db.get_measured(sig, path=db) == 0.25
    # simulate a jax upgrade: the env signature changes, the entry written
    # under the old env must MISS (key mismatch), never serve stale
    monkeypatch.setattr(measure, "_ENV_SIG",
                        ("cpu", "host-cpu", "jax-99.0.0-bumped"))
    cost_db.reset_stats()
    new_sig = sig[:-1] + (measure._env_signature(),)
    assert cost_db.get_measured(new_sig, path=db) is None
    assert cost_db.stats()["misses"] == 1
    assert cost_db.stats()["hits"] == 0


def test_malformed_entry_is_illegal_not_hit(tmp_path):
    db = str(tmp_path / "db.json")
    sig = ("Dense", (("units", 64),), measure._env_signature())
    key = cost_db.record_measured(sig, 1.0, path=db)
    entries = table_store.load(db, reload=True)
    entries[key] = {"seconds": "NaN-ish garbage"}
    table_store.publish(db, entries)
    assert cost_db.get_measured(sig, path=db) is None
    assert cost_db.stats()["illegal"] == 1


def test_db_off_without_path():
    """No path, no FF_COST_DB: the DB must stay inert (hermetic runs)."""
    assert cost_db.resolve_path(None) is None or os.environ.get("FF_COST_DB")
    sig = ("Dense", (("units", 1),))
    assert cost_db.record_measured(sig, 1.0, path=None) is None \
        or os.environ.get("FF_COST_DB")


# ---- warm start: zero re-measures ------------------------------------------

def test_warm_start_analyze_zero_remeasures(tmp_path):
    db = str(tmp_path / "db.json")
    ff = build_mlp()
    cold = measure.analyze_op_costs(ff, MESH, db_path=db)
    assert len(cold) > 0
    n = cost_db.entry_count(db)
    assert n > 0
    cold_stats = cost_db.stats()
    assert cold_stats["stores"] == n

    # fresh process simulation
    measure._SIGNATURE_CACHE.clear()
    table_store.clear_cache()
    cost_db.reset_stats()

    warm = measure.analyze_op_costs(ff, MESH, db_path=db)
    s = cost_db.stats()
    assert s["misses"] == 0, s  # ZERO re-compiles for already-keyed ops
    assert s["hits"] > 0
    assert s["stores"] == 0  # nothing new to write
    assert set(warm) == set(cold)
    for k in cold:
        assert warm[k] == pytest.approx(cold[k], rel=1e-9)


# ---- multi-objective: time subject to HBM cap -------------------------------

def _drill_cap(ff, strategies):
    """A cap strictly between the strategy's unrelieved footprint and its
    best-relief floor: time-only lands over it, relief can get under."""
    cost = CostModel(ff, MESH)
    ops = {op.name: op for op in ff.ops if op.name in strategies}
    peak = sum(cost.op_mem_bytes(ops[n], strategies[n].axis_map or {})
               for n in ops)
    floor = sum(min(cost.op_mem_bytes(ops[n], strategies[n].axis_map or {},
                                      mem_mode=mm) for mm in MEM_MODES)
                for n in ops)
    assert floor < peak
    return (floor + peak) / 2.0


def test_multi_objective_drill_chooses_relief_and_lints_clean():
    from flexflow_tpu.analysis import analyze

    ff = build_mlp()
    time_only = optimize_strategies(ff, budget=80, mesh_shape=MESH, seed=3,
                                    use_native=False)
    cap = _drill_cap(ff, time_only)
    tiny = MachineModel(hbm_bytes=cap)

    # time-only objective: over cap, and fflint ESCALATES to error because
    # the relief modes could have brought it under (satellite 3)
    rep = analyze(ff, strategies=time_only, mesh_shape=MESH, machine=tiny,
                  passes=("legality", "perf"))
    over = rep.by_code("hbm-over-capacity")
    assert over and over[0].severity == "error"
    assert "multi-objective" in over[0].message

    # multi-objective search with the same budget/seed: picks relief modes
    multi = optimize_strategies_multi(ff, budget=80, mesh_shape=MESH, seed=3,
                                      hbm_cap_bytes=cap, use_native=False)
    chosen = {n: pc.mem_mode for n, pc in multi.items()
              if pc.mem_mode != "none"}
    assert chosen, "tight cap must force at least one relief mode"
    assert all(m in MEM_MODES for m in chosen.values())
    summary = ff._search_summary
    assert summary["over_cap"] is False
    assert summary["peak_hbm_bytes"] <= cap
    assert summary["predicted_step_s"] >= summary["base_step_s"]
    assert ff._predicted_step_time == summary["predicted_step_s"]

    # the chosen strategy lints UNDER cap (footprint pass audits mem_mode)
    rep2 = analyze(ff, strategies=multi, mesh_shape=MESH, machine=tiny,
                   passes=("legality", "perf"))
    assert not rep2.by_code("hbm-over-capacity")


def test_multi_objective_no_cap_is_time_only():
    """With the default (real) capacity a small model fits: the relief
    loop must be a no-op and the result identical to the time objective."""
    ff = build_mlp()
    time_only = optimize_strategies(ff, budget=60, mesh_shape=MESH, seed=7,
                                    use_native=False)
    multi = optimize_strategies_multi(ff, budget=60, mesh_shape=MESH, seed=7,
                                      use_native=False)
    assert all(pc.mem_mode == "none" for pc in multi.values())
    assert {n: pc.axis_map for n, pc in multi.items()} \
        == {n: pc.axis_map for n, pc in time_only.items()}
    assert ff._search_summary["over_cap"] is False


def test_mem_mode_accounting_monotone():
    """Relief modes must actually relieve (bytes strictly drop vs none for
    a weighted op) and cost time where physics says they must."""
    ff = build_mlp()
    cost = CostModel(ff, MESH)
    op = ff.get_op_by_name("fc1")
    am = {"data": 0}  # replicated over 'model' => relief degree 2
    base = cost.op_mem_bytes(op, am)
    for mm in ("zero1", "zero3", "offload", "remat"):
        assert cost.op_mem_bytes(op, am, mem_mode=mm) < base, mm
        assert cost.mem_mode_time(op, am, mm) > 0.0, mm
    assert cost.mem_mode_time(op, am, "none") == 0.0


# ---- SOAP space extensions --------------------------------------------------

def test_sequence_parallel_axis_in_candidates():
    cfg = FFConfig(batch_size=8, mesh_shape=MESH)
    ff = FFModel(cfg)
    x = ff.create_tensor([8, 16, 64], name="x")
    ff.transformer_pipeline_stack(x, 4, 4, name="stack")
    op = ff.get_op_by_name("stack")
    assert op.partitionable_output_dims() == [0, 1]
    assert op.single_axis_dims() == [1]
    maps = legal_axis_maps(op, MESH)
    seq = [m for m in maps if 1 in m.values()]
    assert seq, "sequence-parallel candidates missing"
    # single-axis dim: no candidate shards seq over two axes
    for m in seq:
        assert sum(1 for d in m.values() if d == 1) == 1


def test_expert_parallel_axis_in_candidates_and_pricing():
    ff = build_moe()
    op = ff.get_op_by_name("moe")
    assert op.expert_parallel_size() == 4
    maps = legal_axis_maps(op, MESH)
    ep = [m for m in maps if EXPERT in m.values()]
    assert ep, "expert-parallel candidates missing"
    cost = CostModel(ff, MESH)
    t_dp = cost.op_compute_time(op, {"data": 0})
    t_ep = cost.op_compute_time(op, {"data": 0, "model": EXPERT})
    assert t_ep > 0.0 and t_dp > 0.0
    # EXPERT shards the weights, not the output
    wp = op.weight_partition({"data": 0, "model": EXPERT})
    assert wp["w_in"][0] == "model"
    assert op.output_axis_map({"data": 0, "model": EXPERT}) \
        == {"data": 0, "model": None}
    # ...and the EXPERT strategy survives legality + serialization checks
    pc = ParallelConfig.from_axis_map(3, MESH, {"data": 0, "model": EXPERT})
    assert pc.device_ids == tuple(range(4))
    from flexflow_tpu.analysis import analyze

    rep = analyze(ff, strategies={"moe": pc}, mesh_shape=MESH,
                  passes=("legality",))
    assert not rep.by_code("dim-out-of-range")
    assert not rep.by_code("axis-unknown")


def test_expert_gated_by_parameter_parallel_flag():
    ff = build_moe()
    op = ff.get_op_by_name("moe")
    maps = legal_axis_maps(op, MESH, enable_parameter_parallel=False)
    assert not any(EXPERT in m.values() for m in maps)


# ---- elastic N -> M transfer ------------------------------------------------

def test_warm_start_seed_carries_legal_maps():
    ff = build_mlp()
    saved = {"fc1": ParallelConfig(axis_map={"data": 0, "model": 1}),
             "fc2": ParallelConfig(axis_map={"gone_axis": 0}),
             "out": ParallelConfig(axis_map={"data": 0, "model": 99})}
    seed = warm_start_seed(ff, MESH, saved)
    assert seed is not None
    assert seed["fc1"] == {"data": 0, "model": 1}  # legal: carried
    # axis absent from the new mesh / illegal dim: DP fallback, not crash
    assert seed["fc2"] == {"data": 0}
    assert seed["out"] == {"data": 0}
    # nothing carries -> None (caller skips the seed entirely)
    assert warm_start_seed(ff, MESH, {"fc1": ParallelConfig(
        axis_map={"gone": 0})}) is None
    assert warm_start_seed(ff, MESH, None) is None


def test_n_to_m_warm_start_search_and_ranking(tmp_path):
    """Strategy searched at N=4 chips warm-starts the M=2 search through
    rank_mesh_candidates, sharing cost-DB-backed measured entries."""
    db = str(tmp_path / "db.json")
    ff = build_mlp()
    measured = measure.analyze_op_costs(ff, MESH, db_path=db)
    at_n = optimize_strategies(ff, budget=60, mesh_shape=MESH, seed=5,
                               measured=measured, use_native=False)
    # M-chip candidates ranked under the SAME measured table
    ranked = rank_mesh_candidates(ff, [{"data": 2}, {"data": 4}],
                                  strategies=at_n, measured=measured)
    assert len(ranked) == 2
    assert ranked[0][0] <= ranked[1][0]
    # the M-chip search accepts the N-chip table as a warm seed and must
    # do no worse than a cold search of the same budget
    cold = optimize_strategies(ff, budget=40, mesh_shape={"data": 2}, seed=5,
                               use_native=False)
    warm = optimize_strategies(ff, budget=40, mesh_shape={"data": 2}, seed=5,
                               warm_start=at_n, use_native=False)
    cost = CostModel(ff, {"data": 2})
    t_cold = cost.iteration_time({n: pc.axis_map for n, pc in cold.items()})
    t_warm = cost.iteration_time({n: pc.axis_map for n, pc in warm.items()})
    assert t_warm <= t_cold * 1.0001


# ---- calibration ------------------------------------------------------------

def test_export_calibration_gauges_and_lint(tmp_path):
    from flexflow_tpu.analysis import analyze
    from flexflow_tpu.runtime import telemetry

    telemetry.reset()
    try:
        db = str(tmp_path / "db.json")
        ff = build_mlp()
        ff._predicted_step_time = 0.012
        hist = telemetry.registry().histogram(
            "ff_train_step_seconds", "fit() per-step wall time")
        for _ in range(8):
            hist.observe(0.010)
        rec = cost_db.export_calibration(ff, path=db)
        assert rec is not None
        assert rec["source"] == "telemetry"
        assert rec["predicted_s"] == pytest.approx(0.012)
        assert rec["ratio"] == pytest.approx(0.012 / rec["observed_s"])
        scrape = telemetry.registry().to_prometheus()
        assert "ff_csim_error_ratio" in scrape
        assert "ff_csim_predicted_step_seconds" in scrape
        assert "ff_csim_observed_step_seconds" in scrape
        # persisted as a telemetry-tagged calib entry
        entries = table_store.load(db, reload=True)
        assert any(k.startswith("calib|") for k in entries)
        # fflint surfaces the same drift as a csim-calibration info note
        rep = analyze(ff, strategies={}, mesh_shape=MESH,
                      passes=("legality", "perf"))
        cal = rep.by_code("csim-calibration")
        assert cal and cal[0].severity == "info"
        assert "ratio" in cal[0].message
    finally:
        telemetry.reset()


def test_export_calibration_absent_without_signals(tmp_path):
    from flexflow_tpu.runtime import telemetry

    telemetry.reset()
    try:
        ff = build_mlp()
        assert cost_db.export_calibration(ff) is None  # no prediction
        ff._predicted_step_time = 0.01
        assert cost_db.export_calibration(ff) is None  # no observations
    finally:
        telemetry.reset()
