"""Model-zoo smoke tests: every model builds, compiles under a hybrid mesh,
and runs one training step with finite loss (analog of the reference's
multi_gpu_tests.sh example sweep, scaled to CI shapes)."""

import numpy as np
import pytest

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, AdamOptimizer)


def one_step(ff, batch, loss=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
             final=None, optimizer=None):
    ff.compile(optimizer or SGDOptimizer(lr=0.01), loss,
               [MetricsType.METRICS_ACCURACY], final_tensor=final)
    l, _ = ff._run_train_step(batch)
    assert np.isfinite(float(l)), f"loss {l}"
    return float(l)


def test_alexnet_cifar10_builds_and_steps():
    from flexflow_tpu.models.cnn import alexnet_cifar10

    B = 16
    ff = FFModel(FFConfig(batch_size=B, mesh_shape={"data": 4}))
    x, out = alexnet_cifar10(ff, B)
    rs = np.random.RandomState(0)
    one_step(ff, {"input": rs.randn(B, 3, 32, 32).astype(np.float32),
                  "label": rs.randint(0, 10, (B, 1)).astype(np.int32)},
             final=out)


@pytest.mark.slow  # 49 s: the conv zoo is covered by alexnet/inception
def test_resnet50_builds_and_steps():
    from flexflow_tpu.models.cnn import resnet50

    B = 8
    ff = FFModel(FFConfig(batch_size=B, mesh_shape={"data": 4}))
    x, out = resnet50(ff, B, num_classes=100, image_size=64)
    assert len(ff.ops) > 100  # 16 bottleneck blocks + stem + head
    rs = np.random.RandomState(0)
    one_step(ff, {"input": rs.randn(B, 3, 64, 64).astype(np.float32),
                  "label": rs.randint(0, 100, (B, 1)).astype(np.int32)},
             final=out)


@pytest.mark.slow  # 8 s zoo build
def test_vit_builds_and_steps():
    from flexflow_tpu.models.vit import vit

    B = 8
    ff = FFModel(FFConfig(batch_size=B, mesh_shape={"data": 4}))
    x, out = vit(ff, B, image_size=32, patch_size=8, hidden=64, layers=2,
                 heads=4, num_classes=10)
    rs = np.random.RandomState(0)
    one_step(ff, {"input": rs.randn(B, 3, 32, 32).astype(np.float32),
                  "label": rs.randint(0, 10, (B, 1)).astype(np.int32)},
             final=out)


def test_inception_builds_and_steps():
    from flexflow_tpu.models.cnn import inception_v3_stem

    B = 4
    ff = FFModel(FFConfig(batch_size=B, mesh_shape={"data": 2}))
    x, out = inception_v3_stem(ff, B, num_classes=10)
    rs = np.random.RandomState(0)
    one_step(ff, {"input": rs.randn(B, 3, 299, 299).astype(np.float32),
                  "label": rs.randint(0, 10, (B, 1)).astype(np.int32)},
             final=out)


@pytest.mark.slow  # 46 s: the smaller inception build stays in tier-1
def test_inception_v3_full_builds_and_steps():
    from flexflow_tpu.models.cnn import inception_v3

    B = 2
    ff = FFModel(FFConfig(batch_size=B, mesh_shape={"data": 2}))
    x, out = inception_v3(ff, B, num_classes=10, image_size=299)
    # full tower: stem(7) + 3xA + B + 4xC + D + 2xE + head — branchy
    assert len(ff.ops) > 90
    rs = np.random.RandomState(0)
    one_step(ff, {"input": rs.randn(B, 3, 299, 299).astype(np.float32),
                  "label": rs.randint(0, 10, (B, 1)).astype(np.int32)},
             final=out)


def test_candle_uno_builds_and_steps():
    from flexflow_tpu.models.cnn import candle_uno

    B = 8
    ff = FFModel(FFConfig(batch_size=B, mesh_shape={"data": 4}))
    inputs, out = candle_uno(ff, B, dense_layers=(64, 64),
                             dense_feature_layers=(32, 32))
    assert len(inputs) == 7
    rs = np.random.RandomState(0)
    batch = {"label": rs.rand(B, 1).astype(np.float32)}
    for name, t in inputs.items():
        batch[name] = rs.randn(B, t.dims[1]).astype(np.float32)
    one_step(ff, batch, loss=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
             final=out)


def test_dlrm_builds_and_steps():
    from flexflow_tpu.models.dlrm import dlrm

    B = 32
    ff = FFModel(FFConfig(batch_size=B, mesh_shape={"data": 4, "model": 2}))
    dense_in, sparse_ins, out = dlrm(
        ff, B, embedding_entries=1000, num_tables=4, dense_dim=16,
        mlp_bot=(64, 64), mlp_top=(64, 64, 1))
    rs = np.random.RandomState(0)
    batch = {"dense_input": rs.randn(B, 16).astype(np.float32),
             "label": rs.rand(B, 1).astype(np.float32)}
    for i in range(4):
        batch[f"sparse_{i}"] = rs.randint(0, 1000, (B, 1)).astype(np.int32)
    one_step(ff, batch, loss=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
             final=out)


def test_nmt_builds_and_steps():
    from flexflow_tpu.models.nmt import nmt_seq2seq

    B = 8
    ff = FFModel(FFConfig(batch_size=B, mesh_shape={"data": 4}))
    src, tgt, logits = nmt_seq2seq(ff, B, src_len=10, tgt_len=10,
                                   embed_size=64, hidden_size=64,
                                   vocab_size=500, num_layers=2)
    rs = np.random.RandomState(0)
    one_step(ff, {"src_tokens": rs.randint(0, 500, (B, 10)).astype(np.int32),
                  "tgt_tokens": rs.randint(0, 500, (B, 10)).astype(np.int32),
                  "label": rs.randint(0, 500, (B, 10, 1)).astype(np.int32)},
             final=logits)


@pytest.mark.slow  # 10 s zoo build; transformer coverage stays via inception/gpt tests
def test_bert_base_builds_and_steps():
    from flexflow_tpu.models.bert import bert_base

    B = 4
    ff = FFModel(FFConfig(batch_size=B, mesh_shape={"data": 2, "model": 2}))
    tokens, pos, out = bert_base(ff, B, seq_len=32, hidden=64, layers=2,
                                 heads=4, vocab_size=1000)
    rs = np.random.RandomState(0)
    one_step(ff, {"input": rs.randint(0, 1000, (B, 32)).astype(np.int32),
                  "positions": np.tile(np.arange(32, dtype=np.int32), (B, 1)),
                  "label": rs.randint(0, 2, (B, 1)).astype(np.int32)},
             final=out)


@pytest.mark.slow  # 8 s zoo build; MoE pinned by test_moe_numerics/test_pipeline_moe
def test_gpt_moe_builds_and_steps():
    from flexflow_tpu.models.bert import gpt_lm

    B = 4
    ff = FFModel(FFConfig(batch_size=B,
                          mesh_shape={"data": 2, "expert": 2, "model": 2}))
    tokens, logits = gpt_lm(ff, B, seq_len=16, hidden=32, layers=2, heads=4,
                            vocab_size=256, moe_every=2, num_experts=4)
    rs = np.random.RandomState(0)
    one_step(ff, {"input": rs.randint(0, 256, (B, 16)).astype(np.int32),
                  "label": rs.randint(0, 256, (B, 16, 1)).astype(np.int32)},
             final=logits, optimizer=AdamOptimizer(alpha=1e-3))


def test_gpt_pipelined_builds_and_steps():
    from flexflow_tpu.models.bert import gpt_pipelined

    B = 8
    ff = FFModel(FFConfig(batch_size=B, mesh_shape={"pipe": 2, "data": 2}))
    tokens, logits = gpt_pipelined(ff, B, seq_len=8, hidden=32, layers=4,
                                   heads=2, vocab_size=128)
    rs = np.random.RandomState(0)
    one_step(ff, {"input": rs.randint(0, 128, (B, 8)).astype(np.int32),
                  "label": rs.randint(0, 128, (B, 8, 1)).astype(np.int32)},
             final=logits, optimizer=AdamOptimizer(alpha=1e-3))


@pytest.mark.slow  # 15 s; the seq2seq graph builds+trains in test_generation's seq2seq tests
def test_seq2seq_transformer_builds_and_steps():
    """Encoder-decoder with DISTINCT src/tgt lengths: causal decoder
    self-attn + sq != sk cross-attention (the flash cross-attn workload,
    VERDICT r3 #6) trains under a hybrid mesh; with the flash kernel
    forced on, the forward matches the einsum path."""
    import jax.numpy as jnp

    from flexflow_tpu.models.transformer import build_seq2seq_transformer

    B, SSRC, STGT, D, V = 8, 16, 8, 32, 64
    ff = FFModel(FFConfig(batch_size=B, mesh_shape={"data": 4, "model": 2}))
    src, tgt, out = build_seq2seq_transformer(
        ff, B, src_len=SSRC, tgt_len=STGT, hidden=D, layers=2, heads=2,
        vocab_size=V)
    rs = np.random.RandomState(0)
    one_step(ff, {"src": rs.randn(B, SSRC, D).astype(np.float32),
                  "tgt": rs.randn(B, STGT, D).astype(np.float32),
                  "label": rs.randint(0, V, (B, STGT, 1)).astype(np.int32)},
             final=out)


def test_seq2seq_flash_cross_matches_einsum(monkeypatch):
    from flexflow_tpu.models.transformer import build_seq2seq_transformer

    # lengths chosen to pass _flash_ok's 128-divisibility gate so the
    # cross-attention (sq=64 != sk=128) genuinely takes the flash path
    B, SSRC, STGT, D = 2, 128, 64, 32
    rs = np.random.RandomState(1)
    xs = rs.randn(B, SSRC, D).astype(np.float32)
    xt = rs.randn(B, STGT, D).astype(np.float32)

    def run():
        ff = FFModel(FFConfig(batch_size=B, mesh_shape={"data": 1}, seed=9))
        src, tgt, out = build_seq2seq_transformer(
            ff, B, src_len=SSRC, tgt_len=STGT, hidden=D, layers=1, heads=2)
        ff.compile(optimizer=None, final_tensor=out)
        return np.asarray(ff.predict({"src": xs, "tgt": xt}))

    monkeypatch.delenv("FF_FORCE_FLASH_ATTENTION", raising=False)
    y_einsum = run()
    monkeypatch.setenv("FF_FORCE_FLASH_ATTENTION", "1")
    y_flash = run()
    np.testing.assert_allclose(y_flash, y_einsum, rtol=2e-4, atol=2e-5)
