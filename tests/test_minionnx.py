"""Minimal ONNX codec (flexflow_tpu/onnx/minionnx.py): wire-format
round-trip, helper constructors, and offline end-to-end import + training
through ONNXModel (reference flow: examples/python/onnx/* without the onnx
package installed)."""

import numpy as np
import pytest

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.onnx import ONNXModel
from flexflow_tpu.onnx import minionnx as mo


def _mlp_model(batch=16, in_dim=64, hidden=128, classes=10):
    rs = np.random.RandomState(0)
    w1 = mo.from_array(rs.randn(hidden, in_dim).astype(np.float32), "w1")
    w2 = mo.from_array(rs.randn(classes, hidden).astype(np.float32), "w2")
    nodes = [
        mo.make_node("Gemm", ["input", "w1"], ["h"], name="fc1"),
        mo.make_node("Relu", ["h"], ["hr"]),
        mo.make_node("Gemm", ["hr", "w2"], ["logits"], name="fc2"),
    ]
    g = mo.make_graph(
        nodes, "mlp",
        [mo.make_tensor_value_info("input", mo.DT_FLOAT, [batch, in_dim])],
        [mo.make_tensor_value_info("logits", mo.DT_FLOAT, [batch, classes])],
        initializer=[w1, w2])
    return mo.make_model(g)


def test_wire_round_trip(tmp_path):
    m = _mlp_model()
    path = str(tmp_path / "m.onnx")
    mo.save(m, path)
    m2 = mo.load(path)
    assert [n.op_type for n in m2.graph.node] == ["Gemm", "Relu", "Gemm"]
    assert m2.graph.node[0].input == ["input", "w1"]
    assert m2.graph.node[0].name == "fc1"
    assert m2.graph.input[0].name == "input"
    assert m2.graph.input[0].type.shape_dims == [16, 64]
    assert m2.graph.initializer[0].dims == [128, 64]
    np.testing.assert_array_equal(mo.to_array(m2.graph.initializer[0]),
                                  mo.to_array(m.graph.initializer[0]))


def test_attribute_round_trip(tmp_path):
    n = mo.make_node("Conv", ["x", "k"], ["y"], name="c",
                     kernel_shape=[3, 3], strides=[2, 2],
                     pads=[1, 1, 1, 1], alpha=0.5, mode="same")
    g = mo.make_graph([n], "g",
                      [mo.make_tensor_value_info("x", mo.DT_FLOAT, [1])],
                      [mo.make_tensor_value_info("y", mo.DT_FLOAT, [1])])
    path = str(tmp_path / "a.onnx")
    mo.save(mo.make_model(g), path)
    node = mo.load(path).graph.node[0]
    attrs = {a.name: a for a in node.attribute}
    assert attrs["kernel_shape"].ints == [3, 3]
    assert attrs["strides"].type == mo.INTS
    assert attrs["alpha"].f == pytest.approx(0.5)
    assert attrs["mode"].s == b"same"


def test_offline_import_and_train(tmp_path):
    """ONNXModel loads a minionnx-serialized file (no onnx package needed)
    and the imported graph trains."""
    path = str(tmp_path / "mlp.onnx")
    mo.save(_mlp_model(), path)

    cfg = FFConfig(batch_size=16, mesh_shape={"data": 2})
    ff = FFModel(cfg)
    x = ff.create_tensor([16, 64], name="input")
    out = ONNXModel(path).apply(ff, {"input": x})
    assert out.dims == (16, 10)
    ff.compile(SGDOptimizer(lr=0.05),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=out)
    rs = np.random.RandomState(0)
    SingleDataLoader(ff, x, rs.randn(32, 64).astype(np.float32))
    SingleDataLoader(ff, ff.label_tensor,
                     rs.randint(0, 10, (32, 1)).astype(np.int32))
    losses = []
    for _ in range(4):
        loss, _ = ff._run_train_step(ff._stage_batch())
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_packed_varint_fields_parse():
    """Real onnx files pack repeated int64 fields (proto3 default); the
    reader must accept the packed encoding even though the writer emits
    unpacked."""
    out = bytearray()
    # TensorProto.dims (field 1) packed: [128, 64]
    payload = bytearray()
    for v in (128, 64):
        b = bytearray()
        mo._w_varint(b, v)
        payload.extend(b)
    mo._w_len(out, 1, bytes(payload))
    mo._w_int(out, 2, mo.DT_FLOAT)
    t = mo._dec_tensor(bytes(out))
    assert t.dims == [128, 64]
