"""Minimal ONNX codec (flexflow_tpu/onnx/minionnx.py): wire-format
round-trip, helper constructors, and offline end-to-end import + training
through ONNXModel (reference flow: examples/python/onnx/* without the onnx
package installed)."""

import numpy as np
import pytest

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.onnx import ONNXModel
from flexflow_tpu.onnx import minionnx as mo


def _mlp_model(batch=16, in_dim=64, hidden=128, classes=10):
    rs = np.random.RandomState(0)
    w1 = mo.from_array(rs.randn(hidden, in_dim).astype(np.float32), "w1")
    w2 = mo.from_array(rs.randn(classes, hidden).astype(np.float32), "w2")
    nodes = [
        mo.make_node("Gemm", ["input", "w1"], ["h"], name="fc1"),
        mo.make_node("Relu", ["h"], ["hr"]),
        mo.make_node("Gemm", ["hr", "w2"], ["logits"], name="fc2"),
    ]
    g = mo.make_graph(
        nodes, "mlp",
        [mo.make_tensor_value_info("input", mo.DT_FLOAT, [batch, in_dim])],
        [mo.make_tensor_value_info("logits", mo.DT_FLOAT, [batch, classes])],
        initializer=[w1, w2])
    return mo.make_model(g)


def test_wire_round_trip(tmp_path):
    m = _mlp_model()
    path = str(tmp_path / "m.onnx")
    mo.save(m, path)
    m2 = mo.load(path)
    assert [n.op_type for n in m2.graph.node] == ["Gemm", "Relu", "Gemm"]
    assert m2.graph.node[0].input == ["input", "w1"]
    assert m2.graph.node[0].name == "fc1"
    assert m2.graph.input[0].name == "input"
    assert m2.graph.input[0].type.shape_dims == [16, 64]
    assert m2.graph.initializer[0].dims == [128, 64]
    np.testing.assert_array_equal(mo.to_array(m2.graph.initializer[0]),
                                  mo.to_array(m.graph.initializer[0]))


def test_attribute_round_trip(tmp_path):
    n = mo.make_node("Conv", ["x", "k"], ["y"], name="c",
                     kernel_shape=[3, 3], strides=[2, 2],
                     pads=[1, 1, 1, 1], alpha=0.5, mode="same")
    g = mo.make_graph([n], "g",
                      [mo.make_tensor_value_info("x", mo.DT_FLOAT, [1])],
                      [mo.make_tensor_value_info("y", mo.DT_FLOAT, [1])])
    path = str(tmp_path / "a.onnx")
    mo.save(mo.make_model(g), path)
    node = mo.load(path).graph.node[0]
    attrs = {a.name: a for a in node.attribute}
    assert attrs["kernel_shape"].ints == [3, 3]
    assert attrs["strides"].type == mo.INTS
    assert attrs["alpha"].f == pytest.approx(0.5)
    assert attrs["mode"].s == b"same"


def test_offline_import_and_train(tmp_path):
    """ONNXModel loads a minionnx-serialized file (no onnx package needed)
    and the imported graph trains."""
    path = str(tmp_path / "mlp.onnx")
    mo.save(_mlp_model(), path)

    cfg = FFConfig(batch_size=16, mesh_shape={"data": 2})
    ff = FFModel(cfg)
    x = ff.create_tensor([16, 64], name="input")
    out = ONNXModel(path).apply(ff, {"input": x})
    assert out.dims == (16, 10)
    ff.compile(SGDOptimizer(lr=0.05),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=out)
    rs = np.random.RandomState(0)
    SingleDataLoader(ff, x, rs.randn(32, 64).astype(np.float32))
    SingleDataLoader(ff, ff.label_tensor,
                     rs.randint(0, 10, (32, 1)).astype(np.int32))
    losses = []
    for _ in range(4):
        loss, _ = ff._run_train_step(ff._stage_batch())
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_packed_varint_fields_parse():
    """Real onnx files pack repeated int64 fields (proto3 default); the
    reader must accept the packed encoding even though the writer emits
    unpacked."""
    out = bytearray()
    # TensorProto.dims (field 1) packed: [128, 64]
    payload = bytearray()
    for v in (128, 64):
        b = bytearray()
        mo._w_varint(b, v)
        payload.extend(b)
    mo._w_len(out, 1, bytes(payload))
    mo._w_int(out, 2, mo.DT_FLOAT)
    t = mo._dec_tensor(bytes(out))
    assert t.dims == [128, 64]


# ---- keras_exp: GENUINE tf.keras bytes (VERDICT r4 #6) ----------------------


@pytest.mark.slow  # 17 s real-TF-bytes variant; codec covered by the other tests
def test_keras_exp_real_tf_keras_bytes_through_minionnx():
    """The keras_exp loop on REAL tf.keras state: a live Keras model's
    layers + weights are exported to ONNX protobuf bytes, those exact
    bytes are parsed back by minionnx, replayed through ONNXModelKeras,
    and the resulting FFModel's forward pass must equal tf.keras's own
    prediction — proving the bytes carry the real keras weights (the
    round-3 gap: only hand-built minionnx graphs fed this path)."""
    keras = pytest.importorskip("keras")
    from keras import layers

    from flexflow_tpu.keras_exp.models import Model

    inp = keras.Input((48,), name="kx")
    t = layers.Dense(24, activation="relu")(inp)
    t = layers.Dense(24, activation="tanh")(t)
    out = layers.Dense(6)(t)
    km = keras.Model(inp, out)

    cfg = FFConfig(batch_size=8, mesh_shape={"data": 2}, seed=0)
    m = Model(inputs=inp, outputs=out, ffconfig=cfg)
    # the interface is the serialized wire bytes
    assert isinstance(m.onnx_bytes, bytes) and len(m.onnx_bytes) > 4000
    reparsed = mo.parse(m.onnx_bytes)
    assert [n.op_type for n in reparsed.graph.node] == \
        [n.op_type for n in m.onnx_model.graph.node]
    assert reparsed.producer_name == "flexflow_tpu.keras_exp"

    import keras.optimizers as kopt

    m.compile(optimizer=kopt.Adam(learning_rate=0.01),
              loss="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    rs = np.random.RandomState(3)
    xb = rs.randn(8, 48).astype(np.float32)
    np.testing.assert_allclose(m.predict(xb),
                               km.predict(xb, verbose=0),
                               rtol=1e-4, atol=1e-5)

    # and it trains: the learnable labels must get a lower loss after
    # fit (a broken optimizer mapping or weight load would stall here)
    x = rs.randn(32, 48).astype(np.float32)
    y = (x[:, :6].argmax(1)).astype(np.int32)
    ff = m.ffmodel  # probe loss on the first batch before/after fit
    from flexflow_tpu.runtime.dataloader import attach_training_data

    attach_training_data(ff, m._input_fftensors, [x], y, m._loss)
    batch = {dl.name: dl.data[:8] for dl in ff._dataloaders}
    loss0, _, _ = ff.evaluate(batch)
    m.fit(x, y, epochs=5)
    loss1, _, _ = ff.evaluate(batch)
    assert float(loss1) < float(loss0), (float(loss0), float(loss1))


def test_keras_exp_nested_submodels_and_concat_export():
    """Sub-model inlining (reference func_cifar10_cnn_nested /
    func_mnist_mlp_concat): nested keras Models and Concatenate export,
    replay, and train; graph input order follows model.inputs order."""
    keras = pytest.importorskip("keras")
    from keras import layers

    from flexflow_tpu.keras_exp.models import Model

    def block(tag):
        it = keras.Input((20,))
        t = layers.Dense(10, activation="relu", name=f"d{tag}")(it)
        return keras.Model(it, t, name=f"blk{tag}")

    i1 = keras.Input((20,), name="inA")
    i2 = keras.Input((20,), name="inB")
    t1, t2 = block(1)(i1), block(2)(i2)
    cat = layers.Concatenate(axis=1)([t1, t2])
    out = layers.Dense(4)(cat)

    cfg = FFConfig(batch_size=8, mesh_shape={"data": 2}, seed=1)
    m = Model(inputs={1: i1, 2: i2}, outputs=out, ffconfig=cfg)
    g = m.onnx_model.graph
    assert [vi.name for vi in g.input] == ["inA", "inB"]
    # inlined sub-model weights carry the scoped names
    names = {t.name for t in g.initializer}
    assert "blk1/d1/kernel:0" in names and "blk2/d2/kernel:0" in names

    import keras.optimizers as kopt

    m.compile(optimizer=kopt.SGD(learning_rate=0.05),
              loss="sparse_categorical_crossentropy", metrics=["accuracy"])
    km = keras.Model([i1, i2], out)
    rs = np.random.RandomState(5)
    xa = rs.randn(8, 20).astype(np.float32)
    xb = rs.randn(8, 20).astype(np.float32)
    np.testing.assert_allclose(m.predict([xa, xb]),
                               km.predict([xa, xb], verbose=0),
                               rtol=1e-4, atol=1e-5)
    x1 = rs.randn(16, 20).astype(np.float32)
    x2 = rs.randn(16, 20).astype(np.float32)
    y = rs.randint(0, 4, 16).astype(np.int32)
    m.fit([x1, x2], y, epochs=2)


def test_keras_exp_conv_channels_first_export_and_train():
    """Conv2D path: HWIO keras kernels land as OIHW ONNX initializers and
    the FF conv forward matches tf.keras once weights ride the bytes."""
    keras = pytest.importorskip("keras")
    from keras import layers

    from flexflow_tpu.keras_exp.models import Model

    cf = dict(data_format="channels_first")
    inp = keras.Input((3, 12, 12), name="img")
    t = layers.Conv2D(8, (3, 3), activation="relu", **cf)(inp)
    t = layers.MaxPooling2D((2, 2), strides=(2, 2), **cf)(t)
    t = layers.Flatten(**cf)(t)
    out = layers.Dense(5)(t)

    cfg = FFConfig(batch_size=4, mesh_shape={"data": 2}, seed=2)
    m = Model(inputs=inp, outputs=out, ffconfig=cfg)
    conv_w = next(t for t in m.onnx_model.graph.initializer
                  if t.name.endswith("kernel:0") and len(t.dims) == 4)
    assert conv_w.dims[0] == 8 and conv_w.dims[1] == 3  # OIHW

    import keras.optimizers as kopt

    m.compile(optimizer=kopt.SGD(learning_rate=0.05),
              loss="sparse_categorical_crossentropy", metrics=["accuracy"])
    rs = np.random.RandomState(7)
    x = rs.randn(16, 3, 12, 12).astype(np.float32)
    y = rs.randint(0, 5, 16).astype(np.int32)
    # forward parity BEFORE training mutates the FF weights
    km = keras.Model(inp, out)
    xb = x[:4]
    try:
        ref = km.predict(xb, verbose=0)
        tf_ok = True
    except Exception:
        tf_ok = False  # TF CPU cannot execute channels_first conv
    if tf_ok:
        np.testing.assert_allclose(m.predict(xb), ref, rtol=1e-3,
                                   atol=1e-4)
    m.fit(x, y, epochs=2)
