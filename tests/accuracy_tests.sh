#!/bin/bash
# Accuracy-gate sweep (analog of the reference's tests/accuracy_tests.sh:
# examples run with VerifyMetrics/EpochVerifyMetrics callbacks that raise if
# the accuracy target is not reached). Uses real datasets when the Keras
# cache is present, else the deterministic synthetic stand-ins (which are
# learnable by construction, so the gates stay meaningful).
#
# Usage: tests/accuracy_tests.sh [N_DEVICES]
set -e
set -x

NDEV="${1:-8}"
ROOT="$(cd "$(dirname "$0")/.." && pwd)"
export FLEXFLOW_FORCE_CPU_DEVICES="$NDEV"
export EPOCHS="${EPOCHS:-4}"
export FF_ACCURACY_GATE=1
cd "$ROOT"

python examples/keras/mnist_mlp.py
python examples/keras/mnist_cnn.py
python examples/keras/cifar10_cnn.py

echo "accuracy_tests: ALL PASSED"
