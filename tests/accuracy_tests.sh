#!/bin/bash
# Accuracy-gate sweep (analog of the reference's tests/accuracy_tests.sh:
# examples run with VerifyMetrics/EpochVerifyMetrics callbacks that raise if
# the accuracy target is not reached). Uses real datasets when the Keras
# cache is present, else the deterministic synthetic stand-ins (which are
# learnable by construction, so the gates stay meaningful).
#
# Usage: tests/accuracy_tests.sh [N_DEVICES]
#
# Defaults are sized for a small host: XLA's CPU collectives need every
# virtual device's thread to reach an all-reduce rendezvous within a 40 s
# kill timer, so on a 1-core machine a long conv program over many virtual
# devices can starve a participant and abort. 2 devices + a capped dataset
# keep the gates meaningful without tripping that.
set -e
set -x

NDEV="${1:-2}"
ROOT="$(cd "$(dirname "$0")/.." && pwd)"
export FLEXFLOW_FORCE_CPU_DEVICES="$NDEV"
export EPOCHS="${EPOCHS:-4}"
export FF_ACCURACY_GATE=1
export FLEXFLOW_DATASET_LIMIT="${FLEXFLOW_DATASET_LIMIT:-2048}"
cd "$ROOT"

python examples/keras/mnist_mlp.py
python examples/keras/mnist_cnn.py
python examples/keras/cifar10_cnn.py

echo "accuracy_tests: ALL PASSED"
