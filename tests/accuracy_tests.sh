#!/bin/bash
# Accuracy-gate sweep (analog of the reference's tests/accuracy_tests.sh:
# examples run with VerifyMetrics/EpochVerifyMetrics callbacks that raise if
# the accuracy target is not reached).
#
# Data tiers:
#  * REAL data, always: digits_mlp / digits_cnn train the bundled UCI
#    handwritten digits (flexflow_tpu/data/digits.npz) to >=90% — the
#    real-data gate the
#    reference gets from MNIST (accuracy.py:18-24). This zero-egress image
#    ships no MNIST/CIFAR/Reuters files and no network, so the bundled
#    digits set is the only real image data available.
#  * All 5 reference gate models (MNIST_MLP, MNIST_CNN, REUTERS_MLP,
#    CIFAR10_CNN, CIFAR10_ALEXNET) run against the Keras cache when present,
#    else the deterministic synthetic stand-ins (learnable by construction).
#
# Usage: tests/accuracy_tests.sh [N_DEVICES]
#
# Defaults are sized for a small host: XLA's CPU collectives need every
# virtual device's thread to reach an all-reduce rendezvous within a 40 s
# kill timer, so on a 1-core machine a long conv program over many virtual
# devices can starve a participant and abort. 2 devices + a capped dataset
# keep the gates meaningful without tripping that.
set -e
set -x

NDEV="${1:-2}"
ROOT="$(cd "$(dirname "$0")/.." && pwd)"
export FLEXFLOW_FORCE_CPU_DEVICES="$NDEV"
export EPOCHS="${EPOCHS:-4}"
export FF_ACCURACY_GATE=1
export FLEXFLOW_DATASET_LIMIT="${FLEXFLOW_DATASET_LIMIT:-2048}"
cd "$ROOT"

# real-data gates (bundled digits)
python examples/keras/digits_mlp.py
python examples/keras/digits_cnn.py

# the 5 reference gate models (real data when cached, synthetic stand-ins
# otherwise)
python examples/keras/mnist_mlp.py
python examples/keras/mnist_cnn.py
# reuters/alexnet pin their epoch count: they cross the 90% gate at epoch
# 3-4, so a user-supplied fast-sweep EPOCHS<4 would fail them spuriously
EPOCHS=6 python examples/keras/seq_reuters_mlp.py
python examples/keras/cifar10_cnn.py
EPOCHS=6 python examples/keras/func_cifar10_alexnet.py

echo "accuracy_tests: ALL PASSED"
