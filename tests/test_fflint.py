"""fflint (flexflow_tpu/analysis): negative-violation corpus, clean passes,
CLI behavior, compile() integration, and the no-mesh enforcement.

The corpus tests assert BOTH halves of the acceptance contract: every
seeded violation is caught with an op-name + pass-name diagnostic, and
every clean strategy the repo actually ships/searches lints with zero
errors and zero warnings.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from flexflow_tpu.analysis import StrategyLintError, analyze
from flexflow_tpu.analysis.__main__ import main as fflint_main
from flexflow_tpu.analysis.models import build_model
from flexflow_tpu.parallel.pconfig import CONTRACT, STAGE, ParallelConfig
from flexflow_tpu.parallel.strategy import save_strategies_to_file

MESH = {"data": 4, "model": 2}


def _transformer(mesh=None, **args):
    return build_model("transformer", mesh or MESH, args)


def _codes(report):
    return set(report.codes())


def _find(report, code):
    vs = report.by_code(code)
    assert vs, f"expected a {code!r} violation; got {report.codes()}"
    return vs


# ---------------------------------------------------------------- negative

def test_bad_axis_name():
    ff = _transformer()
    rep = analyze(ff, strategies={
        "ffn1_0": ParallelConfig(axis_map={"modle": 0})}, mesh_shape=MESH)
    v = _find(rep, "axis-unknown")[0]
    assert v.severity == "error" and v.pass_name == "legality"
    assert v.op_name == "ffn1_0" and "modle" in v.message


def test_dim_out_of_range():
    ff = _transformer()
    rep = analyze(ff, strategies={
        "ffn1_0": ParallelConfig(axis_map={"model": 7})}, mesh_shape=MESH)
    v = _find(rep, "dim-out-of-range")[0]
    assert v.op_name == "ffn1_0" and v.severity == "error"


def test_non_divisible_dim():
    # batch 30 does not divide by the 4-way data axis -> XLA would pad
    ff = _transformer(batch=30)
    rep = analyze(ff, strategies={
        "ffn1_0": ParallelConfig(axis_map={"data": 0})}, mesh_shape=MESH)
    vs = _find(rep, "shard-indivisible")
    v = next(v for v in vs if v.op_name == "ffn1_0")
    assert v.severity == "warning" and "pad" in v.message


def test_contract_on_non_contraction_op():
    ff = _transformer()
    rep = analyze(ff, strategies={
        "ln1_0": ParallelConfig(axis_map={"model": CONTRACT})},
        mesh_shape=MESH)
    v = _find(rep, "contract-on-non-contraction")[0]
    assert v.op_name == "ln1_0" and v.pass_name == "legality"


def test_stage_on_non_pipelinable_op():
    ff = _transformer()
    rep = analyze(ff, strategies={
        "ffn1_0": ParallelConfig(axis_map={"model": STAGE})}, mesh_shape=MESH)
    v = _find(rep, "stage-on-non-pipelinable")[0]
    assert v.op_name == "ffn1_0" and v.severity == "error"


def test_stage_indivisible():
    # 5 layers cannot split over a 2-way stage axis
    ff = build_model("pipeline", MESH, {"layers": 5})
    rep = analyze(ff, strategies={
        "stack": ParallelConfig(axis_map={"model": STAGE})}, mesh_shape=MESH)
    assert any(v.op_name == "stack" for v in _find(rep, "stage-indivisible"))


def test_degree_mismatch():
    # degrees recorded for a differently-sized mesh
    ff = _transformer()
    rep = analyze(ff, strategies={
        "ffn1_0": ParallelConfig(dims=(2, 1, 1),
                                 axis_map={"data": 0})}, mesh_shape=MESH)
    v = _find(rep, "degree-mismatch")[0]
    assert v.op_name == "ffn1_0" and "(4, 1, 1)" in v.message


def test_device_id_range_and_block_too_small():
    ff = _transformer()
    rep = analyze(ff, strategies={
        "ffn1_0": ParallelConfig(dims=(4, 1, 1), axis_map={"data": 0},
                                 device_ids=(0, 1, 97, 98))},
        mesh_shape=MESH)
    assert _find(rep, "device-id-range")[0].op_name == "ffn1_0"
    rep = analyze(ff, strategies={
        "ffn1_0": ParallelConfig(dims=(4, 1, 1), axis_map={"data": 0},
                                 device_ids=(0, 1))}, mesh_shape=MESH)
    assert _find(rep, "device-block-too-small")[0].op_name == "ffn1_0"


def test_overlapping_device_blocks():
    mesh = {"data": 12}
    ff = build_model("mlp", mesh, {"batch": 48})
    rep = analyze(ff, strategies={
        "fc_0": ParallelConfig(dims=(1, 1), axis_map={},
                               device_ids=tuple(range(4, 8))),
        "fc_1": ParallelConfig(dims=(1, 1), axis_map={},
                               device_ids=tuple(range(6, 12)))},
        mesh_shape=mesh)
    v = _find(rep, "device-block-overlap")[0]
    assert v.severity == "error" and "fc_0" in v.message


def test_device_count_mismatch_is_named():
    ff = _transformer()
    rep = analyze(ff, strategies={
        "ffn1_0": ParallelConfig(dims=(4, 1, 1), axis_map={"data": 0},
                                 device_ids=(0, 1, 2, 3, 4))},
        mesh_shape=MESH)
    v = _find(rep, "device-count-mismatch")[0]
    assert v.severity == "warning" and "range(4)" in v.message


def test_truncated_axismap_record(tmp_path):
    p = tmp_path / "trunc.ff"
    p.write_text("1\nfoo\n0\n2\n1\t4\n4\n0\t1\t2\t3\n@axismap 2 data 0 model\n")
    rep = analyze(None, strategy_file=str(p))
    v = _find(rep, "schema-axismap-truncated")[0]
    assert v.op_name == "foo" and v.pass_name == "schema"


def test_truncated_file(tmp_path):
    p = tmp_path / "trunc2.ff"
    p.write_text("3\nfoo\n0\n2\n1\t4\n4\n0\t1\t2\t3\n")
    rep = analyze(None, strategy_file=str(p))
    assert "schema-truncated" in _codes(rep)


def test_unknown_op_warns():
    ff = _transformer()
    rep = analyze(ff, strategies={
        "no_such_op": ParallelConfig(axis_map={"data": 0})}, mesh_shape=MESH)
    assert _find(rep, "unknown-op")[0].severity == "warning"


# ---------------------------------------------------------------- perf

def test_reshard_ranked_by_bytes():
    ff = _transformer()
    strategies = {
        "ffn1_0": ParallelConfig.from_axis_map(
            3, MESH, {"data": 0, "model": 2}),
        "ffn2_0": ParallelConfig.from_axis_map(3, MESH, {"data": 0}),
    }
    rep = analyze(ff, strategies=strategies, mesh_shape=MESH)
    notes = [v for v in rep.notes() if v.code == "reshard"]
    assert notes, "expected reshard notes for the TP->DP boundary"
    byte_counts = [v.est_bytes for v in notes]
    assert byte_counts == sorted(byte_counts, reverse=True)
    assert all(v.est_seconds is not None and v.est_seconds > 0
               for v in notes)


def test_dcn_collective_contract_across_hosts():
    """ISSUE 10: CONTRACT assigned to a DCN-spanning axis psums
    activations across hosts every layer — the perf pass must name it
    (and the same strategy on a flat single-host mesh must NOT fire)."""
    ff = _transformer()
    ff.config.dcn_mesh_shape = {"data": 2}
    strategies = {"ffn1_0": ParallelConfig.from_axis_map(
        3, MESH, {"data": CONTRACT})}
    rep = analyze(ff, strategies=strategies, mesh_shape=MESH)
    vs = _find(rep, "dcn-collective")
    assert any(v.severity == "warning" and v.op_name == "ffn1_0"
               and "EVERY layer" in v.message for v in vs), vs
    # flat mesh: same strategy, no DCN axes declared -> no dcn finding
    ff2 = _transformer()
    rep2 = analyze(ff2, strategies=strategies, mesh_shape=MESH)
    assert "dcn-collective" not in _codes(rep2)


def test_dcn_collective_reshard_across_hosts():
    """A per-layer reshard whose implied collective crosses a DCN axis is
    escalated to a warning and renamed dcn-collective, however small."""
    ff = _transformer()
    ff.config.dcn_mesh_shape = {"data": 2}
    strategies = {
        "ffn1_0": ParallelConfig.from_axis_map(3, MESH, {"data": 2}),
        "ffn2_0": ParallelConfig.from_axis_map(3, MESH, {"data": 0}),
    }
    rep = analyze(ff, strategies=strategies, mesh_shape=MESH)
    vs = _find(rep, "dcn-collective")
    assert any("SPAN HOSTS" in v.message and v.severity == "warning"
               for v in vs), vs


def test_hierarchical_candidate_lints_clean_of_dcn_findings():
    """The search's own hierarchical candidate (data on DCN, TP inside
    ICI) must produce ZERO dcn-collective findings — the lint and the
    candidate generator agree on what a good two-tier strategy is."""
    from flexflow_tpu.search.driver import hierarchical_strategy

    ff = _transformer()
    ff.config.dcn_mesh_shape = {"data": 2}
    hier = hierarchical_strategy(ff, MESH, {"data": 2})
    strategies = {
        name: ParallelConfig.from_axis_map(
            ff.get_op_by_name(name).outputs[0].num_dims, MESH, am)
        for name, am in hier.items()}
    rep = analyze(ff, strategies=strategies, mesh_shape=MESH)
    assert "dcn-collective" not in _codes(rep), rep.codes()


def test_replicated_weight_no_fsdp(monkeypatch):
    import flexflow_tpu.analysis.perf as perf

    monkeypatch.setattr(perf, "WEIGHT_WARN_BYTES", 1024.0)
    ff = _transformer()
    rep = analyze(ff, mesh_shape=MESH)  # default DP: weights replicated
    vs = _find(rep, "replicated-weight-no-fsdp")
    assert all(v.severity == "warning" for v in vs)
    assert any("fsdp_axis" in v.message for v in vs)


def test_hbm_footprint_and_over_capacity():
    from flexflow_tpu.search.machine import MachineModel

    ff = _transformer()
    rep = analyze(ff, mesh_shape=MESH)
    assert "hbm-footprint" in _codes(rep)  # always an info note
    tiny = MachineModel(hbm_bytes=1024.0)  # 1 KiB chip: everything overflows
    rep = analyze(ff, mesh_shape=MESH, machine=tiny)
    assert _find(rep, "hbm-over-capacity")[0].severity == "warning"


def test_pipeline_bubble_and_imbalance():
    ff = build_model("pipeline", {"data": 2, "pipe": 2},
                     {"layers": 4, "num_microbatches": 1})
    rep = analyze(ff, strategies={
        "stack": ParallelConfig(axis_map={"data": 0, "pipe": STAGE})},
        mesh_shape={"data": 2, "pipe": 2})
    v = _find(rep, "pipeline-bubble")[0]
    assert v.severity == "warning"  # m < n
    # 3 layers over 2 stages: FLOP imbalance
    ff = build_model("pipeline", {"data": 2, "pipe": 3}, {"layers": 3})
    rep = analyze(ff, strategies={
        "stack": ParallelConfig(axis_map={"data": 0, "pipe": STAGE})},
        mesh_shape={"data": 2, "pipe": 2})
    assert "pipeline-flop-imbalance" in _codes(rep)


# ---------------------------------------------------------------- clean

def _clean_strategies(ff):
    """The strategy families scripts/validate_strategies.py exercises:
    data parallelism plus search winners (from_axis_map over the mesh)."""
    from flexflow_tpu.search.driver import (data_parallel_strategy,
                                            optimize_strategies)

    dp = {name: ParallelConfig.from_axis_map(
        next(o for o in ff.ops if o.name == name).outputs[0].num_dims,
        MESH, am)
        for name, am in data_parallel_strategy(ff, MESH).items()}
    searched = optimize_strategies(ff, budget=40, mesh_shape=MESH, seed=1)
    return {"dp": dp, "searched": searched}


def test_clean_strategies_zero_violations():
    ff = _transformer(batch=32, seq=16, hidden=32, layers=1)
    for label, strat in _clean_strategies(ff).items():
        rep = analyze(ff, strategies=strat, mesh_shape=MESH)
        assert not rep.errors(), (label, [str(v) for v in rep.errors()])
        assert not rep.warnings(), (label, [str(v) for v in rep.warnings()])


def test_shipped_example_strategies_are_clean():
    root = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "examples", "strategies")
    manifest = os.path.join(root, "MANIFEST")
    assert os.path.exists(manifest), "examples/strategies/MANIFEST missing"
    ran = 0
    with open(manifest) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            fname, model, mesh, margs = line.split("|")
            rc = fflint_main([model.strip(),
                              os.path.join(root, fname.strip()),
                              "--mesh", mesh.strip(), "--strict", "--quiet"]
                             + sum((["--model-arg", a] for a in
                                    margs.strip().split() if a), []))
            assert rc == 0, f"{fname.strip()} failed fflint --strict"
            ran += 1
    assert ran >= 2


def test_pass_subset_still_analyzes_the_named_file(tmp_path):
    """A --passes subset must not silently fall back to the model's own
    (empty) table: the named file is what gets analyzed."""
    ff = _transformer()
    p = tmp_path / "bad.ff"
    save_strategies_to_file(str(p), {
        "ffn1_0": ParallelConfig(axis_map={"bogus": 0}, dims=(1, 1, 1),
                                 device_ids=(0,))})
    rep = analyze(ff, mesh_shape=MESH, strategy_file=str(p),
                  passes=("legality",))
    assert "axis-unknown" in _codes(rep)
    # structurally unreadable + schema deselected: still errors, never
    # a false clean bill
    q = tmp_path / "trunc.ff"
    q.write_text("2\nfoo\n0\n")
    rep = analyze(ff, mesh_shape=MESH, strategy_file=str(q),
                  passes=("legality",))
    assert rep.errors()


def test_resolution_errors_survive_pass_deselection():
    """A perf-only run must not report clean on a strategy whose axis_map
    could not even resolve (the bad entries are stripped before perf)."""
    ff = _transformer()
    rep = analyze(ff, strategies={
        "ffn1_0": ParallelConfig(axis_map={"modle": 0})}, mesh_shape=MESH,
        passes=("perf",))
    assert "axis-unknown" in _codes(rep)
    assert rep.errors()


def test_stage_multiple_ids_not_flagged_as_mismatch():
    """csim/from_axis_map's canonical stage-inclusive device list is not a
    device-count-mismatch (save accepts it; legality must agree)."""
    mesh = {"data": 2, "pipe": 2}
    ff = build_model("pipeline", mesh, {"layers": 4})
    pc = ParallelConfig.from_axis_map(3, mesh, {"data": 0, "pipe": STAGE})
    rep = analyze(ff, strategies={"stack": pc}, mesh_shape=mesh)
    assert "device-count-mismatch" not in _codes(rep)
    assert not rep.errors()


# ---------------------------------------------------------------- CLI

def test_cli_exit_codes(tmp_path):
    good = tmp_path / "good.ff"
    ff = _transformer(batch=32, seq=16, hidden=32, layers=1)
    strategies = {
        op.name: ParallelConfig.from_axis_map(
            op.outputs[0].num_dims, MESH, {"data": 0})
        for op in ff.ops if op.name.startswith(("ffn", "head"))}
    save_strategies_to_file(str(good), strategies)
    rc = fflint_main(["transformer", str(good), "--mesh", "data=4,model=2",
                      "--strict", "--quiet", "--model-arg", "batch=32",
                      "--model-arg", "seq=16", "--model-arg", "hidden=32",
                      "--model-arg", "layers=1"])
    assert rc == 0
    bad = tmp_path / "bad.ff"
    bad.write_text("1\nfoo\n0\n2\n1\t4\n4\n0\t1\t2\t3\n@axismap 1 data\n")
    rc = fflint_main(["none", str(bad)])
    assert rc == 1
    rc = fflint_main(["no-such-model", str(good)])
    assert rc == 2


def test_cli_json_output(tmp_path, capsys):
    import json

    p = tmp_path / "bad.ff"
    p.write_text("1\nfoo\n9\n2\n1\t4\n4\n0\t1\t2\t3\n")
    rc = fflint_main(["none", str(p), "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0  # device-type 9 is a warning, not an error
    assert any(v["code"] == "schema-device-type"
               for v in out["violations"])


# ------------------------------------------------------- static-ness proof

def test_analysis_never_builds_a_mesh(monkeypatch, tmp_path):
    """The acceptance contract: every pass is pure static analysis. Stub
    mesh construction to raise — the full analyzer (legality + perf +
    schema, library AND CLI) must still run."""
    import jax.sharding

    import flexflow_tpu.parallel.mesh as mesh_mod

    def _boom(*a, **k):
        raise AssertionError("fflint must not build a jax.sharding.Mesh")

    monkeypatch.setattr(jax.sharding.Mesh, "__init__", _boom)
    monkeypatch.setattr(mesh_mod, "make_mesh", _boom)

    ff = _transformer()
    p = tmp_path / "s.ff"
    save_strategies_to_file(str(p), {
        "ffn1_0": ParallelConfig.from_axis_map(
            3, MESH, {"data": 0, "model": CONTRACT}),
        "stackless": ParallelConfig(axis_map={"bogus": 1})})
    rep = analyze(ff, mesh_shape=MESH, strategy_file=str(p))
    assert rep.violations  # it actually analyzed (unknown-op etc.)
    assert "internal-error" not in _codes(rep)
    rc = fflint_main(["transformer", str(p), "--mesh", "data=4,model=2"])
    assert rc in (0, 1)  # ran to completion without touching Mesh


# ------------------------------------------------------- compile() modes

def test_compile_strict_rejects_bad_strategy():
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models.transformer import build_encoder_classifier

    cfg = FFConfig(batch_size=8, mesh_shape={"data": 2},
                   strategy_lint="strict")
    cfg.strategies["ffn1_0"] = ParallelConfig(axis_map={"bogus_axis": 0})
    ff = FFModel(cfg)
    _, out = build_encoder_classifier(ff, 8, 16, 32, 1, 4)
    with pytest.raises(StrategyLintError) as ei:
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                   final_tensor=out)
    assert "axis-unknown" in str(ei.value) and "ffn1_0" in str(ei.value)


def test_compile_warn_mode_proceeds():
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models.transformer import build_encoder_classifier

    cfg = FFConfig(batch_size=8, mesh_shape={"data": 2},
                   strategy_lint="warn")
    # warning-severity finding: device list inconsistent with num_parts
    cfg.strategies["ffn1_0"] = ParallelConfig(
        dims=(2, 1, 1), axis_map={"data": 0}, device_ids=(0, 1, 2))
    ff = FFModel(cfg)
    _, out = build_encoder_classifier(ff, 8, 16, 32, 1, 4)
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               final_tensor=out)
    assert ff.executor is not None
