"""Native threaded dataloader (runtime/csrc/dataloader.cc via ctypes).

Covers the reference's dataloader semantics (flexflow_dataloader.cc: full
dataset resident, next_batch slices samples; one shared index map across the
input and label streams) plus the shuffle/prefetch extensions.
"""

import numpy as np
import pytest

from flexflow_tpu.runtime.native_loader import (NativeBatchLoader, load_lib)

pytestmark = pytest.mark.skipif(load_lib() is None,
                                reason="native dataloader unavailable")


def _make(n=64, feat=5, batch=8, **kw):
    x = np.arange(n * feat, dtype=np.float32).reshape(n, feat)
    y = np.arange(n, dtype=np.int32).reshape(n, 1)
    return x, y, NativeBatchLoader([("input", x), ("label", y)], batch, **kw)


def test_sequential_matches_slicing():
    x, y, dl = _make()
    assert dl.num_batches == 8
    for b in range(dl.num_batches):
        got = dl.next_batch()
        np.testing.assert_array_equal(got["input"], x[b * 8:(b + 1) * 8])
        np.testing.assert_array_equal(got["label"], y[b * 8:(b + 1) * 8])
    assert dl.next_batch() is None  # end of epoch
    dl.close()


def test_shuffle_consistent_across_arrays():
    x, y, dl = _make(shuffle=True, seed=7)
    seen = []
    for _ in range(dl.num_batches):
        got = dl.next_batch()
        # row i of input must be the sample y[i] says it is
        for i in range(got["label"].shape[0]):
            idx = int(got["label"][i, 0])
            np.testing.assert_array_equal(got["input"][i], x[idx])
            seen.append(idx)
    assert sorted(seen) == list(range(64))     # a permutation, every sample once
    assert seen != list(range(64))             # actually shuffled
    dl.close()


def test_reset_reshuffles():
    _, _, dl = _make(shuffle=True, seed=3)
    first = [int(v) for b in iter(dl.next_batch, None) for v in b["label"][:, 0]]
    dl.reset()
    second = [int(v) for b in iter(dl.next_batch, None) for v in b["label"][:, 0]]
    assert sorted(first) == sorted(second) == list(range(64))
    assert first != second
    dl.close()


def test_mid_epoch_reset():
    x, _, dl = _make()
    dl.next_batch()
    dl.next_batch()
    dl.reset()
    got = dl.next_batch()
    np.testing.assert_array_equal(got["input"], x[:8])  # back to batch 0
    dl.close()


def test_nondivisible_batch_drops_tail():
    x = np.arange(10, dtype=np.float32).reshape(10, 1)
    dl = NativeBatchLoader([("input", x)], 4)
    assert dl.num_batches == 2
    batches = list(iter(dl.next_batch, None))
    assert len(batches) == 2
    dl.close()


def test_many_threads_in_order():
    x, _, dl = _make(n=256, batch=4, num_threads=4, prefetch_slots=6)
    for b in range(dl.num_batches):
        got = dl.next_batch()
        np.testing.assert_array_equal(got["input"], x[b * 4:(b + 1) * 4])
    dl.close()


def test_fit_uses_native_loader():
    from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                              SGDOptimizer, SingleDataLoader)

    rs = np.random.RandomState(0)
    n, feat = 64, 8
    cfg = FFConfig(batch_size=16, epochs=2, mesh_shape={"data": 1},
                   native_dataloader=True, dataloader_shuffle=True)
    ff = FFModel(cfg)
    x = ff.create_tensor([16, feat], name="input")
    t = ff.dense(x, 4)
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=t)
    SingleDataLoader(ff, x, rs.randn(n, feat).astype(np.float32))
    SingleDataLoader(ff, ff.label_tensor,
                     rs.randint(0, 4, (n, 1)).astype(np.int32))
    perf = ff.fit(verbose=False)
    assert perf.train_all == n  # the last epoch saw every sample
