"""Elastic recovery tests (runtime/elastic.py, integrity manifests in
runtime/checkpoint.py, serving drain in runtime/serving.py).

The recovery contract: a training run checkpointed on N devices resumes on
N-1 (or a differently-shaped mesh) with params bitwise-identical after the
re-shard and the global batch preserved via grad-accum adjustment; a
corrupted latest checkpoint fails manifest verification and resume falls
back to the newest intact step; ``on_topology_change="abort"`` raises
cleanly; a serving engine drains (stop admitting, finish in-flight slots)
instead of hard-stopping. Everything runs deterministically on CPU —
topology changes via explicit meshes or the ``shrink(<k>)@resume`` fault,
corruption via ``corrupt_ckpt@save:<n>``.
"""

import json
import os

import numpy as np
import pytest

from flexflow_tpu import (ActiMode, CheckpointCorruptError, FFConfig,
                          FFModel, LossType, MetricsType, SGDOptimizer,
                          SingleDataLoader, TopologyChangedError,
                          TrainSupervisor)
from flexflow_tpu.runtime import faultinject, resilience
from flexflow_tpu.runtime.checkpoint import (MANIFEST_NAME, auto_resume,
                                             intact_steps,
                                             latest_intact_step,
                                             latest_step,
                                             restore_checkpoint,
                                             verify_checkpoint, verify_step)
from flexflow_tpu.runtime.elastic import mesh_candidates
from flexflow_tpu.runtime.faultinject import FaultPlan


@pytest.fixture(autouse=True)
def _fresh_fault_state(monkeypatch):
    monkeypatch.delenv("FF_FAULT", raising=False)
    faultinject.reset()
    resilience.reset_counters()
    yield
    faultinject.reset()


def _build(ckpt_dir, *, mesh=None, policy="resume_resharded", accum=1,
           min_devices=1, verify=True, seed=3, n=64):
    cfg = FFConfig(batch_size=16, epochs=1, seed=seed,
                   checkpoint_dir=str(ckpt_dir),
                   mesh_shape=dict(mesh) if mesh else None,
                   on_topology_change=policy,
                   grad_accum_steps=accum,
                   elastic_min_devices=min_devices,
                   verify_checkpoints=verify)
    ff = FFModel(cfg)
    x = ff.create_tensor([16, 8], name="x")
    t = ff.dense(x, 16, ActiMode.AC_MODE_RELU, name="fc1")
    ff.dense(t, 4, name="out")
    ff.compile(SGDOptimizer(lr=0.1),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY])
    rs = np.random.RandomState(7)
    SingleDataLoader(ff, x, rs.randn(n, 8).astype(np.float32))
    SingleDataLoader(ff, ff.label_tensor,
                     rs.randint(0, 4, (n, 1)).astype(np.int32))
    return ff


# -------------------------------------------- FF_FAULT grammar additions


def test_fault_parser_value_grammar():
    """kind(value)@site:index — the parameterized-kind extension carrying
    e.g. the shrink target device count."""
    p = FaultPlan.parse("shrink(2)@resume:1,corrupt_ckpt@save:3")
    assert ("shrink", "resume", 1) in p.events
    assert ("corrupt_ckpt", "save", 3) in p.events
    assert p.fire("shrink", "resume")
    assert p.last_value == 2
    assert not p.fire("shrink", "resume"), "occurrence 2 not scheduled"
    # un-parameterized kinds report no value
    assert not p.fire("corrupt_ckpt", "save")   # occurrence 1
    assert not p.fire("corrupt_ckpt", "save")   # occurrence 2
    assert p.fire("corrupt_ckpt", "save")       # occurrence 3 fires
    assert p.last_value is None
    # values ride ranges too (each expanded event carries the value)
    r = FaultPlan.parse("shrink(4)@resume:2-3")
    assert not r.fire("shrink", "resume")
    assert r.fire("shrink", "resume") and r.last_value == 4
    assert r.fire("shrink", "resume") and r.last_value == 4
    # step-site events surface the value through at_step as well
    s = FaultPlan.parse("throttle(9)@step:5")
    assert s.at_step("throttle", 5) and s.last_value == 9
    for bad in ("shrink(x)@resume:1", "shrink(2@resume:1",
                "shrink)2(@resume:1", "(2)@resume:1"):
        with pytest.raises(ValueError):
            FaultPlan.parse(bad)


def test_fault_parser_replica_kinds():
    """The fleet-router drill grammar (ISSUE 8): crash@replica:<r> and
    hang@replica:<r> are IDENTITY-indexed (index names the replica, 0
    allowed), peeked with pending() and consumed one-shot with
    at_site(); slow(<ms>)@serve:<n> rides the existing occurrence
    counting with the stall milliseconds as its value."""
    p = FaultPlan.parse(
        "crash@replica:0,hang@replica:1,slow(250)@serve:3")
    assert ("crash", "replica", 0) in p.events
    assert ("hang", "replica", 1) in p.events
    # pending() peeks without consuming — the router polls it every busy
    # tick until its own tick counter reaches the trigger
    assert p.pending("crash", "replica", 0) == (True, None)
    assert p.pending("crash", "replica", 0) == (True, None)
    assert p.pending("crash", "replica", 1) == (False, None)
    assert p.at_site("crash", "replica", 0) and p.last_value is None
    assert not p.at_site("crash", "replica", 0), "one-shot"
    assert p.pending("crash", "replica", 0) == (False, None), \
        "a consumed event is no longer pending"
    assert p.at_site("hang", "replica", 1)
    # slow is occurrence-counted on the serve site: fires on the 3rd
    # admission with the stall parameter
    assert not p.fire("slow", "serve")
    assert not p.fire("slow", "serve")
    assert p.fire("slow", "serve") and p.last_value == 250
    # a crash trigger tick rides the value grammar: crash(5)@replica:2
    # = crash replica 2 at its 5th busy tick
    t = FaultPlan.parse("crash(5)@replica:2")
    assert t.pending("crash", "replica", 2) == (True, 5)
    assert t.at_site("crash", "replica", 2) and t.last_value == 5
    # at_step is the step-site specialization of at_site
    s = FaultPlan.parse("nan_loss@step:7")
    assert s.pending("nan_loss", "step", 7) == (True, None)
    assert s.at_step("nan_loss", 7) and not s.at_step("nan_loss", 7)


def test_fault_parser_tier_migration_kinds():
    """The tiered-prefix-cache drill grammar (ISSUE 12):
    d2h_fail@migrate:<n> fails the n-th HBM->host demotion (the page
    dies exactly as it would without a host tier) and
    h2d_fail@promote:<n> fails the n-th host->HBM promotion (cold-
    prefill fallback). Both ride the occurrence-counted site machinery —
    migrate and promote counters are independent."""
    p = FaultPlan.parse("d2h_fail@migrate:2,h2d_fail@promote:1")
    assert ("d2h_fail", "migrate", 2) in p.events
    assert ("h2d_fail", "promote", 1) in p.events
    # occurrence counting per (kind, site): demotion 1 passes, 2 fails
    assert not p.fire("d2h_fail", "migrate")
    assert p.fire("d2h_fail", "migrate")
    assert not p.fire("d2h_fail", "migrate"), "occurrence 3 clean"
    # the promote counter never advanced while demotions fired
    assert p.fire("h2d_fail", "promote")
    assert not p.fire("h2d_fail", "promote")
    # ranges expand (a flaky-host drill fails a run of migrations)
    r = FaultPlan.parse("d2h_fail@migrate:1-3")
    assert [r.fire("d2h_fail", "migrate") for _ in range(4)] \
        == [True, True, True, False]
    # an unrelated plan never accumulates migrate/promote counters
    q = FaultPlan.parse("nan_loss@step:1")
    for _ in range(5):
        assert not q.fire("d2h_fail", "migrate")
    assert ("d2h_fail", "migrate") not in q._counts


def test_fault_parser_deploy_kinds():
    """The rolling-deploy drill grammar (ISSUE 17):
    corrupt_ckpt@publish:<n> tears the n-th artifact landing in the
    watch path, swap_fail@deploy:<n> dies after the n-th weight install,
    slow(<ms>)@canary:<n> stalls the n-th CANARY admission by <ms>. All
    three ride the occurrence-counted site machinery; publish, deploy
    and canary counters are independent of each other AND of the save /
    serve sites the same kinds fire on elsewhere."""
    p = FaultPlan.parse(
        "corrupt_ckpt@publish:2,swap_fail@deploy:1,slow(400)@canary:3")
    assert ("corrupt_ckpt", "publish", 2) in p.events
    assert ("swap_fail", "deploy", 1) in p.events
    assert ("slow", "canary", 3) in p.events
    # publish counter: artifact 1 lands clean, artifact 2 is torn
    assert not p.fire("corrupt_ckpt", "publish")
    assert p.fire("corrupt_ckpt", "publish")
    # the save-site counter for the SAME kind never advanced
    assert ("corrupt_ckpt", "save") not in p._counts
    # deploy counter: the very first swap dies
    assert p.fire("swap_fail", "deploy") and p.last_value is None
    assert not p.fire("swap_fail", "deploy"), "occurrence 2 clean"
    # canary counter carries the stall milliseconds, independent of the
    # serve-site slow counter
    assert not p.fire("slow", "canary")
    assert not p.fire("slow", "canary")
    assert p.fire("slow", "canary") and p.last_value == 400
    assert ("slow", "serve") not in p._counts
    # a sustained-breach drill stalls a RANGE of canary admissions
    r = FaultPlan.parse("slow(300)@canary:1-4")
    assert [r.fire("slow", "canary") for _ in range(5)] \
        == [True, True, True, True, False]
    assert r.last_value == 300


def test_fault_parser_elastic_fleet_kinds():
    """The elastic-fleet drill grammar (ISSUE 20):
    preempt(<deadline_ms>)@replica:<r> is identity-indexed — the value
    is an evacuation DEADLINE in ms, not a tick, and the router consumes
    it once at replica r's first busy tick; slow_evac(<ms>)@evacuate:<n>
    is occurrence-counted and stalls the n-th prefix-slab export, the
    lever that forces a deadline miss deterministically."""
    p = FaultPlan.parse("preempt(800)@replica:0,slow_evac(250)@evacuate:2")
    assert ("preempt", "replica", 0) in p.events
    assert ("slow_evac", "evacuate", 2) in p.events
    # identity-indexed: peek without consuming, any number of times
    assert p.pending("preempt", "replica", 0) == (True, 800)
    assert p.pending("preempt", "replica", 0) == (True, 800)
    # the wrong replica never matches
    assert p.pending("preempt", "replica", 1) == (False, None)
    # one-shot consume carries the deadline; a second consume is inert
    assert p.at_site("preempt", "replica", 0) and p.last_value == 800
    assert not p.at_site("preempt", "replica", 0)
    assert p.pending("preempt", "replica", 0) == (False, None)
    # evacuate counter: export 1 clean, export 2 stalled by 250 ms
    assert not p.fire("slow_evac", "evacuate")
    assert p.fire("slow_evac", "evacuate") and p.last_value == 250
    assert not p.fire("slow_evac", "evacuate")
    # a deadline-less preempt is legal (router falls back to the
    # FFConfig.preempt_deadline_s default)
    q = FaultPlan.parse("preempt@replica:1")
    assert q.pending("preempt", "replica", 1) == (True, None)
    # an unrelated plan never accumulates evacuate counters
    r = FaultPlan.parse("nan_loss@step:1")
    for _ in range(3):
        assert not r.fire("slow_evac", "evacuate")
    assert ("slow_evac", "evacuate") not in r._counts


# ------------------------------------------------- integrity manifest


def test_manifest_roundtrip_and_corruption_detected(tmp_path):
    ff = _build(tmp_path, mesh={"data": 2})
    sup = TrainSupervisor(ff, str(tmp_path))
    sup.step()
    sup.save(reason="test")
    step_dir = tmp_path / "step_1"
    # manifest written inside the published dir, covering every other file
    mpath = step_dir / MANIFEST_NAME
    assert mpath.exists()
    manifest = json.loads(mpath.read_text())
    assert manifest["algo"] == "sha256"
    assert "ff_meta.json" in manifest["files"]
    assert "strategy.txt" in manifest["files"]
    on_disk = sorted(
        os.path.relpath(os.path.join(r, f), step_dir).replace(os.sep, "/")
        for r, _d, fs in os.walk(step_dir) for f in fs)
    assert sorted(manifest["files"]) == [p for p in on_disk
                                         if p != MANIFEST_NAME]
    verify_checkpoint(str(tmp_path), 1)  # round-trip: intact passes
    assert verify_step(str(tmp_path), 1)
    # flip one payload byte -> verification must name the file
    payload = max(((os.path.getsize(os.path.join(step_dir, p)), p)
                   for p in manifest["files"]))[1]
    full = os.path.join(step_dir, payload)
    blob = bytearray(open(full, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(full, "wb").write(bytes(blob))
    with pytest.raises(CheckpointCorruptError, match="hash mismatch"):
        verify_checkpoint(str(tmp_path), 1)
    assert not verify_step(str(tmp_path), 1)
    assert intact_steps(str(tmp_path)) == []


def test_corrupted_latest_falls_back_to_previous_intact(tmp_path,
                                                        monkeypatch):
    monkeypatch.setenv("FF_FAULT", "corrupt_ckpt@save:2")
    faultinject.reset()
    ff = _build(tmp_path, mesh={"data": 2})
    sup = TrainSupervisor(ff, str(tmp_path))
    sup.step(); sup.save(reason="test")   # step 1, intact
    sup.step(); sup.save(reason="test")   # step 2, payload corrupted
    assert latest_step(str(tmp_path)) == 2
    assert latest_intact_step(str(tmp_path)) == 1
    monkeypatch.delenv("FF_FAULT")
    faultinject.reset()
    # a fresh job must resume from step 1 (warning logged), not crash on 2
    ff2 = _build(tmp_path, mesh={"data": 2})
    sup2 = TrainSupervisor(ff2, str(tmp_path))
    assert sup2.resume() == 1
    assert resilience.COUNTERS["corrupt_checkpoints_skipped"] >= 1
    assert sup2.run(4) == "completed"
    # auto_resume takes the same fallback; with EVERY step corrupt it must
    # raise loudly instead of silently training from scratch
    ff3 = _build(tmp_path / "all_bad", mesh={"data": 2})
    monkeypatch.setenv("FF_FAULT", "corrupt_ckpt@save:1")
    faultinject.reset()
    sup3 = TrainSupervisor(ff3, str(tmp_path / "all_bad"))
    sup3.step(); sup3.save(reason="test")
    monkeypatch.delenv("FF_FAULT")
    faultinject.reset()
    ff4 = _build(tmp_path / "all_bad", mesh={"data": 2})
    with pytest.raises(CheckpointCorruptError):
        auto_resume(ff4, str(tmp_path / "all_bad"))


def test_raced_damage_mid_restore_reclassified_and_falls_back(tmp_path):
    ff = _build(tmp_path, mesh={"data": 2})
    sup = TrainSupervisor(ff, str(tmp_path))
    sup.step(); sup.save(reason="test")   # step 1, intact
    sup.step(); sup.save(reason="test")   # step 2, damaged BELOW
    # damage landing AFTER the intact scan's hash pass: delete the whole
    # orbax payload (meta/strategy stay readable, so the scan still
    # yields the step) — the orbax read fails with a generic error, not
    # a CheckpointCorruptError
    step_dir = tmp_path / "step_2"
    manifest = json.loads((step_dir / MANIFEST_NAME).read_text())
    for rel in manifest["files"]:
        if rel not in ("ff_meta.json", "strategy.txt"):
            os.remove(step_dir / rel)
    ff2 = _build(tmp_path, mesh={"data": 2})
    # restore_checkpoint re-checks the manifest on failure and
    # reclassifies: the resume chains catch CheckpointCorruptError, so a
    # raw orbax/OSError here would crash instead of falling back
    with pytest.raises(CheckpointCorruptError, match="missing"):
        restore_checkpoint(ff2, str(tmp_path), step=2, verify=False)
    # end to end: a scan that TRUSTS step 2 (verified earlier in the
    # process, damaged since — exactly the race) falls back to step 1
    ff2._elastic_verified_step = 2
    assert auto_resume(ff2, str(tmp_path)) == 1


def test_coordinator_probe_retries_until_late_bind():
    import socket
    import threading
    import time

    from flexflow_tpu.launcher import _coordinator_reachable

    # bound but not listening: connects are REFUSED instantly. On a
    # preempted pool the coordinator often binds seconds after the
    # workers start — a single instantaneous probe would spuriously fall
    # back single-process (split-brain on the shared checkpoint dir), so
    # the probe must retry until its window closes
    s = socket.socket()
    try:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        assert not _coordinator_reachable(f"127.0.0.1:{port}", 0.7)
        t = threading.Thread(target=lambda: (time.sleep(0.8), s.listen(8)))
        t.start()
        try:
            assert _coordinator_reachable(f"127.0.0.1:{port}", 5.0)
        finally:
            t.join()
    finally:
        s.close()


def test_latest_step_skips_unreadable_meta(tmp_path):
    ff = _build(tmp_path, mesh={"data": 2})
    sup = TrainSupervisor(ff, str(tmp_path))
    sup.step(); sup.save(reason="test")
    # a damaged newer dir (unparseable per-step meta) used to raise
    # mid-resume from load_meta; now it is skipped
    bad = tmp_path / "step_99"
    bad.mkdir()
    (bad / "ff_meta.json").write_text("{not json")
    assert latest_step(str(tmp_path)) == 1
    assert latest_intact_step(str(tmp_path)) == 1
    ff2 = _build(tmp_path, mesh={"data": 2})
    assert auto_resume(ff2, str(tmp_path)) == 1


def test_retention_never_deletes_last_intact(tmp_path, monkeypatch):
    monkeypatch.setenv("FF_FAULT",
                       "corrupt_ckpt@save:2,corrupt_ckpt@save:3")
    faultinject.reset()
    ff = _build(tmp_path, mesh={"data": 2})
    sup = TrainSupervisor(ff, str(tmp_path), keep=1)
    sup.step(); sup.save(reason="test")   # step 1 intact
    sup.step(); sup.save(reason="test")   # step 2 corrupt
    sup.step(); sup.save(reason="test")   # step 3 corrupt
    dirs = sorted(d for d in os.listdir(tmp_path) if d.startswith("step_"))
    # keep=1 would normally leave only step_3 — but every survivor is
    # corrupt, so the newest INTACT step must outlive the window
    assert "step_1" in dirs, dirs
    assert latest_intact_step(str(tmp_path)) == 1
    monkeypatch.delenv("FF_FAULT")
    faultinject.reset()
    ff2 = _build(tmp_path, mesh={"data": 2})
    sup2 = TrainSupervisor(ff2, str(tmp_path))
    assert sup2.resume() == 1


# ------------------------------------------------ topology-change resume


def test_resume_resharded_onto_fewer_devices(tmp_path):
    # checkpoint on a 4-device data mesh
    ff_a = _build(tmp_path, mesh={"data": 4})
    sup_a = TrainSupervisor(ff_a, str(tmp_path))
    assert sup_a.run(4) == "completed"
    w_a = np.asarray(ff_a.get_weights("fc1"))
    opt_a = {k: np.asarray(v)
             for k, v in ff_a.opt_state.get("fc1", {}).items()} \
        if ff_a.opt_state else {}
    # "one host died": the restart only has 2 devices
    ff_b = _build(tmp_path, mesh={"data": 2})
    dec = ff_b._elastic
    assert dec is not None and dec.changed
    assert dec.saved_mesh == {"data": 4} and dec.new_mesh == {"data": 2}
    # global batch preserved: data degree halved -> grad accum doubled,
    # so rows/device/microstep is unchanged
    assert ff_b.config.grad_accum_steps == 2
    assert ff_b.config.batch_size == ff_a.config.batch_size
    sup_b = TrainSupervisor(ff_b, str(tmp_path))
    assert sup_b.resume() == 4
    assert resilience.COUNTERS["elastic_resumes"] >= 1
    # restored params/opt-state bitwise-match the saved ones after the
    # re-shard round-trip
    np.testing.assert_array_equal(np.asarray(ff_b.get_weights("fc1")), w_a)
    for k, v in opt_a.items():
        np.testing.assert_array_equal(np.asarray(ff_b.opt_state["fc1"][k]),
                                      v)
    # and training keeps making progress on the shrunk pool
    assert sup_b.run(16) == "completed"
    losses = sup_b.losses
    assert len(losses) == 12 and np.isfinite(losses).all()
    assert np.mean(losses[-4:]) < np.mean(losses[:4]), \
        f"loss did not keep decreasing post-resume: {losses}"


def test_resume_on_differently_shaped_mesh_is_bitwise(tmp_path):
    ff_a = _build(tmp_path, mesh={"data": 4})
    sup_a = TrainSupervisor(ff_a, str(tmp_path))
    assert sup_a.run(3) == "completed"
    w_a = np.asarray(ff_a.get_weights("fc1"))
    # same device count, different axes: dp=4 -> dp=2 x tp=2
    ff_b = _build(tmp_path, mesh={"data": 2, "model": 2})
    assert ff_b._elastic is not None and ff_b._elastic.changed
    # data degree 4 -> 2 still halves, so accum doubles to hold the
    # per-device microbatch
    assert ff_b.config.grad_accum_steps == 2
    sup_b = TrainSupervisor(ff_b, str(tmp_path))
    assert sup_b.resume() == 3
    np.testing.assert_array_equal(np.asarray(ff_b.get_weights("fc1")), w_a)


def test_same_topology_restart_adopts_saved_accum(tmp_path):
    # an earlier elastic resume doubled accum and later checkpoints
    # recorded it; a SECOND restart on the unchanged mesh must adopt the
    # saved factor, not silently reset to the config default and halve
    # the effective batch the trajectory was trained at
    ff_a = _build(tmp_path, mesh={"data": 2}, accum=2)
    assert TrainSupervisor(ff_a, str(tmp_path)).run(2) == "completed"
    ff_b = _build(tmp_path, mesh={"data": 2})  # config accum defaults to 1
    dec = ff_b._elastic
    assert dec is not None and not dec.changed
    assert ff_b.config.grad_accum_steps == 2
    assert dec.grad_accum == 2
    sup_b = TrainSupervisor(ff_b, str(tmp_path))
    assert sup_b.resume() == 2


def test_shrink_fault_refits_mesh_with_ranked_candidates(tmp_path,
                                                         monkeypatch):
    ff_a = _build(tmp_path, mesh={"data": 4})
    TrainSupervisor(ff_a, str(tmp_path)).run(2)
    # the restart still ASKS for 4 devices, but the shrink fault presents
    # only 2 — the policy must refit over the saved axes instead of dying
    # in make_mesh ("mesh needs 4 devices, have 2")
    monkeypatch.setenv("FF_FAULT", "shrink(2)@resume:1")
    faultinject.reset()
    ff_b = _build(tmp_path, mesh={"data": 4})
    dec = ff_b._elastic
    assert dec is not None and dec.changed
    assert dec.new_mesh == {"data": 2}
    assert dec.ranked_candidates >= 1
    assert ff_b.config.mesh_shape == {"data": 2}
    assert ff_b.config.grad_accum_steps == 2
    sup_b = TrainSupervisor(ff_b, str(tmp_path))
    assert sup_b.resume() == 2


def test_on_topology_change_abort_raises_cleanly(tmp_path):
    ff_a = _build(tmp_path, mesh={"data": 4})
    TrainSupervisor(ff_a, str(tmp_path)).run(2)
    with pytest.raises(TopologyChangedError, match="abort"):
        _build(tmp_path, mesh={"data": 2}, policy="abort")
    # same topology never trips the policy
    ff_same = _build(tmp_path, mesh={"data": 4}, policy="abort")
    sup = TrainSupervisor(ff_same, str(tmp_path))
    assert sup.resume() == 2


def test_elastic_min_devices_refuses_tiny_pools(tmp_path):
    ff_a = _build(tmp_path, mesh={"data": 4})
    TrainSupervisor(ff_a, str(tmp_path)).run(2)
    with pytest.raises(TopologyChangedError, match="elastic_min_devices"):
        _build(tmp_path, mesh={"data": 2}, min_devices=4)


def test_mesh_candidates_enumeration():
    cands = mesh_candidates({"data": 4, "model": 2}, 4)
    assert {"data": 2, "model": 2} in cands
    assert {"data": 4, "model": 1} in cands
    assert {"data": 1, "model": 4} in cands
    assert all(c["data"] * c["model"] == 4 for c in cands)
    # axis names (and order) come from the saved mesh
    assert all(list(c) == ["data", "model"] for c in cands)
    assert mesh_candidates({"data": 8}, 3) == [{"data": 3}]


def test_config_validation():
    with pytest.raises(ValueError, match="on_topology_change"):
        FFConfig(mesh_shape={"data": 1}, on_topology_change="panic")
    with pytest.raises(ValueError, match="elastic_min_devices"):
        FFConfig(mesh_shape={"data": 1}, elastic_min_devices=0)
    cfg = FFConfig.parse_args(["--on-topology-change", "abort",
                               "--no-verify-checkpoints",
                               "--elastic-min-devices", "2"])
    assert cfg.on_topology_change == "abort"
    assert cfg.verify_checkpoints is False
    assert cfg.elastic_min_devices == 2


# --------------------------------------------------- serving drain/health


@pytest.fixture(scope="module")
def serve_ff():
    from flexflow_tpu.models.llama import llama_lm

    cfg = FFConfig(batch_size=2, mesh_shape={"data": 1})
    model = FFModel(cfg)
    _, logits = llama_lm(model, 2, seq_len=16, hidden=32, layers=1,
                         heads=2, kv_heads=1, vocab_size=61)
    model.compile(final_tensor=logits)
    return model


def test_drain_finishes_inflight_and_refuses_new(serve_ff):
    rs = np.random.RandomState(0)
    prompts = [rs.randint(1, 61, (n,)).astype(np.int32)
               for n in (4, 7, 3, 6, 5, 8)]
    eng = serve_ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                       max_seq_len=64, decode_chunk=4)
    assert eng.health()["status"] == "idle"
    # max_new (12) spans several decode chunks so slots are genuinely
    # mid-flight when the queue empties
    reqs = [eng.submit(p, max_new_tokens=12) for p in prompts]
    while eng.health()["queued"]:
        eng.step()
    assert eng.health()["status"] == "busy"
    snap = eng.drain()
    assert snap["drained"] and snap["queued"] == 0
    assert snap["completed"] == len(prompts) and snap["failed"] == 0
    assert [r.state for r in reqs] == ["done"] * len(prompts)
    health = eng.health()
    assert health["status"] == "drained"
    assert not health["admitting"] and health["active_slots"] == 0
    assert health["completed"] == len(prompts)
    assert health["recompiles"] == eng.recompile_count
    with pytest.raises(RuntimeError, match="draining"):
        eng.submit(prompts[0], max_new_tokens=1)
    # idempotent: a second drain is a no-op returning the same snapshot
    snap2 = eng.drain()
    assert snap2["completed"] == snap["completed"]
    # drained slots returned every page: free, or cached refcount-0 in
    # the prefix trie (flushing the cache reclaims the remainder)
    assert snap2["free_pages"] + snap2["kv_pages_cached"] \
        == snap2["kv_pages"] - 1
    assert snap2["prefix_refs_live"] == 0
    eng.flush_prefix_cache()
    assert eng.stats()["free_pages"] == snap2["kv_pages"] - 1


def test_drain_leaves_queued_requests_for_resubmission(serve_ff):
    rs = np.random.RandomState(1)
    prompts = [rs.randint(1, 61, (5,)).astype(np.int32) for _ in range(4)]
    eng = serve_ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                       max_seq_len=64, decode_chunk=4)
    reqs = [eng.submit(p, max_new_tokens=8) for p in prompts]
    eng.step()  # admits 2 of 4; the other 2 stay queued
    snap = eng.drain()
    assert snap["queued"] == 2
    assert sum(r.state == "done" for r in reqs) == 2
    assert sum(r.state == "queued" for r in reqs) == 2
    # the frozen queue belongs to the replacement engine: it neither
    # holds health() in "draining" forever nor keeps step() reporting
    # work (a while-step loop — run(None) — must terminate, not spin)
    assert eng.health()["status"] == "drained"
    assert eng.step() is False
    assert eng.run(None) == reqs[2:]  # returns the queued 2, no livelock
    assert sum(r.state == "queued" for r in reqs) == 2
