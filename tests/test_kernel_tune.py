"""Block-size autotuner (search/kernel_tune.py) + the measure.py timing
primitive and cost-signature bugfix it rides on.

Anchors:
  * table round-trip: a tuned winner persists to disk and a fresh
    lookup serves it; flash_attention's block resolution consults it;
  * cold fallback: no table -> the static _pick_block heuristic,
    byte-identical to the pre-tuner behavior, and a MISS is counted;
  * keying: dtype is part of the shape signature and the device key
    carries the jax version — a bf16-measured entry can never serve an
    fp32 query, and a version bump invalidates by key mismatch;
  * an illegal persisted entry (blocks not dividing the shape) falls
    back loudly instead of crashing the trace;
  * measure._op_signature records input dtypes + the environment
    signature (the ISSUE-7 cost-table bugfix).
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu.ops.pallas_kernels import _pick_block, _resolve_blocks
from flexflow_tpu.search import kernel_tune, measure


@pytest.fixture
def table(tmp_path, monkeypatch):
    """A fresh table file path wired through the env knob, with the
    in-process cache and counters reset around the test."""
    path = str(tmp_path / "kernel_tune.json")
    monkeypatch.setenv("FF_KERNEL_TUNE_TABLE", path)
    kernel_tune.reload(path)
    kernel_tune.reset_stats()
    yield path
    kernel_tune.reload(path)
    kernel_tune.reset_stats()


def test_cold_fallback_is_static_heuristic(table):
    assert kernel_tune.lookup_blocks(
        "flash_fwd", seq_q=640, seq_k=640, head_dim=64,
        dtype=jnp.float32, batch=1, heads=1, causal=True) is None
    assert kernel_tune.stats()["misses"] == 1
    bq, bk = _resolve_blocks("flash_fwd", 640, 640, 64, jnp.float32,
                             None, None)
    assert (bq, bk) == (_pick_block(640, 512), _pick_block(640, 512)) \
        == (128, 128)


def test_record_roundtrip_and_resolve(table):
    sig = kernel_tune.shape_sig(seq_q=640, seq_k=640, head_dim=64,
                                dtype=jnp.float32, batch=1, heads=1,
                                causal=True)
    kernel_tune.record("flash_fwd", sig, (320, 640), 1.5e-3,
                       candidates={(128, 128): 2e-3, (320, 640): 1.5e-3})
    # in-memory cache refreshed by record(); a cold re-read also works
    kernel_tune.reload(table)
    assert kernel_tune.lookup_blocks(
        "flash_fwd", seq_q=640, seq_k=640, head_dim=64,
        dtype=jnp.float32, batch=1, heads=1, causal=True) == (320, 640)
    # the kernel entry point consults the table (tuned != static 128)
    assert _resolve_blocks("flash_fwd", 640, 640, 64, jnp.float32,
                           None, None) == (320, 640)
    # batch/heads/causal are IN the key: any mismatch misses to static
    assert _resolve_blocks("flash_fwd", 640, 640, 64, jnp.float32,
                           None, None, batch=32, heads=1,
                           causal=True) == (128, 128)
    assert _resolve_blocks("flash_fwd", 640, 640, 64, jnp.float32,
                           None, None, causal=False) == (128, 128)
    # explicit blocks BYPASS the table (the tuner's own sweep must)
    assert _resolve_blocks("flash_fwd", 640, 640, 64, jnp.float32,
                           640, 128) == (640, 128)
    # and the file on disk is a valid atomic-written JSON table
    with open(table) as f:
        data = json.load(f)
    assert data["version"] == 1
    (key, entry), = data["entries"].items()
    assert key.startswith("flash_fwd|") and sig in key
    assert kernel_tune.device_key() in key
    assert entry["blocks"] == [320, 640]


def test_dtype_and_version_are_in_the_key(table):
    f32sig = kernel_tune.shape_sig(seq_q=256, seq_k=256, head_dim=64,
                                   dtype=jnp.float32, batch=1, heads=4,
                                   causal=True)
    kernel_tune.record("flash_fwd", f32sig, (128, 128), 1e-3)
    # same shape, bf16 query: MISS (a bf16 tile has half the bytes — an
    # f32-measured winner is noise for it)
    assert kernel_tune.lookup_blocks(
        "flash_fwd", seq_q=256, seq_k=256, head_dim=64,
        dtype=jnp.bfloat16, batch=1, heads=4, causal=True) is None
    assert kernel_tune.lookup_blocks(
        "flash_fwd", seq_q=256, seq_k=256, head_dim=64,
        dtype=jnp.float32, batch=1, heads=4, causal=True) == (128, 128)
    # a jax-version bump (simulated: rewrite the key with another
    # version) invalidates by mismatch, never serves stale blocks
    with open(table) as f:
        data = json.load(f)
    (key, entry), = data["entries"].items()
    stale = key.replace(f"jax-{jax.__version__}", "jax-0.0.1")
    assert stale != key
    with open(table, "w") as f:
        json.dump({"version": 1, "entries": {stale: entry}}, f)
    kernel_tune.reload(table)
    assert kernel_tune.lookup_blocks(
        "flash_fwd", seq_q=256, seq_k=256, head_dim=64,
        dtype=jnp.float32, batch=1, heads=4, causal=True) is None


def test_table_written_after_first_lookup_is_picked_up(table):
    """A long-lived consumer must see a table another process writes
    AFTER its first (empty) lookup — the cache is keyed by the file's
    (mtime, size), not cached-forever (the documented out-of-process
    re-tune flow)."""
    assert kernel_tune.lookup_blocks(
        "flash_fwd", seq_q=640, seq_k=640, head_dim=64,
        dtype=jnp.float32, batch=1, heads=1, causal=True) is None
    sig = kernel_tune.shape_sig(seq_q=640, seq_k=640, head_dim=64,
                                dtype=jnp.float32, batch=1, heads=1,
                                causal=True)
    key = f"flash_fwd|{kernel_tune.device_key()}|{sig}"
    # out-of-band write (no record(), no reload — a foreign process)
    with open(table, "w") as f:
        json.dump({"version": 1,
                   "entries": {key: {"blocks": [320, 640],
                                     "seconds": 1e-3}}}, f)
    os.utime(table, (0, 0))  # force a stat change even on coarse clocks
    assert kernel_tune.lookup_blocks(
        "flash_fwd", seq_q=640, seq_k=640, head_dim=64,
        dtype=jnp.float32, batch=1, heads=1, causal=True) == (320, 640)


def test_illegal_entry_falls_back(table):
    sig = kernel_tune.shape_sig(seq_q=256, seq_k=256, head_dim=64,
                                dtype=jnp.float32, batch=1, heads=1,
                                causal=True)
    kernel_tune.record("flash_fwd", sig, (96, 96), 1e-3)  # !| 256
    assert _resolve_blocks("flash_fwd", 256, 256, 64, jnp.float32,
                           None, None) == (256, 256)     # static pick
    st = kernel_tune.stats()
    # an illegal entry is a MISS (the static pick governed this trace),
    # never a hit — the hit counter means "a tuned pick actually ran"
    assert st["illegal"] == 1 and st["hits"] == 0 and st["misses"] == 1


def test_tune_then_consume_end_to_end(table):
    """The real sweep on a small shape: times every legal candidate
    through the dispatch-floor harness, persists the winner, and the
    flash forward then runs with the tuned blocks (interpret mode on
    CPU — the same code path a TPU re-tune takes)."""
    rec = kernel_tune.tune_flash_attention(
        128, head_dim=8, heads=2, batch=1,
        candidates=((64, 64), (128, 128), (512, 512)), iters=1)
    assert rec["kernel"] == "flash_fwd"
    assert tuple(rec["blocks"]) in ((64, 64), (128, 128))  # 512 illegal
    assert set(rec["candidates"]) == {"64x64", "128x128"}
    assert rec["static"] == [128, 128]
    got = kernel_tune.lookup_blocks("flash_fwd", seq_q=128, seq_k=128,
                                    head_dim=8, dtype=jnp.float32,
                                    batch=1, heads=2, causal=True)
    assert got == tuple(rec["blocks"])
    assert _resolve_blocks("flash_fwd", 128, 128, 8, jnp.float32,
                           None, None, batch=1, heads=2,
                           causal=True) == got
    # the consuming kernel actually executes with the tuned table live
    from flexflow_tpu.ops.pallas_kernels import flash_attention_fwd_pallas

    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(1, 128, 2, 8), jnp.float32)
    out, _ = flash_attention_fwd_pallas(q, q, q, True, 0.35,
                                        need_lse=False)
    assert out.shape == (2, 128, 8)  # (B*H, S, D) internal layout
    assert bool(jnp.isfinite(out).all())


def test_time_scalar_program_primitive():
    fn = jax.jit(lambda x: jnp.sum(x * 2.0))
    dt = measure.time_scalar_program(fn, jnp.ones((64, 64)), warmup=1,
                                     iters=2)
    assert dt > 0.0


def test_measure_signature_records_dtype_and_env():
    """ISSUE-7 bugfix: the cost-table signature must carry input dtypes
    and the (backend, device kind, jax version) environment — shapes
    alone let a bf16 timing serve an fp32 query across version bumps."""
    from flexflow_tpu import ActiMode, FFConfig, FFModel

    cfg = FFConfig(batch_size=2, mesh_shape={"data": 1})
    ff = FFModel(cfg)
    x = ff.create_tensor([2, 8], name="x")
    ff.dense(x, 4, ActiMode.AC_MODE_RELU, name="d0")
    op = next(o for o in ff.ops if o.name == "d0")
    sig = measure._op_signature(op, [(2, 8)], [(8, 4)])
    env = measure._env_signature()
    assert env == (jax.default_backend(),) + env[1:]
    assert env[2] == jax.__version__
    assert sig[-1] == env, "environment signature missing from cost key"
    dtypes = sig[-2]
    assert len(dtypes) == len(op.inputs) and "FLOAT" in dtypes[0].upper()
