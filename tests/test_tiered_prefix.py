"""Tiered (HBM -> host) prefix cache: the tier state machine alone.

Sub-second pure-host unit tests (ISSUE 12 satellite) for
runtime/serving.py RadixPrefixCache's host tier — no engine, no device,
no compiles: the D2H/H2D callables are injected fakes, so demote/promote
ordering under the ordered publisher, the cross-tier refcount rules, the
host-tier LRU and the abandoned-migration generation check are all
pinned as host logic. The engine-integrated paths (real pools, real
token identity) live in tests/test_disagg.py.
"""

import threading
import time

import numpy as np
import pytest

from flexflow_tpu.runtime import faultinject
from flexflow_tpu.runtime.serving import RadixPrefixCache

PS = 2  # page size: tiny, so prompts stay readable


class FakeIO:
    """Injected batched D2H/H2D (the engine's real callables move page
    LISTS — one gather per demotion sweep, one padded writer dispatch
    per promotion batch): page payloads are dicts; ``gate(page)`` makes
    that page's publish wait on an Event (the deterministic in-flight
    window every ordering/abandonment test needs)."""

    def __init__(self):
        self.gates = {}
        self.published = []     # resolve completion order (page ids)
        self.written = []       # (page, payload) h2d writes
        self.h2d_boom = False

    def gate(self, page):
        ev = self.gates[page] = threading.Event()
        return ev

    def d2h(self, pages):
        def resolve():
            out = []
            for page in pages:
                ev = self.gates.get(page)
                if ev is not None:
                    assert ev.wait(30), \
                        f"gate for page {page} never opened"
                self.published.append(page)
                out.append({"page": page, "bytes": f"kv-{page}"})
            return out

        return resolve

    def h2d(self, pages, payloads):
        if self.h2d_boom:
            raise RuntimeError("injected H2D loss")
        self.written.extend(
            (int(p), pl) for p, pl in zip(pages, payloads))


def make_cache(host_pages=8):
    io = FakeIO()
    return RadixPrefixCache(PS, host_pages=host_pages,
                            d2h=io.d2h, h2d=io.h2d), io


def publish(cache, prompt, pages):
    """Publish ``pages`` for ``prompt`` the way a finished prefill does:
    inserted at ref 1, released to the warm ref-0 cached state."""
    prompt = np.asarray(prompt, np.int32)
    matched = cache.match(prompt, len(prompt) // PS)
    created = cache.insert(prompt, matched,
                           len(matched), list(pages))
    cache.release(created)
    return matched + created


def prompt_of(*chunks):
    return np.asarray([t for c in chunks for t in c], np.int32)


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("FF_FAULT", raising=False)
    faultinject.reset()
    yield
    faultinject.reset()


# ---- demote / promote round trip -----------------------------------------


def test_demote_publishes_and_promote_restores_bitwise_payload():
    cache, io = make_cache()
    path = publish(cache, prompt_of((1, 2), (3, 4)), [5, 6])
    freed = cache.evict(2)
    # leaf-first cascade: the deep page reclaims first, pages free
    # immediately (the D2H snapshot already started)
    assert sorted(freed) == [5, 6]
    assert [n.tier for n in path] == ["host", "host"]
    assert cache.pages == 0 and cache.host_used == 2
    assert cache.demotions == 2
    assert cache.wait_migrations(5)
    # promotion hands the SAME payload back through h2d
    assert cache.promote(path[0], 9)
    assert path[0].tier == "hbm" and path[0].page == 9
    assert io.written == [(9, {"page": 5, "bytes": "kv-5"})] \
        or io.written == [(9, {"page": 6, "bytes": "kv-6"})]
    assert cache.promotions == 1
    assert cache.host_used == 1 and cache.pages == 1
    # a re-match walks through the promoted page again
    m = cache.match(prompt_of((1, 2), (3, 4)), 2)
    assert [n.tier for n in m] == ["hbm", "host"]


def test_ordered_publisher_resolves_in_submission_order():
    cache, io = make_cache()
    publish(cache, prompt_of((1, 2)), [3])
    publish(cache, prompt_of((5, 6)), [4])
    g3, g4 = io.gate(3), io.gate(4)
    cache.match(prompt_of((1, 2)), 1)    # page 3 is now the NEWER use
    freed = cache.evict(2)
    assert sorted(freed) == [3, 4]
    # open the gates out of order: the ordered publisher still resolves
    # strictly in submission order (LRU order: 4 demoted first)
    g3.set()
    time.sleep(0.05)
    assert io.published == [], \
        "publish for page 3 must wait behind the earlier submission"
    g4.set()
    assert cache.wait_migrations(5)
    assert io.published == [4, 3]


def test_promote_waits_for_inflight_publish():
    cache, io = make_cache()
    (node,) = publish(cache, prompt_of((1, 2)), [3])
    gate = io.gate(3)
    cache.evict(1)
    assert node.tier == "host" and node.hostdata is None
    got = {}

    def promoter():
        got["ok"] = cache.promote(node, 7)

    t = threading.Thread(target=promoter)
    t.start()
    time.sleep(0.05)
    assert t.is_alive(), "promote must wait for the pending publish"
    gate.set()
    t.join(10)
    assert got["ok"] and node.tier == "hbm" and node.page == 7
    assert io.written[0][0] == 7


# ---- refcount rules across tiers -----------------------------------------


def test_refcount_rules_across_tiers():
    cache, _ = make_cache()
    (node,) = publish(cache, prompt_of((1, 2)), [3])
    # a mounted page never demotes
    cache.acquire([node])
    assert cache.evict(1) == []
    assert node.tier == "hbm"
    cache.release([node])
    # a demoted page cannot be mounted without promotion
    cache.evict(1)
    assert node.tier == "host"
    with pytest.raises(AssertionError, match="promoted before"):
        cache.acquire([node])
    assert cache.live_refs() == 0
    # promoted -> mountable again
    assert cache.promote(node, 9)
    cache.acquire([node])
    assert cache.live_refs() == 1
    cache.release([node])


def test_path_tier_invariant_hbm_then_host():
    """Demotion is deep-first (a node with an HBM child never demotes),
    so every root->node path reads hbm* then host* — the rule that keeps
    a mounted prefix from sitting below a host page."""
    cache, _ = make_cache()
    a, b, c = publish(cache, prompt_of((1, 2), (3, 4), (5, 6)),
                      [3, 4, 5])
    cache.evict(1)
    assert [n.tier for n in (a, b, c)] == ["hbm", "hbm", "host"]
    cache.evict(1)
    assert [n.tier for n in (a, b, c)] == ["hbm", "host", "host"]
    cache.evict(1)
    assert [n.tier for n in (a, b, c)] == ["host", "host", "host"]
    assert cache.wait_migrations(5)
    # promotion is root-first through _promote-style walks: promoting
    # the HEAD restores hbm->host ordering, never host->hbm
    assert cache.promote(a, 9)
    assert [n.tier for n in (a, b, c)] == ["hbm", "host", "host"]


# ---- host-tier LRU --------------------------------------------------------


def test_host_lru_evicts_oldest_for_real():
    cache, _ = make_cache(host_pages=2)
    n1 = publish(cache, prompt_of((1, 2)), [3])[0]
    n2 = publish(cache, prompt_of((5, 6)), [4])[0]
    n3 = publish(cache, prompt_of((7, 8)), [5])[0]
    cache.match(prompt_of((1, 2)), 1)   # n1 is the warmest
    freed = cache.evict(3)
    assert sorted(freed) == [3, 4, 5]
    assert cache.wait_migrations(5)
    # capacity 2: the third demotion killed the host tier's oldest
    assert cache.host_used == 2
    assert cache.host_evictions == 1
    tiers = {id(n): n.tier for n in (n1, n2, n3)}
    assert list(tiers.values()).count("host") == 2
    assert n1.tier == "host", "the warmest page must survive the LRU"
    # the killed prefix is gone from the trie entirely
    dead = n2 if n2.tier != "host" else n3
    assert cache.match(prompt_of(tuple(dead.chunk)), 1) == []


# ---- abandoned migrations (generation check) ------------------------------


def test_abandoned_migration_publish_is_dropped():
    """A node killed while its D2H publish is still in flight must NOT
    be resurrected by the late-completing payload — the generation
    check drops it (the PipelineLoader abandoned-pull rule applied to
    page migration)."""
    cache, io = make_cache()
    (node,) = publish(cache, prompt_of((1, 2)), [3])
    gate = io.gate(3)
    cache.evict(1)
    gen_at_demote = node.gen
    # flush kills the host copy while the publish is pending
    cache.evict(cache.host_pages + 8, pressure=False)
    assert node.tier == "reaped" and node.gen > gen_at_demote
    gate.set()
    assert cache.wait_migrations(5)
    assert node.hostdata is None, "late publish resurrected a dead node"
    assert cache.host_used == 0
    assert cache.match(prompt_of((1, 2)), 1) == []


def test_promote_after_republish_same_tokens_uses_new_generation():
    """Kill a host copy, republish the same chunk with a NEW page, then
    let the OLD publish land: the new node must be untouched (its own
    generation), and promoting it serves the new payload."""
    cache, io = make_cache()
    (old,) = publish(cache, prompt_of((1, 2)), [3])
    gate = io.gate(3)
    cache.evict(1)
    cache.evict(99, pressure=False)         # old copy dies, publish open
    (new,) = publish(cache, prompt_of((1, 2)), [6])
    gate.set()
    assert cache.wait_migrations(5)
    assert new.tier == "hbm" and new.page == 6
    cache.evict(1)
    assert cache.wait_migrations(5)
    assert new.hostdata == {"page": 6, "bytes": "kv-6"}


# ---- failure injection ----------------------------------------------------


def test_d2h_fail_page_dies_as_today(monkeypatch):
    monkeypatch.setenv("FF_FAULT", "d2h_fail@migrate:1")
    faultinject.reset()
    cache, io = make_cache()
    publish(cache, prompt_of((1, 2)), [3])
    publish(cache, prompt_of((5, 6)), [4])
    freed = cache.evict(2)
    # both pages free either way; the failed one's node is GONE (no
    # host copy), the second demotes normally
    assert sorted(freed) == [3, 4]
    assert cache.demote_failures == 1 and cache.demotions == 1
    assert cache.host_used == 1
    alive = [p for p in ((1, 2), (5, 6))
             if cache.match(prompt_of(p), 1)]
    assert len(alive) == 1
    assert cache.wait_migrations(5)


def test_d2h_fail_on_parent_reaps_selected_child_cleanly(monkeypatch):
    """Cascade corner (found by the engine identity tests): the sweep
    selects the leaf, then d2h_fail fires on its PARENT — the kill
    reaps the already-selected child too. The child's page must free
    exactly once and never reach the snapshot (a page -1 gather would
    read junk and double-free)."""
    monkeypatch.setenv("FF_FAULT", "d2h_fail@migrate:2")
    faultinject.reset()
    cache, io = make_cache()
    publish(cache, prompt_of((1, 2), (3, 4)), [5, 6])
    freed = cache.evict(2)
    assert sorted(freed) == [5, 6], "both pages free, each exactly once"
    assert all(p >= 0 for p in freed)
    assert cache.pages == 0 and cache.host_used == 0
    assert cache.demote_failures == 1
    assert cache.match(prompt_of((1, 2)), 1) == []
    assert cache.wait_migrations(5)
    assert io.published == [], "nothing may publish after the kill"


def test_h2d_fail_falls_back_cold(monkeypatch):
    monkeypatch.setenv("FF_FAULT", "h2d_fail@promote:1")
    faultinject.reset()
    cache, io = make_cache()
    (n1,) = publish(cache, prompt_of((1, 2)), [3])
    (n2,) = publish(cache, prompt_of((5, 6)), [4])
    cache.evict(2)
    assert cache.wait_migrations(5)
    assert not cache.promote(n1, 9), "injected h2d_fail must fail"
    assert cache.promote_failures == 1
    assert n1.tier == "reaped", "a failed promotion kills the host copy"
    assert cache.match(prompt_of((1, 2)), 1) == []
    # the next promotion (occurrence 2) succeeds — no sticky state
    assert cache.promote(n2, 9)
    assert n2.tier == "hbm"


def test_h2d_exception_falls_back_cold():
    cache, io = make_cache()
    (node,) = publish(cache, prompt_of((1, 2)), [3])
    cache.evict(1)
    assert cache.wait_migrations(5)
    io.h2d_boom = True
    assert not cache.promote(node, 9)
    assert cache.promote_failures == 1 and node.tier == "reaped"


# ---- compatibility and plumbing ------------------------------------------


def test_tier_off_is_the_old_evict():
    cache = RadixPrefixCache(PS)        # host_pages=0: no callables OK
    publish(cache, prompt_of((1, 2), (3, 4)), [3, 4])
    freed = cache.evict(2)
    assert sorted(freed) == [3, 4]
    assert cache.host_used == 0 and cache.demotions == 0
    assert cache.match(prompt_of((1, 2)), 1) == []
    with pytest.raises(ValueError, match="d2h and h2d"):
        RadixPrefixCache(PS, host_pages=4)


def test_flush_kills_both_tiers():
    cache, _ = make_cache()
    publish(cache, prompt_of((1, 2)), [3])
    publish(cache, prompt_of((5, 6)), [4])
    cache.evict(1)                       # one page host-resident
    assert cache.wait_migrations(5)
    freed = cache.evict(99, pressure=False)
    assert len(freed) == 1               # only the HBM page frees bytes
    assert cache.pages == 0 and cache.host_used == 0
    assert cache.evictions == 1, "flush must stay out of the pressure " \
                                 "signal"


def test_depth1_tier_events_feed_affinity():
    cache, _ = make_cache(host_pages=1)
    (n1,) = publish(cache, prompt_of((1, 2)), [3])
    publish(cache, prompt_of((5, 6)), [4])
    cache.evict(1)
    assert cache.wait_migrations(5)
    assert cache.promote(n1, 9) or True  # n1 may or may not be the LRU pick
    cache.evict(1)                       # second demotion overflows cap 1
    assert cache.wait_migrations(5)
    events = cache.drain_tier_events()
    assert events, "depth-1 transitions must be recorded"
    assert all(isinstance(k, tuple) and t in ("host", "hbm", None)
               for k, t in events)
    assert cache.drain_tier_events() == [], "drain must pop"


def test_forget_then_reinsert_is_clean():
    cache, _ = make_cache()
    p = prompt_of((1, 2), (3, 4))
    publish(cache, p, [3, 4])
    freed = cache.forget(p)
    assert sorted(freed) == [3, 4]
    assert cache.match(p, 2) == []
    publish(cache, p, [5, 6])
    assert [n.page for n in cache.match(p, 2)] == [5, 6]
