"""In-graph compute/communication overlap (FFConfig.overlap_grad_sync)
and async checkpointing (FFConfig.async_checkpointing).

The contract: bucketed grad reduce-scatter inside the accumulation scan +
the ZeRO-1 sharded optimizer update change PLACEMENT, never values — the
loss trajectory and params are pinned against the serial-epilogue path
(bitwise on this CPU mesh for f32; the acceptance criterion allows a
documented tolerance where a backend's reduction order differs), under
grad accumulation, FSDP, Adam, and resume-from-checkpoint. Async saves
publish the same atomic tmp-dir + manifest checkpoints as sync saves,
strictly in order, with failures surfaced at the next quiesce.
"""

import os
import tempfile

import numpy as np
import pytest

from flexflow_tpu import (AdamOptimizer, FFConfig, FFModel, LossType,
                          MetricsType, SGDOptimizer, SingleDataLoader,
                          TrainSupervisor)


def _build(overlap, accum=4, fsdp="", master="float32", opt=None,
           mesh=None, **cfg_kw):
    cfg = FFConfig(batch_size=16, mesh_shape=mesh or {"data": 4},
                   grad_accum_steps=accum, overlap_grad_sync=overlap,
                   fsdp_axis=fsdp, master_dtype=master, **cfg_kw)
    ff = FFModel(cfg)
    x = ff.create_tensor([16, 32], name="input")
    t = ff.dense(x, 64, name="d1")
    t = ff.relu(t, name="r1")
    t = ff.dense(t, 8, name="head")
    ff.compile(opt or SGDOptimizer(lr=0.1),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=t)
    return ff


def _copy_weights(src, dst):
    for op, ws in src.params.items():
        for w, v in ws.items():
            dst.set_weights(op, w, np.asarray(v))


def _batch(seed=0):
    rs = np.random.RandomState(seed)
    return {"input": rs.randn(16, 32).astype(np.float32),
            "label": rs.randint(0, 8, (16, 1)).astype(np.int32)}


# Documented tolerance (ISSUE 10 acceptance): the overlap path changes the
# cross-data-shard reduction from all-reduce to reduce-scatter, whose ring
# ordering XLA may choose differently — values agree to a few f32 ULPs per
# step (measured: <= 1.2e-7 relative on this mesh), never more. Everything
# placement-only (ZeRO-1 layout, the all-gather return) is exactly bitwise
# and covered by test_overlap_resume_from_checkpoint_pinned's overlap-vs-
# overlap equality.
TOL = dict(atol=1e-5, rtol=1e-5)


def _assert_pinned(a, b, steps=3, atol=TOL["atol"], rtol=TOL["rtol"]):
    batch = _batch()
    for i in range(steps):
        la, _ = a._run_train_step(batch)
        lb, _ = b._run_train_step(batch)
        np.testing.assert_allclose(float(la), float(lb), atol=atol,
                                   rtol=rtol, err_msg=f"loss step {i}")
    for op, ws in a.params.items():
        for w, v in ws.items():
            np.testing.assert_allclose(
                np.asarray(v, np.float32),
                np.asarray(b.params[op][w], np.float32),
                atol=atol, rtol=rtol, err_msg=f"{op}/{w}")


# ---- overlap numerics pinned vs the serial epilogue ------------------------


def test_overlap_accum_pinned():
    """Bucketed reduce-scatter in the scan + ZeRO-1 update vs the serial
    epilogue, pinned at the documented tolerance (see TOL)."""
    a, b = _build(False), _build(True)
    _copy_weights(a, b)
    _assert_pinned(a, b)


def test_overlap_no_accum_pinned():
    """accum=1: no scan, but the ZeRO-1 wrapper still reduce-scatters the
    grads and shards the update — pinned too."""
    a, b = _build(False, accum=1), _build(True, accum=1)
    _copy_weights(a, b)
    _assert_pinned(a, b)


def test_overlap_composes_with_fsdp():
    """fsdp_axis == the data axis: ZeRO-3 already owns every shardable
    weight, the ZeRO-1 layout degrades to a no-op, values stay pinned."""
    a, b = _build(False, fsdp="data"), _build(True, fsdp="data")
    _copy_weights(a, b)
    _assert_pinned(a, b)


def test_overlap_adam_pinned():
    a = _build(False, opt=AdamOptimizer(alpha=0.01))
    b = _build(True, opt=AdamOptimizer(alpha=0.01))
    _copy_weights(a, b)
    _assert_pinned(a, b)


def test_overlap_opt_state_sharded():
    """The ZeRO-1 point: optimizer-state HBM divides by the data degree —
    each moment leaf is genuinely sharded over 'data', its local shard a
    quarter of the global array on the data=4 mesh."""
    ff = _build(True, opt=AdamOptimizer(alpha=0.01))
    m = ff.opt_state["m"]["d1"]["kernel"]
    assert "data" in str(m.sharding.spec), m.sharding.spec
    local = m.addressable_shards[0].data.size
    assert local * 4 == m.size, (local, m.size)
    # while the PARAMS stay in their strategy layout (all-gathered once
    # per step by the update's return constraint)
    p = ff.params["d1"]["kernel"]
    assert p.addressable_shards[0].data.size * 2 >= p.size


def test_overlap_noop_without_data_axis():
    """No data axis > 1: nothing to scatter over — compile falls back to
    the plain update (logged) and training runs unchanged."""
    from flexflow_tpu.runtime.optimizer import Zero1Update

    ff = _build(True, accum=2, mesh={"model": 2})
    assert not isinstance(ff.optimizer, Zero1Update)
    loss0, _ = ff._run_train_step(_batch())
    loss1, _ = ff._run_train_step(_batch())
    assert float(loss1) < float(loss0)


def test_grad_scatter_shardings_layout():
    """Executor helper: every scatterable weight gains 'data' on exactly
    one previously-unsharded dim; under fsdp_axis='data' the spec is
    unchanged (ZeRO-3 already spent the axis)."""
    ff = _build(True)
    sc = ff.executor.grad_scatter_shardings()
    for op, per in sc.items():
        for w, ns in per.items():
            assert "data" in str(ns.spec), (op, w, ns.spec)
    ff2 = _build(True, fsdp="data")
    base = ff2.executor.param_shardings()
    sc2 = ff2.executor.grad_scatter_shardings()
    for op, per in sc2.items():
        for w, ns in per.items():
            assert ns.spec == base[op][w].spec, (op, w)


def test_overlap_resume_from_checkpoint_pinned():
    """Acceptance: overlap + sharded update stays pinned across a
    save/restore boundary — an overlap run resumed from its own
    checkpoint matches the uninterrupted overlap run AND the sync path."""
    from flexflow_tpu.runtime.checkpoint import (restore_checkpoint,
                                                 save_checkpoint)

    batch = _batch()
    sync = _build(False)
    full = _build(True)
    _copy_weights(sync, full)
    with tempfile.TemporaryDirectory() as d:
        for _ in range(2):
            sync._run_train_step(batch)
            full._run_train_step(batch)
        save_checkpoint(full, d)
        resumed = _build(True)
        restore_checkpoint(resumed, d)
        # the RNG key is supervisor metadata; mirror it by hand here
        resumed._rng = full._rng
        for i in range(2):
            ls, _ = sync._run_train_step(batch)
            lf, _ = full._run_train_step(batch)
            lr, _ = resumed._run_train_step(batch)
            # overlap-vs-overlap across the checkpoint boundary is exact:
            # same programs, restored-from-host identical values
            assert float(lf) == float(lr), (i, float(lf), float(lr))
            np.testing.assert_allclose(float(ls), float(lf), **TOL)
        for op, ws in full.params.items():
            for w, v in ws.items():
                np.testing.assert_array_equal(
                    np.asarray(v), np.asarray(resumed.params[op][w]),
                    err_msg=f"{op}/{w}")


# ---- fp32 gradient accumulation (satellite) --------------------------------


def test_bf16_accum_sums_in_fp32():
    """bf16 master weights: the accumulation scan's carry is f32, so the
    accum=8 trajectory stays within ~1 bf16 ULP of the full-batch bf16
    step — the documented tolerance (each microbatch grad is individually
    bf16-rounded before the sum, so exactness is not on the table)."""
    a = _build(False, accum=1, master="bfloat16")
    b = _build(True, accum=8, master="bfloat16")
    _copy_weights(a, b)
    batch = _batch()
    for _ in range(3):
        la, _ = a._run_train_step(batch)
        lb, _ = b._run_train_step(batch)
        assert abs(float(la) - float(lb)) < 5e-3, (float(la), float(lb))
    for op, ws in a.params.items():
        for w, v in ws.items():
            np.testing.assert_allclose(
                np.asarray(v, np.float32),
                np.asarray(b.params[op][w], np.float32),
                atol=1e-2, rtol=1e-2, err_msg=f"{op}/{w}")


def test_f32_accum_carry_unchanged():
    """f32 grads accumulate in f32 exactly as before — the fp32-carry
    change is a no-op for full precision (pinned bitwise by
    test_overlap_accum_pinned_bitwise against the seed-path semantics)."""
    import jax.numpy as jnp

    ff = _build(False, accum=2)
    # the scan carry dtype is an implementation detail; pin the observable:
    # two steps of accum=2 match accum=1 on the same batch (mean-of-means)
    ref = _build(False, accum=1)
    _copy_weights(ff, ref)
    batch = _batch()
    l2, _ = ff._run_train_step(batch)
    l1, _ = ref._run_train_step(batch)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    assert ff.params["d1"]["kernel"].dtype == jnp.float32


# ---- async checkpointing ---------------------------------------------------


def _supervised(tmp, total, preempt_at=None, **cfg_kw):
    ff = _build(True, accum=2, checkpoint_dir=tmp, checkpoint_every=2,
                async_checkpointing=True, **cfg_kw)
    rs = np.random.RandomState(0)
    xop = ff.get_op_by_name("input")
    SingleDataLoader(ff, xop.outputs[0], rs.randn(64, 32).astype(np.float32))
    SingleDataLoader(ff, ff.label_tensor,
                     rs.randint(0, 8, (64, 1)).astype(np.int32))
    sup = TrainSupervisor(ff, tmp)
    if preempt_at is None:
        status = sup.run(total)
        return ff, sup, status
    sup.resume()
    while ff._step_count < preempt_at:
        sup.step()
        sup.after_step()
    sup.request_preempt()
    stopped = sup.after_step()
    assert stopped
    sup.finalize()
    return ff, sup, "preempted"


def test_async_checkpoint_bitwise_resume():
    """The acceptance drill: an overlapped-sync run interrupted mid-way
    resumes BITWISE from an async-written checkpoint, and the published
    step passes manifest verification."""
    from flexflow_tpu.runtime.checkpoint import (latest_intact_step,
                                                 pending_saves,
                                                 verify_checkpoint)

    with tempfile.TemporaryDirectory() as d_ref, \
            tempfile.TemporaryDirectory() as d:
        _, sup_ref, status = _supervised(d_ref, total=6)
        assert status == "completed"
        ref_losses = ["%.9f" % l for l in sup_ref.losses]

        _, sup1, _ = _supervised(d, total=6, preempt_at=3)
        assert pending_saves(d) == 0  # finalize quiesced the publisher
        step = latest_intact_step(d)
        assert step == 3
        verify_checkpoint(d, step)

        _, sup2, status = _supervised(d, total=6)
        assert status == "completed"
        assert ["%.9f" % l for l in sup2.losses] == ref_losses[3:]


def test_async_saves_publish_in_order():
    from flexflow_tpu.runtime.checkpoint import (latest_step,
                                                 save_checkpoint,
                                                 wait_pending_saves)

    ff = _build(True, accum=2)
    with tempfile.TemporaryDirectory() as d:
        save_checkpoint(ff, d, step=1, async_save=True)
        save_checkpoint(ff, d, step=2, async_save=True, keep=2)
        wait_pending_saves(d)
        assert latest_step(d) == 2
        assert {"step_1", "step_2"} <= set(os.listdir(d))


def test_async_save_failure_surfaces_at_wait():
    from flexflow_tpu.runtime.checkpoint import (save_checkpoint,
                                                 wait_pending_saves)

    ff = _build(True, accum=2)
    with tempfile.TemporaryDirectory() as d:
        blocker = os.path.join(d, "not_a_dir")
        with open(blocker, "w") as f:
            f.write("x")
        save_checkpoint(ff, os.path.join(blocker, "ckpt"), step=1,
                        async_save=True)
        with pytest.raises(RuntimeError, match="async checkpoint save"):
            wait_pending_saves()
        # the failure is consumed: a second quiesce is clean
        wait_pending_saves()


def test_async_checkpoint_matches_sync_bytes():
    """An async-published step is byte-equivalent in content to a sync one
    (same manifest file set; same restored values)."""
    from flexflow_tpu.runtime.checkpoint import (restore_checkpoint,
                                                 save_checkpoint,
                                                 wait_pending_saves)

    ff = _build(True, accum=2)
    ff._run_train_step(_batch())
    with tempfile.TemporaryDirectory() as ds, \
            tempfile.TemporaryDirectory() as da:
        save_checkpoint(ff, ds, step=1)
        save_checkpoint(ff, da, step=1, async_save=True)
        wait_pending_saves(da)
        r1, r2 = _build(True, accum=2), _build(True, accum=2)
        restore_checkpoint(r1, ds)
        restore_checkpoint(r2, da)
        for op, ws in r1.params.items():
            for w, v in ws.items():
                np.testing.assert_array_equal(
                    np.asarray(v), np.asarray(r2.params[op][w]))


def test_async_saver_backpressure():
    """A publisher slower than the save cadence blocks the submitter at
    wait_below(dir, 1) — at most one snapshot queues behind the in-flight
    publish, instead of host memory growing without bound."""
    import threading
    import time

    from flexflow_tpu.runtime.checkpoint import _SAVER

    gate = threading.Event()
    tag = os.path.join(tempfile.gettempdir(), "_ff_bp_probe")
    _SAVER.submit(tag, 1, gate.wait)         # occupies the publisher
    _SAVER.submit(tag, 2, lambda: None)      # one queued behind it
    assert _SAVER.pending(tag) == 2
    done = []

    def submitter():
        _SAVER.wait_below(tag, 1)            # the backpressure point
        done.append(1)

    th = threading.Thread(target=submitter, daemon=True)
    th.start()
    time.sleep(0.2)
    assert not done, "wait_below returned while 2 saves were pending"
    gate.set()
    th.join(10)
    assert done, "wait_below never unblocked after the publisher drained"
    _SAVER.wait(tag)
    assert _SAVER.pending(tag) == 0


# ---- observability (satellite: profiler breakdown) -------------------------


def test_step_phase_breakdown_keys():
    ff = _build(True, accum=2)
    bd = ff.step_breakdown(batch=_batch(), iters=1)
    for k in ("device_step_ms", "epilogue_ms", "compute_ms",
              "epilogue_fraction", "collective_instructions",
              "collective_bytes", "grad_sync_overlapped"):
        assert k in bd, k
    assert bd["device_step_ms"] > 0
    assert bd["epilogue_ms"] > 0
    assert 0 <= bd["epilogue_fraction"] <= 1
    assert bd["grad_sync_overlapped"] is True
    assert bd["collective_instructions"] >= 0
    # merged into last_step_breakdown (alongside fit's host-side numbers)
    assert ff.last_step_breakdown["device_step_ms"] == bd["device_step_ms"]
    # training still healthy after profiling (no donated-buffer damage)
    ff._run_train_step(_batch())


def test_hlo_collective_stats_parse():
    from flexflow_tpu.runtime.profiler import hlo_collective_stats

    txt = """
  %ar = f32[128,64]{1,0} all-reduce(%x), replica_groups={}
  %ag.1 = bf16[32]{0} all-gather(%y), dimensions={0}
  %rs = f32[16]{0} reduce-scatter(%z), dimensions={0}
  %d = f32[16]{0} all-reduce-done(%ar2)
  %plain = f32[4,4]{1,0} add(%a, %b)
"""
    s = hlo_collective_stats(txt)
    assert s["collective_instructions"] == 3
    assert s["collective_bytes"] == 128 * 64 * 4 + 32 * 2 + 16 * 4
    assert s["collective_all_reduce"] == 1
    # async '-start' lowering: the tuple result aliases the operand —
    # only the RESULT (last element) counts, never ~2x
    s2 = hlo_collective_stats(
        "  %a = (bf16[1024]{0}, bf16[8192]{0}) all-gather-start(%x)\n")
    assert s2["collective_instructions"] == 1
    assert s2["collective_bytes"] == 8192 * 2


# ---- config surface --------------------------------------------------------


def test_config_flags_roundtrip():
    cfg = FFConfig.parse_args(["--overlap-grad-sync",
                               "--async-checkpointing"])
    assert cfg.overlap_grad_sync and cfg.async_checkpointing
    cfg = FFConfig.parse_args([])
    assert not cfg.overlap_grad_sync and not cfg.async_checkpointing
