"""Worker for the multi-host SERVING leg (VERDICT r3 #9): train ->
sharded checkpoint -> restore into a FRESH model on the same 2-process
mesh -> KV-cache greedy decode. Every controller must emit bit-identical
tokens (SPMD decode: same program, same restored params, same prompt).

Prints `MULTIHOST-SERVE pid=<i> tokens=<csv>` for the parent to compare.
"""

import sys

import numpy as np

import jax


def main():
    assert jax.process_count() == 2, jax.process_count()
    pid = jax.process_index()
    ckpt_dir = sys.argv[1]

    from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                              SGDOptimizer, SingleDataLoader)
    from flexflow_tpu.models.llama import llama_lm
    from flexflow_tpu.parallel.pconfig import ParallelConfig
    from flexflow_tpu.runtime.checkpoint import (restore_checkpoint,
                                                 save_checkpoint)

    VOCAB, B, S = 61, 4, 8
    mesh_shape = {"data": 4, "model": 2}

    def build(seed):
        cfg = FFConfig(batch_size=B, mesh_shape=mesh_shape, seed=seed)
        for i in range(2):
            # TP over 'model': attention head-split + FFN column-parallel
            # (the Megatron pair, as test_generation's TP decode does)
            cfg.strategies[f"attn_{i}"] = ParallelConfig.from_axis_map(
                3, mesh_shape, {"data": 0, "model": 2})
            cfg.strategies[f"ffn_gate_{i}"] = ParallelConfig.from_axis_map(
                3, mesh_shape, {"data": 0, "model": 2})
            cfg.strategies[f"ffn_up_{i}"] = ParallelConfig.from_axis_map(
                3, mesh_shape, {"data": 0, "model": 2})
        ff = FFModel(cfg)
        tokens_t, logits = llama_lm(ff, B, seq_len=S, hidden=32, layers=2,
                                    heads=4, kv_heads=2, vocab_size=VOCAB)
        ff.compile(SGDOptimizer(lr=0.05),
                   LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                   [MetricsType.METRICS_ACCURACY], final_tensor=logits)
        return ff, tokens_t

    # phase 1: train a few steps, checkpoint
    ff, tokens_t = build(seed=11)
    rs = np.random.RandomState(0)
    toks = rs.randint(0, VOCAB, (B * 2, S)).astype(np.int32)
    SingleDataLoader(ff, tokens_t, toks)
    SingleDataLoader(ff, ff.label_tensor, toks[..., None].astype(np.int32))
    for _ in range(3):
        loss, _ = ff._run_train_step(ff._stage_batch())
    save_checkpoint(ff, ckpt_dir)
    trained = np.asarray(
        ff.params["attn_0"]["wq"].addressable_shards[0].data)

    # phase 2: FRESH model (different init seed — restore must overwrite),
    # restore on the 2-process mesh, then decode
    ff2, _ = build(seed=99)
    fresh = np.asarray(
        ff2.params["attn_0"]["wq"].addressable_shards[0].data)
    assert np.abs(fresh - trained).max() > 0, \
        "seed-99 init equals trained params — restore check is vacuous"
    restore_checkpoint(ff2, ckpt_dir)
    back = np.asarray(
        ff2.params["attn_0"]["wq"].addressable_shards[0].data)
    # restore must actually overwrite the fresh init with the trained
    # shards — otherwise identical-token comparison passes vacuously on
    # identical fresh inits
    np.testing.assert_allclose(back, trained, rtol=1e-6)
    prompt = rs.randint(0, VOCAB, (B, 5)).astype(np.int32)
    out = ff2.generate(prompt, max_new_tokens=6)
    flat = ",".join(str(int(t)) for t in np.asarray(out).ravel())
    print(f"MULTIHOST-SERVE pid={pid} tokens={flat}", flush=True)


if __name__ == "__main__":
    main()
