"""Fleet serving router (runtime/router.py ServingRouter).

Correctness anchors:
  * the router moves work, never changes it: greedy fleet output is
    token-identical to solo generate, at any replica count, through any
    failover — a resubmitted request's final stream is ONE replica's
    complete greedy decode, never a splice;
  * failover is exactly-once: a crashed/hung replica is fenced, its
    in-flight and queued requests resubmit to survivors at most once
    (losses cap at 2; attempts == 2 on a mixed fleet, where no
    handoff double-dispatch exists), nothing is lost, nothing is
    duplicated;
  * deadlines are honored at the cheapest point: expired-while-queued
    requests retire as "timeout" with zero dispatch (and zero compiles);
    expired in-flight work on a fenced replica is NOT resubmitted;
  * shedding is fast: a full router queue rejects in microseconds with
    state "rejected" — accepted work is unaffected;
  * prefix affinity sends shared-prompt traffic to the replica whose
    trie already holds the pages (hits concentrate on one engine).

Every failure drill is deterministic via FF_FAULT (crash@replica,
hang@replica, slow@serve — runtime/faultinject.py).
"""

import time

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.models.llama import llama_lm
from flexflow_tpu.runtime import faultinject

VOCAB = 89


@pytest.fixture(scope="module")
def ff():
    cfg = FFConfig(batch_size=2, mesh_shape={"data": 1})
    model = FFModel(cfg)
    _, logits = llama_lm(model, 2, seq_len=16, hidden=64, layers=2,
                         heads=4, kv_heads=2, vocab_size=VOCAB)
    model.compile(final_tensor=logits)
    return model


@pytest.fixture(scope="module")
def draft(ff):
    """A smaller draft LM over the SAME vocabulary (random weights — the
    reject path runs hard), for the prefix+speculation failover test."""
    cfg = FFConfig(batch_size=2, mesh_shape={"data": 1})
    model = FFModel(cfg)
    _, logits = llama_lm(model, 2, seq_len=16, hidden=32, layers=1,
                         heads=2, kv_heads=2, vocab_size=VOCAB)
    model.compile(final_tensor=logits)
    return model


def _prompts(seed, lengths):
    rs = np.random.RandomState(seed)
    return [rs.randint(1, VOCAB, (L,)).astype(np.int32) for L in lengths]


def _solo_check(ff, reqs, max_new):
    for r in reqs:
        solo = ff.generate(r.prompt[None, :], max_new_tokens=max_new)
        np.testing.assert_array_equal(
            np.asarray(r.tokens, np.int32), solo[0, r.prompt.size:],
            err_msg=f"request {r.rid} (attempts {r.attempts}, replica "
                    f"{r.replica}) diverged from its solo run")


def _arm_fault(monkeypatch, spec):
    monkeypatch.setenv("FF_FAULT", spec)
    faultinject.reset()


def _disarm_fault(monkeypatch):
    monkeypatch.delenv("FF_FAULT", raising=False)
    faultinject.reset()


# ---- host-side semantics (no decode, no compiles: tier-1 fast) -----------


def test_router_validation_and_rejection_is_fast(ff):
    """Malformed submits raise synchronously; a full queue rejects in
    well under a millisecond of work (shedding must be cheaper than the
    work it sheds); constructor guards hold."""
    router = ff.make_serving_router(replicas=1, serve_slots=2,
                                    kv_page_size=4, max_seq_len=32,
                                    max_queue=2, start=False)
    try:
        with pytest.raises(ValueError, match="empty"):
            router.submit(np.zeros((0,), np.int32), 4)
        with pytest.raises(ValueError, match="max_new_tokens"):
            router.submit(np.arange(1, 5, dtype=np.int32), 0)
        with pytest.raises(ValueError, match="max_seq_len"):
            router.submit(np.arange(1, 30, dtype=np.int32), 16)
        with pytest.raises(ValueError, match="deadline_s"):
            router.submit(np.arange(1, 5, dtype=np.int32), 4,
                          deadline_s=-1.0)
        a = router.submit(np.arange(1, 5, dtype=np.int32), 4)
        b = router.submit(np.arange(1, 6, dtype=np.int32), 4)
        t0 = time.perf_counter()
        shed = [router.submit(np.arange(1, 5, dtype=np.int32), 4)
                for _ in range(20)]
        dt = time.perf_counter() - t0
        assert [r.state for r in shed] == ["rejected"] * 20
        assert all(r.attempts == 0 and r.t_done for r in shed)
        assert dt < 0.1, f"20 rejections took {dt:.3f}s — not 'fast'"
        assert a.state == "queued" and b.state == "queued"
        st = router.stats()
        assert st["rejected"] == 20 and st["queued"] == 2
        assert st["submitted"] == 22 and st["max_queue"] == 2
    finally:
        router.close()
    with pytest.raises(ValueError, match="replicas"):
        ff.make_serving_router(replicas=0, start=False)
    with pytest.raises(ValueError, match="max_queue"):
        ff.make_serving_router(replicas=1, max_queue=-1, start=False)
    with pytest.raises(ValueError, match="health_timeout_s"):
        ff.make_serving_router(replicas=1, health_timeout_s=0.0,
                               start=False)
    with pytest.raises(ValueError):
        FFConfig(batch_size=2, mesh_shape={"data": 1}, serve_max_queue=-1)
    cfg = FFConfig.parse_args(["--batch-size", "2",
                               "--serve-max-queue", "9"])
    assert cfg.serve_max_queue == 9


def test_deadline_expired_while_queued_never_dispatches(ff):
    """A request whose deadline passes in the router queue retires as
    "timeout" with zero dispatch — and therefore zero compiles: the
    cheapest possible retirement."""
    router = ff.make_serving_router(replicas=2, serve_slots=2,
                                    kv_page_size=4, max_seq_len=32,
                                    start=False)
    try:
        req = router.submit(np.arange(1, 6, dtype=np.int32), 4,
                            deadline_s=0.0)
        time.sleep(0.005)
        router.start()
        router.wait([req], timeout=30)
        assert req.state == "timeout" and req.attempts == 0
        assert "router queue" in req.error
        st = router.stats()
        assert st["timeouts"] == 1 and st["dispatched"] == 0
        assert all(e.recompile_count == 0 for e in router.engines), \
            "an expired-in-queue request must never reach a device"
        assert router.health()["status"] == "idle"
    finally:
        router.close()


def test_router_stats_and_health_keys(ff):
    """The fleet observability surface: counters + per-replica rows in
    stats(), a cheap health() that never touches an engine lock."""
    router = ff.make_serving_router(replicas=2, serve_slots=2,
                                    kv_page_size=4, max_seq_len=32,
                                    start=False)
    try:
        st = router.stats()
        for key in ("replicas", "alive", "submitted", "dispatched",
                    "completed", "failed", "timeouts", "rejected",
                    "fenced", "resubmitted", "queued", "max_queue",
                    "ttft_p50_ms", "ttft_p99_ms", "affinity_keys",
                    "per_replica"):
            assert key in st, f"stats() missing {key}"
        assert len(st["per_replica"]) == 2
        for row in st["per_replica"]:
            for key in ("replica", "fenced", "fence_reason",
                        "outstanding", "active_slots", "queued"):
                assert key in row, f"per_replica row missing {key}"
        h = router.health()
        for key in ("status", "admitting", "alive", "replicas", "queued",
                    "outstanding", "fenced", "max_queue"):
            assert key in h, f"health() missing {key}"
        assert h["status"] == "idle" and h["alive"] == 2
        assert all(e.recompile_count == 0 for e in router.engines)
    finally:
        router.close()


# ---- fleet semantics (decode on both replicas) ----------------------------


@pytest.mark.slow  # 25 s; the router CI tier runs the full file
def test_fleet_token_identity_and_both_replicas_serve(ff):
    """More requests than one replica's capacity, mixed lengths: every
    stream equals its solo generate run, and least-loaded dispatch
    actually spreads work across BOTH replicas."""
    prompts = _prompts(3, [5, 9, 3, 12, 7, 6, 17, 2, 11, 4])
    router = ff.make_serving_router(replicas=2, serve_slots=2,
                                    kv_page_size=4, max_seq_len=64)
    try:
        reqs = router.run(prompts, max_new_tokens=6, timeout=300)
        assert [r.state for r in reqs] == ["done"] * len(prompts)
        _solo_check(ff, reqs, 6)
        st = router.stats()
        assert st["completed"] == len(prompts)
        assert st["fenced"] == 0 and st["resubmitted"] == 0
        served = [e.stats()["completed"] for e in router.engines]
        assert all(c > 0 for c in served), \
            f"least-loaded dispatch left a replica idle: {served}"
        assert sum(served) == len(prompts), "requests duplicated or lost"
        assert 0 < st["ttft_p50_ms"] <= st["ttft_p99_ms"]
    finally:
        router.close()


@pytest.mark.slow  # 25 s; router CI tier runs the full file
def test_crash_failover_exactly_once_token_identity(ff, monkeypatch):
    """FF_FAULT crash@replica:0 mid-flight: the replica is fenced, its
    in-flight and queued work resubmits to the survivor exactly once,
    every request completes with its solo tokens, none is duplicated."""
    prompts = _prompts(5, [5, 9, 3, 12, 7, 6])
    router = ff.make_serving_router(replicas=2, serve_slots=2,
                                    kv_page_size=4, max_seq_len=64,
                                    decode_chunk=2, start=False)
    try:
        router.warmup(_prompts(6, [5, 9]), max_new_tokens=2)
        warm_done = router.engines[1].stats()["completed"]
        _arm_fault(monkeypatch, "crash(3)@replica:0")
        reqs = router.run(prompts, max_new_tokens=12, timeout=300)
        assert [r.state for r in reqs] == ["done"] * len(prompts)
        _solo_check(ff, reqs, 12)
        st = router.stats()
        assert st["fenced"] == 1 and st["resubmitted"] >= 1
        assert st["completed"] == len(prompts), "lost or duplicated"
        assert all(1 <= r.attempts <= 2 for r in reqs), \
            "resubmission must happen at most once"
        assert any(r.attempts == 2 for r in reqs), \
            "the crash was supposed to catch work in flight"
        # the fenced replica's engine is abandoned; the survivor did the
        # failover work (delta past its warmup traffic)
        assert router.engines[1].stats()["completed"] - warm_done == sum(
            1 for r in reqs if r.replica == 1)
        assert router.health()["alive"] == 1
    finally:
        _disarm_fault(monkeypatch)
        router.close()


@pytest.mark.slow  # 45 s; router CI tier runs the full file — the
# satellite pin: failover token identity with prefix cache AND
# speculation live on both replicas
def test_requeue_after_crash_token_identity_with_prefix_and_spec(
        ff, draft, monkeypatch):
    """A request resubmitted to a second replica mid-stream produces the
    SAME greedy tokens as an uninterrupted single-replica run, with the
    radix prefix cache and speculative decoding enabled on both
    replicas: the failover path composes with every serving feature
    without touching the stream."""
    rs = np.random.RandomState(11)
    system = rs.randint(1, VOCAB, (8,)).astype(np.int32)  # 2 full pages
    prompts = [np.concatenate([system,
                               rs.randint(1, VOCAB, (L,)).astype(np.int32)])
               for L in (2, 6, 4, 3, 5)]
    kwargs = dict(serve_slots=2, kv_page_size=4, max_seq_len=64,
                  decode_chunk=2, draft_model=draft, speculate_k=2)

    # the uninterrupted single-replica reference run
    ref = ff.make_serving_engine(**kwargs)
    want = [np.asarray(r.tokens, np.int32)
            for r in ref.run(prompts, max_new_tokens=10)]

    router = ff.make_serving_router(replicas=2, start=False, **kwargs)
    try:
        router.warmup(prompts[:2], max_new_tokens=2)
        _arm_fault(monkeypatch, "crash(3)@replica:0")
        reqs = router.run(prompts, max_new_tokens=10, timeout=300)
        assert [r.state for r in reqs] == ["done"] * len(prompts)
        st = router.stats()
        assert st["fenced"] == 1 and st["resubmitted"] >= 1
        assert any(r.attempts == 2 for r in reqs), \
            "no request was actually resubmitted mid-stream"
        for w, r in zip(want, reqs):
            np.testing.assert_array_equal(
                w, np.asarray(r.tokens, np.int32),
                err_msg=f"request {r.rid} (attempts {r.attempts}) "
                        f"diverged from the uninterrupted run")
        # the survivor's prefix cache and speculation genuinely ran
        sst = router.engines[1].stats()
        assert sst["prefix_hits"] > 0 and sst["spec_proposed"] > 0
    finally:
        _disarm_fault(monkeypatch)
        router.close()


@pytest.mark.slow  # 20 s; router CI tier runs the full file
def test_hang_detected_fenced_and_survivor_completes(ff, monkeypatch):
    """FF_FAULT hang@replica:1: the wedged driver stops heartbeating,
    the health sweep fences it within health_timeout_s, its work moves
    to the survivor, every stream stays solo-identical. Warm programs
    first — a tight timeout is only meaningful when a healthy tick is
    milliseconds (a cold tick legitimately compiles for seconds)."""
    prompts = _prompts(7, [5, 9, 3, 12])
    router = ff.make_serving_router(replicas=2, serve_slots=2,
                                    kv_page_size=4, max_seq_len=64,
                                    decode_chunk=2, prefix_cache=False,
                                    health_timeout_s=1.0, start=False)
    try:
        router.warmup(_prompts(8, [6, 10]), max_new_tokens=2)
        _arm_fault(monkeypatch, "hang@replica:1")
        t0 = time.monotonic()
        reqs = router.run(prompts, max_new_tokens=10, timeout=300)
        assert [r.state for r in reqs] == ["done"] * len(prompts)
        _solo_check(ff, reqs, 10)
        st = router.stats()
        assert st["fenced"] == 1
        assert "hang" in router.stats()["per_replica"][1]["fence_reason"]
        # detection is bounded by the timeout, not by luck
        assert time.monotonic() - t0 < 60
    finally:
        _disarm_fault(monkeypatch)
        router.close()


@pytest.mark.slow  # 20 s; router CI tier runs the full file
def test_slow_replica_expired_inflight_not_resubmitted(ff, monkeypatch):
    """FF_FAULT slow(400)@serve:1 stalls replica 0's first admission past
    the request's 150 ms deadline; when the replica is then crashed, the
    expired in-flight request retires as "timeout" WITHOUT being
    resubmitted (the work is already worthless) while non-expired work
    fails over normally."""
    prompts = _prompts(9, [5, 9])
    router = ff.make_serving_router(replicas=2, serve_slots=2,
                                    kv_page_size=4, max_seq_len=64,
                                    decode_chunk=2, prefix_cache=False,
                                    start=False)
    try:
        router.warmup(_prompts(10, [6, 10]), max_new_tokens=2)
        _arm_fault(monkeypatch, "slow(400)@serve:1,crash(3)@replica:0")
        # submit a ALONE and wait for its dispatch (least-loaded
        # tie-break -> replica 0) so the process-global slow@serve
        # occurrence 1 deterministically lands on ITS admission, then
        # send b (replica 0 now loaded -> replica 1)
        a = router.submit(prompts[0], 12, deadline_s=0.15)
        router.start()
        t0 = time.monotonic()
        while a.attempts == 0 and time.monotonic() - t0 < 60:
            time.sleep(0.002)
        assert a.replica == 0, "tie-break must send the first request to 0"
        time.sleep(0.1)   # replica 0 is now inside its slow admission
        b = router.submit(prompts[1], 12)
        router.wait([a, b], timeout=300)
        assert a.state == "timeout" and a.attempts == 1
        assert "fenced replica" in a.error
        assert b.state == "done"
        st = router.stats()
        assert st["fenced"] == 1
        assert st["resubmitted"] == 0, \
            "expired in-flight work must not burn survivor capacity"
        assert st["timeouts"] == 1
    finally:
        _disarm_fault(monkeypatch)
        router.close()


@pytest.mark.slow  # 20 s; router CI tier runs the full file
def test_prefix_affinity_concentrates_shared_prompts(ff):
    """Shared-prefix traffic lands on the replica that already holds the
    prefix pages: after the first shared-prompt request homes, the rest
    follow it (prefix hits concentrate on ONE engine) while background
    traffic still balances."""
    rs = np.random.RandomState(13)
    system = rs.randint(1, VOCAB, (8,)).astype(np.int32)  # 2 full pages
    shared = [np.concatenate([system,
                              rs.randint(1, VOCAB, (L,)).astype(np.int32)])
              for L in (2, 5, 3, 4)]
    router = ff.make_serving_router(replicas=2, serve_slots=2,
                                    kv_page_size=4, max_seq_len=64)
    try:
        # home the prefix: run the first shared prompt alone
        first = router.run([shared[0]], max_new_tokens=4, timeout=300)[0]
        home = first.replica
        reqs = router.run(shared[1:], max_new_tokens=4, timeout=300)
        assert all(r.state == "done" for r in reqs)
        assert all(r.replica == home for r in reqs), (
            f"shared-prefix requests scattered: "
            f"{[r.replica for r in reqs]}, home {home}")
        hits = [e.stats()["prefix_hits"] for e in router.engines]
        assert hits[home] == len(shared) - 1
        assert hits[1 - home] == 0
        _solo_check(ff, [first] + reqs, 4)
        assert router.stats()["affinity_keys"] >= 1
    finally:
        router.close()


@pytest.mark.slow  # 20 s; router CI tier runs the full file
def test_shedding_accepted_work_unaffected_and_fleet_drains(ff):
    """With a bounded queue, shed load never touches accepted work:
    accepted requests all complete solo-identical; drain() settles the
    fleet and leaves every surviving engine drained."""
    prompts = _prompts(15, [5, 9, 3, 12, 7, 6, 4, 8])
    router = ff.make_serving_router(replicas=1, serve_slots=2,
                                    kv_page_size=4, max_seq_len=64,
                                    max_queue=3, start=False)
    try:
        reqs = [router.submit(p, max_new_tokens=5) for p in prompts]
        accepted = [r for r in reqs if r.state == "queued"]
        shed = [r for r in reqs if r.state == "rejected"]
        assert len(accepted) == 3 and len(shed) == len(prompts) - 3
        snap = router.drain()   # starts the drivers, finishes the queue
        assert snap["drained"] and snap["rejected"] == len(shed)
        assert [r.state for r in accepted] == ["done"] * len(accepted)
        _solo_check(ff, accepted, 5)
        assert router.health()["status"] == "drained"
        assert router.engines[0].health()["status"] == "drained"
        with pytest.raises(RuntimeError, match="draining"):
            router.submit(prompts[0], 4)
    finally:
        router.close()


@pytest.mark.slow  # 15 s; router CI tier runs the full file
def test_serve_fleet_api(ff):
    """FFModel.serve_fleet: the one-shot fleet surface returns outputs
    aligned with prompts (None for shed/expired) plus the fleet ledger."""
    prompts = _prompts(17, [5, 9, 3, 12])
    outs, st = ff.serve_fleet(prompts, max_new_tokens=5, replicas=2,
                              serve_slots=2, kv_page_size=4,
                              max_seq_len=64)
    assert st["completed"] == len(prompts) and st["alive"] == 2
    for p, out in zip(prompts, outs):
        solo = ff.generate(p[None, :], max_new_tokens=5)
        np.testing.assert_array_equal(out, solo[0, :p.size + 5])
