"""Scanned multi-step training (executor.make_train_scan / FFModel.train_scanned).

The scanned path runs N steps as one lax.scan program — the TPU-native
analog of the reference's Legion tracing replay around each training
iteration (python/flexflow/keras/models/base_model.py:408-418). These
tests pin its contract: identical math to the per-step path on a
deterministic model (same data order, same updates), correct dataloader
cursor hand-off between the two paths, and fit(scan_steps=...) reaching
the same accuracy gates as the plain loop.
"""

import numpy as np

from flexflow_tpu import (ActiMode, FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)

from tests.test_training import build_mlp, make_blobs


def _fresh_model(scan_steps=0, epochs=2):
    cfg = FFConfig(batch_size=64, epochs=epochs, scan_steps=scan_steps)
    ff, xt = build_mlp(cfg)
    ff.compile(SGDOptimizer(lr=0.1),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY])
    x, y = make_blobs()
    SingleDataLoader(ff, xt, x)
    SingleDataLoader(ff, ff.label_tensor, y)
    return ff


def test_scanned_matches_per_step():
    # no dropout in the MLP -> both paths are deterministic and must agree
    ff_loop = _fresh_model()
    ff_scan = _fresh_model()
    n = 6
    for _ in range(n):
        ff_loop._run_train_step(ff_loop._stage_batch())
    losses, mets = ff_scan.train_scanned(n)
    assert losses.shape == (n,)
    assert all(v.shape == (n,) for v in mets.values())
    for op_name, ws in ff_loop.params.items():
        for w_name, w in ws.items():
            np.testing.assert_allclose(
                np.asarray(w), np.asarray(ff_scan.params[op_name][w_name]),
                rtol=2e-5, atol=2e-5,
                err_msg=f"{op_name}.{w_name} diverged between per-step "
                        f"and scanned training")
    assert ff_scan._step_count == n


def test_scanned_cursor_interleaves_with_per_step():
    # scan advances the dataloader cursor exactly like n per-step calls,
    # so mixing the two paths keeps the same batch order
    ff_loop = _fresh_model()
    ff_mix = _fresh_model()
    for _ in range(5):
        ff_loop._run_train_step(ff_loop._stage_batch())
    ff_mix.train_scanned(2)
    ff_mix._run_train_step(ff_mix._stage_batch())
    ff_mix.train_scanned(2)
    for op_name, ws in ff_loop.params.items():
        for w_name, w in ws.items():
            np.testing.assert_allclose(
                np.asarray(w), np.asarray(ff_mix.params[op_name][w_name]),
                rtol=2e-5, atol=2e-5)


def test_fit_scan_steps_trains():
    perf = _fresh_model(scan_steps=4, epochs=5).fit(verbose=False)
    assert perf.accuracy > 0.9, f"accuracy {perf.accuracy}"


def test_fit_scan_ragged_tail():
    # 8 batches per epoch, chunks of 3 -> 3+3+2: the 2-step tail runs
    # through the per-step program (no second scan compile) and the
    # epoch still covers all samples
    ff = _fresh_model(scan_steps=3, epochs=4)
    perf = ff.fit(verbose=False)
    assert perf.train_all == 512
    assert perf.accuracy > 0.9, f"accuracy {perf.accuracy}"


def test_scanned_wraps_dataset():
    ff = _fresh_model()
    nb = ff._dataloaders[0].num_batches
    losses, _ = ff.train_scanned(nb + 3)  # wraps past the dataset end
    assert losses.shape == (nb + 3,)
    assert np.isfinite(np.asarray(losses)).all()
