"""Randomized generation cross-feature sweep (VERDICT r3 #7).

Drives random combinations of {greedy, temperature, top-k, beam} x
{ragged prompts, chunked prefill, int8, tied weights, GQA/MHA, MoE}
against the naive full-forward rescoring oracle: every claim the decode
path makes (chosen tokens, reported per-token logprobs, beam scores) is
re-derived by running the TRAINING graph forward on the realized token
prefix — the oracle that caught the beam cache-poisoning (3cf0d66) and
int8 cache-validity (c51d982) bug class after the fact, now run across
the whole feature lattice before the fact.

Model/oracle pairs are cached per architecture so ~200 sampled configs
reuse a handful of compiled programs (the Generator's LRU does the
rest); FF_GEN_SWEEP_N overrides the sample count.
"""

import os

import numpy as np
import jax.numpy as jnp
import pytest

from flexflow_tpu import FFConfig, FFModel

VOCAB = 61
B, S0, NEW = 2, 8, 5
N_CONFIGS = int(os.environ.get("FF_GEN_SWEEP_N", "220"))
# tier-1 budget: the full 220-config sweep alone ate the entire 870 s
# tier-1 window (the suite never reached the files after it). The first
# TIER1_CONFIGS samples stay in tier-1 (every mode/arch lands several
# times in 32 draws); the tail carries the `slow` marker and runs in the
# nightly/`unit` tiers. Each i seeds its own RandomState, so the subset
# is the same configs tier-1 always ran.
TIER1_CONFIGS = int(os.environ.get("FF_GEN_SWEEP_TIER1", "32"))

_MODELS = {}


def _build(arch):
    """arch: (family, tied, kv_heads, moe)."""
    family, tied, kv_heads, moe = arch
    cfg = FFConfig(batch_size=B, mesh_shape={"data": 1}, seed=7)
    ff = FFModel(cfg)
    if family == "llama":
        from flexflow_tpu.models.llama import llama_lm

        _, logits = llama_lm(ff, B, seq_len=S0, hidden=32, layers=2,
                             heads=4, kv_heads=kv_heads, vocab_size=VOCAB,
                             tie_embeddings=tied)
    else:
        from flexflow_tpu.models.bert import gpt_lm

        _, logits = gpt_lm(ff, B, seq_len=S0, hidden=32, layers=2, heads=4,
                           vocab_size=VOCAB, moe_every=2, num_experts=4)
        # the decode path routes MoE with capacity = slab token count
        # (zero drops, generation.py decode walk); the full-forward oracle
        # must match that semantic, so lift the training capacity above
        # any token count this sweep feeds it — otherwise capacity-bound
        # drops in the ORACLE (not the decode) fail the comparison
        from flexflow_tpu.ffconst import OperatorType

        for op in ff.ops:
            if op.op_type == OperatorType.OP_MOE:
                op.capacity = 64
    ff.compile(final_tensor=logits)
    return ff


def _model(arch):
    if arch not in _MODELS:
        _MODELS[arch] = _build(arch)
    return _MODELS[arch]


def _oracle_model(arch, quantize):
    """The rescoring oracle: the same graph full-forward. For int8 it
    carries the DEQUANTIZED weights (decode computes with q*s, so the
    oracle must too — full-precision logits would differ legitimately)."""
    if not quantize:
        return _model(arch)
    key = arch + ("deq",)
    if key not in _MODELS:
        from flexflow_tpu.runtime.generation import Generator

        src = _model(arch)
        gen = src._generators.get(("int8-oracle")) or Generator(
            src, quantize="int8")
        qp = gen._quantized_params()
        ff = _build(arch)
        for op_name, ws in qp.items():
            for w_name, v in ws.items():
                if isinstance(v, dict) and "q" in v:
                    ff.set_weights(op_name, w_name, np.asarray(
                        v["q"].astype(jnp.float32) * v["s"]))
                else:
                    ff.set_weights(op_name, w_name, np.asarray(v))
        _MODELS[key] = ff
    return _MODELS[key]


def _full_logits(ff, toks):
    return np.asarray(ff.predict({"input": np.asarray(toks, np.int32)}))


def _sample_config(rs):
    mode = rs.choice(["greedy", "temp", "topk", "beam"])
    arch_pool = [("llama", False, 0, False),   # MHA
                 ("llama", True, 2, False),    # tied + GQA
                 ("llama", False, 2, False),   # GQA
                 ("gpt", False, 0, True)]      # MoE
    arch = arch_pool[rs.randint(len(arch_pool))]
    quant = "int8" if rs.rand() < 0.25 else None
    ragged = rs.rand() < 0.3  # beam included since r5 (VERDICT r4 #4)
    chunk = int(rs.choice([0, 0, 3]))  # ragged x chunk legal since r5
    # eos early-stop joins the lattice for non-beam modes: a random token
    # declared eos; rows that emit it must pad (and score 0) afterwards
    eos = int(rs.randint(VOCAB)) if mode != "beam" and rs.rand() < 0.3 \
        else None
    cfgd = {"mode": mode, "arch": arch, "quant": quant, "ragged": ragged,
            "chunk": chunk, "eos": eos}
    if mode == "temp":
        cfgd["temperature"], cfgd["top_k"] = 0.7, 0
    elif mode == "topk":
        cfgd["temperature"], cfgd["top_k"] = 1.0, 5
    elif mode == "beam":
        cfgd["num_beams"] = int(rs.choice([2, 3]))
        cfgd["length_penalty"] = float(rs.choice([0.0, 1.0]))
    return cfgd


def _row_prefix(toks, lengths, row):
    return toks[row, :lengths[row]] if lengths is not None else toks[row]


def _stable_log_softmax(v):
    v = v.astype(np.float64)
    m = v.max()
    return v - (m + np.log(np.exp(v - m).sum()))


def _oracle_rows(ff, prompt, lengths, out_tokens):
    """Teacher-forcing oracle, ONE forward per row: run the training graph
    on each row's realized sequence (its TRUE prefix for ragged rows, plus
    the generated tokens) and return [(step_logits, logps)] per row, where
    step_logits[j] is the full-vocab distribution that produced generated
    token j and logps[j] its log-softmax score."""
    rows = []
    for r in range(B):
        prefix = _row_prefix(prompt, lengths, r)
        new_toks = out_tokens[r, prompt.shape[1]:]
        seq = np.concatenate([prefix, new_toks]).astype(np.int32)
        logits = _full_logits(ff, seq[None])[0]  # (L+NEW, V)
        L = len(prefix)
        step_logits = logits[L - 1:L - 1 + NEW]
        logps = np.asarray([_stable_log_softmax(step_logits[j])[new_toks[j]]
                            for j in range(NEW)])
        rows.append((step_logits, logps))
    return rows


@pytest.mark.parametrize(
    "i", [pytest.param(j, marks=[pytest.mark.slow] * (j >= TIER1_CONFIGS))
          for j in range(N_CONFIGS)])
def test_generation_sweep(i):
    rs = np.random.RandomState(1000 + i)
    c = _sample_config(rs)
    ff = _model(c["arch"])
    oracle = _oracle_model(c["arch"], c["quant"])
    prompt = rs.randint(0, VOCAB, (B, S0)).astype(np.int32)
    lengths = None
    if c["ragged"]:
        lengths = rs.randint(2, S0 + 1, (B,)).astype(np.int32)
        lengths[rs.randint(B)] = S0  # at least one full row

    if c["mode"] == "beam":
        out, score = ff.generate(prompt, NEW, num_beams=c["num_beams"],
                                 length_penalty=c["length_penalty"],
                                 quantize=c["quant"],
                                 prefill_chunk=c["chunk"],
                                 prompt_lengths=lengths,
                                 return_scores=True)
        assert out.shape == (B, S0 + NEW)
        # oracle: rescore the returned beam token-by-token (each ragged
        # row rescored on its TRUE prefix — pins the per-row prefill
        # position, RoPE offsets, and pad-slot masking under beams)
        rows = _oracle_rows(oracle, prompt, lengths, out)
        want = np.asarray([r[1].sum() for r in rows])
        if c["length_penalty"]:
            want = want / (NEW ** c["length_penalty"])
        np.testing.assert_allclose(score, want, atol=5e-3, rtol=1e-3)
        return

    kwargs = dict(quantize=c["quant"], prefill_chunk=c["chunk"],
                  return_scores=True, seed=int(rs.randint(1 << 16)),
                  temperature=c.get("temperature", 0.0),
                  top_k=c.get("top_k", 0))
    if c["ragged"]:
        kwargs["prompt_lengths"] = lengths
    if c["eos"] is not None:
        kwargs["eos_token_id"] = c["eos"]
        kwargs["pad_token_id"] = 0
    out, scores = ff.generate(prompt, NEW, **kwargs)
    assert out.shape == (B, S0 + NEW) and scores.shape == (B, NEW)
    assert ((out[:, S0:] >= 0) & (out[:, S0:] < VOCAB)).all()

    # eos early-stop: post-eos positions are pad with 0.0 scores; all
    # oracle checks below truncate to each row's live prefix (an eos
    # config must NOT skip the top-k/greedy oracles for pre-eos steps)
    live_new = np.full((B,), NEW, np.int64)
    if c["eos"] is not None:
        for r in range(B):
            hits = np.nonzero(out[r, S0:] == c["eos"])[0]
            if hits.size:
                e = int(hits[0])
                live_new[r] = e + 1
                assert (out[r, S0 + e + 1:] == 0).all(), out[r, S0:]
                assert (scores[r, e + 1:] == 0.0).all(), scores[r]

    # oracle 1: the reported per-token logprob equals full-forward
    # rescoring of the realized sequence (pins cache correctness across
    # RoPE offsets, GQA grouping, ragged masking, chunked prefill, int8)
    rows = _oracle_rows(oracle, prompt, lengths, out)
    for r in range(B):
        np.testing.assert_allclose(scores[r, :live_new[r]],
                                   rows[r][1][:live_new[r]],
                                   atol=5e-3, rtol=1e-3)

    for r in range(B):
        step_logits, _ = rows[r]
        for j in range(int(live_new[r])):
            tok = int(out[r, S0 + j])
            # oracle 2 (top-k): sampled token within the oracle's top-k
            # set (up to float ties at the boundary)
            if c.get("top_k"):
                kth = np.sort(step_logits[j])[-c["top_k"]]
                assert step_logits[j][tok] >= kth - 1e-3, \
                    f"token {tok} outside oracle top-{c['top_k']} step {j}"
            # greedy: chosen token maximizes the oracle logits (tolerance
            # for kernel-order float differences on near-ties)
            if c["mode"] == "greedy":
                assert step_logits[j][tok] >= step_logits[j].max() - 1e-3, \
                    f"greedy token {tok} not argmax at step {j}"
