"""Aux subsystem tests: checkpoint/resume, profiler, taskgraph export."""

import os

import numpy as np
import pytest

from flexflow_tpu import (ActiMode, FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)


def build_and_train(tmp, steps=3, mesh=None):
    cfg = FFConfig(batch_size=32, mesh_shape=mesh or {"data": 4})
    ff = FFModel(cfg)
    x = ff.create_tensor([32, 16], name="x")
    t = ff.dense(x, 32, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.dense(t, 4, name="out")
    ff.compile(SGDOptimizer(lr=0.05, momentum=0.9),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY])
    rs = np.random.RandomState(0)
    xd = rs.randn(256, 16).astype(np.float32)
    y = rs.randint(0, 4, (256, 1)).astype(np.int32)
    SingleDataLoader(ff, x, xd)
    SingleDataLoader(ff, ff.label_tensor, y)
    losses = []
    for _ in range(steps):
        batch = ff._stage_batch()
        l, _ = ff._run_train_step(batch)
        losses.append(float(l))
    return ff, losses


def test_checkpoint_roundtrip(tmp_path):
    from flexflow_tpu.runtime.checkpoint import (latest_step,
                                                 restore_checkpoint,
                                                 save_checkpoint)

    ff, _ = build_and_train(tmp_path, steps=3)
    ckpt_dir = str(tmp_path / "ckpt")
    save_checkpoint(ff, ckpt_dir)
    assert latest_step(ckpt_dir) == 3
    w_before = ff.get_weights("fc1", "kernel")

    # fresh model on a DIFFERENT mesh factorization restores correctly
    ff2, _ = build_and_train(tmp_path, steps=0, mesh={"data": 2, "model": 4})
    step = restore_checkpoint(ff2, ckpt_dir)
    assert step == 3
    np.testing.assert_allclose(ff2.get_weights("fc1", "kernel"), w_before,
                               rtol=1e-6)
    # momentum state restored too
    v = ff2.opt_state["v"]["fc1"]["kernel"]
    assert np.abs(np.asarray(v)).max() > 0

    # training continues from the restored state without error
    batch = ff2._stage_batch()
    l, _ = ff2._run_train_step(batch)
    assert np.isfinite(float(l))


def test_checkpoint_opt_layout_mismatch_refused(tmp_path):
    """ADVICE r4: fused and per-leaf optimizer-state layouts differ; a
    mismatched restore must raise a CLEAR error naming the layouts, not
    an opaque tree-structure failure — and a matching fused->fused
    restore must round-trip."""
    from flexflow_tpu.runtime.checkpoint import (restore_checkpoint,
                                                 save_checkpoint)

    def build(fused, steps):
        cfg = FFConfig(batch_size=16, mesh_shape={"data": 2},
                       fused_optimizer=fused, seed=9)
        ff = FFModel(cfg)
        x = ff.create_tensor([16, 8], name="x")
        ff.dense(x, 4, name="out")
        ff.compile(SGDOptimizer(lr=0.05, momentum=0.9),
                   LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                   [MetricsType.METRICS_ACCURACY])
        rs = np.random.RandomState(0)
        SingleDataLoader(ff, x, rs.randn(32, 8).astype(np.float32))
        SingleDataLoader(ff, ff.label_tensor,
                         rs.randint(0, 4, (32, 1)).astype(np.int32))
        for _ in range(steps):
            ff._run_train_step(ff._stage_batch())
        return ff

    ff = build(fused=True, steps=2)
    ckpt = str(tmp_path / "ck_fused")
    save_checkpoint(ff, ckpt)

    with pytest.raises(ValueError, match="'fused'.*'per_leaf'"):
        restore_checkpoint(build(fused=False, steps=0), ckpt)

    ff3 = build(fused=True, steps=0)
    assert restore_checkpoint(ff3, ckpt) == 2
    np.testing.assert_allclose(ff3.get_weights("out", "kernel"),
                               ff.get_weights("out", "kernel"), rtol=1e-6)
    l, _ = ff3._run_train_step(ff3._stage_batch())
    assert np.isfinite(float(l))


def test_checkpoint_sharded_fused_cross_topology_refused(tmp_path):
    """The sharded-fused flat state's element order is topology-dependent:
    restoring it onto a different mesh/sharding must be refused (silent
    moment-scrambling otherwise), while a params-only checkpoint restores
    into ANY optimizer layout unchecked."""
    from flexflow_tpu.runtime.checkpoint import (restore_checkpoint,
                                                 save_checkpoint)
    from flexflow_tpu.runtime.optimizer import ShardedFusedUpdate

    def build(mesh, fsdp="", fused=True, opt=True):
        cfg = FFConfig(batch_size=16, mesh_shape=dict(mesh), seed=9,
                       fused_optimizer=fused, fsdp_axis=fsdp)
        ff = FFModel(cfg)
        x = ff.create_tensor([16, 8], name="x")
        ff.dense(x, 8, name="out")
        ff.compile(SGDOptimizer(lr=0.05, momentum=0.9) if opt else None,
                   LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                   [MetricsType.METRICS_ACCURACY])
        rs = np.random.RandomState(0)
        SingleDataLoader(ff, x, rs.randn(32, 8).astype(np.float32))
        SingleDataLoader(ff, ff.label_tensor,
                         rs.randint(0, 8, (32, 1)).astype(np.int32))
        return ff

    ff = build({"data": 4}, fsdp="data")
    assert isinstance(ff.optimizer, ShardedFusedUpdate)
    ff._run_train_step(ff._stage_batch())
    ckpt = str(tmp_path / "ck_sf")
    save_checkpoint(ff, ckpt)

    # same layout kind, different topology -> refused with a clear error
    ff2 = build({"data": 2, "model": 2}, fsdp="model")
    assert isinstance(ff2.optimizer, ShardedFusedUpdate)
    with pytest.raises(ValueError, match="topology-dependent"):
        restore_checkpoint(ff2, ckpt)

    # identical topology -> restores
    ff3 = build({"data": 4}, fsdp="data")
    assert restore_checkpoint(ff3, ckpt) == 1

    # params-only checkpoint (optimizer=None) -> restores into a fused
    # model without tripping the layout guard
    ff4 = build({"data": 4}, fsdp="data", opt=False)
    ckpt2 = str(tmp_path / "ck_weights_only")
    save_checkpoint(ff4, ckpt2)
    ff5 = build({"data": 4}, fsdp="data")
    restore_checkpoint(ff5, ckpt2)
    np.testing.assert_allclose(ff5.get_weights("out", "kernel"),
                               ff4.get_weights("out", "kernel"), rtol=1e-6)


def test_profiler_per_op(tmp_path):
    from flexflow_tpu.runtime.profiler import export_taskgraph, profile_step

    ff, _ = build_and_train(tmp_path, steps=1)
    rs = np.random.RandomState(1)
    rows = profile_step(ff, {"x": rs.randn(32, 16).astype(np.float32)})
    assert {r["op"] for r in rows} == {"fc1", "out"}
    assert all(r["ms"] >= 0 for r in rows)

    dot = export_taskgraph(ff, str(tmp_path / "graph.dot"))
    content = open(dot).read()
    assert "fc1" in content and "->" in content


def test_in_situ_op_summary(tmp_path):
    """In-situ attribution (VERDICT r2 missing #4): the compiled PRODUCTION
    train step's instructions attribute back to graph ops through the
    named_scope metadata — forward and backward sides both present."""
    from flexflow_tpu.runtime.profiler import in_situ_op_summary

    ff, _ = build_and_train(tmp_path, steps=1)
    rows = in_situ_op_summary(ff, ff._stage_batch())
    by_op = {r["op"]: r for r in rows}
    assert "fc1" in by_op and "out" in by_op, rows
    assert by_op["fc1"]["fwd_instructions"] > 0
    assert by_op["fc1"]["bwd_instructions"] > 0


def test_launcher_single_host(tmp_path):
    import subprocess
    import sys

    script = tmp_path / "script.py"
    script.write_text(
        "import jax\nprint('NDEV', len(jax.devices()))\n")
    out = subprocess.run(
        [sys.executable, "-m", "flexflow_tpu.launcher", str(script),
         "--cpu-devices", "4"],
        capture_output=True, text=True, cwd="/root/repo",
        env={**os.environ, "JAX_PLATFORMS": ""})
    assert "NDEV 4" in out.stdout, out.stdout + out.stderr


def test_standalone_sim_script(tmp_path):
    """scripts/standalone_sim.py (analog of the reference's legacy
    scripts/simulator.cc standalone MCMC prototype) runs and emits a loadable
    strategy file."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = tmp_path / "s.txt"
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "standalone_sim.py"),
         "--model", "cnn", "--budget", "50", "--devices", "4",
         "--export", str(out)],
        capture_output=True, text=True, timeout=500)
    assert r.returncode == 0, r.stderr
    assert out.exists()
    from flexflow_tpu.parallel.strategy import load_strategies_from_file

    loaded = load_strategies_from_file(str(out))
    assert "conv1" in loaded


def test_auto_resume_and_model_checkpoint_callback(tmp_path):
    """auto_resume (preemption recovery, SURVEY §5.3 extension) + the
    ModelCheckpoint keras callback."""
    from flexflow_tpu.runtime.checkpoint import auto_resume

    ff, _ = build_and_train(tmp_path, steps=2)
    ckpt = str(tmp_path / "ar")
    assert auto_resume(ff, ckpt) == 0  # fresh start, no checkpoint yet
    from flexflow_tpu.runtime.checkpoint import save_checkpoint

    save_checkpoint(ff, ckpt)
    w = ff.get_weights("fc1", "kernel")

    ff2, _ = build_and_train(tmp_path, steps=0)
    assert auto_resume(ff2, ckpt) == 2
    np.testing.assert_allclose(ff2.get_weights("fc1", "kernel"), w, rtol=1e-6)

    # keras callback writes checkpoints every epoch
    from flexflow_tpu.keras import Sequential
    from flexflow_tpu.keras.callbacks import ModelCheckpoint
    from flexflow_tpu.keras.layers import Dense

    m = Sequential([Dense(8, activation="relu", input_shape=(16,)),
                    Dense(4)])
    m.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    rs = np.random.RandomState(0)
    cdir = str(tmp_path / "cb")
    m.fit(rs.randn(64, 16).astype(np.float32),
          rs.randint(0, 4, 64).astype(np.int32), epochs=2, batch_size=32,
          callbacks=[ModelCheckpoint(cdir)], verbose=False)
    from flexflow_tpu.runtime.checkpoint import latest_step

    assert latest_step(cdir) is not None


def test_device_resident_dataloader_stages_and_slices():
    """The ZC-resident analog path must actually engage: dataset staged on
    device once, next_batch returns a device array under the batch sharding
    (regression guard: a swallowed error here silently falls back to
    per-step host uploads)."""
    import jax

    from flexflow_tpu import (ActiMode, FFConfig, FFModel, LossType,
                              SGDOptimizer, SingleDataLoader)

    cfg = FFConfig(batch_size=16, mesh_shape={"data": 4})
    ff = FFModel(cfg)
    x = ff.create_tensor([16, 32], name="x")
    ff.dense(x, 8, name="out")
    ff.compile(SGDOptimizer(lr=0.1),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY, metrics=[])
    data = np.random.RandomState(0).randn(64, 32).astype(np.float32)
    dl = SingleDataLoader(ff, x, data)
    assert dl.device_eligible()
    assert dl._try_stage_on_device(), "device-resident staging must succeed"
    b = dl.next_batch()
    assert isinstance(b, jax.Array) and b.shape == (16, 32)
    np.testing.assert_allclose(np.asarray(b), data[:16], rtol=1e-6)
    # second batch advances
    np.testing.assert_allclose(np.asarray(dl.next_batch()), data[16:32],
                               rtol=1e-6)
    dl.unstage()
    assert dl._dev_data is None


def test_batch_metrics_ignore_index():
    """Token-accuracy pad mask (ADVICE r3): ignore_index excludes pad
    positions from both the correct count and the denominator."""
    import jax.numpy as jnp

    from flexflow_tpu.ffconst import LossType, MetricsType
    from flexflow_tpu.runtime.metrics import batch_metrics

    logits = jnp.asarray(np.eye(4, dtype=np.float32)[None])  # (1, 4, 4)
    labels = jnp.asarray([[0, 1, 9, 9]], jnp.int32)  # 2 real, 2 pad(=9)
    m = batch_metrics(LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                      [MetricsType.METRICS_ACCURACY], logits, labels,
                      ignore_index=9)
    assert int(m["accuracy_count"]) == 2 and int(m["accuracy_total"]) == 2
    m2 = batch_metrics(LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                       [MetricsType.METRICS_ACCURACY], logits, labels)
    assert int(m2["accuracy_total"]) == 4  # unmasked counts every position


def test_topk_sampling_exactly_k_on_ties():
    """Top-k filter keeps exactly k candidates even when logits tie with
    the k-th value; top_k >= vocab is a legal NO-OP (HF semantics —
    full-distribution sampling), not a crash (ADVICE r3 + r4)."""
    import jax
    import jax.numpy as jnp

    from flexflow_tpu.runtime.generation import Generator

    gen = object.__new__(Generator)  # sampling only — no model needed
    gen.temperature = 1.0
    gen.top_k = 2
    # four-way tie: a >=kth threshold filter would keep all four
    logits = jnp.zeros((512, 4), jnp.float32)
    tok, _ = gen._sample(logits, jax.random.PRNGKey(0))
    assert len(np.unique(np.asarray(tok))) <= 2, \
        "more than top_k distinct tokens sampled on a tie"

    # top_k >= vocab: must sample the FULL distribution (every token
    # reachable on a 4-way tie), identical to top_k=0
    for k in (4, 9999):
        gen = object.__new__(Generator)
        gen.temperature = 1.0
        gen.top_k = k
        tok, _ = gen._sample(logits, jax.random.PRNGKey(0))
        assert len(np.unique(np.asarray(tok))) == 4, \
            f"top_k={k} >= vocab should be a no-op (full distribution)"
