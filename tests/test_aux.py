"""Aux subsystem tests: checkpoint/resume, profiler, taskgraph export."""

import os

import numpy as np
import pytest

from flexflow_tpu import (ActiMode, FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)


def build_and_train(tmp, steps=3, mesh=None):
    cfg = FFConfig(batch_size=32, mesh_shape=mesh or {"data": 4})
    ff = FFModel(cfg)
    x = ff.create_tensor([32, 16], name="x")
    t = ff.dense(x, 32, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.dense(t, 4, name="out")
    ff.compile(SGDOptimizer(lr=0.05, momentum=0.9),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY])
    rs = np.random.RandomState(0)
    xd = rs.randn(256, 16).astype(np.float32)
    y = rs.randint(0, 4, (256, 1)).astype(np.int32)
    SingleDataLoader(ff, x, xd)
    SingleDataLoader(ff, ff.label_tensor, y)
    losses = []
    for _ in range(steps):
        batch = ff._stage_batch()
        l, _ = ff._run_train_step(batch)
        losses.append(float(l))
    return ff, losses


def test_checkpoint_roundtrip(tmp_path):
    from flexflow_tpu.runtime.checkpoint import (latest_step,
                                                 restore_checkpoint,
                                                 save_checkpoint)

    ff, _ = build_and_train(tmp_path, steps=3)
    ckpt_dir = str(tmp_path / "ckpt")
    save_checkpoint(ff, ckpt_dir)
    assert latest_step(ckpt_dir) == 3
    w_before = ff.get_weights("fc1", "kernel")

    # fresh model on a DIFFERENT mesh factorization restores correctly
    ff2, _ = build_and_train(tmp_path, steps=0, mesh={"data": 2, "model": 4})
    step = restore_checkpoint(ff2, ckpt_dir)
    assert step == 3
    np.testing.assert_allclose(ff2.get_weights("fc1", "kernel"), w_before,
                               rtol=1e-6)
    # momentum state restored too
    v = ff2.opt_state["v"]["fc1"]["kernel"]
    assert np.abs(np.asarray(v)).max() > 0

    # training continues from the restored state without error
    batch = ff2._stage_batch()
    l, _ = ff2._run_train_step(batch)
    assert np.isfinite(float(l))


def test_profiler_per_op(tmp_path):
    from flexflow_tpu.runtime.profiler import export_taskgraph, profile_step

    ff, _ = build_and_train(tmp_path, steps=1)
    rs = np.random.RandomState(1)
    rows = profile_step(ff, {"x": rs.randn(32, 16).astype(np.float32)})
    assert {r["op"] for r in rows} == {"fc1", "out"}
    assert all(r["ms"] >= 0 for r in rows)

    dot = export_taskgraph(ff, str(tmp_path / "graph.dot"))
    content = open(dot).read()
    assert "fc1" in content and "->" in content


def test_launcher_single_host(tmp_path):
    import subprocess
    import sys

    script = tmp_path / "script.py"
    script.write_text(
        "import jax\nprint('NDEV', len(jax.devices()))\n")
    out = subprocess.run(
        [sys.executable, "-m", "flexflow_tpu.launcher", str(script),
         "--cpu-devices", "4"],
        capture_output=True, text=True, cwd="/root/repo",
        env={**os.environ, "JAX_PLATFORMS": ""})
    assert "NDEV 4" in out.stdout, out.stdout + out.stderr
