"""Operator-placement execution tests (the SOAP 'O' axis).

The reference places different ops on disjoint GPUs via per-op device_ids
(config.h:47-69, mapper.cc:346-424). Here: strategies whose ParallelConfig
carries a proper device subset lower through PlacementExecutor — per-group
sub-mesh programs chained with device_put — and must match the single-mesh
executor's numerics exactly (same math, different placement).
"""

import numpy as np
import pytest

from flexflow_tpu import (ActiMode, FFConfig, FFModel, LossType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.parallel.pconfig import ParallelConfig
from flexflow_tpu.parallel.placement import (PlacementExecutor, has_placement,
                                             op_block)

MESH = {"data": 4, "model": 2}


def dp4(ndims=2, ids=None):
    pc = ParallelConfig.from_axis_map(ndims, MESH, {"data": 0, "model": None})
    if ids is not None:
        pc.device_ids = tuple(ids)
    return pc


def build_branchy(cfg):
    ff = FFModel(cfg)
    x = ff.create_tensor([cfg.batch_size, 64], name="x")
    a = ff.dense(x, 128, ActiMode.AC_MODE_RELU, name="a1")
    a = ff.dense(a, 128, name="a2")
    b = ff.dense(x, 128, ActiMode.AC_MODE_RELU, name="b1")
    b = ff.dense(b, 128, name="b2")
    t = ff.concat([a, b], axis=1, name="join")
    ff.dense(t, 8, name="head")
    return ff, x


def placement_strategies():
    return {
        "a1": dp4(), "a2": dp4(),
        "b1": dp4(ids=range(4, 8)), "b2": dp4(ids=range(4, 8)),
        "join": dp4(), "head": dp4(),
    }


def test_has_placement_and_op_block():
    assert not has_placement({"a": dp4()}, 8) or len(dp4().device_ids) < 8
    strats = placement_strategies()
    assert has_placement(strats, 8)
    place, ndev = op_block(strats["b1"], {"data": 0}, MESH, 8)
    assert (place, ndev) == (4, 4)
    place, ndev = op_block(strats["a1"], {"data": 0}, MESH, 8)
    assert (place, ndev) == (0, 4)
    # unaligned start snaps down to the block grid
    pc = dp4(ids=range(3, 7))
    assert op_block(pc, {"data": 0}, MESH, 8) == (0, 4)


def test_placement_groups_built():
    cfg = FFConfig(batch_size=16, mesh_shape=MESH)
    cfg.strategies.update(placement_strategies())
    ff, _ = build_branchy(cfg)
    ff.compile(optimizer=None, final_tensor=ff.ops[-1].outputs[0])
    assert isinstance(ff.executor, PlacementExecutor)
    groups = ff.executor.groups
    assert len(groups) >= 3  # a-block, b-block, join/head-block at least
    blocks = {op: (g.place, g.ndev) for g in groups for op in
              [o.name for o in g.ops]}
    assert blocks["a1"] == (0, 4) and blocks["b1"] == (4, 4)
    assert blocks["a1"][0] != blocks["b1"][0]


def test_placement_forward_matches_single_mesh():
    x_data = np.random.RandomState(0).randn(16, 64).astype(np.float32)

    def run(strategies):
        cfg = FFConfig(batch_size=16, mesh_shape=MESH, seed=7)
        cfg.strategies.update(strategies)
        ff, _ = build_branchy(cfg)
        ff.compile(optimizer=None, final_tensor=ff.ops[-1].outputs[0])
        return np.asarray(ff.predict({"x": x_data})), ff

    y_placed, ff_placed = run(placement_strategies())
    assert isinstance(ff_placed.executor, PlacementExecutor)
    y_single, ff_single = run({})
    assert not isinstance(ff_single.executor, PlacementExecutor)
    np.testing.assert_allclose(y_placed, y_single, rtol=1e-4, atol=1e-5)


def test_placement_training_matches_single_mesh():
    """Gradient parity: loss trajectories must match the single-mesh
    executor step for step (same seed, same data)."""
    rs = np.random.RandomState(1)
    x = rs.randn(64, 64).astype(np.float32)
    y = rs.randint(0, 8, (64, 1)).astype(np.int32)

    def losses(strategies, steps=4):
        cfg = FFConfig(batch_size=16, epochs=1, mesh_shape=MESH, seed=3)
        cfg.strategies.update(strategies)
        ff, xt = build_branchy(cfg)
        ff.compile(SGDOptimizer(lr=0.05),
                   LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
        SingleDataLoader(ff, xt, x)
        SingleDataLoader(ff, ff.label_tensor, y)
        out = []
        for _ in range(steps):
            batch = ff._stage_batch()
            loss, _ = ff._run_train_step(batch)
            out.append(float(loss))
        return out

    l_placed = losses(placement_strategies())
    l_single = losses({})
    np.testing.assert_allclose(l_placed, l_single, rtol=2e-4)
    assert l_placed[-1] < l_placed[0]  # it actually trains


def _alternating_a_block_strategies():
    """Each inception-A block's branches alternate device blocks:
    b1,b3 -> devices 0-3; b2,b4 -> devices 4-7."""
    left = lambda: dp4(ndims=4, ids=range(0, 4))      # noqa: E731
    right = lambda: dp4(ndims=4, ids=range(4, 8))     # noqa: E731
    out = {}
    for i in range(3):
        out[f"iA{i}_b1"] = left()
        out.update({f"iA{i}_b2{s}": right() for s in ("a", "b")})
        out.update({f"iA{i}_b3{s}": left() for s in ("a", "b", "c")})
        out.update({f"iA{i}_b4{s}": right() for s in ("a", "b")})
    return out


@pytest.mark.slow  # 28 s InceptionV3-scale; small-graph packing stays tier-1
def test_inception_full_tower_group_packing():
    """VERDICT r2 #6 (structure): the dependency-safe packer on the FULL
    InceptionV3 tower (75x75, the smallest input the D-block grid reduction
    survives). Alternating branches fragmented the old consecutive-run
    grouping into ~4 programs per A-block; the packer must emit ONE group
    per device block per A-block and pack the interleaved left branches
    with adjacent same-block ops."""
    from flexflow_tpu.models.cnn import inception_v3

    cfg = FFConfig(batch_size=8, mesh_shape=MESH, seed=11)
    cfg.strategies.update(_alternating_a_block_strategies())
    ff = FFModel(cfg)
    x, out = inception_v3(ff, 8, num_classes=10, image_size=75)
    ff.compile(optimizer=None, final_tensor=out)
    assert isinstance(ff.executor, PlacementExecutor)
    groups = ff.executor.groups
    blocks = {(g.place, g.ndev) for g in groups}
    assert (0, 4) in blocks and (4, 4) in blocks  # >=2 disjoint sub-meshes
    # each A-block's right-placed branches (b2a,b2b + b4a,b4b) pack into ONE
    # group; the old grouping split them (b3a-c intervene in insertion order)
    right_groups = [g for g in groups if g.place == 4]
    assert len(right_groups) == 3, [repr(g) for g in right_groups]
    for g in right_groups:
        assert len(g.ops) == 4, repr(g)
    # the whole 122-op graph runs as few programs
    assert len(groups) <= 8, [repr(g) for g in groups]


@pytest.mark.slow  # 29 s InceptionV3-scale; parity pinned by the small graphs
def test_inception_branchy_placement_grad_parity():
    """VERDICT r2 #6 (numerics): search-shaped placement training on the
    branchy InceptionV3 stem+3xA section (64x64 keeps two full train runs
    CI-sized) must match the single-mesh executor step for step."""
    from flexflow_tpu.models.cnn import inception_v3_stem

    rs = np.random.RandomState(5)
    x_dat = rs.randn(16, 3, 64, 64).astype(np.float32)
    y_dat = rs.randint(0, 10, (16, 1)).astype(np.int32)

    def losses(strats, steps=2):
        cfg = FFConfig(batch_size=8, mesh_shape=MESH, seed=11)
        cfg.strategies.update(strats)
        ff = FFModel(cfg)
        x, out = inception_v3_stem(ff, 8, num_classes=10, image_size=64)
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                   final_tensor=out)
        SingleDataLoader(ff, x, x_dat)
        SingleDataLoader(ff, ff.label_tensor, y_dat)
        out_losses = []
        for _ in range(steps):
            loss, _ = ff._run_train_step(ff._stage_batch())
            out_losses.append(float(loss))
        return out_losses, ff

    l_placed, ff_placed = losses(_alternating_a_block_strategies())
    assert isinstance(ff_placed.executor, PlacementExecutor)
    assert len([g for g in ff_placed.executor.groups if g.place == 4]) == 3
    l_single, ff_single = losses({})
    assert not isinstance(ff_single.executor, PlacementExecutor)
    np.testing.assert_allclose(l_placed, l_single, rtol=2e-4)
    assert l_placed[-1] < l_placed[0]  # it actually trains


def test_search_to_placement_execution_chain(tmp_path):
    """The full SOAP-O flow: the MCMC discovers an op-placement strategy on
    a branchy graph, compile() lowers it through PlacementExecutor, and a
    training step executes under it."""
    from flexflow_tpu.search.cost_model import CostModel
    from flexflow_tpu.search.csim import native_optimize
    from flexflow_tpu.search.machine import MachineModel
    from flexflow_tpu.parallel.strategy import (load_strategies_from_file,
                                                save_strategies_to_file)

    cfg = FFConfig(batch_size=32, mesh_shape=MESH)
    ff, x = build_branchy(cfg)

    # a tight per-chip HBM makes piling every op onto few devices pay the
    # over-capacity penalty (reference simulator.cc:595-620), so the
    # discovered optimum must spread ops across device blocks — the
    # placement regime this test exists to cover end to end
    machine = MachineModel(hbm_bytes=400e3)
    cost = CostModel(ff, MESH, machine=machine)
    best = native_optimize(ff, cost, MESH, budget=6000, alpha=0.05, seed=1)
    assert set(best) == {"a1", "a2", "b1", "b2", "join", "head"}
    assert has_placement(best, 8), \
        "seed/budget no longer yield an op placement; adjust so this test " \
        "keeps covering the placement-execution chain"
    # apply the found strategy and train one step under it
    cfg.strategies.update(best)
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY, metrics=[])
    assert isinstance(ff.executor, PlacementExecutor)

    rs = np.random.RandomState(0)
    SingleDataLoader(ff, x, rs.randn(64, 64).astype(np.float32))
    SingleDataLoader(ff, ff.label_tensor,
                     rs.randint(0, 8, (64, 1)).astype(np.int32))
    loss, _ = ff._run_train_step(ff._stage_batch())
    assert np.isfinite(float(loss))
    # strategy round-trips through the reference text schema with devices
    path = str(tmp_path / "strategy.txt")
    save_strategies_to_file(path, best)
    loaded = load_strategies_from_file(path)
    for name, pc in best.items():
        assert loaded[name].device_ids == tuple(pc.device_ids)


def test_tied_weights_same_group_placement():
    """tie_weights + placement (VERDICT r3 weak #6): composes when source
    and dest land in the SAME placement group — the group's one program
    resolves the tie and accumulates both gradient contributions. Loss
    trajectory must match the single-mesh executor exactly."""
    rs = np.random.RandomState(5)
    x = rs.randn(64, 64).astype(np.float32)
    y = rs.randint(0, 8, (64, 1)).astype(np.int32)

    def build_tied(cfg):
        ff = FFModel(cfg)
        xt = ff.create_tensor([cfg.batch_size, 64], name="x")
        a = ff.dense(xt, 64, ActiMode.AC_MODE_RELU, name="enc")
        a = ff.dense(a, 64, ActiMode.AC_MODE_RELU, name="dec")
        b = ff.dense(xt, 64, ActiMode.AC_MODE_RELU, name="other")
        t = ff.concat([a, b], axis=1, name="join")
        ff.dense(t, 8, name="head")
        ff.tie_weights("dec", "kernel", "enc", "kernel")
        return ff, xt

    def losses(strategies, steps=4):
        cfg = FFConfig(batch_size=16, epochs=1, mesh_shape=MESH, seed=3)
        cfg.strategies.update(strategies)
        ff, xt = build_tied(cfg)
        ff.compile(SGDOptimizer(lr=0.05),
                   LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
        SingleDataLoader(ff, xt, x)
        SingleDataLoader(ff, ff.label_tensor, y)
        out = []
        for _ in range(steps):
            loss, _ = ff._run_train_step(ff._stage_batch())
            out.append(float(loss))
        return out, ff

    # enc+dec on block 0-3 (one group); 'other' on 4-7
    placed = {
        "enc": dp4(), "dec": dp4(),
        "other": dp4(ids=range(4, 8)),
        "join": dp4(), "head": dp4(),
    }
    l_placed, ffp = losses(placed)
    assert isinstance(ffp.executor, PlacementExecutor)
    # tied dest has no storage of its own under placement either
    assert "kernel" not in ffp.params.get("dec", {})
    l_single, _ = losses({})
    np.testing.assert_allclose(l_placed, l_single, rtol=2e-4)
    assert l_placed[-1] < l_placed[0]


def test_tied_weights_cross_group_same_block_placement():
    """Sandwich shape (reviewer case): embedding-like source on block 0-3,
    a middle op on block 4-7, tied head back on block 0-3 — dependency
    ordering forces source and dest into DIFFERENT groups on the SAME
    block. The dest group takes the source weight as an extra input and
    its gradient contribution sums with the source group's; loss
    trajectory must match the single-mesh executor."""
    rs = np.random.RandomState(9)
    x = rs.randn(64, 64).astype(np.float32)
    y = rs.randint(0, 8, (64, 1)).astype(np.int32)

    def losses(strategies, steps=4):
        cfg = FFConfig(batch_size=16, epochs=1, mesh_shape=MESH, seed=3)
        cfg.strategies.update(strategies)
        ff = FFModel(cfg)
        xt = ff.create_tensor([16, 64], name="x")
        a = ff.dense(xt, 64, ActiMode.AC_MODE_RELU, name="enc")
        a = ff.dense(a, 64, ActiMode.AC_MODE_RELU, name="mid")
        a = ff.dense(a, 64, ActiMode.AC_MODE_RELU, name="dec")
        ff.dense(a, 8, name="head")
        ff.tie_weights("dec", "kernel", "enc", "kernel")
        ff.compile(SGDOptimizer(lr=0.05),
                   LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
        SingleDataLoader(ff, xt, x)
        SingleDataLoader(ff, ff.label_tensor, y)
        out = []
        for _ in range(steps):
            loss, _ = ff._run_train_step(ff._stage_batch())
            out.append(float(loss))
        return out, ff

    placed = {"enc": dp4(), "mid": dp4(ids=range(4, 8)),
              "dec": dp4(), "head": dp4()}
    l_placed, ffp = losses(placed)
    assert isinstance(ffp.executor, PlacementExecutor)
    genc = ffp.executor._op_group["enc"]
    gdec = ffp.executor._op_group["dec"]
    assert genc is not gdec, "sandwich did not split groups — vacuous test"
    assert (genc.place, genc.ndev) == (gdec.place, gdec.ndev)
    l_single, _ = losses({})
    np.testing.assert_allclose(l_placed, l_single, rtol=2e-4)
    assert l_placed[-1] < l_placed[0]


def test_tied_weights_cross_block_placement():
    """VERDICT r4 #5: a tie whose ops land on DIFFERENT device blocks now
    executes — the dest block's program receives the source weight via a
    per-step device_put broadcast, and the dest's gradient contribution
    moves back to the source block before summing (storage + optimizer
    state stay with the source). Loss trajectory must match the
    single-mesh executor; the plausible-LM shape: embedding-like source
    on block 0-3, tied head on block 4-7."""
    rs = np.random.RandomState(13)
    x = rs.randn(64, 64).astype(np.float32)
    y = rs.randint(0, 8, (64, 1)).astype(np.int32)

    def losses(strategies, steps=5):
        cfg = FFConfig(batch_size=16, epochs=1, mesh_shape=MESH, seed=3)
        cfg.strategies.update(strategies)
        ff = FFModel(cfg)
        xt = ff.create_tensor([16, 64], name="x")
        a = ff.dense(xt, 64, ActiMode.AC_MODE_RELU, name="enc")
        a = ff.dense(a, 64, ActiMode.AC_MODE_RELU, name="dec")
        ff.dense(a, 8, name="head")
        ff.tie_weights("dec", "kernel", "enc", "kernel")
        ff.compile(SGDOptimizer(lr=0.02),
                   LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
        SingleDataLoader(ff, xt, x)
        SingleDataLoader(ff, ff.label_tensor, y)
        out = []
        for _ in range(steps):
            loss, _ = ff._run_train_step(ff._stage_batch())
            out.append(float(loss))
        return out, ff

    placed = {"enc": dp4(), "dec": dp4(ids=range(4, 8)),  # different blocks
              "head": dp4(ids=range(4, 8))}
    l_placed, ffp = losses(placed)
    assert isinstance(ffp.executor, PlacementExecutor)
    genc = ffp.executor._op_group["enc"]
    gdec = ffp.executor._op_group["dec"]
    assert (genc.place, genc.ndev) != (gdec.place, gdec.ndev), \
        "ops landed on the same block — vacuous test"
    # storage stays with the source only
    assert "kernel" not in ffp.params.get("dec", {})
    l_single, _ = losses({})
    np.testing.assert_allclose(l_placed, l_single, rtol=2e-4)
    # 64 samples / batch 16 = 4 batches per epoch: step 4 revisits step
    # 0's batch — the tied model must have improved on it
    assert l_placed[4] < l_placed[0]


def test_host_placed_embedding_hetero_dlrm(tmp_path):
    """Heterogeneous placement (VERDICT r4 Missing #3): embeddings run on
    the HOST CPU backend via device_type=CPU strategies — the reference's
    hetero DLRM (dlrm_strategy_hetero.cc + embedding_avx2.cc CPU
    kernels). The host group gets its own 1-device CPU-backend mesh; the
    dense MLPs stay on the accelerator pool; loss parity vs the
    single-mesh executor; devtype survives a strategy-file round trip."""
    from flexflow_tpu.ffconst import AggrMode, DataType
    from flexflow_tpu.models.dlrm import dlrm
    from flexflow_tpu.parallel.strategy import (load_strategies_from_file,
                                                save_strategies_to_file)

    rs = np.random.RandomState(3)
    dense = rs.randn(32, 16).astype(np.float32)
    sparse = [rs.randint(0, 50, (32, 2)).astype(np.int32) for _ in range(2)]
    labels = rs.rand(32, 1).astype(np.float32)

    def losses(strategies, steps=4):
        cfg = FFConfig(batch_size=16, mesh_shape=MESH, seed=5)
        cfg.strategies.update(strategies)
        ff = FFModel(cfg)
        din, sins, out = dlrm(ff, 16, embedding_size=8,
                              embedding_entries=50, num_tables=2,
                              indices_per_table=2, dense_dim=16,
                              mlp_bot=(16, 8), mlp_top=(8, 1))
        ff.compile(SGDOptimizer(lr=0.05),
                   LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
                   final_tensor=out)
        SingleDataLoader(ff, din, dense)
        for s, arr in zip(sins, sparse):
            SingleDataLoader(ff, s, arr)
        SingleDataLoader(ff, ff.label_tensor, labels)
        out_l = []
        for _ in range(steps):
            loss, _ = ff._run_train_step(ff._stage_batch())
            out_l.append(float(loss))
        return out_l, ff

    hetero = {"emb_0": ParallelConfig.host(2),
              "emb_1": ParallelConfig.host(2)}
    l_host, ffh = losses(hetero)
    assert isinstance(ffh.executor, PlacementExecutor)
    g0 = ffh.executor._op_group["emb_0"]
    assert g0.devtype == "CPU"
    assert g0.mesh.devices.flat[0].platform == "cpu"
    # embedding weights live on the host mesh
    emb_w = ffh.params["emb_0"]["kernel"]
    assert list(emb_w.sharding.mesh.devices.flat) == \
        list(g0.mesh.devices.flat)
    l_single, _ = losses({})
    np.testing.assert_allclose(l_host, l_single, rtol=2e-4)

    # devtype CPU survives the reference text schema round trip
    path = str(tmp_path / "hetero.txt")
    save_strategies_to_file(path, hetero)
    back = load_strategies_from_file(path)
    assert back["emb_0"].device_type == "CPU"

    # sharded axis map + CPU placement is refused with a clear error
    bad = {"emb_0": ParallelConfig.host(2)}
    bad["emb_0"].axis_map = {"data": 0}
    cfg = FFConfig(batch_size=16, mesh_shape=MESH, seed=5)
    cfg.strategies.update(bad)
    ff = FFModel(cfg)
    din, sins, out = dlrm(ff, 16, embedding_size=8, embedding_entries=50,
                          num_tables=1, indices_per_table=2, dense_dim=16,
                          mlp_bot=(16, 8), mlp_top=(8, 1))
    with pytest.raises(NotImplementedError, match="device_type CPU"):
        ff.compile(SGDOptimizer(lr=0.05),
                   LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
                   final_tensor=out)
