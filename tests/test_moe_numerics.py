"""MoE routing numerics: with ample capacity, GShard dispatch/combine must
equal the dense top-k mixture computed directly (regression for the
slot-collision bug where different k-rounds reused the same capacity slot)."""

import numpy as np
import jax.numpy as jnp

from flexflow_tpu import FFConfig, FFModel


def dense_reference_moe(x, router, w_in, w_out, k):
    N, D = x.shape
    E = router.shape[1]
    logits = x @ router
    gates = np.exp(logits - logits.max(-1, keepdims=True))
    gates = gates / gates.sum(-1, keepdims=True)
    # top-k selection + renormalize
    order = np.argsort(-gates, axis=-1)[:, :k]
    y = np.zeros_like(x)
    for n in range(N):
        sel = order[n]
        g = gates[n, sel]
        g = g / g.sum()
        for gi, e in zip(g, sel):
            h = x[n] @ w_in[e]
            h = 0.5 * h * (1 + np.tanh(np.sqrt(2 / np.pi)
                                       * (h + 0.044715 * h ** 3)))
            y[n] += gi * (h @ w_out[e])
    return y


def test_moe_matches_dense_mixture_top2():
    N, D, E, F, K = 32, 8, 4, 16, 2
    cfg = FFConfig(batch_size=N, mesh_shape={"data": 1})
    ff = FFModel(cfg)
    x = ff.create_tensor([N, D], name="x")
    # capacity_factor huge => no token dropped => exact equality
    out = ff.moe(x, num_experts=E, hidden_dim=F, k=K, capacity_factor=100.0,
                 name="moe")
    ff.compile(optimizer=None, final_tensor=out)

    xv = np.random.RandomState(0).randn(N, D).astype(np.float32)
    got = np.asarray(ff.predict({"x": xv}))
    want = dense_reference_moe(
        xv, ff.get_weights("moe", "router"),
        ff.get_weights("moe", "w_in"), ff.get_weights("moe", "w_out"), K)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)


def test_moe_capacity_drops_tokens_not_slots():
    """With capacity 1 per expert and k=2, at most E slots total are used —
    outputs stay finite and no slot is double-filled (sums stay bounded)."""
    N, D, E, F = 16, 8, 2, 8
    cfg = FFConfig(batch_size=N, mesh_shape={"data": 1})
    ff = FFModel(cfg)
    x = ff.create_tensor([N, D], name="x")
    out = ff.moe(x, num_experts=E, hidden_dim=F, k=2, capacity_factor=0.01,
                 name="moe")  # capacity = 1
    assert ff.get_op_by_name("moe").capacity == 1
    ff.compile(optimizer=None, final_tensor=out)
    xv = np.random.RandomState(1).randn(N, D).astype(np.float32) * 5
    got = np.asarray(ff.predict({"x": xv}))
    assert np.isfinite(got).all()
    # at most E tokens can be served, rest are zero
    served = (np.abs(got).sum(-1) > 1e-6).sum()
    assert served <= E, f"{served} tokens served with only {E} slots"
