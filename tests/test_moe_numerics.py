"""MoE routing numerics: with ample capacity, GShard dispatch/combine must
equal the dense top-k mixture computed directly (regression for the
slot-collision bug where different k-rounds reused the same capacity slot)."""

import numpy as np
import jax.numpy as jnp

from flexflow_tpu import FFConfig, FFModel


def dense_reference_moe(x, router, w_in, w_out, k):
    N, D = x.shape
    E = router.shape[1]
    logits = x @ router
    gates = np.exp(logits - logits.max(-1, keepdims=True))
    gates = gates / gates.sum(-1, keepdims=True)
    # top-k selection + renormalize
    order = np.argsort(-gates, axis=-1)[:, :k]
    y = np.zeros_like(x)
    for n in range(N):
        sel = order[n]
        g = gates[n, sel]
        g = g / g.sum()
        for gi, e in zip(g, sel):
            h = x[n] @ w_in[e]
            h = 0.5 * h * (1 + np.tanh(np.sqrt(2 / np.pi)
                                       * (h + 0.044715 * h ** 3)))
            y[n] += gi * (h @ w_out[e])
    return y


def test_moe_matches_dense_mixture_top2():
    N, D, E, F, K = 32, 8, 4, 16, 2
    cfg = FFConfig(batch_size=N, mesh_shape={"data": 1})
    ff = FFModel(cfg)
    x = ff.create_tensor([N, D], name="x")
    # capacity_factor huge => no token dropped => exact equality
    out = ff.moe(x, num_experts=E, hidden_dim=F, k=K, capacity_factor=100.0,
                 name="moe")
    ff.compile(optimizer=None, final_tensor=out)

    xv = np.random.RandomState(0).randn(N, D).astype(np.float32)
    got = np.asarray(ff.predict({"x": xv}))
    want = dense_reference_moe(
        xv, ff.get_weights("moe", "router"),
        ff.get_weights("moe", "w_in"), ff.get_weights("moe", "w_out"), K)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)


def test_moe_capacity_drops_tokens_not_slots():
    """With capacity 1 per expert and k=2, at most E slots total are used —
    outputs stay finite and no slot is double-filled (sums stay bounded)."""
    N, D, E, F = 16, 8, 2, 8
    cfg = FFConfig(batch_size=N, mesh_shape={"data": 1})
    ff = FFModel(cfg)
    x = ff.create_tensor([N, D], name="x")
    out = ff.moe(x, num_experts=E, hidden_dim=F, k=2, capacity_factor=0.01,
                 name="moe")  # capacity = 1
    assert ff.get_op_by_name("moe").capacity == 1
    ff.compile(optimizer=None, final_tensor=out)
    xv = np.random.RandomState(1).randn(N, D).astype(np.float32) * 5
    got = np.asarray(ff.predict({"x": xv}))
    assert np.isfinite(got).all()
    # at most E tokens can be served, rest are zero
    served = (np.abs(got).sum(-1) > 1e-6).sum()
    assert served <= E, f"{served} tokens served with only {E} slots"


def test_sort_dispatch_matches_dense():
    """The O(N*k) sort-based dispatch must equal the dense (N,E,C) einsum
    path when capacity does not bind (same top-k, same renormalized gates,
    same aux loss)."""
    import jax.numpy as jnp
    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.ops.moe import MoE

    B, S, D = 4, 8, 16
    rs = np.random.RandomState(0)
    x = rs.randn(B, S, D).astype(np.float32)

    def run(dispatch):
        cfg = FFConfig(batch_size=B, mesh_shape={"data": 1}, seed=2)
        ff = FFModel(cfg)
        xt = ff.create_tensor([B, S, D], name="x")
        out = ff.moe(xt, num_experts=4, hidden_dim=32, k=2,
                     capacity_factor=8.0, dispatch=dispatch, name="moe")
        ff.compile(optimizer=None, final_tensor=out)
        return np.asarray(ff.predict({"x": x})), ff

    y_dense, ff1 = run("dense")
    y_sort, ff2 = run("sort")
    for w in ("router", "w_in", "w_out"):
        np.testing.assert_allclose(ff1.get_weights("moe", w),
                                   ff2.get_weights("moe", w))
    np.testing.assert_allclose(y_sort, y_dense, rtol=1e-4, atol=1e-5)


def test_sort_dispatch_capacity_drops_match_dense():
    """With a binding capacity both paths drop the SAME assignments (the
    round-major position rule)."""
    from flexflow_tpu import FFConfig, FFModel

    B, S, D = 4, 16, 8
    rs = np.random.RandomState(3)
    x = rs.randn(B, S, D).astype(np.float32)

    def run(dispatch):
        cfg = FFConfig(batch_size=B, mesh_shape={"data": 1}, seed=4)
        ff = FFModel(cfg)
        xt = ff.create_tensor([B, S, D], name="x")
        out = ff.moe(xt, num_experts=4, hidden_dim=16, k=2,
                     capacity_factor=0.5,  # capacity binds
                     dispatch=dispatch, name="moe")
        ff.compile(optimizer=None, final_tensor=out)
        return np.asarray(ff.predict({"x": x}))

    np.testing.assert_allclose(run("sort"), run("dense"), rtol=1e-4,
                               atol=1e-5)


def test_sort_dispatch_grads_flow():
    import jax
    import jax.numpy as jnp
    from flexflow_tpu import FFConfig, FFModel

    B, S, D = 2, 8, 8
    rs = np.random.RandomState(5)
    x = jnp.asarray(rs.randn(B, S, D).astype(np.float32))
    cfg = FFConfig(batch_size=B, mesh_shape={"data": 1}, seed=6)
    ff = FFModel(cfg)
    xt = ff.create_tensor([B, S, D], name="x")
    out = ff.moe(xt, num_experts=4, hidden_dim=16, k=2, dispatch="sort",
                 name="moe")
    ff.compile(optimizer=None, final_tensor=out)

    op = ff.get_op_by_name("moe")

    def loss(p):
        ys = op.forward(p, [x])
        return jnp.sum(ys[0] ** 2) + ys[1]

    g = jax.grad(loss)(ff.params["moe"])
    flat = jax.tree_util.tree_leaves(g)
    assert all(np.isfinite(np.asarray(a)).all() for a in flat)
    assert any(np.abs(np.asarray(a)).max() > 0 for a in flat)
