"""Strategy-search tests (analog of the reference's search smoke usage:
--budget N --export file, §3.3 of SURVEY.md)."""

import numpy as np

from flexflow_tpu import ActiMode, FFConfig, FFModel, LossType, MetricsType, SGDOptimizer
from flexflow_tpu.search.cost_model import CostModel
from flexflow_tpu.search.driver import (data_parallel_strategy, legal_axis_maps,
                                        optimize_strategies)


def build_wide_mlp(mesh_shape, batch=64):
    cfg = FFConfig(batch_size=batch, mesh_shape=mesh_shape)
    ff = FFModel(cfg)
    x = ff.create_tensor([batch, 1024], name="x")
    t = ff.dense(x, 8192, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.dense(t, 8192, ActiMode.AC_MODE_RELU, name="fc2")
    t = ff.dense(t, 16, name="out")
    return ff


def build_small_mlp(mesh_shape, batch=16):
    """fc1/fc2 share one per-shard signature (same shapes) — the fixture
    the measurement tests rely on for cache-twin behavior."""
    cfg = FFConfig(batch_size=batch, mesh_shape=mesh_shape)
    ff = FFModel(cfg)
    x = ff.create_tensor([batch, 32], name="x")
    t = ff.dense(x, 64, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.dense(t, 64, ActiMode.AC_MODE_RELU, name="fc2")
    t = ff.dense(t, 8, name="out")
    return ff


def test_legal_axis_maps_divisibility():
    ff = build_wide_mlp({"data": 4, "model": 2})
    op = ff.get_op_by_name("fc1")
    maps = legal_axis_maps(op, {"data": 4, "model": 2})
    for m in maps:
        for ax, d in m.items():
            if d is not None:
                assert op.outputs[0].dims[d] % {"data": 4, "model": 2}[ax] == 0


def test_search_beats_or_matches_dp():
    mesh = {"data": 4, "model": 2}
    ff = build_wide_mlp(mesh)
    cost = CostModel(ff, mesh)
    dp_time = cost.iteration_time(data_parallel_strategy(ff, mesh))
    best = optimize_strategies(ff, budget=300, mesh_shape=mesh, seed=1,
                               use_native=False)
    best_am = {name: pc.axis_map for name, pc in best.items()}
    best_time = cost.iteration_time(best_am)
    assert best_time <= dp_time * 1.0001, (best_time, dp_time)


def test_compile_with_budget_end_to_end(tmp_path):
    mesh = {"data": 4, "model": 2}
    cfg = FFConfig(batch_size=64, mesh_shape=mesh, search_budget=100,
                   export_strategy_file=str(tmp_path / "s.txt"))
    ff = FFModel(cfg)
    x = ff.create_tensor([64, 256], name="x")
    t = ff.dense(x, 2048, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.dense(t, 8, name="out")
    ff.compile(SGDOptimizer(lr=0.1),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY])
    # strategy file exported and non-trivial
    content = (tmp_path / "s.txt").read_text()
    assert content.splitlines()[0].strip() != "0"
    # one step trains without error under the discovered strategy
    from flexflow_tpu import SingleDataLoader

    xdat = np.random.RandomState(0).randn(128, 256).astype(np.float32)
    y = np.random.RandomState(0).randint(0, 8, (128, 1)).astype(np.int32)
    SingleDataLoader(ff, x, xdat)
    SingleDataLoader(ff, ff.label_tensor, y)
    batch = ff._stage_batch()
    loss, _ = ff._run_train_step(batch)
    assert np.isfinite(float(loss))


def test_measured_op_costs_feed_search():
    """search/measure.py (reference: measure_operator_cost,
    simulator.cc:296-316): real timings populate the cost table, signatures
    dedup across identical ops, and the search accepts the table."""
    from flexflow_tpu.search.cost_model import CostModel
    from flexflow_tpu.search.driver import (data_parallel_strategy,
                                            optimize_strategies)
    from flexflow_tpu.search.measure import measure_op_costs

    mesh = {"data": 2, "model": 2}
    ff = build_small_mlp(mesh)
    measured = measure_op_costs(ff, mesh, iters=2)
    assert measured, "no measurements produced"
    assert all(v > 0 for v in measured.values())
    # fc1 at full replication (shard shape == full shape) must be measured
    assert (("fc1", (16, 64)) in measured) or (("fc1", (8, 64)) in measured)

    cost = CostModel(ff, mesh, measured=measured)
    dp = cost.iteration_time(data_parallel_strategy(ff, mesh))
    assert np.isfinite(dp) and dp > 0
    best = optimize_strategies(ff, budget=30, mesh_shape=mesh,
                               measured=measured, use_native=False)
    assert set(best) == {"fc1", "fc2", "out"}


def test_measure_budget_sweeps_cached_twins():
    """Round-5: time_budget_s bounds wall-clock, but keys whose signature
    twin is already in the in-process cache must still carry the measured
    cost (identical computations priced inconsistently in one table would
    skew the MCMC ranking). With a warm cache, budget=0 must reproduce the
    unbudgeted table exactly — every entry a zero-cost cache hit."""
    from flexflow_tpu.search.measure import measure_op_costs

    mesh = {"data": 2, "model": 2}
    ff = build_small_mlp(mesh)
    full = measure_op_costs(ff, mesh, iters=1)
    assert full
    swept = measure_op_costs(ff, mesh, iters=1, time_budget_s=0.0)
    assert swept == full


def test_measure_loop_env_validation(monkeypatch):
    """FF_MEASURE_LOOP: integer respected, garbage rejected loudly (a
    typo'd knob silently defaulting would taint every table row)."""
    import pytest

    import flexflow_tpu.search.measure as M

    monkeypatch.setattr(M, "_LOOP_COUNT", None)
    monkeypatch.setenv("FF_MEASURE_LOOP", "7")
    assert M._loop_count() == 7
    monkeypatch.setattr(M, "_LOOP_COUNT", None)
    monkeypatch.setenv("FF_MEASURE_LOOP", "auto")
    with pytest.raises(ValueError, match="FF_MEASURE_LOOP"):
        M._loop_count()
    monkeypatch.setattr(M, "_LOOP_COUNT", None)


def test_analyze_costs_end_to_end(tmp_path):
    """measure_search_costs='analyze': compile-only XLA cost_analysis feeds
    the search through compile() and the run still trains."""
    cfg = FFConfig(batch_size=32, mesh_shape={"data": 2, "model": 2},
                   search_budget=50, measure_search_costs="analyze")
    ff = FFModel(cfg)
    x = ff.create_tensor([32, 64], name="x")
    t = ff.dense(x, 128, ActiMode.AC_MODE_RELU, name="fc1")
    ff.dense(t, 8, name="out")
    ff.compile(SGDOptimizer(lr=0.1),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY])
    rs = np.random.RandomState(0)
    loss, _ = ff._run_train_step(
        {"x": rs.randn(32, 64).astype(np.float32),
         "label": rs.randint(0, 8, (32, 1)).astype(np.int32)})
    assert np.isfinite(float(loss))


def test_attention_seq_dim_never_multi_axis():
    """single_axis_dims: the proposal space must not shard MHA's seq dim
    over two mesh axes — the ring/Ulysses lowering takes exactly one
    (VERDICT r3 validation-script fallout fix)."""
    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.search.driver import legal_axis_maps

    cfg = FFConfig(batch_size=8, mesh_shape={"data": 2, "model": 2})
    ff = FFModel(cfg)
    x = ff.create_tensor([8, 16, 64], name="x")
    ff.multihead_attention(x, x, x, 64, 4, name="mha")
    op = next(o for o in ff.ops if o.name == "mha")
    for m in legal_axis_maps(op, {"data": 2, "model": 2}):
        seq_axes = [a for a, d in m.items() if d == 1]
        assert len(seq_axes) <= 1, m


def test_native_search_snaps_tied_pair_to_one_block():
    """The annealer doesn't model tie_weights; its winner must still
    execute, so native_optimize snaps every tie-connected component onto
    one device block (PlacementExecutor refuses cross-block ties). Calls
    native_optimize directly — the optimize_strategies fallback to the
    Python annealer has no placement proposals and would make this
    vacuous."""
    from flexflow_tpu import ActiMode, FFConfig, FFModel
    from flexflow_tpu.search.cost_model import CostModel
    from flexflow_tpu.search.csim import native_optimize

    mesh_shape = {"data": 8}
    cfg = FFConfig(batch_size=16, mesh_shape=mesh_shape)
    ff = FFModel(cfg)
    x = ff.create_tensor([16, 64], name="x")
    a = ff.dense(x, 64, ActiMode.AC_MODE_RELU, name="enc")
    a = ff.dense(a, 64, ActiMode.AC_MODE_RELU, name="mid")
    a = ff.dense(a, 64, ActiMode.AC_MODE_RELU, name="dec")
    ff.dense(a, 8, name="head")
    ff.tie_weights("dec", "kernel", "enc", "kernel")

    cost = CostModel(ff, mesh_shape)
    try:
        best = {s: native_optimize(ff, cost, mesh_shape, 2000, 0.05, s)
                for s in range(4)}
    except (ImportError, OSError) as e:
        pytest.skip(f"native search core unavailable: {e}")
    for seed, st in best.items():
        s, d = st["enc"], st["dec"]
        blk = lambda pc: ((min(pc.device_ids), len(pc.device_ids))
                          if pc.device_ids else (0, 8))
        assert blk(s) == blk(d), \
            f"seed {seed}: tied pair on different blocks {blk(s)} {blk(d)}"


def test_snap_tied_blocks_multi_dest_fixpoint():
    """One source, two dests on three different blocks with different
    sharding degrees: the component resolves to ONE block that every
    member's degree divides (a pairwise pass would re-break the first
    pair when handling the second)."""
    from flexflow_tpu import ActiMode, FFConfig, FFModel
    from flexflow_tpu.parallel.pconfig import ParallelConfig
    from flexflow_tpu.search.csim import _snap_tied_blocks

    mesh_shape = {"data": 8}
    cfg = FFConfig(batch_size=16, mesh_shape=mesh_shape)
    ff = FFModel(cfg)
    x = ff.create_tensor([16, 64], name="x")
    a = ff.dense(x, 64, ActiMode.AC_MODE_RELU, name="enc")
    b = ff.dense(a, 64, ActiMode.AC_MODE_RELU, name="dec1")
    ff.dense(b, 64, ActiMode.AC_MODE_RELU, name="dec2")
    ff.tie_weights("dec1", "kernel", "enc", "kernel")
    ff.tie_weights("dec2", "kernel", "enc", "kernel")

    def pc(deg, start, n):
        p = ParallelConfig.from_axis_map(2, {"data": deg}, {"data": 0})
        p.device_ids = tuple(range(start, start + n))
        return p

    out = {"enc": pc(2, 0, 2), "dec1": pc(2, 2, 2), "dec2": pc(4, 4, 4)}
    _snap_tied_blocks(ff, out, 8)
    blocks = {(min(p.device_ids), len(p.device_ids)) for p in out.values()}
    assert len(blocks) == 1, blocks
    (start, n), = blocks
    for name, p in out.items():
        assert n % p.num_parts() == 0, (name, n, p.num_parts())
