"""2-process multi-controller test (VERDICT r1 'prove multi-host').

Spawns two controller processes through flexflow_tpu.launcher — each owns 4
virtual CPU devices, jax.distributed.initialize wires them (gloo CPU
collectives) — and trains a dp x tp model over the 8-device global mesh,
including the orbax sharded checkpoint save/restore round-trip (each host
writes/reads only its shards). The TPU-pod analog of the reference's
GASNet/MPI multi-node path with control replication (mapper.cc:267-282,
python/flexflow.py mpirun driver).
"""

import os
import re
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "multihost_worker.py")
SERVE_WORKER = os.path.join(REPO, "tests", "multihost_serve_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(worker, ckpt, timeout=400):
    """Launch two controller processes through flexflow_tpu.launcher and
    return their stdout, asserting both exited 0."""
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # workers set their own device counts
    env["JAX_PLATFORMS"] = ""
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = []
    for pid in range(2):
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "flexflow_tpu.launcher", worker,
             "--num-processes", "2", "--process-id", str(pid),
             "--coordinator", f"127.0.0.1:{port}",
             "--cpu-devices", "4", "--", ckpt],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=timeout)
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"
    return outs


@pytest.mark.slow  # 10 s 2-process smoke; the resilience CI tier runs it by name
def test_two_process_training_via_launcher(tmp_path):
    outs = _run_workers(WORKER, str(tmp_path / "ckpt"))
    losses = []
    for out in outs:
        m = re.search(r"MULTIHOST pid=\d+ loss=([0-9.]+)", out)
        assert m, out[-2000:]
        losses.append(float(m.group(1)))
        assert "ckpt=ok" in out, out[-2000:]
    # SPMD: both controllers computed the same global loss
    assert losses[0] == pytest.approx(losses[1], rel=1e-6)


@pytest.mark.slow  # 34 s 2-process smoke; training variant stays tier-1
def test_two_process_serving_restore_and_decode(tmp_path):
    """Multi-host SERVING leg (VERDICT r3 #9): train -> sharded checkpoint
    -> restore into a fresh model on the 2-process mesh -> KV-cache greedy
    decode under a TP strategy. Both controllers must produce bit-identical
    tokens — closing the train -> checkpoint -> serve story at the
    multi-controller tier the reference's control replication (§2.5)
    corresponds to."""
    outs = _run_workers(SERVE_WORKER, str(tmp_path / "ckpt_serve"),
                        timeout=500)
    token_rows = []
    for out in outs:
        m = re.search(r"MULTIHOST-SERVE pid=\d+ tokens=([0-9,]+)", out)
        assert m, out[-2000:]
        token_rows.append(m.group(1))
    assert token_rows[0] == token_rows[1], \
        f"controllers decoded different tokens:\n{token_rows[0]}\nvs\n" \
        f"{token_rows[1]}"
