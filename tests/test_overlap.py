"""Host-overlap step engine tests (runtime/pipeline_loader.py + the
dispatch-ahead fit() loop).

The contract under test: turning the overlap engine on changes WHERE the
host blocks, never WHAT gets computed — the loss trajectory is bitwise
identical to the synchronous loop, checkpoints taken mid-prefetch record
the exact consumed dataloader cursor (so resume stays bitwise too),
injected loader IO failures retry inside the worker without reordering
batches or deadlocking, and the warm step program never retraces across
prefetched committed batches.
"""

import os
import time

import numpy as np
import pytest

from flexflow_tpu import (ActiMode, FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.runtime import faultinject, resilience
from flexflow_tpu.runtime.checkpoint import latest_step, load_meta
from flexflow_tpu.runtime.pipeline_loader import PipelineLoader


@pytest.fixture(autouse=True)
def _fresh_fault_state(monkeypatch):
    monkeypatch.delenv("FF_FAULT", raising=False)
    faultinject.reset()
    resilience.reset_counters()
    yield
    faultinject.reset()


def _build(prefetch_depth, *, ckpt_dir="", dispatch_ahead=2, epochs=2,
           n=64, checkpoint_every=0, step_timeout_s=0.0):
    # device_resident_data=False pins the host-resident path the overlap
    # engine targets (device-resident datasets already slice on device);
    # native off so the SingleDataLoader cursor contract is what's tested
    cfg = FFConfig(batch_size=16, epochs=epochs, seed=3,
                   device_resident_data=False, native_dataloader=False,
                   prefetch_depth=prefetch_depth,
                   dispatch_ahead=dispatch_ahead,
                   checkpoint_dir=str(ckpt_dir),
                   checkpoint_every=checkpoint_every,
                   step_timeout_s=step_timeout_s)
    ff = FFModel(cfg)
    x = ff.create_tensor([16, 8], name="x")
    t = ff.dense(x, 16, ActiMode.AC_MODE_RELU, name="fc1")
    ff.dense(t, 4, name="out")
    ff.compile(SGDOptimizer(lr=0.1),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY])
    rs = np.random.RandomState(7)
    SingleDataLoader(ff, x, rs.randn(n, 8).astype(np.float32))
    SingleDataLoader(ff, ff.label_tensor,
                     rs.randint(0, 4, (n, 1)).astype(np.int32))
    return ff


def _fit_recording_losses(ff, **kw):
    """Run fit() recording every step's loss as a host float (the record
    wrapper syncs per step — it perturbs timing, never numerics)."""
    losses = []
    orig = ff._run_train_step

    def rec(batch, **kwargs):
        loss, mets = orig(batch, **kwargs)
        losses.append(float(loss))
        return loss, mets

    ff._run_train_step = rec
    ff.fit(verbose=False, **kw)
    ff._run_train_step = orig
    return losses


# ------------------------------------------------------- bitwise identity


def test_overlap_bitwise_identical_to_sync():
    ls_sync = _fit_recording_losses(_build(0))
    ls_overlap = _fit_recording_losses(_build(2))
    assert len(ls_sync) == 8  # 2 epochs x 4 batches
    assert ls_sync == ls_overlap, \
        "overlap loop must train the exact synchronous trajectory"
    # and with a different in-flight bound (including fully-throttled 0)
    assert _fit_recording_losses(_build(3, dispatch_ahead=0)) == ls_sync


def test_overlap_final_state_and_cursors_match_sync():
    ff_s, ff_o = _build(0), _build(2)
    ff_s.fit(verbose=False)
    ff_o.fit(verbose=False)
    np.testing.assert_array_equal(ff_s.get_weights("fc1"),
                                  ff_o.get_weights("fc1"))
    # stop() rewinds the pulled-ahead cursors to the consumed position:
    # after fit the loaders sit exactly where the sync loop left them
    assert ([dl.next_index for dl in ff_o._dataloaders]
            == [dl.next_index for dl in ff_s._dataloaders])
    assert ff_o._pipeline is None, "pipeline torn down at the end of fit"
    bd = ff_o.last_step_breakdown
    assert bd is not None and bd["overlap"] and bd["steps"] > 0
    assert 0.0 <= bd["host_wait_fraction"] <= 1.0


# ------------------------------------------- checkpoint / resume exactness


def test_kill_and_resume_under_prefetch_restores_exact_cursor(tmp_path,
                                                              monkeypatch):
    # preempt at step 5 = mid-epoch 2 (4 batches/epoch): the checkpoint
    # must record the CONSUMED cursor, not the prefetch worker's
    # pulled-ahead dl.next_index
    monkeypatch.setenv("FF_FAULT", "sigterm@step:5")
    faultinject.reset()
    ff = _build(2, ckpt_dir=tmp_path / "ov", epochs=4)
    ff.fit(verbose=False)
    assert ff._step_count == 5
    assert latest_step(str(tmp_path / "ov")) == 5
    meta = load_meta(str(tmp_path / "ov"), 5)
    assert meta["reason"] == "preempt"
    # sync-loop cursor after 5 batches of 16 over 64 samples: wrapped to 16
    assert meta["dataloaders"] == {"x": 16, "label": 16}

    # the same preemption on the SYNC loop records the identical cursor
    monkeypatch.setenv("FF_FAULT", "sigterm@step:5")
    faultinject.reset()
    ff_s = _build(0, ckpt_dir=tmp_path / "sync", epochs=4)
    ff_s.fit(verbose=False)
    assert load_meta(str(tmp_path / "sync"), 5)["dataloaders"] \
        == meta["dataloaders"]

    # resume under prefetch: remaining 11 steps bitwise-match an
    # uninterrupted synchronous run
    monkeypatch.delenv("FF_FAULT")
    faultinject.reset()
    ff2 = _build(2, ckpt_dir=tmp_path / "ov", epochs=4)
    ff2.fit(verbose=False)
    assert ff2._step_count == 16
    ref = _build(0, epochs=4)
    ref.fit(verbose=False)
    np.testing.assert_array_equal(ff2.get_weights("fc1"),
                                  ref.get_weights("fc1"))


def test_periodic_checkpoint_mid_prefetch_consistent(tmp_path):
    # a periodic save while the worker is pulled ahead must be internally
    # consistent: step counter, cursor and params all "as of step N"
    ff = _build(2, ckpt_dir=tmp_path, epochs=2, checkpoint_every=3)
    ff.fit(verbose=False)
    # periodic saves land at steps 1/4/7 (+ final 8); keep=3 retains 4/7/8
    meta = load_meta(str(tmp_path), 7)
    assert meta["step"] == 7
    # sync-loop cursor after 7 batches of 16 over 64 samples (3 into the
    # second epoch), NOT the worker's pulled-ahead position
    assert meta["dataloaders"] == {"x": 48, "label": 48}


# -------------------------------------------- fault injection in the worker


def test_io_fail_in_prefetch_thread_retries_in_order(monkeypatch):
    monkeypatch.setenv("FF_FAULT", "io_fail@loader:3")
    faultinject.reset()
    ff = _build(2)
    ff.fit(verbose=False)
    assert resilience.COUNTERS["retries"] >= 1
    assert ff._step_count == 8, "retry must not drop or duplicate batches"
    # the retried pull re-pulls the SAME batch: trajectory == no-fault run
    monkeypatch.delenv("FF_FAULT")
    faultinject.reset()
    ref = _build(0)
    ref.fit(verbose=False)
    np.testing.assert_array_equal(ff.get_weights("fc1"),
                                  ref.get_weights("fc1"))


def test_io_fail_exhausted_surfaces_on_training_thread(monkeypatch):
    # every retry attempt of one pull fails -> the worker parks the error
    # and fit raises instead of deadlocking on an empty queue
    monkeypatch.setenv("FF_FAULT", "io_fail@loader:2-5")
    faultinject.reset()
    ff = _build(2)
    with pytest.raises(RuntimeError, match="prefetch worker died"):
        ff.fit(verbose=False)
    assert ff._pipeline is None, "fit's finally must tear the pipeline down"


# -------------------------------------------------------- retrace flatness


def test_warm_step_program_never_retraces_across_prefetched_batches():
    ff = _build(2, epochs=4, n=96)
    if not hasattr(ff._train_step, "_cache_size"):
        pytest.skip("jit cache size introspection unavailable on this jax")
    # warmup: the first step traces once more when the freshly-initialized
    # (uncommitted) opt_state becomes the step's committed output — that
    # is the known pre-existing warmup shape, identical under sync
    ff._run_train_step(ff.executor.shard_batch(ff._stage_batch()))
    ff._run_train_step(ff.executor.shard_batch(ff._stage_batch()))
    warm = ff._train_step._cache_size()
    ff._reset_dataloaders()
    ff.fit(verbose=False)  # 4 epochs x 6 batches through the pipeline
    assert ff._train_step._cache_size() == warm, \
        "prefetched committed batches must reuse the warm executable"


def test_shard_batch_is_cached_and_pass_through():
    import jax

    ff = _build(0)
    raw = ff._stage_batch()
    sharded = ff.executor.shard_batch(raw)
    for v in sharded.values():
        assert isinstance(v, jax.Array) and v.committed
    # cached NamedSharding objects: same instance across calls
    sh1 = ff.executor.batch_sharding("x", 2)
    sh2 = ff.executor.batch_sharding("x", 2)
    assert sh1 is sh2
    # already-committed-correct arrays pass through untouched (no new put)
    again = ff.executor.shard_batch(sharded)
    for k in sharded:
        assert again[k] is sharded[k]


# ------------------------------------------------- pipeline loader directly


def test_pipeline_loader_order_epoch_break_and_cursor_rewind():
    ff = _build(0, n=96)
    pipe = PipelineLoader.from_loaders(ff, depth=3).start()
    try:
        ref = _build(0, n=96)
        expect = [ref._stage_batch() for _ in range(4)]
        for i in range(4):
            got = pipe.get(timeout=30)
            np.testing.assert_array_equal(np.asarray(got["x"]),
                                          expect[i]["x"])
        assert pipe.consumed_cursors() == {"x": 64, "label": 64}
        # give the worker a moment to prefetch ahead, then break the
        # epoch: cursors rewind to consumed, reset runs, prefetch resumes
        time.sleep(0.2)
        pipe.epoch_break(ff._reset_dataloaders)
        assert all(dl.next_index == 0 for dl in ff._dataloaders)
        got = pipe.get(timeout=30)
        np.testing.assert_array_equal(np.asarray(got["x"]), expect[0]["x"])
    finally:
        pipe.stop()
    # stop() after one consumed batch post-reset: cursor sits at 16
    assert all(dl.next_index == 16 for dl in ff._dataloaders)


def test_pipeline_depth_validation_and_config_knobs():
    with pytest.raises(ValueError, match="depth"):
        PipelineLoader(lambda: None, lambda b: b, depth=0)
    with pytest.raises(ValueError):
        FFConfig(prefetch_depth=-1)
    with pytest.raises(ValueError):
        FFConfig(dispatch_ahead=-1)


def test_native_loader_through_pipeline_multi_epoch():
    """The pipeline wraps the native threaded loader too (prefetch-shard
    on top of its host prefetch): end-of-epoch Nones park the worker,
    epoch_break resets + resumes it — 3 epochs must deliver exactly
    3 x num_batches steps."""
    from flexflow_tpu.runtime.native_loader import load_lib

    if load_lib() is None:
        pytest.skip("native dataloader unavailable (no g++)")
    cfg = FFConfig(batch_size=16, epochs=3, seed=3,
                   device_resident_data=False, native_dataloader=True,
                   dataloader_shuffle=True, prefetch_depth=2)
    ff = FFModel(cfg)
    x = ff.create_tensor([16, 8], name="x")
    t = ff.dense(x, 16, ActiMode.AC_MODE_RELU, name="fc1")
    ff.dense(t, 4, name="out")
    ff.compile(SGDOptimizer(lr=0.1),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY])
    rs = np.random.RandomState(7)
    SingleDataLoader(ff, x, rs.randn(64, 8).astype(np.float32))
    SingleDataLoader(ff, ff.label_tensor,
                     rs.randint(0, 4, (64, 1)).astype(np.int32))
    ff.fit(verbose=False)
    assert ff._step_count == 12
    assert ff.last_step_breakdown["overlap"]


# --------------------------------------- barriers / watchdog documentation


def test_sync_fit_has_single_warmup_barrier(monkeypatch):
    """Satellite contract: the epoch loop takes ONE warmup barrier (on the
    first step's loss) plus the single end-of-fit barrier — the former
    duplicated per-branch `block_until_ready(self.params)` syncs are
    gone."""
    import jax

    ff = _build(0)
    calls = []
    orig = jax.block_until_ready
    monkeypatch.setattr(jax, "block_until_ready",
                        lambda x: calls.append(1) or orig(x))
    ff.fit(verbose=False)
    assert len(calls) == 2, \
        f"expected warm + final barriers only, saw {len(calls)}"


def test_overlap_fit_healthy_under_watchdog(tmp_path):
    """The dispatch-ahead drain arms the supervisor watchdog on DEVICE
    progress; a healthy overlapped run completes without firing it."""
    ff = _build(2, ckpt_dir=tmp_path, step_timeout_s=30.0)
    ff.fit(verbose=False)
    assert ff._step_count == 8
    assert resilience.COUNTERS["watchdog_fires"] == 0
    assert latest_step(str(tmp_path)) == 8  # final checkpoint landed
