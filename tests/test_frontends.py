"""Frontend tests: Keras clone (sequential + functional + callbacks) and the
PyTorch-FX importer (.ff round-trip + numerics).

Mirrors the reference e2e tier (tests/multi_gpu_tests.sh runs keras/native/fx
examples) in-process."""

import numpy as np
import pytest


def make_blobs(n=512, d=16, classes=4, seed=0):
    rs = np.random.RandomState(seed)
    centers = rs.randn(classes, d) * 3
    y = rs.randint(0, classes, n)
    x = centers[y] + rs.randn(n, d)
    return x.astype(np.float32), y.astype(np.int32)


def test_keras_sequential_mlp():
    from flexflow_tpu.keras import Sequential
    from flexflow_tpu.keras.layers import Dense
    from flexflow_tpu.keras.optimizers import SGD

    m = Sequential([
        Dense(64, activation="relu", input_shape=(16,)),
        Dense(64, activation="relu"),
        Dense(4),
    ])
    m.compile(optimizer=SGD(learning_rate=0.1),
              loss="sparse_categorical_crossentropy", metrics=["accuracy"])
    x, y = make_blobs()
    perf = m.fit(x, y, epochs=5, verbose=False)
    assert perf.accuracy > 0.9


def test_keras_functional_multi_input_and_callbacks():
    from flexflow_tpu.keras import Model
    from flexflow_tpu.keras.layers import Concatenate, Dense, Input
    from flexflow_tpu.keras.callbacks import (EpochVerifyMetrics,
                                              ModelAccuracy, VerifyMetrics)

    a = Input((8,), name="ia")
    b = Input((8,), name="ib")
    t = Concatenate(axis=1)([a, b])
    t = Dense(64, activation="relu")(t)
    out = Dense(4)(t)
    m = Model(inputs=[a, b], outputs=out)
    m.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    x, y = make_blobs(d=16)
    perf = m.fit([x[:, :8], x[:, 8:]], y, epochs=8, verbose=False,
                 callbacks=[VerifyMetrics(ModelAccuracy.MNIST_MLP),
                            EpochVerifyMetrics(ModelAccuracy.MNIST_MLP)])
    assert perf.accuracy > 0.9


def test_keras_cnn_mnist_synthetic():
    from flexflow_tpu.keras import Sequential
    from flexflow_tpu.keras.layers import (Conv2D, Dense, Flatten,
                                           MaxPooling2D)
    from flexflow_tpu.keras.datasets import mnist

    (x, y), _ = mnist.load_data()
    x = x.reshape(-1, 1, 28, 28).astype(np.float32) / 255.0
    x, y = x[:1024], y[:1024]
    m = Sequential([
        Conv2D(8, 3, strides=2, padding="same", activation="relu",
               input_shape=(1, 28, 28)),
        MaxPooling2D(2),
        Flatten(),
        Dense(32, activation="relu"),
        Dense(10),
    ])
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    perf = m.fit(x, y, epochs=4, verbose=False)
    assert perf.accuracy > 0.8, perf.accuracy


def test_fx_roundtrip_mlp(tmp_path):
    torch = pytest.importorskip("torch")
    import torch.nn as nn

    from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType, SGDOptimizer
    from flexflow_tpu.torch.fx import torch_to_flexflow
    from flexflow_tpu.torch.model import PyTorchModel

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(16, 32)
            self.relu = nn.ReLU()
            self.fc2 = nn.Linear(32, 4)

        def forward(self, x):
            return self.fc2(self.relu(self.fc1(x)))

    net = Net()
    ff_file = str(tmp_path / "net.ff")
    torch_to_flexflow(net, ff_file)

    cfg = FFConfig(batch_size=8, mesh_shape={"data": 1})
    ff = FFModel(cfg)
    x = ff.create_tensor([8, 16], name="x")
    outs = PyTorchModel(ff_file).apply(ff, [x])
    assert len(outs) == 1
    ff.compile(optimizer=None, final_tensor=outs[0])

    # copy torch weights in and compare numerics
    ff.set_weights("fc1", "kernel", net.fc1.weight.detach().numpy().T)
    ff.set_weights("fc1", "bias", net.fc1.bias.detach().numpy())
    ff.set_weights("fc2", "kernel", net.fc2.weight.detach().numpy().T)
    ff.set_weights("fc2", "bias", net.fc2.bias.detach().numpy())
    xv = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    got = np.asarray(ff.predict({"x": xv}))
    with torch.no_grad():
        want = net(torch.from_numpy(xv)).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_fx_cnn_with_residual(tmp_path):
    torch = pytest.importorskip("torch")
    import torch.nn as nn

    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.torch.model import PyTorchModel

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.conv1 = nn.Conv2d(3, 8, 3, padding=1)
            self.bn = nn.BatchNorm2d(8)
            self.relu = nn.ReLU()
            self.conv2 = nn.Conv2d(8, 8, 3, padding=1)
            self.pool = nn.MaxPool2d(2)
            self.flat = nn.Flatten()
            self.fc = nn.Linear(8 * 4 * 4, 10)

        def forward(self, x):
            t = self.relu(self.bn(self.conv1(x)))
            t = t + self.conv2(t)
            return self.fc(self.flat(self.pool(t)))

    cfg = FFConfig(batch_size=4, mesh_shape={"data": 1})
    ff = FFModel(cfg)
    x = ff.create_tensor([4, 3, 8, 8], name="x")
    outs = PyTorchModel(model=Net()).apply(ff, [x])
    ff.compile(optimizer=None, final_tensor=outs[0])
    y = ff.predict({"x": np.zeros((4, 3, 8, 8), np.float32)})
    assert y.shape == (4, 10)


# ---- ONNX importer (duck-typed proto: the onnx package is not bundled) ------

class _FakeAttr:
    def __init__(self, name, type_, **kw):
        self.name, self.type = name, type_
        for k, v in kw.items():
            setattr(self, k, v)


class _FakeTensorInfo:
    def __init__(self, name, dims=()):
        self.name, self.dims = name, list(dims)


class _FakeNode:
    def __init__(self, op_type, inputs, outputs, name="", attrs=()):
        self.op_type, self.input, self.output = op_type, inputs, outputs
        self.name, self.attribute = name, list(attrs)


class _FakeGraph:
    def __init__(self, nodes, inputs, outputs, initializer):
        self.node, self.input, self.output = nodes, inputs, outputs
        self.initializer = initializer


class _FakeModel:
    def __init__(self, graph):
        self.graph = graph


def _mlp_proto():
    """input -> Gemm(512) -> Relu -> Gemm(10), Gemm weights as initializers
    with ONNX (out, in) layout."""
    nodes = [
        _FakeNode("Gemm", ["input", "w1", "b1"], ["h1"], name="gemm1"),
        _FakeNode("Relu", ["h1"], ["r1"], name="relu1"),
        _FakeNode("Gemm", ["r1", "w2", "b2"], ["out"], name="gemm2"),
    ]
    init = [_FakeTensorInfo("w1", (32, 16)), _FakeTensorInfo("b1", (32,)),
            _FakeTensorInfo("w2", (10, 32)), _FakeTensorInfo("b2", (10,))]
    return _FakeModel(_FakeGraph(
        nodes, [_FakeTensorInfo("input", (4, 16))],
        [_FakeTensorInfo("out")], init))


def test_onnx_import_mlp_forward():
    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.onnx import ONNXModel

    cfg = FFConfig(batch_size=4, mesh_shape={"data": 1})
    ff = FFModel(cfg)
    x = ff.create_tensor([4, 16], name="input")
    out = ONNXModel(_mlp_proto()).apply(ff, {"input": x})
    assert out.dims == (4, 10)
    ff.compile(optimizer=None, final_tensor=out)
    y = ff.predict({"input": np.zeros((4, 16), np.float32)})
    assert y.shape == (4, 10)


def test_onnx_keras_variant_builds():
    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.onnx import ONNXModelKeras

    cfg = FFConfig(batch_size=4, mesh_shape={"data": 1})
    ff = FFModel(cfg)
    x = ff.create_tensor([4, 16], name="input")
    out = ONNXModelKeras(_mlp_proto()).apply(ff, {"input": x})
    assert out.dims == (4, 10)


def test_dataset_provenance_recorded_and_stamped():
    """VERDICT r4 #9: every keras dataset load records real|synthetic and
    the gate callbacks stamp it into their output."""
    from flexflow_tpu.keras import datasets
    from flexflow_tpu.keras.callbacks import _data_provenance

    datasets.digits.load_data()
    datasets.mnist.load_data()  # offline image -> synthetic fallback
    prov = datasets.loaded_provenance()
    assert "digits=real" in prov
    assert "mnist=" in prov  # real if a cache exists, else synthetic
    assert _data_provenance() == prov
