"""Pallas paged-attention decode kernel (ops/pallas_kernels.py
paged_attention_fwd_pallas) and its routing knob
(FFConfig.paged_attention_impl).

Correctness anchors:
  * kernel vs the einsum page-gather oracle (bitwise the dense-cache
    attention) within kernel tolerance — decode (S=1), verify slab
    (S=K+1 with per-position frontiers), GQA head grouping, ragged
    row_len/prompt_pad, scrambled page tables, the inactive-slot
    scratch-page-0 state;
  * a full greedy serving run (prefix cache + speculation ON) is
    TOKEN-IDENTICAL between impl='pallas' and impl='einsum' — the kernel
    is a perf mechanism, never semantics;
  * the recompile counter stays flat under warm traffic with the kernel
    path enabled (the kernel does not break the one-program contract).

On CPU the kernel runs in interpret mode — the REAL kernel code path,
executed by every CI tier (the ISSUE-7 routing requirement).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.models.llama import llama_lm
from flexflow_tpu.ops.attention import resolve_paged_attention_impl
from flexflow_tpu.ops.pallas_kernels import paged_attention_fwd_pallas

VOCAB = 89
TOL = dict(rtol=2e-5, atol=2e-5)


@pytest.fixture(scope="module")
def ff():
    cfg = FFConfig(batch_size=2, mesh_shape={"data": 1})
    model = FFModel(cfg)
    # kv_heads=2 < heads=4: the GQA grouping is always exercised
    _, logits = llama_lm(model, 2, seq_len=16, hidden=64, layers=2,
                         heads=4, kv_heads=2, vocab_size=VOCAB)
    model.compile(final_tensor=logits)
    return model


@pytest.fixture(scope="module")
def attn(ff):
    return next(op for op in ff.ops
                if type(op).__name__ == "MultiHeadAttention")


def _pool(rs, attn, n_pages=10, page=4):
    return {
        "k": jnp.asarray(rs.randn(n_pages, page, attn.num_kv_heads,
                                  attn.qk_head_dim), jnp.float32),
        "v": jnp.asarray(rs.randn(n_pages, page, attn.num_kv_heads,
                                  attn.v_head_dim), jnp.float32),
    }


def _params(ff, attn):
    return {k: jnp.asarray(v) for k, v in ff.params[attn.name].items()}


def test_kernel_matches_einsum_decode_ragged_scrambled(ff, attn):
    """S=1 decode step over a deliberately non-identity page table with
    ragged row_len/prompt_pad: the kernel's online softmax must match
    the page-gather einsum (itself bitwise the dense-cache attention,
    tests/test_serving.py) to kernel tolerance."""
    rs = np.random.RandomState(3)
    pool = _pool(rs, attn)
    params = _params(ff, attn)
    table = jnp.asarray([[5, 2, 7, 1], [3, 6, 4, 8]], jnp.int32)
    x = jnp.asarray(rs.randn(2, 1, attn.q_in), jnp.float32)
    wp = jnp.asarray([9, 13], jnp.int32)
    rope = jnp.asarray([4, 7], jnp.int32)
    row_len = jnp.asarray([3, 7], jnp.int32)       # ragged true prompts
    pad = jnp.asarray([8, 8], jnp.int32)           # bucket-padded width
    out_e, cache_e = attn.paged_decode_forward(
        params, [x, x, x], pool, table, wp, rope, row_len, pad,
        impl="einsum")
    out_p, cache_p = attn.paged_decode_forward(
        params, [x, x, x], pool, table, wp, rope, row_len, pad,
        impl="pallas")
    np.testing.assert_allclose(np.asarray(out_e), np.asarray(out_p), **TOL)
    # the scatter half is shared code — the pools must be BITWISE equal
    for n in ("k", "v"):
        np.testing.assert_array_equal(np.asarray(cache_e[n]),
                                      np.asarray(cache_p[n]))


def test_kernel_matches_einsum_verify_slab(ff, attn):
    """S=4 speculative-verify slab: per-position write frontiers give
    in-slab causality; every position's context must match the oracle."""
    rs = np.random.RandomState(5)
    pool = _pool(rs, attn)
    params = _params(ff, attn)
    table = jnp.asarray([[5, 2, 7, 1], [3, 6, 4, 8]], jnp.int32)
    s = 4
    x = jnp.asarray(rs.randn(2, s, attn.q_in), jnp.float32)
    wp0 = jnp.asarray([9, 11], jnp.int32)
    # nondecreasing frontiers incl. the budget clamp (equal tail)
    wp = jnp.minimum(wp0[:, None] + jnp.arange(s)[None, :], 13)
    rope = jnp.asarray([4, 7], jnp.int32)
    row_len = jnp.asarray([3, 7], jnp.int32)
    pad = jnp.asarray([8, 8], jnp.int32)
    out_e, _ = attn.paged_verify_forward(
        params, [x, x, x], pool, table, wp, rope, row_len, pad,
        impl="einsum")
    out_p, _ = attn.paged_verify_forward(
        params, [x, x, x], pool, table, wp, rope, row_len, pad,
        impl="pallas")
    np.testing.assert_allclose(np.asarray(out_e), np.asarray(out_p), **TOL)


def test_kernel_inactive_slot_scratch_page(ff, attn):
    """The serving engine's inactive-slot state (zeroed table -> every
    write lands in scratch page 0, write_pos=row_len=prompt_pad=0): the
    kernel must produce the same finite output as the oracle — its live
    rule admits j=0, so the online softmax never divides by zero."""
    rs = np.random.RandomState(7)
    pool = _pool(rs, attn)
    params = _params(ff, attn)
    table = jnp.asarray([[5, 2, 7, 1], [0, 0, 0, 0]], jnp.int32)
    x = jnp.asarray(rs.randn(2, 1, attn.q_in), jnp.float32)
    wp = jnp.asarray([9, 0], jnp.int32)
    rope = jnp.asarray([4, 0], jnp.int32)
    row_len = jnp.asarray([3, 0], jnp.int32)
    pad = jnp.asarray([8, 0], jnp.int32)
    out_e, _ = attn.paged_decode_forward(
        params, [x, x, x], pool, table, wp, rope, row_len, pad,
        impl="einsum")
    out_p, _ = attn.paged_decode_forward(
        params, [x, x, x], pool, table, wp, rope, row_len, pad,
        impl="pallas")
    assert bool(jnp.isfinite(out_p).all())
    np.testing.assert_allclose(np.asarray(out_e), np.asarray(out_p), **TOL)


def test_kernel_live_pages_cover_prompt_past_frontier(ff, attn):
    """The live-page bound must honor BOTH halves of the live rule: a
    caller querying with write_pos INSIDE the prompt (write_pos <
    row_len — never produced by the serving engine, but legal at the op
    boundary) still attends the whole live prompt, j < row_len. A
    frontier-only bound would silently skip the prompt's tail pages."""
    rs = np.random.RandomState(23)
    pool = _pool(rs, attn)
    params = _params(ff, attn)
    table = jnp.asarray([[5, 2, 7, 1], [3, 6, 4, 8]], jnp.int32)
    x = jnp.asarray(rs.randn(2, 1, attn.q_in), jnp.float32)
    wp = jnp.asarray([5, 2], jnp.int32)            # frontier in page 1/0
    rope = jnp.asarray([5, 2], jnp.int32)
    row_len = jnp.asarray([14, 11], jnp.int32)     # prompt spans 4/3 pages
    pad = jnp.asarray([16, 16], jnp.int32)
    out_e, _ = attn.paged_decode_forward(
        params, [x, x, x], pool, table, wp, rope, row_len, pad,
        impl="einsum")
    out_p, _ = attn.paged_decode_forward(
        params, [x, x, x], pool, table, wp, rope, row_len, pad,
        impl="pallas")
    np.testing.assert_allclose(np.asarray(out_e), np.asarray(out_p), **TOL)


def test_kernel_vs_dense_cache_tolerance(ff, attn):
    """The ISSUE-7 pin: the kernel against decode_forward on the
    EQUIVALENT contiguous dense cache (the pre-paged ground truth) —
    one tolerance bound covering kernel + page-table lookup together."""
    rs = np.random.RandomState(11)
    params = _params(ff, attn)
    b, page, n_pages = 2, 4, 4
    max_len = page * n_pages
    kvh, dqk, dv = attn.num_kv_heads, attn.qk_head_dim, attn.v_head_dim
    dense = {"k": jnp.asarray(rs.randn(b, max_len, kvh, dqk), jnp.float32),
             "v": jnp.asarray(rs.randn(b, max_len, kvh, dv), jnp.float32)}
    x = jnp.asarray(rs.randn(b, 1, attn.q_in), jnp.float32)
    pos, prompt_pad = 9, 8
    rope = jnp.asarray([4, 7], jnp.int32)
    row_len = jnp.asarray([3, 7], jnp.int32)
    table = np.array([[5, 2, 7, 1], [3, 6, 4, 8]], np.int32)
    pool = {"k": jnp.zeros((10, page, kvh, dqk), jnp.float32),
            "v": jnp.zeros((10, page, kvh, dv), jnp.float32)}
    for row in range(b):
        for p in range(n_pages):
            for name in ("k", "v"):
                pool[name] = pool[name].at[table[row, p]].set(
                    dense[name][row, p * page:(p + 1) * page])
    out_d, _ = attn.decode_forward(
        params, [x, x, x], dense, pos, rope_pos=rope,
        row_lengths=row_len, prompt_len=prompt_pad)
    out_k, _ = attn.paged_decode_forward(
        params, [x, x, x], pool, jnp.asarray(table),
        jnp.full((b,), pos, jnp.int32), rope, row_len,
        jnp.full((b,), prompt_pad, jnp.int32), impl="pallas")
    np.testing.assert_allclose(np.asarray(out_d), np.asarray(out_k), **TOL)


def test_kernel_raw_entrypoint_gqa_rows(ff, attn):
    """Direct kernel call: the GQA row layout (query head h reads kv
    head h // group) must match _grouped_cache_attention's reshape —
    checked by feeding DISTINCT per-head queries through both paths."""
    rs = np.random.RandomState(13)
    b, s, h, kvh, d, page = 2, 2, 4, 2, attn.qk_head_dim, 4
    q = jnp.asarray(rs.randn(b, s, h, d), jnp.float32)
    pool = _pool(rs, attn, n_pages=9, page=page)
    table = jnp.asarray([[1, 2, 3], [4, 5, 6]], jnp.int32)
    wp = jnp.asarray([[6, 7], [9, 10]], jnp.int32)
    row_len = jnp.asarray([2, 5], jnp.int32)
    pad = jnp.asarray([4, 6], jnp.int32)
    scale = 0.37
    out = paged_attention_fwd_pallas(q, pool["k"], pool["v"], table, wp,
                                     row_len, pad, scale)
    # oracle: gather + grouped einsum (the _grouped_cache_attention math
    # with an explicit scale)
    max_len = table.shape[1] * page
    gk = pool["k"][table].reshape(b, max_len, kvh, d)
    gv = pool["v"][table].reshape(b, max_len, kvh, d)
    idx = jnp.arange(max_len)
    live = (idx[None, None, :] < row_len[:, None, None]) \
        | ((idx[None, None, :] >= pad[:, None, None])
           & (idx[None, None, :] <= wp[:, :, None]))
    qg = q.reshape(b, s, kvh, h // kvh, d)
    logits = jnp.einsum("bqkgd,bskd->bkgqs", qg, gk,
                        preferred_element_type=jnp.float32) * scale
    logits = jnp.where(live[:, None, None, :, :], logits,
                       jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1)
    want = jnp.einsum("bkgqs,bskd->bqkgd", probs, gv).reshape(b, s, h, d)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), **TOL)


def test_resolve_impl_knob(ff):
    """auto resolves per backend; bad values are rejected; the FFConfig
    knob validates."""
    want_auto = "pallas" if jax.default_backend() == "tpu" else "einsum"
    assert resolve_paged_attention_impl(None, ff.config) == want_auto
    assert resolve_paged_attention_impl("auto", None) == want_auto
    assert resolve_paged_attention_impl("pallas", ff.config) == "pallas"
    assert resolve_paged_attention_impl("einsum", None) == "einsum"
    with pytest.raises(ValueError, match="paged_attention_impl"):
        resolve_paged_attention_impl("cuda", None)
    with pytest.raises(ValueError, match="paged_attention_impl"):
        FFConfig(batch_size=2, mesh_shape={"data": 1},
                 paged_attention_impl="einsums")
    cfg = FFConfig.parse_args(["--batch-size", "2",
                               "--paged-attention-impl", "pallas"])
    assert cfg.paged_attention_impl == "pallas"


@pytest.mark.slow  # ~40 s: two engines, interpret-mode kernel; kernels CI tier
def test_serving_token_identity_pallas_vs_einsum(ff):
    """THE acceptance pin: a full greedy serving run — prefix cache ON,
    speculative decoding ON (self-draft: the accept path genuinely
    runs) — emits exactly the same token streams under impl='pallas'
    (interpret-mode kernel on CPU) and impl='einsum'."""
    rs = np.random.RandomState(17)
    system = rs.randint(1, VOCAB, (8,)).astype(np.int32)  # 2 shared pages
    prompts = [np.concatenate([system,
                               rs.randint(1, VOCAB, (L,)).astype(np.int32)])
               for L in (2, 5, 1, 4)] \
        + [rs.randint(1, VOCAB, (6,)).astype(np.int32)]
    outs = {}
    for impl in ("einsum", "pallas"):
        eng = ff.make_serving_engine(
            serve_slots=2, kv_page_size=4, max_seq_len=64,
            draft_model=ff, speculate_k=2, paged_attention_impl=impl)
        reqs = eng.run(prompts, max_new_tokens=5)
        assert [r.state for r in reqs] == ["done"] * len(prompts)
        outs[impl] = [np.asarray(r.tokens, np.int32) for r in reqs]
        st = eng.stats()
        assert st["paged_attention_impl"] == impl
        assert st["prefix_hits"] > 0 and st["spec_accepted"] > 0
        assert st["pages_touched"] > 0 and st["last_pages_touched"] >= 0
    for a, b in zip(outs["einsum"], outs["pallas"]):
        np.testing.assert_array_equal(
            a, b, err_msg="pallas paged-attention changed the greedy "
                          "token stream (must be a pure perf mechanism)")


@pytest.mark.slow  # ~20 s; kernels CI tier
def test_recompile_flat_with_pallas_impl(ff):
    """The one-program serving contract survives the kernel path: after
    bucket warmup, mixed same-bucket traffic through the pallas impl
    compiles nothing new."""
    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                 max_seq_len=64,
                                 paged_attention_impl="pallas")
    rs = np.random.RandomState(19)
    eng.run([rs.randint(1, VOCAB, (5,)).astype(np.int32),
             rs.randint(1, VOCAB, (12,)).astype(np.int32)],
            max_new_tokens=4)                     # warm buckets 8 + 16
    warm = eng.recompile_count
    eng.run([rs.randint(1, VOCAB, (n,)).astype(np.int32)
             for n in (6, 3, 9, 14, 2)], max_new_tokens=6)
    assert eng.recompile_count == warm, \
        "warm traffic with the pallas kernel path must not recompile"
    st = eng.stats()
    assert st["paged_attention_impl"] == "pallas"
    assert st["pages_touched"] > 0


# ---- paged prefill/append write kernel (ISSUE 18) -------------------------


def _quant_pool(rs, attn, n_pages=10, page=4):
    from flexflow_tpu.ops.attention import page_quantize, page_scale

    kf = jnp.asarray(rs.randn(n_pages, page, attn.num_kv_heads,
                              attn.qk_head_dim), jnp.float32)
    vf = jnp.asarray(rs.randn(n_pages, page, attn.num_kv_heads,
                              attn.v_head_dim), jnp.float32)
    ks, vs = page_scale(kf, 127.0), page_scale(vf, 127.0)
    return {
        "k": page_quantize(kf, ks, 127.0, jnp.int8),
        "v": page_quantize(vf, vs, 127.0, jnp.int8),
        "k_scale": ks, "v_scale": vs,
    }


@pytest.mark.slow  # interpret-mode kernel; kernels CI tier
@pytest.mark.parametrize("length", [5, 13, 16])
def test_prefill_write_kernel_bitwise_full_width(ff, attn, length):
    """The page-at-a-time VMEM scatter vs the einsum big-scatter oracle:
    BITWISE pool equality on every page — the written scatter list AND
    the untouched pages (the aliasing contract: a grid that only visits
    the scatter list must leave every other pool page's bytes alone).
    Ragged tails (length not a page multiple) pad exactly like the
    oracle."""
    rs = np.random.RandomState(11)
    pool = _pool(rs, attn)
    n_pages = -(-length // 4)
    kh = jnp.asarray(rs.randn(1, length, attn.num_kv_heads,
                              attn.qk_head_dim), jnp.float32)
    vh = jnp.asarray(rs.randn(1, length, attn.num_kv_heads,
                              attn.v_head_dim), jnp.float32)
    pages = np.asarray([7, 2, 9, 4][:n_pages], np.int32)
    # both arms jitted: that is how the serving prefill programs run
    # them, and what the bitwise contract is stated over
    out_e = jax.jit(lambda c, k, v: attn.paged_prefill_write(
        c, k, v, pages, impl="einsum"))(pool, kh, vh)
    out_p = jax.jit(lambda c, k, v: attn.paged_prefill_write(
        c, k, v, pages, impl="pallas"))(pool, kh, vh)
    for n in ("k", "v"):
        assert out_p[n].dtype == pool[n].dtype
        np.testing.assert_array_equal(np.asarray(out_e[n]),
                                      np.asarray(out_p[n]))
    # untouched pages kept the incoming pool bytes
    untouched = [p for p in range(10) if p not in pages.tolist()]
    np.testing.assert_array_equal(
        np.asarray(out_p["k"][np.asarray(untouched)]),
        np.asarray(pool["k"][np.asarray(untouched)]))


@pytest.mark.slow  # interpret-mode kernel; kernels CI tier
@pytest.mark.parametrize("length", [6, 16])
def test_prefill_write_kernel_bitwise_quantized(ff, attn, length):
    """Quantized pools: the kernel computes page_scale/page_quantize
    in-register (per-page amax over the slab tile) — payload AND scale
    planes must equal the oracle bitwise, scatter list and untouched
    pages alike (PR 11 published-state contract)."""
    rs = np.random.RandomState(13)
    pool = _quant_pool(rs, attn)
    n_pages = -(-length // 4)
    kh = jnp.asarray(rs.randn(1, length, attn.num_kv_heads,
                              attn.qk_head_dim), jnp.float32)
    vh = jnp.asarray(rs.randn(1, length, attn.num_kv_heads,
                              attn.v_head_dim), jnp.float32)
    pages = np.asarray([3, 8, 1, 6][:n_pages], np.int32)
    out_e = jax.jit(lambda c, k, v: attn.paged_prefill_write(
        c, k, v, pages, impl="einsum"))(pool, kh, vh)
    out_p = jax.jit(lambda c, k, v: attn.paged_prefill_write(
        c, k, v, pages, impl="pallas"))(pool, kh, vh)
    for n in ("k", "v", "k_scale", "v_scale"):
        assert out_p[n].dtype == pool[n].dtype
        np.testing.assert_array_equal(np.asarray(out_e[n]),
                                      np.asarray(out_p[n]))


@pytest.mark.slow  # builds engines; kernels CI tier
def test_prefill_tune_table_roundtrip(tmp_path, ff):
    """tune_paged_prefill persists a measured write-impl winner under
    the 'paged_prefill' kernel key; an 'auto' engine consults it at
    construction (lookup_paged_prefill_impl), keyed by the pool
    STORAGE dtype so int8 and full-width entries never shadow each
    other."""
    import os

    from flexflow_tpu.search import kernel_tune

    table = str(tmp_path / "ktune.json")
    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                 max_seq_len=32)
    op0 = eng.gen.attn_ops[0]
    rec = kernel_tune.tune_paged_prefill(
        page_size=eng.page_size, pages_per_slot=eng.pages_per_slot,
        head_dim=op0.qk_head_dim, kv_heads=op0.num_kv_heads,
        heads=op0.num_heads, slots=eng.slots, iters=1, path=table)
    assert rec["kernel"] == "paged_prefill"
    assert rec["impl"] in ("pallas", "einsum")
    got = kernel_tune.lookup_paged_prefill_impl(
        page_size=eng.page_size, pages_per_slot=eng.pages_per_slot,
        head_dim=op0.qk_head_dim, dtype=jnp.float32, batch=eng.slots,
        heads=op0.num_heads, path=table)
    assert got == rec["impl"]
    # dtype is in the key: the full-width entry must MISS for int8
    assert kernel_tune.lookup_paged_prefill_impl(
        page_size=eng.page_size, pages_per_slot=eng.pages_per_slot,
        head_dim=op0.qk_head_dim, dtype=jnp.int8, batch=eng.slots,
        heads=op0.num_heads, path=table) is None
    old = os.environ.get("FF_KERNEL_TUNE_TABLE")
    os.environ["FF_KERNEL_TUNE_TABLE"] = table
    try:
        kernel_tune.reload(table)
        eng2 = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                      max_seq_len=32,
                                      paged_attention_impl="auto")
        assert eng2.paged_prefill_impl == rec["impl"]
        # an explicit impl request bypasses the table
        eng3 = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                      max_seq_len=32,
                                      paged_attention_impl="pallas")
        assert eng3.paged_prefill_impl == "pallas"
    finally:
        if old is None:
            os.environ.pop("FF_KERNEL_TUNE_TABLE", None)
        else:
            os.environ["FF_KERNEL_TUNE_TABLE"] = old
