"""End-to-end training tests on the 8-device virtual CPU mesh.

Tier-2 of the reference test strategy (tests/multi_gpu_tests.sh +
accuracy_tests.sh): run real training, assert loss decreases / accuracy
reaches a gate, and verify hybrid strategies match data-parallel numerics
(the reference's grad-parity concern, SURVEY §7 hard part 3).
"""

import numpy as np
import jax
import pytest

from flexflow_tpu import (ActiMode, DataType, FFConfig, FFModel, LossType,
                          MetricsType, SGDOptimizer, AdamOptimizer,
                          SingleDataLoader)
from flexflow_tpu.parallel.pconfig import ParallelConfig


def make_blobs(n=512, d=16, classes=4, seed=0):
    rs = np.random.RandomState(seed)
    centers = rs.randn(classes, d) * 3
    y = rs.randint(0, classes, n)
    x = centers[y] + rs.randn(n, d)
    return x.astype(np.float32), y.astype(np.int32).reshape(n, 1)


def build_mlp(cfg, d=16, classes=4, hidden=32):
    ff = FFModel(cfg)
    x = ff.create_tensor([cfg.batch_size, d], name="x")
    t = ff.dense(x, hidden, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.dense(t, hidden, ActiMode.AC_MODE_RELU, name="fc2")
    t = ff.dense(t, classes, name="out")
    return ff, x


def test_mlp_trains_dp():
    cfg = FFConfig(batch_size=64, epochs=5)
    ff, xt = build_mlp(cfg)
    ff.compile(SGDOptimizer(lr=0.1),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY])
    x, y = make_blobs()
    SingleDataLoader(ff, xt, x)
    SingleDataLoader(ff, ff.label_tensor, y)
    perf = ff.fit(verbose=False)
    assert perf.accuracy > 0.9, f"accuracy {perf.accuracy}"


def test_mlp_trains_adam():
    cfg = FFConfig(batch_size=64, epochs=3)
    ff, xt = build_mlp(cfg)
    ff.compile(AdamOptimizer(alpha=0.01),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY])
    x, y = make_blobs()
    SingleDataLoader(ff, xt, x)
    SingleDataLoader(ff, ff.label_tensor, y)
    perf = ff.fit(verbose=False)
    assert perf.accuracy > 0.9, f"accuracy {perf.accuracy}"


def _train_losses(mesh_shape, strategies, steps=5, seed=0):
    """Train a fixed MLP for `steps` and return the loss sequence."""
    cfg = FFConfig(batch_size=64, epochs=1, seed=seed, mesh_shape=mesh_shape)
    cfg.strategies.update(strategies)
    ff, xt = build_mlp(cfg)
    ff.compile(SGDOptimizer(lr=0.05),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY])
    x, y = make_blobs(n=64 * steps)
    SingleDataLoader(ff, xt, x)
    SingleDataLoader(ff, ff.label_tensor, y)
    losses = []
    for _ in range(steps):
        batch = ff._stage_batch()
        loss, _ = ff._run_train_step(batch)
        losses.append(float(loss))
    return losses


def test_tensor_parallel_matches_data_parallel():
    """TP (out-channel split, the reference's parameter-parallel linear,
    linear.cu:144-269) must be numerically identical to DP."""
    dp = _train_losses({"data": 8}, {})
    tp_strategies = {
        "fc1": ParallelConfig.from_axis_map(2, {"data": 4, "model": 2},
                                            {"data": 0, "model": 1}),
        "fc2": ParallelConfig.from_axis_map(2, {"data": 4, "model": 2},
                                            {"data": 0, "model": 1}),
    }
    tp = _train_losses({"data": 4, "model": 2}, tp_strategies)
    np.testing.assert_allclose(dp, tp, rtol=2e-4, atol=2e-5)


def test_hybrid_on_1_device_matches():
    one = _train_losses({"data": 1}, {})
    dp = _train_losses({"data": 8}, {})
    np.testing.assert_allclose(one, dp, rtol=2e-4, atol=2e-5)


def test_strategy_file_roundtrip(tmp_path):
    from flexflow_tpu.parallel.strategy import (load_strategies_from_file,
                                                save_strategies_to_file)

    s = {
        "fc1": ParallelConfig(dims=(4, 2), device_ids=tuple(range(8))),
        "conv1": ParallelConfig(dims=(8, 1, 1, 1), device_ids=tuple(range(8))),
    }
    p = str(tmp_path / "strategy.txt")
    save_strategies_to_file(p, s)
    loaded = load_strategies_from_file(p)
    assert loaded["fc1"].dims == (4, 2)
    assert loaded["conv1"].dims == (8, 1, 1, 1)
    assert loaded["fc1"].device_ids == tuple(range(8))


def test_cnn_with_batchnorm_trains():
    cfg = FFConfig(batch_size=32, epochs=6)
    ff = FFModel(cfg)
    x = ff.create_tensor([32, 1, 8, 8], name="x")
    t = ff.conv2d(x, 8, 3, 3, 1, 1, 1, 1, name="c1")
    t = ff.batch_norm(t, relu=True)
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0)
    t = ff.flat(t)
    t = ff.dense(t, 4, name="out")
    ff.compile(SGDOptimizer(lr=0.05),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY])
    rs = np.random.RandomState(0)
    n = 256
    y = rs.randint(0, 4, n).astype(np.int32)
    xdat = (y[:, None, None, None] * 0.5
            + rs.randn(n, 1, 8, 8) * 0.3).astype(np.float32)
    SingleDataLoader(ff, x, xdat)
    SingleDataLoader(ff, ff.label_tensor, y.reshape(n, 1))
    perf = ff.fit(verbose=False)
    assert perf.accuracy > 0.8, f"accuracy {perf.accuracy}"


def test_bfloat16_mixed_precision_training():
    """compute_dtype='bfloat16': matmuls run in bf16 (MXU-native), master
    params stay f32, loss decreases (runtime/executor.py mixed-precision
    casts; autodiff through the casts yields f32 grads)."""
    import jax.numpy as jnp

    from flexflow_tpu import (ActiMode, FFConfig, FFModel, LossType,
                              MetricsType, SGDOptimizer)

    cfg = FFConfig(batch_size=32, mesh_shape={"data": 2},
                   compute_dtype="bfloat16", seed=3)
    ff = FFModel(cfg)
    x = ff.create_tensor([32, 16], name="x")
    t = ff.dense(x, 64, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.dense(t, 4, name="out")
    ff.compile(SGDOptimizer(lr=0.1),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY])
    assert ff.params["fc1"]["kernel"].dtype == jnp.float32  # master copy

    rs = np.random.RandomState(0)
    xd = rs.randn(32, 16).astype(np.float32)
    y = (xd[:, :4].argmax(1)).astype(np.int32).reshape(-1, 1)  # learnable
    losses = []
    for _ in range(30):
        loss, _ = ff._run_train_step({"x": xd, "label": y})
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0] * 0.7, losses[::10]


def test_corrupt_axismap_dim_index_raises_descriptive_error():
    """ADVICE r4: a hand-edited @axismap record with a dim index outside
    the op's rank must produce a descriptive ValueError, not a bare
    IndexError from deep inside degree re-derivation."""
    import pytest as _pytest

    from flexflow_tpu.parallel.pconfig import ParallelConfig
    from flexflow_tpu.runtime.executor import resolve_axis_map

    pc = ParallelConfig(dims=(2, 1), device_ids=(0, 1),
                        axis_map={"data": 5})  # dim 5 of a rank-2 tensor
    with _pytest.raises(ValueError, match="outside this op's rank 2"):
        resolve_axis_map(pc, {"data": 2}, ndims=2)
    # sentinels still pass through untouched
    pc2 = ParallelConfig(dims=(2, 1), device_ids=(0, 1),
                         axis_map={"data": 0, "model": -2})
    assert resolve_axis_map(pc2, {"data": 2, "model": 2}, ndims=2) \
        == {"data": 0, "model": -2}
