"""Long-context dense attention routing. The streaming Pallas flash kernels
have no sequence cap (K/V tiles stream through the grid; VMEM is O(block^2)),
so flash eligibility no longer depends on sequence length. Flash-refused
shapes (CPU backend, dropout, odd head dims) past BLOCKWISE_SEQ_THRESHOLD
fall back to the blockwise online-softmax scan with a rematerialized
backward — numerically equivalent to the einsum reference."""

import numpy as np
import pytest

import flexflow_tpu.ops.attention as attention_mod
from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.models.transformer import build_encoder_classifier


def _losses(seq, steps=2):
    batch, hidden, layers, heads = 2, 64, 1, 4
    cfg = FFConfig(batch_size=batch, mesh_shape={"data": 1})
    ff = FFModel(cfg)
    x, out = build_encoder_classifier(ff, batch, seq, hidden, layers, heads)
    ff.compile(SGDOptimizer(lr=0.05),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=out)
    rs = np.random.RandomState(0)
    SingleDataLoader(ff, x, rs.randn(batch * 2, seq, hidden)
                     .astype(np.float32))
    SingleDataLoader(ff, ff.label_tensor,
                     rs.randint(0, 16, (batch * 2, 1)).astype(np.int32))
    losses = []
    for _ in range(steps):
        loss, _ = ff._run_train_step(ff._stage_batch())
        losses.append(float(loss))
    return losses


def test_flash_has_no_sequence_cap(monkeypatch):
    """Streaming kernels: a 16k sequence must NOT be refused for length
    (it may still be refused for backend — check shape-gates only)."""
    cfg = FFConfig(batch_size=2, mesh_shape={"data": 1})
    ff = FFModel(cfg)
    x, out = build_encoder_classifier(ff, 2, 256, 64, 1, 4)
    attn = next(op for op in ff.ops
                if op.op_type.name == "OP_MULTIHEAD_ATTENTION")

    class FakeArr:
        def __init__(self, s):
            self.shape = (2, s, 4, 16)

    monkeypatch.setenv("FF_FORCE_FLASH_ATTENTION", "1")
    assert attn._flash_ok(FakeArr(4096), FakeArr(4096)) is True
    assert attn._flash_ok(FakeArr(16384), FakeArr(16384)) is True
    # non-128-divisible (above 128) still refused
    assert attn._flash_ok(FakeArr(129), FakeArr(129)) is False
    # deployment escape hatch still works
    monkeypatch.setenv("FF_FLASH_MAX_SEQ", "4096")
    assert attn._flash_ok(FakeArr(8192), FakeArr(8192)) is False


def test_blockwise_dense_fallback_matches_einsum(monkeypatch):
    """Lower the blockwise threshold so a CPU-sized sequence takes the
    blockwise branch; training losses must match the einsum path."""
    seq = 1024  # > patched threshold, % 512 == 0 -> blockwise branch
    baseline = _losses(seq)
    monkeypatch.setattr(attention_mod, "BLOCKWISE_SEQ_THRESHOLD", 512)
    blockwise = _losses(seq)
    np.testing.assert_allclose(baseline, blockwise, rtol=2e-4, atol=1e-5)


@pytest.mark.slow  # 13 s long-seq variant; shorter parity tests stay tier-1
def test_flash_streaming_parity_long_seq():
    """Interpret-mode grad parity of the streaming flash kernels at a
    sequence length past the old 4k cap (VERDICT r2 #2 acceptance)."""
    import jax
    import jax.numpy as jnp

    from flexflow_tpu.ops.pallas_kernels import flash_attention

    def ref_attn(q, k, v, causal, scale):
        logits = jnp.einsum("bqhk,bshk->bhqs", q, k).astype(jnp.float32)
        logits = logits * scale
        if causal:
            sq, sk = logits.shape[-2], logits.shape[-1]
            mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
            logits = jnp.where(mask, logits, -1e30)
        p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        return jnp.einsum("bhqs,bshk->bqhk", p, v)

    rs = np.random.RandomState(1)
    b, s, h, d = 1, 6144, 1, 32  # > old 4k cap, small enough for CI
    q = jnp.asarray(rs.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rs.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rs.randn(b, s, h, d), jnp.float32)
    scale = 1.0 / np.sqrt(d)
    o1 = flash_attention(q, k, v, True, scale)
    o2 = ref_attn(q, k, v, True, scale)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2),
                               rtol=1e-4, atol=1e-5)
    g1 = jax.grad(lambda a, b_, c: jnp.sum(jnp.sin(
        flash_attention(a, b_, c, True, scale))), argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda a, b_, c: jnp.sum(jnp.sin(
        ref_attn(a, b_, c, True, scale))), argnums=(0, 1, 2))(q, k, v)
    for x, y in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=1e-3, atol=5e-5)
