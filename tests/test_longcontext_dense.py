"""Long-context dense attention routing: flash is capped at FLASH_MAX_SEQ
(the Pallas backward stages the full opposing sequence in VMEM), and longer
dense sequences fall back to the blockwise online-softmax scan with a
rematerialized backward — numerically equivalent to the einsum reference."""

import numpy as np
import pytest

import flexflow_tpu.ops.attention as attention_mod
from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.models.transformer import build_encoder_classifier


def _losses(seq, steps=2):
    batch, hidden, layers, heads = 2, 64, 1, 4
    cfg = FFConfig(batch_size=batch, mesh_shape={"data": 1})
    ff = FFModel(cfg)
    x, out = build_encoder_classifier(ff, batch, seq, hidden, layers, heads)
    ff.compile(SGDOptimizer(lr=0.05),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=out)
    rs = np.random.RandomState(0)
    SingleDataLoader(ff, x, rs.randn(batch * 2, seq, hidden)
                     .astype(np.float32))
    SingleDataLoader(ff, ff.label_tensor,
                     rs.randint(0, 16, (batch * 2, 1)).astype(np.int32))
    losses = []
    for _ in range(steps):
        loss, _ = ff._run_train_step(ff._stage_batch())
        losses.append(float(loss))
    return losses


def test_flash_refused_beyond_max_seq():
    cfg = FFConfig(batch_size=2, mesh_shape={"data": 1})
    ff = FFModel(cfg)
    x, out = build_encoder_classifier(ff, 2, 256, 64, 1, 4)
    attn = next(op for op in ff.ops
                if op.op_type.name == "OP_MULTIHEAD_ATTENTION")

    class FakeArr:
        def __init__(self, s):
            self.shape = (2, s, 4, 16)

    ok_small = attn._flash_ok(FakeArr(attention_mod.FLASH_MAX_SEQ),
                              FakeArr(attention_mod.FLASH_MAX_SEQ))
    refused = attn._flash_ok(FakeArr(attention_mod.FLASH_MAX_SEQ * 2),
                             FakeArr(attention_mod.FLASH_MAX_SEQ * 2))
    assert refused is False
    # small-seq verdict depends on backend (TPU-only kernel) — just type-check
    assert ok_small in (True, False)


def test_blockwise_dense_fallback_matches_einsum(monkeypatch):
    """Lower the flash cap so a CPU-sized sequence takes the blockwise
    branch; training losses must match the einsum path."""
    seq = 1024  # > patched cap, % 512 == 0 -> blockwise branch
    baseline = _losses(seq)
    monkeypatch.setattr(attention_mod, "FLASH_MAX_SEQ", 512)
    blockwise = _losses(seq)
    np.testing.assert_allclose(baseline, blockwise, rtol=2e-4, atol=1e-5)
