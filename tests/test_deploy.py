"""SLO-gated rolling deployment (runtime/deploy.py + serving/router hooks).

Correctness anchors:
  * drain() is no longer terminal: drain -> reopen -> serve works, and
    the reopened engine's tokens still equal solo generate;
  * weight versions partition the KV world: a prompt cached under
    version A admits COLD under version B (zero cross-version prefix
    hits — the version_ns salt, the ISSUE-14 adapter mechanism extended
    to ``(version, adapter)``), and post-swap tokens are identical to a
    reference model holding the new weights;
  * the registry refuses what it cannot prove: a corrupt/torn artifact
    (FF_FAULT corrupt_ckpt@publish) fails manifest verify and the deploy
    is REFUSED before any replica is touched;
  * a torn swap (FF_FAULT swap_fail@deploy) rolls the whole deploy back
    — the fleet ends on the version it started on, exactly-once;
  * swap_weights refuses an engine with live slots (a mid-stream weight
    change would corrupt in-flight decodes).

The canary-breach -> automatic-rollback drill (slow@canary under live
flood) lives in scripts/deploy_smoke.py, where real traffic feeds the
SLO windows; these tests cover every deploy state machine edge that
does not need a flood.
"""

import numpy as np
import pytest

import jax

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.models.llama import llama_lm
from flexflow_tpu.runtime import checkpoint, faultinject
from flexflow_tpu.runtime.deploy import (RollingDeployer,
                                         WeightArtifactRegistry)
from flexflow_tpu.runtime.serving import (DEFAULT_WEIGHT_VERSION,
                                          RadixPrefixCache, version_ns)

VOCAB = 61


@pytest.fixture(scope="module")
def ff():
    cfg = FFConfig(batch_size=2, mesh_shape={"data": 1})
    model = FFModel(cfg)
    _, logits = llama_lm(model, 2, seq_len=16, hidden=64, layers=2,
                         heads=4, kv_heads=2, vocab_size=VOCAB)
    model.compile(final_tensor=logits)
    return model


def _prompts(seed, lengths):
    rs = np.random.RandomState(seed)
    return [rs.randint(1, VOCAB, (L,)).astype(np.int32) for L in lengths]


def _bumped(params, scale=1.25):
    """A same-geometry tree with visibly different weights — 'v1'."""
    return jax.tree_util.tree_map(
        lambda x: (np.asarray(x) * scale).astype(np.asarray(x).dtype),
        params)


def _publish_bumped(ff, watch_dir, step, scale=1.25):
    """Publish a perturbed copy of the model's weights as v<step> and
    restore the model untouched — the test's 'new training run'."""
    reg = WeightArtifactRegistry(str(watch_dir))
    keep = ff.params
    ff.params = ff.executor.reshard_params(_bumped(keep, scale))
    try:
        version = reg.publish(ff, step=step)
    finally:
        ff.params = keep
    return reg, version


def _arm_fault(monkeypatch, spec):
    monkeypatch.setenv("FF_FAULT", spec)
    faultinject.reset()


# ---- version salt (pure host-side, no decode) -----------------------------


def test_version_ns_default_is_unsalted():
    """The construction version (and None/"") must produce the EXACT
    pre-deploy namespace — bare adapter — so a fleet that never deploys
    is bit-identical to the pre-ISSUE-17 trie; any other version salts
    the namespace (and thus the trie's first edge and the router's
    affinity key) per version."""
    for v in (None, "", DEFAULT_WEIGHT_VERSION):
        assert version_ns(v) is None
        assert version_ns(v, "lora-a") == "lora-a"
    assert version_ns("v3") == ("v3", None)
    assert version_ns("v3", "lora-a") == ("v3", "lora-a")
    toks = np.arange(1, 5, dtype=np.int32)
    keys = {RadixPrefixCache.first_chunk(toks, version_ns(v, None))
            for v in (DEFAULT_WEIGHT_VERSION, "v1", "v2")}
    assert len(keys) == 3, "versions must never collide on the trie key"
    # adapter x version compose: four distinct worlds
    keys = {RadixPrefixCache.first_chunk(toks, version_ns(v, a))
            for v in ("v0", "v1") for a in (None, "lora-a")}
    assert len(keys) == 4


def test_registry_publish_verify_load(ff, tmp_path):
    reg = WeightArtifactRegistry(str(tmp_path))
    assert reg.versions() == [] and reg.latest() is None
    assert reg.latest_intact() is None
    with pytest.raises(ValueError, match="reserved"):
        reg.publish(ff, step=0)  # v0 = construction weights
    v = reg.publish(ff, step=3)
    assert v == "v3"
    assert reg.versions() == ["v3"] and reg.latest() == "v3"
    assert reg.latest_intact() == "v3"
    reg.verify(v)  # intact
    host = reg.load_params(v)
    ref_leaves = jax.tree_util.tree_leaves(ff.params)
    got_leaves = jax.tree_util.tree_leaves(host)
    assert len(got_leaves) == len(ref_leaves)
    np.testing.assert_array_equal(np.asarray(got_leaves[0]),
                                  np.asarray(ref_leaves[0]))
    with pytest.raises(ValueError, match="v<step>"):
        reg.step_dir("release-candidate")
    with pytest.raises(ValueError, match="watch directory"):
        WeightArtifactRegistry("")


def test_corrupt_publish_refuses_deploy(ff, tmp_path, monkeypatch):
    """FF_FAULT corrupt_ckpt@publish:1 tears the artifact after it
    lands; verify must fail and the deploy must be REFUSED with zero
    replicas touched."""
    _arm_fault(monkeypatch, "corrupt_ckpt@publish:1")
    reg = WeightArtifactRegistry(str(tmp_path))
    v = reg.publish(ff, step=1)
    with pytest.raises(checkpoint.CheckpointCorruptError):
        reg.verify(v)
    assert reg.latest() == "v1" and reg.latest_intact() is None
    monkeypatch.delenv("FF_FAULT")
    faultinject.reset()

    router = ff.make_serving_router(replicas=2, serve_slots=2,
                                    kv_page_size=4, max_seq_len=64,
                                    start=False)
    try:
        dep = RollingDeployer(router, reg, canary_windows=0)
        report = dep.deploy("v1")
        assert report["state"] == "refused"
        assert "manifest" in report["error"] or report["error"]
        for eng in router.engines:
            assert eng.weight_version == DEFAULT_WEIGHT_VERSION
            assert eng.deploy_state == "serving"
            assert eng.stats()["weight_swaps"] == 0
        st = router.stats()
        assert st["swaps_completed"] == 0 and st["rollbacks"] == 0
        assert not st["deploying"]
    finally:
        router.close()


def test_deploy_completes_and_torn_swap_rolls_back(ff, tmp_path,
                                                   monkeypatch):
    """Idle-fleet state machine, no decode: a clean deploy moves every
    replica to v1 (one swap each, counters pinned); re-deploying the
    same version is a noop; a torn swap (swap_fail@deploy:1) on a later
    deploy rolls the fleet back to v1 exactly."""
    reg, v1 = _publish_bumped(ff, tmp_path, step=1)
    router = ff.make_serving_router(replicas=2, serve_slots=2,
                                    kv_page_size=4, max_seq_len=64,
                                    start=False)
    try:
        dep = RollingDeployer(router, reg, canary_windows=0)
        report = dep.deploy(v1)
        assert report["state"] == "completed"
        assert report["swapped"] == [0, 1]
        assert report["prior_versions"] == [DEFAULT_WEIGHT_VERSION] * 2
        for eng in router.engines:
            assert eng.weight_version == "v1"
            assert eng.deploy_state == "serving"
            assert eng._cache_ns(None) == ("v1", None)
            st = eng.stats()
            assert st["weight_swaps"] == 1
            assert st["weight_version"] == "v1"
        # the override actually carries the bumped weights
        leaf0 = jax.tree_util.tree_leaves(
            router.engines[0].gen._source_params())[0]
        ref0 = jax.tree_util.tree_leaves(ff.params)[0]
        np.testing.assert_allclose(np.asarray(leaf0),
                                   np.asarray(ref0) * 1.25, rtol=1e-5)
        st = router.stats()
        assert st["swaps_completed"] == 2 and st["rollbacks"] == 0
        assert [row["weight_version"] for row in st["per_replica"]] \
            == ["v1", "v1"]
        h = router.health()
        assert h["weight_versions"] == ["v1", "v1"]
        assert not h["deploying"]

        assert dep.deploy(v1)["state"] == "noop"

        # torn swap on the roll to v2: replica 0 restores itself, the
        # deployer rolls the fleet back — everyone ends on v1
        reg2, v2 = _publish_bumped(ff, tmp_path, step=2, scale=1.5)
        _arm_fault(monkeypatch, "swap_fail@deploy:1")
        report = dep.deploy(v2)
        assert report["state"] == "rolled_back"
        assert "swap on replica 0" in report["error"]
        assert report["bundle"] is None  # no flight-recorder dir set
        for eng in router.engines:
            assert eng.weight_version == "v1"
            assert eng.deploy_state == "serving"
        st = router.stats()
        assert st["rollbacks"] == 1
        assert not router._suspended[0] and not router._suspended[1]
    finally:
        monkeypatch.delenv("FF_FAULT", raising=False)
        faultinject.reset()
        router.close()


def test_drain_reopen_gate_without_decode(ff):
    """The admission-gate half of the reopen regression: drain() on an
    idle engine closes submit(), reopen() lifts it — no decode needed."""
    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                 max_seq_len=32)
    snap = eng.drain()
    assert snap["drained"] and snap["completed"] == 0
    with pytest.raises(RuntimeError, match="draining"):
        eng.submit(np.arange(1, 5, dtype=np.int32), 4)
    eng.reopen()
    req = eng.submit(np.arange(1, 5, dtype=np.int32), 4)
    assert req.state == "queued"
    eng.reopen()  # idempotent
    assert eng.stats()["weight_version"] == DEFAULT_WEIGHT_VERSION
    assert eng.stats()["deploy_state"] == "serving"


# ---- decode-carrying paths (deploy CI tier runs these) --------------------


@pytest.mark.slow  # 20 s; deploy CI tier runs the full file
def test_drain_reopen_serve_token_identity(ff):
    """drain -> reopen -> serve: the reopened engine serves again and
    its tokens still equal solo generate (ISSUE 17 satellite — drain
    used to be terminal)."""
    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                 max_seq_len=64)
    first = eng.run(_prompts(0, [5, 9]), max_new_tokens=4)
    assert [r.state for r in first] == ["done", "done"]
    eng.drain()
    eng.reopen()
    prompts = _prompts(1, [6, 11, 4])
    reqs = eng.run(prompts, max_new_tokens=6)
    assert [r.state for r in reqs] == ["done"] * 3
    for r in reqs:
        solo = ff.generate(r.prompt[None, :], max_new_tokens=6)
        np.testing.assert_array_equal(
            np.asarray(r.tokens, np.int32), solo[0, r.prompt.size:],
            err_msg=f"request {r.rid} diverged after drain->reopen")
    assert eng.stats()["completed"] == 5


@pytest.mark.slow  # 15 s; deploy CI tier runs the full file
def test_swap_weights_refuses_live_slots(ff):
    """A mid-stream weight change corrupts in-flight decodes: swapping
    with live slots must raise, and the engine must finish serving the
    in-flight request untouched afterwards."""
    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                 max_seq_len=64, decode_chunk=2)
    req = eng.submit(np.arange(1, 7, dtype=np.int32), 8)
    eng.step()  # admit + first chunk: the slot is live now
    assert eng.active.any()
    with pytest.raises(RuntimeError, match="live slots"):
        eng.swap_weights(None, "v9")
    assert eng.weight_version == DEFAULT_WEIGHT_VERSION
    while eng.step():
        pass
    assert req.state == "done"
    solo = ff.generate(req.prompt[None, :], max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(req.tokens, np.int32),
                                  solo[0, req.prompt.size:])


@pytest.mark.slow  # 40 s; deploy CI tier runs the full file
def test_version_salt_isolates_prefix_cache(ff, tmp_path):
    """The stale-KV kill shot: a prompt whose prefix is HOT under v0
    admits COLD after the swap to v1 (zero cross-version hits — new
    namespace AND the old one flushed), its tokens equal a reference
    model holding the v1 weights, and re-serving it under v1 hits its
    own freshly-cached pages."""
    reg, v1 = _publish_bumped(ff, tmp_path, step=1)
    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                 max_seq_len=64)
    shared = _prompts(7, [8])[0]
    eng.run([shared], max_new_tokens=4)
    base = eng.stats()
    eng.run([shared], max_new_tokens=4)
    warm = eng.stats()
    assert warm["prefix_hits"] == base["prefix_hits"] + 1, \
        "the v0 prefix must be hot before the swap"

    host = reg.load_params(v1)
    tree = ff.executor.reshard_params(host)
    eng.drain()
    eng.swap_weights(tree, v1)
    eng.reopen()
    assert eng.stats()["kv_pages_cached"] == 0, \
        "the swap must flush every v0 page"

    post = eng.stats()
    r1 = eng.run([shared], max_new_tokens=4)[0]
    after = eng.stats()
    assert after["prefix_hits"] == post["prefix_hits"], \
        "a v0-cached prefix must NOT hit under v1"
    # token identity vs a reference holding the v1 weights
    keep = ff.params
    ff.params = tree
    try:
        solo = ff.generate(shared[None, :], max_new_tokens=4)
    finally:
        ff.params = keep
    np.testing.assert_array_equal(np.asarray(r1.tokens, np.int32),
                                  solo[0, shared.size:],
                                  err_msg="post-swap tokens diverged "
                                          "from the v1 reference")
    # and v1's own cache works: the SAME prompt now hits under v1
    eng.run([shared], max_new_tokens=4)
    assert eng.stats()["prefix_hits"] == after["prefix_hits"] + 1


@pytest.mark.slow  # 45 s; deploy CI tier runs the full file
def test_ab_fleet_per_version_hit_accounting(ff, tmp_path):
    """Mid-roll A/B window: replica 0 on v1, replica 1 still on v0
    behind one router. Identical prompts route to a consistent home via
    the version-salted affinity key, prefix hits accrue ONLY inside one
    version's world, and streams are token-identical to that version's
    reference — never a splice of the two."""
    reg, v1 = _publish_bumped(ff, tmp_path, step=1)
    router = ff.make_serving_router(replicas=2, serve_slots=2,
                                    kv_page_size=4, max_seq_len=64)
    try:
        tree = ff.executor.reshard_params(reg.load_params(v1))
        # half a roll, by hand: replica 0 -> v1
        router.suspend_replica(0)
        while not router.replica_quiesced(0):
            pass
        router.engines[0].drain()
        router.engines[0].swap_weights(tree, v1)
        router.engines[0].reopen()
        router.resume_replica(0)
        assert [e.weight_version for e in router.engines] == ["v1", "v0"]

        shared = _prompts(9, [8])[0]
        first = router.run([shared], max_new_tokens=4, timeout=300)[0]
        home = first.replica
        rest = router.run([shared, shared], max_new_tokens=4,
                          timeout=300)
        assert [r.replica for r in rest] == [home, home], \
            "version-salted affinity must keep the prompt on its home"
        other = 1 - home
        assert router.engines[other].stats()["prefix_hits"] == 0, \
            "cross-version world leaked a prefix hit"
        assert router.engines[home].stats()["prefix_hits"] >= 1
        # token identity against the HOME replica's weights
        keep = ff.params
        if router.engines[home].weight_version == v1:
            ff.params = tree
        try:
            solo = ff.generate(shared[None, :], max_new_tokens=4)
        finally:
            ff.params = keep
        for r in [first] + rest:
            np.testing.assert_array_equal(
                np.asarray(r.tokens, np.int32), solo[0, shared.size:],
                err_msg=f"request {r.rid} spliced versions")
        st = router.stats()
        assert sorted(row["weight_version"]
                      for row in st["per_replica"]) == ["v0", "v1"]
    finally:
        router.close()


@pytest.mark.slow  # 35 s; deploy CI tier runs the full file
def test_rolling_deploy_on_live_fleet(ff, tmp_path):
    """End-to-end roll on a STARTED fleet (no flood — deploy_smoke owns
    that): warmup re-runs under the new weights, both replicas end on
    v1, zero recompiles during the swaps (same-geometry override), and
    post-deploy traffic matches the v1 reference."""
    reg, v1 = _publish_bumped(ff, tmp_path, step=1)
    router = ff.make_serving_router(replicas=2, serve_slots=2,
                                    kv_page_size=4, max_seq_len=64,
                                    start=False)
    try:
        warm = _prompts(3, [5, 9])
        router.warmup(warm, max_new_tokens=2)
        router.start()
        pre = [e.stats()["recompiles"] for e in router.engines]
        dep = RollingDeployer(router, reg, canary_windows=0)
        report = dep.deploy(v1, warmup_prompts=warm, max_new_tokens=2)
        assert report["state"] == "completed"
        assert [e.weight_version for e in router.engines] == ["v1", "v1"]
        post = [e.stats()["recompiles"] for e in router.engines]
        assert post == pre, \
            f"same-geometry swap must not retrace: {pre} -> {post}"
        prompts = _prompts(11, [6, 10, 4])
        reqs = router.run(prompts, max_new_tokens=4, timeout=300)
        assert [r.state for r in reqs] == ["done"] * 3
        tree = ff.executor.reshard_params(reg.load_params(v1))
        keep = ff.params
        ff.params = tree
        try:
            for r in reqs:
                solo = ff.generate(r.prompt[None, :], max_new_tokens=4)
                np.testing.assert_array_equal(
                    np.asarray(r.tokens, np.int32),
                    solo[0, r.prompt.size:],
                    err_msg=f"request {r.rid} not serving v1 weights")
        finally:
            ff.params = keep
        assert router.stats()["swaps_completed"] == 2
    finally:
        router.close()
