"""MFU levers (VERDICT r2 #4): bf16 master weights and the fused
residual-add + layernorm op. Numerics verified on the CPU mesh; the
bench ablates them on hardware via FF_BENCH_MASTER_DTYPE /
FF_BENCH_FUSED_LN."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.models.transformer import build_encoder_classifier


def _train(master_dtype="float32", use_fused_ln=False, steps=3,
           compute="float32"):
    cfg = FFConfig(batch_size=4, mesh_shape={"data": 1}, seed=2,
                   compute_dtype=compute, master_dtype=master_dtype,
                   use_fused_ln=use_fused_ln)
    ff = FFModel(cfg)
    x, out = build_encoder_classifier(ff, 4, 32, 64, 2, 4)
    ff.compile(SGDOptimizer(lr=0.05),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=out)
    rs = np.random.RandomState(0)
    SingleDataLoader(ff, x, rs.randn(8, 32, 64).astype(np.float32))
    SingleDataLoader(ff, ff.label_tensor,
                     rs.randint(0, 16, (8, 1)).astype(np.int32))
    losses = []
    for _ in range(steps):
        loss, _ = ff._run_train_step(ff._stage_batch())
        losses.append(float(loss))
    return losses, ff


@pytest.mark.slow  # 21 s; bf16 master also pinned by fused_optimizer_scanned_training_bitwise
def test_bf16_master_weights_train_and_store_bf16():
    losses, ff = _train(master_dtype="bfloat16", compute="bfloat16")
    kernels = [v for op in ff.params.values() for k, v in op.items()
               if k == "kernel"]
    assert kernels and all(w.dtype == jnp.bfloat16 for w in kernels)
    assert losses[-1] < losses[0]  # training still converges
    # f32 math inside the update: trajectories track the f32-master run
    ref, _ = _train(master_dtype="float32", compute="bfloat16")
    np.testing.assert_allclose(losses, ref, rtol=0.08)


def test_fused_add_layernorm_matches_unfused_ops():
    """The fused op's two outputs equal add + layer_norm run separately
    (same weights), forward and gradient."""
    from flexflow_tpu.ops.pallas_kernels import fused_add_layernorm

    rs = np.random.RandomState(3)
    x = jnp.asarray(rs.randn(64, 128), jnp.float32)
    r = jnp.asarray(rs.randn(64, 128), jnp.float32)
    scale = jnp.asarray(rs.rand(128) + 0.5, jnp.float32)
    bias = jnp.asarray(rs.randn(128), jnp.float32)

    def ref(x, r, scale, bias):
        s = x + r
        mean = jnp.mean(s, -1, keepdims=True)
        var = jnp.var(s, -1, keepdims=True)
        return s, (s - mean) * jax.lax.rsqrt(var + 1e-5) * scale + bias

    s1, y1 = fused_add_layernorm(x, r, scale, bias)
    s2, y2 = ref(x, r, scale, bias)
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), atol=1e-6)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), atol=1e-5)

    def loss_f(f):
        def inner(x, r, scale, bias):
            s, y = f(x, r, scale, bias)
            return jnp.sum(jnp.sin(y)) + jnp.sum(jnp.cos(s))
        return inner

    g1 = jax.grad(loss_f(fused_add_layernorm), argnums=(0, 1, 2, 3))(
        x, r, scale, bias)
    g2 = jax.grad(loss_f(ref), argnums=(0, 1, 2, 3))(x, r, scale, bias)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.slow  # 14 s; fused-LN is opt-in (benched as a loss at h=1024), kernel parity test stays
def test_fused_ln_transformer_trains():
    losses, ff = _train(use_fused_ln=True)
    assert losses[-1] < losses[0]
    names = [op.name for op in ff.ops]
    assert any(n.startswith("res1_ln2") for n in names)
    # same norm-parameter count as the unfused graph: 2L+1
    _, ff_ref = _train(use_fused_ln=False, steps=1)
    n_norm_params = sum(1 for op in ff.params.values() for k in op
                       if k in ("scale",))
    n_ref = sum(1 for op in ff_ref.params.values() for k in op
                if k in ("scale",))
    assert n_norm_params == n_ref


@pytest.mark.slow  # 14 s; fused-LN is opt-in, kernel parity test stays
def test_fused_ln_shard_mapped_under_dp(monkeypatch):
    """Multi-chip fused LN: the Pallas kernel runs per-shard inside
    shard_map under a sharded strategy (GSPMD cannot partition a Mosaic
    custom call); losses must match the single-device fused run exactly."""
    monkeypatch.setenv("FF_FORCE_FLASH_ATTENTION", "1")

    def losses(mesh):
        cfg = FFConfig(batch_size=8, mesh_shape=mesh, seed=4,
                       use_fused_ln=True)
        ff = FFModel(cfg)
        x, out = build_encoder_classifier(ff, 8, 32, 128, 1, 4)
        ff.compile(SGDOptimizer(lr=0.05),
                   LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                   [MetricsType.METRICS_ACCURACY], final_tensor=out)
        rs = np.random.RandomState(0)
        SingleDataLoader(ff, x, rs.randn(16, 32, 128).astype(np.float32))
        SingleDataLoader(ff, ff.label_tensor,
                         rs.randint(0, 16, (16, 1)).astype(np.int32))
        return [float(ff._run_train_step(ff._stage_batch())[0])
                for _ in range(3)]

    np.testing.assert_allclose(losses({"data": 1}), losses({"data": 4}),
                               rtol=2e-4)


# ---- fused optimizer update (VERDICT r3 #4) --------------------------------


def _rand_tree(rs, dtype=np.float32):
    mk = lambda *s: jnp.asarray(rs.randn(*s).astype(dtype))
    return {"a": {"kernel": mk(16, 8), "bias": mk(8)},
            "b": {"kernel": mk(8, 4), "bias": mk(4), "scale": mk(4)}}


@pytest.mark.parametrize("opt_kind,kwargs", [
    ("sgd", {}),
    ("sgd", {"momentum": 0.9, "nesterov": True, "weight_decay": 0.01}),
    ("adam", {"weight_decay": 0.01}),
])
@pytest.mark.parametrize("dtype", [np.float32, "bfloat16"])
def test_fused_update_bitwise_matches_per_leaf(opt_kind, kwargs, dtype):
    """FusedUpdate flattens leaves into one vector per dtype bucket; the
    elementwise formula is unchanged, so results must be BIT-identical to
    the per-leaf update across steps (incl. bf16 master storage)."""
    from flexflow_tpu.runtime.optimizer import (AdamOptimizer, FusedUpdate,
                                                SGDOptimizer)

    mk = lambda: (SGDOptimizer(lr=0.1, **kwargs) if opt_kind == "sgd"
                  else AdamOptimizer(alpha=0.01, **kwargs))
    rs = np.random.RandomState(0)
    np_dtype = np.float32 if dtype == "bfloat16" else dtype
    params = _rand_tree(rs, np_dtype)
    if dtype == "bfloat16":
        params = jax.tree.map(lambda a: a.astype(jnp.bfloat16), params)

    ref_opt, fused_opt = mk(), FusedUpdate(mk())
    p_ref, s_ref = params, ref_opt.init_state(params)
    p_fused, s_fused = params, fused_opt.init_state(params)
    for step in range(4):
        grads = _rand_tree(rs, np_dtype)
        if dtype == "bfloat16":
            grads = jax.tree.map(lambda a: a.astype(jnp.bfloat16), grads)
        p_ref, s_ref = jax.jit(ref_opt.update)(p_ref, grads, s_ref)
        p_fused, s_fused = jax.jit(fused_opt.update)(p_fused, grads, s_fused)
        for op in p_ref:
            for w in p_ref[op]:
                a, b = np.asarray(p_ref[op][w]), np.asarray(p_fused[op][w])
                assert a.dtype == b.dtype
                np.testing.assert_array_equal(a, b, err_msg=f"{op}/{w}@{step}")


def test_fused_optimizer_end_to_end_and_sharded_fallback():
    """FFConfig.fused_optimizer trains end-to-end (replicated weights) and
    falls back with a warning when the strategy shards a weight."""
    from flexflow_tpu.parallel.pconfig import ParallelConfig
    from flexflow_tpu.runtime.optimizer import FusedUpdate

    def build(mesh, strategies=None):
        cfg = FFConfig(batch_size=8, mesh_shape=mesh, seed=3,
                       fused_optimizer=True)
        if strategies:
            cfg.strategies.update(strategies)
        ff = FFModel(cfg)
        x = ff.create_tensor([8, 16], name="x")
        t = ff.dense(x, 32, name="fc1")
        ff.dense(t, 8, name="fc2")
        ff.compile(SGDOptimizer(lr=0.1),
                   LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                   [MetricsType.METRICS_ACCURACY])
        return ff

    rs = np.random.RandomState(0)
    ff = build({"data": 2})
    assert isinstance(ff.optimizer, FusedUpdate)
    SingleDataLoader(ff, ff.ops[0].outputs[0],
                     rs.randn(16, 16).astype(np.float32))
    SingleDataLoader(ff, ff.label_tensor,
                     rs.randint(0, 8, (16, 1)).astype(np.int32))
    ff.fit(epochs=2)

    # TP-sharded weight -> the shard-local fused update (VERDICT r4 #3:
    # the lever must not no-op exactly where it matters)
    from flexflow_tpu.runtime.optimizer import ShardedFusedUpdate

    tp = {"fc1": ParallelConfig.from_axis_map(
        2, {"data": 2, "model": 2}, {"data": 0, "model": 1})}
    ff2 = build({"data": 2, "model": 2}, tp)
    assert isinstance(ff2.optimizer, ShardedFusedUpdate)
    SingleDataLoader(ff2, ff2.ops[0].outputs[0],
                     rs.randn(16, 16).astype(np.float32))
    SingleDataLoader(ff2, ff2.label_tensor,
                     rs.randint(0, 8, (16, 1)).astype(np.int32))
    ff2.fit(epochs=2)  # trains end-to-end under TP


def _sharded_vs_per_leaf(mesh_shape, strategies=None, fsdp_axis="",
                         steps=4, master="float32"):
    """Train the same model with fused_optimizer on/off on a sharded mesh;
    return (losses_fused, losses_ref, params_fused, params_ref, opt_f)."""
    from flexflow_tpu.parallel.pconfig import ParallelConfig

    def build(fused):
        cfg = FFConfig(batch_size=8, mesh_shape=dict(mesh_shape), seed=5,
                       fused_optimizer=fused, master_dtype=master,
                       fsdp_axis=fsdp_axis)
        if strategies:
            cfg.strategies.update({k: ParallelConfig.from_axis_map(*v)
                                   for k, v in strategies.items()})
        from flexflow_tpu.ffconst import ActiMode

        ff = FFModel(cfg)
        x = ff.create_tensor([8, 16], name="x")
        t = ff.dense(x, 32, name="fc1", activation=ActiMode.AC_MODE_RELU)
        t = ff.dense(t, 32, name="fc2", activation=ActiMode.AC_MODE_RELU)
        ff.dense(t, 8, name="head")
        from flexflow_tpu import AdamOptimizer

        ff.compile(AdamOptimizer(alpha=0.01),
                   LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                   [MetricsType.METRICS_ACCURACY])
        rs = np.random.RandomState(1)
        SingleDataLoader(ff, x, rs.randn(16, 16).astype(np.float32))
        SingleDataLoader(ff, ff.label_tensor,
                         rs.randint(0, 8, (16, 1)).astype(np.int32))
        losses = [float(ff._run_train_step(ff._stage_batch())[0])
                  for _ in range(steps)]
        return losses, ff

    lf, ff_f = build(True)
    lr, ff_r = build(False)
    return lf, lr, ff_f, ff_r


@pytest.mark.parametrize("case", ["tp", "fsdp"])
def test_sharded_fused_update_bitwise_matches_per_leaf(case):
    """ShardedFusedUpdate (shard_map-local flatten) must be BIT-identical
    to the per-leaf update under TP and FSDP shardings — same elementwise
    formula, concat of local shards changes no values (VERDICT r4 #3)."""
    from flexflow_tpu.runtime.optimizer import ShardedFusedUpdate

    if case == "tp":
        strat = {"fc1": (2, {"data": 2, "model": 2}, {"data": 0, "model": 1}),
                 "fc2": (2, {"data": 2, "model": 2},
                         {"data": 0, "model": -2})}  # CONTRACT row-parallel
        lf, lr, ff_f, ff_r = _sharded_vs_per_leaf({"data": 2, "model": 2},
                                                  strat)
    else:
        lf, lr, ff_f, ff_r = _sharded_vs_per_leaf({"data": 4},
                                                  fsdp_axis="data")
    assert isinstance(ff_f.optimizer, ShardedFusedUpdate)
    np.testing.assert_array_equal(np.asarray(lf), np.asarray(lr))
    for op in ff_r.params:
        for w in ff_r.params[op]:
            a = np.asarray(ff_r.params[op][w])
            b = np.asarray(ff_f.params[op][w])
            assert a.dtype == b.dtype
            np.testing.assert_array_equal(a, b, err_msg=f"{op}/{w}")
    # per-device state bytes match the per-leaf layout: flat state is
    # sharded over ALL axes (each device persists only its slice)
    flat_m = ff_f.opt_state["m"]
    n_dev = ff_f.mesh.devices.size
    for dt, vec in flat_m.items():
        assert vec.addressable_shards[0].data.size * n_dev == vec.size, \
            f"flat state {dt} is not fully sharded"


def test_fused_grad_dtype_mismatch_buckets_by_param_dtype():
    """ADVICE r4: a grad leaf whose dtype differs from its param's must
    not misalign the dtype buckets (grads bucket by PARAM dtype) — and
    a full-precision f32 grad for a bf16 param is NOT rounded through
    bf16, so the result stays bit-identical to the per-leaf update."""
    from flexflow_tpu.runtime.optimizer import (AdamOptimizer, FusedUpdate)

    rs = np.random.RandomState(0)
    params = {"a": {"k": jnp.asarray(rs.randn(8, 4), jnp.float32)},
              "b": {"k": jnp.asarray(rs.randn(4), jnp.bfloat16)}}
    # grads dtypes SWAPPED vs params: independent bucketing would pair
    # a's grad with b's param (symmetric counts -> silent wrong pairing)
    grads = {"a": {"k": jnp.asarray(rs.randn(8, 4), jnp.bfloat16)},
             "b": {"k": jnp.asarray(rs.randn(4), jnp.float32)}}
    mk = lambda: AdamOptimizer(alpha=0.01, weight_decay=0.01)
    fused, ref = FusedUpdate(mk()), mk()
    pf, sf = params, fused.init_state(params)
    pr, sr = params, ref.init_state(params)
    for _ in range(3):
        pf, sf = jax.jit(fused.update)(pf, grads, sf)
        pr, sr = jax.jit(ref.update)(pr, grads, sr)
    for op in params:
        a, b = np.asarray(pr[op]["k"]), np.asarray(pf[op]["k"])
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(a, b, err_msg=op)


@pytest.mark.parametrize("opt_kind", ["sgd", "adam"])
@pytest.mark.parametrize("master", ["float32", "bfloat16"])
def test_fused_optimizer_scanned_training_bitwise(opt_kind, master):
    """train_scanned + FusedUpdate (the bench's chip-ablation path): the
    scanned multi-step program with the fused update must be bit-identical
    to the per-leaf update — a break here would burn the TPU ablation
    window."""
    from flexflow_tpu import AdamOptimizer

    def run(fused):
        cfg = FFConfig(batch_size=8, mesh_shape={"data": 1}, seed=4,
                       fused_optimizer=fused, master_dtype=master)
        ff = FFModel(cfg)
        x = ff.create_tensor([8, 16], name="x")
        t = ff.dense(x, 32, name="fc1")
        ff.dense(t, 8, name="fc2")
        opt = (SGDOptimizer(lr=0.05) if opt_kind == "sgd"
               else AdamOptimizer(alpha=0.01))
        ff.compile(opt, LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                   [MetricsType.METRICS_ACCURACY])
        rs = np.random.RandomState(0)
        SingleDataLoader(ff, x, rs.randn(32, 16).astype(np.float32))
        SingleDataLoader(ff, ff.label_tensor,
                         rs.randint(0, 8, (32, 1)).astype(np.int32))
        losses, _ = ff.train_scanned(6)
        return np.asarray(losses)

    np.testing.assert_array_equal(run(False), run(True))
