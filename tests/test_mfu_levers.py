"""MFU levers (VERDICT r2 #4): bf16 master weights and the fused
residual-add + layernorm op. Numerics verified on the CPU mesh; the
bench ablates them on hardware via FF_BENCH_MASTER_DTYPE /
FF_BENCH_FUSED_LN."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.models.transformer import build_encoder_classifier


def _train(master_dtype="float32", use_fused_ln=False, steps=3,
           compute="float32"):
    cfg = FFConfig(batch_size=4, mesh_shape={"data": 1}, seed=2,
                   compute_dtype=compute, master_dtype=master_dtype,
                   use_fused_ln=use_fused_ln)
    ff = FFModel(cfg)
    x, out = build_encoder_classifier(ff, 4, 32, 64, 2, 4)
    ff.compile(SGDOptimizer(lr=0.05),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=out)
    rs = np.random.RandomState(0)
    SingleDataLoader(ff, x, rs.randn(8, 32, 64).astype(np.float32))
    SingleDataLoader(ff, ff.label_tensor,
                     rs.randint(0, 16, (8, 1)).astype(np.int32))
    losses = []
    for _ in range(steps):
        loss, _ = ff._run_train_step(ff._stage_batch())
        losses.append(float(loss))
    return losses, ff


def test_bf16_master_weights_train_and_store_bf16():
    losses, ff = _train(master_dtype="bfloat16", compute="bfloat16")
    kernels = [v for op in ff.params.values() for k, v in op.items()
               if k == "kernel"]
    assert kernels and all(w.dtype == jnp.bfloat16 for w in kernels)
    assert losses[-1] < losses[0]  # training still converges
    # f32 math inside the update: trajectories track the f32-master run
    ref, _ = _train(master_dtype="float32", compute="bfloat16")
    np.testing.assert_allclose(losses, ref, rtol=0.08)


def test_fused_add_layernorm_matches_unfused_ops():
    """The fused op's two outputs equal add + layer_norm run separately
    (same weights), forward and gradient."""
    from flexflow_tpu.ops.pallas_kernels import fused_add_layernorm

    rs = np.random.RandomState(3)
    x = jnp.asarray(rs.randn(64, 128), jnp.float32)
    r = jnp.asarray(rs.randn(64, 128), jnp.float32)
    scale = jnp.asarray(rs.rand(128) + 0.5, jnp.float32)
    bias = jnp.asarray(rs.randn(128), jnp.float32)

    def ref(x, r, scale, bias):
        s = x + r
        mean = jnp.mean(s, -1, keepdims=True)
        var = jnp.var(s, -1, keepdims=True)
        return s, (s - mean) * jax.lax.rsqrt(var + 1e-5) * scale + bias

    s1, y1 = fused_add_layernorm(x, r, scale, bias)
    s2, y2 = ref(x, r, scale, bias)
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), atol=1e-6)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), atol=1e-5)

    def loss_f(f):
        def inner(x, r, scale, bias):
            s, y = f(x, r, scale, bias)
            return jnp.sum(jnp.sin(y)) + jnp.sum(jnp.cos(s))
        return inner

    g1 = jax.grad(loss_f(fused_add_layernorm), argnums=(0, 1, 2, 3))(
        x, r, scale, bias)
    g2 = jax.grad(loss_f(ref), argnums=(0, 1, 2, 3))(x, r, scale, bias)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_fused_ln_transformer_trains():
    losses, ff = _train(use_fused_ln=True)
    assert losses[-1] < losses[0]
    names = [op.name for op in ff.ops]
    assert any(n.startswith("res1_ln2") for n in names)
    # same norm-parameter count as the unfused graph: 2L+1
    _, ff_ref = _train(use_fused_ln=False, steps=1)
    n_norm_params = sum(1 for op in ff.params.values() for k in op
                       if k in ("scale",))
    n_ref = sum(1 for op in ff_ref.params.values() for k in op
                if k in ("scale",))
    assert n_norm_params == n_ref


def test_fused_ln_shard_mapped_under_dp(monkeypatch):
    """Multi-chip fused LN: the Pallas kernel runs per-shard inside
    shard_map under a sharded strategy (GSPMD cannot partition a Mosaic
    custom call); losses must match the single-device fused run exactly."""
    monkeypatch.setenv("FF_FORCE_FLASH_ATTENTION", "1")

    def losses(mesh):
        cfg = FFConfig(batch_size=8, mesh_shape=mesh, seed=4,
                       use_fused_ln=True)
        ff = FFModel(cfg)
        x, out = build_encoder_classifier(ff, 8, 32, 128, 1, 4)
        ff.compile(SGDOptimizer(lr=0.05),
                   LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                   [MetricsType.METRICS_ACCURACY], final_tensor=out)
        rs = np.random.RandomState(0)
        SingleDataLoader(ff, x, rs.randn(16, 32, 128).astype(np.float32))
        SingleDataLoader(ff, ff.label_tensor,
                         rs.randint(0, 16, (16, 1)).astype(np.int32))
        return [float(ff._run_train_step(ff._stage_batch())[0])
                for _ in range(3)]

    np.testing.assert_allclose(losses({"data": 1}), losses({"data": 4}),
                               rtol=2e-4)
