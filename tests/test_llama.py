"""Llama-family decoder pieces: RoPE, grouped-query attention, SwiGLU.

RoPE is pinned against an independently-written reference rotation; GQA is
pinned against plain MHA with the kv weights explicitly repeated; the full
llama_lm trains on a synthetic next-token task.
"""

import numpy as np
import pytest
import jax.numpy as jnp

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.models.llama import llama_lm
from flexflow_tpu.ops.attention import _apply_rope


def _rope_reference(x, theta):
    """Independent spelling: complex-number rotation per (position, pair)."""
    b, s, h, d = x.shape
    half = d // 2
    inv = theta ** (-np.arange(half) / half)
    ang = np.arange(s)[:, None] * inv[None, :]  # (s, half)
    zc = np.exp(1j * ang)  # (s, half)
    x1 = x[..., :half].astype(np.float64)
    x2 = x[..., half:].astype(np.float64)
    z = (x1 + 1j * x2) * zc[None, :, None, :]
    return np.concatenate([z.real, z.imag], axis=-1)


def test_rope_matches_complex_rotation():
    rs = np.random.RandomState(0)
    x = rs.randn(2, 16, 3, 8).astype(np.float32)
    got = np.asarray(_apply_rope(jnp.asarray(x), 10000.0))
    want = _rope_reference(x, 10000.0)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_rope_preserves_norm():
    # rotation is orthogonal: per-(b,s,h) vector norms are unchanged
    rs = np.random.RandomState(1)
    x = rs.randn(1, 64, 2, 16).astype(np.float32)
    y = np.asarray(_apply_rope(jnp.asarray(x), 10000.0))
    np.testing.assert_allclose(np.linalg.norm(y, axis=-1),
                               np.linalg.norm(x, axis=-1), rtol=1e-4)


def _attn_forward(num_kv_heads, weights=None):
    cfg = FFConfig(batch_size=2, mesh_shape={"data": 1})
    ff = FFModel(cfg)
    x = ff.create_tensor([2, 8, 32], name="x")
    out = ff.multihead_attention(x, x, x, 32, 4, causal=True, bias=False,
                                 num_kv_heads=num_kv_heads, name="attn")
    ff.compile(SGDOptimizer(lr=0.0),
               LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
               [], final_tensor=out)
    if weights is not None:
        for k, v in weights.items():
            ff.params["attn"][k] = jnp.asarray(v)
    rs = np.random.RandomState(2)
    batch = {"x": rs.randn(2, 8, 32).astype(np.float32)}
    return ff, ff.predict(batch)


def test_gqa_matches_mha_with_repeated_kv():
    # kv_heads=2 of 4 -> each kv head serves 2 query heads; explicitly
    # repeating the kv projections in a plain MHA must give the same output
    ff_g, out_g = _attn_forward(2)
    p = ff_g.params["attn"]
    rep = {
        "wq": np.asarray(p["wq"]),
        "wk": np.repeat(np.asarray(p["wk"]), 2, axis=1),
        "wv": np.repeat(np.asarray(p["wv"]), 2, axis=1),
        "wo": np.asarray(p["wo"]),
    }
    _, out_m = _attn_forward(4, weights=rep)
    np.testing.assert_allclose(np.asarray(out_g), np.asarray(out_m),
                               rtol=2e-5, atol=2e-5)
    # and the GQA projections really are smaller
    assert np.asarray(p["wk"]).shape == (32, 2, 8)


def test_gqa_rope_under_ring_sp_matches_dense():
    """GQA + RoPE are applied at the op level BEFORE the attention-path
    dispatch, so they must compose with the ring sequence-parallel
    lowering: seq-sharded output == single-device dense output."""
    from flexflow_tpu.parallel.pconfig import ParallelConfig

    B, S, D, H = 2, 32, 16, 4
    rs = np.random.RandomState(7)
    x = rs.randn(B, S, D).astype(np.float32)

    def build(mesh_shape, strategies):
        cfg = FFConfig(batch_size=B, mesh_shape=mesh_shape, seed=5)
        cfg.strategies.update(strategies)
        ff = FFModel(cfg)
        xt = ff.create_tensor([B, S, D], name="x")
        out = ff.multihead_attention(xt, xt, xt, D, H, causal=True,
                                     bias=False, num_kv_heads=2, rope=True,
                                     name="mha")
        ff.compile(optimizer=None, final_tensor=out)
        return ff

    ff1 = build({"data": 1}, {})
    y_dense = np.asarray(ff1.predict({"x": x}))
    sp = ParallelConfig.from_axis_map(3, {"data": 2, "seq": 4},
                                      {"data": 0, "seq": 1})
    ff2 = build({"data": 2, "seq": 4}, {"mha": sp})
    for w in ("wq", "wk", "wv", "wo"):
        ff2.set_weights("mha", w, ff1.get_weights("mha", w))
    y_sp = np.asarray(ff2.predict({"x": x}))
    np.testing.assert_allclose(y_sp, y_dense, rtol=3e-4, atol=3e-5)


def test_gqa_tp_degree_exceeding_kv_heads_replicates_kv():
    """Head-shard degree 4 with only 2 kv heads: q/o shard, k/v weights
    stay replicated (their heads broadcast to query groups in forward),
    and the model still trains."""
    from flexflow_tpu.parallel.pconfig import ParallelConfig

    mesh = {"data": 2, "model": 4}
    cfg = FFConfig(batch_size=8, mesh_shape=mesh)
    cfg.strategies["attn"] = ParallelConfig.from_axis_map(
        3, mesh, {"data": 0, "model": 2})
    ff = FFModel(cfg)
    x = ff.create_tensor([8, 16, 32], name="x")
    out = ff.multihead_attention(x, x, x, 32, 8, causal=True, bias=False,
                                 num_kv_heads=2, rope=True, name="attn")
    ff.compile(SGDOptimizer(lr=0.1),
               LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
               [], final_tensor=out)
    assert ff.params["attn"]["wq"].sharding.spec[1] == "model"
    assert ff.params["attn"]["wk"].sharding.spec == (None, None, None) \
        or all(e is None for e in ff.params["attn"]["wk"].sharding.spec)
    rs = np.random.RandomState(4)
    SingleDataLoader(ff, x, rs.randn(16, 16, 32).astype(np.float32))
    SingleDataLoader(ff, ff.label_tensor,
                     rs.randn(16, 16, 32).astype(np.float32))
    losses, _ = ff.train_scanned(2)
    assert np.isfinite(np.asarray(losses)).all()


@pytest.mark.slow  # 13 s; llama graphs train in the generation/serving suites
def test_llama_lm_trains():
    # tiny next-token task: constant successor mapping is learnable
    vocab, seq, batch = 64, 16, 8
    cfg = FFConfig(batch_size=batch, epochs=30)
    ff = FFModel(cfg)
    tokens, logits = llama_lm(ff, batch, seq_len=seq, hidden=64, layers=2,
                              heads=4, kv_heads=2, vocab_size=vocab)
    ff.compile(SGDOptimizer(lr=0.5),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=logits)
    rs = np.random.RandomState(3)
    x = rs.randint(0, vocab, (64, seq)).astype(np.int32)
    y = ((x + 1) % vocab)[..., None].astype(np.int32)  # successor token
    SingleDataLoader(ff, tokens, x)
    SingleDataLoader(ff, ff.label_tensor, y)
    perf = ff.fit(verbose=False)
    assert perf.accuracy > 0.9, f"accuracy {perf.accuracy}"
