"""Keras preprocessing clone (reference re-exports keras_preprocessing at
python/flexflow/keras/preprocessing/{text,sequence}.py; here implemented
from scratch for the offline image)."""

import numpy as np

from flexflow_tpu.keras.preprocessing.sequence import pad_sequences
from flexflow_tpu.keras.preprocessing.text import (Tokenizer,
                                                   text_to_word_sequence)


def test_text_to_word_sequence():
    assert text_to_word_sequence("Hello, world! foo_bar") == \
        ["hello", "world", "foo", "bar"]
    assert text_to_word_sequence("Keep CASE", lower=False) == \
        ["Keep", "CASE"]


def test_tokenizer_fit_and_transform():
    tok = Tokenizer(num_words=4)
    tok.fit_on_texts(["the cat sat", "the cat ran", "the dog"])
    # most-frequent word gets index 1
    assert tok.word_index["the"] == 1
    assert tok.word_index["cat"] == 2
    seqs = tok.texts_to_sequences(["the cat", "the emu"])
    assert seqs[0] == [1, 2]
    assert seqs[1] == [1]  # unknown word dropped without oov_token


def test_tokenizer_oov():
    tok = Tokenizer(num_words=10, oov_token="<oov>")
    tok.fit_on_texts(["a b"])
    assert tok.texts_to_sequences(["a z"])[0] == \
        [tok.word_index["a"], tok.word_index["<oov>"]]


def test_sequences_to_matrix_modes():
    tok = Tokenizer(num_words=5)
    m = tok.sequences_to_matrix([[1, 2, 2], [4]], mode="binary")
    np.testing.assert_array_equal(m, [[0, 1, 1, 0, 0], [0, 0, 0, 0, 1]])
    m = tok.sequences_to_matrix([[1, 2, 2]], mode="count")
    np.testing.assert_array_equal(m, [[0, 1, 2, 0, 0]])
    m = tok.sequences_to_matrix([[1, 2, 2]], mode="freq")
    np.testing.assert_allclose(m, [[0, 1 / 3, 2 / 3, 0, 0]])
    # out-of-range ids ignored
    m = tok.sequences_to_matrix([[1, 7, -2]], mode="binary")
    np.testing.assert_array_equal(m, [[0, 1, 0, 0, 0]])


def test_pad_sequences():
    out = pad_sequences([[1, 2], [3]], maxlen=3)
    np.testing.assert_array_equal(out, [[0, 1, 2], [0, 0, 3]])
    out = pad_sequences([[1, 2, 3, 4]], maxlen=2)          # pre-truncate
    np.testing.assert_array_equal(out, [[3, 4]])
    out = pad_sequences([[1, 2, 3, 4]], maxlen=2, truncating="post")
    np.testing.assert_array_equal(out, [[1, 2]])
    out = pad_sequences([[1], []], maxlen=2, padding="post", value=9)
    np.testing.assert_array_equal(out, [[1, 9], [9, 9]])
    # maxlen inferred
    np.testing.assert_array_equal(pad_sequences([[5], [6, 7]]),
                                  [[0, 5], [6, 7]])


def test_digits_dataset_is_real():
    """The bundled digits npz: right shapes/ranges and non-trivially
    learnable structure (class means differ)."""
    from flexflow_tpu.keras.datasets import digits

    (xtr, ytr), (xte, yte) = digits.load_data()
    assert xtr.shape[1:] == (8, 8) and xte.shape[1:] == (8, 8)
    assert xtr.max() <= 16 and xtr.min() >= 0
    assert set(np.unique(ytr)) == set(range(10))
    m0 = xtr[ytr == 0].mean(axis=0)
    m1 = xtr[ytr == 1].mean(axis=0)
    assert np.abs(m0 - m1).max() > 2.0
