"""Native (C++) search core tests: exact parity with the Python cost model,
determinism, memory penalty, DCN tiers, and placement-aware MCMC."""

import numpy as np
import pytest

from flexflow_tpu import ActiMode, FFConfig, FFModel
from flexflow_tpu.search.cost_model import CostModel
from flexflow_tpu.search.csim import CompiledSearchProblem, native_optimize
from flexflow_tpu.search.driver import data_parallel_strategy, legal_axis_maps
from flexflow_tpu.search.machine import MachineModel


def build_wide(mesh_shape, batch=64):
    cfg = FFConfig(batch_size=batch, mesh_shape=mesh_shape)
    cfg.enable_parameter_parallel = True
    ff = FFModel(cfg)
    x = ff.create_tensor([batch, 1024], name="x")
    t = ff.dense(x, 8192, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.dense(t, 8192, ActiMode.AC_MODE_RELU, name="fc2")
    t = ff.dense(t, 16, name="out")
    return ff


MESH = {"data": 4, "model": 2}


def test_native_matches_python_objective_on_random_strategies():
    """The C++ scheduler and CostModel.iteration_time are the same algorithm
    (VERDICT r1 weak #3): they must agree to float tolerance on random
    strategies, so the two objectives cannot drift silently."""
    ff = build_wide(MESH)
    cost = CostModel(ff, MESH)
    prob = CompiledSearchProblem(ff, cost, MESH)
    rs = np.random.RandomState(0)
    ops = prob.ops
    for trial in range(20):
        strategy = {op.name: prob.op_maps[i][rs.randint(len(prob.op_maps[i]))]
                    for i, op in enumerate(ops)}
        c_native = prob.simulate(prob.choices_for(strategy))
        c_python = cost.iteration_time(strategy)
        assert c_native == pytest.approx(c_python, rel=1e-9), \
            f"trial {trial}: native {c_native} != python {c_python}"


def test_native_matches_python_with_placement():
    ff = build_wide(MESH)
    cost = CostModel(ff, MESH)
    prob = CompiledSearchProblem(ff, cost, MESH)
    # shard fc1/fc2 4-way (half the mesh), placed on different blocks
    am4 = {"data": 0}
    strategy = {"fc1": am4, "fc2": am4, "out": am4}
    places = {"fc1": 0, "fc2": 4, "out": 0}
    c_native = prob.simulate(prob.choices_for(strategy), places)
    c_python = cost.iteration_time(strategy, places)
    assert c_native == pytest.approx(c_python, rel=1e-9)
    # a different placement must actually change the simulated time
    c_same = prob.simulate(prob.choices_for(strategy),
                           {"fc1": 0, "fc2": 0, "out": 0})
    assert c_native != pytest.approx(c_same, rel=1e-6)


def test_memory_penalty_rejects_oom_strategy():
    """An over-HBM strategy must cost more than a sharded one (reference
    simulator.cc:595-620: 1 ms/MB over capacity)."""
    mesh = {"data": 1, "model": 8}
    cfg = FFConfig(batch_size=8, mesh_shape=mesh)
    ff = FFModel(cfg)
    x = ff.create_tensor([8, 4096], name="x")
    ff.dense(x, 65536, name="big")  # 4096x65536 f32 = ~1 GiB weights x3
    machine = MachineModel(hbm_bytes=512e6)  # tiny HBM: replication OOMs
    cost = CostModel(ff, mesh, machine=machine)
    prob = CompiledSearchProblem(ff, cost, mesh)
    replicated = prob.simulate(prob.choices_for({"big": {}}))
    sharded = prob.simulate(prob.choices_for({"big": {"model": 1}}))
    assert sharded < replicated
    # the penalty term dominates: ~2.5 GB over 0.5 GB cap -> seconds
    assert replicated > 1.0
    # python objective agrees (same algorithm)
    assert replicated == pytest.approx(
        cost.iteration_time({"big": {}}), rel=1e-9)


def test_dcn_axis_prices_grad_sync_higher():
    """A {hosts:2, data:4} mesh prices a gradient all-reduce differently
    from {data:8} (reference simulator.cc:252-285 inter-node 3-hop model)."""
    ici = MachineModel()
    dcn = MachineModel(dcn_axes={"data": 2})
    nbytes = 64e6
    t_ici = ici.all_reduce_time(nbytes, 8, "data")
    t_dcn = dcn.all_reduce_time(nbytes, 8, "data")
    assert t_dcn > t_ici * 2, (t_dcn, t_ici)
    # and an axis not listed in dcn_axes is unaffected
    assert dcn.all_reduce_time(nbytes, 8, "model") == pytest.approx(t_ici)


def test_native_mcmc_deterministic_and_improves():
    ff = build_wide(MESH)
    cost = CostModel(ff, MESH)
    prob = CompiledSearchProblem(ff, cost, MESH)
    init = prob.choices_for(data_parallel_strategy(ff, MESH))
    dp_cost = prob.simulate(init)
    c1, p1, cost1 = prob.mcmc(init, 500, 0.05, seed=7)
    c2, p2, cost2 = prob.mcmc(init, 500, 0.05, seed=7)
    assert np.array_equal(c1, c2) and np.array_equal(p1, p2) and cost1 == cost2
    assert cost1 <= dp_cost


def test_native_optimize_end_to_end():
    ff = build_wide(MESH)
    cost = CostModel(ff, MESH)
    best = native_optimize(ff, cost, MESH, budget=500, alpha=0.05, seed=3)
    assert set(best) == {"fc1", "fc2", "out"}
    for name, pc in best.items():
        assert pc.num_parts() <= 8
        assert len(pc.device_ids) == pc.num_parts()
    # best strategy cost should not exceed DP
    am = {k: v.axis_map for k, v in best.items()}
    places = {k: (min(v.device_ids) if v.device_ids else 0)
              for k, v in best.items()}
    prob = CompiledSearchProblem(ff, cost, MESH)
    assert prob.simulate(prob.choices_for(am), places) <= \
        prob.simulate(prob.choices_for(data_parallel_strategy(ff, MESH))) \
        * 1.0001


def test_placement_search_beats_dp_on_branchy_graph():
    """Two fat parallel branches (InceptionV3-style): placing them on
    disjoint device blocks must simulate faster than running both
    full-mesh-serial, and the MCMC must find such a strategy (the SOAP 'O'
    axis, reference config.h:47-69 + model.cc:496-525). Parameter parallel
    is disabled — the reference's own default (model.cc:1935) — so sharding
    the weights away is not an option and placement is the winning move."""
    mesh = {"data": 4, "model": 2}
    cfg = FFConfig(batch_size=64, mesh_shape=mesh)
    ff = FFModel(cfg)
    x = ff.create_tensor([64, 1024], name="x")
    a = ff.dense(x, 4096, ActiMode.AC_MODE_RELU, name="branch_a1")
    a = ff.dense(a, 4096, name="branch_a2")
    b = ff.dense(x, 4096, ActiMode.AC_MODE_RELU, name="branch_b1")
    b = ff.dense(b, 4096, name="branch_b2")
    t = ff.concat([a, b], axis=1, name="join")
    ff.dense(t, 16, name="head")

    cost = CostModel(ff, mesh)
    prob = CompiledSearchProblem(ff, cost, mesh, epp=False)
    dp = data_parallel_strategy(ff, mesh)
    dp_cost = prob.simulate(prob.choices_for(dp))

    maps_a1 = legal_axis_maps(ff.get_op_by_name("branch_a1"), mesh,
                              enable_parameter_parallel=False)
    assert {"data": 0, "model": None} in maps_a1  # 4-way block is proposable
    best_c, best_p, best_cost = prob.mcmc(
        prob.choices_for(dp), 8000, 0.05, seed=1)
    assert best_cost < dp_cost * 0.5
    # the found strategy must be executable-aligned: every placement is a
    # legal aligned block
    blocks = {}
    for i, op in enumerate(prob.ops):
        ndev = int(prob.op_ndev[prob.op_cost_offsets[i] + best_c[i]])
        assert best_p[i] % max(ndev, 1) == 0
        blocks[op.name] = set(range(best_p[i], best_p[i] + ndev))
    # and some pair of opposite-branch ops runs on disjoint device blocks
    # (the op-parallel win: branches overlap in time)
    assert any(not (blocks[f"branch_a{i}"] & blocks[f"branch_b{j}"])
               for i in (1, 2) for j in (1, 2))


def test_timeline_matches_simulate_with_placement(tmp_path):
    from flexflow_tpu.runtime.profiler import export_sim_taskgraph

    dot = tmp_path / "g.dot"
    cfg = FFConfig(batch_size=32, mesh_shape={"data": 4, "model": 2},
                   taskgraph_file=str(dot))
    ff = FFModel(cfg)
    x = ff.create_tensor([32, 64], name="x")
    t = ff.dense(x, 256, ActiMode.AC_MODE_RELU, name="fc1")
    ff.dense(t, 64, name="fc2")
    ff.compile(optimizer=None)  # compile triggers the export
    text = dot.read_text()
    assert "simulated iteration:" in text
    assert '"fc1"' in text and '"fc2"' in text and "_sync" in text

    cost = CostModel(ff, cfg.mesh_shape)
    prob = CompiledSearchProblem(ff, cost, cfg.mesh_shape)
    strategy = {n: am for n, am in ff.executor._op_axis_maps.items()}
    ch = prob.choices_for(strategy)
    total_t, rows = prob.simulate_timeline(ch)
    assert abs(total_t - prob.simulate(ch)) < 1e-12
    assert any(r["kind"] == "compute" for r in rows)
    # schedule sanity: no task finishes after the total (memory penalty can
    # push the total above the last task, never below)
    assert all(r["finish"] <= total_t + 1e-12 for r in rows)
