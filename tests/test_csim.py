"""Native (C++) search core tests: parity with the Python cost model on
serial chains, determinism, and end-to-end native MCMC."""

import numpy as np
import pytest

from flexflow_tpu import ActiMode, FFConfig, FFModel
from flexflow_tpu.search.cost_model import CostModel
from flexflow_tpu.search.csim import CompiledSearchProblem, native_optimize
from flexflow_tpu.search.driver import data_parallel_strategy


def build_wide(mesh_shape, batch=64):
    cfg = FFConfig(batch_size=batch, mesh_shape=mesh_shape)
    cfg.enable_parameter_parallel = True
    ff = FFModel(cfg)
    x = ff.create_tensor([batch, 1024], name="x")
    t = ff.dense(x, 8192, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.dense(t, 8192, ActiMode.AC_MODE_RELU, name="fc2")
    t = ff.dense(t, 16, name="out")
    return ff


MESH = {"data": 4, "model": 2}


def test_native_simulate_close_to_python_serial():
    ff = build_wide(MESH)
    cost = CostModel(ff, MESH)
    prob = CompiledSearchProblem(ff, cost, MESH)
    dp = data_parallel_strategy(ff, MESH)
    c_native = prob.simulate(prob.choices_for(dp))
    c_python = cost.iteration_time(dp)
    # native schedules comm/compute overlap, so it can only be <= serial sum
    assert c_native <= c_python * 1.0001
    assert c_native >= 0.2 * c_python  # same order of magnitude


def test_native_mcmc_deterministic_and_improves():
    ff = build_wide(MESH)
    cost = CostModel(ff, MESH)
    prob = CompiledSearchProblem(ff, cost, MESH)
    init = prob.choices_for(data_parallel_strategy(ff, MESH))
    dp_cost = prob.simulate(init)
    b1, c1 = prob.mcmc(init, 500, 0.05, seed=7)
    b2, c2 = prob.mcmc(init, 500, 0.05, seed=7)
    assert np.array_equal(b1, b2) and c1 == c2
    assert c1 <= dp_cost


def test_native_optimize_end_to_end():
    ff = build_wide(MESH)
    cost = CostModel(ff, MESH)
    best = native_optimize(ff, cost, MESH, budget=500, alpha=0.05, seed=3)
    assert set(best) == {"fc1", "fc2", "out"}
    for name, pc in best.items():
        assert pc.num_parts() <= 8
    # best strategy cost (python model) should not exceed DP
    am = {k: v.axis_map for k, v in best.items()}
    prob = CompiledSearchProblem(ff, cost, MESH)
    assert prob.simulate(prob.choices_for(am)) <= \
        prob.simulate(prob.choices_for(data_parallel_strategy(ff, MESH))) * 1.0001


def test_simulate_timeline_and_taskgraph_export(tmp_path):
    """ff_simulate_timeline + the --taskgraph DOT export (reference:
    simulator DotFile with per-task times, simulator.h:78-131)."""
    from flexflow_tpu import ActiMode, FFConfig, FFModel
    from flexflow_tpu.runtime.profiler import export_sim_taskgraph

    dot = tmp_path / "g.dot"
    cfg = FFConfig(batch_size=32, mesh_shape={"data": 4, "model": 2},
                   taskgraph_file=str(dot))
    ff = FFModel(cfg)
    x = ff.create_tensor([32, 64], name="x")
    t = ff.dense(x, 256, ActiMode.AC_MODE_RELU, name="fc1")
    ff.dense(t, 64, name="fc2")
    ff.compile(optimizer=None)  # compile triggers the export
    text = dot.read_text()
    assert "simulated iteration:" in text
    assert '"fc1"' in text and '"fc2"' in text and "_sync" in text

    # timeline total matches plain simulate
    from flexflow_tpu.search.cost_model import CostModel
    from flexflow_tpu.search.csim import CompiledSearchProblem

    cost = CostModel(ff, cfg.mesh_shape)
    prob = CompiledSearchProblem(ff, cost, cfg.mesh_shape)
    strategy = {n: am for n, am in ff.executor._op_axis_maps.items()}
    ch = prob.choices_for(strategy)
    total_t, rows = prob.simulate_timeline(ch)
    assert abs(total_t - prob.simulate(ch)) < 1e-12
    assert any(r["kind"] == "compute" for r in rows)
    # schedule sanity: no task finishes after the total
    assert all(r["finish"] <= total_t + 1e-12 for r in rows)
