"""Native (C++) search core tests: parity with the Python cost model on
serial chains, determinism, and end-to-end native MCMC."""

import numpy as np
import pytest

from flexflow_tpu import ActiMode, FFConfig, FFModel
from flexflow_tpu.search.cost_model import CostModel
from flexflow_tpu.search.csim import CompiledSearchProblem, native_optimize
from flexflow_tpu.search.driver import data_parallel_strategy


def build_wide(mesh_shape, batch=64):
    cfg = FFConfig(batch_size=batch, mesh_shape=mesh_shape)
    cfg.enable_parameter_parallel = True
    ff = FFModel(cfg)
    x = ff.create_tensor([batch, 1024], name="x")
    t = ff.dense(x, 8192, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.dense(t, 8192, ActiMode.AC_MODE_RELU, name="fc2")
    t = ff.dense(t, 16, name="out")
    return ff


MESH = {"data": 4, "model": 2}


def test_native_simulate_close_to_python_serial():
    ff = build_wide(MESH)
    cost = CostModel(ff, MESH)
    prob = CompiledSearchProblem(ff, cost, MESH)
    dp = data_parallel_strategy(ff, MESH)
    c_native = prob.simulate(prob.choices_for(dp))
    c_python = cost.iteration_time(dp)
    # native schedules comm/compute overlap, so it can only be <= serial sum
    assert c_native <= c_python * 1.0001
    assert c_native >= 0.2 * c_python  # same order of magnitude


def test_native_mcmc_deterministic_and_improves():
    ff = build_wide(MESH)
    cost = CostModel(ff, MESH)
    prob = CompiledSearchProblem(ff, cost, MESH)
    init = prob.choices_for(data_parallel_strategy(ff, MESH))
    dp_cost = prob.simulate(init)
    b1, c1 = prob.mcmc(init, 500, 0.05, seed=7)
    b2, c2 = prob.mcmc(init, 500, 0.05, seed=7)
    assert np.array_equal(b1, b2) and c1 == c2
    assert c1 <= dp_cost


def test_native_optimize_end_to_end():
    ff = build_wide(MESH)
    cost = CostModel(ff, MESH)
    best = native_optimize(ff, cost, MESH, budget=500, alpha=0.05, seed=3)
    assert set(best) == {"fc1", "fc2", "out"}
    for name, pc in best.items():
        assert pc.num_parts() <= 8
    # best strategy cost (python model) should not exceed DP
    am = {k: v.axis_map for k, v in best.items()}
    prob = CompiledSearchProblem(ff, cost, MESH)
    assert prob.simulate(prob.choices_for(am)) <= \
        prob.simulate(prob.choices_for(data_parallel_strategy(ff, MESH))) * 1.0001
