"""1F1B pipeline schedule + STAGE (pipeline-parallel) strategy search.

VERDICT r3 #5: pipeline as a schedule library (1F1B with the O(stages)
activation bound, parallel/pipeline.py) and as a search axis (STAGE
axis_map marker proposed by legal_axis_maps, priced by the cost model,
executed by TransformerPipelineStack under any mesh axis name).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from flexflow_tpu.parallel.mesh import make_mesh
from flexflow_tpu.parallel.pipeline import pipeline_train_1f1b


def _mlp_stages(n, d, rs):
    return {"w": jnp.asarray(rs.randn(n, d, d).astype(np.float32) * 0.3),
            "b": jnp.asarray(rs.randn(n, d).astype(np.float32) * 0.1)}


def _stage_fn(p, h):
    return jnp.tanh(h @ p["w"] + p["b"])


def _loss_fn(y, lab, hp):
    return jnp.mean((y @ hp["wo"] - lab) ** 2)


def _serial_loss(stacked, hp, x, lab, n, m):
    xm = x.reshape(m, x.shape[0] // m, *x.shape[1:])
    lm = lab.reshape(m, lab.shape[0] // m, *lab.shape[1:])

    def one(j):
        h = xm[j]
        for i in range(n):
            h = _stage_fn({k: v[i] for k, v in stacked.items()}, h)
        return _loss_fn(h, lm[j], hp)

    return jnp.mean(jnp.stack([one(j) for j in range(m)]))


@pytest.mark.parametrize("n,m", [(4, 8), (4, 4), (2, 6)])
def test_1f1b_matches_serial_autodiff(n, m):
    """Loss, stage grads, head grads, and dx from the hand-scheduled 1F1B
    loop must equal autodiff through the serial model. Grads come back as
    microbatch SUMS (loss_fn returns per-microbatch means), so the serial
    mean-grad scales by m."""
    mb, d = 2, 16
    rs = np.random.RandomState(0)
    stacked = _mlp_stages(n, d, rs)
    head = {"wo": jnp.asarray(rs.randn(d, 4).astype(np.float32) * 0.3)}
    x = jnp.asarray(rs.randn(m * mb, d).astype(np.float32))
    lab = jnp.asarray(rs.randn(m * mb, 4).astype(np.float32))
    mesh = make_mesh({"pipe": n})

    loss, g, gh, dx = jax.jit(
        lambda sp, hp, xx, ll: pipeline_train_1f1b(
            _stage_fn, _loss_fn, sp, xx, ll, mesh,
            num_microbatches=m, head_params=hp))(stacked, head, x, lab)

    ref = jax.grad(_serial_loss, argnums=(0, 1, 2))(
        stacked, head, x, lab, n, m)
    ref_loss = _serial_loss(stacked, head, x, lab, n, m)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for k in stacked:
        np.testing.assert_allclose(np.asarray(g[k]),
                                   np.asarray(ref[0][k]) * m,
                                   rtol=1e-4, atol=1e-5, err_msg=k)
    np.testing.assert_allclose(np.asarray(gh["wo"]),
                               np.asarray(ref[1]["wo"]) * m,
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(ref[2]) * m,
                               rtol=1e-4, atol=1e-5)


def test_1f1b_dp_pp_composition():
    """pipe=4 x data=2: each data slice pipelines its microbatch shard;
    grads psum over data, numerics equal the serial model."""
    n, m, mb, d = 4, 4, 4, 8
    rs = np.random.RandomState(1)
    stacked = _mlp_stages(n, d, rs)
    head = {"wo": jnp.asarray(rs.randn(d, 4).astype(np.float32) * 0.3)}
    x = jnp.asarray(rs.randn(m * mb, d).astype(np.float32))
    lab = jnp.asarray(rs.randn(m * mb, 4).astype(np.float32))
    mesh = make_mesh({"pipe": n, "data": 2})

    loss, g, gh, dx = pipeline_train_1f1b(
        _stage_fn, _loss_fn, stacked, x, lab, mesh,
        num_microbatches=m, head_params=head, data_axis="data")

    ref_loss = _serial_loss(stacked, head, x, lab, n, m)
    ref = jax.grad(_serial_loss, argnums=(0,))(stacked, head, x, lab, n, m)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for k in stacked:
        np.testing.assert_allclose(np.asarray(g[k]),
                                   np.asarray(ref[0][k]) * m,
                                   rtol=1e-4, atol=1e-5, err_msg=k)


def _deep_stack_model(mesh_shape, L=8, B=4, S=16, D=64, H=2):
    from flexflow_tpu import FFConfig, FFModel

    cfg = FFConfig(batch_size=B, mesh_shape=mesh_shape)
    ff = FFModel(cfg)
    xt = ff.create_tensor([B, S, D], name="x")
    t = ff.transformer_pipeline_stack(xt, L, H, name="stack")
    ff.dense(t, 8, name="head")
    return ff, xt


def test_legal_axis_maps_proposes_stage():
    from flexflow_tpu.parallel.pconfig import STAGE
    from flexflow_tpu.search.driver import legal_axis_maps

    mesh_shape = {"grid": 8}
    ff, _ = _deep_stack_model(mesh_shape)
    stack = next(op for op in ff.ops if op.name == "stack")
    maps = legal_axis_maps(stack, mesh_shape)
    assert {"grid": STAGE} in maps, maps
    # head (no stacked layers) must NOT get STAGE proposals
    head = next(op for op in ff.ops if op.name == "head")
    assert not any(d == STAGE for m in legal_axis_maps(head, mesh_shape)
                   for d in m.values())


def test_simulator_prices_pp_above_dp_for_deep_thin_model():
    """Deep stack, small batch: DP pays a full-weight grad all-reduce every
    step; PP shards the layers and pays only bubble + boundary p2p. The
    cost model must rank the pipe strategy faster — this is the 'search
    can discover PP' precondition, and the MCMC must then actually pick
    it."""
    from flexflow_tpu.parallel.pconfig import STAGE
    from flexflow_tpu.search.cost_model import CostModel
    from flexflow_tpu.search.driver import (data_parallel_strategy,
                                            optimize_strategies)

    mesh_shape = {"data": 8}
    ff, _ = _deep_stack_model(mesh_shape, L=8, B=8, S=16, D=128)
    cost = CostModel(ff, mesh_shape)
    dp = data_parallel_strategy(ff, mesh_shape)
    pp = dict(dp)
    pp["stack"] = {"data": STAGE}
    t_dp = cost.iteration_time(dp)
    t_pp = cost.iteration_time(pp)
    assert t_pp < t_dp, f"PP {t_pp} not faster than DP {t_dp}"

    best = optimize_strategies(ff, budget=3000, mesh_shape=mesh_shape,
                               seed=0)
    assert any(d == STAGE
               for d in (best["stack"].axis_map or {}).values()), \
        f"search did not discover PP: {best['stack'].axis_map}"


def test_stage_priced_correctly_under_mesh_override():
    """Searching over a mesh_shape override whose axis is absent from the
    model config must still shard stage weights in weight_partition —
    otherwise grad-sync pricing charges PP candidates a full stacked-weight
    all-reduce and the search can never discover them."""
    from flexflow_tpu.parallel.pconfig import STAGE
    from flexflow_tpu.search.cost_model import CostModel

    ff, _ = _deep_stack_model({"data": 1}, L=8, B=8, S=16, D=128)
    override = {"grid": 8}
    stack = next(op for op in ff.ops if op.name == "stack")
    wp = stack.weight_partition({"grid": STAGE})
    assert wp["w1"][0] == "grid", wp["w1"]
    cost = CostModel(ff, override)
    assert cost.op_grad_sync_time(stack, {"grid": STAGE}) == 0.0
    t_pp = cost.iteration_time({"stack": {"grid": STAGE}, "head": {}})
    t_dp = cost.iteration_time({"stack": {"grid": 0}, "head": {}})
    assert t_pp < t_dp


def test_stack_executes_search_assigned_stage_axis():
    """A STAGE assignment on an arbitrary mesh axis name (not 'pipe') must
    activate the pipeline lowering, shard stage weights over that axis,
    and match the serial model's forward numerics."""
    from flexflow_tpu.parallel.pconfig import STAGE, ParallelConfig

    B, S, D, H, L = 4, 8, 32, 2, 8
    rs = np.random.RandomState(3)
    x = rs.randn(B, S, D).astype(np.float32)

    def build(mesh_shape, strategies=None):
        from flexflow_tpu import FFConfig, FFModel

        cfg = FFConfig(batch_size=B, mesh_shape=mesh_shape, seed=5)
        if strategies:
            cfg.strategies.update(strategies)
        ff = FFModel(cfg)
        xt = ff.create_tensor([B, S, D], name="x")
        t = ff.transformer_pipeline_stack(xt, L, H, name="stack")
        ff.compile(optimizer=None, final_tensor=t)
        return ff

    serial = build({"data": 1})
    y_serial = np.asarray(serial.predict({"x": x}))

    st = {"stack": ParallelConfig.from_axis_map(
        3, {"blocks": 4}, {"blocks": STAGE})}
    piped = build({"blocks": 4}, st)
    for k, v in serial.params["stack"].items():
        piped.set_weights("stack", k, np.asarray(v))
    y_piped = np.asarray(piped.predict({"x": x}))
    np.testing.assert_allclose(y_piped, y_serial, rtol=2e-4, atol=2e-5)

    # stage weights really shard over 'blocks'
    spec = piped.params["stack"]["w1"].sharding.spec
    assert spec[0] == "blocks", spec


def test_stage_strategy_file_round_trip(tmp_path):
    """A search-discovered PP strategy survives save -> load -> execute:
    the @axismap extension record persists STAGE (degrees alone cannot),
    and the loaded file drives the pipelined lowering via
    import_strategy_file."""
    from flexflow_tpu.parallel.pconfig import CONTRACT, STAGE, ParallelConfig
    from flexflow_tpu.parallel.strategy import (load_strategies_from_file,
                                                save_strategies_to_file)

    mesh = {"grid": 4, "data": 2}
    st = {
        "stack": ParallelConfig.from_axis_map(3, mesh,
                                              {"grid": STAGE, "data": 0}),
        "proj": ParallelConfig.from_axis_map(2, mesh,
                                             {"grid": CONTRACT, "data": 0}),
        "head": ParallelConfig.from_axis_map(2, mesh, {"data": 0}),
    }
    f = str(tmp_path / "pp_strategy.txt")
    save_strategies_to_file(f, st)
    back = load_strategies_from_file(f)
    for name in st:
        assert back[name].axis_map == st[name].axis_map, name
        assert back[name].dims == st[name].dims, name

    # execute through import_strategy_file: the stack must actually
    # pipeline over 'grid' (stage weights sharded on the loaded strategy)
    from flexflow_tpu import FFConfig, FFModel

    B, S, D, H, L = 8, 8, 32, 2, 8
    cfg = FFConfig(batch_size=B, mesh_shape=mesh, seed=5,
                   import_strategy_file=f)
    ff = FFModel(cfg)
    xt = ff.create_tensor([B, S, D], name="x")
    t = ff.transformer_pipeline_stack(xt, L, H, name="stack")
    ff.compile(optimizer=None, final_tensor=t)
    assert ff.params["stack"]["w1"].sharding.spec[0] == "grid"


def test_strategy_file_wrong_mesh_fails_clearly(tmp_path):
    """A file written on a differently-NAMED mesh must fail with the axis
    named, not deep inside JAX."""
    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.parallel.pconfig import STAGE, ParallelConfig
    from flexflow_tpu.parallel.strategy import save_strategies_to_file

    f = str(tmp_path / "other_mesh.txt")
    save_strategies_to_file(f, {"stack": ParallelConfig.from_axis_map(
        3, {"grid": 4}, {"grid": STAGE})})
    cfg = FFConfig(batch_size=4, mesh_shape={"pipes": 4},
                   import_strategy_file=f)
    ff = FFModel(cfg)
    xt = ff.create_tensor([4, 8, 32], name="x")
    t = ff.transformer_pipeline_stack(xt, 8, 2, name="stack")
    with pytest.raises(ValueError, match="grid"):
        ff.compile(optimizer=None, final_tensor=t)


def test_1f1b_dead_ticks_cannot_poison_grads_with_nonfinite():
    """ADVICE r4 (medium): warm-up / drain ticks run stage_fn and loss_fn
    on zero-initialized garbage. A stage whose math divides by an
    input-dependent quantity yields inf/NaN there; with the old
    multiply-by-mask accumulation (0 * inf = NaN) one dead tick poisoned
    the grads of the whole step. The select-based mask must keep grads
    finite AND equal to serial autodiff."""
    n, m, mb, d = 4, 6, 2, 16
    rs = np.random.RandomState(7)
    stacked = _mlp_stages(n, d, rs)
    head = {"wo": jnp.asarray(rs.randn(d, 4).astype(np.float32) * 0.3)}
    x = jnp.asarray((rs.randn(m * mb, d) + 3.0).astype(np.float32))
    lab = jnp.asarray(rs.randn(m * mb, 4).astype(np.float32))
    mesh = make_mesh({"pipe": n})

    def bad_stage(p, h):
        # 1/sqrt(mean(h^2)): finite on real activations, inf/NaN on the
        # all-zero garbage that dead ticks carry
        return jnp.tanh(h @ p["w"] + p["b"]) / jnp.sqrt(jnp.mean(h * h))

    def serial(sp, hp, xx, ll):
        xm = xx.reshape(m, mb, d)
        lm = ll.reshape(m, mb, 4)

        def one(j):
            h = xm[j]
            for i in range(n):
                h = bad_stage({k: v[i] for k, v in sp.items()}, h)
            return _loss_fn(h, lm[j], hp)

        return jnp.mean(jnp.stack([one(j) for j in range(m)]))

    loss, g, gh, dx = jax.jit(
        lambda sp, hp, xx, ll: pipeline_train_1f1b(
            bad_stage, _loss_fn, sp, xx, ll, mesh,
            num_microbatches=m, head_params=hp))(stacked, head, x, lab)

    for name, arr in [("loss", loss), ("g.w", g["w"]), ("g.b", g["b"]),
                      ("gh.wo", gh["wo"]), ("dx", dx)]:
        assert bool(jnp.all(jnp.isfinite(arr))), \
            f"{name} contains non-finite values (dead-tick leak)"
    ref = jax.grad(serial, argnums=(0, 1))(stacked, head, x, lab)
    for k in stacked:
        np.testing.assert_allclose(np.asarray(g[k]),
                                   np.asarray(ref[0][k]) * m,
                                   rtol=1e-3, atol=1e-4, err_msg=k)
    np.testing.assert_allclose(np.asarray(gh["wo"]),
                               np.asarray(ref[1]["wo"]) * m,
                               rtol=1e-3, atol=1e-4)
