"""Disaggregated prefill/decode fleet + tiered prefix cache (ISSUE 12).

Engine- and router-level integration of the two coupled perf layers:

  * role split — ``prefill`` replicas absorb long-prompt admission and
    hand the finished prompt's KV pages (+ quantized scales, draft pool
    included) to ``decode`` replicas as a serialized page slab; the
    decode-side submit admits as a prefix HIT, so greedy streams stay
    token-identical to a single-replica run, and a dead prefill tier
    degrades to the cold path (the crash drill);
  * tiered cache — ref-0 pages demote to pinned host memory under pool
    pressure and promote back on a trie match, so a prefix working set
    larger than the HBM pool keeps hitting; migrations are bitwise, so
    token identity vs an untiered cold engine holds exactly.

The tier state machine alone is pinned host-only in
tests/test_tiered_prefix.py; the FF_FAULT grammar in tests/test_elastic.
"""

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.models.llama import llama_lm
from flexflow_tpu.runtime import faultinject

VOCAB = 61
PS = 4


@pytest.fixture(scope="module")
def ff():
    cfg = FFConfig(batch_size=2, mesh_shape={"data": 1})
    model = FFModel(cfg)
    _, logits = llama_lm(model, 2, seq_len=16, hidden=32, layers=2,
                         heads=2, kv_heads=2, vocab_size=VOCAB)
    model.compile(final_tensor=logits)
    return model


def _mixed_prompts(seed, n=8, sys_len=16):
    """Skewed shared-prefix mix: half share a sys_len-token system
    prompt (sys_len/PS full pages), half are distinct background."""
    rs = np.random.RandomState(seed)
    system = rs.randint(1, VOCAB, (sys_len,)).astype(np.int32)
    out = []
    for i in range(n):
        if i % 2 == 0:
            out.append(np.concatenate(
                [system, rs.randint(1, VOCAB, (3,)).astype(np.int32)]))
        else:
            out.append(rs.randint(
                1, VOCAB, (int(rs.randint(5, 12)),)).astype(np.int32))
    return out


def _arm(monkeypatch, spec):
    monkeypatch.setenv("FF_FAULT", spec)
    faultinject.reset()


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("FF_FAULT", raising=False)
    faultinject.reset()
    yield
    faultinject.reset()


# ---- knobs and validation (host-side, tier-1 fast) ------------------------


def test_config_knobs_and_validation(ff):
    with pytest.raises(ValueError, match="host_kv_pages"):
        FFConfig(batch_size=2, mesh_shape={"data": 1}, host_kv_pages=-1)
    with pytest.raises(ValueError, match="serve_replica_roles"):
        FFConfig(batch_size=2, mesh_shape={"data": 1},
                 serve_replica_roles="prefill,turbo")
    cfg = FFConfig.parse_args(
        ["--host-kv-pages", "64",
         "--serve-replica-roles", "prefill,decode,decode"])
    assert cfg.host_kv_pages == 64
    assert cfg.serve_replica_roles == "prefill,decode,decode"
    # engine-side guards
    with pytest.raises(ValueError, match="host_kv_pages"):
        ff.make_serving_engine(host_kv_pages=-3)
    with pytest.raises(ValueError, match="prefix cache"):
        ff.make_serving_engine(host_kv_pages=8, prefix_cache=False)
    # router-side guards
    with pytest.raises(ValueError, match="one role per replica"):
        ff.make_serving_router(replicas=3, roles=["prefill", "decode"],
                               start=False)
    with pytest.raises(ValueError, match="unknown role"):
        ff.make_serving_router(replicas=2, roles=["prefill", "gpu"],
                               start=False)
    with pytest.raises(ValueError, match="nowhere to decode"):
        ff.make_serving_router(replicas=2,
                               roles=["prefill", "prefill"], start=False)
    with pytest.raises(ValueError, match="handoff_min_pages"):
        ff.make_serving_router(replicas=2, handoff_min_pages=0,
                               start=False)
    router = ff.make_serving_router(replicas=2,
                                    roles="prefill,decode", start=False)
    try:
        assert router.roles == ["prefill", "decode"]
        st = router.stats()
        assert st["roles"] == ["prefill", "decode"]
        assert st["handoffs"] == 0 and st["handoff_fallbacks"] == 0
        assert st["per_replica"][0]["role"] == "prefill"
        fleet = st["fleet"]
        for key in ("prefix_hit_rate", "pages_by_tier", "handoffs",
                    "tier_demotions", "tier_promotions", "per_role",
                    "spec_accept_rate"):
            assert key in fleet, f"fleet rollup missing {key}"
        assert fleet["pages_by_tier"] == {"hbm": 0, "host": 0}
        assert set(fleet["per_role"]) == {"prefill", "decode"}
        assert fleet["per_role"]["prefill"]["replicas"] == 1
    finally:
        router.close()


def test_prefill_only_requires_prefix_cache(ff):
    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=PS,
                                 max_seq_len=48, prefix_cache=False)
    with pytest.raises(RuntimeError, match="prefix cache"):
        eng.prefill_into_cache(np.arange(1, 9, dtype=np.int32))
    assert eng.export_prefix_slab(np.arange(1, 9, dtype=np.int32)) is None
    assert eng.import_prefix_slab({"page_size": PS, "tokens": [],
                                   "payload": []}) == 0


def test_dispatch_skips_saturated_prefill_tier(ff):
    """A saturated prefill tier must not stall the whole fleet: a
    phase-"prefill" queue head that cannot place is skipped, and direct
    work behind it still dispatches to the decode side (FIFO is per
    role tier, not fleet-wide)."""
    router = ff.make_serving_router(
        replicas=2, roles=["prefill", "decode"], serve_slots=2,
        kv_page_size=PS, max_seq_len=48, start=False)
    try:
        # saturate the prefill replica's outstanding ledger to its cap
        for i in range(router._cap):
            router._outstanding[0][10_000 + i] = (None, None)
        long_p = np.arange(1, 20, dtype=np.int32)   # handoff-eligible
        short_p = np.arange(1, 4, dtype=np.int32)   # sub-page: direct
        a = router.submit(long_p, 4)
        b = router.submit(short_p, 4)
        with router._lock:
            router._dispatch_locked()
        assert a.state == "queued" and a.phase == "prefill", \
            "the blocked long prompt must stay queued for the " \
            "prefill tier"
        assert b.state == "dispatched" and b.replica == 1, \
            "direct work behind a blocked prefill head must still flow"
        router._outstanding[0].clear()
    finally:
        router.close()


# ---- engine-level handoff primitives --------------------------------------


@pytest.mark.slow  # ~20 s; the disagg CI tier runs the full file
def test_slab_roundtrip_bitwise_and_token_identity(ff):
    """prefill_into_cache -> export -> import on a second engine: the
    imported pages are BITWISE the donor's, the subsequent submit admits
    as a hit, and the stream equals a cold engine's run exactly."""
    prompts = _mixed_prompts(21)
    cold = ff.make_serving_engine(serve_slots=2, kv_page_size=PS,
                                  max_seq_len=48)
    want = [list(r.tokens) for r in cold.run(prompts, max_new_tokens=6)]

    donor = ff.make_serving_engine(serve_slots=2, kv_page_size=PS,
                                   max_seq_len=48)
    published = donor.prefill_into_cache(prompts[0])
    assert published == prompts[0].size // PS
    assert donor.stats()["prefill_only_requests"] == 1
    assert donor.stats()["completed"] == 0, \
        "prefill-only admission must not count as a completion"
    slab = donor.export_prefix_slab(prompts[0])
    assert slab is not None and len(slab["payload"]) == published
    # not-fully-cached prefixes refuse to export (caller goes cold)
    assert donor.export_prefix_slab(
        np.arange(1, 9, dtype=np.int32)) is None

    imp = ff.make_serving_engine(serve_slots=2, kv_page_size=PS,
                                 max_seq_len=48)
    with pytest.raises(ValueError, match="page_size"):
        imp.import_prefix_slab({**slab, "page_size": PS * 2})
    n = imp.import_prefix_slab(slab)
    assert n == published
    st = imp.stats()
    assert st["prefix_slab_imports"] == 1
    assert st["prefix_pages_imported"] == published
    # bitwise pool equality: the imported pages hold the donor's bytes
    donor_path = donor.prefix_cache.match(prompts[0], published)
    imp_path = imp.prefix_cache.match(prompts[0], published)
    for op in donor.gen.attn_ops:
        for dn, im in zip(donor_path, imp_path):
            np.testing.assert_array_equal(
                np.asarray(donor.pool[op.name]["k"][dn.page]),
                np.asarray(imp.pool[op.name]["k"][im.page]))
            np.testing.assert_array_equal(
                np.asarray(donor.pool[op.name]["v"][dn.page]),
                np.asarray(imp.pool[op.name]["v"][im.page]))
    # a second import of the same slab is a no-op (chunks cached)
    assert imp.import_prefix_slab(slab) == 0
    got = [list(r.tokens) for r in imp.run(prompts, max_new_tokens=6)]
    assert got == want, "handoff-imported prefix changed the stream"
    assert imp.stats()["prefix_hits"] >= 1


@pytest.mark.slow  # ~30 s; disagg CI tier runs the full file — the
# quantized leg: slabs carry scales, so int8 pages round-trip bitwise
def test_quantized_slab_handoff_is_bitwise(ff):
    prompts = _mixed_prompts(23)
    kw = dict(serve_slots=2, kv_page_size=PS, max_seq_len=48,
              kv_cache_dtype="int8")
    donor = ff.make_serving_engine(**kw)
    ref = ff.make_serving_engine(**kw)
    published = donor.prefill_into_cache(prompts[0])
    assert ref.prefill_into_cache(prompts[0]) == published
    slab = donor.export_prefix_slab(prompts[0])
    assert all("k_scale" in p[("t", k[1])]
               for p in slab["payload"] for k in p if k[0] == "t"), \
        "quantized slabs must carry the per-page scales"
    imp = ff.make_serving_engine(**kw)
    assert imp.import_prefix_slab(slab) == published
    # identity under int8 KV: importer vs a reference engine seeded by
    # the SAME prefill-only primitive (hit-vs-cold is not bitwise under
    # lossy KV, but the handoff moves pages bitwise, so two engines
    # with identical published state stream identically)
    want = [list(r.tokens) for r in ref.run(prompts, max_new_tokens=6)]
    got = [list(r.tokens) for r in imp.run(prompts, max_new_tokens=6)]
    assert got == want
    # and the slab pages landed bitwise, scales included
    dpath = donor.prefix_cache.match(prompts[0], published)
    ipath = imp.prefix_cache.match(prompts[0], published)
    op = donor.gen.attn_ops[0]
    for dn, im in zip(dpath, ipath):
        np.testing.assert_array_equal(
            np.asarray(donor.pool[op.name]["k"][dn.page]),
            np.asarray(imp.pool[op.name]["k"][im.page]))
        np.testing.assert_array_equal(
            np.asarray(donor.pool[op.name]["k_scale"][dn.page]),
            np.asarray(imp.pool[op.name]["k_scale"][im.page]))


@pytest.mark.slow  # ~20 s; disagg CI tier runs the full file
def test_import_refuses_dtype_mismatch_and_host_tail(ff):
    """Two slab-import guards: (a) a payload whose storage dtype does
    not match the importer's pool is rejected loudly (import_page casts
    silently — a bf16/f32 slab into an int8 pool would publish
    saturating-cast garbage served as a prefix hit); (b) an import may
    not extend the trie below a host-resident tail (it would break the
    hbm*-then-host* invariant) — it no-ops, and normal admission
    promotes + prefills instead, token-identically."""
    rs = np.random.RandomState(41)
    long_p = rs.randint(1, VOCAB, (4 * PS + 2,)).astype(np.int32)
    cold = ff.make_serving_engine(serve_slots=1, kv_page_size=PS,
                                  max_seq_len=48)
    want = [list(r.tokens) for r in cold.run([long_p], max_new_tokens=5)]
    donor = ff.make_serving_engine(serve_slots=1, kv_page_size=PS,
                                   max_seq_len=48)
    donor.prefill_into_cache(long_p)
    slab_long = donor.export_prefix_slab(long_p)
    slab_short = donor.export_prefix_slab(long_p[:2 * PS])
    # (a) dtype mismatch: full-width slab into an int8 pool
    q = ff.make_serving_engine(serve_slots=1, kv_page_size=PS,
                               max_seq_len=48, kv_cache_dtype="int8")
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        q.import_prefix_slab(slab_long)
    # (b) host-resident tail: import refuses, admission recovers
    imp = ff.make_serving_engine(serve_slots=1, kv_page_size=PS,
                                 max_seq_len=48, host_kv_pages=32)
    assert imp.import_prefix_slab(slab_short) == 2
    with imp._lock:
        imp._free_pages.extend(imp.prefix_cache.evict(2))
    assert imp.stats()["kv_pages_host"] == 2
    assert imp.import_prefix_slab(slab_long) == 0, \
        "import below a host-resident tail must refuse"
    got = [list(r.tokens) for r in imp.run([long_p], max_new_tokens=5)]
    assert got == want, "the promote-then-prefill fallback diverged"
    assert imp.stats()["tier_promotions"] == 2


# ---- role-split fleet ------------------------------------------------------


@pytest.mark.slow  # ~35 s; disagg CI tier runs the full file
def test_role_split_fleet_token_identity_and_handoff(ff):
    """1 prefill + 2 decode: long prompts route through the prefill
    replica (prefill-only, no completions there), hand off as slabs,
    and every stream equals the single-replica run."""
    prompts = _mixed_prompts(25, n=10)
    ref = ff.make_serving_engine(serve_slots=2, kv_page_size=PS,
                                 max_seq_len=48)
    want = [list(r.tokens) for r in ref.run(prompts, max_new_tokens=6)]

    router = ff.make_serving_router(
        replicas=3, roles=["prefill", "decode", "decode"],
        serve_slots=2, kv_page_size=PS, max_seq_len=48, start=False)
    try:
        router.warmup(prompts[:4], max_new_tokens=2)
        base_done = [e.stats()["completed"] for e in router.engines]
        reqs = router.run(prompts, max_new_tokens=6, timeout=300)
        assert [r.state for r in reqs] == ["done"] * len(prompts)
        got = [list(r.tokens) for r in reqs]
        assert got == want, "role split changed a greedy stream"
        st = router.stats()
        assert st["handoffs"] >= 1, "no prompt ever handed off"
        assert any(r.handoff for r in reqs)
        # the prefill replica prefilled but completed NOTHING routed
        eng0 = router.engines[0].stats()
        assert eng0["prefill_only_requests"] >= 1
        assert router.engines[0].stats()["completed"] == base_done[0]
        assert sum(e.stats()["completed"] - b for e, b in
                   zip(router.engines, base_done)) == len(prompts)
        # rollup reflects the handoff ledger
        assert st["fleet"]["handoffs"] == st["handoffs"]
        assert st["fleet"]["prefix_slab_exports"] >= 1
        assert st["fleet"]["prefix_slab_imports"] >= 1
        assert st["fleet"]["per_role"]["decode"]["replicas"] == 2
    finally:
        router.close()


@pytest.mark.slow  # ~35 s; disagg CI tier runs the full file — the
# drill: the prefill tier dies mid-handoff, work falls back cold
def test_prefill_replica_crash_cold_path_fallback(ff, monkeypatch):
    prompts = _mixed_prompts(27, n=10)
    ref = ff.make_serving_engine(serve_slots=2, kv_page_size=PS,
                                 max_seq_len=48)
    want = [list(r.tokens) for r in ref.run(prompts, max_new_tokens=6)]
    router = ff.make_serving_router(
        replicas=3, roles=["prefill", "decode", "decode"],
        serve_slots=2, kv_page_size=PS, max_seq_len=48,
        decode_chunk=2, start=False)
    try:
        router.warmup(prompts[:4], max_new_tokens=2)
        warm_compiles = [e.recompile_count for e in router.engines]
        _arm(monkeypatch, "crash(2)@replica:0")
        reqs = router.run(prompts, max_new_tokens=6, timeout=300)
        assert [r.state for r in reqs] == ["done"] * len(prompts), \
            "a prefill-tier death must never strand work"
        assert [list(r.tokens) for r in reqs] == want
        st = router.stats()
        assert st["fenced"] == 1
        assert st["alive"] == 2
        # survivors (decode replicas) compiled NOTHING: the cold-path
        # fallback runs only programs their warmup built
        for r in (1, 2):
            assert router.engines[r].recompile_count \
                == warm_compiles[r], f"survivor {r} recompile leak"
        # exactly-once: engine completions == routed requests
        assert st["completed"] == len(prompts)
        assert all(r.losses <= 1 for r in reqs)
    finally:
        router.close()


# ---- tiered cache, engine-integrated --------------------------------------


@pytest.mark.slow  # ~30 s; disagg CI tier runs the full file
def test_tiered_cache_outhits_untired_and_stays_identical(ff):
    """Working set ~3x the pool: the tiered engine demotes instead of
    dying and promotes on re-match — hit where the untiered engine goes
    cold — while staying token-identical to a pressure-free engine."""
    rs = np.random.RandomState(31)
    prompts = [rs.randint(1, VOCAB, (9,)).astype(np.int32)
               for _ in range(6)]
    kw = dict(serve_slots=1, kv_page_size=PS, max_seq_len=32,
              kv_pages=12)
    tiered = ff.make_serving_engine(host_kv_pages=64, **kw)
    untired = ff.make_serving_engine(**kw)
    roomy = ff.make_serving_engine(serve_slots=1, kv_page_size=PS,
                                   max_seq_len=32)
    want = [[list(r.tokens) for r in roomy.run(prompts, max_new_tokens=4)]
            for _ in range(2)]
    got_t = [[list(r.tokens) for r in tiered.run(prompts, max_new_tokens=4)]
             for _ in range(2)]
    got_u = [[list(r.tokens) for r in untired.run(prompts, max_new_tokens=4)]
             for _ in range(2)]
    assert got_t == want and got_u == want, \
        "tier migrations must never change a greedy stream"
    ts, us = tiered.stats(), untired.stats()
    assert ts["tier_demotions"] > 0 and ts["tier_promotions"] > 0
    assert ts["prefix_hits"] > us["prefix_hits"], (
        f"host tier bought no hits: tiered {ts['prefix_hits']} vs "
        f"untiered {us['prefix_hits']}")
    assert ts["kv_pages_host"] > 0
    snap = tiered.drain()
    assert snap["prefix_refs_live"] == 0
    assert snap["tier_pending_migrations"] == 0, \
        "drain must quiesce the ordered publisher"


@pytest.mark.slow  # ~25 s; disagg CI tier runs the full file
def test_tier_faults_fall_back_token_identical(ff, monkeypatch):
    rs = np.random.RandomState(33)
    prompts = [rs.randint(1, VOCAB, (9,)).astype(np.int32)
               for _ in range(6)]
    roomy = ff.make_serving_engine(serve_slots=1, kv_page_size=PS,
                                   max_seq_len=32)
    want = [list(r.tokens) for r in roomy.run(prompts, max_new_tokens=4)]
    kw = dict(serve_slots=1, kv_page_size=PS, max_seq_len=32,
              kv_pages=12, host_kv_pages=64)
    _arm(monkeypatch, "d2h_fail@migrate:2,h2d_fail@promote:1")
    eng = ff.make_serving_engine(**kw)
    for _ in range(2):
        got = [list(r.tokens)
               for r in eng.run(prompts, max_new_tokens=4)]
        assert got == want, "a failed migration changed a stream"
    st = eng.stats()
    assert st["tier_demote_failures"] == 1
    assert st["tier_promote_failures"] == 1
    assert st["completed"] == 12 and st["failed"] == 0


@pytest.mark.slow  # ~25 s; disagg CI tier runs the full file — the
# thrice-relearned bench gotcha as an API contract
def test_warmup_drives_every_variant_zero_recompiles_after(ff):
    prompts = _mixed_prompts(35, n=8)
    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=PS,
                                 max_seq_len=48, kv_pages=48,
                                 host_kv_pages=32)
    info = eng.warmup(prompts, max_new_tokens=6)
    assert info["programs"] > 0 and info["requests"] == 2 * len(prompts)
    assert ("page_import",) in info["variants"], \
        "a tiered engine's warmup must warm the page-import writer"
    rc = eng.recompile_count
    for _ in range(3):
        reqs = eng.run(prompts, max_new_tokens=6)
        assert all(r.state == "done" for r in reqs)
    assert eng.recompile_count == rc, (
        f"{eng.recompile_count - rc} programs compiled after warmup — "
        f"the (bucket, matched_pages) variant sweep missed one")


@pytest.mark.slow  # ~25 s; disagg CI tier runs the full file
def test_warmup_learns_interleaved_prefill_variants(ff):
    """ISSUE 18: chunk-interleaved admission adds the prefill_ichunk /
    prefill_ifinal program families. warmup() must drive them too — an
    interleave-enabled engine's timed window compiles nothing."""
    prompts = _mixed_prompts(37, n=8)
    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=PS,
                                 max_seq_len=48, kv_pages=48,
                                 prefill_chunk=PS,
                                 prefill_interleave_chunks=1)
    info = eng.warmup(prompts, max_new_tokens=6)
    fams = {v[0] for v in info["variants"] if isinstance(v, tuple)}
    assert "prefill_ichunk" in fams and "prefill_ifinal" in fams, \
        f"warmup missed the interleaved prefill programs: {sorted(fams)}"
    rc = eng.recompile_count
    for _ in range(3):
        reqs = eng.run(prompts, max_new_tokens=6)
        assert all(r.state == "done" for r in reqs)
    assert eng.recompile_count == rc, (
        f"{eng.recompile_count - rc} programs compiled after warmup — "
        f"the interleaved chunk sweep missed a (bucket, start) variant")
