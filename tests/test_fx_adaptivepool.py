"""Characterization of the adaptive-pool emission in torch/fx.py.

The reference's fx exporter hard-coded `3, 1, 0` (kernel 3, stride 1,
pad 0) for AdaptiveAvgPool2d/AdaptiveMaxPool2d — a latent FIXME that
breaks any feature map smaller than 3x3 and silently computes the wrong
pool on anything that isn't 3x3. This rebuild emits the kernel-0 GLOBAL
sentinel instead (`0, 1, 0`), which torch/model.py's replayer resolves
to the input's spatial size at graph build, where shapes are known.
These tests PIN that contract from both sides: the emitted IR line and
the replayed graph (including the small-feature-map case the reference
emission broke).
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _export_lines(net):
    from flexflow_tpu.torch.fx import torch_to_strings

    return torch_to_strings(net)


def test_adaptive_pool_emits_global_kernel_sentinel():
    torch = pytest.importorskip("torch")
    nn = torch.nn

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.pool = nn.AdaptiveAvgPool2d((1, 1))

        def forward(self, x):
            return self.pool(x)

    lines = _export_lines(Net())
    pool_line = next(ln for ln in lines if "POOL2D" in ln)
    # the contract: kernel 0 (global marker), stride 1, pad 0 — NOT the
    # reference's hard-coded 3, 1, 0
    fields = [f.strip() for f in pool_line.split(",")]
    assert fields[3] == "POOL2D"
    assert fields[4:7] == ["0", "1", "0"], pool_line


def test_adaptive_pool_rejects_non_global_output():
    torch = pytest.importorskip("torch")
    nn = torch.nn

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.pool = nn.AdaptiveAvgPool2d((2, 2))

        def forward(self, x):
            return self.pool(x)

    # only global (1x1) adaptive pooling is expressible in the .ff IR;
    # anything else must fail loudly at export, not misexecute
    with pytest.raises(AssertionError, match="output_size"):
        _export_lines(Net())


def test_replayer_resolves_global_pool_on_small_feature_map(tmp_path):
    """2x2 feature map — the case the reference's kernel-3 emission could
    not execute. The kernel-0 sentinel must replay as a full 2x2 window
    (true global average)."""
    torch = pytest.importorskip("torch")
    nn = torch.nn

    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.ffconst import DataType
    from flexflow_tpu.torch.fx import torch_to_flexflow
    from flexflow_tpu.torch.model import PyTorchModel

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.pool = nn.AdaptiveAvgPool2d(1)

        def forward(self, x):
            return self.pool(x)

    ff_file = str(tmp_path / "pool.ff")
    torch_to_flexflow(Net(), ff_file)

    ff = FFModel(FFConfig(batch_size=2, mesh_shape={"data": 1}))
    x = ff.create_tensor([2, 3, 2, 2], DataType.DT_FLOAT, name="x")
    outs = PyTorchModel(ff_file).apply(ff, [x])
    pool_op = outs[0].owner_op
    # kernel resolved to the INPUT spatial size (2x2), not a fixed 3:
    # with the reference's 3/1/0 this shape would be unbuildable
    assert tuple(outs[0].dims) == (2, 3, 1, 1)

    # numerics: global average over the 2x2 window
    xs = np.arange(2 * 3 * 2 * 2, dtype=np.float32).reshape(2, 3, 2, 2)
    import jax.numpy as jnp

    y = pool_op.forward({}, [jnp.asarray(xs)])[0]
    np.testing.assert_allclose(np.asarray(y),
                               xs.mean(axis=(2, 3), keepdims=True),
                               rtol=1e-6)
