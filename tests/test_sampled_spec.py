"""Rejection-sampled speculation + seeded sampling (ISSUE 14).

Correctness anchors:
  * DISTRIBUTION IDENTITY — rejection-sampled speculation emits tokens
    with exactly the non-speculative sampler's distribution (accept
    min(1, p/q), resample from norm(max(p-q, 0))): pinned by comparing
    token-frequency histograms over fixed seed sweeps (total-variation
    distance shrinks toward 0 with sample count, while a genuinely
    different distribution — another temperature — stays far away).
    Both the all-reject-ish regime (independent tiny draft) and the
    long-accept regime (self-draft) are covered, at K = 1 / 3 / 8.
  * GREEDY IS UNTOUCHED — temperature-0 streams through the sampled
    machinery (mixed batches included) are token-identical to solo
    greedy generate, with speculation on or off.
  * SEEDED REPRODUCIBILITY — a request's sample stream is a pure
    function of (seed, token index): identical across slot
    reassignment, engine instances, and FAILOVER RESUBMISSION (the
    fleet crash drill replays the stream bit-for-bit on the survivor).

Everything is deterministic: fixed seeds, fixed thresholds.
"""

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.models.llama import llama_lm
from flexflow_tpu.runtime import faultinject

VOCAB = 16


def _mk_model(hidden):
    cfg = FFConfig(batch_size=2, mesh_shape={"data": 1})
    model = FFModel(cfg)
    _, logits = llama_lm(model, 2, seq_len=16, hidden=hidden, layers=1,
                         heads=2, kv_heads=2, vocab_size=VOCAB)
    model.compile(final_tensor=logits)
    return model


@pytest.fixture(scope="module")
def target():
    return _mk_model(32)


@pytest.fixture(scope="module")
def draft(target):
    """Independently-initialized tiny draft: proposals mostly miss the
    target's distribution, so the REJECT/resample path runs hard."""
    return _mk_model(16)


def _prompts(seed, lengths):
    rs = np.random.RandomState(seed)
    return [rs.randint(1, VOCAB, (L,)).astype(np.int32) for L in lengths]


PROMPTS = None


def _freqs(target, engine_kw, nseeds, max_new=48, temp=0.9, top_p=0.95):
    """Token-frequency histogram over a fixed seed sweep (deterministic:
    counter-based RNG keyed on the request seeds)."""
    global PROMPTS
    if PROMPTS is None:
        PROMPTS = _prompts(1, [4, 6, 5, 7])
    eng = target.make_serving_engine(serve_slots=4, kv_page_size=4,
                                     max_seq_len=64, **engine_kw)
    toks = []
    for s in range(nseeds):
        for r in eng.run(list(PROMPTS), max_new_tokens=max_new,
                         temperature=temp, top_p=top_p, seed=int(s)):
            assert r.state == "done", r.error
            toks.extend(r.tokens)
    toks = np.asarray(toks)
    return np.bincount(toks, minlength=VOCAB) / toks.size, eng.stats()


def _tv(a, b):
    return 0.5 * float(np.abs(a - b).sum())


def test_rejection_spec_matches_sampler_quick(target, draft):
    """K=3, independent draft (heavy rejection): spec vs non-spec token
    frequencies agree (TV well under the different-distribution
    control). Measured TV at these seeds: ~0.08; control ~0.3."""
    base, _ = _freqs(target, {}, nseeds=8)
    spec, st = _freqs(target, {"draft_model": draft, "speculate_k": 3},
                      nseeds=8)
    tv = _tv(base, spec)
    assert tv < 0.15, f"spec distribution drifted: TV={tv:.4f}"
    assert 0.0 < st["spec_accept_rate"] < 0.9, \
        "independent draft should reject a meaningful fraction"
    # the same statistic DOES separate genuinely different
    # distributions: another temperature is far away
    ctrl, _ = _freqs(target, {}, nseeds=8, temp=0.3)
    assert _tv(base, ctrl) > 0.2, "control lost its discrimination power"


@pytest.mark.slow  # ~6 min: K sweep x draft regimes at higher N
def test_rejection_spec_matches_sampler_k_sweep(target, draft):
    """K = 1 / 3 / 8 with the rejecting draft, plus K=3 self-draft
    (long-accept: q == p up to program numerics, accept rate ~0.8).
    Measured TVs at these seeds: 0.04-0.07 at N~4600."""
    base, _ = _freqs(target, {}, nseeds=16)
    for k in (1, 3, 8):
        spec, st = _freqs(target,
                          {"draft_model": draft, "speculate_k": k},
                          nseeds=16)
        tv = _tv(base, spec)
        assert tv < 0.10, f"K={k}: TV={tv:.4f}"
    selfd, st = _freqs(target, {"draft_model": target, "speculate_k": 3},
                       nseeds=16)
    assert _tv(base, selfd) < 0.10
    assert st["spec_accept_rate"] > 0.6, \
        "self-draft should accept most proposals (long-accept regime)"


def test_greedy_streams_token_identical_in_mixed_batch(target, draft):
    """A greedy request decoding NEXT TO sampled tenants (and under
    speculation) emits exactly its solo greedy stream — acceptance
    criterion: temperature-0 streams are token-identical to HEAD."""
    global PROMPTS
    prompts = _prompts(1, [4, 6, 5, 7])
    for kw in ({}, {"draft_model": draft, "speculate_k": 3}):
        eng = target.make_serving_engine(serve_slots=4, kv_page_size=4,
                                         max_seq_len=64, **kw)
        greedy = eng.submit(prompts[0], 8, temperature=0.0)
        for p in prompts[1:]:
            eng.submit(p, 8, temperature=1.1, seed=3)
        while eng.step():
            pass
        solo = target.generate(prompts[0][None, :], max_new_tokens=8)
        np.testing.assert_array_equal(
            np.asarray(greedy.tokens, np.int32),
            solo[0, prompts[0].size:],
            err_msg=f"greedy stream changed under sampled neighbors "
                    f"(spec={bool(kw)})")


def test_seeded_reproducibility_across_slots_and_engines(target, draft):
    """Same (prompt, seed, sampling config) -> same stream, regardless
    of slot position, neighbors, or engine instance. (Speculation
    changes WHICH stream a seed produces — different draw streams — so
    identity is pinned within each engine configuration.)"""
    prompts = _prompts(2, [5, 7, 4])
    kw = dict(kv_page_size=4, max_seq_len=64)
    e1 = target.make_serving_engine(serve_slots=2, **kw)
    a = e1.run([prompts[0]], 8, temperature=0.8, top_p=0.9, seed=11)[0]
    # same engine, different slot/neighbors
    b = e1.run(list(prompts), 8, temperature=0.8, top_p=0.9, seed=11)[0]
    assert a.tokens == b.tokens
    # fresh engine, different slot count
    e2 = target.make_serving_engine(serve_slots=4, **kw)
    c = e2.run([prompts[2], prompts[0]], 8, temperature=0.8, top_p=0.9,
               seed=11)[1]
    assert a.tokens == c.tokens
    # speculative engine: reproducible against itself
    e3 = target.make_serving_engine(serve_slots=2, draft_model=draft,
                                    speculate_k=3, **kw)
    e4 = target.make_serving_engine(serve_slots=3, draft_model=draft,
                                    speculate_k=3, **kw)
    s1 = e3.run([prompts[0]], 8, temperature=0.8, seed=11)[0]
    s2 = e4.run([prompts[1], prompts[0]], 8, temperature=0.8, seed=11)[1]
    assert s1.tokens == s2.tokens


@pytest.mark.slow  # ~60 s: fleet crash drill
def test_sampled_stream_survives_failover(target, monkeypatch):
    """FF_FAULT crash@replica:0 mid-flight on a 2-replica fleet serving
    SAMPLED requests: every resubmitted request's final stream equals
    the uninterrupted single-engine run with the same seed — the
    counter-based RNG makes sampled failover as deterministic as greedy
    failover."""
    prompts = _prompts(3, [5, 7, 4, 6, 5, 7, 4, 6])
    seeds = list(range(100, 100 + len(prompts)))
    ref_eng = target.make_serving_engine(serve_slots=2, kv_page_size=4,
                                         max_seq_len=64)
    refs = [ref_eng.run([p], 10, temperature=0.9, top_p=0.9,
                        seed=s)[0].tokens
            for p, s in zip(prompts, seeds)]
    monkeypatch.setenv("FF_FAULT", "crash(3)@replica:0")
    faultinject.reset()
    try:
        router = target.make_serving_router(
            replicas=2, kv_page_size=4, max_seq_len=64, serve_slots=2,
            start=False)
        reqs = [router.submit(p, 10, temperature=0.9, top_p=0.9, seed=s)
                for p, s in zip(prompts, seeds)]
        router.start()
        router.wait(reqs, timeout=300)
        st = router.stats()
        assert st["fenced"] == 1, "the crash drill must have fired"
        assert st["resubmitted"] >= 1, \
            "the crash was supposed to catch work in flight"
        for r, want in zip(reqs, refs):
            assert r.state == "done", r.error
            assert r.tokens == want, \
                (f"request {r.rid} sampled stream diverged after "
                 f"failover (losses={r.losses})")
        router.close()
    finally:
        monkeypatch.delenv("FF_FAULT", raising=False)
        faultinject.reset()
