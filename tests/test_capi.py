"""C API (flexflow_tpu/capi) integration test: build the shim + the C++
AlexNet app and run it end-to-end on the 8-device virtual CPU mesh.

Reference parity: the C API layer (python/flexflow_c.h) and the C++
example train loop (examples/cpp/AlexNet/alexnet.cc:34-130)."""

import os
import shutil
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CAPI = os.path.join(REPO, "flexflow_tpu", "capi")
CPP = os.path.join(REPO, "examples", "cpp")


@pytest.mark.skipif(shutil.which("g++") is None
                    or shutil.which("python3-config") is None,
                    reason="no C++ toolchain or Python dev headers")
@pytest.mark.slow  # 16 s; the native CI tier builds and drives the C-API alexnet app
def test_capi_alexnet_end_to_end():
    subprocess.run(["make"], cwd=CAPI, check=True, capture_output=True)
    subprocess.run(["make"], cwd=CPP, check=True, capture_output=True)
    env = dict(os.environ)
    env.update({
        "FFT_JAX_PLATFORMS": "cpu",
        "FFT_NUM_CPU_DEVICES": "8",
        "FFT_REPO_ROOT": REPO,
    })
    r = subprocess.run([os.path.join(CPP, "alexnet"), "16", "1", "32"],
                       env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "alexnet_c: SUCCESS" in r.stdout
    assert "devices=8" in r.stdout


@pytest.mark.skipif(shutil.which("g++") is None
                    or shutil.which("python3-config") is None,
                    reason="no C++ toolchain or Python dev headers")
@pytest.mark.slow  # 8 s; the native CI tier drives the C API, alexnet e2e stays
def test_capi_dlrm_end_to_end():
    subprocess.run(["make"], cwd=CAPI, check=True, capture_output=True)
    subprocess.run(["make"], cwd=CPP, check=True, capture_output=True)
    env = dict(os.environ)
    env.update({
        "FFT_JAX_PLATFORMS": "cpu",
        "FFT_NUM_CPU_DEVICES": "4",
        "FFT_REPO_ROOT": REPO,
    })
    r = subprocess.run([os.path.join(CPP, "dlrm"), "16", "2", "500", "32"],
                       env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "dlrm_c: SUCCESS" in r.stdout


@pytest.mark.skipif(shutil.which("g++") is None
                    or shutil.which("python3-config") is None,
                    reason="no C++ toolchain or Python dev headers")
@pytest.mark.slow  # 12 s; the native CI tier drives the C API, alexnet e2e stays
def test_capi_transformer_end_to_end():
    subprocess.run(["make"], cwd=CAPI, check=True, capture_output=True)
    subprocess.run(["make"], cwd=CPP, check=True, capture_output=True)
    env = dict(os.environ)
    env.update({
        "FFT_JAX_PLATFORMS": "cpu",
        "FFT_NUM_CPU_DEVICES": "4",
        "FFT_REPO_ROOT": REPO,
    })
    r = subprocess.run(
        [os.path.join(CPP, "transformer"), "8", "2", "16", "32", "4"],
        env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "transformer_c: SUCCESS" in r.stdout
