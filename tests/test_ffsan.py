"""ffsan (ISSUE 16): lock-order & retrace-hazard static passes plus the
runtime sanitizer plane.

Covers both halves of the acceptance contract:
  * HEAD is clean — `analyze_sources()` over flexflow_tpu/runtime finds
    zero errors and zero warnings (the lock-inventory test additionally
    pins that every runtime lock goes through the locks.py registry, so
    a new raw ``threading.Lock()`` fails CI here).
  * every seeded violation is caught WITH a file:line — inverted
    acquisition (direct and transitive), a lock held across a blocking
    call, jnp dispatch under a lock, an uncommitted device_put, and a
    registry bypass.
  * the runtime sanitizer catches the same two bug classes dynamically:
    order-asserting lock proxies (named pair + both stacks, strict
    raises) and the post-warmup retrace sentinel on a real jax.jit
    cache.
"""

import json
import os
import sys
import threading

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from flexflow_tpu.analysis.__main__ import main as fflint_main
from flexflow_tpu.analysis.sanitize import analyze_sources, default_paths
from flexflow_tpu.analysis.sanitize.lockgraph import build_lockgraph
from flexflow_tpu.runtime import locks
from flexflow_tpu.runtime.locks import (LOCK_RANKS, LockOrderViolation,
                                        RetraceSentinel, RetraceViolation)


@pytest.fixture
def san():
    """Sanitizer 'on' with clean evidence rings; always restored."""
    prev = locks.set_mode("on")
    locks.reset()
    yield locks
    locks.set_mode(prev)
    locks.reset()


@pytest.fixture
def strict():
    prev = locks.set_mode("strict")
    locks.reset()
    yield locks
    locks.set_mode(prev)
    locks.reset()


def _runtime_files():
    [runtime] = default_paths()
    return [os.path.join(runtime, n) for n in sorted(os.listdir(runtime))
            if n.endswith(".py")]


def _codes(report):
    return set(report.codes())


# ------------------------------------------------------------ clean @ HEAD


def test_runtime_clean_at_head():
    """The acceptance gate: both source passes clean over runtime/."""
    report = analyze_sources()
    assert not report.errors() and not report.warnings(), \
        report.format_text()


def test_lock_inventory_pins_registry():
    """Every lock in runtime/ comes from locks.make_* with a declared
    name — a new raw threading.Lock() (or an undeclared name) fails
    here before it fails in review."""
    graph = build_lockgraph(_runtime_files())
    for mod in graph.modules.values():
        raw = [(p, l) for kind, p, l in mod.raw_locks
               if not graph.allowed_at("raw-lock", p, l)]
        assert not raw, \
            f"raw threading primitive(s) bypass the registry: {raw}"
        assert not mod.unknown_factory, mod.unknown_factory
    used = set()
    for mod in graph.modules.values():
        used.update(mod.global_locks.values())
        for cls in mod.classes.values():
            used.update(cls["attr_locks"].values())
    assert used <= set(LOCK_RANKS), used - set(LOCK_RANKS)
    # the inventory the refactor migrated (ISSUE 16's named modules)
    for name in ("engine", "router", "prefix-cache", "adapter-pool",
                 "pipeline-loader", "checkpoint-saver", "watchdog",
                 "flightrec", "telemetry-registry", "telemetry-family",
                 "telemetry-tracer", "native-loader"):
        assert name in used, f"expected registered lock {name!r}"


def test_ranks_strictly_ordered_and_unique():
    ranks = list(LOCK_RANKS.values())
    assert len(set(ranks)) == len(ranks)
    assert LOCK_RANKS["router"] < LOCK_RANKS["engine"] \
        < LOCK_RANKS["flightrec"] < LOCK_RANKS["telemetry-registry"]


# ------------------------------------------------- seeded static mutations


def _seed(tmp_path, name, src):
    f = tmp_path / name
    f.write_text(src)
    return str(f)


def _analyze(path, passes=("concurrency", "tracestability")):
    return analyze_sources(paths=[path], passes=passes)


def _find(report, code):
    vs = report.by_code(code)
    assert vs, f"expected {code!r}; got {report.codes()}"
    for v in vs:
        assert v.file and v.line, f"{code}: missing file:line ({v})"
    return vs


def test_seeded_direct_inversion(tmp_path):
    path = _seed(tmp_path, "inv.py", """\
from flexflow_tpu.runtime import locks
_eng = locks.make_rlock("engine")
_rt = locks.make_rlock("router")

def tick():
    with _eng:
        with _rt:        # router(10) under engine(20): inverted
            pass
""")
    vs = _find(_analyze(path), "lock-order-inversion")
    assert any(v.line == 7 for v in vs), [v.line for v in vs]
    assert "'router'" in vs[0].message and "'engine'" in vs[0].message


def test_seeded_transitive_inversion(tmp_path):
    path = _seed(tmp_path, "trans.py", """\
from flexflow_tpu.runtime import locks
_eng = locks.make_rlock("engine")
_rt = locks.make_rlock("router")

def _admit():
    with _rt:
        pass

def tick():
    with _eng:
        _admit()         # acquires router(10) under engine(20)
""")
    vs = _find(_analyze(path), "lock-order-inversion")
    assert any("via" in v.message for v in vs), [v.message for v in vs]


def test_seeded_lock_across_blocking(tmp_path):
    path = _seed(tmp_path, "blk.py", """\
from flexflow_tpu.runtime import locks
_rt = locks.make_rlock("router")

def flush(arr):
    with _rt:
        arr.block_until_ready()
""")
    vs = _find(_analyze(path), "lock-across-blocking")
    assert "router" in vs[0].message


def test_engine_tick_waiver_is_structural(tmp_path):
    """The documented serving contract: engine lock across dispatch is
    NOT a finding — but any other lock in the same position is."""
    path = _seed(tmp_path, "waiv.py", """\
from flexflow_tpu.runtime import locks
_eng = locks.make_rlock("engine")

def tick(arr):
    with _eng:
        arr.block_until_ready()
""")
    assert not _analyze(path).by_code("lock-across-blocking")


def test_seeded_jnp_under_lock(tmp_path):
    path = _seed(tmp_path, "jnp.py", """\
import jax.numpy as jnp
from flexflow_tpu.runtime import locks
_rt = locks.make_rlock("router")

def score(x):
    with _rt:
        return jnp.sum(x)     # op-by-op dispatch under the lock

def builder(x):
    with _rt:
        def prog(y):
            return jnp.sum(y)  # traced-program body: NOT a finding
        return prog
""")
    vs = _find(_analyze(path), "jnp-under-lock")
    assert all(v.line == 7 for v in vs), [v.line for v in vs]


def test_seeded_uncommitted_device_put(tmp_path):
    path = _seed(tmp_path, "put.py", """\
import jax

def stage(x, dev):
    a = jax.device_put(x)          # uncommitted
    b = jax.device_put(x, dev)     # committed: clean
    return a, b
""")
    vs = _find(_analyze(path), "uncommitted-device-put")
    assert [v.line for v in vs] == [4]


def test_seeded_raw_lock_and_pragma(tmp_path):
    path = _seed(tmp_path, "raw.py", """\
import threading
_a = threading.Lock()
_b = threading.Lock()   # ffsan: allow(raw-lock) — test waiver
""")
    vs = _find(_analyze(path, passes=("concurrency",)), "raw-lock")
    assert [v.line for v in vs] == [2]   # pragma'd line 3 waived


def test_seeded_unknown_lock_name(tmp_path):
    path = _seed(tmp_path, "unk.py", """\
from flexflow_tpu.runtime import locks
_x = locks.make_lock("not-a-declared-name")
""")
    _find(_analyze(path, passes=("concurrency",)), "unknown-lock-name")


# --------------------------------------------------------------------- CLI


def test_cli_source_passes_clean_at_head(capsys):
    rc = fflint_main(["--passes", "concurrency,tracestability",
                      "--tiered-exit"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "0 error(s), 0 warning(s)" in out


def test_cli_tiered_exit_codes(tmp_path, capsys):
    err = _seed(tmp_path, "e.py", "import threading\n_l = threading.Lock()\n")
    warn = _seed(tmp_path, "w.py",
                 "import jax\n\ndef f(x):\n    return jax.device_put(x)\n")
    assert fflint_main(["--passes", "concurrency", "--path", err,
                        "--tiered-exit"]) == 2
    assert fflint_main(["--passes", "tracestability", "--path", warn,
                        "--tiered-exit"]) == 1
    # legacy exit codes stay pinned: errors -> 1, warnings alone -> 0
    assert fflint_main(["--passes", "concurrency", "--path", err]) == 1
    assert fflint_main(["--passes", "tracestability", "--path", warn]) == 0
    capsys.readouterr()


def test_cli_json_format(tmp_path, capsys):
    err = _seed(tmp_path, "e.py", "import threading\n_l = threading.Lock()\n")
    rc = fflint_main(["--passes", "concurrency", "--path", err,
                      "--format", "json", "--tiered-exit"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 2 and doc["num_errors"] == 1
    [v] = doc["violations"]
    assert v["code"] == "raw-lock" and v["file"] == err and v["line"] == 2


def test_cli_usage_is_64_under_tiered_exit(capsys):
    rc = fflint_main(["--passes", "legality", "--tiered-exit"])
    assert rc == 64      # model passes need both positionals
    assert "positionals" in capsys.readouterr().err


# -------------------------------------------------------- runtime: proxies


def test_proxy_detects_inversion_with_both_stacks(san):
    reg = locks.make_lock("telemetry-registry")
    eng = locks.make_rlock("engine")
    with eng:
        with reg:            # increasing rank: legal
            pass
    assert locks.violations() == []
    with reg:
        with eng:            # engine(20) under telemetry-registry(70)
            pass
    [v] = locks.violations()
    assert (v["outer"], v["inner"]) == ("telemetry-registry", "engine")
    assert "acquire" in v["outer_stack"] and v["inner_stack"]
    assert v["thread"] == threading.current_thread().name


def test_proxy_dedups_pairs_but_counts(san):
    reg = locks.make_lock("telemetry-registry")
    eng = locks.make_rlock("engine")
    for _ in range(3):
        with reg:
            with eng:
                pass
    assert len(locks.violations()) == 1
    snap = locks.lock_graph_snapshot()
    assert snap["violation_pairs"] == {"telemetry-registry->engine": 3}


def test_reentrant_and_same_object_always_legal(san):
    eng = locks.make_rlock("engine")
    with eng:
        with eng:            # RLock re-acquire
            pass
    assert locks.violations() == []


def test_strict_mode_raises(strict):
    reg = locks.make_lock("telemetry-registry")
    eng = locks.make_rlock("engine")
    with pytest.raises(LockOrderViolation, match="engine"):
        with reg:
            with eng:
                pass
    # the held-stack survived the raise: a clean acquisition still works
    with eng:
        with reg:
            pass


def test_condition_wait_keeps_held_stack_exact(san):
    cv = locks.make_condition("pipeline-loader")
    rt = locks.make_rlock("router")

    def waker():
        with cv:
            cv.notify_all()

    with cv:
        t = threading.Timer(0.05, waker)
        t.start()
        cv.wait(timeout=2.0)
        t.join()
        # still (re-)holding pipeline-loader(45) after the wait: taking
        # router(10) now must be flagged — proves _acquire_restore
        # re-noted the lock
        with rt:
            pass
    pairs = {(v["outer"], v["inner"]) for v in locks.violations()}
    assert ("pipeline-loader", "router") in pairs


def test_off_mode_returns_raw_primitives():
    prev = locks.set_mode("off")
    try:
        lk = locks.make_lock("engine")
        assert not hasattr(lk, "rank")          # raw threading.Lock
        assert isinstance(locks.make_condition("engine"),
                          threading.Condition)
    finally:
        locks.set_mode(prev)


def test_unknown_name_rejected_at_creation():
    with pytest.raises(ValueError, match="unknown lock name"):
        locks.make_lock("no-such-lock")


# ------------------------------------------------------- runtime: sentinel


def test_retrace_sentinel_on_real_jit_cache(san):
    jax = pytest.importorskip("jax")
    import numpy as np
    fn = jax.jit(lambda x: x + 1)
    s = RetraceSentinel("test-engine")
    x = jax.device_put(np.ones((4,), np.float32), jax.devices()[0])
    s.call("prog", fn, (x,))          # warmup trace
    s.arm()
    s.call("prog", fn, (x,))          # warm hit: clean
    assert s.hits == 0 and locks.retrace_log() == []
    y = jax.device_put(np.ones((4,), np.float32))   # uncommitted twin
    s.call("prog", fn, (y,))
    assert s.hits == 1
    [rec] = locks.retrace_log()
    assert rec["kind"] == "retrace" and rec["owner"] == "test-engine"
    assert any("UNCOMMITTED" in sig for sig in rec["signature"]), rec


def test_sentinel_note_miss_and_suspended(san):
    s = RetraceSentinel("t")
    s.note_miss("early", ())          # pre-arm: warmup compiles are free
    s.arm()
    with s.suspended():               # deliberate warm-path compile
        s.note_miss("imported-page", ())
    assert s.hits == 0
    s.note_miss("late-program", ())
    assert s.hits == 1
    [rec] = locks.retrace_log()
    assert rec["kind"] == "new-program" and "late-program" in rec["program"]


def test_sentinel_strict_raises(strict):
    s = RetraceSentinel("t")
    s.arm()
    with pytest.raises(RetraceViolation, match="late"):
        s.note_miss("late", ())


def test_sentinel_off_mode_is_passthrough():
    prev = locks.set_mode("off")
    try:
        s = RetraceSentinel("t")
        s.arm()
        s.note_miss("anything", ())
        assert s.hits == 0
    finally:
        locks.set_mode(prev)


# ------------------------------------------------------ snapshot & config


def test_lock_graph_snapshot_shape(san):
    eng = locks.make_rlock("engine")
    snap = locks.lock_graph_snapshot()
    assert snap["mode"] == "on"
    assert snap["ranks"] == LOCK_RANKS
    assert {"name": "engine", "rank": 20} in snap["tracked_locks"]
    for key in ("violation_pairs", "violations", "retraces"):
        assert key in snap
    json.dumps(snap)                  # bundle-serializable


def test_config_knob_validation_and_adoption():
    from flexflow_tpu.config import FFConfig
    with pytest.raises(ValueError, match="sanitize"):
        FFConfig(sanitize="bogus")
    prev = locks.mode()
    try:
        locks.configure(FFConfig(sanitize="strict"))
        assert locks.mode() == "strict"
        locks.configure(FFConfig())          # empty: leaves mode alone
        assert locks.mode() == "strict"
    finally:
        locks.set_mode(prev)
