"""Chunk-interleaved prefill admission (ISSUE 18 tentpole, layer a).

A cold prompt longer than one ``prefill_chunk`` no longer prefills to
completion at admission: under ``FFConfig.prefill_interleave_chunks``
its chunks become schedulable quanta interleaved with decode ticks, so
a monster prompt cannot head-of-line-block the replica's decode
streams. Pinned here:

  * token identity — interleaved admission emits exactly the
    run-to-completion stream (greedy AND sampled, einsum AND pallas
    write impls, full-width AND int8 pools): the chunk programs are
    iteration-for-iteration Generator._prefill's ragged chunked loop;
  * the kv_pages default derive leaves prefix-cache slack (the PR 11
    zero-slack finding, fixed here) and logs the split;
  * mid-prefill deadline/fault/drain legs — a slot parked between
    chunks retires/completes exactly like an active one;
  * observability — the new stats keys and the inter-token histogram.

Sequence-parallel prefill (layer b) is pinned in test_seq_parallel.py;
the Pallas write kernel (layer c) in test_pallas_paged.py.
"""

import time

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.models.llama import llama_lm
from flexflow_tpu.runtime import faultinject

VOCAB = 61
PS = 4


@pytest.fixture(scope="module")
def ff():
    cfg = FFConfig(batch_size=2, mesh_shape={"data": 1})
    model = FFModel(cfg)
    _, logits = llama_lm(model, 2, seq_len=16, hidden=32, layers=2,
                         heads=2, kv_heads=2, vocab_size=VOCAB)
    model.compile(final_tensor=logits)
    return model


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("FF_FAULT", raising=False)
    faultinject.reset()
    yield
    faultinject.reset()


def _prompts(seed, lengths):
    rs = np.random.RandomState(seed)
    return [rs.randint(1, VOCAB, (L,)).astype(np.int32) for L in lengths]


# ---- knobs and validation (host-side, tier-1 fast) ------------------------


def test_longctx_knob_validation():
    with pytest.raises(ValueError, match="prefill_interleave_chunks"):
        FFConfig(batch_size=2, mesh_shape={"data": 1},
                 prefill_interleave_chunks=-1)
    with pytest.raises(ValueError, match="seq_parallel_shards"):
        FFConfig(batch_size=2, mesh_shape={"data": 1},
                 seq_parallel_shards=1)
    cfg = FFConfig.parse_args(
        ["--prefill-interleave-chunks", "2", "--seq-parallel-shards", "2",
         "--batch-size", "2"])
    assert cfg.prefill_interleave_chunks == 2
    assert cfg.seq_parallel_shards == 2


@pytest.mark.slow  # model fixture; longctx CI tier runs the full file
def test_longctx_engine_router_validation(ff):
    # the chunk is the interleave quantum: interleaving without chunked
    # prefill has no unit of work to schedule
    with pytest.raises(ValueError, match="prefill_chunk"):
        ff.make_serving_engine(serve_slots=1, kv_page_size=PS,
                               max_seq_len=32, prefill_chunk=0,
                               prefill_interleave_chunks=1)
    with pytest.raises(ValueError, match="seq_parallel_shards"):
        ff.make_serving_router(replicas=2, roles="prefill,decode",
                               seq_parallel_shards=1, max_seq_len=32,
                               start=False)


@pytest.mark.slow  # builds 4 engines; longctx CI tier runs the full file
def test_kv_pages_default_derive_leaves_prefix_slack(ff):
    """The PR 11 finding, fixed: the derived pool must leave slack
    beyond the slots' own pages, or every published prefix page fights
    the next admission and the cache silently goes cold. Derive = 1
    scratch + slots * pages_per_slot + max(pages_per_slot,
    slot_pages // 2) when the prefix cache is on."""
    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=PS,
                                 max_seq_len=32)
    # pages_per_slot = 32/4 = 8; slots 2 -> slot pages 16; slack 8
    assert eng.pages_per_slot == 8
    assert eng.num_pages == 1 + 16 + 8
    # no prefix cache -> nothing to leave slack for
    bare = ff.make_serving_engine(serve_slots=2, kv_page_size=PS,
                                  max_seq_len=32, prefix_cache=False)
    assert bare.num_pages == 1 + 16
    # an explicit kv_pages is always honored verbatim
    pinned = ff.make_serving_engine(serve_slots=2, kv_page_size=PS,
                                    max_seq_len=32, kv_pages=40)
    assert pinned.num_pages == 40
    # big slot counts get at least half the slot pages as slack
    wide = ff.make_serving_engine(serve_slots=4, kv_page_size=PS,
                                  max_seq_len=32)
    assert wide.num_pages == 1 + 32 + 16


@pytest.mark.slow  # model fixture; longctx CI tier runs the full file
def test_longctx_stats_keys_pinned(ff):
    eng = ff.make_serving_engine(serve_slots=1, kv_page_size=PS,
                                 max_seq_len=32, prefill_chunk=PS,
                                 prefill_interleave_chunks=1)
    st = eng.stats()
    for key in ("prefill_interleave_chunks", "prefill_chunks_interleaved",
                "prefill_preempted_ticks", "prefill_partial_slots",
                "partial_slab_imports"):
        assert key in st, key
    assert st["prefill_interleave_chunks"] == 1
    assert st["prefill_chunks_interleaved"] == 0
    reqs = eng.run(_prompts(7, [11]), max_new_tokens=3)
    assert reqs[0].state == "done"
    st = eng.stats()
    assert st["prefill_chunks_interleaved"] == 4   # bucket 16 / chunk 4
    assert st["prefill_partial_slots"] == 0


# ---- token identity -------------------------------------------------------


@pytest.mark.slow  # ~20 s; longctx CI tier runs the full file
def test_interleaved_prefill_token_identical(ff):
    """Interleaved admission vs run-to-completion, greedy and sampled,
    more requests than slots so mid-prefill slots coexist with live
    decode streams: every emitted stream must be identical — the chunk
    quanta replay Generator._prefill's exact loop, so scheduling is
    invisible in the tokens."""
    prompts = _prompts(17, [13, 5, 11, 9, 14, 3, 7])
    base = ff.make_serving_engine(serve_slots=2, kv_page_size=PS,
                                  max_seq_len=32, prefill_chunk=PS)
    want = [list(r.tokens) for r in base.run(prompts, max_new_tokens=5)]
    for budget in (1, 2):
        eng = ff.make_serving_engine(serve_slots=2, kv_page_size=PS,
                                     max_seq_len=32, prefill_chunk=PS,
                                     prefill_interleave_chunks=budget)
        got = [list(r.tokens) for r in eng.run(prompts, max_new_tokens=5)]
        assert got == want, f"budget {budget} changed a greedy stream"
        st = eng.stats()
        assert st["prefill_chunks_interleaved"] > 0
        assert st["prefill_partial_slots"] == 0
    # sampled: same seeds -> same streams regardless of scheduling
    kw = dict(temperature=0.9, top_p=0.8, top_k=7)
    want_s = [list(r.tokens) for r in base.run(
        prompts, max_new_tokens=5, seed=123, **kw)]
    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=PS,
                                 max_seq_len=32, prefill_chunk=PS,
                                 prefill_interleave_chunks=1)
    got_s = [list(r.tokens) for r in eng.run(
        prompts, max_new_tokens=5, seed=123, **kw)]
    assert got_s == want_s, "interleaving changed a sampled stream"


@pytest.mark.slow  # ~15 s; longctx CI tier runs the full file
def test_interleaved_prefill_identity_int8_and_pallas(ff):
    """The same identity under an int8 pool and the pallas write impl:
    the interleaved final scatter must land bitwise the pages the
    run-to-completion program lands (scales included), so the streams
    cannot diverge."""
    prompts = _prompts(19, [12, 6, 9])
    for kw in (dict(kv_cache_dtype="int8"),
               dict(paged_attention_impl="pallas"),
               dict(kv_cache_dtype="int8",
                    paged_attention_impl="pallas")):
        base = ff.make_serving_engine(serve_slots=2, kv_page_size=PS,
                                      max_seq_len=32, prefill_chunk=PS,
                                      **kw)
        want = [list(r.tokens)
                for r in base.run(prompts, max_new_tokens=4)]
        eng = ff.make_serving_engine(serve_slots=2, kv_page_size=PS,
                                     max_seq_len=32, prefill_chunk=PS,
                                     prefill_interleave_chunks=1, **kw)
        got = [list(r.tokens) for r in eng.run(prompts, max_new_tokens=4)]
        assert got == want, f"interleave changed a stream under {kw}"


# ---- mid-prefill deadline / fault / drain legs ----------------------------


@pytest.mark.slow  # model fixture; longctx CI tier runs the full file
def test_mid_prefill_deadline_expires(ff):
    eng = ff.make_serving_engine(serve_slots=1, kv_page_size=PS,
                                 max_seq_len=32, prefill_chunk=PS,
                                 prefill_interleave_chunks=1)
    req = eng.submit(_prompts(23, [13])[0], max_new_tokens=4,
                     deadline=time.perf_counter() + 60.0)
    eng.step()                       # admit + first chunk
    assert eng.stats()["prefill_partial_slots"] == 1
    req.deadline = time.perf_counter() - 0.001
    eng.step()                       # deadline sweep fires pre-budget
    assert req.state == "timeout"
    assert eng.stats()["prefill_partial_slots"] == 0
    assert eng.stats()["timeouts"] == 1
    # the slot and its pages are reusable: a follow-up completes
    done = eng.run(_prompts(24, [9, 5]), max_new_tokens=4)
    assert [r.state for r in done] == ["done", "done"]


@pytest.mark.slow  # model fixture; longctx CI tier runs the full file
def test_mid_prefill_nan_poison_fails_request(ff, monkeypatch):
    """The nan_loss drill must catch an interleaved admission too: the
    poison rides the slot-resident partial state into the FINAL chunk's
    logits, the request retires "failed", and the engine keeps
    serving."""
    monkeypatch.setenv("FF_FAULT", "nan_loss@serve:1")
    faultinject.reset()
    eng = ff.make_serving_engine(serve_slots=1, kv_page_size=PS,
                                 max_seq_len=32, prefill_chunk=PS,
                                 prefill_interleave_chunks=1)
    prompts = _prompts(29, [13, 7])
    reqs = eng.run(prompts, max_new_tokens=4)
    assert reqs[0].state == "failed"
    assert "non-finite" in reqs[0].error
    assert reqs[1].state == "done"
    assert eng.stats()["failed"] == 1


@pytest.mark.slow  # model fixture; longctx CI tier runs the full file
def test_drain_completes_mid_prefill_slots(ff):
    """An admitted request is never cancelled: drain() must keep
    spending prefill quanta until mid-prefill slots finish and decode
    out, even though admission is closed."""
    eng = ff.make_serving_engine(serve_slots=1, kv_page_size=PS,
                                 max_seq_len=32, prefill_chunk=PS,
                                 prefill_interleave_chunks=1)
    req = eng.submit(_prompts(31, [13])[0], max_new_tokens=3)
    eng.step()                       # admit + first chunk only
    assert eng.stats()["prefill_partial_slots"] == 1
    st = eng.drain()
    assert req.state == "done" and len(req.tokens) == 3
    assert st["drained"] and eng.stats()["prefill_partial_slots"] == 0


@pytest.mark.slow  # model fixture; longctx CI tier runs the full file
def test_interleave_emits_intertoken_histogram(ff):
    """The inter-token histogram (the head-of-line metric this ISSUE
    exists to flatten) must keep counting under interleaved admission."""
    from flexflow_tpu.runtime import telemetry

    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=PS,
                                 max_seq_len=32, prefill_chunk=PS,
                                 prefill_interleave_chunks=1)
    eng.set_telemetry_identity("lc0", "longctx-test")
    reqs = eng.run(_prompts(37, [11, 6]), max_new_tokens=4)
    assert all(r.state == "done" for r in reqs)
    itl = telemetry.registry().histogram(
        "ff_serving_intertoken_seconds", labels=("replica", "role"))
    assert itl.labels("lc0", "longctx-test").count == 2 * 3
