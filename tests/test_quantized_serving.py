"""Quantized serving tier (ISSUE 11): int8/fp8 KV pages with in-kernel
dequant + weight-only int8/fp8 serving matmuls.

Correctness anchors:
  * scale round-trip — per-(page, head) quantization error is bounded by
    scale/2 (int8), and REQUANTIZATION at an unchanged scale is exact:
    the append path's unconditional page requant cannot drift tokens
    whose page scale never grew;
  * per-OUTPUT-CHANNEL weight scales are strictly no worse than a
    per-tensor baseline on every zoo layer they quantize (the satellite
    regression pin);
  * pallas-vs-einsum parity on quantized pools: pool state BITWISE
    (the write/requant protocol is shared code), attention to kernel
    tolerance, greedy engine streams token-IDENTICAL with prefix cache
    + speculation + the kernel path all live;
  * copy-on-write survives quantization: a donor's published pages —
    payload AND scales — are bitwise untouched by borrower traffic;
  * quantized engines stay on the one-program contract (recompile
    flatness) and expose the capacity observability keys;
  * full-width divergence budget: quantized KV/weights are lossy by
    design — the budget pinned here is the documented per-dtype bar
    (docs/serving.md "Quantized tier"), not token identity.

All quantized paths run on CPU: the Pallas kernel in interpret mode is
the REAL kernel code path (the ISSUE-7 routing rule).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.models.llama import llama_lm
from flexflow_tpu.ops.attention import (kv_storage_dtype, page_dequantize,
                                        page_quantize, page_scale,
                                        storage_qmax)
from flexflow_tpu.runtime.generation import Generator

VOCAB = 89
TOL = dict(rtol=2e-5, atol=2e-5)
# documented per-dtype divergence budgets vs the full-width path: the
# minimum fraction of greedy positions that must match, measured over
# short mixed streams on the tiny zoo model (deterministic at a pinned
# seed — this is a regression bar, not a statistical test). See
# docs/serving.md "Quantized tier" for the budget rationale.
DIVERGENCE_BUDGET = {"int8": 0.6, "fp8": 0.6}

HAS_FP8 = getattr(jnp, "float8_e4m3fn", None) is not None


@pytest.fixture(scope="module")
def ff():
    cfg = FFConfig(batch_size=2, mesh_shape={"data": 1})
    model = FFModel(cfg)
    # kv_heads=2 < heads=4: GQA grouping always exercised
    _, logits = llama_lm(model, 2, seq_len=16, hidden=64, layers=2,
                         heads=4, kv_heads=2, vocab_size=VOCAB)
    model.compile(final_tensor=logits)
    return model


@pytest.fixture(scope="module")
def attn(ff):
    return next(op for op in ff.ops
                if type(op).__name__ == "MultiHeadAttention")


# ---- knobs & helpers -------------------------------------------------------


def test_config_validation_and_flags():
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        FFConfig(batch_size=2, mesh_shape={"data": 1},
                 kv_cache_dtype="int4")
    with pytest.raises(ValueError, match="serve_weight_dtype"):
        FFConfig(batch_size=2, mesh_shape={"data": 1},
                 serve_weight_dtype="bf16")
    cfg = FFConfig.parse_args(["--kv-cache-dtype", "int8",
                               "--serve-weight-dtype", "fp8"])
    assert cfg.kv_cache_dtype == "int8"
    assert cfg.serve_weight_dtype == "fp8"
    # defaults keep the pre-quant behavior
    assert FFConfig.parse_args([]).kv_cache_dtype == "native"
    assert FFConfig.parse_args([]).serve_weight_dtype == "native"


def test_kv_storage_dtype_mapping():
    assert kv_storage_dtype(None) == (None, None)
    assert kv_storage_dtype("native") == (None, None)
    sd, qm = kv_storage_dtype("bf16")
    assert sd == jnp.bfloat16 and qm is None
    sd, qm = kv_storage_dtype("int8")
    assert sd == jnp.int8 and qm == 127.0
    if HAS_FP8:
        sd, qm = kv_storage_dtype("fp8")
        assert sd == jnp.float8_e4m3fn
        assert qm == float(jnp.finfo(jnp.float8_e4m3fn).max)
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        kv_storage_dtype("int4")
    assert storage_qmax(jnp.int8) == 127.0


def test_scale_round_trip_and_same_scale_requant_exact():
    """int8: |dequant(quant(x)) - x| <= scale/2 per element; and the
    append-path invariant — requantizing at an UNCHANGED scale is the
    identity on the stored payload, for int8 AND fp8."""
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(3, 8, 2, 16) * 5.0, jnp.float32)
    for dt in ("int8",) + (("fp8",) if HAS_FP8 else ()):
        sdtype, qmax = kv_storage_dtype(dt)
        sc = page_scale(x, qmax)                       # (3, 2)
        q = page_quantize(x, sc, qmax, sdtype)
        deq = page_dequantize(q, sc)
        if dt == "int8":
            bound = np.asarray(sc)[:, None, :, None] / 2 + 1e-6
            assert (np.abs(np.asarray(deq - x)) <= bound).all()
        # same-scale requant: bitwise identity on the payload
        q2 = page_quantize(deq, sc, qmax, sdtype)
        np.testing.assert_array_equal(np.asarray(q).view(np.uint8),
                                      np.asarray(q2).view(np.uint8))


def test_per_channel_no_worse_than_per_tensor(ff):
    """The satellite regression pin: per-output-channel weight scales
    must give a max-abs dequant error STRICTLY no worse than a
    per-tensor scale on every zoo layer the quantizer touches — and on
    3-D attention weights (per-head channels) strictly better
    somewhere, or the upgrade did nothing."""
    gen = Generator(ff, quantize="int8")
    qp = gen._quantized_params()
    checked = strict_win = 0
    for op_name, ws in ff.params.items():
        for w_name, w in ws.items():
            if not (w.ndim >= 2 and jnp.issubdtype(w.dtype, jnp.floating)):
                continue
            wf = np.asarray(w, np.float32)
            entry = qp[op_name][w_name]
            deq = np.asarray(entry["q"], np.float32) * np.asarray(entry["s"])
            err_channel = np.abs(deq - wf).max()
            s_tensor = max(np.abs(wf).max() / 127.0, 1e-12)
            q_t = np.clip(np.round(wf / s_tensor), -127, 127)
            err_tensor = np.abs(q_t * s_tensor - wf).max()
            assert err_channel <= err_tensor + 1e-9, (
                f"{op_name}/{w_name}: per-channel err {err_channel} > "
                f"per-tensor {err_tensor}")
            checked += 1
            if err_channel < err_tensor * 0.999:
                strict_win += 1
    assert checked >= 4, "the zoo model must expose quantizable layers"
    assert strict_win >= 1, \
        "per-channel scales never beat per-tensor anywhere"


@pytest.mark.skipif(not HAS_FP8, reason="jax build lacks float8_e4m3fn")
def test_fp8_weight_quantization_finite():
    """fp8 weight-only: quantized tree stores float8_e4m3fn with finite
    payload (overflow would cast to nan — the clip-before-cast rule)."""
    cfg = FFConfig(batch_size=2, mesh_shape={"data": 1})
    ff = FFModel(cfg)
    _, logits = llama_lm(ff, 2, seq_len=8, hidden=32, layers=1, heads=2,
                         kv_heads=2, vocab_size=37)
    ff.compile(final_tensor=logits)
    gen = Generator(ff, quantize="fp8")
    qp = gen._quantized_params()
    seen = 0
    for ws in qp.values():
        for v in ws.values():
            if isinstance(v, dict) and "q" in v:
                assert v["q"].dtype == jnp.float8_e4m3fn
                assert bool(jnp.isfinite(
                    v["q"].astype(jnp.float32)).all())
                seen += 1
    assert seen >= 4
    with pytest.raises(ValueError, match="quantize"):
        Generator(ff, quantize="int4")


# ---- pool write protocol ---------------------------------------------------


def test_prefill_write_sets_scales_pad_tail_harmless(attn):
    """paged_prefill_write on a quantized pool: per-(page, head) scales
    land next to the payload, and the zero pad tail of the last page
    never inflates a scale (the amax is the real tokens')."""
    rs = np.random.RandomState(5)
    pool = attn.init_paged_cache(6, 4, jnp.float32, kv_dtype="int8")
    kh = jnp.asarray(rs.randn(1, 6, 2, 16), jnp.float32)   # 1.5 pages
    vh = jnp.asarray(rs.randn(1, 6, 2, 16), jnp.float32)
    out = attn.paged_prefill_write(pool, kh, vh, jnp.asarray([2, 4],
                                                            jnp.int32))
    assert out["k"].dtype == jnp.int8
    # page 4 holds tokens 4..5 + 2 pad zeros: its scale is the amax of
    # the REAL tokens only
    want = np.abs(np.asarray(kh[0, 4:6], np.float32)).max(axis=(0, 2)) / 127
    np.testing.assert_allclose(np.asarray(out["k_scale"][4]), want,
                               rtol=1e-6)
    # untouched pages keep scale 0 (nothing cached there yet)
    assert float(out["k_scale"][1].max()) == 0.0


def test_append_requant_exact_when_scale_unchanged(attn):
    """Appending a token SMALLER than the page's running max must leave
    every previously stored element bitwise unchanged — the same-scale
    requant exactness the protocol relies on (a growing scale re-rounds,
    which is the documented divergence budget, not silent drift)."""
    rs = np.random.RandomState(7)
    pool = attn.init_paged_cache(4, 4, jnp.float32, kv_dtype="int8")
    big = jnp.asarray(rs.randn(1, 4, 2, 16) * 8.0, jnp.float32)
    pool = attn.paged_prefill_write(pool, big, big,
                                    jnp.asarray([1], jnp.int32))
    before_k = np.asarray(pool["k"][1]).copy()
    small = jnp.asarray(rs.randn(1, 2, 16) * 0.1, jnp.float32)
    out = attn._paged_append(pool, small[0][None], small[0][None],
                             jnp.asarray([1], jnp.int32),
                             jnp.asarray([2], jnp.int32))
    after_k = np.asarray(out["k"][1])
    # positions 0, 1, 3 never re-round; position 2 holds the new token
    for pos in (0, 1, 3):
        np.testing.assert_array_equal(before_k[pos], after_k[pos])
    np.testing.assert_array_equal(np.asarray(pool["k_scale"][1]),
                                  np.asarray(out["k_scale"][1]))


def test_quantized_decode_and_verify_pallas_matches_einsum(ff, attn):
    """Kernel parity on a quantized pool: the in-kernel dequant against
    scalar-prefetched scales must match the dequantizing einsum gather
    (the oracle) to kernel tolerance; the write/requant halves are
    shared code, so the returned pools must be BITWISE equal."""
    rs = np.random.RandomState(11)
    params = {k: jnp.asarray(v) for k, v in ff.params[attn.name].items()}
    for dt in ("int8",) + (("fp8",) if HAS_FP8 else ()):
        pool = attn.init_paged_cache(10, 4, jnp.float32, kv_dtype=dt)
        kh = jnp.asarray(rs.randn(1, 14, 2, 16), jnp.float32)
        vh = jnp.asarray(rs.randn(1, 14, 2, 16), jnp.float32)
        pool = attn.paged_prefill_write(
            pool, kh, vh, jnp.asarray([5, 2, 7, 1], jnp.int32))
        table = jnp.asarray([[5, 2, 7, 1], [3, 6, 4, 8]], jnp.int32)
        x = jnp.asarray(rs.randn(2, 1, attn.q_in), jnp.float32)
        wp = jnp.asarray([9, 13], jnp.int32)
        rope = jnp.asarray([4, 7], jnp.int32)
        rl = jnp.asarray([3, 7], jnp.int32)
        pad = jnp.asarray([8, 8], jnp.int32)
        oe, ce = attn.paged_decode_forward(
            params, [x, x, x], pool, table, wp, rope, rl, pad,
            impl="einsum")
        op_, cp = attn.paged_decode_forward(
            params, [x, x, x], pool, table, wp, rope, rl, pad,
            impl="pallas")
        np.testing.assert_allclose(np.asarray(oe), np.asarray(op_), **TOL)
        for n in ce:
            np.testing.assert_array_equal(np.asarray(ce[n]),
                                          np.asarray(cp[n]),
                                          err_msg=f"{dt}/{n}")
        # verify slab (per-position frontiers + sequential appends)
        s = 3
        xs_ = jnp.asarray(rs.randn(2, s, attn.q_in), jnp.float32)
        wps = jnp.minimum(
            jnp.asarray([9, 11], jnp.int32)[:, None]
            + jnp.arange(s)[None, :], 13)
        ve, cve = attn.paged_verify_forward(
            params, [xs_, xs_, xs_], pool, table, wps, rope, rl, pad,
            impl="einsum")
        vp, cvp = attn.paged_verify_forward(
            params, [xs_, xs_, xs_], pool, table, wps, rope, rl, pad,
            impl="pallas")
        np.testing.assert_allclose(np.asarray(ve), np.asarray(vp), **TOL)
        for n in cve:
            np.testing.assert_array_equal(np.asarray(cve[n]),
                                          np.asarray(cvp[n]),
                                          err_msg=f"{dt}/verify/{n}")


# ---- engine-level contracts ------------------------------------------------


@pytest.mark.slow  # ~40 s: two engines; quant CI tier runs the file
def test_engine_token_identity_pallas_vs_einsum_quantized(ff):
    """THE parity pin: a greedy serving run on an int8 pool with int8
    weights, prefix cache ON and speculation ON emits exactly the same
    streams under impl='pallas' (interpret-mode kernel) and
    impl='einsum' — quantization changes numbers, never the
    pallas/einsum contract."""
    rs = np.random.RandomState(17)
    system = rs.randint(1, VOCAB, (8,)).astype(np.int32)
    prompts = [np.concatenate([system,
                               rs.randint(1, VOCAB, (L,)).astype(np.int32)])
               for L in (2, 5, 1, 4)] \
        + [rs.randint(1, VOCAB, (6,)).astype(np.int32)]
    outs = {}
    for impl in ("einsum", "pallas"):
        eng = ff.make_serving_engine(
            serve_slots=2, kv_page_size=4, max_seq_len=64,
            kv_cache_dtype="int8", weight_dtype="int8",
            draft_model=ff, speculate_k=2, paged_attention_impl=impl)
        reqs = eng.run(prompts, max_new_tokens=5)
        assert [r.state for r in reqs] == ["done"] * len(prompts)
        outs[impl] = [np.asarray(r.tokens, np.int32) for r in reqs]
        st = eng.stats()
        assert st["kv_cache_dtype"] == "int8"
        assert st["weight_dtype"] == "int8"
        assert st["prefix_hits"] > 0 and st["spec_accepted"] > 0
    for a, b in zip(outs["einsum"], outs["pallas"]):
        np.testing.assert_array_equal(
            a, b, err_msg="quantized pallas serving changed the greedy "
                          "stream vs the einsum oracle")


@pytest.mark.slow  # ~35 s; quant CI tier
def test_divergence_budget_vs_full_width(ff):
    """Quantized KV (+ weights) is lossy by design: greedy streams may
    diverge from the full-width path. The documented per-dtype budget
    (DIVERGENCE_BUDGET) is the floor on positionwise agreement over a
    pinned mixed workload — deterministic at this seed, so a numerics
    regression (not mere divergence) trips it."""
    rs = np.random.RandomState(23)
    prompts = [rs.randint(1, VOCAB, (int(n),)).astype(np.int32)
               for n in (6, 11, 3, 9)]
    ref = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                 max_seq_len=64)
    want = [np.asarray(r.tokens, np.int32)
            for r in ref.run(prompts, max_new_tokens=6)]
    dtypes = ["int8"] + (["fp8"] if HAS_FP8 else [])
    for dt in dtypes:
        eng = ff.make_serving_engine(
            serve_slots=2, kv_page_size=4, max_seq_len=64,
            kv_cache_dtype=dt, weight_dtype=dt,
            paged_attention_impl="pallas")
        reqs = eng.run(prompts, max_new_tokens=6)
        assert [r.state for r in reqs] == ["done"] * len(prompts)
        got = [np.asarray(r.tokens, np.int32) for r in reqs]
        agree = float(np.mean([np.mean(a == b)
                               for a, b in zip(want, got)]))
        assert agree >= DIVERGENCE_BUDGET[dt], (
            f"{dt}: greedy agreement {agree:.3f} below the documented "
            f"budget {DIVERGENCE_BUDGET[dt]}")


@pytest.mark.slow  # ~15 s; quant CI tier
def test_cow_isolation_quantized(ff):
    """Copy-on-write survives quantization: borrowers mounting a cached
    prefix write tails/decodes into their OWN pages — the donor's
    published pages are bitwise untouched in payload AND scales."""
    rs = np.random.RandomState(29)
    system = rs.randint(1, VOCAB, (8,)).astype(np.int32)
    prompts = [np.concatenate([system,
                               rs.randint(1, VOCAB, (L,)).astype(np.int32)])
               for L in (2, 6, 4)]
    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                 max_seq_len=64, kv_cache_dtype="int8")
    eng.run([prompts[0]], max_new_tokens=4)      # publish the prefix
    pc = eng.prefix_cache
    shared = []
    node = pc.root
    while node.children:
        node = next(iter(node.children.values()))
        shared.append(node.page)
    assert len(shared) >= 2
    shared = np.asarray(shared, np.int32)
    names = ("k", "v", "k_scale", "v_scale")
    before = {op.name: {n: np.asarray(eng.pool[op.name][n][shared]).copy()
                        for n in names}
              for op in eng.gen.attn_ops}
    reqs = eng.run(prompts[1:], max_new_tokens=4)
    for r in reqs:
        assert r.state == "done" and r.prefix_tokens >= 8
    for op in eng.gen.attn_ops:
        for n in names:
            np.testing.assert_array_equal(
                before[op.name][n],
                np.asarray(eng.pool[op.name][n][shared]),
                err_msg=f"shared quantized page of {op.name}/{n} was "
                        f"written in place (COW violated)")
    st = eng.stats()
    assert st["kv_pages_shared"] == 0  # all retired
    assert st["prefix_refs_live"] == 0


@pytest.mark.slow  # ~20 s; quant CI tier
def test_recompile_flat_quantized(ff):
    """The one-program contract survives the quantized tier: after
    bucket warmup, mixed same-bucket traffic on an int8 pool with int8
    weights compiles nothing new (weights quantized once at init)."""
    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                 max_seq_len=64, kv_cache_dtype="int8",
                                 weight_dtype="int8",
                                 paged_attention_impl="pallas")
    rs = np.random.RandomState(31)
    eng.run([rs.randint(1, VOCAB, (5,)).astype(np.int32),
             rs.randint(1, VOCAB, (12,)).astype(np.int32)],
            max_new_tokens=4)                     # warm buckets 8 + 16
    warm = eng.recompile_count
    eng.run([rs.randint(1, VOCAB, (n,)).astype(np.int32)
             for n in (6, 3, 9, 14, 2)], max_new_tokens=6)
    assert eng.recompile_count == warm, \
        "warm quantized traffic must not recompile"


def test_stats_observability(ff):
    """The router/bench signals: dtypes, bytes-per-token (scales
    included), tokens-per-pool-GB and the capacity multiplier — and the
    bf16 pool halves an f32 pool without any scale machinery."""
    e8 = ff.make_serving_engine(serve_slots=1, kv_page_size=8,
                                max_seq_len=32, kv_cache_dtype="int8")
    ebf = ff.make_serving_engine(serve_slots=1, kv_page_size=8,
                                 max_seq_len=32, kv_cache_dtype="bf16")
    enat = ff.make_serving_engine(serve_slots=1, kv_page_size=8,
                                  max_seq_len=32)
    s8, sbf, snat = e8.stats(), ebf.stats(), enat.stats()
    assert s8["kv_cache_dtype"] == "int8"
    assert sbf["kv_cache_dtype"] == "bfloat16"
    assert snat["kv_cache_dtype"] == "float32"
    assert s8["weight_dtype"] == "native"
    # f32 native -> bf16 is exactly 2x; bf16 -> int8 is ~2x minus the
    # scale sliver (per-page-per-head f32 scales)
    assert snat["kv_bytes_per_token"] == 2 * sbf["kv_bytes_per_token"]
    assert 1.7 < sbf["kv_bytes_per_token"] / s8["kv_bytes_per_token"] <= 2
    assert s8["tokens_per_pool_gb"] > 1.7 * sbf["tokens_per_pool_gb"]
    assert s8["kv_capacity_vs_bf16"] > 1.7
    assert sbf["kv_capacity_vs_bf16"] == 1.0
    assert s8["kv_effective_page_capacity"] > 8  # > page_size tokens
    assert s8["kv_pool_bytes"] < sbf["kv_pool_bytes"] \
        < snat["kv_pool_bytes"]
    h = e8.health()
    assert h["kv_cache_dtype"] == "int8" and h["weight_dtype"] == "native"
    assert h["tokens_per_pool_gb"] == s8["tokens_per_pool_gb"]


def test_weight_dtype_conflict_and_validation(ff):
    with pytest.raises(ValueError, match="weight_dtype"):
        ff.make_serving_engine(weight_dtype="int4", max_seq_len=32)
    with pytest.raises(ValueError, match="conflicts"):
        ff.make_serving_engine(weight_dtype="int8", quantize="fp8",
                               max_seq_len=32)
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        ff.make_serving_engine(kv_cache_dtype="int4", max_seq_len=32)
    # legacy quantize= keeps working and is reported as the weight dtype
    eng = ff.make_serving_engine(serve_slots=1, kv_page_size=8,
                                 max_seq_len=32, quantize="int8")
    assert eng.stats()["weight_dtype"] == "int8"


def test_paged_impl_tuning_table(tmp_path, ff):
    """tune_paged_attention persists a measured impl winner keyed by the
    POOL dtype; an 'auto' engine consults it at construction, and an
    entry tuned on int8 pages can never govern a full-width pool."""
    from flexflow_tpu.search import kernel_tune

    table = str(tmp_path / "ktune.json")
    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                 max_seq_len=32, kv_cache_dtype="int8")
    op0 = eng.gen.attn_ops[0]
    rec = kernel_tune.tune_paged_attention(
        page_size=eng.page_size, pages_per_slot=eng.pages_per_slot,
        head_dim=op0.qk_head_dim, kv_heads=op0.num_kv_heads,
        heads=op0.num_heads, slots=eng.slots, kv_dtype="int8",
        iters=1, path=table)
    assert rec["impl"] in ("pallas", "einsum")
    assert rec["kv_dtype"] == "int8"
    got = kernel_tune.lookup_paged_impl(
        page_size=eng.page_size, pages_per_slot=eng.pages_per_slot,
        head_dim=op0.qk_head_dim, dtype=jnp.int8, batch=eng.slots,
        heads=op0.num_heads, path=table)
    assert got == rec["impl"]
    # dtype is in the key: the int8 entry must MISS for a float32 pool
    assert kernel_tune.lookup_paged_impl(
        page_size=eng.page_size, pages_per_slot=eng.pages_per_slot,
        head_dim=op0.qk_head_dim, dtype=jnp.float32, batch=eng.slots,
        heads=op0.num_heads, path=table) is None
    # an 'auto' engine picks the tuned winner up through the env table
    old = os.environ.get("FF_KERNEL_TUNE_TABLE")
    os.environ["FF_KERNEL_TUNE_TABLE"] = table
    try:
        kernel_tune.reload(table)
        eng2 = ff.make_serving_engine(
            serve_slots=2, kv_page_size=4, max_seq_len=32,
            kv_cache_dtype="int8", paged_attention_impl="auto")
        assert eng2.paged_attention_impl == rec["impl"]
        # an explicit impl request bypasses the table
        eng3 = ff.make_serving_engine(
            serve_slots=2, kv_page_size=4, max_seq_len=32,
            kv_cache_dtype="int8", paged_attention_impl="pallas")
        assert eng3.paged_attention_impl == "pallas"
    finally:
        if old is None:
            os.environ.pop("FF_KERNEL_TUNE_TABLE", None)
        else:
            os.environ["FF_KERNEL_TUNE_TABLE"] = old


@pytest.mark.slow  # ~15 s; quant CI tier
def test_bf16_pool_serves(ff):
    """kv_cache_dtype='bf16' under f32 compute: a plain-cast pool (no
    scales) that halves pool bytes; streams complete and the pool
    really stores bfloat16."""
    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=4,
                                 max_seq_len=64, kv_cache_dtype="bf16",
                                 paged_attention_impl="pallas")
    rs = np.random.RandomState(37)
    reqs = eng.run([rs.randint(1, VOCAB, (n,)).astype(np.int32)
                    for n in (5, 9, 3)], max_new_tokens=5)
    assert [r.state for r in reqs] == ["done"] * 3
    for op in eng.gen.attn_ops:
        assert eng.pool[op.name]["k"].dtype == jnp.bfloat16
        assert "k_scale" not in eng.pool[op.name]
